"""rt/ — the shared runtime core: breaker, lease pool, lease table.

The PR-12 extraction contract: exec/workers.py and serve/engine.py
consume the SAME Breaker/LeasePool implementations (one half-open
probe semantics, one ``TPU_PATTERNS_BREAKER_COOLDOWN_S`` knob), and a
replica quarantine releases every lease — pinned here so the next
"just inline a small breaker" PR fails loudly.
"""

import threading

import pytest

from tpu_patterns import obs, rt
from tpu_patterns.core.timing import clock_ns


class TestBreaker:
    def test_opens_after_threshold_consecutive_failures(self):
        b = rt.Breaker(threshold=2, cooldown_s=3600.0)
        assert b.admit() == "closed"
        assert not b.failure()
        assert b.admit() == "closed"  # one failure absorbs a blip
        assert b.failure()
        assert b.opened
        assert b.admit() == "open"  # not cooled: fall back instantly

    def test_success_resets_the_streak(self):
        b = rt.Breaker(threshold=2, cooldown_s=3600.0)
        b.failure()
        b.success()
        assert not b.failure()  # streak restarted, not continued
        assert not b.opened

    def test_half_open_admits_exactly_one_probe(self):
        b = rt.Breaker(threshold=1, cooldown_s=3600.0)
        assert b.failure()
        b.reopen_at(clock_ns() - int(7200 * 1e9))  # cool down
        assert b.admit() == "probe"
        assert b.admit() == "open"  # the slot is taken
        b.success()
        assert b.admit() == "closed"

    def test_failed_probe_reopens_for_another_cooldown(self):
        b = rt.Breaker(threshold=1, cooldown_s=3600.0)
        b.failure()
        b.reopen_at(clock_ns() - int(7200 * 1e9))
        assert b.admit() == "probe"
        assert b.failure(probe=True)
        assert b.opened and not b.probing
        assert b.admit() == "open"  # fresh cool-down started

    def test_abort_probe_unlatches_and_restarts_the_clock(self):
        b = rt.Breaker(threshold=1, cooldown_s=3600.0)
        b.failure()
        b.reopen_at(clock_ns() - int(7200 * 1e9))
        assert b.admit() == "probe"
        b.abort_probe()
        assert not b.probing
        assert b.admit() == "open"  # clock restarted, still open
        b.reopen_at(clock_ns() - int(7200 * 1e9))
        assert b.admit() == "probe"  # recovery not latched shut

    def test_gauge_tracks_open_state_with_labels(self):
        b = rt.Breaker(
            threshold=1, cooldown_s=3600.0,
            gauge="tpu_patterns_replica_breaker_open", replica="t0",
        )
        b.failure()
        assert obs.gauge(
            "tpu_patterns_replica_breaker_open", replica="t0"
        ).value == 1.0
        b.success()
        assert obs.gauge(
            "tpu_patterns_replica_breaker_open", replica="t0"
        ).value == 0.0

    def test_one_cooldown_knob_everywhere(self):
        # exec re-exports the shared constant: ONE env var tunes every
        # breaker in the tree (workers, replicas, engines)
        from tpu_patterns.exec import workers

        assert workers.BREAKER_COOLDOWN_S is rt.BREAKER_COOLDOWN_S
        b = rt.Breaker()
        assert b.cooldown_s == rt.BREAKER_COOLDOWN_S


class _Item:
    """Liveness-protocol item (the WarmWorker shape)."""

    def __init__(self):
        self.live = True
        self.killed = 0
        self.shut = 0
        self.expired = False

    def alive(self):
        return self.live

    def kill(self):
        self.killed += 1
        self.live = False

    def shutdown(self):
        self.shut += 1
        self.live = False


class TestLeasePool:
    def test_lease_release_reuse_accounting(self):
        pool = rt.LeasePool(2, spawn=_Item)
        a = pool.lease()
        assert isinstance(a, _Item) and pool.misses == 1
        pool.release(a, reusable=True)
        assert pool.lease() is a and pool.hits == 1
        assert pool.stats()["hit_rate"] == 0.5

    def test_max_leased_bounds_the_active_set(self):
        pool = rt.LeasePool(4, max_leased=2, spawn=iter(range(10)).__next__)
        a, b = pool.lease(), pool.lease()
        assert a is not None and b is not None
        assert pool.lease() is None  # width reached: defer, don't grow
        pool.release(a, reusable=True)
        assert pool.lease() is not None

    def test_plain_items_need_no_liveness_protocol(self):
        # the serve engine's scheduler slots are bare ints: always
        # alive, never expired, free to discard
        pool = rt.LeasePool(2, max_leased=2, spawn=iter(range(9)).__next__)
        t = pool.lease()
        pool.release(t, reusable=True)
        assert pool.lease() == t

    def test_unreusable_release_recycles(self):
        pool = rt.LeasePool(2, spawn=_Item)
        a = pool.lease()
        pool.release(a, reusable=False)
        assert a.killed == 1 and pool.recycled == 1
        assert pool.lease() is not a

    def test_expired_and_dead_items_never_come_back(self):
        pool = rt.LeasePool(2, spawn=_Item)
        a = pool.lease()
        a.expired = True
        pool.release(a, reusable=True)
        assert a.killed == 1  # expired: recycled despite reusable
        b = pool.lease()
        pool.release(b, reusable=True)
        b.live = False  # died while parked on the free list
        c = pool.lease()
        assert c is not b and b.killed >= 1

    def test_overflow_release_shuts_down_politely(self):
        pool = rt.LeasePool(1, spawn=_Item)
        a, b = pool.lease(), pool.lease()
        pool.release(a, reusable=True)  # fills the free list (size 1)
        pool.release(b, reusable=True)
        assert b.shut == 1  # no room: polite shutdown, not a kill

    def test_shutdown_hammers_leased_and_drains_free(self):
        pool = rt.LeasePool(2, spawn=_Item)
        a, b = pool.lease(), pool.lease()
        pool.release(a, reusable=True)
        pool.shutdown()
        assert a.shut == 1  # parked: polite
        assert b.killed == 1  # still leased at teardown: the hammer

    def test_breaker_gates_the_spawn_path(self):
        fails = {"n": 0}

        def spawn():
            fails["n"] += 1
            return None

        pool = rt.LeasePool(
            2, spawn=spawn,
            breaker=rt.Breaker(threshold=2, cooldown_s=3600.0),
        )
        assert pool.lease() is None and pool.lease() is None
        assert pool.breaker.opened
        n = fails["n"]
        assert pool.lease() is None  # open: no spawn attempt at all
        assert fails["n"] == n

    def test_spawn_exception_aborts_the_probe(self):
        pool = rt.LeasePool(
            1, breaker=rt.Breaker(threshold=1, cooldown_s=3600.0),
        )

        def boom():
            raise RuntimeError("ENOSPC")

        pool._spawn = boom
        with pytest.raises(RuntimeError):
            pool.lease()  # closed-state spawn crash propagates
        pool.breaker.failure()  # open it
        pool.breaker.reopen_at(clock_ns() - int(7200 * 1e9))
        with pytest.raises(RuntimeError):
            pool.lease()  # the probe crashes...
        assert not pool.breaker.probing  # ...but never latches shut


class TestLeaseTable:
    def test_acquire_release_round_trip(self):
        t = rt.LeaseTable()
        t.acquire(7, meta="req")
        assert 7 in t and len(t) == 1
        assert t.release(7) == "req"
        assert 7 not in t

    def test_double_acquire_is_a_bug_not_a_shrug(self):
        t = rt.LeaseTable()
        t.acquire(1)
        with pytest.raises(ValueError):
            t.acquire(1)

    def test_release_unheld_returns_none(self):
        # a late message after fail-over already settled the rid
        assert rt.LeaseTable().release(42) is None

    def test_release_all_empties(self):
        t = rt.LeaseTable()
        for i in range(5):
            t.acquire(i, meta=i * 10)
        held = t.release_all()
        assert held == {i: i * 10 for i in range(5)}
        assert len(t) == 0

    def test_thread_safety_under_contention(self):
        t = rt.LeaseTable()
        errs = []

        def work(base):
            try:
                for i in range(200):
                    t.acquire((base, i))
                    t.release((base, i))
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [
            threading.Thread(target=work, args=(b,)) for b in range(4)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errs and len(t) == 0


class TestDedup:
    """The extraction IS the point: both subsystems consume rt."""

    def test_worker_pool_is_the_shared_lease_pool(self):
        from tpu_patterns.exec.workers import WorkerPool

        pool = WorkerPool(1, {})
        try:
            assert isinstance(pool, rt.LeasePool)
            assert type(pool.breaker) is rt.Breaker
            # the legacy knobs still drive the shared breaker
            assert pool._dead is False
            pool.breaker.failure()
            pool.breaker.failure()
            assert pool._dead is True
            pool._opened_ns = 123
            assert pool.breaker.opened_ns == 123
        finally:
            pool.shutdown()

    def test_serve_engine_slots_are_the_shared_lease_pool(self, devices):
        import jax

        from tpu_patterns.models.lm import init_lm_params
        from tpu_patterns.models.transformer import (
            ModelConfig,
            _n_experts,
        )
        from tpu_patterns.serve.engine import Request, ServeEngine
        from tpu_patterns.serve.paged import make_paged_lm_decoder

        mesh = jax.sharding.Mesh(
            __import__("numpy").array(devices[:1]).reshape(1, 1, 1),
            ("dp", "sp", "tp"),
        )
        mcfg = ModelConfig(
            embed=16, heads=2, head_dim=4, mlp_mult=2, causal=True,
            dtype="float32", depth=1,
        )
        decoder = make_paged_lm_decoder(
            mesh, mcfg, 32, n_blocks=9, block_len=4, max_len=16
        )
        params = decoder.stack_params(
            init_lm_params(
                jax.random.key(0), mcfg, 32, _n_experts(mesh, mcfg)
            )
        )
        eng = ServeEngine(
            decoder, params, slots=2,
            breaker=rt.Breaker(threshold=2, cooldown_s=3600.0),
        )
        assert isinstance(eng.slot_pool, rt.LeasePool)
        assert type(eng.breaker) is rt.Breaker  # same class as workers'
        # serving holds one slot lease per active row and releases on
        # retire — the run must end with the pool fully settled
        out = eng.run([
            Request(rid=0, tokens=[1, 2, 3], n_gen=2),
            Request(rid=1, tokens=[4, 5, 6, 7, 8], n_gen=2),
            Request(rid=2, tokens=[9, 1], n_gen=1),
        ])
        assert set(out) == {0, 1, 2}
        assert eng.slot_pool.outstanding() == 0
        assert eng.leaked_blocks() == 0

        # persistent decode-step faults must TRIP the breaker (stop
        # with the queue intact), not grind through every request —
        # and a successful PREFILL between failed waves must not reset
        # the streak (each step failure empties the active set, so a
        # prefill always runs in between; resetting there would make
        # the threshold unreachable)
        from tpu_patterns import faults

        eng2 = ServeEngine(
            decoder, params, slots=1,
            breaker=rt.Breaker(threshold=2, cooldown_s=3600.0),
        )
        trace = [
            Request(rid=i, tokens=[1 + i, 2, 3], n_gen=3)
            for i in range(4)
        ]
        faults.configure("serve.step:error:count=99")
        try:
            eng2.run(trace)
        finally:
            faults.configure(None)
        assert eng2.breaker_tripped
        assert eng2.queue  # work handed back, not failed through
        assert len(eng2.failed) == 2  # exactly the threshold's waves
        assert eng2.leaked_blocks() == 0
        assert eng2.slot_pool.outstanding() == 0
