"""Tests for the concurrency suite (SURVEY.md §7 step 4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_patterns.concurrency import (
    BACKENDS,
    Command,
    ConcurrencyConfig,
    MemKind,
    busy_wait_pallas,
    busy_wait_xla,
    get_backend,
    parse_command,
    parse_group,
    run_concurrency,
)
from tpu_patterns.concurrency.commands import alloc
from tpu_patterns.concurrency.harness import TOL_SPEEDUP, auto_tune
from tpu_patterns.core.results import Record, ResultWriter, Verdict


class TestCommandLanguage:
    def test_parse_compute(self):
        c = parse_command("C")
        assert c.kind == "compute" and c.text == "C"

    @pytest.mark.parametrize("tok,src,dst", [
        ("M2D", MemKind.M, MemKind.D),
        ("H2D", MemKind.H, MemKind.D),
        ("D2H", MemKind.D, MemKind.H),
        ("S2D", MemKind.S, MemKind.D),
        ("D2S", MemKind.D, MemKind.S),
        ("D2D", MemKind.D, MemKind.D),
    ])
    def test_parse_copies(self, tok, src, dst):
        c = parse_command(tok)
        assert c.kind == "copy" and c.src is src and c.dst is dst

    def test_reject_garbage(self):
        with pytest.raises(ValueError, match="expected"):
            parse_command("Q2D")
        with pytest.raises(ValueError, match="identical"):
            parse_command("H2H")
        with pytest.raises(ValueError, match="empty"):
            parse_group("   ")

    def test_group_parse(self):
        cmds = parse_group("C M2D D2M")
        assert [c.text for c in cmds] == ["C", "M2D", "D2M"]

    def test_scaled_compute_rescales_tripcount(self):
        c = parse_command("C")
        assert c.scaled(2.0).tripcount == 2 * c.tripcount
        assert c.scaled(1e-9).tripcount == 1  # floor

    def test_scaled_copy_rounds_to_lanes(self):
        c = parse_command("H2D")
        s = c.scaled(0.5)
        assert s.copy_elements % 128 == 0
        assert abs(s.copy_elements - c.copy_elements // 2) <= 128

    def test_alloc_kinds(self):
        c = parse_command("H2D")
        c.copy_elements = 256
        buf = alloc(c)
        assert buf.sharding.memory_kind == "pinned_host"
        m = parse_command("M2D")
        m.copy_elements = 256
        assert isinstance(alloc(m), np.ndarray)


class TestBusyWait:
    def test_xla_pallas_agree(self):
        x = jnp.full((8, 128), 0.5, jnp.float32)
        a = busy_wait_xla(x, 3)
        b = busy_wait_pallas(x, 3, interpret=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    def test_values_stay_finite(self):
        x = jnp.full((8, 128), 1.0, jnp.float32)
        y = busy_wait_xla(x, 10_000)
        assert bool(jnp.isfinite(y).all())
        assert float(jnp.abs(y).max()) > 0


class TestBackendValidation:
    def test_backends_registered(self):
        assert set(BACKENDS) == {"xla", "pallas"}
        with pytest.raises(KeyError, match="xla"):
            get_backend("cuda")

    def test_xla_rejects_m_in_program(self):
        b = get_backend("xla")
        with pytest.raises(ValueError, match="pageable host"):
            b.validate("concurrent", parse_group("C M2D"))
        b.validate("dispatch_async", parse_group("C M2D"))  # ok

    def test_xla_rejects_d2d(self):
        b = get_backend("xla")
        with pytest.raises(ValueError, match="elided"):
            b.validate("concurrent", parse_group("D2D"))

    def test_pallas_rejects_host_copies(self):
        b = get_backend("pallas")
        with pytest.raises(ValueError, match="D2D"):
            b.validate("dma_overlap", parse_group("C H2D"))
        b.validate("dma_serial", parse_group("C D2D"))  # ok

    def test_unknown_mode(self):
        with pytest.raises(ValueError, match="unknown mode"):
            get_backend("xla").validate("warp_speed", parse_group("C"))


def small_cfg(**kw):
    kw.setdefault("reps", 2)
    kw.setdefault("warmup", 1)
    kw.setdefault("tripcount", 50)
    kw.setdefault("elements", 1024)
    kw.setdefault("copy_elements", 1 << 14)
    kw.setdefault("chain_lengths", (1, 3))
    return ConcurrencyConfig(**kw)


class TestHarness:
    def test_auto_tune_equalizes_knobs(self):
        cfg = small_cfg()
        backend = get_backend("xla")
        writer = ResultWriter()
        cmds = [parse_command("C"), parse_command("S2D")]
        for c in cmds:
            c.tripcount, c.copy_elements = cfg.tripcount, cfg.copy_elements
        tuned = auto_tune(backend, cmds, cfg, writer, {})
        assert len(tuned) == 2
        assert tuned[0].tripcount >= 1
        assert tuned[1].copy_elements % 128 == 0

    @pytest.mark.parametrize("mode", ["serial", "concurrent"])
    def test_xla_in_program_modes(self, mode):
        cfg = small_cfg(backend="xla", mode=mode, commands=("C S2D",))
        (rec,) = run_concurrency(cfg)
        m = rec.metrics
        assert m["speedup"] > 0
        assert m["theoretical_speedup"] >= 1.0
        assert m["serial_total_us"] > 0
        assert rec.mode == f"xla:{mode}"

    def test_dispatch_modes_with_m(self):
        cfg = small_cfg(backend="xla", mode="dispatch_async",
                        commands=("M2D D2M",))
        (rec,) = run_concurrency(cfg)
        assert rec.metrics["speedup"] > 0

    @pytest.mark.parametrize("mode", ["dma_serial", "dma_overlap"])
    def test_pallas_modes(self, mode):
        cfg = small_cfg(backend="pallas", mode=mode, commands=("C D2D",))
        (rec,) = run_concurrency(cfg)
        assert rec.metrics["speedup"] > 0

    def test_min_bandwidth_gate(self):
        cfg = small_cfg(backend="xla", mode="concurrent", commands=("C S2D",),
                        min_bandwidth=1e12)
        (rec,) = run_concurrency(cfg)
        assert rec.verdict is Verdict.FAILURE
        assert any("below floor" in n for n in rec.notes)

    def test_exit_code_aggregation(self, tmp_path):
        w = ResultWriter(tmp_path / "r.jsonl")
        cfg = small_cfg(backend="xla", mode="concurrent", commands=("C S2D",),
                        min_bandwidth=1e12)
        run_concurrency(cfg, w)
        assert w.exit_code == 1


def test_serial_mode_time_is_sum_of_solos():
    """Guard on the serial-vs-concurrent CONTRAST itself: serial mode's
    group time must be >= ~the sum of each command's solo time.  The
    serial mode orders commands with lax.optimization_barrier; if a
    future XLA elided the barrier AND merged/overlapped the commands, the
    group time would collapse toward one solo time and every speedup
    verdict would become vacuous SUCCESS — this asserts the contrast's
    denominator stays real (≙ the serial reference, concurency
    main.cpp:281-293)."""
    from tpu_patterns.concurrency import harness
    from tpu_patterns.concurrency.backends import get_backend
    from tpu_patterns.core import timing

    cfg = harness.ConcurrencyConfig(
        backend="xla",
        mode="serial",
        reps=3,
        warmup=1,
        auto_tune=False,
        tripcount=3000,
        elements=16384,
    )
    cmds = harness._apply_defaults(harness.parse_group("C C"), cfg)
    backend = get_backend("xla")
    solo_ns = [harness._measure_solo(backend, c, cfg)[0] for c in cmds]

    built = backend.build(cmds, "serial")
    m = timing.measure_chain(
        built.build_chain,
        reps=cfg.reps,
        warmup=cfg.warmup,
        direct_fn=built.direct_fn,
        label="serial-guard",
    )
    total = sum(solo_ns)
    assert m.per_op_ns >= 0.6 * total, (
        f"serial group ran in {m.per_op_ns:.0f} ns but solos sum to "
        f"{total:.0f} ns — the serial ordering has been elided"
    )
