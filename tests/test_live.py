"""Live telemetry plane (obs/live.py) + SLO burn-rate monitor
(obs/slo.py): dual-window burn accounting, the shed / spec_off
mitigation ladder in the serve engine, the /metrics /healthz /statusz
endpoints (race-free scrapes, fault-injected 503s, fleet lanes), the
engine's rt.LeaseTable in-flight ledger, and the `obs watch` poller."""

import dataclasses
import io
import json
import time
import types
import urllib.error
import urllib.request

import pytest

from tpu_patterns import faults, obs, rt
from tpu_patterns.obs import live as obs_live
from tpu_patterns.obs.live import ObsHttp
from tpu_patterns.obs.slo import SloConfig, SloMonitor
from tpu_patterns.serve import Request, ServeEngine

from test_serve import (
    CFG,
    _decoder_and_params,
    _mesh,
    _mixed_reqs,
    _preempt_engine,
    _trace,
)
from tpu_patterns.models.transformer import ModelConfig

MCFG = ModelConfig(**CFG)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure("")
    yield
    faults.configure(None)


# NB: no autouse detach fixture — the class-scoped ``served_engine``
# plane stays attached across its whole test class; tests that attach
# their own target detach it themselves (engine.run() detaches on exit
# by contract).


def _get(port, path):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _get_json(port, path):
    code, body = _get(port, path)
    return code, json.loads(body)


# -- the burn-rate monitor -------------------------------------------------


class TestSloMonitor:
    def test_good_tokens_keep_burn_at_zero(self):
        m = SloMonitor(SloConfig(
            fast_window_s=1.0, slow_window_s=2.0, budget=0.1,
            multiplier=1.0,
        ))
        for _ in range(5):
            m.observe(tokens=10, met=True)
        snap = m.snapshot()
        assert snap["burn_rate_fast"] == 0.0
        assert not m.mitigating()
        assert m.fires == 0

    def test_bad_tokens_trip_once_and_recover_on_the_window(self):
        m = SloMonitor(SloConfig(
            fast_window_s=0.2, slow_window_s=0.4, budget=0.1,
            multiplier=1.0,
        ))
        m.observe(tokens=10, met=True)
        m.observe(tokens=10, met=False)  # 50% bad >> 10% budget
        assert m.mitigating()
        assert m.fires == 1
        m.observe(tokens=10, met=False)  # still burning: no re-fire
        assert m.fires == 1
        # the episode ends when the buckets age out — no new traffic,
        # no operator action
        time.sleep(0.5)
        assert not m.mitigating()
        # a fresh burst trips a NEW episode
        m.observe(tokens=10, met=False)
        assert m.mitigating()
        assert m.fires == 2

    def test_burn_warning_record_and_gauges_published(self, tmp_path):
        obs.configure(str(tmp_path))
        try:
            m = SloMonitor(SloConfig(
                fast_window_s=1.0, slow_window_s=2.0, budget=0.1,
                multiplier=1.0,
            ))
            m.observe(tokens=20, met=False, ttft_ms=12.0, tpot_ms=3.0)
            assert m.mitigating()
        finally:
            obs.configure(None)
        recs = [
            json.loads(ln)
            for ln in (tmp_path / "slo.jsonl").read_text().splitlines()
        ]
        assert recs[-1]["mode"] == "slo_burn"
        assert recs[-1]["verdict"] == "WARNING"
        assert recs[-1]["metrics"]["burn_rate_fast"] > 1.0
        reg = obs.metrics_registry()
        samples = obs.parse_prom_text(reg.render())
        assert samples[(
            "tpu_patterns_slo_burn_rate", (("window", "fast"),)
        )] > 1.0
        # live tail-latency gauges reached the registry too
        assert samples[("tpu_patterns_slo_live_ttft_p99_ms", ())] == 12.0
        assert samples[("tpu_patterns_slo_live_tpot_p99_ms", ())] == 3.0

    def test_config_invariants_rejected(self):
        with pytest.raises(ValueError):
            SloConfig(fast_window_s=10.0, slow_window_s=5.0)
        with pytest.raises(ValueError):
            SloConfig(budget=0.0)
        with pytest.raises(ValueError):
            SloConfig(multiplier=2.0, recover=3.0)


# -- the mitigation ladder in the engine -----------------------------------


def _bad_deadline(reqs):
    """The same trace with an impossible deadline: every completed
    request books BAD tokens — the deterministic burn trigger."""
    return [
        dataclasses.replace(r, tokens=list(r.tokens), deadline_ms=1e-6)
        for r in reqs
    ]


class TestShedMitigation:
    def test_burn_sheds_admissions_identity_closes(self, devices):
        mesh = _mesh(devices, (1, 2, 2))
        dec, params, _ = _decoder_and_params(mesh, MCFG)
        eng = ServeEngine(
            dec, params, slots=1, burn_mitigation="shed",
            slo=SloConfig(
                fast_window_s=30, slow_window_s=60, budget=0.01,
                multiplier=1.0,
            ),
        )
        trace = _bad_deadline(_trace(6, min_p=3, max_p=8, n_gen=4))
        out = eng.run(trace)
        # the first request completes (slots=1), books its tokens bad,
        # trips the fast window, and every later admission sheds —
        # counted, never silently dropped
        assert eng.slo.fires >= 1
        assert eng.shed and eng.stats["sheds"] == len(eng.shed)
        assert len(out) + len(eng.failed) + len(eng.shed) == len(trace)
        assert eng.leaked_blocks() == 0
        assert len(eng.inflight) == 0
        assert rt.metric_total("tpu_patterns_serve_shed_total") >= len(
            eng.shed
        )

    def test_window_recovery_reopens_admission(self, devices):
        mesh = _mesh(devices, (1, 2, 2))
        dec, params, _ = _decoder_and_params(mesh, MCFG)
        eng = ServeEngine(
            dec, params, slots=1, burn_mitigation="shed",
            slo=SloConfig(
                fast_window_s=0.2, slow_window_s=0.4, budget=0.01,
                multiplier=1.0,
            ),
        )
        trace = _bad_deadline(_trace(4, min_p=3, max_p=8, n_gen=4))
        eng.run(trace)
        shed_before = len(eng.shed)
        assert shed_before > 0
        time.sleep(0.5)  # the fast window drains
        more = _trace(2, min_p=3, max_p=8, n_gen=4, seed=7)
        for r in more:
            r.rid += 100
        out = eng.run(more)
        # recovered: the new requests ADMIT (no deadline -> all good)
        assert all(100 + i in out for i in range(2))
        assert len(eng.shed) == shed_before

    def test_shed_site_error_fails_open_to_admission(self, devices):
        mesh = _mesh(devices, (1, 2, 2))
        dec, params, _ = _decoder_and_params(mesh, MCFG)
        eng = ServeEngine(
            dec, params, slots=1, burn_mitigation="shed",
            slo=SloConfig(
                fast_window_s=30, slow_window_s=60, budget=0.01,
                multiplier=1.0,
            ),
        )
        faults.configure("serve.shed:error:count=1")
        trace = _bad_deadline(_trace(5, min_p=3, max_p=8, n_gen=4))
        out = eng.run(trace)
        # the injected error aborted ONE shed: that request admitted
        # (and completed) instead — mitigation degrades to
        # no-mitigation, never to a lost request
        assert len(out) >= 2  # rid 0 plus the failed-open shed victim
        assert len(out) + len(eng.failed) + len(eng.shed) == len(trace)
        assert eng.shed  # the rest still shed

    def test_spec_off_degrades_to_plain_decode_ids_exact(self, devices):
        mesh = _mesh(devices, (1, 2, 2))
        dec, params, _ = _decoder_and_params(mesh, MCFG)
        trace = _trace(4, min_p=6, max_p=12, n_gen=6, seed=3)

        def run(mitigation, pre_trip):
            eng = ServeEngine(
                dec, params, slots=4, spec_k=2,
                burn_mitigation=mitigation,
                slo=SloConfig(
                    fast_window_s=60, slow_window_s=120, budget=0.01,
                    multiplier=1.0,
                ),
            )
            if pre_trip:
                eng.slo.observe(tokens=50, met=False)
                assert eng.slo.mitigating()
            out = eng.run(
                [dataclasses.replace(r, tokens=list(r.tokens))
                 for r in trace]
            )
            return out, eng

        out_plain, eng_off = run("spec_off", pre_trip=True)
        assert eng_off.stats["spec_steps"] == 0  # degraded all the way
        out_spec, eng_spec = run("off", pre_trip=True)
        assert eng_spec.stats["spec_steps"] > 0  # ladder off: spec ran
        assert out_plain == out_spec  # bit-identical either way

    def test_total_failure_outage_still_burns(self, devices):
        """A request that fails with ZERO tokens out must still book
        bad tokens (its whole n_gen budget): a total outage — every
        request quarantining at prefill — has to fire the burn WARNING
        and engage mitigation, not sail under the radar because n_out
        weighting saw nothing."""
        mesh = _mesh(devices, (1, 2, 2))
        dec, params, _ = _decoder_and_params(mesh, MCFG)
        eng = ServeEngine(
            dec, params, slots=2, burn_mitigation="shed",
            slo=SloConfig(
                fast_window_s=30, slow_window_s=60, budget=0.01,
                multiplier=1.0,
            ),
        )
        # every prefill fails deterministically -> every admitted row
        # quarantines with out == [] (0 tokens generated)
        faults.configure("serve.prefill:error:count=999")
        out = eng.run(_trace(6, min_p=3, max_p=8, n_gen=4))
        assert not out and eng.failed  # the outage really was total
        snap = eng.slo.snapshot()
        assert snap["bad_tokens"] > 0
        assert eng.slo.fires >= 1
        # and the ladder engaged: later admissions shed
        assert eng.shed
        assert len(eng.failed) + len(eng.shed) == 6

    def test_bad_mitigation_rejected(self, devices):
        mesh = _mesh(devices, (1, 2, 2))
        dec, params, _ = _decoder_and_params(mesh, MCFG)
        with pytest.raises(ValueError, match="burn_mitigation"):
            ServeEngine(dec, params, slots=1, burn_mitigation="panic")


class TestInflightLedger:
    def test_table_fills_mid_run_and_settles_empty(self, devices):
        mesh = _mesh(devices, (1, 2, 2))
        dec, params, _ = _decoder_and_params(mesh, MCFG)
        eng = ServeEngine(dec, params, slots=4)
        seen = []

        def source(idle=False):
            seen.append(len(eng.inflight))
            return None  # exhausted: the pre-submitted trace drains

        eng.run(_trace(4, min_p=3, max_p=8, n_gen=6), source=source)
        assert len(eng.inflight) == 0  # settled
        # the ledger held rows while the loop ran
        assert max(seen, default=0) > 0 or len(eng.done) == 4


# -- the HTTP plane --------------------------------------------------------


@pytest.fixture(scope="class")
def served_engine(request, devices):
    """One tiny engine run to completion + a live plane attached to it
    — module-shaped state every endpoint test reads."""
    mesh = _mesh(devices, (1, 2, 2))
    dec, params, _ = _decoder_and_params(mesh, MCFG)
    eng = ServeEngine(dec, params, slots=4)
    eng.run(_trace(4, min_p=3, max_p=8, n_gen=4))
    obs_live.attach_engine(eng)
    plane = ObsHttp(0)
    port = plane.start()
    request.cls.eng = eng
    request.cls.port = port
    yield
    plane.stop()
    obs_live.detach_engine(eng)


@pytest.mark.usefixtures("served_engine")
class TestObsHttp:
    def test_metrics_serves_registry_render_byte_identical(self):
        code, body = _get(self.port, "/metrics")
        assert code == 200

        def without_scrape_counter(text):
            # the scrape books ITSELF into the requests counter (after
            # rendering), so that one series differs between a scrape
            # and a later render — everything else is byte-identical
            return "\n".join(
                ln for ln in text.splitlines()
                if "tpu_patterns_obs_http_requests_total" not in ln
            )

        assert without_scrape_counter(body) == without_scrape_counter(
            obs.metrics_registry().render()
        )
        samples = obs.parse_prom_text(body)
        assert any(
            name == "tpu_patterns_serve_tokens_total"
            for name, _ in samples
        )

    def test_healthz_verdict_and_pool_state(self):
        code, h = _get_json(self.port, "/healthz")
        assert code == 200
        assert h["verdict"] in ("ok", "degraded")
        e = h["engine"]
        assert e["active_rows"] == 0 and e["queued"] == 0
        assert e["done"] == 4 and e["failed"] == 0
        assert (
            e["pool"]["free_blocks"] == e["pool"]["allocatable_blocks"]
        )
        assert "burn_rate_fast" in h["slo"]
        assert "fired" in h["watchdog"]

    def test_statusz_settled_engine_has_no_rows(self):
        code, s = _get_json(self.port, "/statusz")
        assert code == 200
        assert s["engine"]["requests"] == []
        assert s["engine"]["done"] == 4
        recent = s["engine"]["recent"]
        assert recent and all(r["status"] == "done" for r in recent)

    def test_costz_serves_the_book_with_identities(self):
        code, c = _get_json(self.port, "/costz")
        assert code == 200
        snap = c["engine"]
        assert snap["decode_identity_ok"]
        assert snap["prefill_identity_ok"]
        assert snap["conservation_ok"]
        # every retired request has an attribution row with its class
        assert len(snap["requests"]) == 4
        assert all(
            r["priority"] == "interactive" for r in snap["requests"]
        )
        assert sum(
            r["decode_ns"] for r in snap["requests"]
        ) == snap["attributed_decode_ns"]
        # ledger coverage rides along (no decisions on a clean run)
        assert snap["decisions"] == {}

    def test_unknown_path_is_404(self):
        code, body = _get(self.port, "/nope")
        assert code == 404
        assert "/metrics" in body
        assert "/costz" in body  # the endpoint list names it

    def test_scrape_fault_answers_503_counted_never_crashes(self):
        before = rt.metric_total(
            "tpu_patterns_obs_http_requests_total", endpoint="healthz"
        )
        faults.configure("obs.scrape:error:count=1:endpoint=healthz")
        code, _ = _get(self.port, "/healthz")
        assert code == 503
        # the plane healed: the very next scrape answers
        code, _ = _get(self.port, "/healthz")
        assert code == 200
        after = rt.metric_total(
            "tpu_patterns_obs_http_requests_total", endpoint="healthz"
        )
        assert after >= before + 2  # the 503 was counted too

    def test_watch_renders_one_line_per_poll(self):
        out = io.StringIO()
        rc = obs_live.watch(
            f"http://127.0.0.1:{self.port}",
            interval_s=0.01, count=2, out=out,
        )
        assert rc == 0
        lines = out.getvalue().splitlines()
        assert len(lines) == 2
        assert "burn=" in lines[0] and "act=" in lines[0]
        # per-class tail columns (PR 17): the run's requests were all
        # interactive, so the int_ columns appear and bulk_ stay off
        assert "int_ttft_p99=" in lines[0]
        assert "int_tpot_p99=" in lines[0]
        assert "bulk_ttft_p99=" not in lines[0]

    def test_watch_no_plane_is_an_error(self):
        out = io.StringIO()
        rc = obs_live.watch(
            "http://127.0.0.1:9", interval_s=0.01, count=1, out=out,
        )
        assert rc == 1


class TestObsHttpMidRun:
    def test_mid_run_scrape_sees_inflight_rows(self, devices):
        """The acceptance shape: /healthz ok and /statusz showing the
        in-flight table WHILE the scheduler loop runs (the source hook
        scrapes from inside an iteration boundary)."""
        mesh = _mesh(devices, (1, 2, 2))
        dec, params, _ = _decoder_and_params(mesh, MCFG)
        eng = ServeEngine(dec, params, slots=2)
        plane = ObsHttp(0)
        port = plane.start()
        captured = {}

        def source(idle=False):
            if eng.active and "status" not in captured:
                captured["health"] = _get_json(port, "/healthz")[1]
                captured["status"] = _get_json(port, "/statusz")[1]
            # [] keeps the loop polling; None (exhausted) once the
            # pre-submitted trace settled lets the run end
            done = len(eng.done) + len(eng.failed) >= 4
            return None if done else []

        try:
            eng.run(_trace(4, min_p=3, max_p=8, n_gen=6), source=source)
        finally:
            plane.stop()
        assert captured, "the loop never had active rows"
        assert captured["health"]["verdict"] == "ok"
        assert captured["health"]["engine"]["active_rows"] > 0
        rows = captured["status"]["engine"]["requests"]
        assert rows and {"rid", "generated", "n_gen", "age_ms"} <= set(
            rows[0]
        )

    def test_statusz_flags_parked_rows_with_banked_tokens(
        self, devices
    ):
        """A preempting run scraped mid-flight: the parked (preempted)
        bulk row shows in ``parked`` with its banked-token count, the
        in-flight rows carry their priority class, and once the victim
        resumes its row is flagged ``resumed``."""
        eng, dec, params = _preempt_engine(devices)
        reqs = _mixed_reqs()
        plane = ObsHttp(0)
        port = plane.start()
        obs_live.attach_engine(eng)
        captured = {}

        def source(idle=False):
            parked = [
                r.rid for r, _ in eng.queue
                if r.rid in eng.preempted_partial
            ]
            if parked and "status" not in captured:
                captured["status"] = _get_json(port, "/statusz")[1]
            if (
                "status" in captured and "resumed" not in captured
                and any(
                    s.rid in eng.preempted_rids for s in eng.active
                )
            ):
                captured["resumed"] = _get_json(port, "/statusz")[1]
            done = len(eng.done) + len(eng.failed) >= len(reqs)
            return None if done else []

        try:
            eng.run(
                [dataclasses.replace(r) for r in reqs], source=source
            )
        finally:
            plane.stop()
            obs_live.detach_engine(eng)
        assert eng.stats["preempted"] >= 1
        assert "status" in captured, "no scrape saw a parked row"
        s = captured["status"]["engine"]
        parked = s["parked"]
        assert parked and all(p["banked"] >= 1 for p in parked)
        assert all(p["remaining"] > 0 for p in parked)
        # the rows that preempted the victim carry their class
        rows = s["requests"]
        assert rows and all("priority" in r for r in rows)
        assert any(r["priority"] == "interactive" for r in rows)
        if "resumed" in captured:
            rows = captured["resumed"]["engine"]["requests"]
            back = [r for r in rows if r.get("resumed")]
            assert back and all(r["banked"] >= 1 for r in back)

    def test_unhealthy_engine_answers_503(self, devices):
        mesh = _mesh(devices, (1, 2, 2))
        dec, params, _ = _decoder_and_params(mesh, MCFG)
        eng = ServeEngine(dec, params, slots=2, breaker=rt.Breaker())
        eng.breaker_tripped = True
        obs_live.attach_engine(eng)
        plane = ObsHttp(0)
        port = plane.start()
        try:
            code, h = _get_json(port, "/healthz")
        finally:
            plane.stop()
            obs_live.detach_engine(eng)
        assert code == 503
        assert h["verdict"] == "unhealthy"

    def test_nothing_attached_is_ok_not_an_error(self):
        obs_live.attach_engine(None)
        plane = ObsHttp(0)
        port = plane.start()
        try:
            code, h = _get_json(port, "/healthz")
            assert code == 200
            assert h["engine"] is None
            code, s = _get_json(port, "/statusz")
            assert code == 200 and s["engine"] is None
            code, c = _get_json(port, "/costz")
            assert code == 200 and c["engine"] is None
        finally:
            plane.stop()


class TestFleetLanes:
    def _fake_manager(self):
        def handle(rid, state, rids):
            leases = rt.LeaseTable()
            for r in rids:
                leases.acquire(r)
            return types.SimpleNamespace(
                id=rid, state=state, leases=leases,
                breaker=rt.Breaker(),
                obs_stalled=False,
                last_msg_ns=0,
                alive=lambda: state in ("spawning", "ready"),
            )

        return types.SimpleNamespace(
            handles={
                "0": handle("0", "ready", [1, 3]),
                "1": handle("1", "quarantined", []),
            },
            fleet_obs=None,
        )

    def test_statusz_has_one_lane_per_replica(self):
        mgr = self._fake_manager()
        obs_live.attach_fleet(mgr)
        plane = ObsHttp(0)
        port = plane.start()
        try:
            _, s = _get_json(port, "/statusz")
        finally:
            plane.stop()
            obs_live.detach_fleet(mgr)
        lanes = {l["replica"]: l for l in s["fleet"]["replicas"]}
        assert lanes["0"]["inflight"] == [1, 3]
        assert lanes["1"]["state"] == "quarantined"

    def test_healthz_degraded_on_sick_replica_unhealthy_on_none(self):
        mgr = self._fake_manager()
        obs_live.attach_fleet(mgr)
        plane = ObsHttp(0)
        port = plane.start()
        try:
            code, h = _get_json(port, "/healthz")
            assert code == 200 and h["verdict"] == "degraded"
            for handle in mgr.handles.values():
                handle.state = "dead"
                handle.alive = lambda: False
            code, h = _get_json(port, "/healthz")
        finally:
            plane.stop()
            obs_live.detach_fleet(mgr)
        assert code == 503 and h["verdict"] == "unhealthy"
