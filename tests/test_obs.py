"""Observability layer (tpu_patterns/obs): span nesting/threading, ring
wraparound, watchdog hang diagnosis, metrics round trips, Chrome trace
schema, and the CLI surface."""

import json
import math
import os
import threading
import time

import pytest

from tpu_patterns import obs
from tpu_patterns.obs import export as obs_export
from tpu_patterns.obs import metrics as obs_metrics
from tpu_patterns.obs import recorder as obs_recorder
from tpu_patterns.obs import spans as obs_spans


@pytest.fixture(autouse=True)
def _isolated_obs(tmp_path):
    """Each test gets a clean ring, registry, and run dir — obs state is
    process-global by design (that is what makes it a flight recorder),
    so tests must isolate explicitly."""
    obs.flight_recorder().clear()
    obs.metrics_registry().clear()
    obs.configure(str(tmp_path))
    obs.set_enabled(True)
    yield
    obs.flight_recorder().clear()
    obs.metrics_registry().clear()
    obs.configure(None)
    obs.set_enabled(True)


class TestSpans:
    def test_nesting_records_depth_and_parent(self):
        with obs.span("outer", a=1) as so:
            with obs.span("middle") as sm:
                with obs.span("inner"):
                    pass
        entries = {e["name"]: e for e in obs.flight_recorder().snapshot()}
        assert entries["outer"]["depth"] == 0
        assert entries["outer"]["parent_id"] == 0
        assert entries["middle"]["depth"] == 1
        assert entries["middle"]["parent_id"] == so.span_id
        assert entries["inner"]["depth"] == 2
        assert entries["inner"]["parent_id"] == sm.span_id
        assert entries["outer"]["attrs"] == {"a": 1}
        # innermost closes first: ring order is inner, middle, outer
        assert [e["name"] for e in obs.flight_recorder().snapshot()] == [
            "inner", "middle", "outer",
        ]

    def test_duration_on_the_monotonic_clock(self):
        with obs.span("timed"):
            time.sleep(0.02)
        (entry,) = obs.flight_recorder().snapshot()
        assert entry["dur_ns"] >= 15e6  # >= 15ms of the 20ms sleep

    def test_exception_marks_the_span(self):
        with pytest.raises(RuntimeError):
            with obs.span("failing"):
                raise RuntimeError("boom")
        (entry,) = obs.flight_recorder().snapshot()
        assert entry["error"] == "RuntimeError"

    def test_threads_nest_independently(self):
        """Two threads racing nested spans: each thread's stack is its
        own — depths/parents never cross threads."""
        barrier = threading.Barrier(2)

        def work(tag):
            barrier.wait()
            for _ in range(20):
                with obs.span(f"{tag}.outer") as so:
                    with obs.span(f"{tag}.inner") as si:
                        assert si.parent_id == so.span_id
                        assert si.depth == 1

        threads = [
            threading.Thread(target=work, args=(t,), name=t)
            for t in ("t1", "t2")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        entries = obs.flight_recorder().snapshot()
        assert len(entries) == 80
        by_id = {e["span_id"]: e for e in entries}
        for e in entries:
            if e["parent_id"]:
                parent = by_id[e["parent_id"]]
                assert parent["tid"] == e["tid"]  # parents never cross
                assert parent["name"].split(".")[0] == e["name"].split(".")[0]

    def test_event_records_instant(self):
        with obs.span("ctx"):
            obs.event("marker", step=3)
        ev = [
            e for e in obs.flight_recorder().snapshot()
            if e["kind"] == "event"
        ]
        assert len(ev) == 1 and ev[0]["attrs"] == {"step": 3}
        assert ev[0]["depth"] == 1  # nested under the open span

    def test_disabled_is_a_shared_noop(self):
        obs.set_enabled(False)
        s1 = obs.span("a")
        s2 = obs.span("b", deadline_s=1)
        assert s1 is s2  # ONE shared object: no per-call allocation
        with s1:
            pass
        obs.event("e")
        assert len(obs.flight_recorder()) == 0
        assert obs.metrics_registry().metrics() == []

    def test_min_over_reps_unchanged_when_disabled(self):
        """The acceptance bar: obs disabled -> the timing path records
        nothing and the measurement result is structurally identical."""
        from tpu_patterns.core import timing

        obs.set_enabled(False)
        res = timing.min_over_reps(
            lambda: sum(range(100)), reps=3, warmup=1, barrier=None
        )
        assert len(res.times_ns) == 3
        assert len(obs.flight_recorder()) == 0
        obs.set_enabled(True)
        res = timing.min_over_reps(
            lambda: sum(range(100)), reps=3, warmup=1, barrier=None
        )
        assert len(res.times_ns) == 3
        names = [e["name"] for e in obs.flight_recorder().snapshot()]
        assert names == ["timing.min_over_reps"]


class TestFlightRecorder:
    def test_wraparound_keeps_newest(self):
        r = obs_recorder.FlightRecorder(capacity=8)
        for k in range(20):
            r.append({"kind": "event", "name": f"e{k}"})
        assert len(r) == 8
        assert [e["name"] for e in r.snapshot()] == [
            f"e{k}" for k in range(12, 20)
        ]
        assert r.dropped == 12

    def test_dump_parses_back_with_meta_and_open_spans(self, tmp_path):
        with obs.span("closed"):
            pass
        sp = obs.span("still-open", deadline_s=99)
        sp.__enter__()
        try:
            path = obs.dump(str(tmp_path / "d.jsonl"), reason="unit test")
        finally:
            sp.__exit__(None, None, None)
        lines = [json.loads(ln) for ln in open(path)]
        assert lines[0]["kind"] == "meta"
        assert lines[0]["reason"] == "unit test"
        opens = [ln for ln in lines if ln.get("open")]
        assert [o["name"] for o in opens] == ["still-open"]
        assert opens[0]["deadline_ns"] == 99e9
        # the loader skips meta and keeps both spans
        entries = obs_export.load_entries(path)
        assert {e["name"] for e in entries} == {"closed", "still-open"}


class TestWatchdog:
    def test_stalled_fake_collective_is_diagnosed_live(self, tmp_path):
        """The ISSUE's acceptance criterion: a deliberately hung span (a
        stalled fake collective) produces a flight-recorder dump + an
        all-thread stack file in the run directory and a WARNING Record,
        within the watchdog deadline (+ poll latency)."""
        obs.configure(str(tmp_path))
        before = set(obs.fired_dumps())
        release = threading.Event()

        def fake_collective():
            with obs.span(
                "comm.fake_collective", deadline_s=0.2, bytes=1 << 20
            ):
                release.wait(10)

        t = threading.Thread(
            target=fake_collective, name="fake-collective"
        )
        t.start()
        try:
            deadline = time.monotonic() + 6  # 0.2s deadline + poll slack
            while (
                set(obs.fired_dumps()) == before
                and time.monotonic() < deadline
            ):
                time.sleep(0.05)
            new = [p for p in obs.fired_dumps() if p not in before]
            assert new, "watchdog never fired on the stalled span"
        finally:
            release.set()
            t.join()
        (ring_path,) = new
        assert os.path.dirname(ring_path) == str(tmp_path)
        # the dump parses back, and the hung span rides in it, open
        lines = [json.loads(ln) for ln in open(ring_path)]
        assert lines[0]["kind"] == "meta"
        hung = [
            ln
            for ln in lines
            if ln.get("open") and ln["name"] == "comm.fake_collective"
        ]
        assert hung and hung[0]["attrs"] == {"bytes": 1 << 20}
        # the all-thread stack file names the stalled thread
        stacks_path = ring_path.replace(".jsonl", "_stacks.txt")
        assert os.path.exists(stacks_path)
        stacks = open(stacks_path).read()
        assert "fake-collective" in stacks and "fake_collective" in stacks
        # the WARNING Record landed in the run dir's watchdog stream
        from tpu_patterns.core.results import parse_log

        with open(tmp_path / "watchdog.jsonl") as f:
            (rec,) = parse_log(f.readlines())
        assert rec.verdict.value == "WARNING"
        assert rec.commands == "comm.fake_collective"
        assert rec.metrics["deadline_s"] == pytest.approx(0.2)

    def test_span_closing_in_time_never_fires(self):
        before = len(obs.fired_dumps())
        with obs.span("quick", deadline_s=30):
            pass
        time.sleep(1.2)  # two poll periods
        assert len(obs.fired_dumps()) == before


class TestMetrics:
    def test_counter_gauge_histogram(self):
        reg = obs_metrics.Registry()
        reg.counter("c", help="a counter").inc()
        reg.counter("c").inc(2)
        reg.gauge("g", shard="0").set(1.5)
        h = reg.histogram("h", buckets=(10, 100))
        for v in (5, 50, 500):
            h.observe(v)
        assert reg.counter("c").value == 3
        assert reg.gauge("g", shard="0").value == 1.5
        assert h.cumulative() == [(10, 1), (100, 2), (math.inf, 3)]
        assert h.sum == 555 and h.count == 3
        with pytest.raises(ValueError):
            reg.counter("c").inc(-1)

    def test_labels_distinguish_series(self):
        reg = obs_metrics.Registry()
        reg.counter("c", k="a").inc()
        reg.counter("c", k="b").inc(5)
        assert reg.counter("c", k="a").value == 1
        assert reg.counter("c", k="b").value == 5

    def test_prom_text_round_trips(self):
        reg = obs_metrics.Registry()
        reg.counter("steps_total", help="steps run").inc(7)
        reg.gauge("loss", optimizer="sgd").set(0.25)
        h = reg.histogram("lat_ns", buckets=(1000, 1000000), span="x")
        h.observe(500)
        h.observe(2000)
        text = reg.to_prom_text()
        assert "# TYPE steps_total counter" in text
        assert "# HELP steps_total steps run" in text
        samples = obs.parse_prom_text(text)
        assert samples[("steps_total", ())] == 7
        assert samples[("loss", (("optimizer", "sgd"),))] == 0.25
        assert samples[
            ("lat_ns_bucket", (("span", "x"), ("le", "1000")))
        ] == 1
        assert samples[
            ("lat_ns_bucket", (("span", "x"), ("le", "+Inf")))
        ] == 2
        assert samples[("lat_ns_sum", (("span", "x"),))] == 2500
        assert samples[("lat_ns_count", (("span", "x"),))] == 2

    def test_disagg_series_keep_the_naming_conventions(self):
        # the disagg wire accounting: three counters in the repo
        # namespace, _total-suffixed, renderable as Prometheus text —
        # and the transfers series is the handoff ledger's identity
        # counter (obs/decisions.py COUNTER_IDENTITIES)
        from tpu_patterns.obs.decisions import COUNTER_IDENTITIES

        assert COUNTER_IDENTITIES["handoff"] == (
            "tpu_patterns_disagg_transfers_total"
        )
        reg = obs_metrics.Registry()
        reg.counter("tpu_patterns_disagg_transfers_total").inc()
        reg.counter("tpu_patterns_disagg_adopted_blocks_total").inc(4)
        reg.counter("tpu_patterns_disagg_transfer_bytes_total").inc(
            8192
        )
        text = reg.to_prom_text()
        samples = obs.parse_prom_text(text)
        assert samples[("tpu_patterns_disagg_transfers_total", ())] == 1
        assert samples[
            ("tpu_patterns_disagg_adopted_blocks_total", ())
        ] == 4
        assert samples[
            ("tpu_patterns_disagg_transfer_bytes_total", ())
        ] == 8192

    def test_jsonl_round_trips_through_registry(self):
        reg = obs_metrics.Registry()
        reg.counter("c").inc(3)
        reg.gauge("g").set(-1.25)
        h = reg.histogram("h", buckets=(10, 100))
        h.observe(5)
        h.observe(50)
        back = obs_metrics.registry_from_jsonl(
            reg.to_jsonl().splitlines()
        )
        assert back.to_prom_text() == reg.to_prom_text()

    def test_span_layer_feeds_the_registry(self):
        with obs.span("fed"):
            pass
        h = obs.metrics_registry().histogram(
            "tpu_patterns_span_duration_ns", span="fed"
        )
        assert h.count == 1

    def test_pr7_serve_metric_names_export_cleanly(self):
        # the prefix-sharing / speculative-decoding series: counters
        # carry the _total suffix, the histogram exports bucket/sum/
        # count triplets, and everything shares the tpu_patterns_ glob
        reg = obs_metrics.Registry()
        reg.counter("tpu_patterns_serve_prefix_hit_blocks_total").inc(4)
        reg.counter("tpu_patterns_serve_cow_copies_total").inc()
        h = reg.histogram("tpu_patterns_serve_spec_accepted_tokens")
        h.observe(1)
        h.observe(5)
        text = reg.to_prom_text()
        assert (
            "# TYPE tpu_patterns_serve_prefix_hit_blocks_total counter"
            in text
        )
        assert (
            "# TYPE tpu_patterns_serve_cow_copies_total counter" in text
        )
        assert (
            "# TYPE tpu_patterns_serve_spec_accepted_tokens histogram"
            in text
        )
        samples = obs.parse_prom_text(text)
        assert samples[
            ("tpu_patterns_serve_prefix_hit_blocks_total", ())
        ] == 4
        assert samples[("tpu_patterns_serve_cow_copies_total", ())] == 1
        assert samples[
            ("tpu_patterns_serve_spec_accepted_tokens_count", ())
        ] == 2
        assert samples[
            ("tpu_patterns_serve_spec_accepted_tokens_sum", ())
        ] == 6


    def test_kv_tier_series_export_cleanly(self):
        # the tiered-KV-cache series (serve/engine.py evict/onload):
        # counters carry _total, the byte-traffic histograms export
        # bucket/sum/count triplets, all under the tpu_patterns_ glob
        reg = obs_metrics.Registry()
        reg.counter("tpu_patterns_serve_kv_evictions_total").inc(5)
        reg.counter("tpu_patterns_serve_kv_onload_hits_total").inc(2)
        reg.counter("tpu_patterns_serve_kv_tier_fallbacks_total").inc()
        ev = reg.histogram("tpu_patterns_serve_kv_evict_bytes")
        ev.observe(16384.0)
        ev.observe(32768.0)
        reg.histogram("tpu_patterns_serve_kv_onload_bytes").observe(
            16384.0
        )
        text = reg.to_prom_text()
        assert (
            "# TYPE tpu_patterns_serve_kv_evictions_total counter"
            in text
        )
        assert (
            "# TYPE tpu_patterns_serve_kv_evict_bytes histogram" in text
        )
        samples = obs.parse_prom_text(text)
        assert samples[
            ("tpu_patterns_serve_kv_evictions_total", ())
        ] == 5
        assert samples[
            ("tpu_patterns_serve_kv_onload_hits_total", ())
        ] == 2
        assert samples[
            ("tpu_patterns_serve_kv_evict_bytes_count", ())
        ] == 2
        assert samples[
            ("tpu_patterns_serve_kv_evict_bytes_sum", ())
        ] == 49152.0

    def test_store_series_export_cleanly(self):
        # the PR 20 fleet-prefix-store series (serve/engine.py store
        # section + replica.py prewarm): publish/fetch traffic
        # histograms export bucket/sum/count, counters carry _total
        reg = obs_metrics.Registry()
        reg.counter("tpu_patterns_store_publishes_total").inc(3)
        reg.counter("tpu_patterns_store_hits_total").inc(2)
        reg.counter("tpu_patterns_store_prewarms_total").inc(4)
        reg.counter("tpu_patterns_store_fallbacks_total").inc()
        reg.counter("tpu_patterns_fleet_prewarms_total").inc()
        pub = reg.histogram("tpu_patterns_store_publish_bytes")
        pub.observe(4096.0)
        pub.observe(4096.0)
        reg.histogram("tpu_patterns_store_fetch_bytes").observe(4096.0)
        text = reg.to_prom_text()
        assert (
            "# TYPE tpu_patterns_store_publishes_total counter" in text
        )
        assert (
            "# TYPE tpu_patterns_store_publish_bytes histogram" in text
        )
        samples = obs.parse_prom_text(text)
        assert samples[("tpu_patterns_store_publishes_total", ())] == 3
        assert samples[("tpu_patterns_store_hits_total", ())] == 2
        assert samples[("tpu_patterns_store_prewarms_total", ())] == 4
        assert samples[("tpu_patterns_store_fallbacks_total", ())] == 1
        assert samples[("tpu_patterns_fleet_prewarms_total", ())] == 1
        assert samples[
            ("tpu_patterns_store_publish_bytes_count", ())
        ] == 2
        assert samples[
            ("tpu_patterns_store_publish_bytes_sum", ())
        ] == 8192.0
        assert samples[
            ("tpu_patterns_store_fetch_bytes_count", ())
        ] == 1

    def test_router_and_replica_series_export_with_replica_label(self):
        # the PR-12 fleet series (serve/router.py, serve/replica.py):
        # routed / prefix-hit / reroute counters and the breaker-open
        # gauge, all keyed by the `replica` label graftlint knows
        reg = obs_metrics.Registry()
        reg.counter(
            "tpu_patterns_router_routed_total",
            replica="0", mode="prefix",
        ).inc(5)
        reg.counter(
            "tpu_patterns_router_prefix_hits_total", replica="0"
        ).inc(3)
        reg.counter(
            "tpu_patterns_router_reroutes_total", replica="1"
        ).inc()
        reg.gauge(
            "tpu_patterns_replica_breaker_open", replica="1"
        ).set(1.0)
        reg.counter(
            "tpu_patterns_replica_drains_total",
            replica="1", mode="drain",
        ).inc()
        text = reg.to_prom_text()
        assert "# TYPE tpu_patterns_router_routed_total counter" in text
        assert (
            "# TYPE tpu_patterns_replica_breaker_open gauge" in text
        )
        samples = obs.parse_prom_text(text)
        assert samples[(
            "tpu_patterns_router_routed_total",
            (("mode", "prefix"), ("replica", "0")),
        )] == 5
        assert samples[(
            "tpu_patterns_router_prefix_hits_total",
            (("replica", "0"),),
        )] == 3
        assert samples[(
            "tpu_patterns_router_reroutes_total", (("replica", "1"),)
        )] == 1
        assert samples[(
            "tpu_patterns_replica_breaker_open", (("replica", "1"),)
        )] == 1.0
        assert samples[(
            "tpu_patterns_replica_drains_total",
            (("mode", "drain"), ("replica", "1")),
        )] == 1

    def test_serve_latency_metric_names_export_cleanly(self):
        # the request-lifecycle series PR 8 wires out of the engine:
        # queue wait, TTFT, TPOT — histograms under the one namespace,
        # bucket/sum/count triplets round-tripping through prom text
        names = (
            "tpu_patterns_serve_queue_wait_ms",
            "tpu_patterns_serve_ttft_ms",
            "tpu_patterns_serve_tpot_ms",
        )
        reg = obs_metrics.Registry()
        for name in names:
            h = reg.histogram(name)
            h.observe(3.5)
            h.observe(10.0)
        text = reg.to_prom_text()
        samples = obs.parse_prom_text(text)
        for name in names:
            assert f"# TYPE {name} histogram" in text
            assert samples[(f"{name}_count", ())] == 2
            assert samples[(f"{name}_sum", ())] == 13.5

    def test_loadgen_slo_series_export_with_scenario_label(self):
        reg = obs_metrics.Registry()
        reg.gauge("tpu_patterns_loadgen_goodput", scenario="chat").set(
            0.875
        )
        reg.gauge(
            "tpu_patterns_loadgen_ttft_p99_ms", scenario="chat"
        ).set(120.5)
        reg.counter(
            "tpu_patterns_loadgen_requests_total",
            scenario="chat", status="done",
        ).inc(7)
        samples = obs.parse_prom_text(reg.to_prom_text())
        assert samples[
            ("tpu_patterns_loadgen_goodput", (("scenario", "chat"),))
        ] == 0.875
        assert samples[
            ("tpu_patterns_loadgen_ttft_p99_ms", (("scenario", "chat"),))
        ] == 120.5
        assert samples[(
            "tpu_patterns_loadgen_requests_total",
            (("scenario", "chat"), ("status", "done")),
        )] == 7

    def test_perf_series_export_with_executable_label(self):
        # the perfwatch series (perf/registry.py): per-executable
        # gauges keyed by the registry entry name, the capture counter
        # under the _total convention, everything in the one namespace
        reg = obs_metrics.Registry()
        reg.gauge(
            "tpu_patterns_perf_step_ms", executable="decoder.step"
        ).set(5.2)
        reg.gauge(
            "tpu_patterns_perf_analytic_flops", executable="decoder.step"
        ).set(966656.0)
        reg.gauge(
            "tpu_patterns_perf_achieved_gflops", executable="serve.step"
        ).set(0.13)
        reg.gauge(
            "tpu_patterns_perf_achieved_gbps", executable="serve.step"
        ).set(0.07)
        reg.counter("tpu_patterns_perf_captures_total").inc()
        text = reg.to_prom_text()
        assert "# TYPE tpu_patterns_perf_step_ms gauge" in text
        assert (
            "# TYPE tpu_patterns_perf_captures_total counter" in text
        )
        samples = obs.parse_prom_text(text)
        assert samples[(
            "tpu_patterns_perf_step_ms",
            (("executable", "decoder.step"),),
        )] == 5.2
        assert samples[(
            "tpu_patterns_perf_achieved_gflops",
            (("executable", "serve.step"),),
        )] == 0.13
        assert samples[("tpu_patterns_perf_captures_total", ())] == 1
        # and the dump replays losslessly (the history/debug path)
        back = obs_metrics.registry_from_jsonl(
            reg.to_jsonl().splitlines()
        )
        assert back.to_prom_text() == text

    def test_render_and_dump_text_are_byte_identical(self):
        # the /metrics scrape (obs/live.py) calls render(); the
        # --obs-dump/export path calls to_prom_text() — same state must
        # produce the same bytes, or dump and scrape disagree about a
        # run (the PR-15 render() satellite pin)
        reg = obs_metrics.Registry()
        reg.run_stamp = {"run_id": "pin", "git_sha": "0", "mesh_fp": "m"}
        reg.counter("tpu_patterns_scrape_pin_total", site="a").inc(3)
        reg.gauge("tpu_patterns_scrape_pin_gauge").set(-0.5)
        h = reg.histogram("tpu_patterns_scrape_pin_ns", buckets=(10,))
        h.observe(5)
        assert reg.render() == reg.to_prom_text()
        # and the scrape text round-trips through the parser
        assert obs.parse_prom_text(reg.render())[
            ("tpu_patterns_scrape_pin_total", (("site", "a"),))
        ] == 3

    def test_scrape_under_writer_load_is_lossless(self):
        # N writer threads hammer counters/gauges/histograms while M
        # scrapers render() concurrently: every intermediate render
        # must PARSE (no torn lines), and the final totals must be
        # lossless — the race-free-scrape contract /metrics relies on
        import threading

        reg = obs_metrics.Registry()
        reg.run_stamp = {"run_id": "load"}
        n_writers, per_writer = 4, 400
        stop = threading.Event()
        errors: list = []

        def write(k: int):
            c = reg.counter("tpu_patterns_writer_total", worker=str(k))
            shared = reg.counter("tpu_patterns_shared_total")
            h = reg.histogram(
                "tpu_patterns_writer_ns", buckets=(10, 100)
            )
            for i in range(per_writer):
                c.inc()
                shared.inc()
                h.observe(float(i % 200))
                reg.gauge("tpu_patterns_writer_gauge").set(float(i))

        def scrape():
            while not stop.is_set():
                try:
                    obs.parse_prom_text(reg.render())
                except Exception as e:  # pragma: no cover - the failure
                    errors.append(e)
                    return

        writers = [
            threading.Thread(target=write, args=(k,))
            for k in range(n_writers)
        ]
        scrapers = [threading.Thread(target=scrape) for _ in range(2)]
        for t in scrapers + writers:
            t.start()
        for t in writers:
            t.join()
        stop.set()
        for t in scrapers:
            t.join()
        assert not errors, f"scrape tore mid-write: {errors[0]}"
        samples = obs.parse_prom_text(reg.render())
        assert samples[("tpu_patterns_shared_total", ())] == (
            n_writers * per_writer
        )
        for k in range(n_writers):
            assert samples[(
                "tpu_patterns_writer_total", (("worker", str(k)),)
            )] == per_writer
        assert samples[("tpu_patterns_writer_ns_count", ())] == (
            n_writers * per_writer
        )

    def test_obs_http_and_slo_series_export_cleanly(self):
        # the live-telemetry-plane series (obs/live.py + obs/slo.py):
        # scrape accounting keyed by endpoint+status, burn-rate gauges
        # keyed by window, live percentile gauges, the shed counter —
        # naming-convention-clean and parseable
        reg = obs_metrics.Registry()
        reg.counter(
            "tpu_patterns_obs_http_requests_total",
            endpoint="metrics", status="200",
        ).inc(7)
        reg.counter(
            "tpu_patterns_obs_http_requests_total",
            endpoint="healthz", status="503",
        ).inc()
        reg.gauge("tpu_patterns_slo_burn_rate", window="fast").set(2.5)
        reg.gauge("tpu_patterns_slo_burn_rate", window="slow").set(0.8)
        reg.counter("tpu_patterns_slo_burn_warnings_total").inc()
        reg.gauge("tpu_patterns_slo_live_ttft_p99_ms").set(41.5)
        reg.gauge("tpu_patterns_slo_live_tpot_p99_ms").set(3.25)
        reg.counter("tpu_patterns_serve_shed_total").inc(5)
        text = reg.to_prom_text()
        assert (
            "# TYPE tpu_patterns_obs_http_requests_total counter" in text
        )
        assert "# TYPE tpu_patterns_slo_burn_rate gauge" in text
        samples = obs.parse_prom_text(text)
        assert samples[(
            "tpu_patterns_obs_http_requests_total",
            (("endpoint", "metrics"), ("status", "200")),
        )] == 7
        assert samples[(
            "tpu_patterns_slo_burn_rate", (("window", "fast"),)
        )] == 2.5
        assert samples[
            ("tpu_patterns_slo_live_ttft_p99_ms", ())
        ] == 41.5
        assert samples[("tpu_patterns_serve_shed_total", ())] == 5
        back = obs_metrics.registry_from_jsonl(
            reg.to_jsonl().splitlines()
        )
        assert back.to_prom_text() == text


class TestChromeTrace:
    def test_schema_and_ordering(self, tmp_path):
        with obs.span("outer", bytes=42):
            with obs.span("inner"):
                pass
            obs.event("mark")
        path = obs.dump(str(tmp_path / "s.jsonl"))
        trace = obs_export.chrome_trace(obs_export.load_entries(path))
        assert set(trace) == {"traceEvents", "displayTimeUnit"}
        evs = trace["traceEvents"]
        assert len(evs) == 3
        for ev in evs:
            # required trace_event fields
            assert {"name", "ph", "ts", "pid", "tid"} <= set(ev)
            assert ev["ph"] in ("X", "i")
            assert isinstance(ev["ts"], float)
            if ev["ph"] == "X":
                assert "dur" in ev
            else:
                assert ev["s"] == "t"
        assert [e["ts"] for e in evs] == sorted(e["ts"] for e in evs)
        outer = next(e for e in evs if e["name"] == "outer")
        assert outer["args"] == {"bytes": 42}
        json.dumps(trace)  # must be valid JSON end to end

    def test_complete_span_entries_get_named_request_lanes(self):
        # the serve engine books request lifecycles via complete_span
        # with an explicit lane; the exporter must name the lane and
        # keep the spans valid "X" events in the same timeline
        obs.complete_span(
            "req.queued", 1_000, 500, tid=1_000_042, rid=42,
            scenario="chat",
        )
        obs.complete_span(
            "req.decode", 1_500, 900, tid=1_000_042, rid=42,
            scenario="chat",
        )
        with obs.span("serve.step"):
            # scheduler-thread EVENTS also carry rid attrs; they must
            # NOT rename the scheduler's own lane to a request lane
            obs.event("serve.defer", rid="42")
        trace = obs_export.chrome_trace(obs.flight_recorder().snapshot())
        evs = trace["traceEvents"]
        (lane,) = [e for e in evs if e.get("ph") == "M"]
        assert lane["name"] == "thread_name"
        assert lane["tid"] == 1_000_042
        assert lane["args"]["name"] == "req 42 [chat]"
        decode = next(e for e in evs if e["name"] == "req.decode")
        assert decode["ph"] == "X"
        assert decode["ts"] == pytest.approx(1.5)  # ns -> us
        assert decode["dur"] == pytest.approx(0.9)
        assert decode["args"]["rid"] == 42
        # and the span-duration histogram was fed like any span
        h = obs.metrics_registry().histogram(
            "tpu_patterns_span_duration_ns", span="req.decode"
        )
        assert h.count == 1

    def test_complete_span_disabled_is_a_noop(self):
        obs.set_enabled(False)
        obs.complete_span("req.queued", 0, 10, tid=7, rid=1)
        assert len(obs.flight_recorder()) == 0

    def test_write_chrome_trace(self, tmp_path):
        with obs.span("s"):
            pass
        src = obs.dump(str(tmp_path / "s.jsonl"))
        out = obs_export.write_chrome_trace(
            obs_export.load_entries(src), str(tmp_path / "t.json")
        )
        assert json.load(open(out))["traceEvents"]


class TestSummaries:
    def test_span_stats_aggregates(self):
        entries = [
            {"kind": "span", "name": "a", "dur_ns": 2e6},
            {"kind": "span", "name": "a", "dur_ns": 4e6},
            {"kind": "span", "name": "b", "dur_ns": 1e6, "open": True},
            {"kind": "event", "name": "e"},
        ]
        stats = obs_export.span_stats(entries)
        assert stats["a"]["count"] == 2
        assert stats["a"]["total_ms"] == pytest.approx(6.0)
        assert stats["a"]["mean_ms"] == pytest.approx(3.0)
        assert stats["a"]["max_ms"] == pytest.approx(4.0)
        assert stats["b"]["open"] == 1

    def test_summarize_renders(self):
        with obs.span("render.me"):
            pass
        out = obs_export.summarize(obs.flight_recorder().snapshot())
        assert "render.me" in out


def _write_dump(path, entries, wall_ts, clock_ns_base):
    """A fake flight-recorder dump: meta header carrying the (wall,
    monotonic) clock pair the fleet merge aligns processes with."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(json.dumps({
            "kind": "meta", "reason": "test", "pid": 1,
            "capacity": 16, "entries": len(entries), "dropped": 0,
            "wall_ts": wall_ts, "clock_ns": clock_ns_base,
        }) + "\n")
        for e in entries:
            f.write(json.dumps(e) + "\n")


def _span(name, t0, dur, tid, span_id, **attrs):
    return {"kind": "span", "name": name, "t0_ns": t0, "dur_ns": dur,
            "span_id": span_id, "parent_id": 0, "depth": 0, "tid": tid,
            "thread": "t", "attrs": attrs}


def _event(name, t0, tid, **attrs):
    return {"kind": "event", "name": name, "t0_ns": t0, "dur_ns": 0,
            "span_id": 0, "parent_id": 0, "depth": 0, "tid": tid,
            "thread": "t", "attrs": attrs}


def _fake_fleet_dir(root):
    """Parent + two replica dirs telling one rerouted-request story:
    route -> admit/fail @r1 -> reroute -> done @r0, on three different
    monotonic clocks that only the meta pairs can align."""
    _write_dump(
        os.path.join(root, "spans.jsonl"),
        [
            _event("journey.route", 100, 77, jid="j1", rid="0",
                   replica="1"),
            _event("journey.reroute", 300, 77, jid="j1", rid="0",
                   replica="0"),
        ],
        wall_ts=1000.0, clock_ns_base=0,
    )
    _write_dump(
        os.path.join(root, "replica-0", "spans.jsonl"),
        [
            _span("req.queued", 1_000_150, 50, 1_000_000, 5, rid=0,
                  jid="j1", replica="0"),
            _span("req.retired", 1_000_400, 0, 1_000_000, 6, rid=0,
                  jid="j1", replica="0"),
        ],
        wall_ts=1000.0, clock_ns_base=1_000_000,
    )
    failed = _span("req.failed", 2_000_150, 0, 1_000_000, 5, rid=0,
                   jid="j1", replica="1")
    _write_dump(
        os.path.join(root, "replica-1", "spans.jsonl"),
        [failed],
        wall_ts=1000.0, clock_ns_base=2_000_000,
    )
    # the shipped copy of the SAME span, still open: the per-process
    # dedupe must collapse it, closed-beats-open
    _write_dump(
        os.path.join(root, "replica-1", "shipped.jsonl"),
        [{**failed, "open": True}],
        wall_ts=1000.0, clock_ns_base=2_000_000,
    )


class TestDedupeMultiProcess:
    def test_dir_dump_and_shipped_batch_collapse(self):
        # the fleet overlap: a replica's own dump and the shipped copy
        # of the same ring — closed beats open, first-seen order stable
        closed = _span("req.failed", 10, 5, 1, 3, rid=0)
        open_twin = {**closed, "open": True}
        other = _span("serve.step", 20, 5, 1, 4)
        out = obs_export.dedupe_entries([open_twin, other, closed])
        assert out == [closed, other]

    def test_same_ids_from_different_replicas_stay_apart(self):
        # span ids and monotonic clocks restart per process: identical
        # (span_id, t0, tid, name) from two replicas are DIFFERENT spans
        a = {**_span("req.queued", 10, 5, 1, 3, rid=0), "replica": "0"}
        b = {**_span("req.queued", 10, 5, 1, 3, rid=0), "replica": "1"}
        assert obs_export.dedupe_entries([a, b]) == [a, b]


class TestFleetMerge:
    def test_merge_aligns_clocks_tags_processes_and_dedupes(
        self, tmp_path
    ):
        from tpu_patterns.obs import fleet as obs_fleet

        _fake_fleet_dir(str(tmp_path))
        merged, procs = obs_fleet.merge_fleet(str(tmp_path))
        assert procs[obs_fleet.ROUTER_PID] == "router"
        assert procs[0] == "replica 0" and procs[1] == "replica 1"
        # the shipped open twin collapsed into the closed dir-dump span
        fails = [e for e in merged if e["name"] == "req.failed"]
        assert len(fails) == 1 and not fails[0].get("open")
        # three different monotonic clocks, ONE wall-aligned timeline
        order = [e["name"] for e in merged]
        assert order == [
            "journey.route", "req.queued", "req.failed",
            "journey.reroute", "req.retired",
        ]
        assert merged[0]["t0_ns"] == 0  # rebased to the earliest entry
        assert merged[0]["pid"] == obs_fleet.ROUTER_PID
        assert fails[0]["pid"] == 1 and fails[0]["replica"] == "1"

    def test_merged_chrome_trace_has_replica_lanes_and_one_flow(
        self, tmp_path
    ):
        from tpu_patterns.obs import fleet as obs_fleet

        _fake_fleet_dir(str(tmp_path))
        merged, procs = obs_fleet.merge_fleet(str(tmp_path))
        trace = obs_export.chrome_trace(merged, process_names=procs)
        evs = trace["traceEvents"]
        pnames = {
            ev["pid"]: ev["args"]["name"]
            for ev in evs
            if ev["ph"] == "M" and ev["name"] == "process_name"
        }
        assert pnames[0] == "replica 0" and pnames[1] == "replica 1"
        assert pnames[obs_fleet.ROUTER_PID] == "router"
        # the lane-collision fix: both replicas restart rids at 0, the
        # merged lanes qualify the window by replica id
        lanes = {
            (ev["pid"], ev["args"]["name"])
            for ev in evs
            if ev["ph"] == "M" and ev["name"] == "thread_name"
        }
        assert (0, "req 0 @r0") in lanes
        assert (1, "req 0 @r1") in lanes
        # the journey renders as ONE flow: s at the router's route,
        # f at the rerouted completion on replica 0
        flows = [ev for ev in evs if ev.get("ph") in ("s", "t", "f")]
        assert {f["id"] for f in flows} == {"j1"}
        assert [f["ph"] for f in flows].count("s") == 1
        assert [f["ph"] for f in flows].count("f") == 1
        s = next(f for f in flows if f["ph"] == "s")
        fin = next(f for f in flows if f["ph"] == "f")
        assert s["pid"] == obs_fleet.ROUTER_PID
        assert fin["pid"] == 0
        # the failed leg is a mid-journey step on replica 1
        assert any(
            f["ph"] == "t" and f["pid"] == 1 for f in flows
        )
        json.dumps(trace)

    def test_journey_table_tells_the_whole_story(self, tmp_path):
        from tpu_patterns.obs import fleet as obs_fleet

        _fake_fleet_dir(str(tmp_path))
        merged, _ = obs_fleet.merge_fleet(str(tmp_path))
        out = obs_fleet.journey_table(merged, "j1")
        assert "journey j1" in out
        for token in ("journey.route", "req.failed", "journey.reroute",
                      "req.retired", "router", "replica 1",
                      "replica 0"):
            assert token in out
        # a rid resolves to its journey too
        assert obs_fleet.resolve_journey(merged, "0") == "j1"
        assert "no journey" in obs_fleet.journey_table(merged, "999")

    def test_reset_base_drops_stale_replica_dirs(self, tmp_path):
        # the default obs dir is fixed, never timestamped: a new fleet
        # must claim the replica-* namespace or `obs fleet` would merge
        # last run's shipped spans (append-mode!) and ghost replicas
        from tpu_patterns.obs import fleet as obs_fleet

        _fake_fleet_dir(str(tmp_path))
        fo = obs_fleet.FleetObs(str(tmp_path))
        fo.reset_base()
        merged, procs = obs_fleet.merge_fleet(str(tmp_path))
        # the parent's own dumps survive; every replica dir is gone
        assert set(procs) == {obs_fleet.ROUTER_PID}
        assert all(e.get("replica") is None for e in merged)
        obs_fleet.FleetObs(None).reset_base()  # in-memory: a no-op

    def test_fleet_series_naming_keeps_the_conventions(self):
        from tpu_patterns.obs import fleet as obs_fleet

        # the graftlint metric-naming contract, applied to the DYNAMIC
        # fleet namespace: prefix preserved, counters keep _total
        assert obs_fleet.fleet_name(
            "tpu_patterns_serve_requests_total"
        ) == "tpu_patterns_fleet_serve_requests_total"
        assert obs_fleet.fleet_name(
            "tpu_patterns_serve_requests_total"
        ).endswith("_total")
        with pytest.raises(ValueError):
            obs_fleet.fleet_name("rogue_series")

    def test_fleet_series_export_with_replica_label(self):
        # shipped child counters merge into tpu_patterns_fleet_* and
        # export like any first-class series
        reg = obs_metrics.Registry()
        reg.counter(
            "tpu_patterns_fleet_serve_requests_total", replica="0"
        ).inc(5)
        reg.counter(
            "tpu_patterns_fleet_serve_requests_total", replica="1"
        ).inc(3)
        reg.counter(
            "tpu_patterns_fleet_replica_drains_total",
            replica="1", mode="checkpoint",
        ).inc()
        text = reg.to_prom_text()
        assert (
            "# TYPE tpu_patterns_fleet_serve_requests_total counter"
            in text
        )
        samples = obs.parse_prom_text(text)
        assert samples[(
            "tpu_patterns_fleet_serve_requests_total",
            (("replica", "0"),),
        )] == 5
        assert samples[(
            "tpu_patterns_fleet_serve_requests_total",
            (("replica", "1"),),
        )] == 3
        from tpu_patterns import rt

        assert rt.metric_total(
            "tpu_patterns_fleet_serve_requests_total", registry=reg
        ) == 8.0
        assert rt.metric_total(
            "tpu_patterns_fleet_serve_requests_total",
            registry=reg, replica="1",
        ) == 3.0

    def test_elastic_series_export_with_their_labels(self):
        # the PR 16 elastic plane's series: scale events keyed by
        # action + replica, preemptions and sheds keyed by priority
        # class — all first-class prom exports
        reg = obs_metrics.Registry()
        reg.counter(
            "tpu_patterns_fleet_scale_events_total",
            action="out", replica="2",
        ).inc()
        reg.counter(
            "tpu_patterns_fleet_scale_events_total",
            action="in", replica="2",
        ).inc(2)
        reg.counter(
            "tpu_patterns_serve_preempted_total", priority="bulk"
        ).inc(3)
        reg.counter(
            "tpu_patterns_serve_shed_total", priority="interactive"
        ).inc()
        text = reg.to_prom_text()
        assert (
            "# TYPE tpu_patterns_fleet_scale_events_total counter"
            in text
        )
        assert (
            "# TYPE tpu_patterns_serve_preempted_total counter" in text
        )
        samples = obs.parse_prom_text(text)
        assert samples[(
            "tpu_patterns_fleet_scale_events_total",
            (("action", "out"), ("replica", "2")),
        )] == 1
        assert samples[(
            "tpu_patterns_fleet_scale_events_total",
            (("action", "in"), ("replica", "2")),
        )] == 2
        assert samples[(
            "tpu_patterns_serve_preempted_total",
            (("priority", "bulk"),),
        )] == 3
        from tpu_patterns import rt

        assert rt.metric_total(
            "tpu_patterns_fleet_scale_events_total", registry=reg
        ) == 3.0
        assert rt.metric_total(
            "tpu_patterns_serve_shed_total",
            registry=reg, priority="interactive",
        ) == 1.0

    def test_cost_and_decision_series_export(self):
        # the PR 17 attribution plane's series are first-class prom
        # exports: per-class cost counters booked by a real CostBook,
        # the ledger's per-action identity counter, and the shed-rung
        # series — through the default registry, as the engine does it
        from tpu_patterns.obs.cost import CostBook
        from tpu_patterns.obs.decisions import DecisionLedger

        book = CostBook(pool_blocks=4)
        book.start(0)
        book.book_decode(
            1_000_001,
            [(0, "chat", "interactive"), (1, "chat", "bulk")],
        )
        book.book_prefill(500_000, [(1, "chat", "bulk")])
        book.hold(0, 2, scenario="chat", priority="interactive")
        book.drop(0)
        led = DecisionLedger()
        led.book("defer", rid=0, rationale="pool pressure", free=0)
        led.book("preempt", rid=1, jid="j-1", banked=4)
        obs.counter(
            "tpu_patterns_decision_shed_rung_total", rung="bulk"
        ).inc()
        text = obs.metrics_registry().to_prom_text()
        assert "# TYPE tpu_patterns_cost_decode_ns_total counter" in text
        samples = obs.parse_prom_text(text)
        # the odd nanosecond lands on the first row: the exported
        # per-class split closes the measured wall exactly
        assert samples[(
            "tpu_patterns_cost_decode_ns_total",
            (("priority", "interactive"),),
        )] == 500_001
        assert samples[(
            "tpu_patterns_cost_decode_ns_total",
            (("priority", "bulk"),),
        )] == 500_000
        assert samples[(
            "tpu_patterns_cost_prefill_ns_total",
            (("priority", "bulk"),),
        )] == 500_000
        assert (
            "tpu_patterns_cost_block_ns_total",
            (("priority", "interactive"),),
        ) in samples
        assert samples[(
            "tpu_patterns_decision_events_total",
            (("action", "defer"),),
        )] == 1
        assert samples[(
            "tpu_patterns_decision_events_total",
            (("action", "preempt"),),
        )] == 1
        assert samples[(
            "tpu_patterns_decision_shed_rung_total",
            (("rung", "bulk"),),
        )] == 1


class TestObsShipper:
    def test_tap_feeds_deltas_and_metrics_ship_once(self):
        from tpu_patterns.obs import fleet as obs_fleet

        shipper = obs_fleet.ObsShipper(max_batch=8)
        try:
            with obs.span("shipped.region"):
                pass
            obs.counter("tpu_patterns_test_ship_total").inc(2)
            b1 = shipper.batch()
            assert [e["name"] for e in b1["entries"]] == [
                "shipped.region"
            ]
            assert {
                m["metric"]: m["value"] for m in b1["metrics"]
            }["tpu_patterns_test_ship_total"] == 2.0
            # nothing changed: no batch at the next boundary
            assert shipper.batch() is None
            # a counter moves: only the DELTA-carrying series reships,
            # as its new cumulative value
            obs.counter("tpu_patterns_test_ship_total").inc()
            b2 = shipper.batch()
            assert b2["entries"] == []
            assert {
                m["metric"]: m["value"] for m in b2["metrics"]
            } == {"tpu_patterns_test_ship_total": 3.0}
        finally:
            shipper.close()

    def test_closed_tap_stops_feeding(self):
        from tpu_patterns.obs import fleet as obs_fleet

        shipper = obs_fleet.ObsShipper()
        shipper.close()
        obs.event("after.close")
        assert not shipper._tap


class TestObsCLI:
    def _dump_some_spans(self, d):
        with obs.span("cli.span", n=1):
            pass
        obs.dump(os.path.join(d, "spans.jsonl"))
        obs.dump_metrics(os.path.join(d, "metrics.jsonl"))

    def test_summarize(self, tmp_path, capsys):
        from tpu_patterns.cli import main

        self._dump_some_spans(str(tmp_path))
        rc = main(["--obs-dir", str(tmp_path), "obs", "summarize"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "cli.span" in out

    def test_export_both(self, tmp_path, capsys):
        from tpu_patterns.cli import main

        self._dump_some_spans(str(tmp_path))
        trace_out = tmp_path / "trace.json"
        rc = main([
            "--obs-dir", str(tmp_path), "obs", "export",
            "--chrome-trace", str(trace_out), "--prom",
        ])
        assert rc == 0
        assert json.load(open(trace_out))["traceEvents"]
        samples = obs.parse_prom_text(capsys.readouterr().out)
        assert any(
            name == "tpu_patterns_span_duration_ns_count"
            for name, _ in samples
        )

    def test_export_without_target_is_an_error(self, tmp_path):
        from tpu_patterns.cli import main

        with pytest.raises(SystemExit):
            main(["--obs-dir", str(tmp_path), "obs", "export"])

    def test_summarize_empty_dir_is_an_error(self, tmp_path):
        from tpu_patterns.cli import main

        with pytest.raises(SystemExit):
            main(["--obs-dir", str(tmp_path), "obs", "summarize"])

    def test_fleet_merges_and_exports(self, tmp_path, capsys):
        from tpu_patterns.cli import main
        from tpu_patterns.obs import fleet as obs_fleet

        _fake_fleet_dir(str(tmp_path))
        rc = main(["obs", "fleet", str(tmp_path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "req.failed" in out  # merged summarize saw child spans
        trace = json.load(open(tmp_path / "fleet_trace.json"))
        pids = {
            ev["pid"]
            for ev in trace["traceEvents"]
            if ev.get("ph") == "M" and ev["name"] == "process_name"
        }
        assert {0, 1, obs_fleet.ROUTER_PID} <= pids

    def test_fleet_empty_dir_is_an_error(self, tmp_path):
        from tpu_patterns.cli import main

        with pytest.raises(SystemExit):
            main(["obs", "fleet", str(tmp_path)])

    def test_journey_by_jid_and_rid(self, tmp_path, capsys):
        from tpu_patterns.cli import main

        _fake_fleet_dir(str(tmp_path))
        rc = main(["--obs-dir", str(tmp_path), "obs", "journey", "j1"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "journey j1" in out and "req.failed" in out
        rc = main(["--obs-dir", str(tmp_path), "obs", "journey", "0"])
        assert rc == 0
        assert "journey j1" in capsys.readouterr().out

    def test_journey_without_target_is_an_error(self, tmp_path):
        from tpu_patterns.cli import main

        _fake_fleet_dir(str(tmp_path))
        with pytest.raises(SystemExit):
            main(["--obs-dir", str(tmp_path), "obs", "journey"])

    def test_host_device_join_reads_profile(self, tmp_path, capsys):
        from tpu_patterns.cli import main

        self._dump_some_spans(str(tmp_path))
        fixdir = os.path.join(os.path.dirname(__file__), "fixtures")
        rc = main([
            "--obs-dir", str(tmp_path), "obs", "summarize",
            "--profile-dir", fixdir,
        ])
        assert rc == 0
        out = capsys.readouterr().out
        # host spans and every device engine bucket in ONE report
        assert "cli.span" in out
        for token in ("MXU", "ICI", "HBM"):
            assert token in out

    def test_cost_merges_dumps_and_writes_rollup(self, tmp_path, capsys):
        from tpu_patterns.cli import main
        from tpu_patterns.obs.cost import CostBook

        book = CostBook(pool_blocks=4)
        book.start(0)
        book.book_decode(1_000_000, [(0, "chat", "interactive")])
        (tmp_path / "cost.jsonl").write_text(book.to_jsonl())
        rep = tmp_path / "replica-0"
        rep.mkdir()
        child = CostBook(pool_blocks=4, replica="0")
        child.start(0)
        child.book_decode(2_000_000, [(1, "chat", "bulk")])
        (rep / "cost.jsonl").write_text(child.to_jsonl())
        rc = main(["--obs-dir", str(tmp_path), "obs", "cost"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "identities OK" in out
        assert "replica 0" in out  # the child dump merged in
        roll = [
            json.loads(ln)
            for ln in (tmp_path / "cost_rollup.jsonl").read_text()
            .splitlines()
        ]
        by_cls = {r["key"]: r for r in roll if r["by"] == "priority"}
        assert by_cls["bulk"]["decode_ns"] == 2_000_000
        assert by_cls["interactive"]["decode_ns"] == 1_000_000

    def test_cost_empty_dir_is_an_error(self, tmp_path):
        from tpu_patterns.cli import main

        with pytest.raises(SystemExit, match="no cost.jsonl"):
            main(["--obs-dir", str(tmp_path), "obs", "cost"])

    def test_explain_by_rid_and_by_action(self, tmp_path, capsys):
        from tpu_patterns.cli import main

        _fake_fleet_dir(str(tmp_path))
        # a decision instant on the parent's timeline, same request
        with open(tmp_path / "spans.jsonl", "a") as f:
            f.write(json.dumps(_event(
                "decision.preempt", 200, 77, rid="0",
                rationale="bulk victim parked", banked="3",
            )) + "\n")
        rc = main(["--obs-dir", str(tmp_path), "obs", "explain", "0"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "story for 0" in out
        assert "decision.preempt" in out
        assert "bulk victim parked" in out
        assert "req.retired" in out  # lifecycle context rides along
        rc = main([
            "--obs-dir", str(tmp_path), "obs", "explain",
            "--action", "preempt",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        assert "decision.preempt fleet-wide" in out

    def test_explain_without_target_or_action_is_an_error(
        self, tmp_path
    ):
        from tpu_patterns.cli import main

        _fake_fleet_dir(str(tmp_path))
        with pytest.raises(SystemExit, match="obs explain"):
            main(["--obs-dir", str(tmp_path), "obs", "explain"])
        with pytest.raises(SystemExit, match="unknown --action"):
            main([
                "--obs-dir", str(tmp_path), "obs", "explain",
                "--action", "panic",
            ])
