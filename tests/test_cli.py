"""CLI + sweep drivers (SURVEY.md C7, C11, C12)."""

import dataclasses
import json
import os
import sys

import pytest

from tpu_patterns import sweep
from tpu_patterns.cli import build_parser, main

FAST_P2P = ["--count", "8192", "--reps", "2", "--warmup", "1"]


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f]


class TestParser:
    def test_subcommands_parse(self):
        p = build_parser()
        for argv in (
            ["p2p", "--transport", "one_sided", "--devices", "2"],
            ["concurrency", "--backend", "pallas", "--mode", "dma_overlap"],
            ["allreduce", "--variant", "pallas", "--algorithm", "ring_opt"],
            ["miniapps", "--devices", "4"],
            ["topo"],
            ["topo", "3"],
            ["interop"],
            ["sweep", "p2p", "--quick"],
            ["report", "x.log"],
            ["hlocheck", "--seq", "1024", "--depth", "2"],
            ["obs", "summarize"],
            ["obs", "export", "--chrome-trace", "t.json", "--prom"],
            ["obs", "fleet", "results/obs"],
            ["obs", "fleet"],
            ["obs", "journey", "j1a2b-3"],
            ["doctor", "--watch_jsonl", "w.jsonl"],
            ["perf", "report", "--tp", "2"],
            ["perf", "diff", "--include", "serve.step",
             "--measured_tol", "0.5"],
            ["perf", "update-baseline", "--baseline", "b.json"],
            ["perf", "prune-stale"],
            ["lint", "--tier", "c"],
            ["lint", "--tier", "all", "--format", "github"],
            ["lint", "--prune-stale"],
            ["lint", "--strict", "--rules", "clock-discipline",
             "--tier", "a"],
        ):
            args = p.parse_args(argv)
            assert args.cmd == argv[0]

    def test_serve_fleet_mitigation_combo_parses(self):
        # parse-pin for the PR 16 unlock: --replicas together with
        # --burn_mitigation (the ladder runs per-replica now) plus the
        # whole elastic/priority flag family
        args = build_parser().parse_args([
            "serve", "--replicas", "2", "--burn_mitigation", "shed",
            "--scenario", "diurnal:bulk_fraction=0.4",
            "--kv_host_tier", "true", "--preempt", "bulk",
            "--elastic_reserve", "1",
            "--scale_out_occupancy", "1.5",
            "--scale_in_occupancy", "0.2",
            "--scale_sustain_s", "0.25",
            "--scale_cooldown_s", "1.0",
            "--min_live_replicas", "1",
        ])
        assert args.cmd == "serve"
        assert args.replicas == 2
        assert args.burn_mitigation == "shed"
        assert args.preempt == "bulk"
        assert args.kv_host_tier is True
        assert args.elastic_reserve == 1
        assert args.scale_out_occupancy == 1.5
        assert args.scale_in_occupancy == 0.2
        assert args.scale_sustain_s == 0.25
        assert args.scale_cooldown_s == 1.0
        assert args.min_live_replicas == 1
        assert args.scenario == "diurnal:bulk_fraction=0.4"

    def test_serve_disagg_flag_surface(self):
        # the disagg split parses with its gate knob...
        args = build_parser().parse_args([
            "serve", "--replicas", "4", "--disagg", "2:2",
            "--min_ttft_improvement", "1.05",
        ])
        assert args.disagg == "2:2"
        assert args.min_ttft_improvement == 1.05
        # ...and every malformed combo exits loudly at parse time:
        # no fleet, bad grammar, P or D empty, P+D != N, elastic combo
        for argv in (
            ["serve", "--disagg", "1:1"],
            ["serve", "--replicas", "2", "--disagg", "11"],
            ["serve", "--replicas", "2", "--disagg", "1:1:1"],
            ["serve", "--replicas", "2", "--disagg", "2:0"],
            ["serve", "--replicas", "2", "--disagg", "0:2"],
            ["serve", "--replicas", "4", "--disagg", "2:3"],
            ["serve", "--replicas", "3", "--disagg", "2:1",
             "--scenario", "diurnal", "--elastic_reserve", "1"],
        ):
            with pytest.raises(SystemExit):
                main(argv)

    def test_serve_prefix_store_flag_surface(self):
        # the fleet prefix store parses with its fleet context...
        args = build_parser().parse_args([
            "serve", "--replicas", "2", "--prefix_store", "/tmp/ps",
            "--kv_host_tier", "true", "--prefix_share", "true",
        ])
        assert args.prefix_store == "/tmp/ps"
        assert args.kv_host_tier is True
        # ...and every unservable combo exits loudly at PARSE time
        # (the silent-accept path where fleet children dropped the
        # flag is gone): no host tier, no fleet, disagg split, and
        # the routing A/B (store warmth would leak between its legs)
        for argv in (
            ["serve", "--replicas", "2", "--prefix_store", "/tmp/ps"],
            ["serve", "--prefix_store", "/tmp/ps",
             "--kv_host_tier", "true"],
            ["serve", "--replicas", "4", "--disagg", "2:2",
             "--prefix_store", "/tmp/ps", "--kv_host_tier", "true"],
            ["serve", "--replicas", "2", "--prefix_store", "/tmp/ps",
             "--kv_host_tier", "true", "--scenario", "prefix_aware"],
        ):
            with pytest.raises(SystemExit):
                main(argv)

    def test_config_fields_become_flags(self):
        args = build_parser().parse_args(["p2p", "--count", "123", "--dtype", "bfloat16"])
        assert args.count == 123 and args.dtype == "bfloat16"

    def test_concurrency_env_tier(self, monkeypatch):
        # add_config_args gives concurrency the same env tier as the rest.
        monkeypatch.setenv("TPU_PATTERNS_TRIPCOUNT", "777")
        args = build_parser().parse_args(["concurrency"])
        assert args.tripcount == 777

    def test_allreduce_typo_exits_loudly(self):
        # user-input errors must not become SKIPPED (exit 0)
        with pytest.raises(SystemExit):
            main(["allreduce", "--algorithm", "ringg", "--devices", "4"])
        with pytest.raises(SystemExit):
            main(["allreduce", "--mem_kind", "X", "--devices", "4"])


class TestCommands:
    def test_p2p_two_sided(self, tmp_path):
        jl = tmp_path / "p2p.jsonl"
        rc = main(["--jsonl", str(jl), "p2p", *FAST_P2P, "--devices", "8"])
        assert rc == 0
        recs = _read_jsonl(jl)
        assert {r["mode"] for r in recs} == {"unidirectional", "bidirectional"}
        assert all(r["verdict"] == "SUCCESS" for r in recs)

    def test_p2p_skips_on_odd_world(self, tmp_path):
        jl = tmp_path / "p2p.jsonl"
        rc = main(["--jsonl", str(jl), "p2p", *FAST_P2P, "--devices", "3"])
        assert rc == 0
        (rec,) = _read_jsonl(jl)
        assert rec["verdict"] == "SKIPPED"

    def test_allreduce(self, tmp_path):
        jl = tmp_path / "ar.jsonl"
        rc = main(
            ["--jsonl", str(jl), "allreduce", "--devices", "4", "--elements",
             "1024", "--reps", "2", "--algorithm", "ring_opt"]
        )
        assert rc == 0
        (rec,) = _read_jsonl(jl)
        assert rec["verdict"] == "SUCCESS"
        assert rec["mode"] == "xla:ring_opt"

    def test_concurrency(self, tmp_path):
        jl = tmp_path / "con.jsonl"
        rc = main(
            ["--jsonl", str(jl), "concurrency", "--mode", "concurrent",
             "--commands", "C C", "--tripcount", "200", "--elements", "256",
             "--reps", "2"]
        )
        (rec,) = _read_jsonl(jl)
        assert rec["mode"] == "xla:concurrent"
        assert rc == (0 if rec["verdict"] == "SUCCESS" else 1)

    def test_miniapps(self, tmp_path):
        jl = tmp_path / "mini.jsonl"
        rc = main(
            ["--jsonl", str(jl), "miniapps", "--devices", "4", "--elements",
             "512", "--reps", "2"]
        )
        assert rc == 0
        recs = _read_jsonl(jl)
        assert len(recs) >= 5  # the full typed-variant matrix

    def test_topo(self, capsys):
        assert main(["topo"]) == 0
        out = capsys.readouterr().out
        assert "devices: 8" in out and "placement compact:" in out
        assert main(["topo", "2"]) == 0
        n = int(capsys.readouterr().out.strip())
        assert 0 <= n < 8

    def test_interop(self, tmp_path):
        jl = tmp_path / "interop.jsonl"
        rc = main(["--jsonl", str(jl), "interop"])
        recs = _read_jsonl(jl)
        assert recs, "interop must emit records"
        if recs[0]["verdict"] == "SKIPPED":
            pytest.skip(f"native module unavailable: {recs[0]['notes']}")
        assert rc == 0
        got = {r["commands"] for r in recs}
        assert got >= {
            "clock", "checksum", "saxpy", "raw_info",
            "offload_checksum", "offload_saxpy",
        }
        assert all(r["verdict"] == "SUCCESS" for r in recs)

    def test_flagship_zero_offload(self, tmp_path):
        # the full offload path: zero_opts parsing, pinned_host state
        # staging, and the .jitted/abstract-state memory-analysis branch
        jl = tmp_path / "flag.jsonl"
        rc = main(
            ["--jsonl", str(jl), "flagship", "--attn", "xla",
             "--optimizer", "zero-adam-offload", "--dp", "2",
             "--embed", "64", "--head_dim", "8", "--seq", "128",
             "--batch", "4", "--dtype", "float32", "--reps", "2"]
        )
        assert rc == 0
        (rec,) = _read_jsonl(jl)
        assert rec["mode"] == "xla_zero-adam-offload"
        assert rec["verdict"] == "SUCCESS"
        assert rec["metrics"].get("peak_temp_MB", 0) > 0

    def test_report(self, tmp_path, capsys):
        log = tmp_path / "x.log"
        log.write_text(
            "export TPU_PATTERNS_SWEEP_CONFIG=cfg1\n"
            "## serial | C C | SUCCESS\n"
            "## concurrent | C C | FAILURE\n"
        )
        assert main(["report", str(log)]) == 0
        out = capsys.readouterr().out
        assert "SUCCESS" in out and "FAILURE" in out and "cfg1" in out

    def test_report_refuses_unmarked_prefix_grad_records(
        self, tmp_path, capsys
    ):
        """A grad rate captured before the FLOP-accounting fix credits
        dead-code-eliminated kernels; `report` must refuse it unless the
        archive marks the row superseded (VERDICT r3 next #8)."""
        import pytest

        from tpu_patterns.core.results import (
            GRAD_ACCOUNTING_FIX_TS,
            Record,
            Verdict,
        )

        stale = Record(
            pattern="longctx",
            mode="flash_grad",
            commands="sp1 L4096 grad",
            metrics={"tflops": 189.7},
            timestamp=GRAD_ACCOUNTING_FIX_TS - 100.0,
        )
        log = tmp_path / "grad.jsonl"
        log.write_text(stale.to_json() + "\n")
        with pytest.raises(SystemExit) as ei:
            main(["report", str(log)])
        assert ei.value.code == 2
        assert "REFUSED" in capsys.readouterr().err
        # marked superseded -> tabulated, but branded as provenance
        marked = dataclasses.replace(stale, superseded=True)
        log.write_text(marked.to_json() + "\n")
        assert main(["report", str(log)]) == 0
        assert "SUPERSEDED" in capsys.readouterr().out
        # post-fix grad records tabulate normally
        clean = dataclasses.replace(
            stale, timestamp=GRAD_ACCOUNTING_FIX_TS + 100.0
        )
        log.write_text(clean.to_json() + "\n")
        assert main(["report", str(log)]) == 0
        assert "SUPERSEDED" not in capsys.readouterr().out

    def test_committed_grad_archive_is_marked(self):
        """The six retracted rows in the committed archive must stay
        marked — report over the real file must not refuse."""
        import pathlib

        from tpu_patterns.core.results import parse_log, stale_grad_records

        path = (
            pathlib.Path(__file__).parent.parent
            / "docs"
            / "measured"
            / "flash_tpu_v5e.jsonl"
        )
        records = parse_log(path.read_text().splitlines())
        assert len(records) == 13
        assert stale_grad_records(records) == []
        assert sum(r.superseded for r in records) == 6


class TestProfiling:
    def test_enable_profiling_writes_trace(self, tmp_path):
        """--enable_profiling (≙ the reference's flag of the same name)
        must leave a trace artifact behind."""
        prof = tmp_path / "prof"
        rc = main(
            [
                "--enable_profiling", "--profile_dir", str(prof),
                "p2p", *FAST_P2P, "--devices", "2",
            ]
        )
        assert rc == 0
        traces = list(prof.rglob("*"))
        assert any(p.is_file() for p in traces), "no trace files written"


class TestSweep:
    def test_spec_matrices(self):
        p2p = sweep.specs_for("p2p", quick=True)
        assert len(p2p) == 12  # 3 modes x 2 mech x 2 transports x 1 size
        con = sweep.specs_for("concurrency", quick=True)
        assert {s.name.split(".")[1] for s in con} == {"default"}
        ar = sweep.specs_for("allreduce")
        assert any("pallas" in s.name for s in ar)
        lc = sweep.specs_for("longctx", quick=True)
        assert any("agreement" in s.name for s in lc)
        assert any("grad" in s.name for s in lc)
        par = sweep.specs_for("parallel", quick=True)
        assert {s.name.split(".")[0] for s in par} == {
            "pipeline", "moe", "flagship", "decode", "overlap", "lm"
        }
        hier = sweep.specs_for("hier", quick=True)
        assert len(hier) == 2  # 2 dcn splits x 1 dtype
        meas = sweep.specs_for("measured", quick=True)
        assert {s.name.split(".")[0] for s in meas} == {"measured"}
        # onesided + interop + 6 concurrency + 4 flash + 9 MFU-
        # push cells (3 flash block shapes + 1 flagship block shape +
        # 2 compact-causal-grid fwd + compact grad + compact flagship +
        # compact x blocks composed) + 10 flagship (incl. the r3
        # remat/depth4/gqa/rope cells + the r5 remat_dots selective-
        # checkpoint contrast) + decode (mha + gqa + int8) + lm
        assert len(meas) == 35
        # every flash cell pins --devices to exactly 1 (any other world
        # would silently SKIP the cell and checkpoint it as passed)
        for s in meas:
            if "flash" in s.name:
                i = s.argv.index("--devices")
                assert s.argv[i + 1] == "1", s.name
        tune = sweep.specs_for("tune", quick=True)
        assert len(tune) == 8  # 5 chunk counts + 3 block sizes
        rt = sweep.specs_for("runtime", quick=True)
        # >= 4 GENUINE runtime configs (C12 bar), each a real XLA/libtpu/
        # JAX knob — not a framework-internal timing mode
        cfgs = {s.name.split(".")[1] for s in rt}
        assert len(cfgs) >= 4
        real_knobs = {"LIBTPU_INIT_ARGS", "JAX_DEFAULT_MATMUL_PRECISION",
                      "JAX_ENABLE_COMPILATION_CACHE"}
        non_default = [s for s in rt if s.name.split(".")[1] != "default"]
        assert non_default and all(
            real_knobs & {k for k, _ in s.env} for s in non_default
        )
        # both pattern families appear (the reference sweeps env configs
        # over its bench AND its command mixes)
        assert any(s.argv[0] == "concurrency" for s in rt)
        assert any(s.argv[0] == "flagship" for s in rt)
        asym = sweep.specs_for("asymptote")
        # 5 sizes + 3 chunk interpolants + 2 aliased-inplace cells
        assert len(asym) == 10
        assert any("inplace" in s.name for s in asym)
        assert any("755MB" in s.name for s in asym)
        srv = sweep.specs_for("serve", quick=True)
        # base engine + int8 pool + gqa pool (full-verdict cells) + the
        # PR-7 prefix-sharing and speculative-decoding record cells +
        # the tiered-KV-cache admit-where-deferred cell + the fused
        # paged-attention lever (A/B vs serve.continuous)
        assert {s.name for s in srv} == {
            "serve.continuous", "serve.int8_pool", "serve.gqa_pool",
            "serve.prefix_share", "serve.spec_decode", "serve.kv_tier",
            "serve.pallas_attn",
        }
        assert all(s.argv[0] == "serve" for s in srv)
        pal = next(s for s in srv if s.name == "serve.pallas_attn")
        assert "--paged_attn" in pal.argv and "pallas" in pal.argv
        pre = next(s for s in srv if s.name == "serve.prefix_share")
        assert "--prefix_share" in pre.argv
        spc = next(s for s in srv if s.name == "serve.spec_decode")
        assert "--spec_k" in spc.argv
        kvt = next(s for s in srv if s.name == "serve.kv_tier")
        assert "--kv_host_tier" in kvt.argv
        assert any("working_set_mult" in a for a in kvt.argv)
        lg = sweep.specs_for("loadgen", quick=True)
        # one SLO cell per scenario preset + the chaos-under-load cell
        assert {s.name for s in lg} == {
            "loadgen.chat", "loadgen.rag", "loadgen.batch_summarize",
            "loadgen.agentic", "loadgen.chaos_chat",
        }
        assert all(s.argv[0] == "loadgen" for s in lg)
        chaos = next(s for s in lg if s.name == "loadgen.chaos_chat")
        assert "--chaos" in chaos.argv
        # 'all' must be exactly these suites, independently summed
        assert set(sweep.SUITES) == {
            "p2p", "hier", "measured", "tune", "asymptote", "gates",
            "concurrency", "runtime", "allreduce", "longctx", "parallel",
            "serve", "loadgen",
        }
        assert len(sweep.specs_for("all", quick=True)) == len(p2p) + len(
            con
        ) + len(sweep.specs_for("allreduce", quick=True)) + len(lc) + len(
            par
        ) + len(hier) + len(meas) + len(tune) + len(rt) + len(
            sweep.specs_for("gates", quick=True)
        ) + len(sweep.specs_for("asymptote", quick=True)) + len(srv) + len(
            lg
        )

    def test_measured_two_phase_ordering(self):
        # VERDICT r4 next #3: phase 1 = every cell full-size at reps=2
        # (the .fp twins), phase 2 = the refined matrix; a ~30-min window
        # banks breadth before depth.
        full = sweep.specs_for("measured")
        fp = [s for s in full if s.name.endswith(".fp")]
        refined = [s for s in full if not s.name.endswith(".fp")]
        assert len(refined) == 35
        # every cell with a repetition knob (--reps/--steps) gets a twin;
        # interop + 3 decode cells have none and appear refined-only
        assert len(fp) == 31
        last_fp = max(
            i for i, s in enumerate(full) if s.name.endswith(".fp")
        )
        first_ref = min(
            i for i, s in enumerate(full) if not s.name.endswith(".fp")
        )
        assert last_fp < first_ref, "first-pass phase must fully precede"
        # every cell carries its own config tag (collision-avoidance:
        # sibling cells can emit identical record surfaces), and a .fp
        # twin shares its refined cell's tag so supersede is cell-exact
        for s in refined:
            assert ("TPU_PATTERNS_SWEEP_CONFIG", s.name) in s.env
        by_name = {s.name: s for s in refined}
        for s in fp:
            base = by_name[s.name[: -len(".fp")]]
            assert ("TPU_PATTERNS_SWEEP_TIER", "first_pass") in s.env
            assert ("TPU_PATTERNS_SWEEP_CONFIG", base.name) in s.env
            # full workload size: argv differs ONLY at the value slot
            # after --reps/--steps (never a shape-bearing flag)
            assert len(s.argv) == len(base.argv)
            diffs = [
                (i, a, b)
                for i, (a, b) in enumerate(zip(base.argv, s.argv))
                if a != b
            ]
            assert diffs, s.name
            for i, a, b in diffs:
                assert base.argv[i - 1] in ("--reps", "--steps"), s.name
                assert b in ("2", "5")
        # the headline pair leads phase 1, same priority order as refined
        assert full[0].name in (
            "measured.flagship_pallas.fp", "measured.flagship_xla.fp"
        )
        # the CI quick tier is already tiny: no twins there
        assert not any(
            s.name.endswith(".fp")
            for s in sweep.specs_for("measured", quick=True)
        )

    def test_report_prefers_refined_over_first_pass(self):
        from tpu_patterns.core.results import Record, prefer_refined

        fp_env = {"TPU_PATTERNS_SWEEP_TIER": "first_pass"}
        a_fp = Record(pattern="longctx", mode="flash", commands="L4096",
                      metrics={"tflops": 100.0}, env=dict(fp_env))
        a_ref = Record(pattern="longctx", mode="flash", commands="L4096",
                       metrics={"tflops": 110.0})
        b_fp = Record(pattern="longctx", mode="flash", commands="L8192",
                      metrics={"tflops": 90.0}, env=dict(fp_env))
        out = prefer_refined([a_fp, a_ref, b_fp])
        # the refined record shadows its quick twin; an unshadowed quick
        # record (breadth from a short window) still tabulates
        assert a_ref in out and b_fp in out and a_fp not in out

    def test_supersede_unit_is_the_cell(self):
        from tpu_patterns.core.results import Record, prefer_refined

        def rec(cell, commands, tier=None, v=1.0):
            env = {"TPU_PATTERNS_SWEEP_CONFIG": cell}
            if tier:
                env["TPU_PATTERNS_SWEEP_TIER"] = tier
            return Record(pattern="lm", mode="train", commands=commands,
                          metrics={"steps_per_s": v}, env=env)

        # lm-style: the tiers' record surfaces differ (steps count in
        # commands) but share the cell tag -> still superseded
        lm_fp = rec("measured.lm", "B8 steps5", tier="first_pass")
        lm_ref = rec("measured.lm", "B8 steps20")
        # sibling-cell style: IDENTICAL record surface, different cells
        # -> the sibling's refined record must NOT retire this breadth
        sib_fp = rec("measured.lm_lever", "B8 steps20",
                     tier="first_pass", v=2.0)
        out = prefer_refined([lm_fp, lm_ref, sib_fp])
        assert lm_ref in out and sib_fp in out and lm_fp not in out

    def test_partial_refined_cell_keeps_unmatched_fp_records(self):
        # a multi-record cell whose refined run was slice-killed after
        # flushing only its train record must NOT retire the first-pass
        # generate record (no refined twin of it ever landed)
        from tpu_patterns.core.results import Record, prefer_refined

        def rec(mode, tier=None):
            env = {"TPU_PATTERNS_SWEEP_CONFIG": "measured.lm"}
            if tier:
                env["TPU_PATTERNS_SWEEP_TIER"] = tier
            return Record(pattern="lm", mode=mode, commands="B8",
                          metrics={"v": 1.0}, env=env)

        fp_train = rec("train", tier="first_pass")
        fp_gen = rec("generate", tier="first_pass")
        ref_train = rec("train")  # the only record the partial flush kept
        out = prefer_refined([fp_train, fp_gen, ref_train])
        assert ref_train in out and fp_gen in out and fp_train not in out

    def test_summarize_sweep(self, tmp_path):
        # the watcher banks this markdown per slice: refined rows
        # shadow their fp twins, and the asymptote size curve gets a
        # ceiling verdict
        from tpu_patterns.core.results import Record

        FLAGSHIP_CMDS = "dp1 sp1 tp1 B2 L4096 E1024 bfloat16"

        def cell(name, pattern, mode, metrics, tier=None, commands="x",
                 config=None):
            env = {"TPU_PATTERNS_SWEEP_CONFIG": name.removesuffix(".fp")}
            if tier:
                env["TPU_PATTERNS_SWEEP_TIER"] = tier
            rec = Record(pattern=pattern, mode=mode, commands=commands,
                         metrics=metrics, env=env, config=config or {})
            (tmp_path / f"{name}.jsonl").write_text(rec.to_json() + "\n")

        cell("measured.flagship_pallas.fp", "flagship", "pallas",
             {"tflops": 100.0}, tier="first_pass",
             commands=FLAGSHIP_CMDS)
        cell("measured.flagship_pallas", "flagship", "pallas",
             {"tflops": 121.8}, commands=FLAGSHIP_CMDS,
             config={"device_kind": "TPU v5 lite"})
        # an UNshadowed first-pass cell: banked breadth must appear
        cell("measured.flagship_xla.fp", "flagship", "xla",
             {"tflops": 76.0}, tier="first_pass", commands=FLAGSHIP_CMDS)
        # a block-shape lever beating the base: the MFU table must show
        # the pair delta and the distance to the 70% bar
        cell("measured.flagship.pallas_bq512_bk1024", "flagship",
             "pallas", {"tflops": 130.0}, commands=FLAGSHIP_CMDS,
             config={"device_kind": "TPU v5 lite"})
        for mb, g in ((47, 334.0), (189, 335.2), (755, 333.5)):
            cell(f"asymptote.multi.size{mb}MB", "onesided", "local_put",
                 {"bandwidth_GBps": g, "bytes_per_put": mb * 1e6})
        # a --quick run's differently-named cells must still appear —
        # but their sub-MB bytes_per_put keeps them OUT of the ceiling
        # verdict even at an absurd VMEM-resident rate
        cell("asymptote.multi.size262KB", "onesided", "local_put",
             {"bandwidth_GBps": 99999.0, "bytes_per_put": 262144.0})
        # a pre-accounting-fix grad record must be REFUSED (same rule
        # as `report`), not quoted as a result
        from tpu_patterns.core.results import GRAD_ACCOUNTING_FIX_TS

        stale = Record(
            pattern="longctx", mode="flash_grad", commands="x",
            metrics={"tflops": 189.7},
            env={"TPU_PATTERNS_SWEEP_CONFIG": "measured.flash_bf16_grad"},
            timestamp=GRAD_ACCOUNTING_FIX_TS - 10,
        )
        (tmp_path / "measured.flash_bf16_grad.jsonl").write_text(
            stale.to_json() + "\n"
        )
        md = sweep.summarize_sweep(str(tmp_path))
        assert "| measured.flagship_pallas | pallas | tflops | 121.8 |" in md
        assert "100" not in md.split("asymptote")[0]  # fp twin shadowed
        assert "SUCCESS (first_pass)" in md  # unshadowed fp, tier visible
        assert "platform-ceiling evidence" in md  # 0.5% spread over 16x
        assert "r4 plateau" in md  # 335.2 does not beat 335.6
        assert "size262KB" in md  # quick-tier cell names visible
        assert "189.7" not in md and "refused 1 pre-accounting-fix" in md
        # the MFU analysis: lever delta vs base within the same tier,
        # peak fraction, and the honest distance to the 70% bar —
        # scored against the chip the records NAME (device_kind stamp)
        assert "## Flagship MFU analysis (vs the TPU v5 lite 197" in md
        assert "| measured.flagship.pallas_bq512_bk1024 | 130.0 | 66.0% | +6.7% | refined |" in md
        assert "short of the 70% bar" in md  # 130 < 137.9
        # the fp-tier xla cell shows but gets no cross-tier delta
        assert "| measured.flagship_xla.fp | 76.0 | 38.6% | — | first_pass |" in md
        # empty dir: honest emptiness, not a crash
        empty = tmp_path / "empty"
        empty.mkdir()
        assert "no cell records" in sweep.summarize_sweep(str(empty))

    def test_summarize_flags_kernel_limited_and_beaten_plateau(
        self, tmp_path
    ):
        from tpu_patterns.core.results import Record

        for mb, g in ((47, 250.0), (189, 335.0), (755, 360.0)):
            rec = Record(
                pattern="onesided", mode="local_put", commands="x",
                metrics={"bandwidth_GBps": g, "bytes_per_put": mb * 1e6},
                env={"TPU_PATTERNS_SWEEP_CONFIG":
                     f"asymptote.multi.size{mb}MB"},
            )
            (tmp_path / f"asymptote.multi.size{mb}MB.jsonl").write_text(
                rec.to_json() + "\n"
            )
        md = sweep.summarize_sweep(str(tmp_path))
        assert "KERNEL-limited" in md
        assert "BEATS the r4" in md  # 360 > 335.6

    def _flagship_cell(self, tmp_path, name, tflops, converged=1.0,
                       verdict="SUCCESS"):
        import json

        rec = {"pattern": "flagship", "mode": "pallas", "commands": "x",
               "metrics": {"tflops": tflops,
                           "timing_converged": converged},
               "verdict": verdict}
        (tmp_path / f"{name}.jsonl").write_text(json.dumps(rec) + "\n")

    def test_promote_flash_win_becomes_default(self, tmp_path, monkeypatch):
        import json

        from tpu_patterns.models.transformer import ModelConfig

        dest = tmp_path / "flash_tuned.json"
        self._flagship_cell(tmp_path, "measured.flagship_pallas", 121.8)
        self._flagship_cell(
            tmp_path, "measured.flagship.pallas_bq512_bk1024", 130.0
        )
        tuned = sweep.promote_flash(str(tmp_path), dest=str(dest))
        assert tuned["promoted"]
        assert (tuned["block_q"], tuned["block_k"]) == (512, 1024)
        assert json.loads(dest.read_text())["block_q"] == 512
        # ...and ModelConfig resolves the promoted tier lazily
        monkeypatch.setenv("TPU_PATTERNS_FLASH_TUNED", str(dest))
        cfg = ModelConfig()
        assert (cfg.block_q, cfg.block_k) == (512, 1024)
        assert ModelConfig(block_q=2048).block_q == 2048  # explicit wins
        monkeypatch.setenv("TPU_PATTERNS_FLASH_TUNED", "/dev/null")
        assert ModelConfig().block_q == 1024  # absent tier -> hand-picked

    def test_promote_flash_refusals(self, tmp_path):
        # within the noise margin -> no promotion, nothing written
        self._flagship_cell(tmp_path, "measured.flagship_pallas", 121.8)
        self._flagship_cell(
            tmp_path, "measured.flagship.pallas_bq512_bk1024", 122.5
        )
        dest = tmp_path / "flash_tuned.json"
        out = sweep.promote_flash(str(tmp_path), dest=str(dest))
        assert out["promoted"] is False and not dest.exists()
        assert out["reason"] == "within noise margin"
        # a noise-bound lever never qualifies, however fast: the only
        # lever record is now unconverged -> no usable pair -> raise
        self._flagship_cell(
            tmp_path, "measured.flagship.pallas_bq512_bk1024", 150.0,
            converged=0.0,
        )
        with pytest.raises(FileNotFoundError):
            sweep.promote_flash(str(tmp_path), dest=str(dest))
        assert not dest.exists()

    def test_promote_flash_never_compares_across_tiers(self, tmp_path):
        # refined lever vs first-pass-only base: the reps-tier bias can
        # fabricate a >2% "win" — promotion must refuse the comparison
        self._flagship_cell(
            tmp_path, "measured.flagship_pallas.fp", 118.0
        )
        self._flagship_cell(
            tmp_path, "measured.flagship.pallas_bq512_bk1024", 125.0
        )
        dest = tmp_path / "flash_tuned.json"
        out = sweep.promote_flash(str(tmp_path), dest=str(dest))
        assert out["promoted"] is False
        assert out["reason"] == "tier mismatch"
        assert not dest.exists()

    def test_promote_flash_first_pass_fallback(self, tmp_path):
        # refinement never landed: the fp twins carry the comparison,
        # and the provenance records which tier each side came from
        self._flagship_cell(tmp_path, "measured.flagship_pallas.fp", 100.0)
        self._flagship_cell(
            tmp_path, "measured.flagship.pallas_bq512_bk1024.fp", 110.0
        )
        dest = tmp_path / "flash_tuned.json"
        tuned = sweep.promote_flash(str(tmp_path), dest=str(dest))
        assert tuned["promoted"]
        assert tuned["base_tier"] == "first_pass"
        assert tuned["lever_tier"] == "first_pass"

    def test_promote_tuned_picks_best_cell_per_family(self, tmp_path):
        """`sweep promote` folds the winning chunks/block_rows of a tune
        run into a tuned.json that OneSidedConfig reads as defaults."""
        import json

        def cell(name, gbps):
            rec = {
                "pattern": "onesided", "mode": "local_put",
                "metrics": {"bandwidth_GBps": gbps}, "verdict": "SUCCESS",
            }
            (tmp_path / f"{name}.jsonl").write_text(json.dumps(rec) + "\n")

        cell("tune.multi.chunks4", 300.0)
        cell("tune.multi.chunks16", 360.0)
        cell("tune.streamed.rows512", 250.0)
        cell("tune.streamed.rows2048", 340.0)
        dest = tmp_path / "tuned.json"
        tuned = sweep.promote_tuned(str(tmp_path), dest=str(dest))
        assert tuned["chunks"] == 16 and tuned["multi_GBps"] == 360.0
        assert tuned["block_rows"] == 2048 and tuned["streamed_GBps"] == 340.0
        on_disk = json.loads(dest.read_text())
        assert on_disk["chunks"] == 16 and on_disk["block_rows"] == 2048

    def test_promote_tuned_refuses_empty_dir(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            sweep.promote_tuned(str(tmp_path), dest=str(tmp_path / "t.json"))

    def test_onesided_config_reads_tuned_file(self, tmp_path, monkeypatch):
        """The tuned tier reaches OneSidedConfig defaults via
        TPU_PATTERNS_TUNED (same loader as the committed comm/tuned.json)."""
        import importlib
        import json

        from tpu_patterns.comm import onesided

        p = tmp_path / "tuned.json"
        p.write_text(json.dumps({"chunks": 32, "block_rows": 512}))
        monkeypatch.setenv("TPU_PATTERNS_TUNED", str(p))
        try:
            mod = importlib.reload(onesided)
            cfg = mod.OneSidedConfig()
            assert cfg.chunks == 32 and cfg.block_rows == 512
        finally:
            monkeypatch.delenv("TPU_PATTERNS_TUNED")
            importlib.reload(onesided)

    def test_unknown_name_filter(self, tmp_path):
        with pytest.raises(ValueError, match="unknown cell name"):
            sweep.run_sweep("p2p", out_dir=str(tmp_path), names=["nope"])
        # one good + one bad name must also fail, not silently drop coverage
        good = sweep.specs_for("p2p", quick=True)[0].name
        with pytest.raises(ValueError, match="unknown cell name"):
            sweep.run_sweep(
                "p2p", out_dir=str(tmp_path), names=[good, "nope"]
            )

    def test_sweep_rejects_global_jsonl(self, tmp_path):
        with pytest.raises(SystemExit):
            main(["--jsonl", "x.jsonl", "sweep", "p2p", "--quick"])

    def test_run_sweep_subprocess(self, tmp_path, capsys):
        # Two real subprocess cells on the CPU-simulated mesh (≙ two
        # launcher lines of run.sh); env scrubbed of the platform plugin.
        env = {
            k: v for k, v in os.environ.items() if k != "PYTHONPATH"
        }
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        names = [
            "p2p.compact.mesh.two_sided.n2",
            "allreduce.xla.float32.ring.D",
            "longctx.agreement.1dev",
        ]
        rc = sweep.run_sweep(
            "all", out_dir=str(tmp_path), quick=True, names=names, base_env=env
        )
        assert rc == 0
        for name in names:
            assert (tmp_path / f"{name}.log").exists()
            recs = _read_jsonl(tmp_path / f"{name}.jsonl")
            assert all(r["verdict"] in ("SUCCESS", "SKIPPED") for r in recs)
        out = capsys.readouterr().out
        assert "sweep cell" in out

    def test_sweep_resume_skips_completed_failure(self, tmp_path, monkeypatch):
        # an honest FAILURE verdict is a RESULT: resume must not re-measure
        # it, but the aggregate exit code must still reflect it
        name = "p2p.compact.mesh.two_sided.n2"
        calls = []
        monkeypatch.setattr(
            sweep,
            "run_spec",
            lambda spec, out, base_env=None, timeout=None: calls.append(spec.name)
            or (1, True),  # completed, verdict FAILURE
        )
        rc = sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name]
        )
        assert rc == 1 and calls == [name]
        rc = sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
            resume=True,
        )
        assert calls == [name]  # NOT re-run
        assert rc == 1  # but still reported as a failing suite

    def test_sweep_resume_reruns_timeout(self, tmp_path, monkeypatch):
        # a timeout/crash (completed=False) must re-run even with rc!=0
        name = "p2p.compact.mesh.two_sided.n2"
        results = iter([(1, False), (0, True)])
        calls = []
        monkeypatch.setattr(
            sweep,
            "run_spec",
            lambda spec, out, base_env=None, timeout=None: calls.append(spec.name)
            or next(results),
        )
        sweep.run_sweep("p2p", out_dir=str(tmp_path), quick=True, names=[name])
        rc = sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
            resume=True,
        )
        assert calls == [name, name]  # re-ran after the timeout
        assert rc == 0

    def test_sweep_crash_cell_not_checkpointed(self, tmp_path):
        # a REAL crashing subprocess (traceback, no records) must be
        # recorded completed=False so --resume retries it
        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env["TPU_PATTERNS_PLATFORM"] = "bogus_platform"  # backend init dies
        name = "p2p.compact.mesh.two_sided.n2"
        rc = sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
            base_env=env,
        )
        assert rc == 1
        st = sweep.load_sweep_state(str(tmp_path))
        assert st[name]["rc"] != 0
        assert st[name]["completed"] is False

    def test_sweep_resume_skips_passed_cells(self, tmp_path, capsys):
        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        name = "p2p.compact.mesh.two_sided.n2"
        rc = sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
            base_env=env,
        )
        assert rc == 0
        st = sweep.load_sweep_state(str(tmp_path), "p2p")
        assert st[name]["rc"] == 0 and st[name]["sig"]
        capsys.readouterr()
        # resume: the passed cell must be skipped (no subprocess), yet the
        # report still covers it from the on-disk log/jsonl
        rc = sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
            base_env=env, resume=True,
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "resume: already passed" in out
        assert "-> exit" not in out  # nothing re-ran
        assert "SUCCESS" in out  # report still tabulates the resumed cell

    def test_sweep_resume_reruns_failed_cells(self, tmp_path, monkeypatch):
        # a cell recorded rc!=0 must re-run under --resume; a fresh (non-
        # resume) run must forget only the SELECTED cells' history
        import json

        name = "p2p.compact.mesh.two_sided.n2"
        os.makedirs(tmp_path, exist_ok=True)
        with open(tmp_path / "sweep-state.jsonl", "w") as f:
            f.write(json.dumps({"cell": name, "rc": 1, "sig": "x"}) + "\n")
            f.write(json.dumps(
                {"cell": "p2p.other.cell", "rc": 0, "sig": "y"}
            ) + "\n")
            f.write("torn-write{{{\n")  # must be tolerated
        st = sweep.load_sweep_state(str(tmp_path), "p2p")
        assert st[name] == {"rc": 1, "sig": "x", "completed": False}
        calls = []
        monkeypatch.setattr(
            sweep, "run_spec", lambda spec, out, base_env=None, timeout=None: calls.append(
                spec.name
            ) or (0, True),
        )
        sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
            resume=True,
        )
        assert calls == [name]
        # non-resume names-filtered run wipes the selected cell's history
        # but PRESERVES the unselected cell's checkpoint
        sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
        )
        st = sweep.load_sweep_state(str(tmp_path), "p2p")
        assert st[name]["rc"] == 0
        assert st["p2p.other.cell"] == {"rc": 0, "sig": "y", "completed": True}

    def test_sweep_resume_workload_mismatch_reruns(self, tmp_path, monkeypatch):
        # a --quick success must NOT satisfy a later full-size resume: the
        # state entry's workload fingerprint (argv+env) must match too
        name = "p2p.compact.mesh.two_sided.n2"
        calls = []
        monkeypatch.setattr(
            sweep, "run_spec", lambda spec, out, base_env=None, timeout=None: calls.append(
                spec.name
            ) or (0, True),
        )
        sweep.run_sweep("p2p", out_dir=str(tmp_path), quick=True, names=[name])
        assert calls == [name]
        # resume with quick=False: same cell name, different workload
        sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=False, names=[name],
            resume=True,
        )
        assert calls == [name, name]  # re-ran, not skipped
        # resume with the SAME workload is skipped
        sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=False, names=[name],
            resume=True,
        )
        assert calls == [name, name]

    def test_sweep_state_shared_across_suite_args(self, tmp_path, monkeypatch):
        # 'sweep all' and 'sweep p2p' must share one checkpoint history:
        # a failure recorded by the per-suite run must not be shadowed by a
        # stale success from the 'all' run
        name = "p2p.compact.mesh.two_sided.n2"
        rcs = iter([0, 1])
        calls = []
        monkeypatch.setattr(
            sweep, "run_spec", lambda spec, out, base_env=None, timeout=None: calls.append(
                spec.name
            ) or (next(rcs), False),
        )
        sweep.run_sweep("all", out_dir=str(tmp_path), quick=True, names=[name])
        sweep.run_sweep(  # regression recorded under the per-suite arg
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
        )
        assert sweep.load_sweep_state(str(tmp_path))[name]["rc"] == 1
        # 'all --resume' sees the latest (failed) state and re-runs
        monkeypatch.setattr(
            sweep, "run_spec", lambda spec, out, base_env=None, timeout=None: calls.append(
                spec.name
            ) or (0, True),
        )
        sweep.run_sweep(
            "all", out_dir=str(tmp_path), quick=True, names=[name], resume=True
        )
        assert calls == [name, name, name]

    def test_sweep_state_legacy_migration(self, tmp_path, monkeypatch):
        # pre-unification per-suite files fold into the unified file keeping
        # the NEWEST record per cell (ts), then disappear — a stale legacy
        # pass must not shadow a newer failure
        import json

        name = "p2p.compact.mesh.two_sided.n2"
        os.makedirs(tmp_path, exist_ok=True)
        with open(tmp_path / "all.sweep-state.jsonl", "w") as f:
            f.write(json.dumps(
                {"cell": name, "rc": 0, "sig": "s", "ts": 100.0}
            ) + "\n")
        with open(tmp_path / "p2p.sweep-state.jsonl", "w") as f:
            f.write(json.dumps(
                {"cell": name, "rc": 1, "sig": "s", "ts": 200.0}
            ) + "\n")
            f.write(json.dumps(
                {"cell": "p2p.other", "rc": 0, "sig": "y", "ts": 50.0}
            ) + "\n")
        calls = []
        monkeypatch.setattr(
            sweep, "run_spec", lambda spec, out, base_env=None, timeout=None: calls.append(
                spec.name
            ) or (0, True),
        )
        sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
            resume=True,
        )
        # the newest record for the cell was the FAILURE -> it re-ran
        assert calls == [name]
        # legacy files are gone; unified holds the survivors
        assert not (tmp_path / "all.sweep-state.jsonl").exists()
        assert not (tmp_path / "p2p.sweep-state.jsonl").exists()
        st = sweep.load_sweep_state(str(tmp_path))
        assert st[name]["rc"] == 0  # the re-run just recorded success
        assert st["p2p.other"] == {"rc": 0, "sig": "y", "completed": True}

    def test_sweep_resume_env_mismatch_reruns(self, tmp_path, monkeypatch):
        # a pass under JAX_PLATFORMS=cpu must not satisfy a resume under a
        # different platform env (CPU-sim numbers posing as hardware)
        name = "p2p.compact.mesh.two_sided.n2"
        calls = []
        monkeypatch.setattr(
            sweep, "run_spec", lambda spec, out, base_env=None, timeout=None: calls.append(
                spec.name
            ) or (0, True),
        )
        cpu_env = {"JAX_PLATFORMS": "cpu"}
        sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
            base_env=cpu_env,
        )
        sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
            base_env={}, resume=True,
        )
        assert calls == [name, name]  # env changed -> re-ran
        sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
            base_env={}, resume=True,
        )
        assert calls == [name, name]  # same env -> skipped


class TestGatesSuite:
    def test_spec_matrix_runs_configs_repeatedly(self):
        specs = sweep.specs_for("gates", quick=True)
        # quick: 2 configs x 2 consecutive runs
        assert len(specs) == 4
        names = {s.name.rsplit(".", 1)[0] for s in specs}
        assert names == {"gates.flash_bf16_causal", "gates.flash_f32_causal"}
        full = sweep.specs_for("gates")
        # full: 4 configs (incl. the compact-grid backward) x 10
        # consecutive runs (VERDICT r3 next #3)
        assert len(full) == 40

    def test_fit_gates_refits_width_from_spread(self, tmp_path, monkeypatch):
        import json

        from tpu_patterns.core.results import Record

        # a promoted fit on this machine must not leak into the math
        monkeypatch.setenv("TPU_PATTERNS_GATES_FIT", "/dev/null")

        def write(cfg, violations, width=None):
            path = tmp_path / f"gates.{cfg}.r0.jsonl"
            with open(path, "w") as f:
                for i, v in enumerate(violations):
                    metrics = {"gate_violation": v}
                    if width is not None:
                        metrics["gate_width_eps"] = width
                    f.write(
                        Record(
                            pattern="longctx",
                            mode="flash_grad",
                            commands=f"run {i}",
                            metrics=metrics,
                        ).to_json()
                        + "\n"
                    )

        write("clean", [0.3, 0.5, 0.6])
        write("tight", [0.05, 0.08])
        fit = sweep.fit_gates(str(tmp_path))
        clean = fit["configs"]["gates.clean"]
        # worst clean run 0.6 of the 8-eps gate -> 8*0.6*1.5 = 7.2 -> 8
        assert clean["recommended_width_eps"] == 8
        assert not clean["defect"]
        tight = fit["configs"]["gates.tight"]
        assert tight["gate_loose_10x"]
        assert tight["recommended_width_eps"] == 2  # floor
        assert fit["recommended_width_eps"] == 8
        # the fit persists to disk — the promote step and the committed
        # capture both depend on gates_fit.json existing
        on_disk = json.loads((tmp_path / "gates_fit.json").read_text())
        assert on_disk["current_width_eps"] == 8
        assert on_disk["recommended_width_eps"] == 8

    def test_fit_gates_uses_record_width_provenance(
        self, tmp_path, monkeypatch
    ):
        # records taken under DIFFERENT promoted widths carry their own
        # gate_width_eps; the refit works in violation*width, so mixing
        # them is correct and re-fitting after a promotion is idempotent
        # (no ratchet toward the floor)
        from tpu_patterns.core.results import Record

        monkeypatch.setenv("TPU_PATTERNS_GATES_FIT", "/dev/null")
        with open(tmp_path / "gates.mixed.r0.jsonl", "w") as f:
            for v, w in ((0.5, 8.0), (1.0, 4.0)):  # both = 4 eps residue
                f.write(
                    Record(
                        pattern="longctx",
                        mode="flash_grad",
                        commands="x",
                        metrics={
                            "gate_violation": v,
                            "gate_width_eps": w,
                        },
                    ).to_json()
                    + "\n"
                )
        fit = sweep.fit_gates(str(tmp_path))
        mixed = fit["configs"]["gates.mixed"]
        # worst residue 4 eps -> ceil(4 * 1.5) = 6, regardless of which
        # width happened to be live at fit time
        assert mixed["recommended_width_eps"] == 6
        assert not mixed["defect"]  # 1.0 is ON the gate, not over it

    def test_promote_gates_writes_fit_tier(self, tmp_path, monkeypatch):
        import json

        fit = {
            "current_width_eps": 8,
            "recommended_width_eps": 4,
            "configs": {"gates.clean": {"defect": False}},
        }
        (tmp_path / "gates_fit.json").write_text(json.dumps(fit))
        dest = tmp_path / "promoted.json"
        out = sweep.promote_gates(str(tmp_path), dest=str(dest))
        assert out["recommended_width_eps"] == 4
        assert out["source"] == str(tmp_path)
        # the gate reads the promoted tier lazily via the env override
        from tpu_patterns.longctx import pattern

        monkeypatch.setenv("TPU_PATTERNS_GATES_FIT", str(dest))
        assert pattern._gate_width_eps() == 4.0
        monkeypatch.setenv("TPU_PATTERNS_GATES_FIT", "/dev/null")
        assert pattern._gate_width_eps() == 8.0  # fallback width

    def test_promote_gates_refuses_defect(self, tmp_path):
        import json

        fit = {
            "current_width_eps": 8,
            "recommended_width_eps": 40,
            "configs": {"gates.bad": {"defect": True}},
        }
        (tmp_path / "gates_fit.json").write_text(json.dumps(fit))
        with pytest.raises(ValueError, match="defect"):
            sweep.promote_gates(str(tmp_path), dest=str(tmp_path / "x"))
        assert not (tmp_path / "x").exists()  # refusal writes nothing
        with pytest.raises(FileNotFoundError):
            sweep.promote_gates(str(tmp_path / "nope"))

    def test_fit_gates_flags_defect(self, tmp_path, monkeypatch):
        from tpu_patterns.core.results import Record

        monkeypatch.setenv("TPU_PATTERNS_GATES_FIT", "/dev/null")

        with open(tmp_path / "gates.bad.r0.jsonl", "w") as f:
            f.write(
                Record(
                    pattern="longctx",
                    mode="flash_grad",
                    commands="x",
                    metrics={"gate_violation": 1.4},
                ).to_json()
                + "\n"
            )
        fit = sweep.fit_gates(str(tmp_path))
        assert fit["configs"]["gates.bad"]["defect"]

    def test_fit_gates_refuses_empty(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            sweep.fit_gates(str(tmp_path))


class TestRuntimeBite:
    def _write(self, tmp_path, cfg, target, value, platform="tpu"):
        from tpu_patterns.core.results import Record

        path = tmp_path / f"runtime.{cfg}.{target}.jsonl"
        with open(path, "w") as f:
            f.write(
                Record(
                    pattern="x",
                    mode=target,
                    commands="c",
                    metrics={"tflops": value},
                    env={"JAX_PLATFORMS": platform},
                ).to_json()
                + "\n"
            )

    def test_biting_knob_is_success(self, tmp_path):
        from tpu_patterns.core.results import Verdict

        self._write(tmp_path, "default", "flagship", 100.0)
        self._write(tmp_path, "no_latency_hiding", "flagship", 80.0)
        rec = sweep.check_runtime_bite(str(tmp_path), platform="tpu")
        assert rec.verdict is Verdict.SUCCESS
        assert rec.metrics["biting_targets"] == 1.0
        assert rec.metrics["max_rel_move"] == pytest.approx(0.2)

    def test_inert_knobs_flagged_on_tpu(self, tmp_path):
        from tpu_patterns.core.results import Verdict

        self._write(tmp_path, "default", "flagship", 100.0)
        self._write(tmp_path, "no_latency_hiding", "flagship", 100.5)
        rec = sweep.check_runtime_bite(str(tmp_path), platform="tpu")
        assert rec.verdict is Verdict.WARNING
        assert "silently ignored" in rec.notes[0]

    def test_cpu_records_are_skipped_not_flagged(self, tmp_path):
        from tpu_patterns.core.results import Verdict

        self._write(tmp_path, "default", "flagship", 100.0, platform="cpu")
        self._write(
            tmp_path, "no_latency_hiding", "flagship", 100.0, platform="cpu"
        )
        # platform defaults to this process's live backend (cpu here):
        # record env vars are NOT trusted — on real hardware
        # JAX_PLATFORMS is typically unset
        rec = sweep.check_runtime_bite(str(tmp_path))
        assert rec.verdict is Verdict.SKIPPED


class TestSuiteComplete:
    def test_requires_completion_and_matching_sig(self, tmp_path):
        """The capture watcher's gate: every cell completed UNDER THE
        CURRENT signature — state seeded by a quick/different-env run
        must not satisfy a full hardware capture (ADVICE r3)."""
        import json

        from tpu_patterns.sweep import _spec_sig

        specs = sweep.specs_for("tune")
        assert not sweep.suite_complete(str(tmp_path), "tune")
        state = tmp_path / "sweep-state.jsonl"
        with open(state, "w") as f:
            for s in specs:
                f.write(
                    json.dumps(
                        {"cell": s.name, "rc": 0,
                         "sig": _spec_sig(s, None), "completed": True}
                    )
                    + "\n"
                )
        assert sweep.suite_complete(str(tmp_path), "tune")
        # a later incomplete row for one cell flips it (latest wins)
        with open(state, "a") as f:
            f.write(
                json.dumps(
                    {"cell": specs[0].name, "rc": 1,
                     "sig": _spec_sig(specs[0], None), "completed": False}
                )
                + "\n"
            )
        assert not sweep.suite_complete(str(tmp_path), "tune")

    def test_foreign_sig_does_not_satisfy(self, tmp_path):
        import json

        specs = sweep.specs_for("tune")
        with open(tmp_path / "sweep-state.jsonl", "w") as f:
            for s in specs:
                f.write(
                    json.dumps(
                        {"cell": s.name, "rc": 0, "sig": "other-env",
                         "completed": True}
                    )
                    + "\n"
                )
        assert not sweep.suite_complete(str(tmp_path), "tune")
