"""Fused Pallas paged-attention decode kernel (serve/paged_kernel.py):
interpret-mode agreement with the dense gather path on random block
tables (ragged positions, trash pages, inactive rows, int8 pools, the
speculative wide step), the sp-sharded combine, and the backend A/B at
the engine level — greedy ids must be bit-identical dense vs pallas."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from tpu_patterns.models.lm import init_lm_params
from tpu_patterns.models.transformer import ModelConfig, _n_experts
from tpu_patterns.serve import (
    Request,
    ServeEngine,
    TRASH_BLOCK,
    make_paged_lm_decoder,
)
from tpu_patterns.serve.paged import PagedLayout, _pool_attend
from tpu_patterns.serve.paged_kernel import block_tile, paged_attend

CFG = dict(embed=64, heads=8, head_dim=8, causal=True, dtype="float32")
VOCAB = 64


def _mesh(devices, shape):
    n = int(np.prod(shape))
    return Mesh(np.array(devices[:n]).reshape(shape), ("dp", "sp", "tp"))


def _rand_pool(rng, n_blocks, bl_loc, hkv, d, int8=False):
    shape = (n_blocks, bl_loc, hkv, d)
    if not int8:
        return {
            "k": jnp.asarray(rng.randn(*shape), jnp.float32),
            "v": jnp.asarray(rng.randn(*shape), jnp.float32),
        }
    return {
        "k": jnp.asarray(rng.randint(-127, 128, size=shape), jnp.int8),
        "v": jnp.asarray(rng.randint(-127, 128, size=shape), jnp.int8),
        "ks": jnp.asarray(
            rng.uniform(0.005, 0.02, size=shape[:3]), jnp.float32
        ),
        "vs": jnp.asarray(
            rng.uniform(0.005, 0.02, size=shape[:3]), jnp.float32
        ),
    }


def _dense_ref(pool_l, q, tables, pos0, active, layout, sp_axis=None):
    """The dense path's exact mask (the _paged_verify_layer
    construction, W=1 degenerates to the decode-layer mask)."""
    w = q.shape[1]
    n_pages = tables.shape[1]
    posn = layout.page_positions(n_pages, sp_axis)
    tvalid = jnp.repeat(tables > TRASH_BLOCK, layout.bl_loc, axis=1)
    pos = pos0[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    mask = (
        (posn[None, None, :] <= pos[:, :, None])
        & tvalid[:, None, :]
        & active[:, None, None]
    )
    return _pool_attend(pool_l, q, tables, mask, layout, sp_axis)


class TestKernelVsDense:
    """Single-shard interpret-mode agreement: the kernel must reproduce
    the gather -> masked-softmax path to float tolerance on adversarial
    table layouts."""

    B, H, HKV, D = 3, 4, 2, 8
    BL, N_BLOCKS, N_PAGES = 8, 10, 3

    def _case(self, *, w=1, int8=False, seed=0):
        rng = np.random.RandomState(seed)
        layout = PagedLayout(self.N_BLOCKS, self.BL, sp=1)
        pool = _rand_pool(
            rng, self.N_BLOCKS, self.BL, self.HKV, self.D, int8
        )
        q = jnp.asarray(
            rng.randn(self.B, w, self.H, self.D), jnp.float32
        )
        # distinct physical blocks per row, trash in the unreached tail
        perm = 1 + rng.permutation(self.N_BLOCKS - 1)[
            : self.B * self.N_PAGES
        ].reshape(self.B, self.N_PAGES)
        tables = np.asarray(perm, np.int32)
        tables[0, 2] = TRASH_BLOCK  # row 0 never grew a third page
        pos0 = jnp.asarray([5, 11, 2], jnp.int32)  # ragged, mid-block
        active = jnp.asarray([True, True, True])
        return pool, q, jnp.asarray(tables), pos0, active, layout

    def _agree(self, pool, q, tables, pos0, active, layout):
        got = paged_attend(
            pool, q, tables, pos0, active, layout, None, interpret=True
        )
        want = _dense_ref(pool, q, tables, pos0, active, layout)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6
        )

    def test_decode_step_agrees(self):
        self._agree(*self._case(w=1))

    def test_wide_verify_step_agrees(self):
        # W=4: per-query causality inside the window (query i sees
        # positions <= pos0 + i), same kernel as plain decode
        self._agree(*self._case(w=4, seed=1))

    def test_int8_dequant_fused(self):
        # in-kernel dequant: k's scale on the score tile, v's folded
        # after the normalizer — must match the dense dequant order
        self._agree(*self._case(w=1, int8=True, seed=2))

    def test_int8_wide(self):
        self._agree(*self._case(w=4, int8=True, seed=3))

    def test_inactive_row_emits_zero(self):
        pool, q, tables, pos0, _, layout = self._case()
        active = jnp.asarray([True, False, True])
        got = paged_attend(
            pool, q, tables, pos0, active, layout, None, interpret=True
        )
        want = _dense_ref(pool, q, tables, pos0, active, layout)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6
        )
        assert np.all(np.asarray(got)[1] == 0.0)

    def test_all_trash_window_emits_zero(self):
        # a row whose whole table is trash (freshly admitted, nothing
        # written): the NEG_INF guard must yield exact zeros, not NaN
        pool, q, tables, pos0, active, layout = self._case()
        tables = tables.at[2].set(TRASH_BLOCK)
        got = np.asarray(paged_attend(
            pool, q, tables, pos0, active, layout, None, interpret=True
        ))
        assert np.all(np.isfinite(got))
        assert np.all(got[2] == 0.0)

    def test_block_tile_divides_pool_block(self):
        # the tile ladder must never straddle two physical blocks
        for bl_loc in (4, 8, 16, 64, 256):
            for gw in (1, 4, 8):
                bk = block_tile(bl_loc, 64, 4, gw)
                assert bl_loc % bk == 0 and 1 <= bk <= bl_loc


class TestShardedCombine:
    def test_sp_partials_combine_to_dense(self, devices):
        """The out-of-kernel sp combine (pmax / rescale / psum) must
        reproduce the dense sharded attention on a 2-way sp mesh."""
        rng = np.random.RandomState(4)
        b, w, h, hkv, d = 2, 1, 4, 2, 8
        n_blocks, bl, n_pages, sp = 6, 8, 2, 2
        layout = PagedLayout(n_blocks, bl, sp=sp)
        mesh = Mesh(np.array(devices[:sp]).reshape(sp), ("sp",))
        k = jnp.asarray(rng.randn(n_blocks, bl, hkv, d), jnp.float32)
        v = jnp.asarray(rng.randn(n_blocks, bl, hkv, d), jnp.float32)
        q = jnp.asarray(rng.randn(b, w, h, d), jnp.float32)
        tables = jnp.asarray([[1, 2], [3, TRASH_BLOCK]], jnp.int32)
        pos0 = jnp.asarray([13, 6], jnp.int32)
        active = jnp.asarray([True, True])

        def body(k_l, v_l, q_r):
            pool_l = {"k": k_l, "v": v_l}
            pal = paged_attend(
                pool_l, q_r, tables, pos0, active, layout, "sp",
                interpret=True,
            )
            den = _dense_ref(
                pool_l, q_r, tables, pos0, active, layout, "sp"
            )
            return pal, den

        fn = jax.jit(jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P()),
            out_specs=(P(), P()),
            check_vma=False,
        ))
        pal, den = fn(k, v, q)
        np.testing.assert_allclose(
            np.asarray(pal), np.asarray(den), rtol=2e-5, atol=2e-6
        )


class TestEngineBackendAB:
    """The serve-level gate: a full continuous-batching trace must
    retire bit-identical greedy ids on either attention backend."""

    def _ids(self, devices, shape, attn, *, cache_int8=False, spec_k=0,
             depth=2):
        mesh = _mesh(devices, shape)
        mcfg = ModelConfig(**CFG, kv_heads=2, depth=depth)
        dec = make_paged_lm_decoder(
            mesh, mcfg, VOCAB, n_blocks=17, block_len=8, max_len=40,
            cache_int8=cache_int8, attn=attn,
        )
        flat = init_lm_params(
            jax.random.key(0), mcfg, VOCAB, _n_experts(mesh, mcfg)
        )
        params = dec.stack_params(flat)
        rng = np.random.RandomState(11)
        reqs = [
            Request(
                rid=i,
                tokens=rng.randint(
                    0, VOCAB, size=rng.randint(3, 21)
                ).tolist(),
                n_gen=6,
            )
            for i in range(6)
        ]
        eng = ServeEngine(dec, params, slots=4, spec_k=spec_k)
        out = eng.run(reqs)
        assert not eng.failed and eng.leaked_blocks() == 0
        return out

    def test_single_shard_ids_identical(self, devices):
        a = self._ids(devices, (1, 1, 1), "dense", depth=1)
        b = self._ids(devices, (1, 1, 1), "pallas", depth=1)
        assert a == b

    def test_sharded_ids_identical(self, devices):
        a = self._ids(devices, (1, 2, 2), "dense")
        b = self._ids(devices, (1, 2, 2), "pallas")
        assert a == b

    def test_int8_pool_ids_identical(self, devices):
        a = self._ids(devices, (1, 2, 2), "dense", cache_int8=True)
        b = self._ids(devices, (1, 2, 2), "pallas", cache_int8=True)
        assert a == b

    def test_spec_decode_ids_identical(self, devices):
        # the wide verify step runs the same kernel at W = spec_k + 1
        a = self._ids(devices, (1, 2, 2), "dense", spec_k=2)
        b = self._ids(devices, (1, 2, 2), "pallas", spec_k=2)
        assert a == b

    def test_unknown_backend_rejected(self, devices):
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        with pytest.raises(ValueError, match="attn"):
            make_paged_lm_decoder(
                mesh, mcfg, VOCAB, n_blocks=5, block_len=8, max_len=16,
                attn="flash",
            )
