"""ZeRO sharded-optimizer pattern (parallel/zero.py): the sharded update
must be numerically the replicated update, with 1/dp the optimizer state."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_patterns.parallel import zero


def _run_sharded(mesh1d, fn, grads_by_dev, params):
    """Drive zero_* under shard_map on the 8-device x axis: grads vary per
    device (stacked on a leading axis), params replicated."""
    n = 8
    g = jax.device_put(
        jnp.stack(grads_by_dev), NamedSharding(mesh1d, P("x"))
    )
    return jax.jit(
        jax.shard_map(
            fn,
            mesh=mesh1d,
            in_specs=(P("x"), P()),
            out_specs=P(),
            check_vma=False,
        )
    )(g, params)


class TestZeroApplySGD:
    @pytest.mark.parametrize("n_elem", [64, 61])  # 61: pad path (61 % 8 != 0)
    def test_matches_replicated_update(self, mesh1d, n_elem):
        n = 8
        lr = 0.1
        tx = optax.sgd(lr)
        p = jnp.arange(n_elem, dtype=jnp.float32) / n_elem
        grads = [
            jnp.sin(jnp.arange(n_elem, dtype=jnp.float32) + r)
            for r in range(n)
        ]
        want = np.asarray(p) - lr * np.sum([np.asarray(g) for g in grads], 0)

        def body(g_stacked, params):
            g = g_stacked[0]
            state = zero.zero_init(tx, {"w": params}, "x", n)
            new, _ = zero.zero_apply(
                tx, {"w": g}, state, {"w": params}, "x", n
            )
            return new["w"]

        out = _run_sharded(mesh1d, body, grads, p)
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)

    def test_reduced_grads_path(self, mesh1d):
        # grads_reduced=True: every device already holds the summed grad
        n, lr = 8, 0.1
        tx = optax.sgd(lr)
        p = jnp.ones((32,), jnp.float32)
        g_sum = jnp.full((32,), 2.0)

        def body(g_stacked, params):
            state = zero.zero_init(tx, {"w": params}, "x", n)
            new, _ = zero.zero_apply(
                tx, {"w": g_stacked[0]}, state, {"w": params}, "x", n,
                grads_reduced=True,
            )
            return new["w"]

        out = _run_sharded(mesh1d, body, [g_sum] * n, p)
        np.testing.assert_allclose(np.asarray(out), 1.0 - lr * 2.0, rtol=1e-6)


class TestZeroApplyAdam:
    def test_two_steps_match_replicated_adam(self, mesh1d):
        # Adam is stateful: two chained sharded steps must track two
        # replicated-optimizer steps exactly (moments live on the shard)
        n, lr = 8, 0.05
        tx = optax.adam(lr)
        p0 = jnp.linspace(-1.0, 1.0, 48, dtype=jnp.float32)
        grads = [
            jnp.cos(jnp.arange(48, dtype=jnp.float32) * (r + 1))
            for r in range(n)
        ]
        g_sum = jnp.sum(jnp.stack(grads), 0)

        # replicated reference: two adam steps on the summed grad
        ref_state = tx.init({"w": p0})
        ref_p = {"w": p0}
        for _ in range(2):
            upd, ref_state = tx.update({"w": g_sum}, ref_state, ref_p)
            ref_p = optax.apply_updates(ref_p, upd)

        def body(g_stacked, params):
            g = {"w": g_stacked[0]}
            pt = {"w": params}
            state = zero.zero_init(tx, pt, "x", n)
            for _ in range(2):
                pt, state = zero.zero_apply(tx, g, state, pt, "x", n)
            return pt["w"]

        out = _run_sharded(mesh1d, body, grads, p0)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref_p["w"]), rtol=2e-5, atol=1e-6
        )

    def test_state_is_sharded(self, mesh1d):
        # the memory claim itself: adam moments have shard length ceil(n/p)
        n = 8
        tx = optax.adam(1e-3)
        p = jnp.ones((61,), jnp.float32)

        def body(g_stacked, params):
            state = zero.zero_init(tx, {"w": params}, "x", n)
            mu = state[0].mu["w"]
            return jnp.zeros((1,)) + mu.shape[0]

        out = _run_sharded(mesh1d, body, [p] * n, p)
        assert int(np.asarray(out)[0]) == zero.shard_size(61, 8) == 8


class TestMemoryModel:
    def test_dp_factor(self):
        params = {"a": jnp.ones((100,), jnp.float32)}
        m = zero.memory_model(params, axis_size=8, state_arrays=2)
        assert m["opt_state_bytes_replicated"] == 800.0
        assert m["opt_state_bytes_zero"] == 100.0  # ceil(400/8)*2
        assert m["wire_bytes_per_device"] == pytest.approx(2 * 7 / 8 * 400)


class TestZeroTrainStep:
    @pytest.mark.parametrize(
        "extra",
        [
            {},  # single block
            # ZeRO over STACKED params (depth via lax.scan, per-layer
            # remat): the shard machinery flattens whole stacked leaves
            {"depth": 2, "remat": True},
        ],
        ids=["plain", "depth_remat"],
    )
    def test_matches_plain_sgd_train_step(self, devices, extra):
        # the composition gate: one ZeRO-sgd step == make_train_step's SGD
        # (same summed-grad math via scatter instead of psum transpose)
        from tpu_patterns.models import (
            ModelConfig,
            init_params,
            make_train_step,
            make_zero_train_step,
            shard_params,
        )

        mesh = Mesh(np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))
        cfg = ModelConfig(
            embed=64, heads=8, head_dim=8, dtype="float32", **extra
        )
        lr = 1e-3
        params = init_params(jax.random.key(0), cfg)
        if cfg.depth > 1:
            assert params["wqkv"].shape[0] == cfg.depth  # stacked
        x = jax.random.normal(jax.random.key(1), (4, 32, 64), jnp.float32)
        sx = jax.device_put(x, NamedSharding(mesh, P("dp", "sp", None)))

        ref_step, _ = make_train_step(mesh, cfg, lr=lr)
        zstep, zinit, _ = make_zero_train_step(mesh, cfg, lr=lr, optimizer="sgd")

        p_ref = shard_params(params, mesh, cfg)
        shards, state = zinit(shard_params(params, mesh, cfg))
        p_ref, loss_ref = ref_step(p_ref, sx)
        shards, state, loss_z = zstep(shards, state, sx)
        np.testing.assert_allclose(float(loss_z), float(loss_ref), rtol=1e-6)
        p_z = zstep.gather(shards)
        for k in p_ref:
            np.testing.assert_allclose(
                np.asarray(p_z[k]), np.asarray(p_ref[k]), rtol=1e-5, atol=1e-7
            )

    def test_offloaded_state_lives_in_pinned_host(self, devices):
        # ZeRO + host offload: the moments' shardings carry the
        # pinned_host memory kind, params stay in device memory, and the
        # step's math is unchanged vs the on-device state variant
        from tpu_patterns.models import (
            ModelConfig,
            init_params,
            make_zero_train_step,
            shard_params,
        )

        mesh = Mesh(np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))
        cfg = ModelConfig(embed=64, heads=8, head_dim=8, dtype="float32")
        params = init_params(jax.random.key(0), cfg)
        x = jax.random.normal(jax.random.key(1), (4, 32, 64), jnp.float32)
        sx = jax.device_put(x, NamedSharding(mesh, P("dp", "sp", None)))

        outs = {}
        for offload in (False, True):
            step, init_fn, _ = make_zero_train_step(
                mesh, cfg, lr=1e-3, optimizer="adam", offload_state=offload
            )
            shards, state = init_fn(shard_params(params, mesh, cfg))
            kinds = {
                leaf.sharding.memory_kind
                for leaf in jax.tree_util.tree_leaves(state)
            }
            if offload:
                assert kinds == {"pinned_host"}, kinds
                pkinds = {
                    leaf.sharding.memory_kind
                    for leaf in jax.tree_util.tree_leaves(shards)
                }
                assert "pinned_host" not in pkinds  # params stay in HBM
            shards, state, loss = step(shards, state, sx)
            outs[offload] = (float(loss), shards)
        np.testing.assert_allclose(outs[False][0], outs[True][0], rtol=1e-6)
        for k in outs[False][1]:
            np.testing.assert_allclose(
                np.asarray(outs[False][1][k]),
                np.asarray(outs[True][1][k]),
                rtol=1e-6,
            )

    def test_adam_learns(self, devices):
        from tpu_patterns.models import (
            ModelConfig,
            init_params,
            make_zero_train_step,
            shard_params,
        )

        mesh = Mesh(np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))
        cfg = ModelConfig(embed=64, heads=8, head_dim=8, dtype="float32")
        params = init_params(jax.random.key(0), cfg)
        x = jax.random.normal(jax.random.key(1), (4, 32, 64), jnp.float32)
        sx = jax.device_put(x, NamedSharding(mesh, P("dp", "sp", None)))
        step, init_fn, _ = make_zero_train_step(
            mesh, cfg, lr=1e-3, optimizer="adam"
        )
        shards, state = init_fn(shard_params(params, mesh, cfg))
        losses = []
        for _ in range(4):
            shards, state, loss = step(shards, state, sx)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]  # the objective actually descends