"""Profile-trace reader (core/profile.py): wire-format parsing against
synthetically encoded XSpace bytes, op classification, breakdown math,
and the live jax.profiler round trip."""

import os

import pytest

from tpu_patterns.core import profile as prof


# -- tiny protobuf wire encoder (the test's independent implementation:
#    the parser must agree with bytes produced from the schema, not with
#    itself) --------------------------------------------------------------


def _varint(v: int) -> bytes:
    out = b""
    while True:
        b7 = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _field(num: int, wire: int, payload: bytes) -> bytes:
    head = _varint((num << 3) | wire)
    if wire == 2:
        return head + _varint(len(payload)) + payload
    return head + payload


def _msg(num: int, payload: bytes) -> bytes:
    return _field(num, 2, payload)


def _str(num: int, s: str) -> bytes:
    return _field(num, 2, s.encode())


def _int(num: int, v: int) -> bytes:
    return _field(num, 0, _varint(v))


def _event(mid: int, off_ps: int, dur_ps: int) -> bytes:
    return _int(1, mid) + _int(2, off_ps) + _int(3, dur_ps)


def _event_meta(mid: int, name: str) -> bytes:
    return _int(1, mid) + _str(2, name)


def _space(planes: list[bytes]) -> bytes:
    return b"".join(_msg(1, p) for p in planes)


def _tpu_plane() -> bytes:
    """A /device:TPU:0 plane: one op line with one event per category,
    plus a 'Steps' line that re-aggregates and must be skipped."""
    metas = {
        1: "fusion.42",
        2: "all-reduce.3",
        3: "copy-start.7",
        4: "outfeed",
        5: "custom-thing",
    }
    meta_entries = b"".join(
        _msg(4, _int(1, mid) + _msg(2, _event_meta(mid, name)))
        for mid, name in metas.items()
    )
    # op line at timestamp 1000 ns: fusion 4ms, all-reduce 2ms, copy 1ms,
    # outfeed 0.5ms, other 0.5ms; gap of 2ms before the last event
    ms = 10**9  # ps per ms
    events = (
        _msg(4, _event(1, 0, 4 * ms))
        + _msg(4, _event(2, 4 * ms, 2 * ms))
        + _msg(4, _event(3, 6 * ms, 1 * ms))
        + _msg(4, _event(4, 7 * ms, ms // 2))
        + _msg(4, _event(5, 9 * ms + ms // 2, ms // 2))
    )
    op_line = _int(1, 1) + _str(2, "XLA Ops") + _int(3, 1000) + events
    steps_line = (
        _int(1, 2) + _str(2, "Steps") + _int(3, 1000)
        + _msg(4, _event(1, 0, 10 * ms))
    )
    return (
        _int(1, 7)
        + _str(2, "/device:TPU:0")
        + _msg(3, op_line)
        + _msg(3, steps_line)
        + meta_entries
    )


def _host_plane() -> bytes:
    return _int(1, 9) + _str(2, "/host:CPU") + _msg(
        3, _int(1, 1) + _str(2, "python") + _msg(4, _event(1, 0, 123))
    )


class TestWireParser:
    def test_roundtrip_planes_lines_events(self, tmp_path):
        p = tmp_path / "t.xplane.pb"
        p.write_bytes(_space([_tpu_plane(), _host_plane()]))
        planes = prof.parse_xspace(str(p))
        assert [pl.name for pl in planes] == ["/device:TPU:0", "/host:CPU"]
        tpu = planes[0]
        assert [ln.name for ln in tpu.lines] == ["XLA Ops", "Steps"]
        ops = tpu.lines[0]
        assert ops.timestamp_ns == 1000
        assert [e.name for e in ops.events] == [
            "fusion.42", "all-reduce.3", "copy-start.7", "outfeed",
            "custom-thing",
        ]
        assert ops.events[0].duration_ps == 4 * 10**9

    def test_unknown_fields_skipped(self, tmp_path):
        # forward compatibility: an extra length-delimited field (99) and
        # an extra varint (98) inside the plane must not break parsing
        plane = _tpu_plane() + _str(99, "future") + _int(98, 7)
        p = tmp_path / "t.xplane.pb"
        p.write_bytes(_space([plane]))
        (tpu,) = prof.parse_xspace(str(p))
        assert tpu.name == "/device:TPU:0"
        assert len(tpu.lines[0].events) == 5


class TestClassify:
    @pytest.mark.parametrize(
        "name,cat",
        [
            ("fusion.123", "compute"),
            ("dot.7", "compute"),
            ("all-reduce.1", "collective"),
            ("reduce-scatter.2", "collective"),  # not plain 'reduce'
            ("all-to-all", "collective"),
            ("collective-permute-start", "collective"),
            ("copy.3", "dma"),
            ("copy-start.1", "dma"),
            # in-place fused update loop: compute on TPU, not DMA-engine
            # time (VERDICT r3 weak #4)
            ("dynamic-update-slice-fusion", "compute"),
            ("transpose.4", "compute"),  # VPU, not a copy engine
            # a fusion wrapping a copy is still a compute loop
            ("loop_copy_fusion.2", "compute"),
            ("outfeed", "infeed_outfeed"),
            ("reduce.9", "compute"),
            ("send.2", "collective"),
            # word boundaries: collective tokens must not fire inside
            # unrelated op names (ADVICE r3) — 'send' must not match
            # inside 'condsend' (the custom-call token fires instead)
            ("condsend-custom-call", "compute"),
            ("wrecv_thing", "other"),
            # Pallas/Mosaic kernels surface as custom calls and are the
            # framework's hot COMPUTE ops (flash fwd/bwd) — booking
            # them 'other' would fail the unclassified-time gate on the
            # first profiled pallas run (caught by a pre-capture
            # dry-fire of the fixture tier)
            ("some-custom-call", "compute"),
            ("tpu_custom_call.flash_fwd", "compute"),
            ("mosaic_kernel.3", "compute"),
            # ...but a DMA-flavored kernel name keeps its engine bucket
            ("tpu_custom_call.dma_overlap", "dma"),
        ],
    )
    def test_rules(self, name, cat):
        assert prof.classify(name) == cat


class TestBreakdown:
    def test_categories_and_idle(self, tmp_path):
        run = tmp_path / "plugins" / "profile" / "run1"
        os.makedirs(run)
        (run / "host.xplane.pb").write_bytes(
            _space([_tpu_plane(), _host_plane()])
        )
        bd = prof.breakdown(str(tmp_path))
        assert bd is not None
        assert bd["compute_ms"] == pytest.approx(4.0)
        assert bd["collective_ms"] == pytest.approx(2.0)
        assert bd["dma_ms"] == pytest.approx(1.0)
        assert bd["infeed_outfeed_ms"] == pytest.approx(0.5)
        assert bd["other_ms"] == pytest.approx(0.5)
        assert bd["busy_ms"] == pytest.approx(8.0)
        # wall spans first start .. last end = 10 ms; idle = 2 ms gap
        assert bd["wall_ms"] == pytest.approx(10.0)
        assert bd["idle_ms"] == pytest.approx(2.0)
        assert bd["compute_frac"] == pytest.approx(0.5)
        # the Steps line (re-aggregation) must NOT be double counted
        assert bd["busy_ms"] < 10.0 + 1e-6

    def test_multi_plane_idle_sums_per_chip(self, tmp_path):
        # two chips, each 8ms busy over a 10ms span: idle must be 2+2=4,
        # not max(0, 10 - 16) = 0 (the shared-wall undercount)
        run = tmp_path / "plugins" / "profile" / "run1"
        os.makedirs(run)
        (run / "host.xplane.pb").write_bytes(
            _space([_tpu_plane(), _tpu_plane()])
        )
        bd = prof.breakdown(str(tmp_path))
        assert bd["busy_ms"] == pytest.approx(16.0)
        assert bd["idle_ms"] == pytest.approx(4.0)
        assert bd["wall_ms"] == pytest.approx(10.0)
        assert bd["n_device_planes"] == 2.0

    def test_truncated_file_raises_not_hangs(self, tmp_path):
        # the CLI catches parser exceptions; the parser's contract is to
        # RAISE on truncation, never to loop or return silently-wrong data
        blob = _space([_tpu_plane()])
        p = tmp_path / "t.xplane.pb"
        p.write_bytes(blob[: len(blob) // 2])
        with pytest.raises(Exception):
            prof.parse_xspace(str(p))

    def test_no_device_plane_returns_none(self, tmp_path):
        run = tmp_path / "plugins" / "profile" / "run1"
        os.makedirs(run)
        (run / "host.xplane.pb").write_bytes(_space([_host_plane()]))
        assert prof.breakdown(str(tmp_path)) is None

    def test_empty_dir_returns_none(self, tmp_path):
        assert prof.breakdown(str(tmp_path)) is None


class TestLiveTrace:
    def test_jax_trace_parses(self, tmp_path, devices):
        # the real jax.profiler writes a parsable xplane file; on the CPU
        # platform there is no device plane, so breakdown is honestly None
        import jax
        import jax.numpy as jnp
        import glob

        with jax.profiler.trace(str(tmp_path)):
            f = jax.jit(lambda a: (a @ a).sum())
            jax.block_until_ready(f(jnp.ones((128, 128))))
        files = glob.glob(
            str(tmp_path / "**" / "*.xplane.pb"), recursive=True
        )
        assert files, "jax.profiler wrote no xplane file"
        planes = prof.parse_xspace(files[0])
        assert planes and any(
            ln.events for p in planes for ln in p.lines
        )
        assert prof.breakdown(str(tmp_path)) is None


class TestOpNameSnapshot:
    def test_names_counts_and_categories(self, tmp_path):
        run = tmp_path / "plugins" / "profile" / "run1"
        os.makedirs(run)
        (run / "host.xplane.pb").write_bytes(
            _space([_tpu_plane(), _host_plane()])
        )
        names = prof.op_name_snapshot(str(tmp_path))
        assert names is not None
        assert names["fusion.42"]["category"] == "compute"
        assert names["fusion.42"]["count"] == 1
        assert names["all-reduce.3"]["category"] == "collective"
        assert names["custom-thing"]["category"] == "other"
        # Steps-line re-aggregation and the host plane must not appear
        assert "python" not in names

    def test_no_trace_is_none(self, tmp_path):
        assert prof.op_name_snapshot(str(tmp_path)) is None


class TestCrosscheckRate:
    BD = {"compute_ms": 4.0, "busy_ms": 8.0, "wall_ms": 10.0,
          "idle_ms": 2.0}

    def test_coherent_rate(self):
        # 60 TFLOP/s over wall with 40% compute-of-wall -> implied 150,
        # under a 197 peak: the accountings cohere
        cc = prof.crosscheck_rate(60.0, self.BD, 197.0)
        assert cc["implied_mxu_tflops"] == pytest.approx(150.0)
        assert cc["coherent"] == 1.0

    def test_incoherent_rate_flagged(self):
        # 120 TFLOP/s over wall with 40% compute -> implied 300 > 1.1*197:
        # the FLOP multiplier or the classifier is wrong
        cc = prof.crosscheck_rate(120.0, self.BD, 197.0)
        assert cc["implied_mxu_tflops"] == pytest.approx(300.0)
        assert cc["coherent"] == 0.0

    def test_multi_chip_bound_scales(self):
        cc = prof.crosscheck_rate(120.0, self.BD, 197.0, n_chips=2)
        assert cc["coherent"] == 1.0

    def test_no_peak_no_verdict(self):
        cc = prof.crosscheck_rate(120.0, self.BD, None)
        assert "coherent" not in cc


class TestProfileCheckCLI:
    def test_snapshot_gate_and_crosscheck(self, tmp_path, capsys):
        import json

        from tpu_patterns.cli import main
        from tpu_patterns.core.results import Record

        run = tmp_path / "plugins" / "profile" / "run1"
        os.makedirs(run)
        (run / "host.xplane.pb").write_bytes(
            _space([_tpu_plane(), _host_plane()])
        )
        rates = tmp_path / "rates.jsonl"
        rates.write_text(
            Record(
                pattern="longctx",
                mode="flash_grad",
                commands="x",
                metrics={"tflops_hw": 60.0},
            ).to_json()
            + "\n"
        )
        snap = tmp_path / "ops.json"
        jl = tmp_path / "out.jsonl"
        rc = main(
            ["--jsonl", str(jl), "profilecheck", str(tmp_path),
             "--snapshot-out", str(snap), "--rates-jsonl", str(rates)]
        )
        assert rc == 0
        fixture = json.loads(snap.read_text())
        assert fixture["fusion.42"]["category"] == "compute"
        with open(jl) as f:
            recs = [json.loads(ln) for ln in f]
        by_mode = {r["mode"]: r for r in recs}
        assert by_mode["profile_ops"]["metrics"]["unique_names"] == 5.0
        # other = 0.5 of 8ms busy -> 6.25%, under the 20% gate
        assert by_mode["profile_ops"]["verdict"] == "SUCCESS"
        # off-TPU there is no peak: crosscheck reports, verdict SUCCESS
        # (coherent is absent, not failed)
        assert by_mode["profile_crosscheck"]["verdict"] == "SUCCESS"
        assert by_mode["profile_crosscheck"]["metrics"][
            "compute_frac_of_wall"
        ] == pytest.approx(0.4)

    def test_empty_dir_is_skipped(self, tmp_path):
        from tpu_patterns.cli import main

        rc = main(["profilecheck", str(tmp_path)])
        assert rc == 0


class TestCrosscheckZeroCompute:
    def test_positive_rate_with_zero_compute_is_incoherent(self):
        bd = {"compute_ms": 0.0, "busy_ms": 8.0, "wall_ms": 10.0}
        cc = prof.crosscheck_rate(60.0, bd, None)
        assert cc["coherent"] == 0.0  # even with no peak known

    def test_zero_rate_zero_compute_is_fine(self):
        bd = {"compute_ms": 0.0, "busy_ms": 8.0, "wall_ms": 10.0}
        cc = prof.crosscheck_rate(0.0, bd, 197.0)
        assert "coherent" not in cc


class TestCommittedOpNameFixtures:
    """The classifier against COMMITTED vocabulary (VERDICT r3 next #6):
    every op-name fixture under tests/fixtures/ is re-classified by the
    CURRENT rules — a rule change that unbuckets a hot op, or books >20%
    of busy time as 'other', fails here with no TPU needed.  The
    synthetic fixture (scripts/make_xplane_fixture.py) guarantees this
    tier always runs; hardware-ladder snapshots add silicon vocabulary
    alongside it as they land."""

    FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures")

    def _fixtures(self):
        import glob

        return sorted(
            glob.glob(os.path.join(self.FIXDIR, "op_names_*.json"))
        )

    def test_real_vocabulary_classifies(self):
        import json

        fixtures = self._fixtures()
        # the synthetic fixture is committed: this tier may never skip
        # again (it sat skipped for two rounds — VERDICT weak #6)
        assert fixtures, (
            "tests/fixtures/op_names_*.json missing — regenerate with "
            "scripts/make_xplane_fixture.py"
        )
        for path in fixtures:
            with open(path) as f:
                names = json.load(f)
            assert names, path
            total = sum(d["duration_ps"] for d in names.values()) or 1
            other = sum(
                d["duration_ps"]
                for n, d in names.items()
                if prof.classify(n) == "other"
            )
            # same bar as profilecheck's live gate: an unclassified hot
            # op skews every breakdown fraction
            assert other / total <= 0.20, (
                f"{path}: {other / total:.1%} of real busy time "
                "unclassified under current rules"
            )
            # drift net: the category recorded at capture time must
            # match what the current rules produce, or the fixture (and
            # every committed breakdown) is stale
            for n, d in names.items():
                assert prof.classify(n) == d["category"], (
                    f"{path}: rule drift on {n!r}: "
                    f"{d['category']} -> {prof.classify(n)}"
                )

    def test_synthetic_pb_parses_and_classifies(self):
        """The committed BINARY fixture through the real reader: the
        wire-format writer (scripts/make_xplane_fixture.py) and the
        reader must agree on the bytes, and the snapshot derived from
        them must cover every classifier family."""
        pb = os.path.join(self.FIXDIR, "synthetic.xplane.pb")
        assert os.path.exists(pb), (
            "tests/fixtures/synthetic.xplane.pb missing — regenerate "
            "with scripts/make_xplane_fixture.py"
        )
        planes = prof.parse_xspace(pb)
        assert [p.name for p in planes] == ["/device:TPU:0", "/host:CPU"]
        names = prof.op_name_snapshot(self.FIXDIR)
        assert names is not None
        # one representative per family, spelled as silicon spells them
        assert names["fusion.42"]["category"] == "compute"
        assert names["all-reduce.3"]["category"] == "collective"
        assert names["copy-start.11"]["category"] == "dma"
        assert names["tpu_custom_call.flash_fwd"]["category"] == "compute"
        assert names["tpu_custom_call.dma_overlap"]["category"] == "dma"
        assert names["outfeed"]["category"] == "infeed_outfeed"
        assert names["zzz-unknown-op.9"]["category"] == "other"
        # the breakdown runs off the same bytes: busy must exclude the
        # re-aggregating Steps line and the host plane
        bd = prof.breakdown(self.FIXDIR)
        assert bd is not None
        assert bd["busy_ms"] == pytest.approx(
            sum(d["duration_ps"] for d in names.values()) / 1e9
        )
        assert bd["idle_ms"] > 0  # the writer leaves inter-op gaps

    def test_synthetic_json_matches_pb(self):
        """The two committed artifacts describe the same trace — a
        regenerated .pb with a stale .json (or vice versa) fails."""
        import json

        with open(
            os.path.join(self.FIXDIR, "op_names_synthetic.json")
        ) as f:
            committed = json.load(f)
        derived = prof.op_name_snapshot(self.FIXDIR)
        assert derived == committed, (
            "tests/fixtures out of sync — rerun "
            "scripts/make_xplane_fixture.py"
        )
