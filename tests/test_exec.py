"""Concurrent sweep engine (tpu_patterns/exec/, docs/sweep-engine.md)."""

import json
import os
import signal
import sys
import threading
import time

import pytest

from tpu_patterns import sweep
from tpu_patterns.exec import (
    CellClass,
    classify,
    detect_platform,
    run_cells,
    run_command,
)
from tpu_patterns.sweep import SweepSpec


def _cpu_env():
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return env


class TestClassify:
    def test_backend_env_forces_isolation(self):
        # every runtime-suite cell toggles backend-init-time state: a warm
        # worker would render the knob silently inert
        for spec in sweep.runtime_specs(quick=True):
            if any(
                k.startswith(("LIBTPU_", "JAX_")) for k, _ in spec.env
            ):
                assert classify(spec, "cpu") is CellClass.ENV_ISOLATED
                assert classify(spec, "tpu") is CellClass.ENV_ISOLATED

    def test_sweep_config_tag_is_not_isolation(self):
        # the report-keying tag is framework-tier env, re-read per run —
        # it must NOT push a cell off the warm path
        spec = SweepSpec(
            "x", ("p2p",), env=(("TPU_PATTERNS_SWEEP_CONFIG", "x"),)
        )
        assert classify(spec, "cpu") is CellClass.HOST_PARALLEL

    def test_device_commands_exclusive_on_tpu_only(self):
        spec = SweepSpec("x", ("p2p", "--devices", "2"))
        assert classify(spec, "tpu") is CellClass.DEVICE_EXCLUSIVE
        assert classify(spec, "cpu") is CellClass.HOST_PARALLEL
        # libtpu is single-process: even "analysis" commands init the
        # default backend, so on hardware they serialize too
        assert (
            classify(SweepSpec("t", ("topo",)), "tpu")
            is CellClass.DEVICE_EXCLUSIVE
        )
        # only backend-free log/manifest readers stay parallel on TPU
        assert (
            classify(SweepSpec("r", ("report", "x.log")), "tpu")
            is CellClass.HOST_PARALLEL
        )
        # an unknown future subcommand defaults to device-owning (safe)
        assert (
            classify(SweepSpec("n", ("newthing",)), "tpu")
            is CellClass.DEVICE_EXCLUSIVE
        )

    def test_every_suite_cell_classifies(self):
        for spec in sweep.specs_for("all", quick=True):
            assert classify(spec, "tpu") in CellClass
            assert classify(spec, "cpu") in CellClass

    def test_detect_platform_reads_pins_without_backend_touch(self):
        assert detect_platform({"JAX_PLATFORMS": "cpu"}) == "cpu"
        assert detect_platform({"TPU_PATTERNS_PLATFORM": "tpu"}) == "tpu"
        # the package pin outranks the jax one (same precedence as
        # runtime.setup_jax)
        assert (
            detect_platform(
                {"TPU_PATTERNS_PLATFORM": "cpu", "JAX_PLATFORMS": "tpu"}
            )
            == "cpu"
        )


class TestProcessGroupKill:
    def test_timeout_kills_grandchild(self, tmp_path):
        # REGRESSION (round-5 "device backend unreachable"): the old
        # subprocess.run(timeout=...) killed only the direct child; a
        # double-forked grandchild survived holding the TPU and broke
        # the NEXT cell's backend init.  run_command kills the GROUP.
        script = (
            "import subprocess, sys, time\n"
            "p = subprocess.Popen([sys.executable, '-c',"
            " 'import time; time.sleep(600)'])\n"
            "print('GRANDCHILD', p.pid, flush=True)\n"
            "time.sleep(600)\n"
        )
        stdout, rc, timed_out = run_command(
            [sys.executable, "-c", script], timeout=3
        )
        assert timed_out and rc == 1
        assert "GRANDCHILD" in stdout  # partial output survives the kill
        pid = int(stdout.split("GRANDCHILD", 1)[1].split()[0])
        # the grandchild must be DEAD (reaped by init), not orphaned
        for _ in range(50):
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                break
            time.sleep(0.1)
        else:
            os.kill(pid, signal.SIGKILL)  # cleanup before failing
            pytest.fail(f"grandchild {pid} survived the group kill")

    def test_clean_exit_passes_through(self):
        stdout, rc, timed_out = run_command(
            [sys.executable, "-c", "print('ok')"], timeout=30
        )
        assert (stdout.strip(), rc, timed_out) == ("ok", 0, False)


class TestStateContention:
    def test_concurrent_record_cell_is_lossless(self, tmp_path):
        # the engine checkpoints cells from several pool threads at
        # once: N threads x M cells, every record must replay intact
        n_threads, m_cells = 8, 25
        out = str(tmp_path)

        def writer(t):
            for m in range(m_cells):
                sweep._record_cell(
                    out, "s", f"cell.t{t}.m{m}", rc=t % 2,
                    sig=f"sig{t}", completed=True,
                )

        threads = [
            threading.Thread(target=writer, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # raw file: every line is a complete JSON record (no torn writes)
        with open(os.path.join(out, "sweep-state.jsonl")) as f:
            lines = f.readlines()
        assert len(lines) == n_threads * m_cells
        for ln in lines:
            json.loads(ln)
        # replay: every cell present with its own thread's values
        state = sweep.load_sweep_state(out)
        assert len(state) == n_threads * m_cells
        for t in range(n_threads):
            for m in range(m_cells):
                assert state[f"cell.t{t}.m{m}"] == {
                    "rc": t % 2, "sig": f"sig{t}", "completed": True,
                }


class TestScheduler:
    def _stub_specs(self, n_host=6, n_dev=2):
        host = [SweepSpec(f"h{i}", ("topo",)) for i in range(n_host)]
        dev = [SweepSpec(f"d{i}", ("p2p",)) for i in range(n_dev)]
        return host + dev

    def test_results_in_spec_order_and_engine_record(self, tmp_path):
        specs = self._stub_specs()
        seen = []
        lock = threading.Lock()

        def runner(spec):
            with lock:
                seen.append(spec.name)
            time.sleep(0.05)
            return 0, True

        results, rec = run_cells(
            specs, str(tmp_path), jobs=4, warm_workers=False,
            cell_timeout=30, platform="cpu", subprocess_runner=runner,
            progress=lambda s: None,
        )
        assert [r.spec.name for r in results] == [s.name for s in specs]
        assert sorted(seen) == sorted(s.name for s in specs)
        assert all(r.completed and r.rc == 0 for r in results)
        assert rec.pattern == "sweep" and rec.mode == "engine"
        assert rec.metrics["cells"] == len(specs)
        assert rec.metrics["speedup"] > 1.0
        assert rec.verdict.value == "SUCCESS"

    def test_device_exclusive_cells_never_overlap(self, tmp_path):
        # on TPU, device cells must drain strictly serially even while
        # the host pool fans out (only backend-free readers stay
        # host-parallel on hardware)
        specs = [
            SweepSpec(f"h{i}", ("report", "x.log")) for i in range(4)
        ] + [SweepSpec(f"d{i}", ("p2p",)) for i in range(4)]
        active_dev = []
        max_dev = [0]
        lock = threading.Lock()

        def runner(spec):
            is_dev = spec.name.startswith("d")
            with lock:
                if is_dev:
                    active_dev.append(spec.name)
                    max_dev[0] = max(max_dev[0], len(active_dev))
            time.sleep(0.05)
            with lock:
                if is_dev:
                    active_dev.remove(spec.name)
            return 0, True

        _, rec = run_cells(
            specs, str(tmp_path), jobs=4, warm_workers=False,
            cell_timeout=30, platform="tpu", subprocess_runner=runner,
            progress=lambda s: None,
        )
        assert max_dev[0] == 1
        assert rec.metrics["device_exclusive_cells"] == 4
        assert rec.metrics["host_parallel_cells"] == 4

    def test_failures_propagate_and_record(self, tmp_path):
        specs = self._stub_specs(n_host=3, n_dev=0)
        results, _ = run_cells(
            specs, str(tmp_path), jobs=2, warm_workers=False,
            cell_timeout=30, platform="cpu",
            subprocess_runner=lambda s: (1, True),
            progress=lambda s: None,
        )
        assert all(r.rc == 1 and r.completed for r in results)

    def test_env_isolated_fans_out_off_tpu(self, tmp_path):
        # env-isolated means "no warm process", not "serial": off-TPU a
        # private subprocess IS the isolation, so the runtime.* cells
        # must overlap instead of flooring the wall clock
        specs = [
            SweepSpec(
                f"e{i}", ("concurrency",),
                env=(("LIBTPU_INIT_ARGS", f"--flag{i}"),),
            )
            for i in range(4)
        ]
        active, peak = [], [0]
        lock = threading.Lock()

        def runner(spec):
            with lock:
                active.append(spec.name)
                peak[0] = max(peak[0], len(active))
            time.sleep(0.05)
            with lock:
                active.remove(spec.name)
            return 0, True

        results, rec = run_cells(
            specs, str(tmp_path), jobs=4, warm_workers=False,
            cell_timeout=30, platform="cpu", subprocess_runner=runner,
            progress=lambda s: None,
        )
        assert peak[0] > 1  # overlapped
        assert all(r.runner == "subprocess" for r in results)  # no worker
        assert rec.metrics["env_isolated_cells"] == 4
        # ...but on TPU the same cells serialize (they own the chip)
        peak[0] = 0
        _, _ = run_cells(
            specs, str(tmp_path), jobs=4, warm_workers=False,
            cell_timeout=30, platform="tpu", subprocess_runner=runner,
            progress=lambda s: None,
        )
        assert peak[0] == 1

    def test_single_host_cell_is_skipped_verdict(self, tmp_path):
        # one cell at jobs=4: nothing to overlap — the Record must say
        # SKIPPED, never claim a concurrency win
        _, rec = run_cells(
            [SweepSpec("h0", ("topo",))], str(tmp_path), jobs=4,
            warm_workers=False, cell_timeout=30, platform="cpu",
            subprocess_runner=lambda s: (0, True),
            progress=lambda s: None,
        )
        assert rec.verdict.value == "SKIPPED"


class TestRunSweepJobs:
    def test_run_sweep_engine_checkpoints_and_banks_record(
        self, tmp_path, monkeypatch, capsys
    ):
        names = [
            "p2p.compact.mesh.two_sided.n2",
            "p2p.compact.visible.two_sided.n2",
            "p2p.spread.mesh.two_sided.n2",
        ]
        monkeypatch.setattr(
            sweep, "run_spec",
            lambda spec, out, base_env=None, timeout=None: (0, True),
        )
        rc = sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=names,
            base_env={"JAX_PLATFORMS": "cpu"}, jobs=3, warm_workers=False,
        )
        assert rc == 0
        state = sweep.load_sweep_state(str(tmp_path))
        assert all(state[n]["completed"] for n in names)
        with open(tmp_path / "sweep-engine.jsonl") as f:
            recs = [json.loads(ln) for ln in f if ln.strip()]
        assert recs and recs[-1]["mode"] == "engine"
        assert recs[-1]["metrics"]["host_parallel_cells"] == 3
        out = capsys.readouterr().out
        assert "sweep cell" in out and "## engine |" in out

    def test_engine_resume_skips_completed(self, tmp_path, monkeypatch):
        name = "p2p.compact.mesh.two_sided.n2"
        calls = []
        monkeypatch.setattr(
            sweep, "run_spec",
            lambda spec, out, base_env=None, timeout=None: calls.append(
                spec.name
            ) or (0, True),
        )
        sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
            base_env={}, jobs=2, warm_workers=False,
        )
        sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
            base_env={}, jobs=2, warm_workers=False, resume=True,
        )
        assert calls == [name]  # engine + resume share one checkpoint

    def test_engine_failure_rc_aggregates(self, tmp_path, monkeypatch):
        name = "p2p.compact.mesh.two_sided.n2"
        monkeypatch.setattr(
            sweep, "run_spec",
            lambda spec, out, base_env=None, timeout=None: (1, True),
        )
        rc = sweep.run_sweep(
            "p2p", out_dir=str(tmp_path), quick=True, names=[name],
            base_env={}, jobs=2, warm_workers=False,
        )
        assert rc == 1


class TestWarmWorkers:
    @pytest.fixture(scope="class")
    def pool(self, tmp_path_factory):
        from tpu_patterns.exec.workers import WorkerPool

        d = tmp_path_factory.mktemp("workers")
        pool = WorkerPool(1, _cpu_env(), log_dir=str(d))
        yield pool
        pool.shutdown()

    def test_worker_serves_and_reuses(self, pool, tmp_path):
        w = pool.lease()
        assert w is not None and w.ready
        for i in range(2):  # second cell reuses the warm runtime
            log = tmp_path / f"cell{i}.log"
            jsonl = tmp_path / f"cell{i}.jsonl"
            resp = w.request(
                {
                    "op": "cell",
                    "cell": f"cell{i}",
                    "argv": ["topo"],
                    "env": {"TPU_PATTERNS_SWEEP_CONFIG": "t"},
                    "log": str(log),
                    "jsonl": str(jsonl),
                },
                timeout=120,
            )
            assert resp["rc"] == 0 and resp["served"] == i + 1
            assert "devices: 8 (cpu)" in log.read_text()
        pool.release(w, reusable=True)
        w2 = pool.lease()
        assert w2 is w  # reuse hit
        assert pool.hits == 1
        pool.release(w2, reusable=True)

    def test_worker_crash_in_cell_reports_rc_and_traceback(
        self, pool, tmp_path
    ):
        w = pool.lease()
        log = tmp_path / "bad.log"
        resp = w.request(
            {
                "op": "cell",
                "cell": "bad",
                "argv": ["allreduce", "--algorithm", "ringg"],
                "env": {},
                "log": str(log),
                "jsonl": str(tmp_path / "bad.jsonl"),
            },
            timeout=120,
        )
        assert resp["rc"] != 0
        assert w.alive()  # a cell failure must not kill the server
        # nonzero rc -> recycled, preserving the fresh-runtime guarantee
        pool.release(w, reusable=False)
        assert pool.recycled >= 1

    def test_scheduler_worker_path_end_to_end(self, tmp_path):
        # two REAL host-parallel cells through the warm-worker path: the
        # log artifact must carry the export-context prologue and the
        # same completion semantics as the subprocess path
        specs = [
            SweepSpec(
                "t0", ("topo",), env=(("TPU_PATTERNS_SWEEP_CONFIG", "a"),)
            ),
            SweepSpec(
                "t1", ("topo",), env=(("TPU_PATTERNS_SWEEP_CONFIG", "b"),)
            ),
        ]
        results, rec = run_cells(
            specs, str(tmp_path), jobs=2, warm_workers=True,
            cell_timeout=240, base_env=_cpu_env(), platform="cpu",
            progress=lambda s: None,
        )
        assert all(r.rc == 0 and r.completed for r in results)
        assert {r.runner for r in results} == {"worker"}
        text = (tmp_path / "t0.log").read_text()
        assert text.startswith("export TPU_PATTERNS_SWEEP_CONFIG=a\n")
        assert "devices: 8 (cpu)" in text
        assert rec.metrics["worker_cells"] == 2


class TestWorkerCircuitBreaker:
    def test_broken_worker_init_kills_the_warm_path_fast(self, tmp_path):
        # a wedged/broken worker init must not cost a spawn-wait PER
        # CELL: after two consecutive failures the pool declares the
        # warm path dead and lease() returns None instantly
        from tpu_patterns.exec.workers import WorkerPool

        env = _cpu_env()
        env["TPU_PATTERNS_PLATFORM"] = "bogus_platform"  # init dies
        pool = WorkerPool(2, env, log_dir=str(tmp_path))
        try:
            assert pool.lease() is None
            assert pool.lease() is None
            assert pool._dead
            t0 = time.monotonic()
            assert pool.lease() is None  # no spawn attempt at all
            assert time.monotonic() - t0 < 1.0
            assert pool.stats()["worker_hit_rate"] == 0.0
        finally:
            pool.shutdown()


class TestWatchdogQueue:
    def test_queued_deadline_fires_and_disarm_prevents(self, tmp_path):
        from tpu_patterns import obs
        from tpu_patterns.obs import watchdog

        obs.configure(str(tmp_path))
        try:
            fired_before = len(watchdog.fired_dumps())
            w = obs.watch_queued(
                "test.queue.cell", deadline_s=0.2, cell="c1"
            )
            deadline = time.monotonic() + 10
            while (
                len(watchdog.fired_dumps()) == fired_before
                and time.monotonic() < deadline
            ):
                time.sleep(0.1)
            dumps = watchdog.fired_dumps()
            assert len(dumps) > fired_before
            assert "queued" in os.path.basename(dumps[-1])
            w.done()
            # a disarmed watch must NOT fire
            w2 = obs.watch_queued("test.queue.fast", deadline_s=0.2)
            w2.done()
            n = len(watchdog.fired_dumps())
            time.sleep(1.5)
            assert len(watchdog.fired_dumps()) == n
        finally:
            obs.configure(None)


class TestCliFlags:
    def test_engine_flags_parse(self):
        from tpu_patterns.cli import build_parser

        args = build_parser().parse_args(
            ["sweep", "p2p", "--jobs", "4", "--no-warm-workers",
             "--name", "a", "--name", "b"]
        )
        assert args.jobs == 4 and args.no_warm_workers
        assert args.name == ["a", "b"]

    def test_engine_flags_rejected_for_promote_and_summarize(self):
        from tpu_patterns.cli import main

        for suite in ("promote", "summarize"):
            with pytest.raises(SystemExit, match="do not apply"):
                main(["sweep", suite, "--jobs", "4"])
            with pytest.raises(SystemExit, match="do not apply"):
                main(["sweep", suite, "--name", "x"])

    def test_unknown_name_fails_loudly_via_cli(self, tmp_path):
        # a one-line usage error at the CLI boundary, not a traceback
        from tpu_patterns.cli import main

        with pytest.raises(SystemExit, match="unknown cell name"):
            main(["sweep", "p2p", "--quick", "--out", str(tmp_path),
                  "--name", "nope"])
