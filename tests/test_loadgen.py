"""Load generator (tpu_patterns/loadgen): seeded arrival processes,
scenario preset grammar, the streaming percentile sketch, the
loadgen.arrive fault site, and the end-to-end SLO measured pattern —
lifecycle spans, TTFT/TPOT export, goodput verdicts, chaos-under-load
coverage — through the real ServeEngine on the CPU mesh."""

import json
import random

import numpy as np
import pytest
from jax.sharding import Mesh

from tpu_patterns import faults, obs
from tpu_patterns.core.results import ResultWriter, Verdict
from tpu_patterns.loadgen import (
    ArrivalSource,
    LoadGenConfig,
    PRESETS,
    StreamingPercentiles,
    arrival_offsets,
    build_schedule,
    parse_scenario,
    run_loadgen,
)
from tpu_patterns.loadgen.runner import _resolved_specs
from tpu_patterns.loadgen.scenarios import TimedRequest
from tpu_patterns.serve.engine import Request, ServeEngine


class TestArrivals:
    @pytest.mark.parametrize("process", ["poisson", "bursty", "diurnal"])
    def test_seeded_replay_is_bit_identical(self, process):
        a = arrival_offsets(process, 50, 8.0, random.Random(7))
        b = arrival_offsets(process, 50, 8.0, random.Random(7))
        assert a == b
        c = arrival_offsets(process, 50, 8.0, random.Random(8))
        assert a != c  # the seed is the only source of variation

    @pytest.mark.parametrize("process", ["poisson", "bursty", "diurnal"])
    def test_offsets_positive_and_nondecreasing(self, process):
        offs = arrival_offsets(process, 40, 4.0, random.Random(1))
        assert len(offs) == 40
        assert offs[0] > 0
        assert all(x <= y for x, y in zip(offs, offs[1:]))

    def test_diurnal_ramps_up(self):
        # the ramp's whole point: the tail is denser than the head
        offs = arrival_offsets("diurnal", 200, 10.0, random.Random(2))
        gaps = [y - x for x, y in zip(offs, offs[1:])]
        q = len(gaps) // 4
        assert np.mean(gaps[:q]) > np.mean(gaps[-q:])

    @pytest.mark.parametrize("process", ["poisson", "bursty", "diurnal"])
    def test_long_run_rate_matches_the_requested_rate(self, process):
        # the regression that motivates this: per-arrival state
        # switching makes the long-run rate the HARMONIC mean of the
        # state rates — an arithmetic-mean normalization under-delivers
        # ~2x at burstiness 6 and every SLO verdict benches the wrong
        # workload
        offs = arrival_offsets(process, 5000, 8.0, random.Random(0))
        measured = 5000 / offs[-1]
        assert measured == pytest.approx(8.0, rel=0.10), process

    def test_unknown_process_and_bad_params_rejected(self):
        with pytest.raises(ValueError, match="unknown arrival process"):
            arrival_offsets("uniform", 5, 1.0, random.Random(0))
        with pytest.raises(ValueError, match="rate_rps"):
            arrival_offsets("poisson", 5, 0.0, random.Random(0))
        with pytest.raises(ValueError, match="at least one"):
            arrival_offsets("poisson", 0, 1.0, random.Random(0))


class TestStreamingPercentiles:
    @pytest.mark.parametrize("n", [1, 2, 3, 5, 17, 100])
    def test_exact_below_the_cap_vs_numpy(self, n):
        rng = random.Random(n)
        vals = [rng.uniform(-50, 50) for _ in range(n)]
        sk = StreamingPercentiles()
        for v in vals:
            sk.observe(v)
        for q in (0.0, 0.25, 0.5, 0.9, 0.95, 0.99, 1.0):
            want = float(np.quantile(vals, q))  # method="linear"
            assert sk.quantile(q) == pytest.approx(want, abs=1e-9), (n, q)
        assert sk.mean == pytest.approx(float(np.mean(vals)))
        assert sk.count == n

    def test_empty_series_edge(self):
        sk = StreamingPercentiles()
        assert sk.quantile(0.5) is None
        assert sk.mean is None
        assert sk.summary() == {}
        assert len(sk) == 0

    def test_nan_and_bad_q_rejected(self):
        sk = StreamingPercentiles()
        with pytest.raises(ValueError):
            sk.observe(float("nan"))
        sk.observe(1.0)
        with pytest.raises(ValueError):
            sk.quantile(1.5)

    def test_streaming_past_the_cap_stays_accurate(self):
        rng = random.Random(0)
        sk = StreamingPercentiles(max_samples=256)
        for _ in range(10_000):
            sk.observe(rng.uniform(0.0, 1.0))
        # compaction keeps real observed values with bounded rank error
        assert sk.quantile(0.5) == pytest.approx(0.5, abs=0.06)
        assert sk.quantile(0.95) == pytest.approx(0.95, abs=0.06)
        assert sk.quantile(0.0) == sk._min  # extremes exact
        assert sk.quantile(1.0) == sk._max
        assert sk.count == 10_000
        p50, p95, p99 = (
            sk.quantile(0.5), sk.quantile(0.95), sk.quantile(0.99)
        )
        assert p50 <= p95 <= p99

    def test_compaction_is_deterministic(self):
        def build():
            rng = random.Random(5)
            sk = StreamingPercentiles(max_samples=32)
            for _ in range(1000):
                sk.observe(rng.gauss(10, 3))
            return sk

        a, b = build(), build()
        assert a._vw == b._vw  # bit-identical state: replay contract

    def test_merge_small_is_exact_and_streaming(self):
        xs = [float(v) for v in range(10)]
        ys = [float(v) for v in range(100, 120)]
        a, b = StreamingPercentiles(), StreamingPercentiles()
        for v in xs:
            a.observe(v)
        for v in ys:
            b.observe(v)
        a.merge(b)
        both = xs + ys
        for q in (0.1, 0.5, 0.95):
            assert a.quantile(q) == pytest.approx(
                float(np.quantile(both, q))
            )
        assert a.count == len(both)
        # merge past the cap still compacts instead of growing
        big = StreamingPercentiles(max_samples=16)
        for v in range(100):
            big.observe(float(v))
        small = StreamingPercentiles(max_samples=16)
        small.observe(1e6)
        big.merge(small)
        assert len(big._vw) <= 16
        assert big.quantile(1.0) == 1e6


class TestScenarioGrammar:
    def test_presets_cover_every_arrival_process(self):
        assert {s.arrival for s in PRESETS.values()} == {
            "poisson", "bursty", "diurnal",
        }

    def test_parse_defaults_and_overrides(self):
        chat = parse_scenario("chat")
        assert chat == PRESETS["chat"]
        tuned = parse_scenario("chat:requests=9:rate_rps=3.5")
        assert tuned.requests == 9 and tuned.rate_rps == 3.5
        assert tuned.slo_ttft_ms == chat.slo_ttft_ms  # others untouched

    def test_unknown_preset_key_and_value_rejected(self):
        with pytest.raises(ValueError, match="unknown preset"):
            parse_scenario("beam")
        with pytest.raises(ValueError, match="unknown key"):
            parse_scenario("chat:burstiness=2")
        with pytest.raises(ValueError, match="not key=value"):
            parse_scenario("chat:requests")
        with pytest.raises(ValueError, match="not a int"):
            parse_scenario("chat:requests=many")

    def test_working_set_mult_spellable_and_validated(self):
        # the memory-pressure knob (tiered KV cache): spellable in the
        # grammar, defaults off, negatives rejected at parse time
        spec = parse_scenario("chat:working_set_mult=1.4")
        assert spec.working_set_mult == 1.4
        assert parse_scenario("chat").working_set_mult == 0.0
        with pytest.raises(ValueError, match="working_set_mult"):
            parse_scenario("chat:working_set_mult=-1")

    def test_working_set_mult_does_not_move_the_schedule(self):
        # pool sizing is the runner's business: the schedule itself
        # (arrivals, lengths, tokens) must replay bit-identically with
        # the knob on or off
        a = build_schedule(parse_scenario("chat"), vocab=64, seed=3)
        b = build_schedule(
            parse_scenario("chat:working_set_mult=2"), vocab=64, seed=3
        )
        assert [
            (t.arrival_s, t.request.tokens, t.request.n_gen) for t in a
        ] == [
            (t.arrival_s, t.request.tokens, t.request.n_gen) for t in b
        ]

    def test_session_dir_requires_kv_host_tier(self):
        from tpu_patterns.loadgen import LoadGenConfig, validate_config

        with pytest.raises(ValueError, match="kv_host_tier"):
            validate_config(LoadGenConfig(session_dir="/tmp/x"))

    def test_inconsistent_ranges_rejected(self):
        with pytest.raises(ValueError, match="min_prompt <= mean_prompt"):
            parse_scenario("chat:mean_prompt=100")
        with pytest.raises(ValueError, match="SLO budgets"):
            parse_scenario("chat:slo_ttft_ms=0")
        with pytest.raises(ValueError, match="chaos_p99_mult"):
            parse_scenario("chat:chaos_p99_mult=0.5")

    def test_deadline_is_ttft_plus_per_token_budget(self):
        s = parse_scenario("chat:slo_ttft_ms=1000:slo_tpot_ms=100")
        assert s.deadline_ms(1) == 1000
        assert s.deadline_ms(11) == 2000

    def test_resolved_specs_splits_strings_and_applies_overrides(self):
        specs = _resolved_specs(
            LoadGenConfig(scenarios="chat,rag", slo_ttft_ms=9000)
        )
        assert [s.name for s in specs] == ["chat", "rag"]
        assert all(s.slo_ttft_ms == 9000 for s in specs)
        with pytest.raises(ValueError, match="duplicate scenario"):
            _resolved_specs(LoadGenConfig(scenarios=("chat", "chat")))

    def test_validate_config_catches_typos_before_any_compile(self):
        from tpu_patterns.loadgen import validate_config

        validate_config(LoadGenConfig())  # defaults are valid
        with pytest.raises(ValueError, match="unknown preset"):
            validate_config(LoadGenConfig(scenarios=("chatt",)))
        with pytest.raises(ValueError, match="unknown site"):
            validate_config(LoadGenConfig(chaos="nope.site:error"))
        with pytest.raises(ValueError, match="time_scale"):
            validate_config(LoadGenConfig(time_scale=0.0))
        with pytest.raises(ValueError, match="vocab"):
            validate_config(LoadGenConfig(vocab=1))
        with pytest.raises(ValueError, match="min_goodput"):
            validate_config(LoadGenConfig(min_goodput=2.0))


class TestSharedPrefixes:
    """Chat-shaped shared system prompts (PR 12): ``prefix_groups`` x
    ``shared_prefix`` opt in per spec; off by default so existing
    schedules replay bit-identically."""

    def test_grammar_spells_the_new_fields(self):
        spec = parse_scenario("chat:prefix_groups=2:shared_prefix=16")
        assert spec.prefix_groups == 2 and spec.shared_prefix == 16

    def test_fields_come_together_or_not_at_all(self):
        with pytest.raises(ValueError, match="come together"):
            parse_scenario("chat:prefix_groups=2")
        with pytest.raises(ValueError, match="come together"):
            parse_scenario("chat:shared_prefix=8")

    def test_shared_prefix_must_leave_a_private_suffix(self):
        with pytest.raises(ValueError, match="private suffix"):
            parse_scenario(
                "chat:prefix_groups=2:shared_prefix=48"
            )  # == chat max_prompt

    def test_every_prompt_opens_with_a_group_prefix(self):
        spec = parse_scenario(
            "chat:requests=20:prefix_groups=3:shared_prefix=16"
        )
        sched = build_schedule(spec, vocab=64, seed=5)
        prefixes = {
            tuple(tr.request.tokens[:16]) for tr in sched
        }
        assert 1 <= len(prefixes) <= 3  # every prompt uses a pool entry
        for tr in sched:
            assert len(tr.request.tokens) > 16  # private tail exists
            assert len(tr.request.tokens) <= spec.max_prompt

    def test_prefix_free_schedules_are_unchanged(self):
        # the feature draws its extra randoms only when enabled, so a
        # prefix-free spec's schedule is byte-identical to the same
        # spec before the fields existed (and to itself, trivially)
        plain = parse_scenario("chat:requests=8")
        assert plain.prefix_groups == 0 and plain.shared_prefix == 0
        a = build_schedule(plain, vocab=64, seed=1)
        b = build_schedule(plain, vocab=64, seed=1)
        assert a == b
        shared = parse_scenario(
            "chat:requests=8:prefix_groups=2:shared_prefix=16"
        )
        c = build_schedule(shared, vocab=64, seed=1)
        assert [t.arrival_s for t in a] == [t.arrival_s for t in c]
        assert [t.request.tokens for t in a] != [
            t.request.tokens for t in c
        ]


class TestScheduleReplay:
    def test_bit_identical_replay(self):
        spec = parse_scenario("agentic:requests=12")
        a = build_schedule(spec, vocab=64, seed=3, time_scale=0.5)
        b = build_schedule(spec, vocab=64, seed=3, time_scale=0.5)
        assert a == b  # arrivals, lengths, tokens, deadlines — all of it
        c = build_schedule(spec, vocab=64, seed=4, time_scale=0.5)
        assert [t.arrival_s for t in a] != [t.arrival_s for t in c]
        assert [t.request.tokens for t in a] != [
            t.request.tokens for t in c
        ]

    def test_lengths_respect_spec_and_labels_ride_along(self):
        spec = parse_scenario("rag:requests=30")
        sched = build_schedule(spec, vocab=64, seed=0)
        for tr in sched:
            r = tr.request
            assert spec.min_prompt <= len(r.tokens) <= spec.max_prompt
            assert spec.min_gen <= r.n_gen <= spec.max_gen
            assert r.scenario == "rag"
            assert r.deadline_ms == spec.deadline_ms(r.n_gen)

    def test_time_scale_compresses_arrivals_not_deadlines(self):
        spec = parse_scenario("chat:requests=8")
        full = build_schedule(spec, vocab=64, seed=1, time_scale=1.0)
        fast = build_schedule(spec, vocab=64, seed=1, time_scale=0.1)
        for a, b in zip(full, fast):
            assert b.arrival_s == pytest.approx(a.arrival_s * 0.1)
            assert b.request.deadline_ms == a.request.deadline_ms


def _toy_schedule(n, arrival_s=0.0):
    return [
        TimedRequest(
            request=Request(
                rid=i, tokens=[1, 2, 3], n_gen=2, scenario="chat"
            ),
            arrival_s=arrival_s,
        )
        for i in range(n)
    ]


class TestLoadgenArriveSite:
    """The loadgen.arrive fault site fires where the generator releases
    an arrival: error drops (recorded), sleep delays."""

    @pytest.fixture(autouse=True)
    def _clean_faults(self):
        yield
        faults.configure(None)

    def test_site_is_registered(self):
        assert "loadgen.arrive" in faults.KNOWN_SITES
        (spec,) = faults.parse_spec("loadgen.arrive:error:rid=3")
        assert spec.site == "loadgen.arrive"
        assert spec.match == (("rid", "3"),)

    def test_error_drops_the_arrival_and_records_it(self):
        faults.configure("loadgen.arrive:error:count=1")
        src = ArrivalSource(_toy_schedule(3), scenario="chat")
        batch = src(idle=True)
        assert [r.rid for r, _ in batch] == [1, 2]  # rid 0 dropped
        assert list(src.dropped) == [0]
        assert "dropped" in src.dropped[0]
        assert src(idle=False) is None  # exhausted

    def test_rid_match_predicate_targets_one_arrival(self):
        faults.configure("loadgen.arrive:error:rid=1")
        src = ArrivalSource(_toy_schedule(3), scenario="chat")
        batch = src(idle=True)
        assert [r.rid for r, _ in batch] == [0, 2]
        assert list(src.dropped) == [1]

    def test_sleep_delays_but_still_releases(self):
        from tpu_patterns.core.timing import clock_ns

        faults.configure("loadgen.arrive:sleep:delay_s=0.05:count=1")
        src = ArrivalSource(_toy_schedule(2), scenario="chat")
        t0 = clock_ns()
        batch = src(idle=True)
        elapsed_s = (clock_ns() - t0) / 1e9
        assert [r.rid for r, _ in batch] == [0, 1]  # delayed, not dropped
        assert elapsed_s >= 0.05
        assert not src.dropped

    def test_source_paces_future_arrivals(self):
        # nothing due yet + idle engine -> the source owns the wait
        src = ArrivalSource(
            _toy_schedule(1, arrival_s=0.04), scenario="chat",
            max_sleep_s=0.01,
        )
        batches = []
        for _ in range(50):
            b = src(idle=True)
            if b is None:
                break
            batches.extend(b)
        assert [r.rid for r, _ in batches] == [0]


class TestIdlePreemption:
    def test_preempt_while_idle_waiting_for_arrivals_returns(self):
        """A signal taken while the engine idles between sparse
        arrivals must end the run at that boundary, not after the next
        arrival is served (no compiled cores needed: the loop never
        reaches one)."""
        from tpu_patterns.serve.paged import PagedLayout

        class _StubDecoder:
            layout = PagedLayout(n_blocks=4, block_len=8, sp=1)
            n_pages = 2

            def init_pool(self):
                return None

        eng = ServeEngine(_StubDecoder(), params=None, slots=1)
        polled = []

        def source(idle=False):
            polled.append(idle)
            if len(polled) > 3:
                eng._preempt.set()  # the SIGTERM handler's only action
            return []  # arrivals still pending, none due

        out = eng.run([], source=source)
        assert out == {}
        assert eng.preempted_at == 0  # ended at the idle boundary
        assert len(polled) < 10  # did not spin on after the signal


CHAT_QUICK = (
    "chat:requests=6:min_prompt=4:mean_prompt=8:max_prompt=16"
    ":min_gen=2:mean_gen=4:max_gen=6"
)


@pytest.fixture(scope="module")
def slo_run(devices, tmp_path_factory):
    """ONE clean + chaos loadgen run through the real engine (module
    scope: the compile is the expensive part), returning everything the
    assertions below read."""
    obs.flight_recorder().clear()
    obs.metrics_registry().clear()
    mesh = Mesh(np.array(devices[:4]).reshape(1, 2, 2), ("dp", "sp", "tp"))
    cfg = LoadGenConfig(
        vocab=64, embed=64, head_dim=8, depth=1, slots=4, block_len=8,
        scenarios=(CHAT_QUICK,), time_scale=0.02,
        slo_ttft_ms=60_000, slo_tpot_ms=20_000,
        chaos=(
            "serve.step:error:count=1,"
            "loadgen.arrive:error:after=2:count=1"
        ),
        chaos_p99_mult=50,
    )
    jsonl = tmp_path_factory.mktemp("loadgen") / "records.jsonl"
    writer = ResultWriter(jsonl_path=str(jsonl))
    records = run_loadgen(mesh, cfg, writer)
    out = {
        "records": records,
        "jsonl": [
            json.loads(ln)
            for ln in open(jsonl)
            if ln.strip()
        ],
        "entries": obs.flight_recorder().snapshot(),
        "registry": {
            (m.name, tuple(sorted(m.labels.items()))): m
            for m in obs.metrics_registry().metrics()
        },
    }
    obs.flight_recorder().clear()
    obs.metrics_registry().clear()
    yield out


class TestRunLoadgen:
    def test_clean_record_passes_slo_with_full_coverage(self, slo_run):
        rec = next(
            r for r in slo_run["records"] if r.mode.startswith("chat_sp")
        )
        m = rec.metrics
        assert rec.verdict is Verdict.SUCCESS
        assert m["goodput"] == 1.0
        assert m["done"] == m["requests"] == 6.0
        assert m["failed"] == m["dropped"] == 0.0
        for key in ("ttft", "tpot", "e2e"):
            assert (
                0
                < m[f"{key}_p50_ms"]
                <= m[f"{key}_p95_ms"]
                <= m[f"{key}_p99_ms"]
            )
        # e2e covers queueing + every token: it bounds TTFT from above
        assert m["e2e_p99_ms"] >= m["ttft_p99_ms"]

    def test_chaos_record_gates_coverage_and_bounded_p99(self, slo_run):
        rec = next(
            r for r in slo_run["records"] if "_chaos_" in r.mode
        )
        m = rec.metrics
        assert rec.verdict is not Verdict.FAILURE
        assert m["covered"] == 1.0
        assert m["injected"] >= 2.0  # step error + arrival drop fired
        assert m["dropped"] == 1.0
        assert m["done"] + m["failed"] + m["dropped"] == m["requests"]
        assert m["leaked_blocks"] == 0.0
        # bounded: the ratio gate held (ratio < 0 = empty clean series)
        assert m["p99_ratio"] <= m["p99_mult_gate"]

    def test_one_record_per_scenario_lands_in_jsonl(self, slo_run):
        modes = [r["mode"] for r in slo_run["jsonl"]]
        assert len([m for m in modes if m.startswith("chat_sp")]) == 1
        assert len([m for m in modes if "_chaos_" in m]) == 1

    def test_lifecycle_spans_reach_the_flight_recorder(self, slo_run):
        req_spans = [
            e for e in slo_run["entries"]
            if e["name"].startswith("req.")
        ]
        by_name = {}
        for e in req_spans:
            by_name.setdefault(e["name"], []).append(e)
        # 6 clean + 5 chaos-done + 0 quarantined retirements minimum
        assert len(by_name["req.queued"]) >= 11
        # every request gets its OWN lane even though the clean and
        # chaos legs both restart rids at 0 (per-engine lane windows)
        queued = by_name["req.queued"]
        assert len({e["tid"] for e in queued}) == len(queued)
        assert {"req.prefill", "req.decode", "req.first_token",
                "req.retired"} <= set(by_name)
        for e in req_spans:
            assert "rid" in e["attrs"]
            assert e["attrs"].get("scenario") == "chat"
            assert e["tid"] >= 1_000_000  # its own trace lane

    def test_chrome_trace_shows_per_request_lanes(self, slo_run):
        from tpu_patterns.obs import export as obs_export

        trace = obs_export.chrome_trace(slo_run["entries"])
        lanes = [
            e for e in trace["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "thread_name"
        ]
        labels = {e["args"]["name"] for e in lanes}
        assert "req 0 [chat]" in labels
        assert len(labels) == 6  # one named lane per request
        # lifecycle phases ride in the same exported timeline
        names = {e["name"] for e in trace["traceEvents"]}
        assert {"req.queued", "req.prefill", "req.decode"} <= names

    def test_serve_latency_histograms_export_from_the_engine(
        self, slo_run
    ):
        reg = slo_run["registry"]
        done_total = sum(
            r.metrics["done"] for r in slo_run["records"]
        )
        ttft = reg[("tpu_patterns_serve_ttft_ms", ())]
        tpot = reg[("tpu_patterns_serve_tpot_ms", ())]
        waits = reg[("tpu_patterns_serve_queue_wait_ms", ())]
        assert ttft.count == done_total
        assert tpot.count == done_total  # min_gen >= 2: all have TPOT
        assert waits.count >= done_total
        assert ttft.sum > 0 and tpot.sum > 0

    def test_loadgen_gauges_and_counters_export(self, slo_run):
        reg = slo_run["registry"]
        good = reg[
            ("tpu_patterns_loadgen_goodput", (("scenario", "chat"),))
        ]
        assert good.value == 1.0  # chaos leg overwrites... still 1.0
        assert reg[(
            "tpu_patterns_loadgen_requests_total",
            (("scenario", "chat"), ("status", "done")),
        )].value >= 6
        assert reg[(
            "tpu_patterns_loadgen_requests_total",
            (("scenario", "chat"), ("status", "dropped")),
        )].value == 1
        p99 = reg[
            ("tpu_patterns_loadgen_e2e_p99_ms", (("scenario", "chat"),))
        ]
        assert p99.value > 0


class TestPriorityClasses:
    """``bulk_fraction`` tags arrivals with priority classes for the
    PR 16 preemption ladder — spelled in the scenario grammar, drawn
    LAST so priority-free schedules replay bit-identically."""

    def test_grammar_spells_and_validates_bulk_fraction(self):
        # batch-summarize is the diurnal-ramp preset the elastic
        # smoke drives; bulk_fraction rides any preset
        spec = parse_scenario("batch-summarize:bulk_fraction=0.4")
        assert spec.arrival == "diurnal"
        assert spec.bulk_fraction == 0.4
        with pytest.raises(ValueError, match="bulk_fraction"):
            parse_scenario("chat:bulk_fraction=1.5")

    def test_priority_free_schedules_are_unchanged(self):
        # the conditional-last draw: enabling bulk_fraction must not
        # move arrivals, prompts, or lengths — only the priority tags
        plain = parse_scenario("chat:requests=12")
        assert plain.bulk_fraction == 0.0
        a = build_schedule(plain, vocab=64, seed=1)
        assert all(t.request.priority == "interactive" for t in a)
        a2 = build_schedule(plain, vocab=64, seed=1)
        assert a == a2  # no hidden draw when the feature is off
        mixed = parse_scenario("chat:requests=12:bulk_fraction=0.5")
        b = build_schedule(mixed, vocab=64, seed=1)
        # arrivals are drawn up front: the class draw never moves them
        assert [t.arrival_s for t in a] == [t.arrival_s for t in b]
        # request 0's lengths/tokens predate the first class draw
        assert a[0].request.tokens == b[0].request.tokens

    def test_bulk_draw_tags_both_classes_and_replays(self):
        spec = parse_scenario("chat:requests=20:bulk_fraction=0.5")
        a = build_schedule(spec, vocab=64, seed=7)
        classes = {t.request.priority for t in a}
        assert classes == {"interactive", "bulk"}
        b = build_schedule(spec, vocab=64, seed=7)
        assert a == b  # priorities ride the seeded replay

    def test_preempt_config_validated(self):
        from tpu_patterns.loadgen import LoadGenConfig, validate_config

        validate_config(LoadGenConfig(kv_host_tier=True, preempt="bulk"))
        with pytest.raises(ValueError, match="preempt must be"):
            validate_config(LoadGenConfig(preempt="everything"))
        with pytest.raises(ValueError, match="requires kv_host_tier"):
            validate_config(LoadGenConfig(preempt="bulk"))
