"""PatternFormer: the dp x sp x tp training-step composition.

Validation per SURVEY.md §4: the distributed program must reproduce the
single-device result (ring-vs-library philosophy applied to the whole
model), and a training step must actually learn (loss decreases).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_patterns.models import (
    ModelConfig,
    forward_shard,
    init_params,
    make_train_step,
    shard_params,
)

CFG = ModelConfig(embed=64, heads=8, head_dim=8)
B, L = 4, 32


@pytest.fixture(scope="module")
def mesh3d(devices):
    return Mesh(np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.key(0), CFG)


@pytest.fixture(scope="module")
def batch():
    return jax.random.normal(jax.random.key(1), (B, L, CFG.embed), jnp.float32)


def test_single_device_forward(params, batch):
    out = jax.jit(lambda p, x: forward_shard(p, x, CFG))(params, batch)
    assert out.shape == batch.shape
    assert np.isfinite(np.asarray(out)).all()


def test_sharded_loss_matches_single_device(mesh3d, params, batch):
    """The full dp x sp x tp program computes the same objective as one
    device — the whole-model analogue of ring-vs-MPI_Allreduce."""
    step, pspecs = make_train_step(mesh3d, CFG, lr=0.0)
    sp_params = shard_params(params, mesh3d, CFG)
    sx = jax.device_put(batch, NamedSharding(mesh3d, P("dp", "sp", None)))
    _, loss = step(sp_params, sx)

    z = forward_shard(params, batch, CFG)
    want = float(jnp.sum(z.astype(jnp.float32) ** 2))
    assert np.isclose(float(loss), want, rtol=1e-4)


def test_deep_stack_matches_python_loop(mesh3d, batch):
    """depth>1 (scan over stacked params) must equal applying the layers
    sequentially on one device."""
    import dataclasses

    from jax.sharding import NamedSharding

    dcfg = dataclasses.replace(CFG, depth=3)
    stacked = init_params(jax.random.key(7), dcfg)
    want = batch
    for s in range(3):
        want = forward_shard({k: v[s] for k, v in stacked.items()}, want, CFG)
    want_loss = float(jnp.sum(want.astype(jnp.float32) ** 2))

    step, _ = make_train_step(mesh3d, dcfg, lr=0.0)
    p = shard_params(stacked, mesh3d, dcfg)
    sx = jax.device_put(batch, NamedSharding(mesh3d, P("dp", "sp", None)))
    _, loss = step(p, sx)
    np.testing.assert_allclose(float(loss), want_loss, rtol=1e-5)


def test_deep_remat_same_math_less_memory(mesh3d, batch):
    """Per-layer checkpoint under scan: identical loss/params, and the
    compiled step's peak temp memory drops (the O(depth)->O(1) stash)."""
    import dataclasses

    from jax.sharding import NamedSharding

    from tpu_patterns.models.transformer import _memory_metrics

    sx = jax.device_put(batch, NamedSharding(mesh3d, P("dp", "sp", None)))
    dcfg = dataclasses.replace(CFG, depth=4)
    stacked = init_params(jax.random.key(8), dcfg)
    temps = {}
    outs = {}
    for remat in (False, True):
        cfg = dataclasses.replace(dcfg, remat=remat)
        step, _ = make_train_step(mesh3d, cfg, lr=1e-3)
        p = shard_params(stacked, mesh3d, cfg)
        outs[remat] = step(p, sx)
        temps[remat] = _memory_metrics(step, p, sx).get("peak_temp_MB")
    np.testing.assert_allclose(
        float(outs[False][1]), float(outs[True][1]), rtol=1e-6
    )
    for k in outs[False][0]:
        # recomputed forwards may fuse/round differently: close, not
        # bit-identical
        np.testing.assert_allclose(
            np.asarray(outs[False][0][k]), np.asarray(outs[True][0][k]),
            rtol=1e-4, atol=1e-6,
        )
    if temps[False] is not None and temps[True] is not None:
        assert temps[True] < temps[False], temps


def test_remat_dots_policy_same_math_memory_between(mesh3d, batch):
    """Selective (dots) checkpoint: identical loss to both neighbors,
    compiled peak temp between full remat (saves nothing) and no remat
    (saves everything) — the Megatron-style middle point."""
    import dataclasses

    from jax.sharding import NamedSharding

    from tpu_patterns.models.transformer import _memory_metrics

    sx = jax.device_put(batch, NamedSharding(mesh3d, P("dp", "sp", None)))
    dcfg = dataclasses.replace(CFG, depth=4)
    stacked = init_params(jax.random.key(8), dcfg)
    temps, losses = {}, {}
    for name, kw in (
        ("none", dict(remat=False)),
        ("dots", dict(remat=True, remat_policy="dots")),
        ("full", dict(remat=True)),
    ):
        cfg = dataclasses.replace(dcfg, **kw)
        step, _ = make_train_step(mesh3d, cfg, lr=1e-3)
        p = shard_params(stacked, mesh3d, cfg)
        _, losses[name] = step(p, sx)
        temps[name] = _memory_metrics(step, p, sx).get("peak_temp_MB")
    np.testing.assert_allclose(
        float(losses["none"]), float(losses["dots"]), rtol=1e-6
    )
    np.testing.assert_allclose(
        float(losses["none"]), float(losses["full"]), rtol=1e-6
    )
    if all(t is not None for t in temps.values()):
        # saving the dot outputs can only cost memory vs saving nothing,
        # and must still beat saving everything
        assert temps["full"] <= temps["dots"] * 1.01, temps
        assert temps["dots"] < temps["none"], temps


def test_remat_policy_validated(mesh3d):
    import dataclasses

    with pytest.raises(ValueError, match="remat_policy"):
        make_train_step(
            mesh3d,
            dataclasses.replace(CFG, remat=True, remat_policy="bogus"),
            lr=1e-3,
        )


def test_flagship_flops_remat_accounting():
    # dots recompute = attention only: strictly between 3x and 4x fwd
    import dataclasses

    from tpu_patterns.models.transformer import FlagshipConfig, flagship_flops

    base = FlagshipConfig(seq=256, batch=2)
    none = flagship_flops(base)
    dots = flagship_flops(
        dataclasses.replace(base, remat=True, remat_policy="dots")
    )
    full = flagship_flops(dataclasses.replace(base, remat=True))
    assert none < dots < full
    assert full == pytest.approx(none * 4 / 3)


def test_pipeline_rejects_depth(mesh3d):
    import dataclasses

    from tpu_patterns.models import make_pipeline_train_step

    with pytest.raises(ValueError, match="single blocks"):
        make_pipeline_train_step(
            mesh3d, dataclasses.replace(CFG, depth=2), n_micro=2
        )


def test_remat_step_matches_plain(mesh3d, params, batch):
    """jax.checkpoint must change memory, never math: identical loss and
    identical updated params vs the non-remat step."""
    import dataclasses

    from jax.sharding import NamedSharding

    sx = jax.device_put(batch, NamedSharding(mesh3d, P("dp", "sp", None)))
    step, _ = make_train_step(mesh3d, CFG, lr=1e-3)
    rstep, _ = make_train_step(
        mesh3d, dataclasses.replace(CFG, remat=True), lr=1e-3
    )
    p = shard_params(params, mesh3d, CFG)
    new_a, loss_a = step(p, sx)
    new_b, loss_b = rstep(p, sx)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
    for k in new_a:
        np.testing.assert_allclose(
            np.asarray(new_a[k]), np.asarray(new_b[k]), rtol=1e-6, atol=1e-8
        )


def test_flagship_memory_metrics_present():
    from tpu_patterns.models.transformer import _memory_metrics

    f = jax.jit(lambda a: jnp.sum(a * 2.0))
    m = _memory_metrics(f, jnp.ones((128, 128)))
    # best-effort API: when present, the sizes must be sane
    if m:
        assert m["argument_MB"] > 0
        assert m["peak_temp_MB"] >= 0


def test_train_step_learns(mesh3d, params, batch):
    step, _ = make_train_step(mesh3d, CFG, lr=1e-4)
    p = shard_params(params, mesh3d, CFG)
    sx = jax.device_put(batch, NamedSharding(mesh3d, P("dp", "sp", None)))
    losses = []
    for _ in range(5):
        p, loss = step(p, sx)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


class TestDonation:
    """donate=True must (a) actually take (compiled memory analysis:
    aliased input bytes > 0), (b) not change the math, and (c) consume
    the input state — the HBM double-residency the train loop pays for
    without it."""

    def _sharded(self, mesh3d, params, batch):
        return (
            shard_params(params, mesh3d, CFG),
            jax.device_put(
                batch, NamedSharding(mesh3d, P("dp", "sp", None))
            ),
        )

    def test_train_step_donation_takes_and_matches(
        self, mesh3d, params, batch
    ):
        from tpu_patterns.models.transformer import donation_took

        p, sx = self._sharded(mesh3d, params, batch)
        step, _ = make_train_step(mesh3d, CFG, lr=1e-3)
        dstep, _ = make_train_step(mesh3d, CFG, lr=1e-3, donate=True)
        took = donation_took(dstep, p, sx)
        if took is None:
            pytest.skip("backend exposes no memory-analysis API")
        # "where the backend supports it": the CPU backend in CI does
        assert took, "donate_argnums was silently declined"
        new_a, loss_a = step(p, sx)
        new_b, loss_b = dstep(p, sx)  # consumes p
        np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
        for k in new_a:
            np.testing.assert_array_equal(
                np.asarray(new_a[k]), np.asarray(new_b[k])
            )
        # the donated params are GONE — the in-place update is real
        assert all(
            v.is_deleted() for v in p.values()
        ), "donated inputs still alive: the step copied instead of aliasing"

    def test_zero_step_donates_shards_and_moments(self, mesh3d, batch):
        from tpu_patterns.models.transformer import (
            donation_took,
            make_zero_train_step,
        )

        cfg = ModelConfig(embed=64, heads=8, head_dim=8)
        params = init_params(jax.random.key(0), cfg)
        p, sx = self._sharded(mesh3d, params, batch)
        zstep, zinit, _ = make_zero_train_step(
            mesh3d, cfg, lr=1e-3, optimizer="adam", donate=True
        )
        shards, opt = zinit(p)
        took = donation_took(zstep, shards, opt, sx)
        if took is None:
            pytest.skip("backend exposes no memory-analysis API")
        assert took
        new_shards, new_opt, loss = zstep(shards, opt, sx)
        assert np.isfinite(float(loss))
        assert all(
            v.is_deleted() for v in jax.tree_util.tree_leaves(shards)
        )
        assert all(
            v.is_deleted() for v in jax.tree_util.tree_leaves(opt)
        )
        # the returned state is live and usable for the next step
        zstep(new_shards, new_opt, sx)


@pytest.mark.parametrize("layout", ["contiguous", "striped"])
def test_fused_attention_flagship(mesh3d, batch, layout):
    """The train step with cfg.attn="pallas": fused flash kernels forward
    AND backward inside the full dp x sp x tp program.  Loss must match
    the single-device XLA stack (sum-of-squares is token-permutation
    invariant, so the striped feed compares directly), and a step must
    learn."""
    cfg = ModelConfig(embed=64, heads=8, head_dim=8, attn="pallas",
                      attn_layout=layout)
    cfg_ref = ModelConfig(embed=64, heads=8, head_dim=8)
    params = init_params(jax.random.key(2), cfg)
    x = batch
    if layout == "striped":
        sp = int(mesh3d.shape["sp"])
        x = jnp.concatenate([x[:, r::sp] for r in range(sp)], axis=1)
    step, _ = make_train_step(mesh3d, cfg, lr=1e-4)
    p = shard_params(params, mesh3d, cfg)
    sx = jax.device_put(x, NamedSharding(mesh3d, P("dp", "sp", None)))
    p1, loss = step(p, sx)
    z = forward_shard(params, batch, cfg_ref)
    want = float(jnp.sum(z.astype(jnp.float32) ** 2))
    assert np.isclose(float(loss), want, rtol=1e-4), (float(loss), want)
    _, loss2 = step(p1, sx)
    assert float(loss2) < float(loss)


@pytest.mark.parametrize("attn", ["xla", "pallas"])
@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pipeline_schedule_gradients_agree(devices, batch, attn, schedule):
    """EVERY (attention impl x pipeline schedule) combination must produce
    the same updated parameters as the xla+gpipe baseline — this is the
    gate that catches silent gradient-reduction bugs (a wrong-scaled
    gradient still decreases the loss, so learn-tests cannot)."""
    from tpu_patterns.models import init_stack_params, make_pipeline_train_step

    mesh = Mesh(
        np.array(devices[:8]).reshape(1, 2, 2, 2), ("dp", "sp", "tp", "pp")
    )
    base_cfg = ModelConfig(embed=64, heads=8, head_dim=8)
    stack = init_stack_params(jax.random.key(0), base_cfg, 2)
    x = batch

    def run(attn_i, sched_i):
        cfg = ModelConfig(embed=64, heads=8, head_dim=8, attn=attn_i)
        step, pspecs = make_pipeline_train_step(
            mesh, cfg, n_micro=2, lr=1.0, schedule=sched_i
        )
        p = {
            k: jax.device_put(v, NamedSharding(mesh, pspecs[k]))
            for k, v in stack.items()
        }
        sx = jax.device_put(x, NamedSharding(mesh, P("dp", "sp", None)))
        new, loss = step(p, sx)
        return {k: np.asarray(v) for k, v in new.items()}, float(loss)

    got, loss = run(attn, schedule)
    want, loss0 = run("xla", "gpipe")
    assert np.isclose(loss, loss0, rtol=1e-5)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], atol=1e-3, err_msg=k)


def test_params_updated_consistently(mesh3d, params, batch):
    """After a step, tp-replicated params must remain identical across
    replicas (dp/sp grad sync correct) — fetching to host would mask a
    divergence, so compare per-shard."""
    step, _ = make_train_step(mesh3d, CFG, lr=1e-4)
    p = shard_params(params, mesh3d, CFG)
    sx = jax.device_put(batch, NamedSharding(mesh3d, P("dp", "sp", None)))
    p2, _ = step(p, sx)
    for name, arr in p2.items():
        shards = [np.asarray(s.data) for s in arr.addressable_shards]
        # group shards by their index (replicas share an index slice)
        by_index = {}
        for s, d in zip(arr.addressable_shards, shards):
            by_index.setdefault(str(s.index), []).append(d)
        for reps in by_index.values():
            for r in reps[1:]:
                np.testing.assert_array_equal(reps[0], r, err_msg=name)


class TestMoEFlagship:
    CFG = ModelConfig(embed=64, heads=8, head_dim=8, moe=True)

    def test_moe_loss_matches_single_device(self, mesh3d, batch):
        from tpu_patterns.models import make_train_step, shard_params
        from jax.sharding import NamedSharding, PartitionSpec as P

        params = init_params(jax.random.key(7), self.CFG, n_experts=2)
        step, _ = make_train_step(mesh3d, self.CFG, lr=0.0)
        sp = shard_params(params, mesh3d, self.CFG)
        sx = jax.device_put(batch, NamedSharding(mesh3d, P("dp", "sp", None)))
        _, loss = step(sp, sx)
        z = forward_shard(params, batch, self.CFG)
        want = float(jnp.sum(z.astype(jnp.float32) ** 2))
        assert np.isclose(float(loss), want, rtol=1e-4)

    def test_moe_train_learns(self, mesh3d, batch):
        from tpu_patterns.models import make_train_step, shard_params
        from jax.sharding import NamedSharding, PartitionSpec as P

        params = init_params(jax.random.key(8), self.CFG, n_experts=2)
        step, _ = make_train_step(mesh3d, self.CFG, lr=1e-4)
        p = shard_params(params, mesh3d, self.CFG)
        sx = jax.device_put(batch, NamedSharding(mesh3d, P("dp", "sp", None)))
        losses = []
        for _ in range(4):
            p, loss = step(p, sx)
            losses.append(float(loss))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]


class TestPipelineFlagship:
    """Flagship v2: dp x sp x tp x pp (x ep) in one differentiable program."""

    N_MICRO = 2

    @pytest.fixture(scope="class")
    def mesh4d(self, devices):
        from jax.sharding import Mesh

        return Mesh(
            np.array(devices[:8]).reshape(1, 2, 2, 2), ("dp", "sp", "tp", "pp")
        )

    @pytest.mark.parametrize("moe", [False, True])
    def test_pipeline_loss_matches_sequential(self, mesh4d, batch, moe):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpu_patterns.models import (
            forward_stack,
            init_stack_params,
            make_pipeline_train_step,
        )

        cfg = ModelConfig(embed=64, heads=8, head_dim=8, moe=moe)
        n_exp = 2 if moe else 0
        stack = init_stack_params(jax.random.key(9), cfg, 2, n_experts=n_exp)
        step, pspecs = make_pipeline_train_step(mesh4d, cfg, self.N_MICRO, lr=0.0)
        sharded = {
            k: jax.device_put(v, NamedSharding(mesh4d, pspecs[k]))
            for k, v in stack.items()
        }
        sx = jax.device_put(batch, NamedSharding(mesh4d, P("dp", "sp", None)))
        _, loss = step(sharded, sx)
        z = forward_stack(stack, batch, cfg)
        want = float(jnp.sum(z.astype(jnp.float32) ** 2))
        assert np.isclose(float(loss), want, rtol=1e-4), (float(loss), want)

    def test_pipeline_train_learns(self, mesh4d, batch):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpu_patterns.models import init_stack_params, make_pipeline_train_step

        cfg = ModelConfig(embed=64, heads=8, head_dim=8)
        stack = init_stack_params(jax.random.key(10), cfg, 2)
        # the 2-stage sum-of-squares objective diverges at 1e-4
        step, pspecs = make_pipeline_train_step(mesh4d, cfg, self.N_MICRO, lr=1e-5)
        p = {
            k: jax.device_put(v, NamedSharding(mesh4d, pspecs[k]))
            for k, v in stack.items()
        }
        sx = jax.device_put(batch, NamedSharding(mesh4d, P("dp", "sp", None)))
        losses = []
        for _ in range(4):
            p, loss = step(p, sx)
            losses.append(float(loss))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]
