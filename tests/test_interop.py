"""Tests for the native C++ FFI interop layer (SURVEY.md C13/C14, §7 step 5)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpu_patterns.interop import native

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native toolchain unavailable: {native.build_error()}",
)


class TestNativeModule:
    def test_direct_clock_monotonic(self):
        a = native.clock_ns()
        b = native.clock_ns()
        assert b >= a > 0

    def test_registration_idempotent(self):
        assert native.register()
        assert native.register()

    def test_timing_layer_uses_native_clock(self):
        from tpu_patterns.core import timing

        timing._NATIVE_CLOCK = False  # reset probe
        assert timing.clock_ns() > 0
        assert timing._native_clock() is native.clock_ns


class TestHighLevelInterop:
    """≙ the typed interop proof (interop_omp_sycl.cpp:51-72)."""

    def test_ffi_clock_inside_program(self):
        from tpu_patterns.interop import ffi_clock_ns

        t = np.asarray(ffi_clock_ns())
        assert t.dtype == np.uint64 and t[0] > 0

    def test_saxpy_eager_and_jit(self):
        from tpu_patterns.interop import ffi_saxpy

        x = jnp.arange(8.0)
        y = jnp.ones(8)
        np.testing.assert_allclose(np.asarray(ffi_saxpy(2.0, x, y)),
                                   2.0 * np.arange(8.0) + 1.0)
        jitted = jax.jit(lambda a, b: ffi_saxpy(3.0, a, b) * 2.0)
        np.testing.assert_allclose(np.asarray(jitted(x, y)),
                                   2.0 * (3.0 * np.arange(8.0) + 1.0))

    def test_checksum_matches_device_invariant(self):
        from tpu_patterns.comm import expected_checksum, fill_randomly
        from tpu_patterns.interop import ffi_checksum

        x = fill_randomly(5_000, "float32", seed=2)
        assert int(ffi_checksum(x)[0]) == expected_checksum(5_000, "float32")

    def test_pallas_output_flows_into_cpp(self):
        # both-runtime pointer proof: a Pallas(interpret) kernel's output is
        # consumed zero-copy by the C++ handler inside one jit program
        from jax.experimental import pallas as pl
        from tpu_patterns.interop import ffi_checksum

        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...] + 1.0

        @jax.jit
        def program(x):
            y = pl.pallas_call(
                kern,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                interpret=True,
            )(x)
            return ffi_checksum(y)

        x = jnp.zeros((4, 128), jnp.float32)
        assert int(program(x)[0]) == 4 * 128


class TestLowLevelInterop:
    """≙ the raw-handle interop proof (interop_omp_ze_sycl.cpp:25-46,92-113)."""

    def test_raw_call_frame_fields(self):
        from tpu_patterns.interop import raw_info

        info = np.asarray(raw_info(jnp.full((16,), 9.0)))
        api_major, api_minor, stage, nargs, dtype, rank, _ptr, first = info
        assert (api_major, api_minor) >= (0, 1)
        assert stage == 3  # XLA_FFI_ExecutionStage_EXECUTE
        assert nargs == 1
        assert dtype == 11  # XLA_FFI_DataType_F32
        assert rank == 1
        assert first == 9  # read through the shared raw pointer


class TestHostOffload:
    """The TPU-platform interop depth (C14): native C++ reached through
    host offload — pure_callback inside the program where the runtime
    supports host send/recv, explicit PJRT staging everywhere."""

    def test_host_callbacks_under_jit(self):
        from tpu_patterns.interop.calls import (
            host_checksum,
            host_saxpy,
            supports_host_callbacks,
        )

        assert supports_host_callbacks()  # CPU runtime always can
        x = jnp.arange(256, dtype=jnp.float32)
        y = jnp.ones(256, jnp.float32)

        @jax.jit
        def program(x, y):
            # native C++ result feeds further compiled compute: the
            # both-directions sharing proof (interop_omp_sycl.cpp:51-72)
            z = host_saxpy(2.0, x, y)
            return z + host_checksum(x).astype(jnp.float32)

        got = np.asarray(program(x, y))
        want = 2 * np.arange(256) + 1 + np.arange(256).sum()
        np.testing.assert_allclose(got, want)

    def test_offload_roundtrip(self):
        from tpu_patterns.interop.calls import offload_checksum, offload_saxpy

        x = jnp.arange(512, dtype=jnp.float32)
        y = jnp.full((512,), 3.0, jnp.float32)
        assert int(offload_checksum(x)[0]) == int(np.arange(512).sum())
        np.testing.assert_allclose(
            np.asarray(offload_saxpy(0.5, x, y)), 0.5 * np.arange(512) + 3.0
        )

    @pytest.mark.tpu
    def test_offload_on_tpu_device(self):
        """TPU-marked: the staged round trip against REAL device buffers.
        Runs when the default backend is a TPU (pytest forces CPU
        in-process, so this is exercised by `python -m tpu_patterns
        interop` / direct runs on hardware)."""
        if jax.default_backend() != "tpu":
            pytest.skip("needs a TPU backend (run outside the CPU conftest)")
        from tpu_patterns.interop.calls import offload_checksum, offload_saxpy

        x = jnp.arange(1024, dtype=jnp.float32)
        y = jnp.ones(1024, jnp.float32)
        out = offload_saxpy(2.0, x, y)
        assert "TPU" in str(next(iter(out.devices())))
        np.testing.assert_allclose(
            np.asarray(out), 2.0 * np.arange(1024) + 1.0
        )
        assert int(offload_checksum(x)[0]) == int(np.arange(1024).sum())
