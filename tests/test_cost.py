"""Resource attribution + decision ledger (obs/cost.py, obs/decisions.py):
the exact-identity contracts (attributed + unattributed == measured wall,
busy + free block-seconds == pool x elapsed), per-request residency across
preempt/resume, the fail-open ``obs.cost_book`` fault site, the
ledger-vs-counter identity per action, and ``obs explain`` reconstructing
a preempted request's story end to end."""

import dataclasses
import json

import pytest

from tpu_patterns import faults, obs
from tpu_patterns.obs.cost import CostBook, cost_table, load_dir, rollup
from tpu_patterns.obs.decisions import (
    ACTIONS,
    COUNTER_IDENTITIES,
    DecisionLedger,
    decision_entries,
    explain_table,
)
from tpu_patterns.serve import ServeEngine

from test_serve import _mixed_reqs, _preempt_engine


@pytest.fixture(autouse=True)
def _isolated(tmp_path):
    faults.configure("")
    obs.flight_recorder().clear()
    obs.metrics_registry().clear()
    obs.configure(str(tmp_path))
    yield
    faults.configure(None)
    obs.flight_recorder().clear()
    obs.metrics_registry().clear()
    obs.configure(None)


ROWS = [(0, "chat", "interactive"), (1, "chat", "bulk"), (2, "chat", "bulk")]


class TestCostBook:
    def test_equal_share_attribution_is_exact_with_remainder(self):
        # 1_000_001 ns over 3 rows does not divide: the first rem rows
        # take the extra ns and the sum closes EXACTLY, by construction
        book = CostBook(pool_blocks=4)
        book.start(0)
        book.book_decode(1_000_001, ROWS)
        got = [book.requests[r].decode_ns for r, _, _ in ROWS]
        assert sum(got) == 1_000_001
        assert max(got) - min(got) <= 1
        snap = book.snapshot()
        assert snap["decode_identity_ok"]
        assert snap["attributed_decode_ns"] == 1_000_001
        assert snap["unattributed_decode_ns"] == 0

    def test_empty_wave_books_unattributed_identity_still_closes(self):
        book = CostBook(pool_blocks=4)
        book.start(0)
        book.book_decode(500, [])
        book.book_prefill(700, [])
        snap = book.snapshot()
        assert snap["unattributed_decode_ns"] == 500
        assert snap["unattributed_prefill_ns"] == 700
        assert snap["decode_identity_ok"] and snap["prefill_identity_ok"]

    def test_pool_conservation_holds_across_every_tick(self):
        book = CostBook(pool_blocks=7)
        book.start(0)
        for alloc in (3, 7, 2, 0, 5):
            book.tick(alloc)
            snap = book.snapshot()
            assert snap["conservation_ok"]
            assert (
                snap["busy_block_ns"] + snap["free_block_ns"]
                == 7 * snap["elapsed_ns"]
            )
        book.close(0)
        assert book.snapshot()["conservation_ok"]

    def test_residency_settles_on_drop_and_preempt_rehold(self):
        book = CostBook(pool_blocks=8)
        book.start(0)
        book.hold(5, 3, scenario="chat", priority="bulk")
        book.drop(5)  # preempt-park: first leg settles
        first_leg = book.requests[5].block_ns
        assert first_leg >= 0
        first_exported = obs.counter(
            "tpu_patterns_cost_block_ns_total", priority="bulk"
        ).value
        assert first_exported == first_leg
        book.hold(5, 3, scenario="chat", priority="bulk")  # resume
        book.drop(5)  # retire
        assert book.requests[5].block_ns >= first_leg
        # the metric got the DELTA on the second drop, not the first
        # leg twice: counter total == per-request total exactly
        assert obs.counter(
            "tpu_patterns_cost_block_ns_total", priority="bulk"
        ).value == book.requests[5].block_ns
        assert not book._holding

    def test_drop_without_hold_is_a_noop(self):
        book = CostBook(pool_blocks=4)
        book.start(0)
        book.drop(99)  # hold skipped by a fault or never admitted
        assert 99 not in book.requests

    def test_snapshot_rollups_group_by_class_and_scenario(self):
        book = CostBook(pool_blocks=4)
        book.start(0)
        book.book_decode(900, ROWS)
        snap = book.snapshot()
        by_cls = snap["by_priority"]
        assert by_cls["interactive"]["requests"] == 1
        assert by_cls["bulk"]["requests"] == 2
        assert (
            by_cls["interactive"]["decode_ns"]
            + by_cls["bulk"]["decode_ns"] == 900
        )
        assert snap["by_scenario"]["chat"]["requests"] == 3
        assert rollup(snap["requests"], "scenario")["chat"][
            "decode_ns"
        ] == 900

    def test_jsonl_roundtrip_and_table_render(self, tmp_path):
        book = CostBook(pool_blocks=4, replica="2")
        book.start(0)
        book.book_decode(1_000_000, ROWS)
        book.book_prefill(600_000, ROWS[:1])
        (tmp_path / "cost.jsonl").write_text(book.to_jsonl())
        metas, reqs = load_dir(str(tmp_path))
        assert len(metas) == 1 and len(reqs) == 3
        assert metas[0]["decode_identity_ok"]
        assert all(r["replica"] == "2" for r in reqs)
        text = cost_table(metas, reqs)
        assert "identities OK" in text
        assert "interactive" in text and "bulk" in text

    def test_table_without_dumps_says_so(self):
        assert "no cost.jsonl" in cost_table([], [])

    def test_booking_fault_fails_open_identities_intact(self):
        # an injected obs.cost_book error skips the WHOLE booking —
        # total and shares move together, so the identity never opens
        book = CostBook(pool_blocks=4)
        book.start(0)
        faults.configure("obs.cost_book:error:count=1")
        book.book_decode(1_000, ROWS)  # skipped (fault fires once)
        book.book_decode(2_000, ROWS)  # lands
        snap = book.snapshot()
        assert snap["decode_wall_ns"] == 2_000
        assert snap["attributed_decode_ns"] == 2_000
        assert snap["decode_identity_ok"]

    def test_hold_fault_fails_open_drop_stays_safe(self):
        book = CostBook(pool_blocks=4)
        book.start(0)
        faults.configure("obs.cost_book:error:count=1")
        book.hold(0, 2, scenario="chat", priority="bulk")  # skipped
        book.drop(0)  # must not raise on the missing holding
        assert not book._holding


class TestDecisionLedger:
    def test_book_counts_and_exports_the_identity_counter(self):
        led = DecisionLedger(replica="1")
        led.book("defer", rid=3, rationale="pool pressure", free=0)
        led.book("evict", count=4, victims="5,6,7,8")
        assert led.count() == 5
        assert led.count("defer") == 1
        assert led.count("evict") == 4
        assert obs.counter(
            "tpu_patterns_decision_events_total", action="defer"
        ).value == 1
        assert obs.counter(
            "tpu_patterns_decision_events_total", action="evict"
        ).value == 4
        assert led.events[0]["inputs"] == {"free": 0}
        assert led.events[0]["replica"] == "1"

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError, match="unknown decision action"):
            DecisionLedger().book("panic")

    def test_every_action_has_a_counter_identity(self):
        assert set(COUNTER_IDENTITIES) == set(ACTIONS)

    def test_booking_fault_drops_record_and_counter_together(self):
        led = DecisionLedger()
        faults.configure("obs.cost_book:error:count=1")
        led.book("shed", rid=1)  # skipped whole
        led.book("shed", rid=2)  # lands
        assert led.count("shed") == 1
        assert obs.counter(
            "tpu_patterns_decision_events_total", action="shed"
        ).value == 1

    def test_events_land_in_the_flight_recorder(self, tmp_path):
        led = DecisionLedger()
        led.book(
            "preempt", rid=7, jid="j-7",
            rationale="bulk victim parked", banked=4,
        )
        path = obs.dump(str(tmp_path / "spans.jsonl"))
        entries = [
            json.loads(ln) for ln in open(path) if ln.strip()
        ]
        ev = [e for e in entries if e.get("name") == "decision.preempt"]
        assert len(ev) == 1
        assert ev[0]["attrs"]["rid"] == "7"
        assert ev[0]["attrs"]["jid"] == "j-7"
        assert ev[0]["attrs"]["banked"] == "4"


class TestExplain:
    def _entries(self):
        led = DecisionLedger()
        led.book("defer", rid=1, rationale="pool pressure", free=0)
        led.book("preempt", rid=2, rationale="bulk victim", banked=3)
        obs.event("serve.preempted", rid="2", priority="bulk")
        obs.event("journey.admit", rid="1")
        return [dict(e) for e in obs.flight_recorder().snapshot()]

    def test_filter_by_rid_includes_story_events(self):
        got = decision_entries(self._entries(), key="2")
        names = [e["name"] for e in got]
        assert "decision.preempt" in names
        assert "serve.preempted" in names
        assert "decision.defer" not in names  # rid 1's story, not 2's

    def test_filter_by_action_is_fleet_wide(self):
        got = decision_entries(self._entries(), action="defer")
        assert [e["name"] for e in got] == ["decision.defer"]

    def test_table_renders_rationale_and_inputs(self):
        text = explain_table(self._entries(), key="2")
        assert "story for 2" in text
        assert "bulk victim" in text
        assert "banked=3" in text

    def test_no_match_says_so(self):
        assert "no decisions" in explain_table([], key=None)

    def test_handoff_decision_tells_the_disagg_story(self):
        # `obs explain <rid>` on a disagg fleet: the booked handoff
        # (src/dst/blocks inputs) and the journey.handoff instant both
        # land in the request's story
        led = DecisionLedger()
        led.book(
            "handoff", rid=3, jid="j-3",
            rationale="prefill complete; KV blocks shipped",
            src="0", dst="2", blocks=2, recompute=False,
        )
        obs.event("journey.handoff", jid="j-3", rid="3", src="0",
                  replica="2")
        entries = [dict(e) for e in obs.flight_recorder().snapshot()]
        got = decision_entries(entries, key="3")
        names = [e["name"] for e in got]
        assert "decision.handoff" in names
        assert "journey.handoff" in names
        text = explain_table(entries, key="3")
        assert "KV blocks shipped" in text
        assert "dst=2" in text


class TestEngineAttribution:
    """The integration contract on a real preempting run: every identity
    closes, the ledger matches the engine's own stats, and the explain
    story reconstructs the preempted request end to end."""

    def test_preempting_run_closes_every_identity(self, devices):
        eng, dec, params = _preempt_engine(devices)
        reqs = _mixed_reqs()
        out = eng.run([dataclasses.replace(r) for r in reqs])
        assert out and not eng.failed
        assert eng.stats["preempted"] >= 1

        snap = eng.cost.snapshot()
        assert snap["decode_identity_ok"]
        assert snap["prefill_identity_ok"]
        assert snap["conservation_ok"]
        assert not eng.cost._holding  # every residency settled
        # every served request got device time attributed, tagged with
        # its class
        assert {r["rid"] for r in snap["requests"]} >= {
            r.rid for r in reqs
        }
        classes = {
            r["rid"]: r["priority"] for r in snap["requests"]
        }
        for r in reqs:
            assert classes[r.rid] == r.priority
        # decode attribution really is the measured wall, split
        assert snap["attributed_decode_ns"] > 0

        # ledger-vs-stats identity: the preempt decisions booked are
        # exactly the preemptions the engine counted
        assert eng.decisions.count("preempt") == eng.stats["preempted"]
        ev = [
            e for e in eng.decisions.events if e["action"] == "preempt"
        ]
        assert all(e["rationale"] for e in ev)
        assert all("free" in e["inputs"] for e in ev)

    def test_explain_reconstructs_a_preempted_request(
        self, devices, tmp_path
    ):
        eng, dec, params = _preempt_engine(devices)
        out = eng.run(
            [dataclasses.replace(r) for r in _mixed_reqs()]
        )
        assert out
        victims = [
            e for e in eng.decisions.events if e["action"] == "preempt"
        ]
        assert victims
        rid = victims[0]["rid"]
        path = obs.dump(str(tmp_path / "spans.jsonl"))
        entries = [
            json.loads(ln) for ln in open(path)
            if ln.strip() and json.loads(ln).get("kind") != "meta"
        ]
        text = explain_table(entries, key=str(rid))
        assert f"story for {rid}" in text
        assert "decision.preempt" in text
        assert "serve.preempted" in text
        # the request retired after the preemption: the story ends well
        assert "req.retired" in text
