"""Test harness: CPU-simulated 8-device mesh.

The reference has no cluster-free distributed story (SURVEY.md §4): its tests
need mpirun + real GPUs.  Here every pattern runs in CI on 8 virtual CPU
devices with real XLA collectives — the config is forced before first backend
use so it also overrides the environment's TPU platform plugin.
"""

import os

os.environ.setdefault("TPU_PATTERNS_TEST_DEVICES", "8")
_N_DEVICES = os.environ["TPU_PATTERNS_TEST_DEVICES"]

# Pin the legacy XLA:CPU runtime for the whole suite.  jaxlib 0.4.3x's
# new thunk runtime intermittently corrupts the glibc heap under this
# suite's load (full 1100+-test runs die ~90% in with "corrupted
# double-linked list" / SIGSEGV inside a compiled donated-pool call;
# MALLOC_PERTURB_ moves the detonation to the first reuse — a native
# use-after-free, not a repo bug: subsets always pass and the failure
# set is identical when the run survives).  The flag must be in place
# before first backend init, same contract as the device count below.
if "--xla_cpu_use_thunk_runtime" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_cpu_use_thunk_runtime=false"
    ).strip()

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")
# EXACTLY ONE device-count mechanism: newer JAX rejects the XLA flag and
# jax_num_cpu_devices set together, older JAX only has the flag.  Both
# work here because the flag is read at first backend init, which has
# not happened yet (jax_platforms above would have raised otherwise).
if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", int(_N_DEVICES))
elif "--xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={_N_DEVICES}"
    ).strip()


def load_root_module(name):
    """Import a repo-root module (bench, __graft_entry__) by path —
    they live outside the package, so the tests that exercise driver
    contracts share this one loader instead of hand-rolling importlib
    boilerplate per file."""
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(root, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: needs a real TPU backend (skipped under the CPU conftest)",
    )
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 `-m 'not slow'` gate "
        "(multi-process end-to-end runs covered by the CI smokes)",
    )


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest must provide >= 8 virtual devices"
    return devs


@pytest.fixture(scope="session")
def mesh1d(devices):
    from jax.sharding import Mesh

    return Mesh(np.array(devices[:8]).reshape(8), ("x",))


@pytest.fixture(scope="session")
def mesh2d(devices):
    from jax.sharding import Mesh

    return Mesh(np.array(devices[:8]).reshape(4, 2), ("x", "y"))
