"""Pipeline (pp) and expert (ep) parallelism vs sequential ground truth.

Same §4 philosophy: the distributed schedule must reproduce the
single-device composition exactly.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_patterns.parallel import moe_apply, pipeline_apply

PP = 8
N_MICRO, B, E = 6, 4, 32


def _stage_fn(w, x):
    # one "layer": a tanh-matmul keeps values bounded and stage-dependent
    return jnp.tanh(x @ w)


@pytest.fixture(scope="module")
def stage_weights():
    return jax.random.normal(jax.random.key(0), (PP, E, E), jnp.float32) * 0.5


@pytest.fixture(scope="module")
def micro():
    return jax.random.normal(jax.random.key(1), (N_MICRO, B, E), jnp.float32)


def test_pipeline_matches_sequential(mesh1d, stage_weights, micro):
    fn = jax.jit(
        jax.shard_map(
            functools.partial(
                pipeline_apply,
                lambda w, x: _stage_fn(w[0], x),  # shard is [1, E, E]
                axis_name="x",
                axis_size=PP,
            ),
            mesh=mesh1d,
            in_specs=(P("x", None, None), P()),
            out_specs=P(),
        )
    )
    # shard_map positional order: (stage_params, micro)
    w = jax.device_put(stage_weights, NamedSharding(mesh1d, P("x", None, None)))
    got = fn(w, micro)

    want = micro
    for s in range(PP):
        want = jax.vmap(lambda m: _stage_fn(stage_weights[s], m))(want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pipeline_single_stage(stage_weights, micro):
    """pp=1 degenerates to a plain per-microbatch map."""
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
    fn = jax.jit(
        jax.shard_map(
            functools.partial(
                pipeline_apply,
                lambda w, x: _stage_fn(w[0], x),
                axis_name="x",
                axis_size=1,
            ),
            mesh=mesh,
            in_specs=(P("x", None, None), P()),
            out_specs=P(),
        )
    )
    w0 = stage_weights[:1]
    got = fn(w0, micro)
    want = jax.vmap(lambda m: _stage_fn(stage_weights[0], m))(micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_top1_route_counts_in_int32():
    """Slot counting must not happen in the token dtype: bf16 cumsum
    saturates at 256 and would silently collide dispatch slots."""
    from tpu_patterns.parallel import top1_route

    x = jnp.ones((300, 8), jnp.bfloat16)
    wg = jnp.zeros((8, 4), jnp.bfloat16).at[0, 0].set(100.0)
    onehot, weight = top1_route(x, wg)
    assert onehot.dtype == jnp.int32
    pos = jnp.cumsum(onehot, axis=0) - onehot
    slots = np.asarray(jnp.sum(pos * onehot, axis=-1))
    assert len(np.unique(slots)) == 300  # distinct beyond bf16's 256 limit


class TestMoE:
    EP = 8
    T = 16  # tokens per rank

    def _setup(self):
        e = E
        k1, k2, k3 = jax.random.split(jax.random.key(2), 3)
        # experts: one [E, E] matrix per ep rank
        we = jax.random.normal(k1, (self.EP, e, e), jnp.float32) * 0.3
        wg = jax.random.normal(k2, (e, self.EP), jnp.float32)
        x = jax.random.normal(k3, (self.EP * self.T, e), jnp.float32)
        return we, wg, x

    @staticmethod
    def _expert(w, x):
        return jnp.tanh(x @ w)

    def test_moe_matches_dense_routing(self, mesh1d):
        we, wg, x = self._setup()
        fn = jax.jit(
            jax.shard_map(
                functools.partial(
                    moe_apply,
                    lambda w, x: self._expert(w[0], x),  # shard is [1, E, E]
                    axis_name="x",
                    axis_size=self.EP,
                ),
                mesh=mesh1d,
                in_specs=(P("x", None, None), P(), P("x", None)),
                out_specs=P("x", None),
            )
        )
        sw = jax.device_put(we, NamedSharding(mesh1d, P("x", None, None)))
        sx = jax.device_put(x, NamedSharding(mesh1d, P("x", None)))
        got = np.asarray(fn(sw, wg, sx))

        # dense reference: every token through its argmax expert
        gates = jax.nn.softmax(x @ wg, axis=-1)
        idx = np.asarray(jnp.argmax(gates, axis=-1))
        weight = np.asarray(jnp.max(gates, axis=-1))
        want = np.stack(
            [
                weight[t] * np.asarray(self._expert(we[idx[t]], x[t]))
                for t in range(x.shape[0])
            ]
        )
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_moe_all_tokens_one_expert(self, mesh1d):
        """Capacity = T must absorb the worst-case route (everyone to
        expert 0) without dropping tokens."""
        we, _, x = self._setup()
        # gate forced: huge bias toward expert 0
        wg = jnp.zeros((E, self.EP)).at[0, 0].set(100.0)
        x = x.at[:, 0].set(1.0)
        fn = jax.jit(
            jax.shard_map(
                functools.partial(
                    moe_apply,
                    lambda w, x: self._expert(w[0], x),  # shard is [1, E, E]
                    axis_name="x",
                    axis_size=self.EP,
                ),
                mesh=mesh1d,
                in_specs=(P("x", None, None), P(), P("x", None)),
                out_specs=P("x", None),
            )
        )
        sw = jax.device_put(we, NamedSharding(mesh1d, P("x", None, None)))
        sx = jax.device_put(x, NamedSharding(mesh1d, P("x", None)))
        got = np.asarray(fn(sw, wg, sx))
        gates = jax.nn.softmax(x @ wg, axis=-1)
        weight = np.asarray(jnp.max(gates, axis=-1))
        want = np.asarray(self._expert(we[0], x)) * weight[:, None]
        np.testing.assert_allclose(got, want, atol=1e-5)


class TestMoECapacity:
    """Capacity-factor regimes (C = ceil(cf*T/E)): exact when cf is
    generous, deterministic overflow drops when it binds."""

    def _setup(self, tokens=32, dim=16, ep=4, seed=2):
        keys = jax.random.split(jax.random.key(seed), 3)
        we = jax.random.normal(keys[0], (ep, dim, dim), jnp.float32) * 0.3
        wg = jax.random.normal(keys[1], (dim, ep), jnp.float32)
        xs = jax.random.normal(keys[2], (tokens, dim), jnp.float32)
        return we, wg, xs

    def _run(self, cf, we, wg, xs):
        import functools

        from tpu_patterns.parallel.moe import moe_apply

        ep = we.shape[0]
        mesh = Mesh(np.array(jax.devices()[:ep]), ("x",))
        fn = jax.jit(
            jax.shard_map(
                functools.partial(
                    moe_apply,
                    lambda w, a: jnp.tanh(a @ w[0]),
                    axis_name="x",
                    axis_size=ep,
                    capacity_factor=cf,
                ),
                mesh=mesh,
                in_specs=(P("x", None, None), P(), P("x", None)),
                out_specs=P("x", None),
            )
        )
        return np.asarray(
            fn(
                jax.device_put(we, NamedSharding(mesh, P("x", None, None))),
                wg,
                jax.device_put(xs, NamedSharding(mesh, P("x", None))),
            )
        )

    def _dense_want(self, we, wg, xs, cap):
        """Shared host replay (moe.host_reference): routing at device
        precision, slot counting + tanh expert in f32."""
        from tpu_patterns.parallel.moe import host_reference

        return host_reference(we, wg, xs, we.shape[0], cap)

    def test_generous_capacity_is_exact(self, mesh1d):
        from tpu_patterns.parallel.moe import capacity

        ep, tokens = 4, 32
        we, wg, xs = self._setup(tokens * ep)
        cf = float(ep)  # C = T: nothing can drop
        assert capacity(tokens, ep, cf) == tokens
        got = self._run(cf, we, wg, xs)
        want, dropped = self._dense_want(we, wg, xs, tokens)
        assert dropped == 0
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_binding_capacity_drops_deterministically(self, mesh1d):
        from tpu_patterns.parallel.moe import capacity

        ep, tokens = 4, 32
        we, wg, xs = self._setup(tokens * ep)
        cap = capacity(tokens, ep, 0.5)  # C = ceil(0.5*32/4) = 4
        assert cap == 4
        got = self._run(0.5, we, wg, xs)
        want, dropped = self._dense_want(we, wg, xs, cap)
        assert dropped > 0, "test must exercise the dropping regime"
        np.testing.assert_allclose(got, want, atol=1e-5)
        # dropped tokens are exactly zero rows
        zero_rows = np.where(np.all(want == 0, axis=1))[0]
        assert np.all(got[zero_rows] == 0)

    def test_dispatch_stats_match_host_replay(self):
        from tpu_patterns.parallel.moe import dispatch_stats, top1_route

        we, wg, xs = self._setup(64)
        onehot, _ = top1_route(xs, wg)
        n_dropped, per_expert = dispatch_stats(onehot, 8)
        idx = np.asarray(jnp.argmax(xs @ wg, axis=-1))
        counts = {}
        kept = np.zeros(wg.shape[-1], np.int32)
        drops = 0
        for e in idx:
            c = counts.get(int(e), 0)
            counts[int(e)] = c + 1
            if c < 8:
                kept[int(e)] += 1
            else:
                drops += 1
        assert int(n_dropped) == drops
        np.testing.assert_array_equal(np.asarray(per_expert), kept)

    def test_flagship_moe_capacity_factor(self, mesh1d):
        """ModelConfig.capacity_factor threads through the flagship MoE
        FFN: a binding factor changes the output (drops) while a generous
        one reproduces the exact path."""
        from tpu_patterns.models import ModelConfig, forward_shard, init_params

        cfg_exact = ModelConfig(embed=32, heads=4, head_dim=8, moe=True)
        cfg_loose = ModelConfig(
            embed=32, heads=4, head_dim=8, moe=True, capacity_factor=8.0
        )
        cfg_tight = ModelConfig(
            embed=32, heads=4, head_dim=8, moe=True, capacity_factor=0.25
        )
        params = init_params(jax.random.key(0), cfg_exact, n_experts=4)
        x = jax.random.normal(jax.random.key(1), (2, 16, 32), jnp.float32)
        out_exact = np.asarray(forward_shard(params, x, cfg_exact))
        out_loose = np.asarray(forward_shard(params, x, cfg_loose))
        out_tight = np.asarray(forward_shard(params, x, cfg_tight))
        np.testing.assert_allclose(out_exact, out_loose, atol=1e-6)
        assert not np.allclose(out_exact, out_tight, atol=1e-6)
