"""Pipeline (pp) and expert (ep) parallelism vs sequential ground truth.

Same §4 philosophy: the distributed schedule must reproduce the
single-device composition exactly.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_patterns.parallel import moe_apply, pipeline_apply

PP = 8
N_MICRO, B, E = 6, 4, 32


def _stage_fn(w, x):
    # one "layer": a tanh-matmul keeps values bounded and stage-dependent
    return jnp.tanh(x @ w)


@pytest.fixture(scope="module")
def stage_weights():
    return jax.random.normal(jax.random.key(0), (PP, E, E), jnp.float32) * 0.5


@pytest.fixture(scope="module")
def micro():
    return jax.random.normal(jax.random.key(1), (N_MICRO, B, E), jnp.float32)


def test_pipeline_matches_sequential(mesh1d, stage_weights, micro):
    fn = jax.jit(
        jax.shard_map(
            functools.partial(
                pipeline_apply,
                lambda w, x: _stage_fn(w[0], x),  # shard is [1, E, E]
                axis_name="x",
                axis_size=PP,
            ),
            mesh=mesh1d,
            in_specs=(P("x", None, None), P()),
            out_specs=P(),
        )
    )
    # shard_map positional order: (stage_params, micro)
    w = jax.device_put(stage_weights, NamedSharding(mesh1d, P("x", None, None)))
    got = fn(w, micro)

    want = micro
    for s in range(PP):
        want = jax.vmap(lambda m: _stage_fn(stage_weights[s], m))(want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pipeline_single_stage(stage_weights, micro):
    """pp=1 degenerates to a plain per-microbatch map."""
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
    fn = jax.jit(
        jax.shard_map(
            functools.partial(
                pipeline_apply,
                lambda w, x: _stage_fn(w[0], x),
                axis_name="x",
                axis_size=1,
            ),
            mesh=mesh,
            in_specs=(P("x", None, None), P()),
            out_specs=P(),
        )
    )
    w0 = stage_weights[:1]
    got = fn(w0, micro)
    want = jax.vmap(lambda m: _stage_fn(stage_weights[0], m))(micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_top1_route_counts_in_int32():
    """Slot counting must not happen in the token dtype: bf16 cumsum
    saturates at 256 and would silently collide dispatch slots."""
    from tpu_patterns.parallel import top1_route

    x = jnp.ones((300, 8), jnp.bfloat16)
    wg = jnp.zeros((8, 4), jnp.bfloat16).at[0, 0].set(100.0)
    onehot, weight = top1_route(x, wg)
    assert onehot.dtype == jnp.int32
    pos = jnp.cumsum(onehot, axis=0) - onehot
    slots = np.asarray(jnp.sum(pos * onehot, axis=-1))
    assert len(np.unique(slots)) == 300  # distinct beyond bf16's 256 limit


class TestMoE:
    EP = 8
    T = 16  # tokens per rank

    def _setup(self):
        e = E
        k1, k2, k3 = jax.random.split(jax.random.key(2), 3)
        # experts: one [E, E] matrix per ep rank
        we = jax.random.normal(k1, (self.EP, e, e), jnp.float32) * 0.3
        wg = jax.random.normal(k2, (e, self.EP), jnp.float32)
        x = jax.random.normal(k3, (self.EP * self.T, e), jnp.float32)
        return we, wg, x

    @staticmethod
    def _expert(w, x):
        return jnp.tanh(x @ w)

    def test_moe_matches_dense_routing(self, mesh1d):
        we, wg, x = self._setup()
        fn = jax.jit(
            jax.shard_map(
                functools.partial(
                    moe_apply,
                    lambda w, x: self._expert(w[0], x),  # shard is [1, E, E]
                    axis_name="x",
                    axis_size=self.EP,
                ),
                mesh=mesh1d,
                in_specs=(P("x", None, None), P(), P("x", None)),
                out_specs=P("x", None),
            )
        )
        sw = jax.device_put(we, NamedSharding(mesh1d, P("x", None, None)))
        sx = jax.device_put(x, NamedSharding(mesh1d, P("x", None)))
        got = np.asarray(fn(sw, wg, sx))

        # dense reference: every token through its argmax expert
        gates = jax.nn.softmax(x @ wg, axis=-1)
        idx = np.asarray(jnp.argmax(gates, axis=-1))
        weight = np.asarray(jnp.max(gates, axis=-1))
        want = np.stack(
            [
                weight[t] * np.asarray(self._expert(we[idx[t]], x[t]))
                for t in range(x.shape[0])
            ]
        )
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_moe_all_tokens_one_expert(self, mesh1d):
        """Capacity = T must absorb the worst-case route (everyone to
        expert 0) without dropping tokens."""
        we, _, x = self._setup()
        # gate forced: huge bias toward expert 0
        wg = jnp.zeros((E, self.EP)).at[0, 0].set(100.0)
        x = x.at[:, 0].set(1.0)
        fn = jax.jit(
            jax.shard_map(
                functools.partial(
                    moe_apply,
                    lambda w, x: self._expert(w[0], x),  # shard is [1, E, E]
                    axis_name="x",
                    axis_size=self.EP,
                ),
                mesh=mesh1d,
                in_specs=(P("x", None, None), P(), P("x", None)),
                out_specs=P("x", None),
            )
        )
        sw = jax.device_put(we, NamedSharding(mesh1d, P("x", None, None)))
        sx = jax.device_put(x, NamedSharding(mesh1d, P("x", None)))
        got = np.asarray(fn(sw, wg, sx))
        gates = jax.nn.softmax(x @ wg, axis=-1)
        weight = np.asarray(jnp.max(gates, axis=-1))
        want = np.asarray(self._expert(we[0], x)) * weight[:, None]
        np.testing.assert_allclose(got, want, atol=1e-5)
