"""Autoregressive decode with the sequence-parallel KV cache
(models/decode.py): teacher-forcing equivalence, layout math, rollout."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_patterns.models.decode import (
    DecodeConfig,
    _CacheLayout,
    _ragged_gate,
    _stacked_params,
    _stacked_specs,
    _teacher_forcing_gate,
    make_decoder,
    run_decode,
)
from tpu_patterns.models.transformer import ModelConfig

CFG = dict(embed=64, heads=8, head_dim=8)


class TestCacheLayout:
    def test_positions_cover_every_slot_once(self):
        # union of all ranks' closed-form positions == [0, prefill+gen)
        lay = _CacheLayout(prefill=16, gen_cap=8, sp=4)
        seen = []
        for r in range(4):
            prompt = [r * lay.lp_loc + i for i in range(lay.lp_loc)]
            gen = [16 + r * lay.lg_loc + i for i in range(lay.lg_loc)]
            seen += prompt + gen
        assert sorted(seen) == list(range(24))

    def test_write_offset_owns_each_position_once(self):
        lay = _CacheLayout(prefill=16, gen_cap=8, sp=4)
        for t in range(16, 24):
            owners = []
            for r in range(4):
                rel = t - 16 - r * lay.lg_loc
                if 0 <= rel < lay.lg_loc:
                    owners.append((r, lay.lp_loc + rel))
            assert len(owners) == 1, t

    def test_indivisible_rejected(self):
        with pytest.raises(ValueError, match="divide over sp"):
            _CacheLayout(prefill=15, gen_cap=8, sp=4)
        with pytest.raises(ValueError, match="divide over sp"):
            _CacheLayout(prefill=16, gen_cap=7, sp=4)
        with pytest.raises(ValueError, match="layout"):
            _CacheLayout(prefill=16, gen_cap=8, sp=4, layout="diagonal")

    def test_striped_positions_cover_every_slot_once(self):
        # striped: rank r's prompt slot i holds r + i*sp; gen index n
        # lands on rank n % sp — union over ranks covers [0, 24) once
        lay = _CacheLayout(prefill=16, gen_cap=8, sp=4, layout="striped")
        seen = []
        for r in range(4):
            prompt = [r + i * 4 for i in range(lay.lp_loc)]
            gen = [16 + (r + j * 4) for j in range(lay.lg_loc)]
            seen += prompt + gen
        assert sorted(seen) == list(range(24))

    def test_striped_write_offset_owns_each_gen_index_once(self):
        lay = _CacheLayout(prefill=16, gen_cap=8, sp=4, layout="striped")
        for n in range(8):
            owners = []
            for r in range(4):
                if n % 4 == r and n // 4 < lay.lg_loc:
                    owners.append((r, lay.lp_loc + n // 4))
            assert len(owners) == 1, n

    def test_striped_prompt_local_slot_inverts_positions(self):
        # prompt_local_slot is the inverse of prompt_positions: every
        # global position is owned by exactly one (rank, slot), and that
        # slot's position maps back
        lay = _CacheLayout(prefill=16, gen_cap=8, sp=4, layout="striped")
        for pos in range(16):
            owners = [
                (r, pos // 4)
                for r in range(4)
                if pos % 4 == r and pos // 4 < lay.lp_loc
            ]
            assert len(owners) == 1, pos
            r, slot = owners[0]
            assert r + slot * 4 == pos


class TestTeacherForcing:
    @pytest.mark.parametrize(
        "shape,depth",
        [
            ((2, 2, 2), 2),
            ((1, 4, 1), 1),
            ((1, 1, 2), 2),
            ((1, 1, 1), 1),
            ((4, 2, 1), 1),  # dp > 2: probe batch must scale with dp
        ],
    )
    def test_decode_matches_training_forward(self, devices, shape, depth):
        # the KV-cache invariant: cache-path outputs == full causal
        # forward at every position, across sp/tp layouts
        n = int(np.prod(shape))
        mesh = Mesh(np.array(devices[:n]).reshape(shape), ("dp", "sp", "tp"))
        assert _teacher_forcing_gate(mesh, ModelConfig(**CFG, depth=depth))


@pytest.fixture(scope="module")
def mesh3d(devices):
    return Mesh(np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))


class TestStripedDecode:
    """The striped layout x feature matrix (VERDICT r2 #4): a
    striped-trained model generates over its own token placement."""

    @pytest.mark.parametrize(
        "shape,kv,int8,rope",
        [
            ((2, 2, 2), 0, False, False),  # MHA
            ((1, 4, 1), 0, False, False),  # sp-only mesh
            ((2, 2, 2), 4, False, True),  # GQA + rope (striped positions)
            ((1, 4, 1), 2, False, True),
            ((2, 2, 2), 0, True, False),  # int8 cache
            ((1, 4, 1), 0, True, False),
        ],
    )
    def test_striped_decode_matches_training_forward(
        self, devices, shape, kv, int8, rope
    ):
        n = int(np.prod(shape))
        mesh = Mesh(np.array(devices[:n]).reshape(shape), ("dp", "sp", "tp"))
        cfg = ModelConfig(
            **CFG, depth=2, attn_layout="striped", kv_heads=kv, rope=rope
        )
        assert _teacher_forcing_gate(mesh, cfg, cache_int8=int8)


class TestMoEDecode:
    """ep-aware decode (VERDICT r2 #4): generation routes through the
    SAME top-1 experts as training, experts one per tp rank."""

    @pytest.mark.parametrize(
        "shape,layout",
        [
            ((2, 2, 2), "contiguous"),
            ((1, 2, 4), "contiguous"),  # 4 experts
            ((1, 1, 1), "contiguous"),  # single device runs every expert
            ((2, 2, 2), "striped"),  # moe x striped compose
        ],
    )
    def test_moe_decode_matches_training_forward(self, devices, shape, layout):
        n = int(np.prod(shape))
        mesh = Mesh(np.array(devices[:n]).reshape(shape), ("dp", "sp", "tp"))
        cfg = ModelConfig(**CFG, depth=2, moe=True, attn_layout=layout)
        assert _teacher_forcing_gate(mesh, cfg)


class TestGQA:
    @pytest.mark.parametrize("shape", [(2, 2, 2), (1, 4, 1)])
    def test_gqa_decode_matches_training_forward(self, devices, shape):
        # the KV-cache invariant under grouped K/V heads (cache at Hkv)
        n = int(np.prod(shape))
        mesh = Mesh(np.array(devices[:n]).reshape(shape), ("dp", "sp", "tp"))
        assert _teacher_forcing_gate(
            mesh, ModelConfig(**CFG, depth=2, kv_heads=2)
        )

    def test_gqa_equals_mha_when_groups_degenerate(self):
        # kv_heads == heads with wkv == the wqkv k/v slices must produce
        # the SAME forward as the fused MHA layout
        from tpu_patterns.models.transformer import (
            forward_shard,
            init_params,
        )

        mha = ModelConfig(**CFG)
        gqa = ModelConfig(**CFG, kv_heads=CFG["heads"])
        p = init_params(jax.random.key(0), mha)
        pg = {
            "wq": p["wqkv"][0],
            "wkv": p["wqkv"][1:],
            "wo": p["wo"],
            "w1": p["w1"],
            "w2": p["w2"],
        }
        x = jax.random.normal(jax.random.key(1), (2, 16, mha.embed))
        np.testing.assert_allclose(
            np.asarray(forward_shard(pg, x, gqa)),
            np.asarray(forward_shard(p, x, mha)),
            rtol=0,
            atol=1e-6,
        )

    def test_cache_shrinks_by_group_factor(self, devices):
        mesh = Mesh(np.array(devices[:4]).reshape(1, 2, 2), ("dp", "sp", "tp"))
        b, lp, gen = 2, 8, 4
        sizes = {}
        for kv in (0, 2):
            cfg = ModelConfig(**CFG, dtype="float32", kv_heads=kv)
            prefill, _ = make_decoder(mesh, cfg, b, lp, gen)
            params = jax.device_put(
                _stacked_params(jax.random.key(0), cfg),
                {k: NamedSharding(mesh, s)
                 for k, s in _stacked_specs(cfg).items()},
            )
            x = jax.device_put(
                jax.random.normal(jax.random.key(1), (b, lp, cfg.embed)),
                NamedSharding(mesh, P("dp", "sp", None)),
            )
            caches, _ = prefill(params, x)
            sizes[kv] = caches["k"].size
        assert sizes[2] * 4 == sizes[0]  # 8 heads -> 2 kv heads

    def test_indivisible_kv_heads_fail_fast(self, devices):
        # training factories must raise the clear error, not XLA's
        from tpu_patterns.models.transformer import make_train_step

        mesh = Mesh(
            np.array(devices[:4]).reshape(1, 1, 4), ("dp", "sp", "tp")
        )
        with pytest.raises(ValueError, match="divide over tp"):
            make_train_step(mesh, ModelConfig(**CFG, kv_heads=2))
        with pytest.raises(ValueError, match="divide over tp"):
            make_decoder(mesh, ModelConfig(**CFG, kv_heads=2), 2, 8, 4)

    def test_gqa_training_step_runs(self, devices):
        from tpu_patterns.models.transformer import (
            init_params,
            make_train_step,
            shard_params,
        )

        mesh = Mesh(np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))
        cfg = ModelConfig(**CFG, kv_heads=4)
        step, _ = make_train_step(mesh, cfg, lr=1e-3)
        params = shard_params(
            init_params(jax.random.key(0), cfg), mesh, cfg
        )
        x = jax.device_put(
            jax.random.normal(jax.random.key(1), (4, 32, cfg.embed)),
            NamedSharding(mesh, P("dp", "sp", None)),
        )
        new, loss = step(params, x)
        assert np.isfinite(float(loss))
        # the grouped projections receive gradient
        assert not np.allclose(
            np.asarray(new["wkv"]), np.asarray(params["wkv"])
        )


class TestRope:
    def test_rotation_preserves_norm_and_is_relative(self):
        # rope is a rotation (norm-preserving), and rotated dot products
        # depend only on the position DIFFERENCE (the relative property)
        from tpu_patterns.models.transformer import apply_rope, rope_tables

        d = 16
        q = jax.random.normal(jax.random.key(0), (1, 1, 2, d))
        k = jax.random.normal(jax.random.key(1), (1, 1, 2, d))

        def rotated_dot(i, j):
            ci, si = rope_tables(
                jnp.array([i]), d, 10000.0, jnp.float32
            )
            cj, sj = rope_tables(
                jnp.array([j]), d, 10000.0, jnp.float32
            )
            qi = apply_rope(q, ci, si)
            kj = apply_rope(k, cj, sj)
            return float(jnp.sum(qi * kj)), float(jnp.sum(qi * qi))

        d57, nq = rotated_dot(5, 7)
        d810, nq2 = rotated_dot(8, 10)
        assert np.isclose(d57, d810, rtol=1e-5)  # same offset 2
        assert np.isclose(nq, float(jnp.sum(q * q)), rtol=1e-5)
        d59, _ = rotated_dot(5, 9)
        assert not np.isclose(d57, d59, rtol=1e-3)  # offset matters

    @pytest.mark.parametrize("layout", ["contiguous", "striped"])
    def test_sp_rope_loss_matches_single_device(self, devices, layout):
        # the position test the sp layouts cannot fake: with rope ON, a
        # wrong per-shard offset changes the objective
        from tpu_patterns.models.transformer import (
            forward_shard,
            init_params,
            make_train_step,
            shard_params,
        )

        mesh = Mesh(
            np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp")
        )
        cfg = ModelConfig(**CFG, rope=True, attn_layout=layout)
        params = init_params(jax.random.key(0), cfg)
        x = jax.random.normal(jax.random.key(1), (4, 32, cfg.embed))
        step, _ = make_train_step(mesh, cfg, lr=0.0)
        sx_full = x
        if layout == "striped":
            sp = 2
            sx_full = jnp.concatenate(
                [x[:, r::sp] for r in range(sp)], axis=1
            )
        sx = jax.device_put(
            sx_full, NamedSharding(mesh, P("dp", "sp", None))
        )
        _, loss = step(shard_params(params, mesh, cfg), sx)
        z = forward_shard(params, x, dataclasses.replace(
            cfg, attn_layout="contiguous"
        ))
        want = float(jnp.sum(z.astype(jnp.float32) ** 2))
        assert np.isclose(float(loss), want, rtol=1e-4)

    def test_rope_changes_the_forward(self):
        from tpu_patterns.models.transformer import (
            forward_shard,
            init_params,
        )

        plain = ModelConfig(**CFG)
        roped = ModelConfig(**CFG, rope=True)
        p = init_params(jax.random.key(0), plain)
        x = jax.random.normal(jax.random.key(1), (2, 16, plain.embed))
        a = np.asarray(forward_shard(p, x, plain))
        b = np.asarray(forward_shard(p, x, roped))
        assert not np.allclose(a, b, atol=1e-3)

    @pytest.mark.parametrize("kv", [0, 2])
    def test_rope_decode_matches_training_forward(self, devices, kv):
        mesh = Mesh(
            np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp")
        )
        assert _teacher_forcing_gate(
            mesh, ModelConfig(**CFG, depth=2, rope=True, kv_heads=kv)
        )


class TestRollout:
    def test_self_feeding_rollout_is_deterministic(self, mesh3d):
        cfg = ModelConfig(**CFG, dtype="float32", causal=True, depth=2)
        b, lp, gen = 2, 8, 4
        prefill, generate = make_decoder(mesh3d, cfg, b, lp, gen)
        params = jax.device_put(
            _stacked_params(jax.random.key(0), cfg),
            {k: NamedSharding(mesh3d, s)
             for k, s in _stacked_specs(cfg).items()},
        )
        x = jax.device_put(
            jax.random.normal(jax.random.key(1), (b, lp, cfg.embed)),
            NamedSharding(mesh3d, P("dp", "sp", None)),
        )
        caches, y0 = prefill(params, x)
        t0 = jnp.asarray(lp, jnp.int32)
        _, ys1 = generate(params, caches, y0, t0, gen)
        _, ys2 = generate(params, caches, y0, t0, gen)
        assert ys1.shape == (b, gen, cfg.embed)
        np.testing.assert_array_equal(np.asarray(ys1), np.asarray(ys2))
        assert np.isfinite(np.asarray(ys1)).all()

    def test_chunked_generation_matches_one_shot(self, mesh3d):
        # generating 4 then 4 (cache threaded through) == generating 8
        cfg = ModelConfig(**CFG, dtype="float32", causal=True, depth=1)
        b, lp = 2, 8
        prefill, generate = make_decoder(mesh3d, cfg, b, lp, 8)
        params = jax.device_put(
            _stacked_params(jax.random.key(2), cfg),
            {k: NamedSharding(mesh3d, s)
             for k, s in _stacked_specs(cfg).items()},
        )
        x = jax.device_put(
            jax.random.normal(jax.random.key(3), (b, lp, cfg.embed)),
            NamedSharding(mesh3d, P("dp", "sp", None)),
        )
        caches, y0 = prefill(params, x)
        t0 = jnp.asarray(lp, jnp.int32)
        _, ys_once = generate(params, caches, y0, t0, 8)
        c, ys_a = generate(params, caches, y0, t0, 4)
        _, ys_b = generate(
            params, c, ys_a[:, -1:, :], t0 + 4, 4
        )
        got = np.concatenate([np.asarray(ys_a), np.asarray(ys_b)], axis=1)
        np.testing.assert_allclose(
            got, np.asarray(ys_once), rtol=0, atol=1e-6
        )

    def test_donated_cache_updates_in_place(self, mesh3d):
        """donate=True: generate consumes the KV cache (no whole-cache
        copy per call), the buffers really alias (the consumed input is
        deleted, not copied), and the tokens match the copying decoder's
        bit for bit."""
        cfg = ModelConfig(**CFG, dtype="float32", causal=True, depth=1)
        b, lp, gen = 2, 8, 4
        prefill, generate = make_decoder(mesh3d, cfg, b, lp, gen)
        dprefill, dgenerate = make_decoder(
            mesh3d, cfg, b, lp, gen, donate=True
        )
        params = jax.device_put(
            _stacked_params(jax.random.key(2), cfg),
            {k: NamedSharding(mesh3d, s)
             for k, s in _stacked_specs(cfg).items()},
        )
        x = jax.device_put(
            jax.random.normal(jax.random.key(3), (b, lp, cfg.embed)),
            NamedSharding(mesh3d, P("dp", "sp", None)),
        )
        t0 = jnp.asarray(lp, jnp.int32)
        caches, y0 = prefill(params, x)
        _, ys_ref = generate(params, caches, y0, t0, gen)
        dcaches, dy0 = dprefill(params, x)
        c2, ys_don = dgenerate(params, dcaches, dy0, t0, gen)
        np.testing.assert_array_equal(np.asarray(ys_ref), np.asarray(ys_don))
        # the input cache is consumed — the scatter went in place
        assert all(v.is_deleted() for v in dcaches.values())
        # the returned cache is the live continuation
        _, ys_more = dgenerate(params, c2, ys_don[:, -1:, :], t0 + gen, gen)
        assert np.isfinite(np.asarray(ys_more)).all()


class TestInt8Cache:
    def test_quantize_roundtrip_error_bounded(self):
        from tpu_patterns.models.decode import _quantize_kv

        x = jax.random.normal(jax.random.key(0), (2, 4, 16, 32))
        q, s = _quantize_kv(x)
        assert q.dtype == jnp.int8 and s.shape == (2, 4, 16)
        deq = np.asarray(q, np.float32) * np.asarray(s)[..., None]
        err = np.abs(deq - np.asarray(x))
        bound = np.asarray(s)[..., None] * 0.5 + 1e-7
        assert (err <= bound).all()

    @pytest.mark.parametrize("kv,rope", [(0, False), (2, True)])
    def test_int8_gate_passes_and_float_tolerance_fails_nothing(
        self, devices, kv, rope
    ):
        # the quantized cache path must stay within the quantization
        # error bound of the training forward, across sp/tp and with
        # GQA + rope composed in
        mesh = Mesh(
            np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp")
        )
        cfg = ModelConfig(**CFG, depth=2, kv_heads=kv, rope=rope)
        assert _teacher_forcing_gate(mesh, cfg, cache_int8=True)

    def test_int8_cache_dtype_and_scales_present(self, devices):
        mesh = Mesh(
            np.array(devices[:4]).reshape(1, 2, 2), ("dp", "sp", "tp")
        )
        cfg = ModelConfig(**CFG, dtype="float32")
        b, lp, gen = 2, 8, 4
        prefill, generate = make_decoder(
            mesh, cfg, b, lp, gen, cache_int8=True
        )
        params = jax.device_put(
            _stacked_params(jax.random.key(0), cfg),
            {k: NamedSharding(mesh, s)
             for k, s in _stacked_specs(cfg).items()},
        )
        x = jax.device_put(
            jax.random.normal(jax.random.key(1), (b, lp, cfg.embed)),
            NamedSharding(mesh, P("dp", "sp", None)),
        )
        caches, y0 = prefill(params, x)
        assert caches["k"].dtype == jnp.int8
        assert caches["ks"].dtype == jnp.float32
        # int8 k/v + f32 scales: byte footprint ~ (1 + 4/D) per element
        kv_bytes = caches["k"].size + caches["ks"].size * 4
        float_bytes = caches["k"].size * 4
        assert kv_bytes < float_bytes / 2
        _, ys = generate(params, caches, y0, jnp.asarray(lp), gen)
        assert np.isfinite(np.asarray(ys)).all()


class TestRagged:
    @pytest.mark.parametrize(
        "rope,layout",
        [(False, "contiguous"), (True, "contiguous"), (True, "striped")],
    )
    def test_ragged_decode_matches_per_row_forward(
        self, devices, rope, layout
    ):
        # rows with DIFFERENT prompt lengths (right-padded): teacher-
        # forced decode of row b at gen step n must equal the plain
        # causal forward of that row's own unpadded sequence at position
        # lens[b] + n.  rope=True makes positions load-bearing; the
        # striped case additionally proves ragged masks/gathers against
        # the striped slot placement (rows' valid tokens scatter across
        # ranks instead of filling them in order).  One implementation
        # of the invariant: the same _ragged_gate the multichip dryrun
        # runs at its primary factorization.
        mesh = Mesh(
            np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp")
        )
        assert _ragged_gate(
            mesh, ModelConfig(depth=1, rope=rope, attn_layout=layout)
        )

    @pytest.mark.parametrize("layout", ["contiguous", "striped"])
    def test_ragged_edges_full_and_min_length_rows(self, devices, layout):
        # the boundary lengths a spread of "interior" lens never hits:
        # lens == prefill_len (the last valid slot is the FINAL prompt
        # slot, owned only by the last rank under contiguous and by rank
        # (lp-1) % sp under striped) and lens == 1 (the first slot, rank
        # 0's alone) — _gather_last_valid and the ragged decode masks
        # must be exact at both extremes, under both layouts
        mesh = Mesh(
            np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp")
        )
        assert _ragged_gate(
            mesh,
            ModelConfig(depth=1, rope=True, attn_layout=layout),
            lens_fn=lambda b, lp: np.array(
                [lp if i % 2 == 0 else 1 for i in range(b)], np.int32
            ),
        )

    def test_ragged_gate_rejects_out_of_range_lens(self, devices):
        mesh = Mesh(
            np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp")
        )
        with pytest.raises(ValueError, match="lens_fn"):
            _ragged_gate(
                mesh,
                ModelConfig(depth=1),
                lens_fn=lambda b, lp: np.full((b,), lp + 1, np.int32),
            )

    @pytest.mark.parametrize("layout", ["contiguous", "striped"])
    def test_gather_last_valid_edge_lens_single_rank(self, layout):
        # the unsharded inverse map directly: full-length and length-1
        # rows pick exactly their own last valid position
        from tpu_patterns.models.decode import (
            _CacheLayout,
            _gather_last_valid,
        )

        lp = 8
        lay = _CacheLayout(prefill=lp, gen_cap=4, sp=1, layout=layout)
        y = jax.random.normal(jax.random.key(0), (3, lp, 16))
        lens = jnp.asarray([lp, 1, 5], jnp.int32)
        got = np.asarray(_gather_last_valid(y, lens, lay, None))
        for b, ln in enumerate([lp, 1, 5]):
            np.testing.assert_array_equal(
                got[b, 0], np.asarray(y)[b, ln - 1]
            )

    def test_ragged_selffeeding_rollout_finite(self, devices):
        mesh = Mesh(
            np.array(devices[:4]).reshape(2, 2, 1), ("dp", "sp", "tp")
        )
        cfg = ModelConfig(**CFG, dtype="float32", rope=True)
        b, lp, gen = 2, 8, 4
        prefill, generate = make_decoder(mesh, cfg, b, lp, gen)
        params = jax.device_put(
            _stacked_params(jax.random.key(0), cfg),
            {k: NamedSharding(mesh, s)
             for k, s in _stacked_specs(cfg).items()},
        )
        x = jax.device_put(
            jax.random.normal(jax.random.key(1), (b, lp, cfg.embed)),
            NamedSharding(mesh, P("dp", "sp", None)),
        )
        lens = jax.device_put(
            jnp.asarray([8, 5], jnp.int32), NamedSharding(mesh, P("dp"))
        )
        caches, y0 = prefill(params, x, lens)
        _, ys = generate(params, caches, y0, (lens, 0), gen)
        assert ys.shape == (b, gen, cfg.embed)
        assert np.isfinite(np.asarray(ys)).all()


class TestRunDecode:
    def test_measured_pattern_succeeds(self, mesh3d, capsys):
        from tpu_patterns.core.results import ResultWriter

        cfg = DecodeConfig(
            embed=64, heads=8, head_dim=8, dtype="float32", depth=1,
            batch=2, prefill=8, gen=4, reps=2, warmup=1,
        )
        writer = ResultWriter()
        (rec,) = run_decode(mesh3d, cfg, writer)
        assert rec.verdict.value == "SUCCESS"
        assert rec.metrics["tokens_per_s"] > 0
        assert rec.metrics["cache_MB"] > 0
