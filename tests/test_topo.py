"""Tests for topology discovery & placement (SURVEY.md §7 step 2)."""

import dataclasses

import pytest

from tpu_patterns.topo import (
    Mechanism,
    PlacementMode,
    bootstrap,
    discover,
    make_mesh,
    order_devices,
    select_devices,
)


@dataclasses.dataclass
class FakeDevice:
    """Stands in for a PJRT TPU device: a 2x2 torus, 2 cores per chip."""

    id: int
    coords: tuple
    core_on_chip: int
    process_index: int = 0
    platform: str = "faketpu"


def fake_slice():
    devs = []
    i = 0
    for x in range(2):
        for y in range(2):
            for core in range(2):
                devs.append(FakeDevice(id=i, coords=(x, y), core_on_chip=core))
                i += 1
    return devs


class TestTopology:
    def test_torus_shape_and_cores(self):
        topo = discover(fake_slice())
        assert topo.num_devices == 8
        assert topo.torus_shape == (2, 2)
        assert topo.cores_per_chip == 2

    def test_planes_are_ici_rings(self):
        topo = discover(fake_slice())
        rings = topo.planes()
        # 2 axes x 2 cross-positions x 2 cores = 8 rings of length 2
        assert len(rings) == 8
        for ring in rings:
            assert len(ring) == 2
            a, b = (topo.devices[i] for i in ring)
            # members of a ring differ in exactly one torus coordinate
            assert sum(x != y for x, y in zip(a.coords, b.coords)) == 1
            assert a.core_on_chip == b.core_on_chip

    def test_neighbors_on_2x2(self):
        topo = discover(fake_slice())
        for d in topo.devices:
            assert len(topo.neighbors(d.index)) == 2

    def test_flat_and_entry(self):
        topo = discover(fake_slice())
        flat = topo.flat()
        assert sorted(flat) == list(range(8))
        assert topo.entry(0) == flat[0]
        assert topo.entry(9) == flat[1]  # wraps modulo, devices.hpp:46-48 style

    def test_synthetic_coords_on_cpu(self, devices):
        topo = discover(devices)
        assert topo.devices[0].synthetic_coords
        assert topo.torus_shape == (len(devices),)
        assert topo.planes()  # still yields at least one plane
        assert "devices:" in topo.describe()


class TestNativeTopologyCore:
    """The C++ core (csrc/topo.cc) must agree byte-for-byte with the
    Python implementation across topology shapes — same twin discipline
    as the checksum/clock FFI modules (SURVEY.md §2.2 item 2)."""

    def _topologies(self):
        def grid(dims, cores=1):
            devs, i = [], 0
            def rec(prefix, rest):
                nonlocal i
                if not rest:
                    for c in range(cores):
                        devs.append(
                            FakeDevice(id=i, coords=tuple(prefix),
                                       core_on_chip=c)
                        )
                        i += 1
                    return
                for v in range(rest[0]):
                    rec(prefix + [v], rest[1:])
            rec([], list(dims))
            return devs

        return {
            "2x2x2cores": fake_slice(),
            "chain8": grid([8]),
            "2x4": grid([2, 4]),
            "2x2x2": grid([2, 2, 2]),
            "4x1": grid([4, 1]),  # degenerate second axis
            "single": grid([1]),
            "3d_cores": grid([2, 2, 2], cores=2),
        }

    @pytest.fixture(autouse=True)
    def _require_native(self):
        from tpu_patterns.topo import native as topo_native

        if topo_native.load() is None:
            pytest.skip(
                f"native topo core unavailable: {topo_native.load_error()}"
            )

    def test_planes_native_matches_python(self):
        for name, devs in self._topologies().items():
            topo = discover(devs)
            py = topo.planes(impl="python")
            cc = topo.planes(impl="native")
            assert cc == py, f"{name}: native {cc} != python {py}"

    def test_neighbors_native_matches_python(self):
        for name, devs in self._topologies().items():
            topo = discover(devs)
            for d in topo.devices:
                py = topo.neighbors(d.index, impl="python")
                cc = topo.neighbors(d.index, impl="native")
                assert cc == py, f"{name}[{d.index}]: {cc} != {py}"

    def test_auto_prefers_native_and_agrees(self, monkeypatch):
        topo = discover(fake_slice())
        assert topo.planes() == topo.planes(impl="python")
        # ...and auto really ROUTES to the native core (a silent
        # fallback would make the assertion above vacuous)
        from tpu_patterns.topo import native as topo_native

        sentinel = [[99]]
        monkeypatch.setattr(
            topo_native, "planes_native", lambda devs: sentinel
        )
        assert topo.planes() is sentinel

    def test_native_maps_positions_to_device_index(self):
        # a hand-built Topology whose .index differs from list position:
        # both impls must speak DeviceInfo.index, not positions
        from tpu_patterns.topo.topology import DeviceInfo, Topology

        devs = [
            DeviceInfo(index=10 + p, id=p, process_index=0,
                       platform="fake", coords=(c,), core_on_chip=0,
                       synthetic_coords=False)
            for p, c in enumerate(range(4))
        ]
        topo = Topology(devices=devs)
        assert topo.planes(impl="native") == topo.planes(impl="python")
        assert topo.planes(impl="native") == [[10, 11, 12, 13]]

    def test_bad_impl_rejected(self):
        topo = discover(fake_slice())
        with pytest.raises(ValueError, match="impl"):
            topo.planes(impl="cuda")
        with pytest.raises(ValueError, match="impl"):
            topo.neighbors(0, impl="cuda")


class TestPlacement:
    def test_compact_fills_chip_first(self):
        topo = discover(fake_slice())
        order = order_devices(topo, PlacementMode.COMPACT)
        first_two = [topo.devices[i] for i in order[:2]]
        assert first_two[0].coords == first_two[1].coords  # same chip
        assert first_two[0].core_on_chip != first_two[1].core_on_chip

    def test_spread_round_robins_chips(self):
        topo = discover(fake_slice())
        order = order_devices(topo, PlacementMode.SPREAD)
        first_four = [topo.devices[i] for i in order[:4]]
        assert len({d.coords for d in first_four}) == 4  # all different chips
        assert all(d.core_on_chip == 0 for d in first_four)

    def test_plan_walks_rings(self):
        topo = discover(fake_slice())
        order = order_devices(topo, PlacementMode.PLAN)
        assert sorted(order) == list(range(8))
        # the first pair comes off one ring: directly wired neighbors
        a, b = (topo.devices[i] for i in order[:2])
        assert sum(x != y for x, y in zip(a.coords, b.coords)) == 1

    def test_select_devices_wraps(self):
        topo = discover(fake_slice())
        sel = select_devices(10, topo)
        assert len(sel) == 10
        assert sel[8] == sel[0]

    def test_make_mesh_full(self, devices):
        mesh = make_mesh(("x",), devices=devices)
        assert mesh.devices.shape == (len(devices),)

    def test_make_mesh_2d_and_modes(self, devices):
        mesh = make_mesh(("x", "y"), shape=(4, 2), mode=PlacementMode.SPREAD,
                         devices=devices)
        assert mesh.axis_names == ("x", "y")
        assert mesh.devices.shape == (4, 2)

    def test_make_mesh_visible_subset(self, devices):
        mesh = make_mesh(("x",), shape=(2,), mechanism=Mechanism.VISIBLE,
                         devices=devices)
        assert mesh.devices.shape == (2,)

    def test_make_mesh_mesh_mechanism_requires_cover(self, devices):
        with pytest.raises(ValueError, match="cover all"):
            make_mesh(("x",), shape=(2,), mechanism=Mechanism.MESH,
                      devices=devices)

    def test_make_mesh_rejects_oversubscription(self, devices):
        with pytest.raises(ValueError, match="oversubscribe"):
            make_mesh(("x",), shape=(2 * len(devices),),
                      mechanism=Mechanism.VISIBLE, devices=devices)


class TestBootstrap:
    def test_single_process_noop(self):
        info = bootstrap()
        assert info.num_processes == 1
        assert info.process_id == 0
        assert info.is_coordinator
        assert info.local_device_count >= 1

    def test_partial_config_rejected(self, monkeypatch):
        # coordinator set, num_processes missing: must not silently run N
        # independent single-process jobs
        with pytest.raises(ValueError, match="partial"):
            bootstrap(coordinator_address="localhost:1234")
        with pytest.raises(ValueError, match="partial"):
            bootstrap(num_processes=4)
        with pytest.raises(ValueError, match="partial"):
            bootstrap(coordinator_address="localhost:1234", num_processes=4)

    def test_rank_only_env_is_single_process(self, monkeypatch):
        # mpirun -n 1 style: a rank var alone is not a distributed config
        monkeypatch.setenv("PMI_RANK", "0")
        info = bootstrap()
        assert info.num_processes == 1
