"""Archive integrity audit: committed measurements obey TODAY's gates.

The measurement-integrity tier (physics bounds, stale-grad refusal)
landed AFTER the round-2 capture, so the committed archive predates the
gates that would have vetted it (VERDICT r4 weak #3).  This audit
applies the current gates retroactively to every record under
``docs/measured/`` — and keeps applying them to whatever the capture
watcher banks next, so a record stream that violates physics can never
sit committed without CI saying so.

Constants are the v5e tables from ``runtime.py`` (every committed
capture ran on one TPU v5 lite chip); rows explicitly flagged
implausible by their own capture are honest FAILURE evidence and are
exempt from the bound they already report violating.
"""

import functools
import glob
import json
import os

import pytest

from tpu_patterns.core.results import Record, stale_grad_records
from tpu_patterns.runtime import (
    HBM_SPEC_GBPS,
    SPEC_PLAUSIBILITY_MARGIN,
    _CHIP_PEAK_TFLOPS,
)

ROOT = os.path.join(os.path.dirname(__file__), "..", "docs", "measured")
V5E_HBM = HBM_SPEC_GBPS["v5 lite"]
V5E_PEAK_BF16 = _CHIP_PEAK_TFLOPS["v5 lite"]


def _record_files():
    return sorted(
        p
        for p in glob.glob(os.path.join(ROOT, "**", "*.jsonl"), recursive=True)
        # sweep checkpoint state is {"cell": ...} bookkeeping, not
        # Records — exact name only, so a future record stream with
        # "state" in its name cannot silently escape the audit
        if os.path.basename(p) != "sweep-state.jsonl"
    )


@functools.cache
def _records():
    out = []
    for path in _record_files():
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                rec = Record.from_json(line)  # a torn line fails the audit
                out.append((f"{os.path.relpath(path, ROOT)}:{lineno}", rec))
    return out


class TestMeasuredArchive:
    def test_archive_exists_and_parses(self):
        recs = _records()
        assert len(recs) > 20, "archive unexpectedly empty"

    def test_no_unmarked_pre_fix_grad_records(self):
        # every grad rate captured before the FLOP-accounting fix must
        # carry superseded=true — the same refusal report/summarize apply
        stale = stale_grad_records(r for _, r in _records())
        assert stale == [], [r.mode for r in stale]

    def test_hbm_copy_rates_physically_plausible(self):
        # a copy moves 2x its rate in HBM traffic; committed local_put
        # rows must fit under the chip spec (+ calibration slack) unless
        # the row itself flags the violation as its finding
        bound = SPEC_PLAUSIBILITY_MARGIN * V5E_HBM
        for where, r in _records():
            if r.mode != "local_put":
                continue
            if r.metrics.get("hbm_plausible") == 0.0:
                continue  # honest flagged evidence of the artifact class
            for key, bw in r.metrics.items():
                if key.startswith("bandwidth_GBps"):
                    # a non-numeric metric is itself a schema violation
                    # the audit must surface, not skip around
                    assert isinstance(bw, (int, float)), f"{where}: {key}"
                    assert 2.0 * bw <= bound, (
                        f"{where}: {key}={bw:.1f} GB/s implies "
                        f"{2 * bw:.0f} GB/s of HBM traffic > {bound:.0f}"
                    )

    def test_tflops_bounded_by_chip_peak(self):
        # no committed rate may exceed what the MXU can issue; bf16 peak
        # is the loosest honest bound (archive rows don't all carry
        # their dtype, and an f32 row above the BF16 peak is just as
        # impossible)
        bound = SPEC_PLAUSIBILITY_MARGIN * V5E_PEAK_BF16
        for where, r in _records():
            for key in ("tflops", "tflops_hw"):
                rate = r.metrics.get(key)
                if rate is not None:
                    assert rate <= bound, (
                        f"{where}: {key}={rate:.1f} exceeds the v5e "
                        f"{V5E_PEAK_BF16:g} TFLOP/s peak (+slack)"
                    )

    def test_speedups_bounded_by_theoretical(self):
        # the concurrency harness's own contract: measured speedup can
        # approach but not meaningfully exceed the theoretical maximum
        for where, r in _records():
            s = r.metrics.get("speedup")
            t = r.metrics.get("theoretical_speedup")
            if s is not None and t is not None:
                assert s <= SPEC_PLAUSIBILITY_MARGIN * t, (
                    f"{where}: speedup {s:.2f} > theoretical {t:.2f}"
                )

    def test_bench_files_parse_and_carry_schema(self):
        # the banked bench_*.json files feed bench.py's stale fallback;
        # a corrupt or schema-less one silently narrows that safety net
        from conftest import load_root_module

        bench = load_root_module("bench")
        files = glob.glob(os.path.join(ROOT, "**", "bench_*.json"),
                          recursive=True)
        assert files, "no banked bench files"
        good = 0
        for path in files:
            with open(path) as f:
                line = bench.last_metric_line(f.read())
            assert line is not None, f"{path}: no driver-schema line"
            rec = json.loads(line)
            assert {"metric", "value", "unit", "vs_baseline"} <= set(rec)
            if rec["metric"] != "bench_error":
                good += 1
        assert good >= 1, "no numeric banked bench record in the archive"
