"""Collective matmul (parallel/overlap.py): the decomposed ppermute-ring
forms must reproduce the XLA collective and the plain matmul exactly."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_patterns.core.results import ResultWriter
from tpu_patterns.parallel.overlap import (
    OverlapConfig,
    allgather_matmul,
    matmul_reducescatter,
    run_overlap,
)


def _mesh(devices, n):
    return Mesh(np.array(devices[:n]), ("x",))


def _apply(mesh, fn, x, w, in_specs, out_specs, n, decomposed):
    return jax.jit(
        jax.shard_map(
            functools.partial(
                fn, axis_name="x", axis_size=n, decomposed=decomposed
            ),
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
        )
    )(
        jax.device_put(x, NamedSharding(mesh, in_specs[0])),
        jax.device_put(w, NamedSharding(mesh, in_specs[1])),
    )


class TestAllGatherMatmul:
    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_matches_plain_matmul(self, devices, n):
        mesh = _mesh(devices, n)
        b, e, f = 4 * n, 32, 8 * n
        x = jax.random.normal(jax.random.key(0), (b, e), jnp.float32)
        w = jax.random.normal(jax.random.key(1), (e, f), jnp.float32)
        want = np.asarray(x @ w)
        specs = ((P("x", None), P(None, "x")), P(None, "x"))
        for dec in (False, True):
            got = _apply(
                mesh, allgather_matmul, x, w, specs[0], specs[1], n, dec
            )
            np.testing.assert_allclose(
                np.asarray(got), want, rtol=0, atol=1e-5
            )

    def test_decomposed_equals_baseline_bitwise_blocks(self, devices):
        # same per-block dot shapes -> identical numerics block by block
        n, mesh = 4, _mesh(devices, 4)
        x = jax.random.normal(jax.random.key(2), (8 * n, 64), jnp.float32)
        w = jax.random.normal(jax.random.key(3), (64, 4 * n), jnp.float32)
        specs = ((P("x", None), P(None, "x")), P(None, "x"))
        base = _apply(mesh, allgather_matmul, x, w, specs[0], specs[1], n, False)
        dec = _apply(mesh, allgather_matmul, x, w, specs[0], specs[1], n, True)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(dec))


class TestMatmulReduceScatter:
    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_matches_plain_matmul(self, devices, n):
        mesh = _mesh(devices, n)
        b, e, f = 4 * n, 32 * n, 8
        x = jax.random.normal(jax.random.key(4), (b, e), jnp.float32)
        w = jax.random.normal(jax.random.key(5), (e, f), jnp.float32)
        want = np.asarray(x @ w)
        specs = ((P(None, "x"), P("x", None)), P("x", None))
        for dec in (False, True):
            got = _apply(
                mesh, matmul_reducescatter, x, w, specs[0], specs[1], n, dec
            )
            np.testing.assert_allclose(
                np.asarray(got), want, rtol=0, atol=1e-4
            )


class TestRunOverlap:
    def test_measured_pattern_succeeds(self, devices):
        mesh = _mesh(devices, 8)
        cfg = OverlapConfig(
            rows=16, contract=64, cols=32, dtype="float32",
            reps=2, warmup=1,
        )
        recs = run_overlap(mesh, cfg, ResultWriter())
        assert [r.mode for r in recs] == ["ag", "rs"]
        for r in recs:
            assert r.verdict.value == "SUCCESS", r.notes
            assert r.metrics["speedup"] > 0
            assert r.metrics["ring_bytes"] > 0

    def test_divergence_is_failure(self, devices, monkeypatch):
        # a broken decomposition must FAIL the verdict, not pass silently
        import tpu_patterns.parallel.overlap as ov

        orig = ov.allgather_matmul

        def broken(x, w, axis_name, axis_size, decomposed=True):
            out = orig(x, w, axis_name, axis_size, decomposed)
            return out + 1.0 if decomposed else out

        monkeypatch.setattr(ov, "allgather_matmul", broken)
        mesh = _mesh(devices, 4)
        cfg = OverlapConfig(
            rows=8, contract=32, cols=16, dtype="float32",
            pattern="ag", reps=2, warmup=1,
        )
        (rec,) = ov.run_overlap(mesh, cfg, ResultWriter())
        assert rec.verdict.value == "FAILURE"
        assert any("diverges" in note for note in rec.notes)
