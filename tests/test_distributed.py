"""Multi-process distributed backend: 2 real processes over localhost.

The reference's multi-node story is `mpirun -n N` + MPI_Init
(SURVEY.md §4: no cluster-free mode exists there).  Here the same contract
— launcher env -> bootstrap() -> global collectives — runs as two actual
OS processes joined through jax.distributed over a localhost coordinator,
verifying on the global mesh: a psum, a cross-process ppermute ring, the
hierarchical (dcn x ici) allreduce with the process boundary as the real
dcn tier, and the FULL flagship training step with its sp axis spanning
the processes — the ring-attention ppermutes and the sp loss psum ride
gloo, while tp pairs stay intra-process — loss matching the
single-device reference exactly.  CPU devices, Gloo collectives: no
hardware needed — the cluster-free distributed mode the reference lacks.
"""

import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent

WORKER = textwrap.dedent(
    """
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    if hasattr(jax.config, "jax_num_cpu_devices"):
        jax.config.update("jax_num_cpu_devices", 2)
    else:  # old JAX: the XLA flag, set before first backend init
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2"
        ).strip()
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import numpy as np
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from tpu_patterns.comm.ring import ring_perm
    from tpu_patterns.topo.bootstrap import bootstrap

    info = bootstrap()  # identity comes from the env tier, as a launcher would set it
    assert info.num_processes == 2, info
    assert info.local_device_count == 2, info
    assert info.global_device_count == 4, info

    mesh = Mesh(np.array(jax.devices()), ("x",))
    n = 4

    def body():
        r = lax.axis_index("x")
        mine = (r + 1).astype(jnp.float32).reshape(1)
        # cross-process ring shift: value from the left neighbor
        shifted = lax.ppermute(mine, "x", ring_perm(n))
        # weight by 2^r so a misrouted permutation changes the total
        total = lax.psum(shifted * (2.0 ** r), "x")
        return total

    fn = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=(), out_specs=P("x"))
    )
    out = np.asarray(fn().addressable_shards[0].data)
    expect = sum(((i - 1) % n + 1) * 2.0**i for i in range(n))
    assert np.allclose(out, expect), (out, expect)

    # Hierarchical tier decomposition where the PROCESS boundary is the
    # real dcn axis: detect_hierarchy groups the 4 global devices by
    # process (2 x 2), and the cross-tier allreduce must equal the global
    # sum — reduce_scatter/all_gather riding intra-process links, the psum
    # crossing gloo between processes (comm/hierarchical.py).
    from tpu_patterns.comm.hierarchical import (
        detect_hierarchy,
        hierarchical_allreduce,
    )

    n_groups, ordered = detect_hierarchy(jax.devices())
    assert n_groups == 2, n_groups  # one group per process
    hmesh = Mesh(np.array(ordered).reshape(2, 2), ("dcn", "ici"))
    hn = 8

    def hbody():
        r = lax.axis_index("dcn") * 2 + lax.axis_index("ici")
        shard = r.astype(jnp.float32) + jnp.arange(hn, dtype=jnp.float32)
        return hierarchical_allreduce(shard, "ici", 2, "dcn")[None, None]

    hfn = jax.jit(
        jax.shard_map(
            hbody, mesh=hmesh, in_specs=(), out_specs=P("dcn", "ici", None)
        )
    )
    local = np.asarray(hfn().addressable_shards[0].data)[0, 0]
    # sum over ranks r=0..3 of (r + j) = 6 + 4j
    assert np.allclose(local, 6.0 + 4.0 * np.arange(hn)), local

    # The flagship training step ACROSS the process boundary: a
    # ("dp","sp","tp") mesh whose sp axis spans the two processes, so the
    # ring-attention ppermutes and the sp loss psum ride gloo (tp pairs
    # stay intra-process) — the full model-training analogue of the
    # reference's multi-node mpirun story.
    from tpu_patterns.models import ModelConfig, init_params, make_train_step
    from tpu_patterns.models.transformer import forward_shard

    cfg = ModelConfig(embed=32, heads=4, head_dim=8, dtype="float32")
    m3 = Mesh(np.array(jax.devices()).reshape(1, 2, 2), ("dp", "sp", "tp"))
    params = init_params(jax.random.key(0), cfg)  # deterministic: all ranks agree
    x_np = np.asarray(
        jax.random.normal(jax.random.key(1), (2, 16, 32), jnp.float32)
    )
    step, pspecs = make_train_step(m3, cfg, lr=0.0)

    def put_global(arr, spec):
        from jax.sharding import NamedSharding

        sh = NamedSharding(m3, spec)
        return jax.make_array_from_callback(
            arr.shape, sh, lambda idx: np.asarray(arr)[idx]
        )

    gp = {k: put_global(np.asarray(v), pspecs[k]) for k, v in params.items()}
    gx = put_global(x_np, P("dp", "sp", None))
    _, loss = step(gp, gx)
    # single-device reference on the full arrays (pure local math)
    ref = forward_shard(params, jnp.asarray(x_np), cfg)
    want_loss = float(jnp.sum(ref.astype(jnp.float32) ** 2))
    assert np.isclose(float(loss), want_loss, rtol=1e-5), (
        float(loss), want_loss,
    )
    print(f"rank {info.process_id} OK", flush=True)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_bootstrap_and_collectives(tmp_path):
    port = _free_port()
    procs, logs = [], []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env.update(
            {
                "PYTHONPATH": str(ROOT),
                "JAX_PLATFORMS": "cpu",
                "TPU_PATTERNS_COORDINATOR": f"127.0.0.1:{port}",
                "TPU_PATTERNS_NUM_PROCESSES": "2",
                "TPU_PATTERNS_PROCESS_ID": str(rank),
            }
        )
        # Workers write to files, not pipes: an undrained pipe can block a
        # worker mid-collective and hang its peer until timeout.
        log = tmp_path / f"rank{rank}.log"
        logs.append(log)
        with open(log, "w") as f:
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-c", WORKER],
                    env=env,
                    stdout=f,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )

    def all_output() -> str:
        return "\n".join(
            f"--- rank {r} ---\n{log.read_text()}" for r, log in enumerate(logs)
        )

    for rank, p in enumerate(procs):
        try:
            p.wait(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
                q.wait()
            pytest.fail(f"rank {rank} timed out; worker logs:\n{all_output()}")
    for rank, (p, log) in enumerate(zip(procs, logs)):
        out = log.read_text()
        assert p.returncode == 0, f"rank {rank} failed:\n{all_output()}"
        assert f"rank {rank} OK" in out


CKPT_WORKER = textwrap.dedent(
    """
    import json
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    if hasattr(jax.config, "jax_num_cpu_devices"):
        jax.config.update("jax_num_cpu_devices", 2)
    else:  # old JAX: the XLA flag, set before first backend init
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2"
        ).strip()
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpu_patterns import ckpt
    from tpu_patterns.topo.bootstrap import bootstrap

    info = bootstrap()
    mesh = Mesh(np.array(jax.devices()), ("x",))
    root = os.environ["TPU_PATTERNS_TEST_CKPT_DIR"]

    # globally sharded [8, 4] with distinct values per element, plus a
    # fully replicated leaf (only ONE process holds its replica 0)
    want = np.arange(32, dtype=np.float32).reshape(8, 4)
    sh = NamedSharding(mesh, P("x"))
    w = jax.make_array_from_callback(want.shape, sh, lambda idx: want[idx])
    rep = jax.device_put(
        jnp.asarray([3.5, -1.25]), NamedSharding(mesh, P())
    )
    tree = {"w": w, "rep": rep}
    ckpt.save(root, 7, tree)  # internal barriers: all ranks participate

    # each process verifies its own shard file holds ONLY local shards
    rank = jax.process_index()
    with open(os.path.join(root, "step_7", f"shards_proc{rank}.json")) as f:
        table = json.load(f)
    leaf_of = {}
    with open(os.path.join(root, "step_7", "manifest.json")) as f:
        for leaf_info in json.load(f)["leaves"]:
            leaf_of[leaf_info["key"]] = leaf_info["leaf"]
    w_rows = sorted(
        e["index"][0][0] for e in table if e["leaf"] == leaf_of["['w']"]
    )
    # rank r's two local devices hold rows [4r, 4r+2) and [4r+2, 4r+4):
    # ONLY those may appear in its file (a dedup regression writing a
    # remote shard here must fail loudly)
    assert w_rows == [4 * rank, 4 * rank + 2], (rank, table)
    # replica-0 dedup ACROSS processes: the replicated leaf must appear
    # exactly ONCE in the union of both processes' shard tables
    rep_entries = 0
    for p in range(2):
        with open(
            os.path.join(root, "step_7", f"shards_proc{p}.json")
        ) as f:
            rep_entries += sum(
                1 for e in json.load(f) if e["leaf"] == leaf_of["['rep']"]
            )
    assert rep_entries == 1, rep_entries

    # elastic restore onto the same mesh; every process checks every
    # ADDRESSABLE shard of the result against the truth
    back = ckpt.restore(root, tree)
    for shard in back["w"].addressable_shards:
        np.testing.assert_array_equal(
            np.asarray(shard.data), want[shard.index]
        )
    for shard in back["rep"].addressable_shards:
        np.testing.assert_array_equal(
            np.asarray(shard.data), np.asarray([3.5, -1.25], np.float32)
        )
    print(f"rank {rank} OK")
    """
)


def test_two_process_checkpoint_roundtrip(tmp_path):
    # the multi-process save path: per-process shard files, replica-0
    # dedup ACROSS processes, sync barriers inside save, shared-fs commit
    port = _free_port()
    ckpt_dir = tmp_path / "ckpt"
    procs, logs = [], []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env.update(
            {
                "PYTHONPATH": str(ROOT),
                "JAX_PLATFORMS": "cpu",
                "TPU_PATTERNS_COORDINATOR": f"127.0.0.1:{port}",
                "TPU_PATTERNS_NUM_PROCESSES": "2",
                "TPU_PATTERNS_PROCESS_ID": str(rank),
                "TPU_PATTERNS_TEST_CKPT_DIR": str(ckpt_dir),
            }
        )
        log = tmp_path / f"ckpt_rank{rank}.log"
        logs.append(log)
        with open(log, "w") as f:
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-c", CKPT_WORKER],
                    env=env,
                    stdout=f,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )

    def all_output() -> str:
        return "\n".join(
            f"--- rank {r} ---\n{log.read_text()}"
            for r, log in enumerate(logs)
        )

    for rank, p in enumerate(procs):
        try:
            p.wait(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
                q.wait()
            pytest.fail(f"rank {rank} timed out:\n{all_output()}")
    for rank, (p, log) in enumerate(zip(procs, logs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{all_output()}"
        assert f"rank {rank} OK" in log.read_text()
    # both processes' shard files exist in the committed step
    names = sorted(os.listdir(ckpt_dir / "step_7"))
    assert "proc0.npz" in names and "proc1.npz" in names


def test_four_process_dryrun():
    """The dryrun's multi-process mode at 4 OS processes x 2 devices:
    the flagship pipelined step, elastic checkpoint, decode
    teacher-forcing gate, and LM train+rollout with the sp axis crossing
    THREE process boundaries over gloo (VERDICT r3 next #4: the
    reference's every-test-is-mpirun discipline applied to the driver's
    own correctness artifact).  The spawner raises with full worker logs
    on any failure."""
    from conftest import load_root_module

    graft = load_root_module("__graft_entry__")
    graft.dryrun_multiprocess(n_processes=4, n_local=2, timeout=480.0)
