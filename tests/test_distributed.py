"""Multi-process distributed backend: 2 real processes over localhost.

The reference's multi-node story is `mpirun -n N` + MPI_Init
(SURVEY.md §4: no cluster-free mode exists there).  Here the same contract
— launcher env -> bootstrap() -> global collectives — runs as two actual
OS processes joined through jax.distributed over a localhost coordinator,
verifying on the global mesh: a psum, a cross-process ppermute ring, the
hierarchical (dcn x ici) allreduce with the process boundary as the real
dcn tier, and the FULL flagship training step with its sp axis spanning
the processes — the ring-attention ppermutes and the sp loss psum ride
gloo, while tp pairs stay intra-process — loss matching the
single-device reference exactly.  CPU devices, Gloo collectives: no
hardware needed — the cluster-free distributed mode the reference lacks.
"""

import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent

WORKER = textwrap.dedent(
    """
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    import numpy as np
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from tpu_patterns.comm.ring import ring_perm
    from tpu_patterns.topo.bootstrap import bootstrap

    info = bootstrap()  # identity comes from the env tier, as a launcher would set it
    assert info.num_processes == 2, info
    assert info.local_device_count == 2, info
    assert info.global_device_count == 4, info

    mesh = Mesh(np.array(jax.devices()), ("x",))
    n = 4

    def body():
        r = lax.axis_index("x")
        mine = (r + 1).astype(jnp.float32).reshape(1)
        # cross-process ring shift: value from the left neighbor
        shifted = lax.ppermute(mine, "x", ring_perm(n))
        # weight by 2^r so a misrouted permutation changes the total
        total = lax.psum(shifted * (2.0 ** r), "x")
        return total

    fn = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=(), out_specs=P("x"))
    )
    out = np.asarray(fn().addressable_shards[0].data)
    expect = sum(((i - 1) % n + 1) * 2.0**i for i in range(n))
    assert np.allclose(out, expect), (out, expect)

    # Hierarchical tier decomposition where the PROCESS boundary is the
    # real dcn axis: detect_hierarchy groups the 4 global devices by
    # process (2 x 2), and the cross-tier allreduce must equal the global
    # sum — reduce_scatter/all_gather riding intra-process links, the psum
    # crossing gloo between processes (comm/hierarchical.py).
    from tpu_patterns.comm.hierarchical import (
        detect_hierarchy,
        hierarchical_allreduce,
    )

    n_groups, ordered = detect_hierarchy(jax.devices())
    assert n_groups == 2, n_groups  # one group per process
    hmesh = Mesh(np.array(ordered).reshape(2, 2), ("dcn", "ici"))
    hn = 8

    def hbody():
        r = lax.axis_index("dcn") * 2 + lax.axis_index("ici")
        shard = r.astype(jnp.float32) + jnp.arange(hn, dtype=jnp.float32)
        return hierarchical_allreduce(shard, "ici", 2, "dcn")[None, None]

    hfn = jax.jit(
        jax.shard_map(
            hbody, mesh=hmesh, in_specs=(), out_specs=P("dcn", "ici", None)
        )
    )
    local = np.asarray(hfn().addressable_shards[0].data)[0, 0]
    # sum over ranks r=0..3 of (r + j) = 6 + 4j
    assert np.allclose(local, 6.0 + 4.0 * np.arange(hn)), local

    # The flagship training step ACROSS the process boundary: a
    # ("dp","sp","tp") mesh whose sp axis spans the two processes, so the
    # ring-attention ppermutes and the sp loss psum ride gloo (tp pairs
    # stay intra-process) — the full model-training analogue of the
    # reference's multi-node mpirun story.
    from tpu_patterns.models import ModelConfig, init_params, make_train_step
    from tpu_patterns.models.transformer import forward_shard

    cfg = ModelConfig(embed=32, heads=4, head_dim=8, dtype="float32")
    m3 = Mesh(np.array(jax.devices()).reshape(1, 2, 2), ("dp", "sp", "tp"))
    params = init_params(jax.random.key(0), cfg)  # deterministic: all ranks agree
    x_np = np.asarray(
        jax.random.normal(jax.random.key(1), (2, 16, 32), jnp.float32)
    )
    step, pspecs = make_train_step(m3, cfg, lr=0.0)

    def put_global(arr, spec):
        from jax.sharding import NamedSharding

        sh = NamedSharding(m3, spec)
        return jax.make_array_from_callback(
            arr.shape, sh, lambda idx: np.asarray(arr)[idx]
        )

    gp = {k: put_global(np.asarray(v), pspecs[k]) for k, v in params.items()}
    gx = put_global(x_np, P("dp", "sp", None))
    _, loss = step(gp, gx)
    # single-device reference on the full arrays (pure local math)
    ref = forward_shard(params, jnp.asarray(x_np), cfg)
    want_loss = float(jnp.sum(ref.astype(jnp.float32) ** 2))
    assert np.isclose(float(loss), want_loss, rtol=1e-5), (
        float(loss), want_loss,
    )
    print(f"rank {info.process_id} OK", flush=True)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_bootstrap_and_collectives(tmp_path):
    port = _free_port()
    procs, logs = [], []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env.update(
            {
                "PYTHONPATH": str(ROOT),
                "JAX_PLATFORMS": "cpu",
                "TPU_PATTERNS_COORDINATOR": f"127.0.0.1:{port}",
                "TPU_PATTERNS_NUM_PROCESSES": "2",
                "TPU_PATTERNS_PROCESS_ID": str(rank),
            }
        )
        # Workers write to files, not pipes: an undrained pipe can block a
        # worker mid-collective and hang its peer until timeout.
        log = tmp_path / f"rank{rank}.log"
        logs.append(log)
        with open(log, "w") as f:
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-c", WORKER],
                    env=env,
                    stdout=f,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )

    def all_output() -> str:
        return "\n".join(
            f"--- rank {r} ---\n{log.read_text()}" for r, log in enumerate(logs)
        )

    for rank, p in enumerate(procs):
        try:
            p.wait(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
                q.wait()
            pytest.fail(f"rank {rank} timed out; worker logs:\n{all_output()}")
    for rank, (p, log) in enumerate(zip(procs, logs)):
        out = log.read_text()
        assert p.returncode == 0, f"rank {rank} failed:\n{all_output()}"
        assert f"rank {rank} OK" in out
