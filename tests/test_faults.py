"""Fault injection + self-healing recovery (tpu_patterns/faults/,
docs/robustness.md).

Every named fault site has a test here that FIRES it and asserts the
documented recovery behavior — the acceptance bar of the robustness PR:

  worker.ready   kill pre-ready -> subprocess fallback, breaker counts
  cell.run       crash attempt 1 -> retried to SUCCESS; same-rc crashes
                 -> quarantined without burning the budget
  ckpt.save      kill mid-save -> torn .tmp the next save sweeps;
                 transient error -> retried to a clean commit
  ckpt.restore   transient error -> retried, tree bit-identical
  train.step     injected NaN -> halt (FAILURE verdict) or skip-step
  serve.prefill  transient error -> retried, ids exact; deterministic
                 error -> exactly the admitted rows quarantined
  serve.step     deterministic error -> active set quarantined;
                 preempt -> snapshot, then --resume is bit-identical
  serve.verify   transient error -> wide step retried, ids exact;
                 deterministic error -> rows quarantined with shared-
                 block refcounts balanced (nothing leaks, nothing lost)

PR 16's elastic-fleet sites fire next to the machinery they cut into:
serve.preempt (fail-open: preemption aborts, the ladder degrades to
shed, the victim keeps running) in tests/test_serve.py
TestPreemption; fleet.scale_out / fleet.scale_in (the scale attempt
aborts, the fleet stays at its current size) in tests/test_replica.py
TestElasticFleet.
"""

import dataclasses
import json
import os
import subprocess
import sys
import textwrap

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_patterns import ckpt, faults, obs
from tpu_patterns.faults import (
    FaultSpec,
    InjectedFault,
    Quarantined,
    RetryPolicy,
    call_with_retry,
    inject,
    parse_spec,
    run_cell_attempts,
)

from test_serve import CFG, Request, _decoder_and_params, _mesh, _trace


@pytest.fixture(autouse=True)
def _clean_faults():
    # a test's spec must never leak into the next test (or the ambient
    # environment into a test): explicit override, cleared on exit
    faults.configure("")
    yield
    faults.configure(None)


def _fast_policy(**kw):
    kw.setdefault("max_attempts", 2)
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("jitter_frac", 0.0)
    return RetryPolicy(**kw)


def _counter_value(name, **labels):
    return obs.counter(name, **labels).value


class TestSpecGrammar:
    def test_full_spec_round_trip(self):
        (s,) = parse_spec(
            "serve.step:preempt:after=2:count=1:step=5:delay_s=1.5"
        )
        assert s == FaultSpec(
            site="serve.step", action="preempt", after=2, count=1,
            delay_s=1.5, match=(("step", "5"),),
        )

    def test_multiple_specs_and_defaults(self):
        a, b = parse_spec("ckpt.save:error, cell.run:crash:rc=7")
        assert (a.site, a.action, a.count, a.after) == (
            "ckpt.save", "error", 1, 0
        )
        assert (b.site, b.action, b.rc) == ("cell.run", "crash", 7)

    @pytest.mark.parametrize(
        "bad",
        [
            "siteonly",
            "ckpt.save:frobnicate",  # unknown action
            "ckpt.save:error:notkv",
            "serve.steps:preempt",  # typo'd site would inject nothing
            "cell.run:crash:cout=1",  # typo'd key would match nothing
        ],
    )
    def test_malformed_specs_fail_loudly(self, bad):
        # a typo'd chaos run must error, not silently inject nothing
        with pytest.raises(ValueError):
            parse_spec(bad)


class TestInjector:
    def test_inactive_is_a_noop(self):
        faults.configure("")
        assert not faults.active()
        assert inject("anything", step=3) is None

    def test_count_after_window_the_ordinals(self):
        faults.configure("ckpt.save:error:after=1:count=2")
        assert inject("ckpt.save") is None  # ordinal 0: before the window
        for _ in range(2):  # ordinals 1, 2: fire
            with pytest.raises(InjectedFault):
                inject("ckpt.save")
        assert inject("ckpt.save") is None  # ordinal 3: window spent

    def test_match_predicates_gate_by_ctx(self):
        faults.configure("cell.run:error:cell=serve_base:count=9")
        assert inject("cell.run", cell="other") is None
        assert inject("serve.step", cell="serve_base") is None
        with pytest.raises(InjectedFault):
            inject("cell.run", cell="serve_base")

    def test_injected_fault_is_an_oserror(self):
        # every I/O retry path must treat a firing like a transient
        # I/O failure without special-casing
        assert issubclass(InjectedFault, OSError)

    def test_seeded_probability_replays_bit_identically(self, monkeypatch):
        monkeypatch.setenv(faults.injector.ENV_SEED, "7")

        def pattern():
            faults.configure(None)  # fresh in-process ordinals
            faults.configure("ckpt.save:error:count=99:p=0.5")
            fired = []
            for _ in range(24):
                try:
                    fired.append(inject("ckpt.save") is not None)
                except InjectedFault:
                    fired.append(True)
            return fired

        first = pattern()
        assert first == pattern()
        assert True in first and False in first  # p actually gates

    def test_state_dir_shares_ordinals_across_registries(
        self, tmp_path, monkeypatch
    ):
        # "crash on attempt 1, succeed on attempt 2" across fresh
        # PROCESSES needs file-backed ordinals; fresh registries model
        # fresh processes
        monkeypatch.setenv(faults.injector.ENV_STATE, str(tmp_path))
        faults.configure("ckpt.save:error:count=1")
        with pytest.raises(InjectedFault):
            inject("ckpt.save")
        faults.configure(None)
        faults.configure("ckpt.save:error:count=1")  # a "new process"
        assert inject("ckpt.save") is None  # ordinal 1 from the state file

    def test_firing_is_counted_and_logged(self):
        faults.configure("worker.ready:error")
        before = _counter_value(
            "tpu_patterns_faults_injected_total",
            site="worker.ready", action="error",
        )
        with pytest.raises(InjectedFault):
            inject("worker.ready", step=1)
        assert (
            _counter_value(
                "tpu_patterns_faults_injected_total",
                site="worker.ready", action="error",
            )
            == before + 1
        )
        with open(os.path.join(obs.run_dir(), "faults.jsonl")) as f:
            recs = [json.loads(ln) for ln in f if ln.strip()]
        assert any(
            r["mode"] == "worker.ready" and r["verdict"] == "WARNING"
            for r in recs
        )


class TestRetryPolicy:
    def test_backoff_is_exponential_and_capped(self):
        p = RetryPolicy(
            backoff_base_s=0.1, backoff_mult=2.0, backoff_max_s=0.5,
            jitter_frac=0.0,
        )
        assert [p.backoff_s(a) for a in (1, 2, 3, 4)] == [
            0.1, 0.2, 0.4, 0.5
        ]

    def test_seeded_jitter_is_deterministic_and_bounded(self):
        p = RetryPolicy(backoff_base_s=0.1, jitter_frac=0.25, seed=3)
        assert p.backoff_s(1) == p.backoff_s(1)
        assert 0.075 <= p.backoff_s(1) <= 0.125

    def test_transient_failure_retries_to_success(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) == 1:
                raise OSError("blip")
            return "ok"

        assert (
            call_with_retry(
                flaky, policy=_fast_policy(), site="t", sleep=lambda s: None
            )
            == "ok"
        )
        assert len(calls) == 2

    def test_same_signature_twice_quarantines(self):
        before = _counter_value(
            "tpu_patterns_faults_quarantined_total", site="t"
        )

        def determined():
            raise OSError("same wall every time")

        with pytest.raises(Quarantined) as e:
            call_with_retry(
                determined, policy=_fast_policy(max_attempts=5),
                site="t", sleep=lambda s: None,
            )
        assert isinstance(e.value.__cause__, OSError)
        assert (
            _counter_value("tpu_patterns_faults_quarantined_total", site="t")
            == before + 1
        )

    def test_changing_signature_exhausts_budget_then_reraises(self):
        n = [0]

        def shapeshifter():
            n[0] += 1
            raise OSError(f"failure {n[0]}")

        with pytest.raises(OSError, match="failure 3"):
            call_with_retry(
                shapeshifter, policy=_fast_policy(max_attempts=3),
                site="t", sleep=lambda s: None,
            )

    def test_non_retryable_exceptions_propagate_immediately(self):
        def bug():
            raise KeyError("programming error")

        with pytest.raises(KeyError):
            call_with_retry(
                bug, policy=_fast_policy(), site="t", sleep=lambda s: None
            )


class TestRunCellAttempts:
    def test_completed_cell_never_retried_even_on_failure_rc(self):
        # an honest FAILURE verdict is a RESULT; re-measuring it would
        # defeat both the checkpoint and the measurement
        seen = []

        def attempt(n):
            seen.append(n)
            return 3, True

        assert run_cell_attempts(
            attempt, policy=_fast_policy(), cell="c", sleep=lambda s: None
        ) == (3, True, 1, False)
        assert seen == [1]

    def test_crash_then_success_retries(self):
        def attempt(n):
            return (41, False) if n == 1 else (0, True)

        rc, completed, attempts, quarantined = run_cell_attempts(
            attempt, policy=_fast_policy(), cell="c", sleep=lambda s: None
        )
        assert (rc, completed, attempts, quarantined) == (0, True, 2, False)

    def test_same_rc_twice_quarantines(self):
        rc, completed, attempts, quarantined = run_cell_attempts(
            lambda n: (137, False),
            policy=_fast_policy(max_attempts=5), cell="c",
            sleep=lambda s: None,
        )
        assert (rc, completed, attempts, quarantined) == (137, False, 2, True)

    def test_should_stop_halts_the_retry_loop(self):
        rcs = iter([(41, False), (42, False)])
        rc, completed, attempts, _ = run_cell_attempts(
            lambda n: next(rcs), policy=_fast_policy(max_attempts=5),
            cell="c", should_stop=lambda: True, sleep=lambda s: None,
        )
        assert attempts == 1 and not completed


def _cpu_env(**extra):
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.pop("TPU_PATTERNS_FAULTS", None)
    env.pop("TPU_PATTERNS_FAULTS_STATE", None)
    env.update(extra)
    return env


class TestCellRunSite:
    """`cell.run` fires in cli.main before dispatch; the sweep retry
    loop (run_cell_attempts around run_spec) is the recovery."""

    def _run(self, tmp_path, spec_text, max_attempts=2):
        from tpu_patterns.sweep import SweepSpec, run_spec

        env = _cpu_env(
            TPU_PATTERNS_FAULTS=spec_text,
            TPU_PATTERNS_FAULTS_STATE=str(tmp_path / "fault-state"),
        )
        (tmp_path / "empty").mkdir(exist_ok=True)
        spec = SweepSpec("chaos_cell", ("ckpt", str(tmp_path / "empty")))
        return run_cell_attempts(
            lambda attempt: run_spec(
                spec, str(tmp_path / "out"), base_env=env, timeout=120
            ),
            policy=_fast_policy(max_attempts=max_attempts),
            cell=spec.name,
            sleep=lambda s: None,
        )

    def test_crash_on_attempt_one_retries_to_success(self, tmp_path):
        # count=1 + a shared state dir: the crash fires in the FIRST
        # cell subprocess only; the retry's fresh process sees ordinal 1
        rc, completed, attempts, quarantined = self._run(
            tmp_path, "cell.run:crash:count=1:cell=chaos_cell"
        )
        assert (rc, completed, attempts, quarantined) == (0, True, 2, False)

    def test_same_crash_signature_twice_quarantines(self, tmp_path):
        rc, completed, attempts, quarantined = self._run(
            tmp_path, "cell.run:crash:count=9", max_attempts=4
        )
        assert rc == 41 and not completed
        assert attempts == 2 and quarantined  # budget NOT burned


class TestWorkerReadySite:
    def test_kill_before_ready_falls_back_and_counts(self, tmp_path):
        # a worker SIGKILLed before the ready handshake must cost one
        # fallback, not wedge the schedule
        from tpu_patterns.exec.workers import WorkerPool

        before = _counter_value("tpu_patterns_exec_spawn_failures_total")
        pool = WorkerPool(
            1,
            _cpu_env(TPU_PATTERNS_FAULTS="worker.ready:kill:count=99"),
            log_dir=str(tmp_path),
        )
        try:
            assert pool.lease() is None
            assert pool.lease() is None
            assert pool._dead  # two consecutive failures open the breaker
            assert (
                _counter_value("tpu_patterns_exec_spawn_failures_total")
                >= before + 2
            )
            assert (
                obs.gauge("tpu_patterns_exec_breaker_open").value == 1.0
            )
        finally:
            pool.shutdown()

    def test_breaker_half_open_probe_recovers_the_warm_path(self):
        # state machine only (no real processes): open -> cool-down ->
        # one probing lease -> closed on success / re-open on failure
        from tpu_patterns.core.timing import clock_ns
        from tpu_patterns.exec.workers import WorkerPool

        class FakeWorker:
            ready = True
            expired = False

            def alive(self):
                return True

            def kill(self):
                pass

            shutdown = kill

        pool = WorkerPool(1, {}, breaker_cooldown_s=3600.0)
        spawns = {"fail": True, "n": 0}

        def fake_spawn():
            spawns["n"] += 1
            return None if spawns["fail"] else FakeWorker()

        pool._spawn = fake_spawn
        try:
            assert pool.lease() is None and pool.lease() is None
            assert pool._dead
            before = obs.counter(
                "tpu_patterns_exec_fallbacks_total", reason="breaker_open"
            ).value
            n_spawns = spawns["n"]
            assert pool.lease() is None  # open, not cooled: NO spawn
            assert spawns["n"] == n_spawns
            assert (
                obs.counter(
                    "tpu_patterns_exec_fallbacks_total",
                    reason="breaker_open",
                ).value
                == before + 1
            )
            pool._opened_ns = clock_ns() - int(7200 * 1e9)  # cool down
            assert pool.lease() is None  # half-open probe... fails
            assert spawns["n"] == n_spawns + 1
            assert pool._dead  # re-opened for another cool-down
            spawns["fail"] = False
            pool._opened_ns = clock_ns() - int(7200 * 1e9)
            w = pool.lease()  # half-open probe succeeds
            assert isinstance(w, FakeWorker)
            assert not pool._dead  # breaker closed: warm path is back
            pool.release(w, reusable=True)
            assert pool.lease() is w
        finally:
            pool._free = []  # fakes must not hit real shutdown plumbing
            pool._leased = set()
            pool.shutdown()


def _tree():
    return {
        "w": jnp.arange(32, dtype=jnp.float32).reshape(4, 8),
        "b": jnp.ones(3, jnp.float32),
    }


def _assert_tree_equal(a, b):
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


class TestCkptSites:
    def test_kill_mid_save_leaves_torn_tmp_next_save_sweeps(self, tmp_path):
        # the atomic-commit contract under a real SIGKILL: shards on
        # disk, no manifest -> not a committed step; a later save sweeps
        # the wreck; the committed tree is bit-identical to its source
        root = str(tmp_path / "ck")
        prog = textwrap.dedent(
            """
            import sys
            import jax.numpy as jnp
            from tpu_patterns import ckpt
            tree = {"w": jnp.arange(32, dtype=jnp.float32).reshape(4, 8),
                    "b": jnp.ones(3, jnp.float32)}
            ckpt.save(sys.argv[1], 1, tree)
            """
        )
        proc = subprocess.run(
            [sys.executable, "-c", prog, root],
            env=_cpu_env(TPU_PATTERNS_FAULTS="ckpt.save:kill"),
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == -9, proc.stderr
        torn = os.path.join(root, ".tmp.step_1")
        assert os.path.isdir(torn) and os.listdir(torn)  # shards landed
        assert ckpt.latest_step(root) is None  # restore ignores the wreck
        with pytest.raises(FileNotFoundError):
            ckpt.restore(root, _tree())
        tree = _tree()
        ckpt.save(root, 2, tree)
        assert not os.path.exists(torn)  # swept by the next commit
        assert ckpt.available_steps(root) == [2]
        _assert_tree_equal(tree, ckpt.restore(root, _tree()))

    def test_save_retries_transient_error_to_clean_commit(self, tmp_path):
        faults.configure("ckpt.save:error:count=1")
        before = _counter_value(
            "tpu_patterns_faults_retries_total", site="ckpt.save"
        )
        root = str(tmp_path / "ck")
        tree = _tree()
        ckpt.save(root, 1, tree)
        assert (
            _counter_value("tpu_patterns_faults_retries_total",
                           site="ckpt.save")
            == before + 1
        )
        assert ckpt.available_steps(root) == [1]
        assert not [
            n for n in os.listdir(root) if n.startswith(".tmp.")
        ]  # the failed attempt's tmp dir was re-prepared, then committed
        _assert_tree_equal(tree, ckpt.restore(root, _tree()))

    def test_restore_retries_transient_error_bit_identical(self, tmp_path):
        root = str(tmp_path / "ck")
        tree = _tree()
        ckpt.save(root, 1, tree)
        faults.configure("ckpt.restore:error:count=1")
        before = _counter_value(
            "tpu_patterns_faults_retries_total", site="ckpt.restore"
        )
        back = ckpt.restore(root, _tree())
        assert (
            _counter_value("tpu_patterns_faults_retries_total",
                           site="ckpt.restore")
            == before + 1
        )
        _assert_tree_equal(tree, back)

    def test_restore_missing_step_is_not_a_transient_fault(self, tmp_path):
        # absence is a state: an explicit never-committed step must raise
        # FileNotFoundError immediately — not retry, not Quarantined
        root = str(tmp_path / "ck")
        ckpt.save(root, 1, _tree())
        before = _counter_value(
            "tpu_patterns_faults_retries_total", site="ckpt.restore"
        )
        with pytest.raises(FileNotFoundError):
            ckpt.restore(root, _tree(), step=5)
        assert (
            _counter_value("tpu_patterns_faults_retries_total",
                           site="ckpt.restore")
            == before
        )

    def test_async_saver_retries_injected_error(self, tmp_path):
        faults.configure("ckpt.save:error:count=1")
        root = str(tmp_path / "ck")
        tree = _tree()
        with ckpt.AsyncSaver() as saver:
            saver.save(root, 1, tree)
        assert ckpt.available_steps(root) == [1]
        _assert_tree_equal(tree, ckpt.restore(root, _tree()))


@pytest.fixture(scope="module")
def mesh3d(devices):
    from jax.sharding import Mesh

    return Mesh(np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))


def _train(mesh, tmp_path, **kw):
    from tpu_patterns.core.results import ResultWriter
    from tpu_patterns.models.train_loop import TrainLoopConfig, train

    cfg = TrainLoopConfig(
        embed=64, heads=8, head_dim=8, seq=32, batch=4, steps=4,
        lr=1e-4, **kw,
    )
    jsonl = str(tmp_path / "train.jsonl")
    out = train(mesh, cfg, ResultWriter(jsonl_path=jsonl))
    with open(jsonl) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    return out, recs


class TestTrainStepSite:
    def test_nan_with_halt_policy_stops_with_failure_verdict(
        self, mesh3d, tmp_path
    ):
        faults.configure("train.step:nan:step=2")
        before = _counter_value(
            "tpu_patterns_train_nonfinite_total", optimizer="sgd"
        )
        # nonfinite="halt" default; every=1 so the 4-step run checks
        # (auto-thinned halt checks every 10th step + ckpt boundaries)
        out, recs = _train(mesh3d, tmp_path, nonfinite_every=1)
        assert not np.isfinite(out["loss"])
        assert (
            _counter_value("tpu_patterns_train_nonfinite_total",
                           optimizer="sgd")
            == before + 1
        )
        warn = [r for r in recs if r["mode"] == "nonfinite"]
        assert warn and warn[0]["metrics"]["step"] == 2.0
        final = recs[-1]
        assert final["verdict"] == "FAILURE"
        assert any("halted at step 2" in n for n in final["notes"])

    def test_nan_with_skip_step_policy_reverts_and_continues(
        self, mesh3d, tmp_path
    ):
        faults.configure("train.step:nan:step=2")
        before = _counter_value(
            "tpu_patterns_train_steps_skipped_total", optimizer="sgd"
        )
        out, recs = _train(mesh3d, tmp_path, nonfinite="skip-step")
        assert np.isfinite(out["loss"])  # the poisoned update was reverted
        assert (
            _counter_value("tpu_patterns_train_steps_skipped_total",
                           optimizer="sgd")
            == before + 1
        )
        assert recs[-1]["verdict"] == "SUCCESS"

    def test_unknown_policy_rejected(self, mesh3d, tmp_path):
        with pytest.raises(ValueError, match="nonfinite"):
            _train(mesh3d, tmp_path, nonfinite="wish-harder")

    def test_thinned_check_is_forced_before_checkpoint(
        self, mesh3d, tmp_path
    ):
        # NaN enters at step 1; the thinned check (every 4) would not
        # look until step 3 — but a checkpoint is due at step 2, and a
        # poisoned tree must NEVER be committed, so the ckpt-time forced
        # check halts first and the dir stays checkpoint-free
        from tpu_patterns import ckpt
        ckpt_dir = str(tmp_path / "ckpts")
        faults.configure("train.step:nan:step=1")
        out, recs = _train(
            mesh3d, tmp_path, nonfinite_every=4,
            ckpt_dir=ckpt_dir, ckpt_every=2, ckpt_async=False,
        )
        assert recs[-1]["verdict"] == "FAILURE"
        assert ckpt.latest_step(ckpt_dir) is None

    def test_skip_step_rejects_thinned_checks(self, mesh3d, tmp_path):
        # a late-detected blowup leaves no clean state to revert to
        with pytest.raises(ValueError, match="nonfinite_every"):
            _train(
                mesh3d, tmp_path, nonfinite="skip-step", nonfinite_every=2
            )


class TestServeSites:
    def _engine_bits(self, devices, n_blocks=13):
        from tpu_patterns.models.transformer import ModelConfig

        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, flat = _decoder_and_params(mesh, mcfg,
                                                n_blocks=n_blocks)
        return mesh, mcfg, dec, params, flat

    def test_prefill_transient_error_retries_ids_exact(self, devices):
        from tpu_patterns.serve import ServeEngine

        mesh, mcfg, dec, params, flat = self._engine_bits(devices)
        reqs = _trace(3, n_gen=3)
        want = ServeEngine(dec, params, slots=2).run(
            [dataclasses.replace(r) for r in reqs]
        )
        faults.configure("serve.prefill:error:count=1")
        before = _counter_value(
            "tpu_patterns_faults_retries_total", site="serve.prefill"
        )
        eng = ServeEngine(dec, params, slots=2,
                          retry_policy=_fast_policy())
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert got == want and not eng.failed
        assert (
            _counter_value("tpu_patterns_faults_retries_total",
                           site="serve.prefill")
            == before + 1
        )

    def test_prefill_deterministic_error_quarantines_admitted_rows(
        self, devices
    ):
        from tpu_patterns.serve import ServeEngine

        _, _, dec, params, _ = self._engine_bits(devices)
        faults.configure("serve.prefill:error:count=99")
        eng = ServeEngine(dec, params, slots=2,
                          retry_policy=_fast_policy())
        got = eng.run([dataclasses.replace(r) for r in _trace(3, n_gen=3)])
        assert got == {}
        assert sorted(eng.failed) == [0, 1, 2]  # per-request verdicts
        assert all("prefill" in v for v in eng.failed.values())
        # every block came home: quarantine must not leak pool blocks
        assert sorted(eng.free) == list(range(1, dec.layout.n_blocks))

    def test_step_deterministic_error_quarantines_active_set(self, devices):
        from tpu_patterns.serve import ServeEngine

        _, _, dec, params, _ = self._engine_bits(devices)
        faults.configure("serve.step:error:count=99")
        before = _counter_value("tpu_patterns_serve_quarantined_total")
        eng = ServeEngine(dec, params, slots=2,
                          retry_policy=_fast_policy())
        got = eng.run([dataclasses.replace(r) for r in _trace(2, n_gen=3)])
        assert got == {} and sorted(eng.failed) == [0, 1]
        assert (
            _counter_value("tpu_patterns_serve_quarantined_total")
            == before + 2
        )
        assert sorted(eng.free) == list(range(1, dec.layout.n_blocks))

    def test_verify_transient_error_retries_ids_exact(self, devices):
        # the speculative wide step has its own site: a transient error
        # retries under the serve policy and the committed stream stays
        # bit-identical to plain decode
        from tpu_patterns.serve import ServeEngine

        _, _, dec, params, _ = self._engine_bits(devices)
        reqs = _trace(3, n_gen=4)
        want = ServeEngine(dec, params, slots=2).run(
            [dataclasses.replace(r) for r in reqs]
        )
        faults.configure("serve.verify:error:count=1")
        before = _counter_value(
            "tpu_patterns_faults_retries_total", site="serve.verify"
        )
        eng = ServeEngine(dec, params, slots=2, spec_k=3,
                          retry_policy=_fast_policy())
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert got == want and not eng.failed
        assert (
            _counter_value("tpu_patterns_faults_retries_total",
                           site="serve.verify")
            == before + 1
        )

    def test_verify_deterministic_error_quarantines_and_balances_refs(
        self, devices
    ):
        # chaos-smoke's contract, in process: a deterministic verify
        # failure under sharing + speculation quarantines the rows (no
        # request lost) and the shared blocks' refcounts still balance
        from tpu_patterns.serve import ServeEngine

        _, _, dec, params, _ = self._engine_bits(devices, n_blocks=17)
        rng = np.random.RandomState(5)
        shared = rng.randint(0, 64, 16).tolist()
        reqs = [
            Request(rid=i,
                    tokens=shared + rng.randint(0, 64, 3).tolist(),
                    n_gen=4)
            for i in range(3)
        ]
        faults.configure("serve.verify:error:count=99")
        eng = ServeEngine(dec, params, slots=3, prefix_share=True,
                          spec_k=3, retry_policy=_fast_policy())
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert got == {}
        assert sorted(eng.failed) == [0, 1, 2]  # nothing silently lost
        assert all("after retries" in v for v in eng.failed.values())
        # refcounts balanced: every shared block came home exactly once
        assert eng.leaked_blocks() == 0 and not eng.ref
        assert sorted(eng.free) == list(range(1, dec.layout.n_blocks))
        assert len(eng.index) == 0

    def test_preempt_snapshots_and_resume_is_bit_identical(
        self, devices, tmp_path
    ):
        # the tentpole gate, in-process: SIGTERM mid-serve -> finish the
        # step, snapshot through ckpt atomic commit; a fresh engine
        # restores and the merged ids are bit-identical to an
        # uninterrupted run of the same trace
        from tpu_patterns.serve import ServeEngine

        _, _, dec, params, _ = self._engine_bits(devices, n_blocks=17)
        reqs = _trace(5, n_gen=4)
        want = ServeEngine(dec, params, slots=2).run(
            [dataclasses.replace(r) for r in reqs]
        )
        snap = str(tmp_path / "snap")
        fp = {"cfg": "test"}
        faults.configure("serve.step:preempt:after=2:count=1")
        before = _counter_value("tpu_patterns_serve_preemptions_total")
        eng = ServeEngine(dec, params, slots=2, snapshot_dir=snap,
                          fingerprint=fp)
        partial = eng.run([dataclasses.replace(r) for r in reqs])
        assert eng.preempted_at is not None
        assert len(partial) < len(reqs)  # it really stopped mid-trace
        assert (
            _counter_value("tpu_patterns_serve_preemptions_total")
            == before + 1
        )
        assert ckpt.latest_step(snap) == eng.preempted_at

        faults.configure("")
        eng2 = ServeEngine(dec, params, slots=2, snapshot_dir=snap,
                           fingerprint=fp)
        assert eng2.restore_snapshot() == eng.preempted_at
        got = eng2.run([])
        assert got == want  # bit-identical, including pre-preempt rows

    def test_step_outer_span_covers_the_injected_sleep(
        self, devices, tmp_path
    ):
        """The PR 9 perfwatch blind spot, closed: ``serve.step`` opens
        AFTER the fault-injection site inside the step, so an injected
        sleep (or retry backoff) was invisible to span summaries.
        ``serve.step_outer`` wraps inject + retries — under a 50ms
        injected sleep the outer total must exceed the inner by it."""
        from tpu_patterns.serve import ServeEngine

        _, _, dec, params, _ = self._engine_bits(devices)
        obs.flight_recorder().clear()
        faults.configure("serve.step:sleep:delay_s=0.05:count=1")
        eng = ServeEngine(dec, params, slots=2,
                          retry_policy=_fast_policy())
        out = eng.run([dataclasses.replace(r) for r in _trace(2, n_gen=3)])
        assert out and not eng.failed  # sleep delays, never fails
        path = obs.dump(str(tmp_path / "spans.jsonl"))
        inner = outer = 0
        for ln in open(path):
            e = json.loads(ln)
            if e.get("name") == "serve.step":
                inner += e["dur_ns"]
            elif e.get("name") == "serve.step_outer":
                outer += e["dur_ns"]
        assert inner > 0 and outer > 0
        # both series export; the injected 50ms lands ONLY in the outer
        assert outer >= inner + 40_000_000

    def test_cost_book_site_fires_and_fails_open(self, devices):
        """``obs.cost_book`` faults skip the booking whole and never
        touch the serve path: the run completes bit-identical and the
        book's internal identities stay closed (totals and shares are
        skipped together)."""
        from tpu_patterns.serve import ServeEngine

        _, _, dec, params, _ = self._engine_bits(devices)
        reqs = _trace(3, n_gen=3)
        want = ServeEngine(dec, params, slots=2).run(
            [dataclasses.replace(r) for r in reqs]
        )
        before = _counter_value(
            "tpu_patterns_faults_injected_total",
            site="obs.cost_book", action="error",
        )
        faults.configure("obs.cost_book:error:count=3")
        eng = ServeEngine(dec, params, slots=2)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert got == want and not eng.failed  # serving untouched
        assert _counter_value(
            "tpu_patterns_faults_injected_total",
            site="obs.cost_book", action="error",
        ) == before + 3
        snap = eng.cost.snapshot()
        assert snap["decode_identity_ok"]
        assert snap["prefill_identity_ok"]
        assert snap["conservation_ok"]

    def test_resume_rejects_mismatched_fingerprint(self, devices, tmp_path):
        from tpu_patterns.serve import ServeEngine

        _, _, dec, params, _ = self._engine_bits(devices)
        snap = str(tmp_path / "snap")
        faults.configure("serve.step:preempt:count=1")
        eng = ServeEngine(dec, params, slots=2, snapshot_dir=snap,
                          fingerprint={"gen": "6"})
        eng.run([dataclasses.replace(r) for r in _trace(2, n_gen=3)])
        assert eng.preempted_at is not None
        faults.configure("")
        other = ServeEngine(dec, params, slots=2, snapshot_dir=snap,
                            fingerprint={"gen": "9"})
        with pytest.raises(ValueError, match="different config"):
            other.restore_snapshot()
