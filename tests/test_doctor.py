"""Runtime health probes (core/doctor.py): layer classification,
hang containment, healthy-path metrics."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from tpu_patterns.core.doctor import (
    DoctorConfig,
    _probe,
    record_watch_poll,
    run_doctor,
)
from tpu_patterns.core.results import Record, ResultWriter, Verdict

ROOT = Path(__file__).resolve().parent.parent


class TestProbe:
    def test_hang_is_killed_and_classified(self):
        out = _probe("import time; time.sleep(3600)", timeout=2)
        assert not out["ok"]
        assert "hang" in out["error"]
        assert out["elapsed_s"] < 10

    def test_crash_is_classified_with_stderr_tail(self):
        out = _probe("raise RuntimeError('boom')", timeout=10)
        assert not out["ok"]
        assert "rc=1" in out["error"] and "boom" in out["error"]

    def test_garbage_output_is_an_error(self):
        out = _probe("print('not json')", timeout=10)
        assert not out["ok"]
        assert "parseable" in out["error"]

    def test_last_json_line_wins(self):
        out = _probe(
            "print('chatter'); print('{\"x\": 1}')", timeout=10
        )
        assert out["ok"] and out["x"] == 1


class TestRunDoctor:
    def test_healthy_cpu_backend(self, monkeypatch, tmp_path):
        # pin the probe children to cpu unconditionally and without
        # leaking into later tests
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        monkeypatch.delenv("TPU_PATTERNS_PLATFORM", raising=False)
        # hermetic watchdog probe: ambient hang dumps under the default
        # run dir (a previous run's live diagnosis) must not flip this
        # test's healthy verdict to WARNING
        from tpu_patterns import obs

        obs.configure(str(tmp_path))
        try:
            writer = ResultWriter()
            (rec,) = run_doctor(DoctorConfig(probe_timeout=120), writer)
        finally:
            obs.configure(None)
        assert rec.verdict.value == "SUCCESS", rec.notes
        assert rec.metrics["backend_init_ok"] == 1.0
        assert rec.metrics["tiny_op_ok"] == 1.0
        assert rec.metrics["deep_compute_ok"] == 1.0
        assert rec.metrics["native_ffi_ok"] == 1.0
        assert rec.metrics["native_loader_ok"] == 1.0
        assert rec.metrics["watchdog_ok"] == 1.0
        assert rec.metrics["tiny_op_compile_s"] >= 0

    def test_warm_worker_probe_opt_in(self, monkeypatch, tmp_path):
        # --workers true certifies the sweep engine's fast path: worker
        # spawns, backend-warms, answers a ping — and its timings become
        # doctor metrics
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        monkeypatch.delenv("TPU_PATTERNS_PLATFORM", raising=False)
        from tpu_patterns import obs

        obs.configure(str(tmp_path))
        try:
            (rec,) = run_doctor(
                DoctorConfig(probe_timeout=240, deep=False, workers=True),
                ResultWriter(),
            )
        finally:
            obs.configure(None)
        assert rec.metrics["warm_worker_ok"] == 1.0, rec.notes
        assert rec.metrics["warm_worker_spawn_s"] > 0
        assert rec.metrics["warm_worker_ping_ms"] >= 0

    def test_worker_probe_absent_by_default(self, monkeypatch, tmp_path):
        # the default doctor stays fast: no worker spawn, no metric row
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        monkeypatch.delenv("TPU_PATTERNS_PLATFORM", raising=False)
        from tpu_patterns import obs

        obs.configure(str(tmp_path))
        try:
            (rec,) = run_doctor(
                DoctorConfig(probe_timeout=120, deep=False), ResultWriter()
            )
        finally:
            obs.configure(None)
        assert "warm_worker_ok" not in rec.metrics

    def test_broken_backend_names_the_layer_and_skips_the_rest(self):
        # a bogus platform kills the first probe child fast; the doctor
        # must name backend_init and not waste deadlines on later layers
        env = {
            k: v
            for k, v in os.environ.items()
            if k not in ("PYTHONPATH", "TPU_PATTERNS_PLATFORM")
        }
        env["PYTHONPATH"] = str(ROOT)
        env["JAX_PLATFORMS"] = "no_such_platform"
        proc = subprocess.run(
            [
                sys.executable, "-m", "tpu_patterns", "doctor",
                "--probe_timeout", "60",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=200,
            cwd=ROOT,
        )
        assert proc.returncode != 0  # FAILURE verdict -> nonzero exit
        out = proc.stdout + proc.stderr
        assert "backend_init" in out
        assert "skipped" in out  # deep_compute not attempted


def _rec(failing: dict | None = None) -> Record:
    """A doctor-shaped Record: failing = {layer: 0.0} metrics."""
    metrics = {"backend_init_ok": 1.0, "tiny_op_ok": 1.0}
    if failing:
        metrics.update(failing)
    return Record(
        pattern="doctor",
        mode="down" if failing else "cpu",
        metrics=metrics,
        verdict=Verdict.FAILURE if failing else Verdict.SUCCESS,
    )


class TestWatchMode:
    """Episode coalescing (VERDICT weak #7): consecutive failing polls
    are ONE open/close entry, not a line (and a commit) per poll."""

    def test_consecutive_failures_coalesce(self, tmp_path):
        path = str(tmp_path / "watch.jsonl")
        fail = _rec({"backend_init_ok": 0.0})
        assert record_watch_poll(path, fail) == "opened"
        assert record_watch_poll(path, fail) == "extended"
        assert record_watch_poll(path, fail) == "extended"
        lines = open(path).read().strip().splitlines()
        assert len(lines) == 1  # three polls, ONE entry
        ep = json.loads(lines[0])
        assert ep["pattern"] == "doctor_episode"
        assert ep["mode"] == "backend_init"
        assert ep["metrics"]["polls"] == 3.0
        assert ep["metrics"]["open"] == 1.0
        assert ep["metrics"]["last_ts"] >= ep["metrics"]["opened_ts"]

    def test_recovery_closes_the_episode(self, tmp_path):
        path = str(tmp_path / "watch.jsonl")
        record_watch_poll(path, _rec({"backend_init_ok": 0.0}))
        assert record_watch_poll(path, _rec()) == "closed"
        lines = [json.loads(ln) for ln in open(path)]
        assert len(lines) == 2  # closed episode + the recovery record
        assert lines[0]["metrics"]["open"] == 0.0
        assert "closed_ts" in lines[0]["metrics"]
        assert lines[1]["pattern"] == "doctor"

    def test_signature_change_opens_a_new_episode(self, tmp_path):
        path = str(tmp_path / "watch.jsonl")
        record_watch_poll(path, _rec({"backend_init_ok": 0.0}))
        assert (
            record_watch_poll(path, _rec({"deep_compute_ok": 0.0}))
            == "opened"
        )
        lines = [json.loads(ln) for ln in open(path)]
        assert len(lines) == 2
        assert lines[0]["metrics"]["open"] == 0.0  # old one closed
        assert lines[1]["mode"] == "deep_compute"
        assert lines[1]["metrics"]["open"] == 1.0

    def test_healthy_polls_append_plain_records(self, tmp_path):
        path = str(tmp_path / "watch.jsonl")
        assert record_watch_poll(path, _rec()) == "recorded"
        assert record_watch_poll(path, _rec()) == "recorded"
        assert len(open(path).readlines()) == 2

    def test_episode_log_parses_as_records(self, tmp_path):
        from tpu_patterns.core.results import parse_log

        path = str(tmp_path / "watch.jsonl")
        record_watch_poll(path, _rec({"backend_init_ok": 0.0}))
        record_watch_poll(path, _rec({"backend_init_ok": 0.0}))
        record_watch_poll(path, _rec())
        recs = parse_log(open(path).readlines())
        assert [r.pattern for r in recs] == ["doctor_episode", "doctor"]
        assert recs[0].verdict is Verdict.FAILURE


class TestWatchdogProbe:
    """The obs watchdog's hang dumps become a doctor layer: healthy
    runtime + recent dump -> WARNING (read the dump before trusting an
    unattended run); no dumps -> the probe is silent."""

    @pytest.fixture
    def fast_doctor(self, monkeypatch):
        # probe children + native builds are not what this tier tests
        import tpu_patterns.core.doctor as doctor_mod

        monkeypatch.setattr(
            doctor_mod,
            "_probe",
            lambda script, timeout: {"ok": True, "elapsed_s": 0.0},
        )
        from tpu_patterns.interop import native
        from tpu_patterns.io import loader as io_loader

        monkeypatch.setattr(native, "available", lambda: True)
        monkeypatch.setattr(io_loader, "native_available", lambda: True)
        return doctor_mod

    def test_recent_dump_warns(self, fast_doctor, tmp_path):
        from tpu_patterns import obs

        obs.configure(str(tmp_path))
        (tmp_path / "hang_comm.fake_1.jsonl").write_text(
            '{"kind": "meta"}\n'
        )
        try:
            (rec,) = run_doctor(DoctorConfig(), ResultWriter())
        finally:
            obs.configure(None)
        assert rec.verdict is Verdict.WARNING
        assert rec.metrics["watchdog_recent_dumps"] == 1.0
        assert any("hang_comm.fake_1" in n for n in rec.notes)

    def test_stale_dump_is_ignored(self, fast_doctor, tmp_path):
        from tpu_patterns import obs

        obs.configure(str(tmp_path))
        p = tmp_path / "hang_old_1.jsonl"
        p.write_text('{"kind": "meta"}\n')
        old = p.stat().st_mtime - 7200
        os.utime(p, (old, old))
        try:
            (rec,) = run_doctor(DoctorConfig(), ResultWriter())
        finally:
            obs.configure(None)
        assert rec.verdict is Verdict.SUCCESS
        assert rec.metrics["watchdog_recent_dumps"] == 0.0
