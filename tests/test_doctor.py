"""Runtime health probes (core/doctor.py): layer classification,
hang containment, healthy-path metrics."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

from tpu_patterns.core.doctor import DoctorConfig, _probe, run_doctor
from tpu_patterns.core.results import ResultWriter

ROOT = Path(__file__).resolve().parent.parent


class TestProbe:
    def test_hang_is_killed_and_classified(self):
        out = _probe("import time; time.sleep(3600)", timeout=2)
        assert not out["ok"]
        assert "hang" in out["error"]
        assert out["elapsed_s"] < 10

    def test_crash_is_classified_with_stderr_tail(self):
        out = _probe("raise RuntimeError('boom')", timeout=10)
        assert not out["ok"]
        assert "rc=1" in out["error"] and "boom" in out["error"]

    def test_garbage_output_is_an_error(self):
        out = _probe("print('not json')", timeout=10)
        assert not out["ok"]
        assert "parseable" in out["error"]

    def test_last_json_line_wins(self):
        out = _probe(
            "print('chatter'); print('{\"x\": 1}')", timeout=10
        )
        assert out["ok"] and out["x"] == 1


class TestRunDoctor:
    def test_healthy_cpu_backend(self, monkeypatch):
        # pin the probe children to cpu unconditionally and without
        # leaking into later tests
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        monkeypatch.delenv("TPU_PATTERNS_PLATFORM", raising=False)
        writer = ResultWriter()
        (rec,) = run_doctor(DoctorConfig(probe_timeout=120), writer)
        assert rec.verdict.value == "SUCCESS", rec.notes
        assert rec.metrics["backend_init_ok"] == 1.0
        assert rec.metrics["tiny_op_ok"] == 1.0
        assert rec.metrics["deep_compute_ok"] == 1.0
        assert rec.metrics["native_ffi_ok"] == 1.0
        assert rec.metrics["native_loader_ok"] == 1.0
        assert rec.metrics["tiny_op_compile_s"] >= 0

    def test_broken_backend_names_the_layer_and_skips_the_rest(self):
        # a bogus platform kills the first probe child fast; the doctor
        # must name backend_init and not waste deadlines on later layers
        env = {
            k: v
            for k, v in os.environ.items()
            if k not in ("PYTHONPATH", "TPU_PATTERNS_PLATFORM")
        }
        env["PYTHONPATH"] = str(ROOT)
        env["JAX_PLATFORMS"] = "no_such_platform"
        proc = subprocess.run(
            [
                sys.executable, "-m", "tpu_patterns", "doctor",
                "--probe_timeout", "60",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=200,
            cwd=ROOT,
        )
        assert proc.returncode != 0  # FAILURE verdict -> nonzero exit
        out = proc.stdout + proc.stderr
        assert "backend_init" in out
        assert "skipped" in out  # deep_compute not attempted
