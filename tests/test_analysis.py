"""graftlint (tpu_patterns/analysis/): per-rule firing/clean/suppressed
fixtures, suppression justification contract, fingerprint stability, the
baseline ratchet round-trip, Record emission, the shared walker, and the
Tier-B trace checks (donation mismatch, callback/f64 jaxpr scan, bucket
discipline) — plus the repo-level gates the CI lint job runs."""

import ast
import io
import json
import os
import subprocess
import sys
import textwrap

import pytest

from tpu_patterns.analysis import astlint, engine, walker
from tpu_patterns.analysis import findings as fnd


def _sf(code: str, rel: str = "tpu_patterns/fake/mod.py"):
    code = textwrap.dedent(code)
    return astlint.SourceFile(
        path="/" + rel,
        rel=rel,
        text=code,
        lines=code.splitlines(),
        tree=ast.parse(code),
    )


def _run(rule, *sfs):
    """Rule + suppression pipeline over in-memory sources."""
    out = rule.run(list(sfs))
    fnd.apply_suppressions(
        out, {sf.rel: fnd.scan_allows(sf.lines) for sf in sfs}
    )
    return out


def _live(findings):
    return [f for f in findings if not f.suppressed]


ALLOW = "# graftlint: allow[{rule}] -- fixture says so"


class TestClockDiscipline:
    RULE = astlint.ClockDiscipline

    def test_fires(self):
        fs = _run(self.RULE(), _sf("""
            import time
            t = time.time()
            d = time.perf_counter_ns()
        """))
        assert len(_live(fs)) == 2
        assert all(f.rule == "clock-discipline" for f in fs)

    def test_from_import_fires(self):
        fs = _run(self.RULE(), _sf("from time import perf_counter\n"))
        assert len(_live(fs)) == 1

    def test_clean(self):
        fs = _run(self.RULE(), _sf("""
            from tpu_patterns.core.timing import clock_ns
            import time
            t = clock_ns()
            time.sleep(0)  # sleep is another rule's business
        """))
        assert fs == []

    def test_timing_home_allowed(self):
        fs = _run(self.RULE(), _sf(
            "import time\nt = time.time()\n",
            rel="tpu_patterns/core/timing.py",
        ))
        assert fs == []

    def test_suppressed(self):
        fs = _run(self.RULE(), _sf(f"""
            import time
            {ALLOW.format(rule="clock-discipline")}
            t = time.time()
        """))
        assert len(fs) == 1 and fs[0].suppressed
        assert fs[0].justification == "fixture says so"


class TestHostSyncInHotPath:
    def _rule(self):
        return astlint.HostSyncInHotPath(hot_roots={
            "tpu_patterns/fake/mod.py": frozenset({"Engine._step"}),
        })

    def test_fires_including_reachable_helper(self):
        fs = _run(self._rule(), _sf("""
            import numpy as np

            class Engine:
                def _step(self):
                    x = np.asarray(self.tok)
                    self._helper()

                def _helper(self):
                    return self.y.item()
        """))
        live = _live(fs)
        assert len(live) == 2  # np.asarray in root, .item() via call graph
        assert {"_step" in f.message or "_helper" in f.message
                for f in live} == {True}

    def test_clean_outside_hot_path(self):
        fs = _run(self._rule(), _sf("""
            import numpy as np

            class Engine:
                def _step(self):
                    return self.pool

                def report(self):  # not reachable from the loop roots
                    return np.asarray(self.stats)
        """))
        assert fs == []

    def test_suppressed(self):
        fs = _run(self._rule(), _sf(f"""
            import jax

            class Engine:
                def _step(self):
                    {ALLOW.format(rule="host-sync-in-hot-path")}
                    return jax.device_get(self.tok)
        """))
        assert len(fs) == 1 and fs[0].suppressed


class TestUnseededRandomness:
    RULE = astlint.UnseededRandomness

    def test_fires(self):
        fs = _run(self.RULE(), _sf("""
            import random
            import numpy as np
            a = random.random()
            random.seed(4)
            b = np.random.rand(3)
        """))
        assert len(_live(fs)) == 3

    def test_clean_seeded_objects(self):
        fs = _run(self.RULE(), _sf("""
            import random
            import numpy as np
            rng = random.Random(7)
            a = rng.random()
            g = np.random.default_rng(7)
            st = np.random.RandomState(3)
        """))
        assert fs == []

    def test_suppressed(self):
        fs = _run(self.RULE(), _sf(f"""
            import random
            {ALLOW.format(rule="unseeded-randomness")}
            a = random.random()
        """))
        assert len(fs) == 1 and fs[0].suppressed


class TestFaultSiteRegistry:
    REG = """
        KNOWN_SITES = frozenset({"a.save", "b.run"})
    """

    def _rule(self, reg_rel="tpu_patterns/fake/reg.py"):
        r = astlint.FaultSiteRegistry()
        r.REGISTRY_FILE = reg_rel
        return r

    def test_unknown_and_orphan_sites_fire(self):
        reg = _sf(self.REG, rel="tpu_patterns/fake/reg.py")
        call = _sf("""
            from tpu_patterns import faults
            faults.inject("a.save")
            faults.inject("zz.typo")
        """)
        fs = _live(_run(self._rule(), reg, call))
        msgs = " | ".join(f.message for f in fs)
        assert len(fs) == 2
        assert "zz.typo" in msgs  # unregistered call site
        assert "b.run" in msgs  # registered but never called

    def test_non_literal_site_fires(self):
        reg = _sf(self.REG, rel="tpu_patterns/fake/reg.py")
        call = _sf("""
            from tpu_patterns import faults
            site = "a.save"
            faults.inject(site)
            faults.inject("b.run")
        """)
        fs = _live(_run(self._rule(), reg, call))
        assert len(fs) == 2  # non-literal + a.save now orphaned
        assert any("string literal" in f.message for f in fs)

    def test_clean(self):
        reg = _sf(self.REG, rel="tpu_patterns/fake/reg.py")
        call = _sf("""
            from tpu_patterns import faults
            faults.inject("a.save")
            faults.inject("b.run", step=3)
        """)
        assert _run(self._rule(), reg, call) == []

    def test_suppressed(self):
        reg = _sf(self.REG, rel="tpu_patterns/fake/reg.py")
        call = _sf(f"""
            from tpu_patterns import faults
            faults.inject("b.run")
            {ALLOW.format(rule="fault-site-registry")}
            faults.inject("zz.typo")
        """)
        fs = _run(self._rule(), reg, call)
        assert len(fs) == 2  # typo call suppressed; a.save orphan live
        assert any(f.suppressed for f in fs)
        assert len(_live(fs)) == 1

    def test_missing_registry_is_silent(self):
        # partial corpora (fixture dirs) must not fail the rule
        assert _run(self._rule(), _sf("x = 1\n")) == []


class TestMetricNaming:
    RULE = astlint.MetricNaming

    def test_fires_on_prefix_suffix_and_label(self):
        fs = _live(_run(self.RULE(), _sf("""
            from tpu_patterns import obs
            obs.counter("steps_total").inc()
            obs.counter("tpu_patterns_steps").inc()
            obs.gauge("tpu_patterns_loss", flavor="x").set(1.0)
        """)))
        msgs = " | ".join(f.message for f in fs)
        assert len(fs) == 3
        assert "prefix" in msgs and "_total" in msgs and "flavor" in msgs

    def test_clean(self):
        fs = _run(self.RULE(), _sf("""
            from tpu_patterns import obs
            obs.counter("tpu_patterns_steps_total", site="x").inc()
            obs.gauge("tpu_patterns_loss", mode="eval").set(1.0)
            obs.histogram("tpu_patterns_step_ms", help="h").observe(2)
            name = compute()
            obs.counter(name).inc()  # dynamic replay: not checkable
        """))
        assert fs == []

    def test_registry_impl_excluded(self):
        fs = _run(self.RULE(), _sf(
            "self.counter(\"whatever\", weird_label=1)\n",
            rel="tpu_patterns/obs/metrics.py",
        ))
        assert fs == []

    def test_suppressed(self):
        fs = _run(self.RULE(), _sf(f"""
            from tpu_patterns import obs
            {ALLOW.format(rule="metric-naming")}
            obs.counter("legacy_name").inc()
        """))
        assert len(fs) == 1 and fs[0].suppressed


class TestBareExcept:
    RULE = astlint.BareExceptInRuntime

    def test_fires(self):
        fs = _live(_run(self.RULE(), _sf("""
            try:
                work()
            except:
                pass
            try:
                work()
            except Exception:
                pass
        """)))
        assert len(fs) == 2

    def test_clean(self):
        fs = _run(self.RULE(), _sf("""
            import logging
            try:
                work()
            except OSError:
                pass
            try:
                work()
            except Exception:
                logging.exception("leaves a trail")
        """))
        assert fs == []

    def test_suppressed(self):
        fs = _run(self.RULE(), _sf(f"""
            try:
                work()
            {ALLOW.format(rule="bare-except-in-runtime")}
            except Exception:
                pass
        """))
        assert len(fs) == 1 and fs[0].suppressed


class TestSleepOutsideBackoff:
    RULE = astlint.SleepOutsideBackoff

    def test_fires(self):
        fs = _live(_run(self.RULE(), _sf("""
            import time
            time.sleep(5)
        """)))
        assert len(fs) == 1

    def test_from_import_fires(self):
        fs = _live(_run(self.RULE(), _sf("from time import sleep\n")))
        assert len(fs) == 1

    def test_backoff_home_allowed(self):
        fs = _run(self.RULE(), _sf(
            "import time\ntime.sleep(1)\n",
            rel="tpu_patterns/faults/retry.py",
        ))
        assert fs == []

    def test_suppressed(self):
        fs = _run(self.RULE(), _sf(f"""
            import time
            {ALLOW.format(rule="sleep-outside-backoff")}
            time.sleep(5)
        """))
        assert len(fs) == 1 and fs[0].suppressed


class TestLockDiscipline:
    RULE = astlint.LockDiscipline

    CODE = """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # graftlint: guarded-by[_lock]
                self.count = 0  # graftlint: guarded-by[_lock]

            def good(self, x):
                with self._lock:
                    self._items.append(x)
                    self.count += 1

            def bad(self, x):
                self._items.append(x)
                self.count += 1
                del self._items[0]
    """

    def test_fires_outside_lock_only(self):
        fs = _live(_run(self.RULE(), _sf(self.CODE)))
        assert len(fs) == 3
        assert all("bad" in f.message for f in fs)

    def test_init_assignment_exempt(self):
        # the declaring method builds the object pre-publication
        fs = _run(self.RULE(), _sf("""
            import threading

            class Pool:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # graftlint: guarded-by[_lock]
                    self._items.append(1)
        """))
        assert fs == []

    def test_unannotated_class_is_silent(self):
        fs = _run(self.RULE(), _sf("""
            class Pool:
                def __init__(self):
                    self._items = []

                def bad(self, x):
                    self._items.append(x)
        """))
        assert fs == []

    def test_suppressed(self):
        fs = _run(self.RULE(), _sf(f"""
            import threading

            class Pool:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # graftlint: guarded-by[_lock]

                def hot(self, x):
                    {ALLOW.format(rule="lock-discipline")}
                    self._items.append(x)
        """))
        assert len(fs) == 1 and fs[0].suppressed


class TestSuppressions:
    def test_allow_without_justification_is_ignored(self):
        fs = _run(astlint.SleepOutsideBackoff(), _sf("""
            import time
            # graftlint: allow[sleep-outside-backoff]
            time.sleep(5)
        """))
        assert len(fs) == 1
        assert not fs[0].suppressed  # stays live: the gate still fails
        assert "no '-- justification'" in fs[0].message

    def test_allow_for_other_rule_does_not_cover(self):
        fs = _run(astlint.SleepOutsideBackoff(), _sf("""
            import time
            # graftlint: allow[clock-discipline] -- wrong rule named
            time.sleep(5)
        """))
        assert len(_live(fs)) == 1

    def test_multi_rule_allow(self):
        allows = fnd.scan_allows([
            "# graftlint: allow[rule-a,rule-b] -- shared reason",
            "x = 1",
        ])
        assert allows[2].rules == frozenset({"rule-a", "rule-b"})
        assert allows[2].justification == "shared reason"


class TestFingerprints:
    def test_line_number_free_and_duplicate_stable(self):
        f1 = fnd.Finding("r", "p.py", 10, "m", snippet="time.sleep(1)")
        f2 = fnd.Finding("r", "p.py", 99, "m", snippet="time.sleep(1)")
        f3 = fnd.Finding("r", "p.py", 120, "m", snippet="time.sleep(1)")
        fnd.fingerprint_findings([f1])
        fps = [f.fingerprint for f in fnd.fingerprint_findings([f2, f3])]
        # first occurrence keeps its fingerprint wherever it moves...
        assert f1.fingerprint == fps[0]
        # ...and the second identical violation stays distinct
        assert fps[0] != fps[1]


@pytest.fixture
def corpus(tmp_path):
    """A fake package root with one violation, plus a baseline path."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("import time\ntime.sleep(3)\n")
    (pkg / "clean.py").write_text("x = 1\n")
    return pkg, str(tmp_path / "baseline.json")


class TestRatchet:
    def test_round_trip(self, corpus):
        pkg, bl = corpus
        rep = engine.run_lint(tier="a", root=str(pkg), baseline_path=bl)
        assert rep.exit_code == 1 and len(rep.new) == 1

        # pin the debt -> same run now exits 0, findings ride as baselined
        fnd.save_baseline(bl, rep.new, {})
        rep2 = engine.run_lint(tier="a", root=str(pkg), baseline_path=bl)
        assert rep2.exit_code == 0
        assert len(rep2.baselined) == 1 and rep2.new == []

        # a NEW violation still fails: the ratchet only tightens
        (pkg / "mod2.py").write_text("import time\ntime.sleep(9)\n")
        rep3 = engine.run_lint(tier="a", root=str(pkg), baseline_path=bl)
        assert rep3.exit_code == 1 and len(rep3.new) == 1
        assert "mod2" in rep3.new[0].path

        # fixing the pinned violation reports the stale entry
        (pkg / "mod.py").write_text("x = 2\n")
        (pkg / "mod2.py").write_text("y = 3\n")
        rep4 = engine.run_lint(tier="a", root=str(pkg), baseline_path=bl)
        assert rep4.exit_code == 0 and len(rep4.stale) == 1

    def test_justifications_survive_repin(self, corpus):
        pkg, bl = corpus
        rep = engine.run_lint(tier="a", root=str(pkg), baseline_path=bl)
        fnd.save_baseline(bl, rep.new, {})
        old = fnd.load_baseline(bl)
        fp = next(iter(old))
        old[fp]["justification"] = "known debt, tracked in #42"
        with open(bl, "w") as f:
            json.dump(
                {"version": fnd.BASELINE_VERSION,
                 "entries": list(old.values())}, f,
            )
        rep2 = engine.run_lint(tier="a", root=str(pkg), baseline_path=bl)
        fnd.save_baseline(bl, rep2.baselined, fnd.load_baseline(bl))
        assert (
            fnd.load_baseline(bl)[fp]["justification"]
            == "known debt, tracked in #42"
        )

    def test_partial_update_refused(self, corpus):
        pkg, bl = corpus
        with pytest.raises(ValueError, match="FULL run"):
            engine.run_lint(
                tier="a", root=str(pkg), baseline_path=bl,
                update_baseline=True,
            )

    def test_version_mismatch_fails_loudly(self, corpus):
        pkg, bl = corpus
        with open(bl, "w") as f:
            json.dump({"version": 99, "entries": []}, f)
        with pytest.raises(ValueError, match="version"):
            engine.run_lint(tier="a", root=str(pkg), baseline_path=bl)

    def test_unknown_rule_rejected(self, corpus):
        pkg, bl = corpus
        with pytest.raises(ValueError, match="unknown rule"):
            engine.run_lint(
                tier="a", root=str(pkg), baseline_path=bl,
                rules=["not-a-rule"],
            )

    def test_rules_tier_mismatch_rejected(self, corpus):
        # a known rule filtered out by --tier must not read as a clean
        # lint that checked nothing
        pkg, bl = corpus
        with pytest.raises(ValueError, match="no rule left to run"):
            engine.run_lint(
                tier="b", root=str(pkg), baseline_path=bl,
                rules=["clock-discipline"],
            )


class TestRecordsAndFormats:
    def _report(self, corpus):
        pkg, bl = corpus
        return engine.run_lint(tier="a", root=str(pkg), baseline_path=bl)

    def test_one_record_per_rule_with_verdicts(self, corpus):
        from tpu_patterns.core.results import ResultWriter, Verdict

        rep = self._report(corpus)
        stream = io.StringIO()
        writer = ResultWriter(stream=stream)
        engine.write_records(rep, writer)
        text = stream.getvalue()
        recs = [ln for ln in text.splitlines() if ln.startswith("## ")]
        ast_rules = {r.name for r in astlint.AST_RULES}
        assert len(recs) == len(ast_rules)
        assert "## sleep-outside-backoff | tierA | FAILURE" in text
        assert "## clock-discipline | tierA | SUCCESS" in text
        assert writer.exit_code == 1

    def test_lint_metrics_emitted(self, corpus):
        from tpu_patterns import obs
        from tpu_patterns.core.results import ResultWriter

        engine.write_records(
            self._report(corpus), ResultWriter(stream=io.StringIO())
        )
        prom = obs.metrics.default().to_prom_text()
        assert "tpu_patterns_lint_findings" in prom
        assert 'rule="sleep-outside-backoff"' in prom
        assert "tpu_patterns_lint_files_scanned" in prom

    def test_jsonl_format_is_machine_pure(self, corpus):
        rep = self._report(corpus)
        stream = io.StringIO()
        engine.emit(rep, fmt="jsonl", stream=stream)
        lines = [l for l in stream.getvalue().splitlines() if l]
        objs = [json.loads(l) for l in lines]
        assert objs and all("rule" in o and "status" in o for o in objs)
        assert any(o["status"] == "new" for o in objs)

    def test_github_format_annotates(self, corpus):
        rep = self._report(corpus)
        stream = io.StringIO()
        engine.emit(rep, fmt="github", stream=stream)
        text = stream.getvalue()
        assert "::error file=" in text and "sleep-outside-backoff" in text
        assert "::notice title=graftlint::" in text


class TestWalker:
    def test_shared_exclusions(self, tmp_path):
        (tmp_path / "ok.py").write_text("x = 1\n")
        for d in ("__pycache__", "build", "fixtures", "results", "docs"):
            (tmp_path / d).mkdir()
            (tmp_path / d / "no.py").write_text("x = 1\n")
        (tmp_path / "gen_pb2.py").write_text("x = 1\n")
        (tmp_path / "marked.py").write_text("# @generated by tool\nx = 1\n")
        (tmp_path / "note.txt").write_text("not python\n")
        got = [os.path.basename(p)
               for p in walker.iter_source_files(str(tmp_path))]
        assert got == ["ok.py"]

    def test_exclusion_list_pinned(self):
        # the ONE exclusion policy every source-level tool shares:
        # results/ and docs/ archive .py snippets (banked artifacts,
        # doc excerpts) and fixture output dirs are machine-written —
        # a tool walking any of them lints files nobody maintains
        assert {
            "__pycache__", "build", "dist", "fixtures", "results",
            "docs", ".git", ".eggs", ".venv", "venv", "node_modules",
        } <= set(walker.EXCLUDED_DIRS)

    def test_repo_rooted_walk_skips_archives(self):
        # the gap this pins: a walk from the REPO root (not the package)
        # must not surface results/ or docs/ snippet files
        for p in walker.iter_source_files(walker.repo_root()):
            rel = os.path.relpath(p, walker.repo_root())
            top = rel.split(os.sep)[0]
            assert top not in ("results", "docs", "build"), rel

    def test_package_walk_skips_pycache(self):
        for p in walker.iter_source_files():
            assert "__pycache__" not in p and "/build/" not in p


class TestRepoGate:
    """The CI lint job's contract, pinned as tests."""

    def test_tier_a_clean_against_committed_baseline(self):
        rep = engine.run_lint(tier="a")
        assert rep.new == [], [
            f"{f.location()}: [{f.rule}] {f.message}" for f in rep.new
        ]

    def test_committed_baseline_entries_all_justified(self):
        bl = fnd.load_baseline(fnd.default_baseline_path())
        missing = [e for e in bl.values() if not e.get("justification")]
        assert missing == [], "baseline entries need a justification"

    def test_timing_shim_still_works(self):
        # deprecated exec shim: same exit contract as always, body is
        # now `tpu-patterns lint --rules clock-discipline --tier a`
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "scripts", "lint_timing.py")],
            capture_output=True, text=True, timeout=180,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "## clock-discipline | tierA | SUCCESS" in proc.stdout

    # NB: the CLI tests run in a SUBPROCESS on purpose — cli.main()
    # calls setup_jax(), which enables the persistent compilation cache
    # process-wide; doing that inside the shared 8-device test process
    # (this file runs alphabetically first) destabilizes later suites.

    def _cli(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "tpu_patterns", "lint", *args],
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )

    def test_cli_lint_tier_a(self):
        proc = self._cli("--tier", "a")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "## clock-discipline | tierA | SUCCESS" in proc.stdout

    def test_cli_strict_ignores_baseline(self, tmp_path):
        # the timing gate's mode: a violation pinned in a baseline must
        # STILL fail under --strict (a clock violation is never debt)
        bad = tmp_path / "pkg"
        bad.mkdir()
        (bad / "mod.py").write_text("import time\nt = time.time()\n")
        rep = engine.run_lint(
            tier="a", rules=["clock-discipline"], root=str(bad),
            baseline_path=str(tmp_path / "bl.json"),
        )
        fnd.save_baseline(str(tmp_path / "bl.json"), rep.new, {})
        # baselined: the ratcheted run passes...
        rep2 = engine.run_lint(
            tier="a", rules=["clock-discipline"], root=str(bad),
            baseline_path=str(tmp_path / "bl.json"),
        )
        assert rep2.exit_code == 0
        # ...the strict run (the shim/CI gate) still fails
        rep3 = engine.run_lint(
            tier="a", rules=["clock-discipline"], root=str(bad),
            baseline_path=str(tmp_path / "bl.json"), use_baseline=False,
        )
        assert rep3.exit_code == 1

    def test_cli_lint_unknown_rule_fails_loudly(self):
        proc = self._cli("--rules", "nope")
        assert proc.returncode != 0
        assert "unknown rule" in proc.stderr


class TestTraceChecks:
    """Tier B: the compiled-artifact checks can fire AND pass."""

    def test_donation_mismatch_fires(self):
        import jax
        import jax.numpy as jnp

        from tpu_patterns.analysis.tracelint import check_donation_takes

        x = jnp.zeros((64, 64), jnp.float32)
        undonated = jax.jit(lambda a: a + 1)
        fs = check_donation_takes(undonated, (x,), "fixture", "x.py")
        if fs == [] and check_donation_takes(
            jax.jit(lambda a: a + 1, donate_argnums=(0,)), (x,),
            "fixture", "x.py",
        ) == []:
            pytest.skip("backend exposes no memory-analysis API")
        assert len(fs) == 1 and fs[0].rule == "trace-donation"
        assert "aliases 0 bytes" in fs[0].message

    def test_donation_clean_when_declared_and_taken(self):
        import jax
        import jax.numpy as jnp

        from tpu_patterns.analysis.tracelint import check_donation_takes

        x = jnp.zeros((64, 64), jnp.float32)
        donated = jax.jit(lambda a: a + 1, donate_argnums=(0,))
        assert check_donation_takes(donated, (x,), "fixture", "x.py") == []

    def test_host_callback_fires(self):
        import jax
        import jax.numpy as jnp

        from tpu_patterns.analysis.tracelint import scan_jaxpr

        x = jnp.zeros((4,), jnp.float32)

        def g(a):
            return jax.pure_callback(
                lambda v: v, jax.ShapeDtypeStruct(a.shape, a.dtype), a
            )

        fs = scan_jaxpr(jax.jit(g), (x,), "fixture", "x.py")
        assert [f.rule for f in fs] == ["trace-host-callback"]

    def test_f64_upcast_fires_and_scan_recurses(self):
        import jax
        import jax.numpy as jnp

        from tpu_patterns.analysis.tracelint import scan_jaxpr

        x = jnp.zeros((4,), jnp.float32)
        old = jax.config.jax_enable_x64
        jax.config.update("jax_enable_x64", True)
        try:
            def g(a):  # upcast INSIDE a scan body: the walker must recurse
                def body(c, _):
                    return c + a.astype("float64").sum(), None

                return jax.lax.scan(body, 0.0, None, length=2)[0]

            fs = scan_jaxpr(jax.jit(g), (x,), "fixture", "x.py")
        finally:
            jax.config.update("jax_enable_x64", old)
        assert any(f.rule == "trace-f64-upcast" for f in fs)

    def test_clean_jitted_scan(self):
        import jax
        import jax.numpy as jnp

        from tpu_patterns.analysis.tracelint import scan_jaxpr

        x = jnp.zeros((4,), jnp.float32)
        f = jax.jit(
            lambda a: jax.lax.scan(
                lambda c, _: (c + 1.0, c), a, None, length=3
            )[0]
        )
        assert scan_jaxpr(f, (x,), "fixture", "x.py") == []

    def test_bucket_discipline_clean_and_fires(self, monkeypatch):
        from tpu_patterns.analysis import tracelint
        from tpu_patterns.serve import engine as serve_engine

        assert tracelint.trace_bucket_shapes() == []
        monkeypatch.setattr(
            serve_engine, "_bucket", lambda n, cap: min(n + 2, cap + 1)
        )
        fs = tracelint.trace_bucket_shapes()
        assert fs and all(f.rule == "trace-bucket-shapes" for f in fs)

    def test_crashed_check_is_a_finding(self, monkeypatch):
        from tpu_patterns.analysis import tracelint

        def boom():
            raise RuntimeError("verifier exploded")

        monkeypatch.setitem(
            tracelint.TRACE_CHECKS, "trace-bucket-shapes", boom
        )
        fs = tracelint.run_trace_checks(["trace-bucket-shapes"])
        assert len(fs) == 1
        assert "check crashed" in fs[0].message
        assert "verifier exploded" in fs[0].message

    def test_trace_findings_ride_the_baseline(self, tmp_path):
        # Tier-B debt is suppressed via the baseline (no source line to
        # annotate): a pinned trace finding stops gating
        f = fnd.Finding(
            "trace-donation", "tpu_patterns/models/transformer.py", 0,
            "m", tier="B",
        )
        fnd.fingerprint_findings([f])
        bl = str(tmp_path / "bl.json")
        fnd.save_baseline(bl, [f], {})
        assert f.fingerprint in fnd.load_baseline(bl)

    def test_repo_entry_points_pass_all_trace_checks(self):
        """The acceptance gate: both donation and purity hold for the
        real train/serve entry points on the CPU backend."""
        rep = engine.run_lint(tier="b")
        assert rep.new == [], [
            f"{f.location()}: [{f.rule}] {f.message}" for f in rep.new
        ]


class TestMultiLineSuppression:
    """Satellite: allow anchors cover whole logical statements, so a
    finding anchored at a multi-line statement's first physical line is
    covered by an allow riding any of its lines (or standing above a
    decorator chain)."""

    def test_trailing_allow_on_later_physical_line_covers_statement(self):
        fs = _run(astlint.ClockDiscipline(), _sf("""
            import time
            t = (
                time
                .time()  # graftlint: allow[clock-discipline] -- fixture says so
            )
        """))
        assert len(fs) == 1 and fs[0].suppressed

    def test_standalone_allow_covers_implicit_continuation(self):
        # the finding anchors INSIDE the bracketed continuation (line 2
        # of the statement); the allow above the statement still covers
        fs = _run(astlint.SleepOutsideBackoff(), _sf("""
            import time
            # graftlint: allow[sleep-outside-backoff] -- fixture says so
            handlers = [
                time.sleep,
            ]
        """))
        assert len(fs) == 1 and fs[0].suppressed

    def test_standalone_allow_covers_decorator_chain_and_def(self):
        allows = fnd.scan_allows([
            "# graftlint: allow[some-rule] -- fixture says so",
            "@deco(",
            "    1,",
            ")",
            "@other",
            "def f():",
            "    pass",
        ])
        assert 6 in allows  # the def header itself
        assert allows[6].rules == frozenset({"some-rule"})
        assert 7 not in allows  # the body is NOT blanket-covered

    def test_decorator_chain_survives_interleaved_comments_and_blanks(self):
        # blank and comment lines interleave legally in a decorator
        # chain; the walk must still reach the def header
        allows = fnd.scan_allows([
            "# graftlint: allow[some-rule] -- fixture says so",
            "@deco",
            "# explanatory comment",
            "",
            "def f():",
            "    pass",
        ])
        assert 5 in allows  # the def header, past the comment + blank
        assert 6 not in allows

    def test_multiline_decorator_argument_covered(self):
        fs = _run(astlint.ClockDiscipline(), _sf("""
            import time
            # graftlint: allow[clock-discipline] -- fixture says so
            @retry(
                deadline=time.time(),
            )
            def f():
                pass
        """))
        assert len(fs) == 1 and fs[0].suppressed

    def test_coverage_stays_statement_scoped(self):
        # the fix must not turn an allow into a file-wide blanket: a
        # violation in the NEXT statement stays live
        fs = _run(astlint.SleepOutsideBackoff(), _sf("""
            import time
            # graftlint: allow[sleep-outside-backoff] -- fixture says so
            time.sleep(1)
            time.sleep(2)
        """))
        assert len(fs) == 2
        assert [f.suppressed for f in sorted(fs, key=lambda f: f.line)] \
            == [True, False]


class TestPruneStale:
    """Satellite: --prune-stale drops fixed debt without re-pinning."""

    def test_round_trip_preserves_survivor_justifications(self, corpus):
        from tpu_patterns.core import ratchet

        pkg, bl = corpus
        (pkg / "mod2.py").write_text("import time\ntime.sleep(9)\n")
        rep = engine.run_lint(tier="a", root=str(pkg), baseline_path=bl)
        assert len(rep.new) == 2
        fnd.save_baseline(bl, rep.new, {})
        old = fnd.load_baseline(bl)
        for fp in old:
            old[fp]["justification"] = f"debt note for {fp}"
        ratchet.save_entries(
            bl, list(old.values()), version=fnd.BASELINE_VERSION
        )

        # fix ONE violation, prune: the fixed entry leaves the ledger,
        # the survivor keeps its value AND justification byte-for-byte
        (pkg / "mod2.py").write_text("y = 3\n")
        rep2 = engine.run_lint(
            tier="a", root=str(pkg), baseline_path=bl, prune_stale=True,
        )
        assert rep2.exit_code == 0
        assert rep2.stale == []  # pruned this run, not just reported
        after = fnd.load_baseline(bl)
        assert len(after) == 1
        (fp, entry), = after.items()
        assert entry == old[fp]

        # idempotent: a second prune with nothing stale changes nothing
        engine.run_lint(
            tier="a", root=str(pkg), baseline_path=bl, prune_stale=True,
        )
        assert fnd.load_baseline(bl) == after

    def test_partial_rules_prune_only_their_own_entries(self, corpus):
        from tpu_patterns.core import ratchet

        pkg, bl = corpus
        rep = engine.run_lint(tier="a", root=str(pkg), baseline_path=bl)
        fnd.save_baseline(bl, rep.new, {})
        # seed a foreign-rule entry the sleep-only run must NOT prune
        old = fnd.load_baseline(bl)
        foreign = {
            "rule": "clock-discipline", "path": "gone.py",
            "fingerprint": "aaaa000011112222", "text": "time.time()",
            "justification": "other rule's debt",
        }
        ratchet.save_entries(
            bl, list(old.values()) + [foreign],
            version=fnd.BASELINE_VERSION,
        )
        engine.run_lint(
            tier="a", root=str(pkg), baseline_path=bl,
            rules=["sleep-outside-backoff"], prune_stale=True,
        )
        after = fnd.load_baseline(bl)
        assert "aaaa000011112222" in after  # unexercised rule survived

    def test_prune_refused_in_strict_mode(self, corpus):
        pkg, bl = corpus
        with pytest.raises(ValueError, match="strict mode"):
            engine.run_lint(
                tier="a", root=str(pkg), baseline_path=bl,
                use_baseline=False, prune_stale=True,
            )

    def test_prune_and_update_are_mutually_exclusive(self, corpus):
        pkg, bl = corpus
        with pytest.raises(ValueError, match="pass one"):
            engine.run_lint(
                tier="all", root=str(pkg), baseline_path=bl,
                update_baseline=True, prune_stale=True,
            )

    def test_core_prune_missing_file_is_noop(self, tmp_path):
        from tpu_patterns.core import ratchet

        kept, dropped = ratchet.prune_stale(
            str(tmp_path / "absent.json"), ["x"], version=1
        )
        assert (kept, dropped) == (0, [])


class TestTierPlumbing:
    def test_rule_tiers(self):
        assert engine.rule_tier("clock-discipline") == "A"
        assert engine.rule_tier("trace-donation") == "B"
        assert engine.rule_tier("mesh-axis-order") == "C"
        assert engine.rule_tier("recompile-hazard") == "C"

    def test_catalog_covers_all_tiers(self):
        from tpu_patterns.analysis.shardlint import SHARD_CHECKS

        names = set(engine.rule_names())
        assert set(SHARD_CHECKS) <= names
        docs = engine.rule_docs()
        assert all(r in docs and docs[r] for r in names)

    def test_both_excludes_tier_c(self, corpus):
        pkg, bl = corpus
        with pytest.raises(ValueError, match="no rule left"):
            engine.run_lint(
                tier="both", root=str(pkg), baseline_path=bl,
                rules=["mesh-axis-order"],
            )

    def test_unknown_tier_rejected(self, corpus):
        pkg, bl = corpus
        with pytest.raises(ValueError, match="tier"):
            engine.run_lint(tier="z", root=str(pkg), baseline_path=bl)

    def test_update_baseline_requires_tier_all(self, corpus):
        # "both" stopped being the full catalog when Tier C landed: a
        # re-pin from it would drop every shardlint entry
        pkg, bl = corpus
        with pytest.raises(ValueError, match="FULL run"):
            engine.run_lint(
                tier="both", root=str(pkg), baseline_path=bl,
                update_baseline=True,
            )


def _mesh8(names):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices())
    if len(names) == 2:
        return Mesh(devs.reshape(4, 2), names)
    return Mesh(devs, names)


def _spmd_fixture(name, build, **kw):
    from tpu_patterns.perf.registry import SpmdEntry

    return SpmdEntry(name, kw.pop("axes", ("sp", "tp")), build, **kw)


class TestShardChecks:
    """Tier C: every rule fires, passes, and suppresses on fixture
    entries fed through the registry's fixture door."""

    # -- collective-axis-discipline --------------------------------------

    def _bad_axis_entry(self):
        def build():
            import jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P

            m = _mesh8(("sp", "tp"))
            fn = jax.jit(jax.shard_map(
                lambda x: lax.psum(x, "zz"),
                mesh=m, in_specs=(P("sp"),), out_specs=P(),
            ))
            return fn, (jnp.ones((8,)),)

        return _spmd_fixture("fix.badaxis", build)

    def test_axis_discipline_fires_on_wrong_axis(self):
        from tpu_patterns.analysis import shardlint

        fs = shardlint.run_shard_checks(
            ["collective-axis-discipline"],
            entries=[self._bad_axis_entry()],
        )
        assert len(fs) == 1
        assert fs[0].rule == "collective-axis-discipline"
        assert "failed to lower" in fs[0].message
        assert fs[0].tier == "C"

    def test_axis_discipline_fires_on_unused_axis(self):
        from tpu_patterns.analysis import shardlint

        def build():
            import jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P

            m = _mesh8(("sp", "tp"))  # tp (size 2) never referenced
            fn = jax.jit(jax.shard_map(
                lambda x: lax.psum(x, "sp"),
                mesh=m, in_specs=(P("sp"),), out_specs=P(),
            ))
            return fn, (jnp.ones((8,)),)

        fs = shardlint.run_shard_checks(
            ["collective-axis-discipline"],
            entries=[_spmd_fixture("fix.unused", build)],
        )
        assert len(fs) == 1 and "unused" in fs[0].message

    def test_axis_discipline_clean(self):
        from tpu_patterns.analysis import shardlint

        def build():
            import jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P

            m = _mesh8(("sp", "tp"))
            fn = jax.jit(jax.shard_map(
                lambda x: lax.psum(lax.psum(x, "sp"), "tp"),
                mesh=m, in_specs=(P(("sp", "tp")),), out_specs=P(),
            ))
            return fn, (jnp.ones((8,)),)

        assert shardlint.run_shard_checks(
            ["collective-axis-discipline"],
            entries=[_spmd_fixture("fix.clean", build)],
        ) == []

    def test_shard_finding_suppressed_by_anchor_allow(self):
        # the registration-anchored suppression contract: an allow on
        # the entry's anchor line covers its findings
        from tpu_patterns.analysis import shardlint

        e = dataclasses_replace_anchor(
            self._bad_axis_entry(), "tpu_patterns/fake/reg.py", 2
        )
        fs = shardlint.run_shard_checks(
            ["collective-axis-discipline"], entries=[e]
        )
        allows = {e.path: fnd.scan_allows([
            "# graftlint: allow[collective-axis-discipline] -- fixture says so",
            "ENTRY = register(...)",
        ])}
        fnd.apply_suppressions(fs, allows)
        assert len(fs) == 1 and fs[0].suppressed
        assert fs[0].justification == "fixture says so"

    def test_shard_finding_suppressed_through_engine_scan(self):
        # end-to-end through the engine's scan_finding_allows: the
        # committed fixture file's allow suppresses a finding anchored
        # at it, with no Tier-A walk having loaded the file
        from tpu_patterns.analysis import shardlint

        e = dataclasses_replace_anchor(
            self._bad_axis_entry(),
            "tests/fixtures/shardlint_allow_fixture.py", 6,
        )
        fs = shardlint.run_shard_checks(
            ["collective-axis-discipline"], entries=[e]
        )
        allows = engine.scan_finding_allows(fs, {})
        fnd.apply_suppressions(fs, allows)
        assert len(fs) == 1 and fs[0].suppressed

    # -- mesh-axis-order -------------------------------------------------

    def _order_entry(self, mesh_names, spec_axes):
        def build():
            import jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P

            m = _mesh8(mesh_names)
            fn = jax.jit(jax.shard_map(
                lambda x: lax.psum(x, mesh_names),
                mesh=m, in_specs=(P(spec_axes),), out_specs=P(),
            ))
            return fn, (jnp.ones((8,)),)

        return _spmd_fixture("fix.order", build, axes=("sp", "tp"))

    def test_mesh_axis_order_fires_on_reversed_mesh(self):
        from tpu_patterns.analysis import shardlint

        fs = shardlint.run_shard_checks(
            ["mesh-axis-order"],
            entries=[self._order_entry(("tp", "sp"), ("tp", "sp"))],
        )
        assert len(fs) == 1 and "canonical order" in fs[0].message

    def test_mesh_axis_order_fires_on_merged_spec(self):
        from tpu_patterns.analysis import shardlint

        fs = shardlint.run_shard_checks(
            ["mesh-axis-order"],
            entries=[self._order_entry(("sp", "tp"), ("tp", "sp"))],
        )
        assert fs and all("against the canonical" in f.message for f in fs)

    def test_mesh_axis_order_clean(self):
        from tpu_patterns.analysis import shardlint

        assert shardlint.run_shard_checks(
            ["mesh-axis-order"],
            entries=[self._order_entry(("sp", "tp"), ("sp", "tp"))],
        ) == []

    def test_mesh_axis_order_suppressed(self):
        from tpu_patterns.analysis import shardlint

        e = dataclasses_replace_anchor(
            self._order_entry(("tp", "sp"), ("tp", "sp")),
            "tpu_patterns/fake/reg.py", 2,
        )
        fs = shardlint.run_shard_checks(["mesh-axis-order"], entries=[e])
        fnd.apply_suppressions(fs, {e.path: fnd.scan_allows([
            "# graftlint: allow[mesh-axis-order] -- fixture says so",
            "ENTRY = register(...)",
        ])})
        assert len(fs) == 1 and fs[0].suppressed

    # -- collective-in-decode-hot-path -----------------------------------

    def _hot_entry(self, declared):
        def build():
            import jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P

            m = _mesh8(("sp", "tp"))
            fn = jax.jit(jax.shard_map(
                lambda x: lax.all_gather(lax.psum(x, "tp"), "sp"),
                mesh=m, in_specs=(P("sp"),), out_specs=P(None, None),
            ))
            return fn, (jnp.ones((8,)),)

        return _spmd_fixture(
            "fix.hot", build, declared_collectives=declared,
        )

    def test_decode_collectives_fires_on_undeclared(self):
        from tpu_patterns.analysis import shardlint

        fs = shardlint.run_shard_checks(
            ["collective-in-decode-hot-path"],
            entries=[self._hot_entry(frozenset({("psum", ("tp",))}))],
        )
        assert len(fs) == 1
        assert "NEW collective all_gather" in fs[0].message

    def test_decode_collectives_clean_when_declared(self):
        from tpu_patterns.analysis import shardlint

        declared = frozenset({
            ("psum", ("tp",)), ("all_gather", ("sp",)),
        })
        assert shardlint.run_shard_checks(
            ["collective-in-decode-hot-path"],
            entries=[self._hot_entry(declared)],
        ) == []

    def test_decode_collectives_suppressed(self):
        from tpu_patterns.analysis import shardlint

        e = dataclasses_replace_anchor(
            self._hot_entry(frozenset()), "tpu_patterns/fake/reg.py", 2
        )
        fs = shardlint.run_shard_checks(
            ["collective-in-decode-hot-path"], entries=[e]
        )
        fnd.apply_suppressions(fs, {e.path: fnd.scan_allows([
            "# graftlint: allow[collective-in-decode-hot-path] -- fixture says so",
            "ENTRY = register(...)",
        ])})
        assert fs and all(f.suppressed for f in fs)

    # -- donation-coverage -----------------------------------------------

    def _donate_entry(self, declare: bool):
        def build():
            import jax
            import jax.numpy as jnp

            kw = {"donate_argnums": (0,)} if declare else {}
            fn = jax.jit(lambda a: a + 1, **kw)
            return fn, (jnp.zeros((64, 64), jnp.float32),)

        return _spmd_fixture("fix.donate", build, axes=(), donates=True)

    def test_donation_coverage_fires(self):
        from tpu_patterns.analysis import shardlint

        fs = shardlint.run_shard_checks(
            ["donation-coverage"], entries=[self._donate_entry(False)]
        )
        if not fs and shardlint.run_shard_checks(
            ["donation-coverage"], entries=[self._donate_entry(True)]
        ) == []:
            pytest.skip("backend exposes no memory-analysis API")
        assert len(fs) == 1 and "aliases 0 bytes" in fs[0].message

    def test_donation_coverage_clean(self):
        from tpu_patterns.analysis import shardlint

        assert shardlint.run_shard_checks(
            ["donation-coverage"], entries=[self._donate_entry(True)]
        ) == []

    def test_donation_coverage_suppressed(self):
        from tpu_patterns.analysis import shardlint

        e = dataclasses_replace_anchor(
            self._donate_entry(False), "tpu_patterns/fake/reg.py", 2
        )
        fs = shardlint.run_shard_checks(["donation-coverage"], entries=[e])
        if not fs:
            pytest.skip("backend exposes no memory-analysis API")
        fnd.apply_suppressions(fs, {e.path: fnd.scan_allows([
            "# graftlint: allow[donation-coverage] -- fixture says so",
            "ENTRY = register(...)",
        ])})
        assert fs[0].suppressed

    # -- implicit-reshard ------------------------------------------------

    def _reshard_entry(self, clean: bool):
        def build():
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            m = _mesh8(("sp", "tp"))
            x = jax.device_put(
                jnp.ones((8, 8)), NamedSharding(m, P("sp", None))
            )
            if clean:
                # elementwise: stays on the input sharding, no comm
                return jax.jit(lambda a: a * 2), (x,)
            # full reduction: the partitioner must insert an all-reduce
            # the (collective-free) jaxpr never asked for
            return jax.jit(lambda a: a.sum()), (x,)

        return _spmd_fixture("fix.reshard", build, axes=(), hot=True)

    def test_implicit_reshard_fires_on_inserted_collective(self):
        from tpu_patterns.analysis import shardlint

        fs = shardlint.run_shard_checks(
            ["implicit-reshard"], entries=[self._reshard_entry(False)]
        )
        assert fs and "never asked for" in fs[0].message

    def test_implicit_reshard_clean(self):
        from tpu_patterns.analysis import shardlint

        assert shardlint.run_shard_checks(
            ["implicit-reshard"], entries=[self._reshard_entry(True)]
        ) == []

    def test_implicit_reshard_suppressed(self):
        from tpu_patterns.analysis import shardlint

        e = dataclasses_replace_anchor(
            self._reshard_entry(False), "tpu_patterns/fake/reg.py", 2
        )
        fs = shardlint.run_shard_checks(["implicit-reshard"], entries=[e])
        fnd.apply_suppressions(fs, {e.path: fnd.scan_allows([
            "# graftlint: allow[implicit-reshard] -- fixture says so",
            "ENTRY = register(...)",
        ])})
        assert fs and all(f.suppressed for f in fs)

    # -- recompile-hazard ------------------------------------------------

    def test_recompile_hazard_clean_fires_and_suppresses(self, monkeypatch):
        # one engine-driven test for all three shapes (the scripted
        # trace compiles real executables — keep it to one pass each)
        from tpu_patterns.analysis import shardlint
        from tpu_patterns.serve import engine as serve_engine

        assert shardlint.run_shard_checks(["recompile-hazard"]) == []

        monkeypatch.setattr(
            serve_engine, "_bucket", lambda n, cap: min(n + 2, cap + 1)
        )
        fs = shardlint.run_shard_checks(["recompile-hazard"])
        assert fs and all(f.rule == "recompile-hazard" for f in fs)
        assert any("outside the declared bucket set" in f.message
                   for f in fs)
        # suppression: anchored at the scripted-trace registration
        allows = {fs[0].path: {fs[0].line: fnd.Allow(
            rules=frozenset({"recompile-hazard"}),
            justification="fixture says so", line=fs[0].line,
        )}}
        fnd.apply_suppressions(fs, allows)
        assert all(f.suppressed for f in fs)

    # -- crash-to-finding + registry plumbing ----------------------------

    def test_crashed_check_is_a_finding(self, monkeypatch):
        from tpu_patterns.analysis import shardlint

        def boom(_summaries):
            raise RuntimeError("verifier exploded")

        monkeypatch.setitem(
            shardlint._SUMMARY_RULES, "mesh-axis-order", boom
        )
        fs = shardlint.run_shard_checks(["mesh-axis-order"], entries=[])
        assert len(fs) == 1 and "check crashed" in fs[0].message

    def test_skipped_entry_is_not_a_finding(self):
        from tpu_patterns.analysis import shardlint
        from tpu_patterns.perf.registry import SpmdSkip

        def build():
            raise SpmdSkip("world too small")

        fs = shardlint.run_shard_checks(
            ["collective-axis-discipline"],
            entries=[_spmd_fixture("fix.skip", build)],
        )
        assert fs == []

    def test_register_spmd_entry_feeds_the_catalog(self):
        from tpu_patterns.perf import registry

        e = _spmd_fixture("fix.registered", lambda: None)
        registry.register_spmd_entry(e)
        try:
            assert e in registry.spmd_entries()
        finally:
            registry._EXTRA_SPMD_ENTRIES.remove(e)

    def test_registry_declares_the_serve_family(self):
        from tpu_patterns.perf import registry

        entries = {e.name: e for e in registry.spmd_entries()}
        for name in ("train.step", "zero.step", "decoder.prefill",
                     "decoder.step", "decoder.verify", "copy_blocks",
                     "moe.dispatch", "pipeline.apply", "longctx.ring",
                     "longctx.ulysses", "longctx.flash", "comm.p2p",
                     "comm.ring", "comm.hier"):
            assert name in entries, name
        assert entries["decoder.step"].hot
        assert entries["decoder.verify"].hot
        assert entries["train.step"].donates
        assert entries["decoder.step"].declared_collectives


def dataclasses_replace_anchor(entry, path, line):
    import dataclasses as _dc

    return _dc.replace(entry, anchor_path=path, anchor_line=line)


class TestPagedKernelEntries:
    """PR-18 registry surface: the pallas decode twins and the fused-
    sampling core are enumerated, and the decode hot-path audit sees
    the sampling all_gather — firing without the declaration, clean
    with it."""

    def test_registry_declares_the_pallas_family(self):
        from tpu_patterns.perf import registry
        from tpu_patterns.serve.paged import (
            DECODE_DECLARED_COLLECTIVES,
            SAMPLED_DECODE_DECLARED_COLLECTIVES,
        )

        entries = {e.name: e for e in registry.spmd_entries()}
        for name in ("decoder.step_pallas", "decoder.verify_pallas",
                     "decoder.step_sampled"):
            assert name in entries, name
            assert entries[name].hot and entries[name].donates
        # the kernel is rank-local; its sp combine runs outside, so the
        # pallas twins declare EXACTLY the dense budget
        assert (entries["decoder.step_pallas"].declared_collectives
                == DECODE_DECLARED_COLLECTIVES)
        assert (entries["decoder.step_sampled"].declared_collectives
                == SAMPLED_DECODE_DECLARED_COLLECTIVES)
        assert (("all_gather", ("tp",))
                in SAMPLED_DECODE_DECLARED_COLLECTIVES)

    def _sampled_entry(self, declared):
        import dataclasses as _dc

        from tpu_patterns.perf import registry

        e = next(x for x in registry.spmd_entries()
                 if x.name == "decoder.step_sampled")
        return _dc.replace(e, declared_collectives=declared)

    def test_sampling_gather_fires_against_dense_budget(self):
        # the REAL sampled core against the dense declaration: the
        # candidate all_gather over tp is a NEW finding
        from tpu_patterns.analysis import shardlint
        from tpu_patterns.serve.paged import DECODE_DECLARED_COLLECTIVES

        fs = shardlint.run_shard_checks(
            ["collective-in-decode-hot-path"],
            entries=[self._sampled_entry(DECODE_DECLARED_COLLECTIVES)],
        )
        assert fs
        assert any("all_gather" in f.message for f in fs)

    def test_sampling_gather_clean_with_declared_budget(self):
        from tpu_patterns.analysis import shardlint
        from tpu_patterns.serve.paged import (
            SAMPLED_DECODE_DECLARED_COLLECTIVES,
        )

        assert shardlint.run_shard_checks(
            ["collective-in-decode-hot-path"],
            entries=[
                self._sampled_entry(SAMPLED_DECODE_DECLARED_COLLECTIVES)
            ],
        ) == []

    def test_pallas_step_clean_on_decode_audit(self):
        # kernel enabled, dense budget: no new collective — the fused
        # path must not widen the decode collective footprint
        from tpu_patterns.analysis import shardlint
        from tpu_patterns.perf import registry

        entries = [e for e in registry.spmd_entries()
                   if e.name in ("decoder.step_pallas",
                                 "decoder.verify_pallas")]
        assert len(entries) == 2
        assert shardlint.run_shard_checks(
            ["collective-in-decode-hot-path"], entries=entries
        ) == []
