"""perfwatch (tpu_patterns/perf): provenance stamps, analytic cost
accounting, the shared ratchet core, noise-banded baseline diffs, the
history/timeline store, and the capture -> diff loop including a
faults-driven step-time regression."""

import json
import os

import numpy as np
import pytest

from tpu_patterns.core import ratchet
from tpu_patterns.core.results import Record, ResultWriter
from tpu_patterns.perf import analytic, provenance
from tpu_patterns.perf import baseline as perf_baseline
from tpu_patterns.perf import history as perf_history
from tpu_patterns.perf import report as perf_report

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- provenance ------------------------------------------------------------


class TestProvenance:
    def test_two_runs_in_one_process_get_distinct_run_ids(self):
        a = provenance.new_run()
        b = provenance.new_run()
        assert a.run_id != b.run_id
        # the code and the environment did NOT change between them
        assert a.git_sha == b.git_sha
        assert a.mesh_fp == b.mesh_fp

    def test_stamp_fields_shape(self):
        d = provenance.stamp_dict()
        assert set(d) == {"run_id", "git_sha", "mesh_fp"}
        assert len(d["mesh_fp"]) == 12

    def test_result_writer_stamps_every_record(self, tmp_path):
        path = tmp_path / "r.jsonl"
        w = ResultWriter(jsonl_path=path, stream=open(os.devnull, "w"))
        w.record(Record(pattern="p", mode="m"))
        w.record(Record(pattern="p", mode="m2"))
        lines = [json.loads(ln) for ln in open(path)]
        for d in lines:
            assert d["run"]["run_id"]
            assert "git_sha" in d["run"] and "mesh_fp" in d["run"]
        # one writer session = one run: the two records agree
        assert lines[0]["run"] == lines[1]["run"]

    def test_cli_main_rotates_the_run_stamp(self, tmp_path, capsys):
        from tpu_patterns.cli import main

        log = tmp_path / "x.log"
        log.write_text("## m | c | SUCCESS\n")
        main(["report", str(log)])
        first = provenance.current_stamp().run_id
        main(["report", str(log)])
        second = provenance.current_stamp().run_id
        capsys.readouterr()
        assert first != second

    def test_mesh_fp_is_a_pure_function_of_the_env(self, monkeypatch):
        # the fingerprint must be identical whether the stamp is taken
        # before or after backend init (fresh CLI vs warm worker) —
        # live backend state must never fold in
        import jax

        a = provenance.mesh_fingerprint()
        jax.devices()  # force backend init (a no-op if already up)
        assert provenance.mesh_fingerprint() == a
        monkeypatch.setenv("TPU_PATTERNS_CPU_DEVICES", "99")
        assert provenance.mesh_fingerprint() != a  # env DOES identify

    def test_reexported_dump_keeps_the_source_runs_stamp(self):
        # obs export --prom re-renders a PAST run's dump: the numbers
        # must stay attributed to the run that produced them
        from tpu_patterns.obs import metrics as obs_metrics

        reg = obs_metrics.Registry()
        reg.gauge("tpu_patterns_perf_step_ms", executable="x").set(1.0)
        lines = reg.to_jsonl().splitlines()
        head = json.loads(lines[0])
        head["run_id"], head["git_sha"] = "src-run", "src-sha"
        lines[0] = json.dumps(head, sort_keys=True)
        back = obs_metrics.registry_from_jsonl(lines)
        assert back.run_stamp["run_id"] == "src-run"
        assert "run_id=src-run" in back.to_prom_text().splitlines()[0]
        rehead = json.loads(back.to_jsonl().splitlines()[0])
        assert rehead["run_id"] == "src-run"
        assert rehead["git_sha"] == "src-sha"

    def test_metrics_dumps_carry_the_stamp(self):
        from tpu_patterns.obs import metrics as obs_metrics

        reg = obs_metrics.Registry()
        reg.gauge("tpu_patterns_perf_step_ms", executable="x").set(1.5)
        head = json.loads(reg.to_jsonl().splitlines()[0])
        assert head["type"] == "run" and head["run_id"]
        assert reg.to_prom_text().splitlines()[0].startswith("# RUN ")
        # replay skips the stamp line instead of choking on it
        back = obs_metrics.registry_from_jsonl(
            reg.to_jsonl().splitlines()
        )
        assert back.to_prom_text() == reg.to_prom_text()


# -- analytic accounting ---------------------------------------------------


def _mcfg(**kw):
    from tpu_patterns.models.transformer import ModelConfig

    base = dict(
        embed=64, heads=4, head_dim=16, mlp_mult=4, causal=True,
        dtype="float32", depth=2, rope=True,
    )
    base.update(kw)
    return ModelConfig(**base)


class TestAnalytic:
    def test_prefill_matches_hand_computed_count(self):
        # independent derivation, term by term at literal dims:
        # B=4 rows, L=24, E=64, H=4, D=16 (HD=64), hidden=256, depth=2,
        # V=256 — the acceptance bar is 5%
        cfg = _mcfg()
        B, L, E, HD, HID, DEPTH, V = 4, 24, 64, 64, 256, 2, 256
        qkv = 2 * B * L * E * (3 * HD)  # fused q,k,v projections
        out = 2 * B * L * HD * E
        scores = 2 * B * 4 * L * L * 16 / 2  # per-head q.K, causal half
        attnv = 2 * B * 4 * L * L * 16 / 2  # per-head scores.V
        mlp = 2 * B * L * E * HID + 2 * B * L * HID * E
        hand = DEPTH * (qkv + out + scores + attnv + mlp) + 2 * B * E * V
        got = analytic.prefill_flops(cfg, V, B, L)
        assert abs(got - hand) / hand < 0.05, (got, hand)

    def test_step_matches_hand_computed_count(self):
        # one token per row attending over ctx=24 cached positions
        cfg = _mcfg()
        B, E, HD, HID, DEPTH, V, CTX = 4, 64, 64, 256, 2, 256, 24
        qkv = 2 * B * E * (3 * HD)
        out = 2 * B * HD * E
        attn = 2 * B * HD * CTX + 2 * B * HD * CTX
        mlp = 2 * B * E * HID + 2 * B * HID * E
        hand = DEPTH * (qkv + out + attn + mlp) + 2 * B * E * V
        got = analytic.step_flops(cfg, V, B, CTX)
        assert abs(got - hand) / hand < 0.05, (got, hand)

    def test_step_bytes_match_hand_computed_floor(self):
        # params once + ctx KV read + 1 KV write + f32 logits out
        cfg = _mcfg()
        B, V, CTX = 4, 256, 24
        pbytes = analytic.param_count(cfg, V) * 4  # float32
        kv_tok = 2 * (2 * 4 * 16 * 4)  # depth * (K+V * Hkv*D * 4B)
        hand = pbytes + B * CTX * kv_tok + B * kv_tok + B * V * 4
        got = analytic.step_hbm_bytes(cfg, V, B, CTX)
        assert abs(got - hand) / hand < 0.05, (got, hand)

    def test_gqa_shrinks_kv_projection_only(self):
        full = analytic.step_flops(_mcfg(), 256, 4, 24)
        gqa = analytic.step_flops(_mcfg(kv_heads=2), 256, 4, 24)
        assert gqa < full
        # the delta is exactly the kv projection halving, per layer:
        # 2*B*E*(2*KVD_full - 2*KVD_gqa) = 2*4*64*64, times depth 2
        assert full - gqa == 2 * (2 * 4 * 64 * 64)

    def test_verify_width_one_approximates_a_step(self):
        cfg = _mcfg()
        v1 = analytic.verify_flops(cfg, 256, 4, 1, 24)
        st = analytic.step_flops(cfg, 256, 4, 24)
        assert abs(v1 - st) / st < 0.01

    def test_param_count_matches_the_real_tree(self):
        import jax

        from tpu_patterns.models.lm import init_lm_params

        cfg = _mcfg()
        flat = init_lm_params(jax.random.key(0), cfg, 256, 0)
        real = sum(int(np.prod(v.shape)) for v in flat.values())
        assert analytic.param_count(cfg, 256) == real

    def test_train_flops_agree_with_flagship_accounting(self):
        from tpu_patterns.models.transformer import flagship_flops

        cfg = _mcfg()
        got = analytic.train_step_flops(cfg, 8, 32)

        class Duck:
            batch, seq, embed, heads, head_dim = 8, 32, 64, 4, 16
            kv_heads, mlp_mult, causal, depth = 0, 4, True, 2
            remat, remat_policy = False, "full"

        assert got == flagship_flops(Duck())


# -- the shared ratchet core -----------------------------------------------


class TestRatchetCore:
    def test_save_load_round_trip_and_version_gate(self, tmp_path):
        path = str(tmp_path / "b.json")
        entries = [
            {"fingerprint": "aa", "justification": "", "v": 1},
            {"fingerprint": "bb", "justification": "why", "v": 2},
        ]
        assert ratchet.save_entries(path, entries, version=3) == 2
        back = ratchet.load_entries(path, version=3)
        assert set(back) == {"aa", "bb"}
        with pytest.raises(ValueError, match="baseline version"):
            ratchet.load_entries(path, version=4)

    def test_missing_file_is_empty_not_an_error(self, tmp_path):
        assert ratchet.load_entries(
            str(tmp_path / "absent.json"), version=1
        ) == {}

    def test_justifications_survive_a_repin(self):
        old = {"aa": {"fingerprint": "aa", "justification": "pinned why"}}
        new = ratchet.preserve_justifications(
            [{"fingerprint": "aa", "justification": ""},
             {"fingerprint": "bb", "justification": "fresh"}],
            old,
        )
        assert new[0]["justification"] == "pinned why"
        assert new[1]["justification"] == "fresh"

    def test_split_entries_with_stale_filter(self):
        baseline = {
            "aa": {"fingerprint": "aa", "rule": "r1"},
            "bb": {"fingerprint": "bb", "rule": "r2"},
        }
        new, pinned, stale = ratchet.split_entries(
            {"aa", "cc"}, baseline,
            stale_filter=lambda e: e["rule"] == "r1",
        )
        assert new == {"cc"} and pinned == {"aa"}
        assert stale == []  # bb's rule did not run -> not declared fixed

    def test_committed_analysis_baseline_still_loads(self):
        # the extraction must keep graftlint's committed file readable
        from tpu_patterns.analysis.findings import (
            default_baseline_path,
            load_baseline,
        )

        entries = load_baseline(default_baseline_path())
        assert entries, "committed analysis baseline should be non-empty"
        for e in entries.values():
            assert {"rule", "path", "fingerprint", "text"} <= set(e)


# -- the perf baseline bands -----------------------------------------------


def _snapshot(step_ms=5.0, flops=1e8, mesh_fp="m1", **extra):
    ex = {
        "analytic_flops": flops,
        "step_ms": step_ms,
        "temp_bytes": 1000.0,
        "compile_s": 2.0,
    }
    ex.update(extra)
    return {
        "run": {"run_id": "r", "git_sha": "s", "mesh_fp": mesh_fp},
        "ts": 1.0,
        "config": {"embed": 64, "k": 3},
        "mesh": {"shape": {"dp": 1, "sp": 4, "tp": 2}, "devices": 8,
                 "platform": "cpu"},
        "executables": {"decoder.step": ex},
    }


class TestPerfBaseline:
    def _pin(self, tmp_path, snap):
        path = str(tmp_path / "perf.json")
        perf_baseline.save_baseline(path, snap, {})
        return path, perf_baseline.load_baseline(path)

    def test_clean_diff_against_own_pin_passes(self, tmp_path):
        snap = _snapshot()
        _, bl = self._pin(tmp_path, snap)
        d = perf_baseline.diff_snapshot(snap, bl)
        assert d.exit_code == 0
        assert not d.regressions and not d.unbaselined and not d.stale
        assert d.checked > 0

    def test_measured_band_flags_only_a_real_stall(self, tmp_path):
        _, bl = self._pin(tmp_path, _snapshot(step_ms=5.0))
        # 2x regime shift on a shared CPU host: inside the band
        ok = perf_baseline.diff_snapshot(_snapshot(step_ms=10.0), bl)
        assert ok.exit_code == 0
        # 4x IS a stall (an injected sleep is 10-20x)
        bad = perf_baseline.diff_snapshot(_snapshot(step_ms=20.0), bl)
        assert bad.exit_code == 1
        assert bad.regressions[0].executable == "decoder.step"
        assert bad.regressions[0].metric == "step_ms"
        assert "decoder.step.step_ms" in bad.regressions[0].message()

    def test_measured_improvement_is_not_a_failure(self, tmp_path):
        _, bl = self._pin(tmp_path, _snapshot(step_ms=50.0))
        d = perf_baseline.diff_snapshot(_snapshot(step_ms=1.0), bl)
        assert d.exit_code == 0
        assert d.improvements and d.improvements[0].metric == "step_ms"

    def test_analytic_drift_gates_both_directions(self, tmp_path):
        _, bl = self._pin(tmp_path, _snapshot(flops=1e8))
        # FLOPs silently DROPPING = work dead-code-eliminated out of
        # the measured program — the grad-gate accounting bug class
        d = perf_baseline.diff_snapshot(_snapshot(flops=0.9e8), bl)
        assert d.exit_code == 1
        d = perf_baseline.diff_snapshot(_snapshot(flops=1.1e8), bl)
        assert d.exit_code == 1
        d = perf_baseline.diff_snapshot(_snapshot(flops=1e8 * 1.0005), bl)
        assert d.exit_code == 0

    def test_foreign_mesh_fp_skips_machine_bound_gates_only(
        self, tmp_path
    ):
        _, bl = self._pin(tmp_path, _snapshot(step_ms=5.0, flops=1e8))
        # another machine: 100x step time is SKIPPED, visible not fatal
        d = perf_baseline.diff_snapshot(
            _snapshot(step_ms=500.0, flops=1e8, mesh_fp="other"), bl
        )
        assert d.exit_code == 0
        assert "decoder.step.step_ms" in d.skipped
        # ... but the device-independent analytic count still gates
        d = perf_baseline.diff_snapshot(
            _snapshot(step_ms=500.0, flops=2e8, mesh_fp="other"), bl
        )
        assert d.exit_code == 1
        assert d.regressions[0].metric == "analytic_flops"

    def test_changed_capture_shape_is_unbaselined_not_regressed(
        self, tmp_path
    ):
        _, bl = self._pin(tmp_path, _snapshot())
        moved = _snapshot(step_ms=500.0, flops=7e9)
        moved["config"]["embed"] = 128  # a different capture shape
        d = perf_baseline.diff_snapshot(moved, bl)
        assert d.exit_code == 0
        assert d.unbaselined and d.stale  # re-pin deliberately

    def test_measurement_policy_is_not_identity(self, tmp_path):
        _, bl = self._pin(tmp_path, _snapshot())
        quieter = _snapshot()
        quieter["config"]["k"] = 11  # raising k must not churn the pin
        d = perf_baseline.diff_snapshot(quieter, bl)
        assert not d.unbaselined and not d.stale

    def test_justification_survives_update(self, tmp_path):
        snap = _snapshot()
        path, bl = self._pin(tmp_path, snap)
        fp = perf_baseline.fingerprint(
            "decoder.step", "step_ms",
            perf_baseline.config_fingerprint(snap),
        )
        bl[fp]["justification"] = "accepted: scheduler rework tax"
        ratchet.save_entries(
            path, sorted(bl.values(), key=lambda e: e["fingerprint"]),
            version=perf_baseline.BASELINE_VERSION,
        )
        perf_baseline.save_baseline(
            path, snap, perf_baseline.load_baseline(path)
        )
        again = perf_baseline.load_baseline(path)
        assert again[fp]["justification"] == (
            "accepted: scheduler rework tax"
        )

    def test_tolerance_override(self, tmp_path):
        _, bl = self._pin(tmp_path, _snapshot(step_ms=5.0))
        d = perf_baseline.diff_snapshot(
            _snapshot(step_ms=10.0), bl, tolerances={"measured": 0.5}
        )
        assert d.exit_code == 1  # the quiet-box band catches a 2x

    def test_tolerance_none_makes_measured_informational(self, tmp_path):
        # the committed-ledger mode (perf diff --measured_tol -1): an
        # aged pin's wall-clock entries stop gating entirely while the
        # analytic ratchet stays live
        _, bl = self._pin(tmp_path, _snapshot(step_ms=5.0, flops=1e8))
        d = perf_baseline.diff_snapshot(
            _snapshot(step_ms=500.0, flops=1e8), bl,
            tolerances={"measured": None},
        )
        assert d.exit_code == 0
        d = perf_baseline.diff_snapshot(
            _snapshot(step_ms=500.0, flops=2e8), bl,
            tolerances={"measured": None},
        )
        assert d.exit_code == 1
        assert d.regressions[0].metric == "analytic_flops"

    def test_subset_capture_never_declares_the_rest_stale(
        self, tmp_path
    ):
        snap = _snapshot()
        snap["executables"]["train.step"] = {
            "analytic_flops": 2e8, "step_ms": 9.0,
        }
        _, bl = self._pin(tmp_path, snap)
        only = _snapshot()  # decoder.step alone "ran"
        d = perf_baseline.diff_snapshot(only, bl)
        assert d.exit_code == 0
        assert not d.stale

    def test_informational_classes_never_gate(self, tmp_path):
        _, bl = self._pin(tmp_path, _snapshot(compile_s=2.0))
        d = perf_baseline.diff_snapshot(_snapshot(compile_s=200.0), bl)
        assert d.exit_code == 0


# -- history + timeline ----------------------------------------------------


class TestHistoryTimeline:
    def test_append_and_load_round_trip(self, tmp_path):
        d = str(tmp_path / "perf")
        s1, s2 = _snapshot(), _snapshot(step_ms=6.0)
        perf_history.append_snapshot(s1, d)
        perf_history.append_snapshot(s2, d)
        back = perf_history.load_history(d)
        assert len(back) == 2
        assert back[1]["executables"]["decoder.step"]["step_ms"] == 6.0

    def test_torn_tail_line_is_skipped(self, tmp_path):
        d = str(tmp_path / "perf")
        perf_history.append_snapshot(_snapshot(), d)
        with open(perf_history.history_path(d), "a") as f:
            f.write('{"run": {"trunc')
        assert len(perf_history.load_history(d)) == 1

    def test_committed_bench_rounds_land_on_the_timeline(self):
        rounds = perf_history.load_bench_rounds(ROOT)
        assert len(rounds) >= 5
        assert [r["round"] for r in rounds] == sorted(
            r["round"] for r in rounds
        )
        # the hardware outage IS part of the trajectory
        assert any("unreachable" in r["error"] for r in rounds)

    def test_results_records_are_ingested_with_their_stamps(
        self, tmp_path
    ):
        res = tmp_path / "results"
        res.mkdir()
        w = ResultWriter(
            jsonl_path=res / "serve.jsonl", stream=open(os.devnull, "w")
        )
        w.record(Record(pattern="serve", mode="slots8",
                        metrics={"speedup": 2.7}))
        (res / "noise.jsonl").write_text(
            '{"type": "run", "run_id": "x"}\nnot json\n'
        )
        tl = perf_history.build_timeline(
            str(tmp_path / "perf"), str(res), str(tmp_path)
        )
        assert len(tl["records"]) == 1
        assert tl["records"][0]["run"]["run_id"]
        assert tl["records"][0]["pattern"] == "serve"

    def test_report_renders_all_sections(self, tmp_path):
        d = str(tmp_path / "perf")
        perf_history.append_snapshot(_snapshot(), d)
        tl = perf_history.build_timeline(d, str(tmp_path / "none"), ROOT)
        text = perf_report.render(_snapshot(), tl)
        assert "perfwatch snapshot" in text
        assert "decoder.step" in text
        assert "driver captures" in text
        assert "step_ms over runs" in text


# -- metric-naming: the new series pass graftlint --------------------------


class TestLintIntegration:
    def test_executable_label_is_known(self):
        from tpu_patterns.analysis.astlint import MetricNaming

        assert "executable" in MetricNaming.KNOWN_LABELS

    def test_perf_series_pass_metric_naming(self, tmp_path):
        from tpu_patterns.analysis.engine import lint_sources

        p = tmp_path / "perf_fixture.py"
        p.write_text(
            "from tpu_patterns import obs\n"
            'obs.gauge("tpu_patterns_perf_step_ms",'
            ' executable="decoder.step").set(1.0)\n'
            'obs.counter("tpu_patterns_perf_captures_total").inc()\n'
        )
        findings, _ = lint_sources([str(p)], rules=["metric-naming"])
        assert findings == []


# -- capture -> diff, end to end on the CPU mesh ---------------------------


@pytest.fixture(scope="module")
def perf_mesh(devices):
    from jax.sharding import Mesh

    return Mesh(
        np.array(devices[:8]).reshape(1, 4, 2), ("dp", "sp", "tp")
    )


@pytest.fixture(scope="module")
def captured(perf_mesh):
    """One real capture shared by the e2e assertions (compiles are the
    cost; k/inner stay small — band logic is unit-tested above)."""
    from tpu_patterns.perf.registry import PerfConfig, capture

    cfg = PerfConfig(
        k=2, inner=4,
        include="decoder.prefill,decoder.step,serve.step",
    )
    return capture(perf_mesh, cfg), cfg


class TestCaptureE2E:
    def test_snapshot_shape_and_stamp(self, captured):
        snap, _cfg = captured
        assert set(snap["executables"]) == {
            "decoder.prefill", "decoder.step", "serve.step"
        }
        assert snap["run"]["run_id"] and len(snap["run"]["mesh_fp"]) == 12
        for name, m in snap["executables"].items():
            assert m["analytic_flops"] > 0, name
            assert m["step_ms"] > 0, name
            assert m["achieved_gflops"] > 0, name

    def test_xla_counts_within_sanity_band_of_analytic(self, captured):
        # cost_analysis reports PER-DEVICE flops; the whole-mesh total
        # must bracket the analytic model count (masked full-window
        # attention and collective overhead push it above, per-device
        # sharding pulls it below — an order-of-magnitude disagreement
        # means the accounting broke)
        snap, _cfg = captured
        n = snap["mesh"]["devices"]
        for name in ("decoder.prefill", "decoder.step"):
            m = snap["executables"][name]
            assert "xla_flops" in m, "CPU backend exposes cost_analysis"
            ratio = m["xla_flops"] * n / m["analytic_flops"]
            assert 0.3 < ratio < 5.0, (name, ratio)

    def test_pool_donation_shows_in_alias_bytes(self, captured):
        snap, _cfg = captured
        assert snap["executables"]["decoder.step"]["alias_bytes"] > 0

    def test_mfu_scored_against_the_capture_dtype_peak(self):
        # an f32 capture against the bf16 peak halves every MFU — the
        # derive step must pass the capture dtype through
        from unittest import mock

        from tpu_patterns.perf import registry as perf_registry

        m = {"step_ms": 1.0, "analytic_flops": 1e9,
             "analytic_hbm_bytes": 1e6}
        with mock.patch(
            "tpu_patterns.runtime.chip_peak_tflops",
            side_effect=lambda dtype: 100.0
            if np.dtype(dtype).itemsize < 4 else 50.0,
        ) as peak:
            perf_registry._derive(m, 1, "float32")
        assert peak.call_args == mock.call(dtype="float32")
        assert m["mfu"] == pytest.approx((1e9 / 1.0e-3 / 1e12) / 50.0)

    def test_span_join_fed_the_histograms(self, captured):
        from tpu_patterns import obs

        h = obs.histogram(
            "tpu_patterns_span_duration_ns", span="perf.decoder.step"
        )
        assert h.count > 0
        assert obs.gauge(
            "tpu_patterns_perf_step_ms", executable="decoder.step"
        ).value > 0

    def test_clean_diff_against_own_pin_is_green(
        self, captured, tmp_path
    ):
        snap, _cfg = captured
        path = str(tmp_path / "bl.json")
        perf_baseline.save_baseline(path, snap, {})
        d = perf_baseline.diff_snapshot(
            snap, perf_baseline.load_baseline(path)
        )
        assert d.exit_code == 0 and not d.regressions

    def test_sleep_fault_at_serve_step_flags_the_regression(
        self, captured, perf_mesh, tmp_path
    ):
        # the acceptance loop: pin a clean serve.step capture, re-capture
        # under an injected sleep at the serve.step fault site, and the
        # diff must name the step-time regression per-executable; a
        # clean re-capture afterwards passes the noise band again
        from tpu_patterns import faults
        from tpu_patterns.perf.registry import PerfConfig, capture

        cfg = PerfConfig(k=2, inner=4, include="serve.step")
        path = str(tmp_path / "bl.json")
        clean = capture(perf_mesh, cfg)
        perf_baseline.save_baseline(path, clean, {})
        bl = perf_baseline.load_baseline(path)
        try:
            faults.configure(
                "serve.step:sleep:delay_s=0.1:count=10000"
            )
            slow = capture(perf_mesh, cfg)
        finally:
            faults.configure(None)
        d = perf_baseline.diff_snapshot(slow, bl)
        assert d.exit_code == 1
        assert any(
            f.executable == "serve.step" and f.metric == "step_ms"
            for f in d.regressions
        )
        # back-to-back clean runs stay inside the band
        again = capture(perf_mesh, cfg)
        d2 = perf_baseline.diff_snapshot(again, bl)
        assert not any(
            f.metric == "step_ms" for f in d2.regressions
        ), [f.message() for f in d2.regressions]


class TestPruneStale:
    """`perf prune-stale`: drop entries whose executable left the
    registry, leaving every surviving pin — value, justification —
    byte-for-byte untouched (unlike a full re-pin)."""

    def _seeded_baseline(self, tmp_path):
        snap = {
            "run": {"mesh_fp": "m-fp"},
            "config": {"vocab": 16},
            "mesh": {"shape": {"dp": 1, "sp": 1, "tp": 1}},
            "executables": {
                "train.step": {"analytic_flops": 1.0, "step_ms": 2.0},
                "ghost.step": {"analytic_flops": 3.0},
            },
        }
        bl = str(tmp_path / "b.json")
        perf_baseline.save_baseline(bl, snap, {})
        old = perf_baseline.load_baseline(bl)
        for e in old.values():
            e["justification"] = f"pinned {e['metric']}"
        ratchet.save_entries(
            bl, list(old.values()),
            version=perf_baseline.BASELINE_VERSION,
        )
        return bl, old

    def test_cli_prunes_removed_executables_only(self, tmp_path):
        import subprocess
        import sys

        bl, old = self._seeded_baseline(tmp_path)
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_patterns", "perf", "prune-stale",
             "--baseline", bl],
            capture_output=True, text=True, timeout=240,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "ghost.step" in proc.stdout  # pruned entries are named
        after = perf_baseline.load_baseline(bl)
        assert {e["executable"] for e in after.values()} == {"train.step"}
        for fp, e in after.items():
            assert e == old[fp]  # survivors byte-for-byte, value included

    def test_core_prune_preserves_entry_order(self, tmp_path):
        bl, old = self._seeded_baseline(tmp_path)
        keep = {
            fp for fp, e in old.items()
            if e["executable"] == "train.step"
        }
        ratchet.prune_stale(
            bl, keep, version=perf_baseline.BASELINE_VERSION
        )
        with open(bl) as f:
            entries = json.load(f)["entries"]
        want = [e for e in old.values() if e["executable"] == "train.step"]
        assert entries == want  # pure deletion: order + content intact
