"""Tests for core config/results/timing (SURVEY.md §7 step 1)."""

import dataclasses
import enum
import io

import pytest

from tpu_patterns.core import (
    Record,
    ResultWriter,
    TimingResult,
    Verdict,
    clock_ns,
    config_from_tiers,
    device_barrier,
    global_interval_ns,
    min_over_reps,
    parse_log,
)
from tpu_patterns.core.config import config_to_dict
from tpu_patterns.core.results import tabulate_records


class Mode(enum.Enum):
    SERIAL = "serial"
    ASYNC = "async"


@dataclasses.dataclass
class DemoConfig:
    reps: int = 10
    min_bandwidth: float = -1.0
    verbose: bool = False
    mode: Mode = Mode.SERIAL
    commands: tuple[str, ...] = ("C",)


class TestConfigTiers:
    def test_defaults(self):
        cfg = config_from_tiers(DemoConfig, argv=[], env={})
        assert cfg == DemoConfig()

    def test_env_tier(self):
        cfg = config_from_tiers(
            DemoConfig, argv=[], env={"TPU_PATTERNS_REPS": "3", "TPU_PATTERNS_MODE": "async"}
        )
        assert cfg.reps == 3
        assert cfg.mode is Mode.ASYNC

    def test_cli_overrides_env(self):
        cfg = config_from_tiers(
            DemoConfig,
            argv=["--reps", "7", "--commands", "C,M2D", "--verbose", "true"],
            env={"TPU_PATTERNS_REPS": "3"},
        )
        assert cfg.reps == 7
        assert cfg.commands == ("C", "M2D")
        assert cfg.verbose is True

    def test_to_dict_json_friendly(self):
        d = config_to_dict(DemoConfig())
        assert d["mode"] == "serial"
        assert d["commands"] == ["C"]

    def test_pep604_optional_field(self):
        @dataclasses.dataclass
        class C:
            limit: int | None = None

        assert config_from_tiers(C, argv=["--limit", "5"], env={}).limit == 5
        assert config_from_tiers(C, argv=[], env={"TPU_PATTERNS_LIMIT": "7"}).limit == 7
        assert config_from_tiers(C, argv=["--limit", "none"], env={}).limit is None


class TestResults:
    def test_record_roundtrip(self):
        rec = Record(
            pattern="p2p",
            mode="unidirectional",
            commands="pairs=4",
            metrics={"bandwidth_GBps": 123.4},
            verdict=Verdict.SUCCESS,
        )
        back = Record.from_json(rec.to_json())
        assert back.metrics == rec.metrics
        assert back.verdict is Verdict.SUCCESS

    def test_writer_markers_and_exit_code(self, tmp_path):
        buf = io.StringIO()
        w = ResultWriter(tmp_path / "out.jsonl", stream=buf)
        w.progress("auto-tuning")
        w.metric("Unidirectional Bandwidth", 99.5, "GB/s")
        w.record(Record(pattern="p2p", mode="uni", commands="2dev"))
        w.record(
            Record(pattern="p2p", mode="bi", commands="2dev", verdict=Verdict.FAILURE)
        )
        out = buf.getvalue()
        assert "# auto-tuning" in out
        assert "## uni | 2dev | SUCCESS" in out
        assert "## bi | 2dev | FAILURE" in out
        assert w.exit_code == 1
        lines = (tmp_path / "out.jsonl").read_text().splitlines()
        assert len(lines) == 2

    def test_parse_log_reference_format(self):
        # The exact shape concurency/parse.py consumes: export-context lines
        # followed by ## verdict markers.
        log = [
            "+ export ZE_AFFINITY_MASK=0.0",
            "## serial | C C | SUCCESS",
            "## out_of_order | C C | FAILURE",
            "+ export ZE_AFFINITY_MASK=0",
            "## out_of_order | C C | SUCCESS",
        ]
        recs = parse_log(log)
        assert len(recs) == 3
        assert recs[1].verdict is Verdict.FAILURE
        assert recs[2].env["ZE_AFFINITY_MASK"] == "0"

    def test_parse_log_jsonl_dedup(self, tmp_path):
        buf = io.StringIO()
        w = ResultWriter(tmp_path / "o.jsonl", stream=buf)
        rec = w.record(Record(pattern="x", mode="m", commands="c"))
        # A log that interleaves the JSON record with its own marker line
        mixed = [rec.to_json()] + buf.getvalue().splitlines()
        recs = parse_log(mixed)
        assert len(recs) == 1

    def test_parse_log_dedup_marker_first_and_empty_commands(self, tmp_path):
        # ResultWriter emits the marker to stdout BEFORE appending the JSON;
        # `cat run.log out.jsonl` therefore puts markers first.  Records with
        # empty commands fall back to the pattern name in both places.
        buf = io.StringIO()
        w = ResultWriter(tmp_path / "o.jsonl", stream=buf)
        w.record(Record(pattern="p2p", mode="uni", commands=""))
        mixed = (
            buf.getvalue().splitlines()
            + (tmp_path / "o.jsonl").read_text().splitlines()
        )
        recs = parse_log(mixed)
        assert len(recs) == 1
        assert recs[0].commands == "p2p"

    def test_tabulate(self):
        recs = [
            Record(pattern="c", mode="serial", commands="C C", verdict=Verdict.SUCCESS),
            Record(pattern="c", mode="async", commands="C C", verdict=Verdict.FAILURE),
        ]
        table = tabulate_records(recs)
        assert "serial" in table and "async" in table and "C C" in table

    def test_tabulate_surfaces_integrity_flags(self):
        recs = [
            Record(
                pattern="onesided", mode="local_put", commands="1dev",
                verdict=Verdict.FAILURE,
                metrics={
                    "bandwidth_GBps": 103523.6,
                    "timing_converged": 0.0,
                    "hbm_plausible": 0.0,
                },
            ),
            Record(
                pattern="onesided", mode="clean", commands="1dev",
                verdict=Verdict.SUCCESS,
                metrics={
                    "bandwidth_GBps": 335.6,
                    "timing_converged": 1.0,
                    "hbm_plausible": 1.0,
                },
            ),
        ]
        table = tabulate_records(recs)
        # the 103 TB/s artifact reads as flagged, the clean row does not
        assert "NOISE-BOUND" in table and "NOT-HBM" in table
        assert table.count("NOISE-BOUND") == 1
        assert "335.6" in table and "[" not in table.split("335.6")[1].split("|")[0]


class TestTiming:
    def test_clock_monotonic(self):
        a = clock_ns()
        b = clock_ns()
        assert b >= a

    def test_min_over_reps_runs_and_fences(self):
        import jax.numpy as jnp

        calls = []

        def fn():
            calls.append(1)
            return jnp.zeros(8) + 1.0

        res = min_over_reps(fn, reps=3, warmup=1)
        assert len(res.times_ns) == 3
        assert len(calls) == 4  # warmup + reps
        assert res.min_ns > 0
        assert res.min_ns <= res.mean_ns

    def test_gbps_is_bytes_per_ns(self):
        t = TimingResult(times_ns=[2_000, 1_000])
        assert t.gbps(5_000) == pytest.approx(5.0)  # 5000 B / 1000 ns = 5 GB/s

    def test_global_interval_single_process(self):
        assert global_interval_ns(10, 25) == 15

    def test_device_barrier_noop_safe(self):
        device_barrier()


class TestMeasureChain:
    def _builder(self):
        import jax
        import jax.numpy as jnp

        x = jnp.arange(128, dtype=jnp.float32)

        def build(k):
            f = jax.jit(
                lambda a: jax.lax.fori_loop(0, k, lambda _, b: b * 1.0001, a).sum()
            )
            return lambda: f(x)

        return build

    def test_direct_mode_default_on_cpu(self):
        from tpu_patterns.core import TimingMode, default_timing_mode, measure_chain

        assert default_timing_mode() is TimingMode.DIRECT
        m = measure_chain(self._builder(), reps=3, warmup=1)
        assert m.mode is TimingMode.DIRECT
        assert m.per_op_ns > 0
        assert m.long is None

    def test_amortized_mode(self):
        from tpu_patterns.core import TimingMode, measure_chain

        m = measure_chain(
            self._builder(), reps=3, warmup=1, lengths=(1, 5),
            mode=TimingMode.AMORTIZED,
        )
        assert m.mode is TimingMode.AMORTIZED
        assert m.per_op_ns > 0
        assert m.lengths == (1, 5)
        assert m.long is not None
        # per-op estimate can't exceed the long chain's total time
        assert m.per_op_ns <= m.long.min_ns

    def test_env_override(self, monkeypatch):
        from tpu_patterns.core import TimingMode, default_timing_mode

        monkeypatch.setenv("TPU_PATTERNS_TIMING", "amortized")
        assert default_timing_mode() is TimingMode.AMORTIZED

    def test_adaptive_lengths_respect_max_chain(self):
        # lengths=None + AMORTIZED is the default TPU path: the long chain
        # grows geometrically but must never exceed max_chain (regression:
        # the cap was once checked before the multiply, giving 2x overshoot)
        from tpu_patterns.core import TimingMode, measure_chain

        m = measure_chain(
            self._builder(), reps=3, warmup=1, lengths=None,
            mode=TimingMode.AMORTIZED, max_chain=64,
        )
        assert m.lengths[1] <= 64
        assert m.per_op_ns > 0
        assert len(m.long.times_ns) == 3  # accepted k1 got the full reps

    def test_adaptive_handles_negative_diff(self):
        # a "chain" whose runtime does not grow with k (noise-only) must
        # still terminate and fall back to the upper-bound estimate
        from tpu_patterns.core import TimingMode, measure_chain

        def build(k):
            return lambda: 0

        m = measure_chain(
            build, reps=2, warmup=0, lengths=None,
            mode=TimingMode.AMORTIZED, max_chain=32, barrier=None,
        )
        assert m.lengths[1] <= 32
        assert m.per_op_ns >= 0

    def test_convergence_flag(self):
        # The r4 live artifact: 32768 near-free VMEM copies never
        # separated from the fetch round trip, yet the rate was recorded
        # as a clean measurement.  A chain that hits max length with the
        # differential still under the jitter floor must say so.
        from tpu_patterns.core import TimingMode, measure_chain

        def free(k):
            return lambda: 0  # per-op cost ~0: diff can never clear 10 ms

        m = measure_chain(
            free, reps=2, warmup=0, lengths=None,
            mode=TimingMode.AMORTIZED, max_chain=16, barrier=None,
        )
        assert m.converged is False

        import time

        def slow(k):
            return lambda: time.sleep(0.004 * k)  # 4 ms/iter: clears fast

        m2 = measure_chain(
            slow, reps=2, warmup=0, lengths=None,
            mode=TimingMode.AMORTIZED, max_chain=64, barrier=None,
        )
        assert m2.converged is True
        # DIRECT mode has no differential to converge: flag stays True
        m3 = measure_chain(
            self._builder(), reps=2, warmup=0, mode=TimingMode.DIRECT,
            direct_fn=self._builder()(1),
        )
        assert m3.converged is True


class TestChipPeak:
    def test_dtype_scales_peak(self, monkeypatch):
        """float32 issues through the MXU at half the bf16 rate: the
        sanity ceiling must halve with it, or an f32 accounting bug of
        up to 2x sails under a bf16 gate (ADVICE r3)."""
        import jax

        from tpu_patterns import runtime

        class _Dev:
            platform = "tpu"
            device_kind = "TPU v5 lite"

        monkeypatch.setattr(jax, "devices", lambda: [_Dev()])
        assert runtime.chip_peak_tflops() == 197.0
        assert runtime.chip_peak_tflops("bfloat16") == 197.0
        assert runtime.chip_peak_tflops("float32") == 98.5
        assert runtime.chip_peak_tflops("int8") == 197.0

    def test_off_tpu_is_none(self):
        from tpu_patterns import runtime

        assert runtime.chip_peak_tflops("float32") is None
