"""Native prefetch loader (csrc/loader.cc + io/loader.py): determinism,
seek, aliasing discipline, prefetch-ahead, and train-loop composition."""

import time

import numpy as np
import pytest

from tpu_patterns.io import loader as L

pytestmark = pytest.mark.skipif(
    not L.native_available(),
    reason=f"native toolchain unavailable: {L.build_error()}",
)


class TestDeterminism:
    def test_two_instances_agree(self):
        with L.NativeLoader(7, (4, 8)) as a, L.NativeLoader(7, (4, 8)) as b:
            for _ in range(6):
                xa, sa = a.next()
                xb, sb = b.next()
                assert sa == sb
                np.testing.assert_array_equal(xa, xb)

    def test_matches_reference_oracle(self):
        with L.NativeLoader(11, (32,)) as ld:
            for want in range(8):
                x, step = ld.next()
                assert step == want
                np.testing.assert_array_equal(
                    x, L.fill_reference(11, 32, step)
                )

    def test_different_seeds_and_steps_differ(self):
        a = L.fill_reference(1, 64, 0)
        assert not np.array_equal(a, L.fill_reference(2, 64, 0))
        assert not np.array_equal(a, L.fill_reference(1, 64, 1))

    def test_values_in_unit_range(self):
        x = L.fill_reference(3, 4096, 5)
        assert x.min() >= -1.0 and x.max() < 1.0
        assert np.abs(x.mean()) < 0.1  # roughly centered


class TestSeek:
    def test_seek_replays_the_stream(self):
        with L.NativeLoader(5, (16,)) as ld:
            first = [ld.next()[0].copy() for _ in range(6)]
            ld.seek(2)
            for want in range(2, 6):
                x, step = ld.next()
                assert step == want
                np.testing.assert_array_equal(x, first[want])

    def test_seek_forward_skips(self):
        with L.NativeLoader(5, (16,)) as ld:
            ld.seek(1000)
            x, step = ld.next()
            assert step == 1000
            np.testing.assert_array_equal(x, L.fill_reference(5, 16, 1000))

    def test_rapid_seeks_discard_stale_fills(self):
        # seeks racing in-flight producer fills: stale epochs must never
        # surface as the wrong batch
        with L.NativeLoader(9, (1024,), buffers=4, threads=3) as ld:
            for target in (50, 3, 777, 0, 123):
                ld.seek(target)
                x, step = ld.next()
                assert step == target
                np.testing.assert_array_equal(
                    x, L.fill_reference(9, 1024, target)
                )


class TestPrefetch:
    def test_producers_fill_ahead(self):
        with L.NativeLoader(1, (1024,), buffers=4, threads=2) as ld:
            consumed = 0
            for _ in range(4):
                ld.next()
                consumed += 1
            # the ring holds buffers-1 fillable slots; producers should
            # get ahead of the consumer within a generous deadline
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if ld.filled_total >= consumed + 1:
                    break
                time.sleep(0.01)
            assert ld.filled_total >= consumed + 1

    def test_view_is_readonly_and_stable_until_next(self):
        with L.NativeLoader(2, (64,), buffers=3, threads=2) as ld:
            x, step = ld.next()
            assert not x.flags.writeable
            snapshot = x.copy()
            # producers refill other slots meanwhile; OUR slot must not
            # change before the next() call
            time.sleep(0.1)
            np.testing.assert_array_equal(x, snapshot)

    def test_bad_config_rejected(self):
        with pytest.raises(ValueError, match="bad loader config"):
            L.NativeLoader(0, (8,), buffers=1)


class TestTrainLoopComposition:
    def test_native_resume_bit_exact(self, devices, tmp_path):
        from jax.sharding import Mesh

        from tests.test_ckpt import _assert_tree_equal
        from tpu_patterns.models.train_loop import TrainLoopConfig, train

        mesh = Mesh(
            np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp")
        )

        def cfg(tmp, **kw):
            base = dict(
                embed=64, heads=8, head_dim=8, seq=32, batch=4, steps=6,
                lr=1e-4, data="native", ckpt_dir=str(tmp), ckpt_every=2,
            )
            base.update(kw)
            return TrainLoopConfig(**base)

        ref = train(mesh, cfg(tmp_path / "a"))
        train(mesh, cfg(tmp_path / "b", steps=4))
        res = train(mesh, cfg(tmp_path / "b", resume=True))
        assert res["start_step"] == 4
        assert np.isfinite(res["loss"])
        assert ref["loss"] == res["loss"]
        _assert_tree_equal(ref["state"], res["state"])

    def test_native_and_synthetic_streams_differ(self, devices):
        # sanity: the two sources are different streams (the native one
        # is NOT jax.random) — a config typo cannot silently alias them
        from jax.sharding import Mesh

        from tpu_patterns.models.train_loop import (
            TrainLoopConfig,
            _make_batch_source,
        )

        mesh = Mesh(
            np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp")
        )
        cfg_s = TrainLoopConfig(embed=64, head_dim=8, seq=32, batch=4)
        cfg_n = TrainLoopConfig(
            embed=64, head_dim=8, seq=32, batch=4, data="native"
        )
        gs, cs = _make_batch_source(cfg_s, mesh, 0)
        gn, cn = _make_batch_source(cfg_n, mesh, 0)
        try:
            assert not np.allclose(
                np.asarray(gs(0)), np.asarray(gn(0)), atol=1e-3
            )
        finally:
            cs()
            cn()
