"""Continuous-batching serve engine over the paged KV cache (serve/):
layout math, token exactness vs per-request dense decode, int8 parity,
admission/deferral scheduling, pool donation, and memory scaling."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tpu_patterns.models.lm import init_lm_params, make_lm_decoder
from tpu_patterns.models.transformer import ModelConfig, _n_experts
from tpu_patterns.serve import (
    Request,
    ServeConfig,
    ServeEngine,
    TRASH_BLOCK,
    make_paged_lm_decoder,
    run_serve,
)
from tpu_patterns.serve.paged import PagedLayout, _pool_write

CFG = dict(embed=64, heads=8, head_dim=8, causal=True, dtype="float32")
VOCAB = 64


def _mesh(devices, shape):
    n = int(np.prod(shape))
    return Mesh(np.array(devices[:n]).reshape(shape), ("dp", "sp", "tp"))


def _decoder_and_params(
    mesh, mcfg, *, n_blocks=13, block_len=8, max_len=40, cache_int8=False,
    seed=0,
):
    dec = make_paged_lm_decoder(
        mesh, mcfg, VOCAB, n_blocks=n_blocks, block_len=block_len,
        max_len=max_len, cache_int8=cache_int8,
    )
    flat = init_lm_params(
        jax.random.key(seed), mcfg, VOCAB, _n_experts(mesh, mcfg)
    )
    return dec, dec.stack_params(flat), flat


def _trace(n, vocab=VOCAB, min_p=3, max_p=20, n_gen=6, seed=1):
    rng = np.random.RandomState(seed)
    return [
        Request(
            rid=i,
            tokens=rng.randint(
                0, vocab, size=rng.randint(min_p, max_p + 1)
            ).tolist(),
            n_gen=n_gen,
        )
        for i in range(n)
    ]


def _dense_ids(mesh, mcfg, flat_params, req, lpd, gen_cap, cache_int8=False):
    """Per-request dense greedy decode — the exactness oracle."""
    sp = int(mesh.shape["sp"])
    lpd = lpd + (-lpd % sp)
    gen_cap = gen_cap + (-gen_cap % sp)
    pre, gen = make_lm_decoder(
        mesh, mcfg, VOCAB, 1, lpd, gen_cap, cache_int8=cache_int8
    )
    toks = np.zeros((1, lpd), np.int32)
    toks[0, : len(req.tokens)] = req.tokens
    lens = jnp.asarray([len(req.tokens)], jnp.int32)
    caches, t0 = pre(flat_params, toks, lens)
    out = [int(np.asarray(t0)[0])]
    if req.n_gen > 1:
        _, ids = gen(flat_params, caches, t0, (lens, 0), req.n_gen - 1)
        out += np.asarray(ids)[0].tolist()
    return out


class TestPagedLayout:
    def test_each_offset_owned_by_one_rank(self):
        lay = PagedLayout(n_blocks=5, block_len=8, sp=4)
        for o in range(8):
            owners = [r for r in range(4) if o // lay.bl_loc == r]
            assert len(owners) == 1, o

    def test_page_positions_cover_block_once_across_ranks(self):
        # union over ranks of page_positions == every position the
        # window covers, each exactly once
        lay = PagedLayout(n_blocks=5, block_len=8, sp=4)
        n_pages = 3
        seen = []
        for r in range(4):
            j = np.arange(n_pages)[:, None]
            ol = np.arange(lay.bl_loc)[None, :]
            seen += (j * lay.block_len + r * lay.bl_loc + ol).reshape(-1).tolist()
        assert sorted(seen) == list(range(n_pages * 8))

    def test_invalid_layouts_rejected(self):
        with pytest.raises(ValueError, match="divide over sp"):
            PagedLayout(n_blocks=4, block_len=6, sp=4)
        with pytest.raises(ValueError, match="trash"):
            PagedLayout(n_blocks=1, block_len=8, sp=1)

    def test_blocks_for(self):
        lay = PagedLayout(n_blocks=4, block_len=8, sp=1)
        assert [lay.blocks_for(n) for n in (1, 8, 9, 16, 17)] == [
            1, 1, 2, 2, 3,
        ]


class TestFactoryContracts:
    def test_dp_rejected(self, devices):
        mesh = _mesh(devices, (2, 2, 2))
        with pytest.raises(ValueError, match="fold dp into sp"):
            make_paged_lm_decoder(
                mesh, ModelConfig(**CFG), VOCAB,
                n_blocks=4, block_len=8, max_len=16,
            )

    def test_block_len_must_divide_sp(self, devices):
        mesh = _mesh(devices, (1, 4, 1))
        with pytest.raises(ValueError, match="divide over sp"):
            make_paged_lm_decoder(
                mesh, ModelConfig(**CFG), VOCAB,
                n_blocks=4, block_len=6, max_len=16,
            )

    def test_submit_validation(self, devices):
        mesh = _mesh(devices, (1, 1, 1))
        dec, params, _ = _decoder_and_params(
            mesh, ModelConfig(**CFG), n_blocks=3, block_len=8, max_len=16
        )
        eng = ServeEngine(dec, params, slots=2)
        with pytest.raises(ValueError, match="needs"):
            # 3 blocks needed, pool has 2 allocatable
            eng.submit(Request(rid=0, tokens=list(range(16)), n_gen=2))
        with pytest.raises(ValueError, match="empty prompt"):
            eng.submit(Request(rid=1, tokens=[], n_gen=2))


class TestExactness:
    """The serving invariant: batching/paging must never change what a
    request would have said alone — greedy ids bit-identical to the
    per-request dense decoder, on the 8-device CPU mesh."""

    @pytest.mark.parametrize(
        "shape,kv,rope,int8",
        [
            ((1, 4, 2), 0, True, False),  # sp x tp, rope positions live
            ((1, 8, 1), 0, False, False),  # sp-only
            ((1, 2, 4), 4, True, False),  # GQA pool over tp=4
            ((1, 4, 2), 0, True, True),  # int8 pool (satellite parity)
            ((1, 1, 1), 2, True, False),  # single device
        ],
    )
    def test_engine_matches_per_request_dense_decode(
        self, devices, shape, kv, rope, int8
    ):
        mesh = _mesh(devices, shape)
        mcfg = ModelConfig(**CFG, depth=2, kv_heads=kv, rope=rope)
        dec, params, flat = _decoder_and_params(
            mesh, mcfg, cache_int8=int8
        )
        reqs = _trace(5)
        eng = ServeEngine(dec, params, slots=3)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        for r in reqs:
            want = _dense_ids(
                mesh, mcfg, flat, r, lpd=20, gen_cap=r.n_gen,
                cache_int8=int8,
            )
            assert got[r.rid] == want, f"rid {r.rid}"

    def test_admission_edges_full_and_min_prompts(self, devices):
        # rows at the window edges: a full-length prompt (every table
        # block used by prefill alone) beside minimum-length rows
        mesh = _mesh(devices, (1, 4, 2))
        mcfg = ModelConfig(**CFG, depth=1, rope=True)
        dec, params, flat = _decoder_and_params(
            mesh, mcfg, n_blocks=17, block_len=8, max_len=40
        )
        rng = np.random.RandomState(3)
        reqs = [
            Request(rid=0, tokens=rng.randint(0, VOCAB, 34).tolist(),
                    n_gen=6),  # 34 + 6 == max_len: full window
            Request(rid=1, tokens=[5], n_gen=6),  # minimum prompt
            Request(rid=2, tokens=[7], n_gen=1),  # retires at prefill
            Request(rid=3, tokens=rng.randint(0, VOCAB, 35).tolist(),
                    n_gen=6),  # span 35+6-1 == the 40-slot window
                               # exactly (the last token's K/V is never
                               # stored, so this FITS)
        ]
        eng = ServeEngine(dec, params, slots=3)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert len(got[2]) == 1
        for r in reqs:
            want = _dense_ids(mesh, mcfg, flat, r, lpd=36, gen_cap=8)
            assert got[r.rid] == want[: r.n_gen], f"rid {r.rid}"


class TestScheduler:
    def test_pool_exhaustion_defers_and_completes(self, devices):
        # a pool too small for the whole trace at once: admission must
        # DEFER (count it), never overcommit, and still finish everything
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(
            mesh, mcfg, n_blocks=5, block_len=8, max_len=24
        )
        reqs = _trace(6, min_p=8, max_p=14, n_gen=4)
        eng = ServeEngine(dec, params, slots=4)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert sorted(got) == [r.rid for r in reqs]
        assert all(len(v) == 4 for v in got.values())
        assert eng.stats["deferrals"] > 0
        # every block came home: the free list is whole again
        assert sorted(eng.free) == list(range(1, 5))

    def test_blocks_recycle_across_requests(self, devices):
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(
            mesh, mcfg, n_blocks=4, block_len=8, max_len=16
        )
        # each request needs 2 blocks; the pool has 3 allocatable — the
        # second wave can only run on the first wave's freed blocks
        reqs = _trace(4, min_p=8, max_p=10, n_gen=3)
        eng = ServeEngine(dec, params, slots=2)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert len(got) == 4

    def test_bucketed_executables_stay_bounded(self, devices):
        # steady-state serving must reuse a small compiled set: row
        # buckets are powers of two capped at slots
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(
            mesh, mcfg, n_blocks=17, block_len=8, max_len=32
        )
        eng = ServeEngine(dec, params, slots=4)
        eng.run([dataclasses.replace(r) for r in _trace(7, n_gen=3)])
        n_prefill, n_step = dec.compiled_buckets()
        assert n_step <= 3  # {1, 2, 4}
        assert n_prefill <= 4


class TestInt8PoolParity:
    """Satellite: _quantize_kv must round-trip through the paged pool
    with the dense path's error bound, ragged lens included."""

    def test_pool_roundtrip_error_bounded_ragged(self):
        lay = PagedLayout(n_blocks=6, block_len=8, sp=1)
        hkv, d = 4, 16
        rng = np.random.RandomState(0)
        pool = {
            "k": jnp.zeros((6, 8, hkv, d), jnp.int8),
            "v": jnp.zeros((6, 8, hkv, d), jnp.int8),
            "ks": jnp.zeros((6, 8, hkv), jnp.float32),
            "vs": jnp.zeros((6, 8, hkv), jnp.float32),
        }
        # two ragged rows: 11 and 3 positions, tables [1,2] and [3]
        lens = [11, 3]
        tables = [[1, 2], [3]]
        x = rng.randn(2, 16, hkv, d).astype(np.float32)
        for b, ln in enumerate(lens):
            for t in range(ln):
                pb = tables[b][t // lay.block_len]
                ob = t % lay.block_len
                pool = _pool_write(
                    pool,
                    jnp.asarray(x[b, t][None]),
                    jnp.asarray(x[b, t][None]),
                    jnp.asarray([pb]),
                    jnp.asarray([ob]),
                )
        # gather back through the tables and check the dense bound:
        # per-slot error <= scale/2 (same gate as TestInt8Cache)
        for b, ln in enumerate(lens):
            for t in range(ln):
                pb = tables[b][t // lay.block_len]
                ob = t % lay.block_len
                q = np.asarray(pool["k"][pb, ob], np.float32)
                s = np.asarray(pool["ks"][pb, ob])
                deq = q * s[:, None]
                err = np.abs(deq - x[b, t])
                assert (err <= s[:, None] * 0.5 + 1e-7).all(), (b, t)

    def test_trash_block_contents_never_leak(self, devices):
        """Poison the trash block with huge values: results must not
        move — routed-away writes land there, masked reads never
        surface it."""
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, flat = _decoder_and_params(
            mesh, mcfg, n_blocks=9, block_len=8, max_len=24
        )
        reqs = _trace(3, n_gen=3)
        eng = ServeEngine(dec, params, slots=2)
        poison = np.array(eng.pool["k"])  # writable copy
        poison[:, TRASH_BLOCK] = 1e4  # huge but finite
        eng.pool["k"] = jnp.asarray(poison)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        for r in reqs:
            want = _dense_ids(mesh, mcfg, flat, r, lpd=20, gen_cap=4)
            assert got[r.rid] == want[: r.n_gen], f"rid {r.rid}"
        assert TRASH_BLOCK not in eng.free  # trash never enters the pool


class TestDonation:
    """The serve path's answer to run_decode's copy-per-chain: ONE pool
    threads through every step, donated and updated in place (extends
    the PR-3 donation tests to the paged cache)."""

    def test_step_consumes_pool_and_aliases(self, devices):
        from tpu_patterns.models.transformer import donation_took

        mesh = _mesh(devices, (1, 4, 2))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(mesh, mcfg)
        pool = dec.init_pool()
        rows = 2
        args = (
            params, pool,
            jnp.zeros((rows,), jnp.int32),
            jnp.asarray([4, 3], jnp.int32),
            jnp.zeros((rows,), jnp.int32),
            jnp.asarray([[1, 2, 0, 0, 0], [3, 0, 0, 0, 0]], jnp.int32),
            jnp.ones((rows,), bool),
        )
        took = donation_took(dec.step_jit(rows), *args)
        if took is None:
            pytest.skip("backend exposes no memory-analysis API")
        assert took, "pool donation was silently declined"
        new_pool, _ = dec.step_jit(rows)(*args)
        assert all(
            v.is_deleted() for v in pool.values()
        ), "donated pool still alive: the step copied instead of aliasing"
        # the returned pool is the live continuation
        assert np.isfinite(np.asarray(new_pool["k"], np.float32)).all()

    def test_alias_analysis_survives_persistent_cache(
        self, devices, tmp_path
    ):
        """The warm-CLI regression: with the persistent compilation
        cache enabled and the step executable already persisted, a
        cache-HIT deserialization reports alias bytes == 0 — the gate
        must compile for real (analysis_compile) and still see the
        donated pool aliased."""
        if not hasattr(jax.config, "jax_enable_compilation_cache"):
            pytest.skip("no compilation-cache config on this JAX")
        from jax.experimental.compilation_cache import (
            compilation_cache as cc,
        )

        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(mesh, mcfg)
        prev_dir = jax.config.jax_compilation_cache_dir
        prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
        jax.config.update("jax_compilation_cache_dir", str(tmp_path))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        cc.reset_cache()  # re-latch onto the tmp cache dir
        try:
            rows = 2
            pool = dec.init_pool()
            args = (
                params, pool,
                jnp.zeros((rows,), jnp.int32),
                jnp.zeros((rows,), jnp.int32),
                jnp.zeros((rows,), jnp.int32),
                jnp.zeros((rows, dec.n_pages), jnp.int32),
                jnp.zeros((rows,), bool),
            )
            dec.step_jit(rows)(*args)  # normal compile -> persisted entry
            assert any(tmp_path.iterdir()), "no cache entry written"
            mm = dec.memory_metrics(params, rows)
            if mm is None:
                pytest.skip("backend exposes no memory-analysis API")
            assert mm["alias_bytes"] >= mm["pool_bytes"]
        finally:
            jax.config.update("jax_compilation_cache_dir", prev_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", prev_min
            )
            cc.reset_cache()

    def test_alias_covers_whole_pool(self, devices):
        mesh = _mesh(devices, (1, 4, 2))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(mesh, mcfg)
        mm = dec.memory_metrics(params, 2)
        if mm is None:
            pytest.skip("backend exposes no memory-analysis API")
        assert mm["alias_bytes"] >= mm["pool_bytes"]
        assert mm["pool_bytes_global"] == dec.pool_nbytes()
        # pool_nbytes is the formula; a REAL pool must weigh the same
        pool = dec.init_pool()
        assert sum(int(v.nbytes) for v in pool.values()) == dec.pool_nbytes()


class TestMemoryScaling:
    def test_cache_bytes_scale_with_pool_not_batch_max_len(self, devices):
        """The PagedAttention claim at the compiled level: doubling the
        POOL moves the step's argument bytes by exactly the pool delta,
        while batch x max_len (slots, table window) stays fixed."""
        mesh = _mesh(devices, (1, 4, 2))
        mcfg = ModelConfig(**CFG, depth=1)
        sizes = {}
        for n_blocks in (9, 17):
            dec, params, _ = _decoder_and_params(
                mesh, mcfg, n_blocks=n_blocks, block_len=8, max_len=40
            )
            mm = dec.memory_metrics(params, 4)
            if mm is None:
                pytest.skip("backend exposes no memory-analysis API")
            sizes[n_blocks] = mm
        d_arg = sizes[17]["argument_bytes"] - sizes[9]["argument_bytes"]
        d_pool = sizes[17]["pool_bytes"] - sizes[9]["pool_bytes"]
        assert d_pool > 0
        assert d_arg == pytest.approx(d_pool)


class TestRunServe:
    def test_measured_pattern_succeeds(self, devices):
        from tpu_patterns.core.results import ResultWriter

        mesh = _mesh(devices, (1, 8, 1))
        cfg = ServeConfig(
            vocab=VOCAB, embed=64, head_dim=8, depth=1, requests=6,
            min_prompt=4, max_prompt=16, gen=6, slots=4, block_len=8,
        )
        writer = ResultWriter()
        (rec,) = run_serve(mesh, cfg, writer)
        assert rec.verdict.value == "SUCCESS", rec.notes
        assert rec.metrics["exact"] == 1.0
        assert rec.metrics["speedup"] > 1.0
        assert rec.metrics["cache_MB"] < rec.metrics["dense_cache_MB"]

    def test_metrics_reach_the_obs_registry(self, devices):
        from tpu_patterns import obs

        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(mesh, mcfg)
        before = obs.counter("tpu_patterns_serve_tokens_total").value
        eng = ServeEngine(dec, params, slots=2)
        eng.run([dataclasses.replace(r) for r in _trace(2, n_gen=3)])
        assert (
            obs.counter("tpu_patterns_serve_tokens_total").value
            == before + 6
        )
        assert obs.histogram("tpu_patterns_serve_step_ms").count > 0
        assert obs.histogram("tpu_patterns_serve_queue_wait_ms").count > 0
