"""Continuous-batching serve engine over the paged KV cache (serve/):
layout math, token exactness vs per-request dense decode, int8 parity,
admission/deferral scheduling, pool donation, memory scaling, CoW
prefix sharing (radix index, refcount invariants, boundary copies), and
self-drafting speculative decoding (wide-step exactness, acceptance)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tpu_patterns.models.lm import init_lm_params, make_lm_decoder
from tpu_patterns.models.transformer import ModelConfig, _n_experts
from tpu_patterns.serve import (
    PrefixIndex,
    Request,
    ServeConfig,
    ServeEngine,
    TRASH_BLOCK,
    make_paged_lm_decoder,
    run_serve,
)
from tpu_patterns.serve.paged import PagedLayout, _pool_write

CFG = dict(embed=64, heads=8, head_dim=8, causal=True, dtype="float32")
VOCAB = 64


def _mesh(devices, shape):
    n = int(np.prod(shape))
    return Mesh(np.array(devices[:n]).reshape(shape), ("dp", "sp", "tp"))


def _decoder_and_params(
    mesh, mcfg, *, n_blocks=13, block_len=8, max_len=40, cache_int8=False,
    seed=0,
):
    dec = make_paged_lm_decoder(
        mesh, mcfg, VOCAB, n_blocks=n_blocks, block_len=block_len,
        max_len=max_len, cache_int8=cache_int8,
    )
    flat = init_lm_params(
        jax.random.key(seed), mcfg, VOCAB, _n_experts(mesh, mcfg)
    )
    return dec, dec.stack_params(flat), flat


def _trace(n, vocab=VOCAB, min_p=3, max_p=20, n_gen=6, seed=1):
    rng = np.random.RandomState(seed)
    return [
        Request(
            rid=i,
            tokens=rng.randint(
                0, vocab, size=rng.randint(min_p, max_p + 1)
            ).tolist(),
            n_gen=n_gen,
        )
        for i in range(n)
    ]


def _dense_ids(mesh, mcfg, flat_params, req, lpd, gen_cap, cache_int8=False):
    """Per-request dense greedy decode — the exactness oracle."""
    sp = int(mesh.shape["sp"])
    lpd = lpd + (-lpd % sp)
    gen_cap = gen_cap + (-gen_cap % sp)
    pre, gen = make_lm_decoder(
        mesh, mcfg, VOCAB, 1, lpd, gen_cap, cache_int8=cache_int8
    )
    toks = np.zeros((1, lpd), np.int32)
    toks[0, : len(req.tokens)] = req.tokens
    lens = jnp.asarray([len(req.tokens)], jnp.int32)
    caches, t0 = pre(flat_params, toks, lens)
    out = [int(np.asarray(t0)[0])]
    if req.n_gen > 1:
        _, ids = gen(flat_params, caches, t0, (lens, 0), req.n_gen - 1)
        out += np.asarray(ids)[0].tolist()
    return out


class TestPagedLayout:
    def test_each_offset_owned_by_one_rank(self):
        lay = PagedLayout(n_blocks=5, block_len=8, sp=4)
        for o in range(8):
            owners = [r for r in range(4) if o // lay.bl_loc == r]
            assert len(owners) == 1, o

    def test_page_positions_cover_block_once_across_ranks(self):
        # union over ranks of page_positions == every position the
        # window covers, each exactly once
        lay = PagedLayout(n_blocks=5, block_len=8, sp=4)
        n_pages = 3
        seen = []
        for r in range(4):
            j = np.arange(n_pages)[:, None]
            ol = np.arange(lay.bl_loc)[None, :]
            seen += (j * lay.block_len + r * lay.bl_loc + ol).reshape(-1).tolist()
        assert sorted(seen) == list(range(n_pages * 8))

    def test_invalid_layouts_rejected(self):
        with pytest.raises(ValueError, match="divide over sp"):
            PagedLayout(n_blocks=4, block_len=6, sp=4)
        with pytest.raises(ValueError, match="trash"):
            PagedLayout(n_blocks=1, block_len=8, sp=1)

    def test_blocks_for(self):
        lay = PagedLayout(n_blocks=4, block_len=8, sp=1)
        assert [lay.blocks_for(n) for n in (1, 8, 9, 16, 17)] == [
            1, 1, 2, 2, 3,
        ]


class TestFactoryContracts:
    def test_dp_rejected(self, devices):
        mesh = _mesh(devices, (2, 2, 2))
        with pytest.raises(ValueError, match="fold dp into sp"):
            make_paged_lm_decoder(
                mesh, ModelConfig(**CFG), VOCAB,
                n_blocks=4, block_len=8, max_len=16,
            )

    def test_block_len_must_divide_sp(self, devices):
        mesh = _mesh(devices, (1, 4, 1))
        with pytest.raises(ValueError, match="divide over sp"):
            make_paged_lm_decoder(
                mesh, ModelConfig(**CFG), VOCAB,
                n_blocks=4, block_len=6, max_len=16,
            )

    def test_submit_validation(self, devices):
        mesh = _mesh(devices, (1, 1, 1))
        dec, params, _ = _decoder_and_params(
            mesh, ModelConfig(**CFG), n_blocks=3, block_len=8, max_len=16
        )
        eng = ServeEngine(dec, params, slots=2)
        with pytest.raises(ValueError, match="needs"):
            # 3 blocks needed, pool has 2 allocatable
            eng.submit(Request(rid=0, tokens=list(range(16)), n_gen=2))
        with pytest.raises(ValueError, match="empty prompt"):
            eng.submit(Request(rid=1, tokens=[], n_gen=2))


class TestExactness:
    """The serving invariant: batching/paging must never change what a
    request would have said alone — greedy ids bit-identical to the
    per-request dense decoder, on the 8-device CPU mesh."""

    @pytest.mark.parametrize(
        "shape,kv,rope,int8",
        [
            ((1, 4, 2), 0, True, False),  # sp x tp, rope positions live
            ((1, 8, 1), 0, False, False),  # sp-only
            ((1, 2, 4), 4, True, False),  # GQA pool over tp=4
            ((1, 4, 2), 0, True, True),  # int8 pool (satellite parity)
            ((1, 1, 1), 2, True, False),  # single device
        ],
    )
    def test_engine_matches_per_request_dense_decode(
        self, devices, shape, kv, rope, int8
    ):
        mesh = _mesh(devices, shape)
        mcfg = ModelConfig(**CFG, depth=2, kv_heads=kv, rope=rope)
        dec, params, flat = _decoder_and_params(
            mesh, mcfg, cache_int8=int8
        )
        reqs = _trace(5)
        eng = ServeEngine(dec, params, slots=3)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        for r in reqs:
            want = _dense_ids(
                mesh, mcfg, flat, r, lpd=20, gen_cap=r.n_gen,
                cache_int8=int8,
            )
            assert got[r.rid] == want, f"rid {r.rid}"

    def test_admission_edges_full_and_min_prompts(self, devices):
        # rows at the window edges: a full-length prompt (every table
        # block used by prefill alone) beside minimum-length rows
        mesh = _mesh(devices, (1, 4, 2))
        mcfg = ModelConfig(**CFG, depth=1, rope=True)
        dec, params, flat = _decoder_and_params(
            mesh, mcfg, n_blocks=17, block_len=8, max_len=40
        )
        rng = np.random.RandomState(3)
        reqs = [
            Request(rid=0, tokens=rng.randint(0, VOCAB, 34).tolist(),
                    n_gen=6),  # 34 + 6 == max_len: full window
            Request(rid=1, tokens=[5], n_gen=6),  # minimum prompt
            Request(rid=2, tokens=[7], n_gen=1),  # retires at prefill
            Request(rid=3, tokens=rng.randint(0, VOCAB, 35).tolist(),
                    n_gen=6),  # span 35+6-1 == the 40-slot window
                               # exactly (the last token's K/V is never
                               # stored, so this FITS)
        ]
        eng = ServeEngine(dec, params, slots=3)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert len(got[2]) == 1
        for r in reqs:
            want = _dense_ids(mesh, mcfg, flat, r, lpd=36, gen_cap=8)
            assert got[r.rid] == want[: r.n_gen], f"rid {r.rid}"


class TestScheduler:
    def test_pool_exhaustion_defers_and_completes(self, devices):
        # a pool too small for the whole trace at once: admission must
        # DEFER (count it), never overcommit, and still finish everything
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(
            mesh, mcfg, n_blocks=5, block_len=8, max_len=24
        )
        reqs = _trace(6, min_p=8, max_p=14, n_gen=4)
        eng = ServeEngine(dec, params, slots=4)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert sorted(got) == [r.rid for r in reqs]
        assert all(len(v) == 4 for v in got.values())
        assert eng.stats["deferrals"] > 0
        # every block came home: the free list is whole again
        assert sorted(eng.free) == list(range(1, 5))

    def test_blocks_recycle_across_requests(self, devices):
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(
            mesh, mcfg, n_blocks=4, block_len=8, max_len=16
        )
        # each request needs 2 blocks; the pool has 3 allocatable — the
        # second wave can only run on the first wave's freed blocks
        reqs = _trace(4, min_p=8, max_p=10, n_gen=3)
        eng = ServeEngine(dec, params, slots=2)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert len(got) == 4

    def test_bucketed_executables_stay_bounded(self, devices):
        # steady-state serving must reuse a small compiled set: row
        # buckets are powers of two capped at slots
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(
            mesh, mcfg, n_blocks=17, block_len=8, max_len=32
        )
        eng = ServeEngine(dec, params, slots=4)
        eng.run([dataclasses.replace(r) for r in _trace(7, n_gen=3)])
        n_prefill, n_step = dec.compiled_buckets()
        assert n_step <= 3  # {1, 2, 4}
        assert n_prefill <= 4


class TestInt8PoolParity:
    """Satellite: _quantize_kv must round-trip through the paged pool
    with the dense path's error bound, ragged lens included."""

    def test_pool_roundtrip_error_bounded_ragged(self):
        lay = PagedLayout(n_blocks=6, block_len=8, sp=1)
        hkv, d = 4, 16
        rng = np.random.RandomState(0)
        pool = {
            "k": jnp.zeros((6, 8, hkv, d), jnp.int8),
            "v": jnp.zeros((6, 8, hkv, d), jnp.int8),
            "ks": jnp.zeros((6, 8, hkv), jnp.float32),
            "vs": jnp.zeros((6, 8, hkv), jnp.float32),
        }
        # two ragged rows: 11 and 3 positions, tables [1,2] and [3]
        lens = [11, 3]
        tables = [[1, 2], [3]]
        x = rng.randn(2, 16, hkv, d).astype(np.float32)
        for b, ln in enumerate(lens):
            for t in range(ln):
                pb = tables[b][t // lay.block_len]
                ob = t % lay.block_len
                pool = _pool_write(
                    pool,
                    jnp.asarray(x[b, t][None]),
                    jnp.asarray(x[b, t][None]),
                    jnp.asarray([pb]),
                    jnp.asarray([ob]),
                )
        # gather back through the tables and check the dense bound:
        # per-slot error <= scale/2 (same gate as TestInt8Cache)
        for b, ln in enumerate(lens):
            for t in range(ln):
                pb = tables[b][t // lay.block_len]
                ob = t % lay.block_len
                q = np.asarray(pool["k"][pb, ob], np.float32)
                s = np.asarray(pool["ks"][pb, ob])
                deq = q * s[:, None]
                err = np.abs(deq - x[b, t])
                assert (err <= s[:, None] * 0.5 + 1e-7).all(), (b, t)

    def test_trash_block_contents_never_leak(self, devices):
        """Poison the trash block with huge values: results must not
        move — routed-away writes land there, masked reads never
        surface it."""
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, flat = _decoder_and_params(
            mesh, mcfg, n_blocks=9, block_len=8, max_len=24
        )
        reqs = _trace(3, n_gen=3)
        eng = ServeEngine(dec, params, slots=2)
        poison = np.array(eng.pool["k"])  # writable copy
        poison[:, TRASH_BLOCK] = 1e4  # huge but finite
        eng.pool["k"] = jnp.asarray(poison)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        for r in reqs:
            want = _dense_ids(mesh, mcfg, flat, r, lpd=20, gen_cap=4)
            assert got[r.rid] == want[: r.n_gen], f"rid {r.rid}"
        assert TRASH_BLOCK not in eng.free  # trash never enters the pool


class TestDonation:
    """The serve path's answer to run_decode's copy-per-chain: ONE pool
    threads through every step, donated and updated in place (extends
    the PR-3 donation tests to the paged cache)."""

    def test_step_consumes_pool_and_aliases(self, devices):
        from tpu_patterns.models.transformer import donation_took

        mesh = _mesh(devices, (1, 4, 2))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(mesh, mcfg)
        pool = dec.init_pool()
        rows = 2
        args = (
            params, pool,
            jnp.zeros((rows,), jnp.int32),
            jnp.asarray([4, 3], jnp.int32),
            jnp.zeros((rows,), jnp.int32),
            jnp.asarray([[1, 2, 0, 0, 0], [3, 0, 0, 0, 0]], jnp.int32),
            jnp.ones((rows,), bool),
        )
        took = donation_took(dec.step_jit(rows), *args)
        if took is None:
            pytest.skip("backend exposes no memory-analysis API")
        assert took, "pool donation was silently declined"
        new_pool, _ = dec.step_jit(rows)(*args)
        assert all(
            v.is_deleted() for v in pool.values()
        ), "donated pool still alive: the step copied instead of aliasing"
        # the returned pool is the live continuation
        assert np.isfinite(np.asarray(new_pool["k"], np.float32)).all()

    def test_alias_analysis_survives_persistent_cache(
        self, devices, tmp_path
    ):
        """The warm-CLI regression: with the persistent compilation
        cache enabled and the step executable already persisted, a
        cache-HIT deserialization reports alias bytes == 0 — the gate
        must compile for real (analysis_compile) and still see the
        donated pool aliased."""
        if not hasattr(jax.config, "jax_enable_compilation_cache"):
            pytest.skip("no compilation-cache config on this JAX")
        from jax.experimental.compilation_cache import (
            compilation_cache as cc,
        )

        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(mesh, mcfg)
        prev_dir = jax.config.jax_compilation_cache_dir
        prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
        jax.config.update("jax_compilation_cache_dir", str(tmp_path))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        cc.reset_cache()  # re-latch onto the tmp cache dir
        try:
            rows = 2
            pool = dec.init_pool()
            args = (
                params, pool,
                jnp.zeros((rows,), jnp.int32),
                jnp.zeros((rows,), jnp.int32),
                jnp.zeros((rows,), jnp.int32),
                jnp.zeros((rows, dec.n_pages), jnp.int32),
                jnp.zeros((rows,), bool),
            )
            dec.step_jit(rows)(*args)  # normal compile -> persisted entry
            assert any(tmp_path.iterdir()), "no cache entry written"
            mm = dec.memory_metrics(params, rows)
            if mm is None:
                pytest.skip("backend exposes no memory-analysis API")
            assert mm["alias_bytes"] >= mm["pool_bytes"]
        finally:
            jax.config.update("jax_compilation_cache_dir", prev_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", prev_min
            )
            cc.reset_cache()

    def test_alias_covers_whole_pool(self, devices):
        mesh = _mesh(devices, (1, 4, 2))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(mesh, mcfg)
        mm = dec.memory_metrics(params, 2)
        if mm is None:
            pytest.skip("backend exposes no memory-analysis API")
        assert mm["alias_bytes"] >= mm["pool_bytes"]
        assert mm["pool_bytes_global"] == dec.pool_nbytes()
        # pool_nbytes is the formula; a REAL pool must weigh the same
        pool = dec.init_pool()
        assert sum(int(v.nbytes) for v in pool.values()) == dec.pool_nbytes()


class TestMemoryScaling:
    def test_cache_bytes_scale_with_pool_not_batch_max_len(self, devices):
        """The PagedAttention claim at the compiled level: doubling the
        POOL moves the step's argument bytes by exactly the pool delta,
        while batch x max_len (slots, table window) stays fixed."""
        mesh = _mesh(devices, (1, 4, 2))
        mcfg = ModelConfig(**CFG, depth=1)
        sizes = {}
        for n_blocks in (9, 17):
            dec, params, _ = _decoder_and_params(
                mesh, mcfg, n_blocks=n_blocks, block_len=8, max_len=40
            )
            mm = dec.memory_metrics(params, 4)
            if mm is None:
                pytest.skip("backend exposes no memory-analysis API")
            sizes[n_blocks] = mm
        d_arg = sizes[17]["argument_bytes"] - sizes[9]["argument_bytes"]
        d_pool = sizes[17]["pool_bytes"] - sizes[9]["pool_bytes"]
        assert d_pool > 0
        assert d_arg == pytest.approx(d_pool)


def _shared_reqs(n, s_len, max_sfx, n_gen=6, seed=2, vocab=VOCAB):
    """n requests whose prompts open with the same s_len tokens."""
    rng = np.random.RandomState(seed)
    shared = rng.randint(0, vocab, s_len).tolist()
    return [
        Request(
            rid=i,
            tokens=shared + rng.randint(
                0, vocab, size=rng.randint(1, max_sfx + 1)
            ).tolist(),
            n_gen=n_gen,
        )
        for i in range(n)
    ]


def _assert_block_invariants(eng):
    """The refcount contract: every allocated block is referenced by
    exactly ref[b] live tables, the trash block is never counted or
    freed, and the index only describes live blocks."""
    from collections import Counter

    live = Counter(
        b for s in eng.active for b in s.table if b != TRASH_BLOCK
    )
    assert dict(eng.ref) == dict(live)
    assert TRASH_BLOCK not in eng.ref and TRASH_BLOCK not in eng.free
    allocated = set(range(1, eng.layout.n_blocks)) - set(eng.free)
    assert allocated == set(live)
    if eng.index is not None:
        assert eng.index.blocks() <= set(live)


class TestPrefixIndex:
    def test_plan_aliases_full_blocks_and_finds_boundary_donor(self):
        idx = PrefixIndex(block_len=4)
        toks = list(range(10))  # blocks (0..3), (4..7); 8,9 partial
        assert idx.insert(toks, [5, 6, 7]) == [5, 6]  # partial not indexed
        idx.materialize([5, 6])
        # full two-block match + 2-token boundary overlap into block 6's
        # sibling?  no sibling: donor must come from an indexed child
        plan = idx.plan(list(range(8)) + [99, 98])
        assert plan.aliased == (5, 6) and plan.donor is None
        # a second prompt diverging INSIDE block 2 gets block 6 as donor
        plan = idx.plan(list(range(6)) + [99, 98])
        assert plan.aliased == (5,)
        assert plan.donor == 6 and plan.donor_len == 2
        assert plan.shared_len(4) == 6

    def test_unmaterialized_children_never_donate(self):
        idx = PrefixIndex(block_len=4)
        idx.insert(list(range(8)), [3, 4])
        plan = idx.plan(list(range(6)) + [99, 98])
        assert plan.aliased == (3,)  # same-wave full alias is fine
        assert plan.donor is None  # but an unwritten block cannot copy
        idx.materialize([4])
        assert idx.plan(list(range(6)) + [99, 98]).donor == 4

    def test_remove_block_prunes_exactly(self):
        idx = PrefixIndex(block_len=2)
        idx.insert([1, 2, 3, 4, 5, 6], [7, 8, 9])
        assert idx.blocks() == {7, 8, 9}
        idx.remove_block(8)  # parent may go before its child
        idx.remove_block(9)
        assert idx.blocks() == {7}
        assert idx.plan([1, 2, 3, 4]).aliased == (7,)
        idx.remove_block(7)
        assert len(idx) == 0 and idx.plan([1, 2]).aliased == ()

    def test_state_round_trip_is_exact(self):
        idx = PrefixIndex(block_len=2)
        idx.insert([1, 2, 3, 4], [5, 6])
        idx.insert([1, 2, 9, 9, 4, 4], [5, 7, 8])
        idx.materialize([5, 7])
        back = PrefixIndex.from_state(2, idx.to_state())
        assert back.to_state() == idx.to_state()
        assert back.blocks() == idx.blocks()
        assert back.plan([1, 2, 9, 9]).aliased == (5, 7)
        # block 6 never materialized: the flag survives the round trip,
        # so it still cannot donate a boundary copy
        assert back.plan([1, 2, 3, 3]).donor is None


class TestPrefixSharing:
    """The CoW radix cache: shared-prefix traces must save blocks and
    change NOTHING about any request's tokens."""

    def test_shared_trace_saves_blocks_ids_exact(self, devices):
        mesh = _mesh(devices, (1, 4, 2))
        mcfg = ModelConfig(**CFG, depth=2, rope=True)
        # pool big enough that the non-shared baseline never defers:
        # the contrast is allocation, not scheduling
        dec, params, flat = _decoder_and_params(
            mesh, mcfg, n_blocks=33, block_len=8, max_len=40
        )
        reqs = _shared_reqs(8, s_len=16, max_sfx=5)
        plain = ServeEngine(dec, params, slots=8)
        want = plain.run([dataclasses.replace(r) for r in reqs])
        eng = ServeEngine(dec, params, slots=8, prefix_share=True)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert got == want
        for r in reqs:  # and the engine-independent oracle agrees
            dense = _dense_ids(mesh, mcfg, flat, r, lpd=24, gen_cap=8)
            assert got[r.rid] == dense[: r.n_gen], f"rid {r.rid}"
        peak_s, peak_p = (
            eng.stats["peak_blocks"], plain.stats["peak_blocks"]
        )
        assert peak_s < peak_p
        # 2 shared blocks x 7 aliasing rows over 8 x 3-4 blocks >= 30%
        assert 1 - peak_s / peak_p >= 0.3
        assert eng.stats["prefix_hit_blocks"] > 0
        assert sorted(eng.free) == list(range(1, 33))
        assert not eng.ref and len(eng.index) == 0

    def test_cow_boundary_copy_ids_exact(self, devices):
        mesh = _mesh(devices, (1, 4, 2))
        mcfg = ModelConfig(**CFG, depth=2, rope=True)
        dec, params, flat = _decoder_and_params(
            mesh, mcfg, n_blocks=25, block_len=8, max_len=40
        )
        rng = np.random.RandomState(7)
        base = rng.randint(0, VOCAB, 24).tolist()  # 3 full blocks
        reqs = [
            # long-lived donor: still active when later waves admit
            Request(rid=0, tokens=list(base), n_gen=12),
            Request(rid=1, tokens=base[:8] + [9, 9], n_gen=2),
            # wave 2: diverges INSIDE block 3 -> boundary CoW copy
            Request(rid=2, tokens=base[:20] + [1, 2, 3], n_gen=4),
            # wave 3: exact 2-block prefix; decode extends a private block
            Request(rid=3, tokens=base[:16], n_gen=4),
        ]
        want = ServeEngine(dec, params, slots=2).run(
            [dataclasses.replace(r) for r in reqs]
        )
        eng = ServeEngine(dec, params, slots=2, prefix_share=True)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert got == want
        assert eng.stats["cow_copies"] >= 1
        assert eng.stats["prefix_hit_blocks"] > 0
        assert sorted(eng.free) == list(range(1, 25))

    def test_sharing_admits_where_rectangles_defer(self, devices):
        """The shared-aware admission satellite: a second shared-prefix
        request whose FULL rectangle exceeds the free list must admit
        immediately by aliasing, where the rectangle count deferred."""
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        # 7 allocatable blocks; each request's RECTANGLE is 4 blocks
        # (prompt 22/23 + gen 4 - 1 -> span 25/26 over block_len 8)
        dec, params, _ = _decoder_and_params(
            mesh, mcfg, n_blocks=8, block_len=8, max_len=32
        )
        rng = np.random.RandomState(2)
        shared = rng.randint(0, VOCAB, 16).tolist()  # 2 full blocks
        reqs = [
            Request(rid=0, tokens=shared + rng.randint(0, VOCAB, 6).tolist(),
                    n_gen=4),
            Request(rid=1, tokens=shared + rng.randint(0, VOCAB, 7).tolist(),
                    n_gen=4),
        ]
        # rectangles: 4 + 4 = 8 > 7 free -> the plain engine defers
        plain = ServeEngine(dec, params, slots=2)
        plain.run([dataclasses.replace(r) for r in reqs])
        assert plain.stats["deferrals"] > 0
        # sharing: request 2 aliases the 2 shared blocks -> 4 + 2 fit
        eng = ServeEngine(dec, params, slots=2, prefix_share=True)
        eng.run([dataclasses.replace(r) for r in reqs])
        assert eng.stats["deferrals"] == 0
        assert eng.stats["prefix_hit_blocks"] >= 2


class TestRefcountInvariants:
    """Property-style: after every scheduler iteration of a mixed
    shared trace — and across quarantine and preempt/resume — the
    refcounts exactly mirror live table references, the trash block is
    never counted, and snapshots reproduce the index bit-for-bit."""

    def _instrument(self, eng):
        orig_retire = eng._retire

        def retire_checked():
            orig_retire()
            _assert_block_invariants(eng)

        eng._retire = retire_checked

    @pytest.mark.parametrize("spec_k", [0, 3])
    def test_invariants_hold_through_mixed_traces(self, devices, spec_k):
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(
            mesh, mcfg, n_blocks=13, block_len=8, max_len=40
        )
        reqs = _shared_reqs(6, s_len=16, max_sfx=5, n_gen=5) + _trace(
            2, n_gen=3, seed=9
        )
        for i, r in enumerate(reqs):
            r.rid = i
        eng = ServeEngine(
            dec, params, slots=3, prefix_share=True, spec_k=spec_k
        )
        self._instrument(eng)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert sorted(got) == list(range(len(reqs)))
        _assert_block_invariants(eng)
        assert not eng.ref and sorted(eng.free) == list(range(1, 13))

    def test_preempt_resume_reproduces_index_and_ids(
        self, devices, tmp_path
    ):
        from tpu_patterns import faults

        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(
            mesh, mcfg, n_blocks=17, block_len=8, max_len=40
        )
        reqs = _shared_reqs(5, s_len=16, max_sfx=5, n_gen=6)
        want = ServeEngine(dec, params, slots=3, prefix_share=True).run(
            [dataclasses.replace(r) for r in reqs]
        )
        snap = str(tmp_path / "snap")
        try:
            faults.configure("serve.step:preempt:after=2:count=1")
            eng = ServeEngine(
                dec, params, slots=3, prefix_share=True,
                snapshot_dir=snap, fingerprint={"t": "idx"},
            )
            eng.run([dataclasses.replace(r) for r in reqs])
            assert eng.preempted_at is not None
            _assert_block_invariants(eng)
            assert len(eng.index) > 0  # shared blocks were in flight
        finally:
            faults.configure("")
        eng2 = ServeEngine(
            dec, params, slots=3, prefix_share=True,
            snapshot_dir=snap, fingerprint={"t": "idx"},
        )
        eng2.restore_snapshot()
        # the exact index: same tree, same blocks, same flags
        assert eng2.index.to_state() == eng.index.to_state()
        assert eng2.ref == eng.ref
        _assert_block_invariants(eng2)
        got = eng2.run([])
        assert got == want  # rides the exactness-after-resume gate


class TestSpecDecode:
    """Self-drafting speculative decoding: the wide verify step may
    only change how many tokens a step commits, never which ones."""

    def test_draft_is_prompt_lookup(self):
        d = ServeEngine._draft
        # trailing 2-gram (7, 8) last seen at position 1 -> continue 9, 5
        assert d([3, 7, 8, 9, 5, 7, 8], 2) == [9, 5]
        assert d([3, 7, 8, 9, 5, 7, 8], 4) == [9, 5, 7, 8]
        # a period-1 loop: the most recent 3-gram match sits one token
        # from the end, so exactly that one continuation is proposed
        assert d([1, 1, 1, 1], 3) == [1]
        assert d([2, 1, 2, 1, 2, 1], 3) == [2, 1]  # period-2 tail
        assert d([1, 2, 3, 4], 3) == []  # nothing repeats
        assert d([5], 3) == []  # too short to match

    @pytest.mark.parametrize(
        "shape,kv,int8",
        [((1, 1, 1), 0, False), ((1, 4, 2), 0, False),
         ((1, 4, 2), 0, True), ((1, 2, 4), 4, False)],  # GQA over tp=4
    )
    def test_spec_ids_bit_identical_to_plain_and_dense(
        self, devices, shape, kv, int8
    ):
        mesh = _mesh(devices, shape)
        mcfg = ModelConfig(**CFG, depth=2, rope=True, kv_heads=kv)
        dec, params, flat = _decoder_and_params(
            mesh, mcfg, cache_int8=int8
        )
        # repetitive prompts: drafts fire, acceptance is real
        rng = np.random.RandomState(4)
        reqs = [
            Request(
                rid=i,
                tokens=(rng.randint(0, VOCAB, 3).tolist() * 7)[
                    : int(rng.randint(6, 19))
                ],
                n_gen=8,
            )
            for i in range(5)
        ]
        want = ServeEngine(dec, params, slots=3).run(
            [dataclasses.replace(r) for r in reqs]
        )
        eng = ServeEngine(dec, params, slots=3, spec_k=4)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert got == want
        for r in reqs:
            dense = _dense_ids(
                mesh, mcfg, flat, r, lpd=20, gen_cap=8, cache_int8=int8
            )
            assert got[r.rid] == dense[: r.n_gen], f"rid {r.rid}"
        assert eng.stats["spec_steps"] > 0
        # fewer scheduler steps than tokens: speculation really batched
        assert eng.stats["spec_tokens"] > eng.stats["spec_row_steps"]

    def test_random_trace_degenerates_to_plain_exactly(self, devices):
        # near-zero acceptance: every step must still commit >= 1 token
        # and the stream must stay identical to plain decode
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(mesh, mcfg)
        reqs = _trace(4, n_gen=6)
        want = ServeEngine(dec, params, slots=2).run(
            [dataclasses.replace(r) for r in reqs]
        )
        eng = ServeEngine(dec, params, slots=2, spec_k=4)
        got = eng.run([dataclasses.replace(r) for r in reqs])
        assert got == want

    def test_spec_metrics_reach_the_registry(self, devices):
        from tpu_patterns import obs

        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(mesh, mcfg)
        h = obs.histogram("tpu_patterns_serve_spec_accepted_tokens")
        before = h.count
        eng = ServeEngine(dec, params, slots=2, spec_k=3)
        eng.run([dataclasses.replace(r) for r in _trace(2, n_gen=4)])
        assert h.count > before
        assert h.sum >= h.count  # every observation commits >= 1 token


class TestRunServePrefixSpec:
    def test_both_records_succeed_on_the_smoke_shape(self, devices):
        from tpu_patterns.core.results import ResultWriter

        mesh = _mesh(devices, (1, 8, 1))
        cfg = ServeConfig(
            vocab=VOCAB, embed=64, head_dim=8, depth=1, requests=8,
            min_prompt=4, max_prompt=24, gen=6, slots=8, block_len=8,
            shared_prefix=16, prefix_share=True, spec_k=4,
        )
        writer = ResultWriter()
        pre, spec = run_serve(mesh, cfg, writer)
        assert pre.verdict.value == "SUCCESS", pre.notes
        assert pre.metrics["exact"] == 1.0
        assert pre.metrics["block_savings"] >= 0.3
        assert (
            pre.metrics["prefix_pool_MB"]
            < pre.metrics["nonshared_pool_MB"]
        )
        assert spec.verdict.value == "SUCCESS", spec.notes
        assert spec.metrics["exact"] == 1.0
        assert spec.metrics["accepted_tokens_per_step"] > 1.0

    def test_sharing_counters_reach_the_registry(self, devices):
        from tpu_patterns import obs

        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(
            mesh, mcfg, n_blocks=33, block_len=8, max_len=40
        )
        hits = obs.counter("tpu_patterns_serve_prefix_hit_blocks_total")
        before = hits.value
        eng = ServeEngine(dec, params, slots=4, prefix_share=True)
        eng.run(
            [dataclasses.replace(r)
             for r in _shared_reqs(4, s_len=16, max_sfx=4, n_gen=3)]
        )
        assert hits.value > before
        assert hits.value - before == eng.stats["prefix_hit_blocks"]


class TestRunServe:
    def test_measured_pattern_succeeds(self, devices):
        from tpu_patterns.core.results import ResultWriter

        mesh = _mesh(devices, (1, 8, 1))
        cfg = ServeConfig(
            vocab=VOCAB, embed=64, head_dim=8, depth=1, requests=6,
            min_prompt=4, max_prompt=16, gen=6, slots=4, block_len=8,
        )
        writer = ResultWriter()
        (rec,) = run_serve(mesh, cfg, writer)
        assert rec.verdict.value == "SUCCESS", rec.notes
        assert rec.metrics["exact"] == 1.0
        assert rec.metrics["speedup"] > 1.0
        assert rec.metrics["cache_MB"] < rec.metrics["dense_cache_MB"]

    def test_metrics_reach_the_obs_registry(self, devices):
        from tpu_patterns import obs

        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(mesh, mcfg)
        before = obs.counter("tpu_patterns_serve_tokens_total").value
        eng = ServeEngine(dec, params, slots=2)
        eng.run([dataclasses.replace(r) for r in _trace(2, n_gen=3)])
        assert (
            obs.counter("tpu_patterns_serve_tokens_total").value
            == before + 6
        )
        assert obs.histogram("tpu_patterns_serve_step_ms").count > 0
        assert obs.histogram("tpu_patterns_serve_queue_wait_ms").count > 0


# -- tiered KV cache (serve/kvtier.py) ----------------------------------


def _conv_reqs(n_conv, bl=8, n_gen=4, seed=4, vocab=VOCAB):
    """The conversation-shaped tier trace: one shared 2-block system
    prompt, per-conversation history growing by one block per turn,
    submitted turn-major (turn 2 arrives after turn 1 retired)."""
    rng = np.random.RandomState(seed)
    shared = rng.randint(0, vocab, size=2 * bl).tolist()
    convs = [
        rng.randint(0, vocab, size=2 * bl).tolist() for _ in range(n_conv)
    ]
    reqs, rid = [], 0
    for turn in (1, 2):
        for g in range(n_conv):
            reqs.append(Request(
                rid=rid, tokens=shared + convs[g][: turn * bl],
                n_gen=n_gen,
            ))
            rid += 1
    return reqs


def _assert_tier_invariants(eng):
    """The tiered refcount contract: refcounts mirror live table
    references exactly, retained blocks are allocated-but-ref-0, the
    free list is disjoint from both the indexed and the retained sets,
    host-resident handles exist in the tier store and nowhere on
    device, and nothing leaks."""
    from collections import Counter

    live = Counter(
        b for s in eng.active for b in s.table if b != TRASH_BLOCK
    )
    assert dict(eng.ref) == dict(live)
    assert TRASH_BLOCK not in eng.ref and TRASH_BLOCK not in eng.free
    allocated = set(range(1, eng.layout.n_blocks)) - set(eng.free)
    assert allocated == set(live) | set(eng.retained)
    assert not set(eng.retained) & set(live)
    assert not set(eng.free) & eng.index.blocks()
    assert not set(eng.free) & set(eng.retained)
    assert eng.index.host_handles() == set(eng.tier.store)
    assert eng.leaked_blocks() == 0


def _tier_engine(devices, *, n_blocks=15, session_dir=None, slots=4,
                 fingerprint=None, cache_int8=False, shape=(1, 1, 1)):
    mesh = _mesh(devices, shape)
    mcfg = ModelConfig(**CFG, depth=1)
    dec, params, flat = _decoder_and_params(
        mesh, mcfg, n_blocks=n_blocks, block_len=8, max_len=40,
        cache_int8=cache_int8,
    )
    eng = ServeEngine(
        dec, params, slots=slots, kv_host_tier=True,
        session_dir=session_dir, fingerprint=fingerprint,
    )
    return eng, dec, params, mesh, mcfg, flat


class TestHostTier:
    """kvtier.HostTier unit contract: store/capacity/commit/load."""

    LEAVES = {
        "k": ((1, 8, 2, 4), np.dtype(np.float32)),
        "v": ((1, 8, 2, 4), np.dtype(np.float32)),
    }

    def _block(self, seed):
        rng = np.random.RandomState(seed)
        return {
            n: rng.randn(*shape).astype(dt)
            for n, (shape, dt) in self.LEAVES.items()
        }

    def test_put_get_discard_and_capacity_order(self):
        from tpu_patterns.serve.kvtier import HostTier

        tier = HostTier(self.LEAVES, block_len=8, capacity_blocks=2)
        h0 = tier.put(self._block(0), (1, 2))
        h1 = tier.put(self._block(1), (1, 2, 3))
        assert len(tier) == 2 and not tier.over_capacity()
        h2 = tier.put(self._block(2), (4,))
        assert tier.over_capacity() and tier.oldest() == h0
        tier.discard(h0)
        assert not tier.over_capacity() and tier.oldest() == h1
        assert np.array_equal(tier.get(h2)["k"], self._block(2)["k"])
        with pytest.raises(ValueError, match="leaves"):
            tier.put({"k": self._block(0)["k"]}, (9,))
        with pytest.raises(ValueError, match="shape"):
            tier.put(
                {"k": np.zeros((2, 8, 2, 4), np.float32),
                 "v": np.zeros((2, 8, 2, 4), np.float32)},
                (9,),
            )

    def test_commit_load_round_trip_bit_exact(self, tmp_path):
        from tpu_patterns.serve.kvtier import HostTier

        sd = str(tmp_path / "sess")
        tier = HostTier(
            self.LEAVES, block_len=8, session_dir=sd,
            fingerprint={"cfg": 1},
        )
        blocks = {h: self._block(h) for h in range(3)}
        handles = {
            tier.put(
                {n: a.copy() for n, a in blocks[i].items()}, (10 + i,)
            ): i
            for i in range(3)
        }
        assert tier.commit() is not None
        fresh = HostTier(
            self.LEAVES, block_len=8, session_dir=sd,
            fingerprint={"cfg": 1},
        )
        entries = fresh.load_session()
        assert sorted(p for p, _ in entries) == [(10,), (11,), (12,)]
        for path, h in entries:
            want = blocks[path[0] - 10]
            got = fresh.get(h)
            for name in want:
                assert np.array_equal(got[name], want[name])

    def test_load_rejects_foreign_fingerprint(self, tmp_path):
        from tpu_patterns.serve.kvtier import HostTier

        sd = str(tmp_path / "sess")
        tier = HostTier(
            self.LEAVES, block_len=8, session_dir=sd,
            fingerprint={"cfg": 1},
        )
        tier.put(self._block(0), (1,))
        tier.commit()
        other = HostTier(
            self.LEAVES, block_len=8, session_dir=sd,
            fingerprint={"cfg": 2},
        )
        with pytest.raises(ValueError, match="different pool/model"):
            other.load_session()

    def test_empty_and_missing_sessions(self, tmp_path):
        from tpu_patterns.serve.kvtier import HostTier

        sd = str(tmp_path / "sess")
        tier = HostTier(self.LEAVES, block_len=8, session_dir=sd)
        assert tier.load_session() == []  # nothing committed yet
        tier.commit()  # an EMPTY tier commits and loads back empty
        fresh = HostTier(self.LEAVES, block_len=8, session_dir=sd)
        assert fresh.load_session() == []
        assert HostTier(self.LEAVES, block_len=8).commit() is None


class TestPrefixIndexHost:
    """Host-resident node state transitions on the radix index."""

    def _index_with(self, tokens, blocks):
        idx = PrefixIndex(block_len=4)
        idx.insert(tokens, blocks)
        idx.materialize(blocks)
        return idx

    def test_evict_restore_round_trip(self):
        idx = self._index_with(list(range(8)), [5, 6])
        assert idx.has_resident_children(5)
        assert not idx.has_resident_children(6)
        idx.evict_block(6, handle=0)
        assert idx.blocks() == {5} and idx.host_handles() == {0}
        plan = idx.plan(list(range(8)))
        assert plan.aliased == (5,) and plan.restores == (0,)
        idx.restore_block(0, 9)  # back onto a DIFFERENT physical id
        assert idx.blocks() == {5, 9} and not idx.host_handles()
        assert idx.plan(list(range(8))).aliased == (5, 9)

    def test_plan_stops_at_device_below_host(self):
        idx = self._index_with(list(range(12)), [3, 4, 5])
        idx.evict_block(4, handle=7)  # middle of the chain
        plan = idx.plan(list(range(12)))
        # device prefix, then the host run; the device node BELOW the
        # unrestored host node is unreachable coverage — not offered
        assert plan.aliased == (3,)
        assert plan.restores == (7,)

    def test_host_nodes_never_donate(self):
        idx = self._index_with(list(range(8)), [5, 6])
        idx.evict_block(6, handle=0)
        plan = idx.plan(list(range(4)) + [4, 5, 99])
        assert plan.donor is None  # the matching child is host-resident

    def test_node_path_and_add_host_path(self):
        idx = self._index_with(list(range(8)), [5, 6])
        assert idx.node_path(6) == tuple(range(8))
        fresh = PrefixIndex(block_len=4)
        # orphan (parent chain missing) is refused
        assert not fresh.add_host_path(tuple(range(8)), 1)
        assert fresh.add_host_path(tuple(range(4)), 0)
        assert fresh.add_host_path(tuple(range(8)), 1)
        assert fresh.add_host_path(tuple(range(8)), 2) is False  # dup
        plan = fresh.plan(list(range(8)))
        assert plan.aliased == () and plan.restores == (0, 1)

    def test_remove_handle_drops_host_subtree(self):
        fresh = PrefixIndex(block_len=4)
        fresh.add_host_path(tuple(range(4)), 0)
        fresh.add_host_path(tuple(range(8)), 1)
        assert sorted(fresh.remove_handle(0)) == [1]
        assert fresh.host_handles() == set()
        assert fresh.plan(list(range(8))).restores == ()

    def test_state_round_trip_with_host_nodes(self):
        idx = self._index_with(list(range(8)), [5, 6])
        idx.evict_block(6, handle=3)
        clone = PrefixIndex.from_state(4, idx.to_state())
        assert clone.to_state() == idx.to_state()
        assert clone.blocks() == {5} and clone.host_handles() == {3}
        # tier-free trees keep the pre-tier 4-element encoding
        plain = self._index_with(list(range(4)), [2])
        assert all(len(e) == 4 for e in plain.to_state())


class TestKVTier:
    """The degradation ladder (alias -> evict -> defer) end to end."""

    def test_ladder_admits_where_defer_only_defers(self, devices):
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(
            mesh, mcfg, n_blocks=15, block_len=8, max_len=40
        )
        reqs = _conv_reqs(6)
        base = ServeEngine(dec, params, slots=4)
        out_base = base.run([dataclasses.replace(r) for r in reqs])
        tier = ServeEngine(dec, params, slots=4, kv_host_tier=True)
        out_tier = tier.run([dataclasses.replace(r) for r in reqs])
        assert base.stats["deferrals"] > 0
        assert tier.stats["deferrals"] == 0
        assert tier.stats["pressure_admits"] > 0
        assert tier.stats["evictions"] > 0
        assert tier.stats["onload_hits"] > 0
        assert tier.stats["steps"] < base.stats["steps"]
        assert out_tier == out_base  # eviction invisible in the ids
        assert tier.leaked_blocks() == 0
        assert len(tier.retained) + len(tier.free) == 14  # all settled

    def test_leaf_first_keeps_shared_parents_hot(self, devices):
        eng, dec, params, *_ = _tier_engine(devices, n_blocks=33)
        reqs = _conv_reqs(2)[:2]  # turn 1 only
        eng.run([dataclasses.replace(r) for r in reqs])
        # retained now: S1, S2 (shared, parents) + 2 private leaves
        assert len(eng.retained) == 4
        shared_blocks = {
            b for b in eng.index.blocks()
            if eng.index.has_resident_children(b)
        }
        assert len(shared_blocks) == 2  # S1 (child S2), S2 (child privs)
        evicted = eng._evict_for(1, set())
        assert evicted == 1
        # the shared prefix stayed device-resident; a leaf went to host
        assert shared_blocks <= eng.index.blocks()
        assert len(eng.tier) == 1
        _assert_tier_invariants(eng)

    def test_restored_blocks_bit_identical(self, devices):
        eng, dec, params, *_ = _tier_engine(devices, n_blocks=15)
        stored: dict[int, dict] = {}
        checked = []
        orig_evict = eng.index.evict_block
        orig_restore = eng.index.restore_block

        def evict_hook(block, handle):
            stored[handle] = {
                n: np.array(a) for n, a in eng.tier.get(handle).items()
            }
            orig_evict(block, handle)

        def restore_hook(handle, block):
            orig_restore(handle, block)
            got = dec.gather_jit(1)(
                eng.pool, np.asarray([block], np.int32)
            )
            for n, a in stored[handle].items():
                assert np.array_equal(np.asarray(got[n])[:, 0], a), n
            checked.append(handle)

        eng.index.evict_block = evict_hook
        eng.index.restore_block = restore_hook
        eng.run([dataclasses.replace(r) for r in _conv_reqs(6)])
        assert eng.stats["onload_hits"] > 0
        assert len(checked) == eng.stats["onload_hits"]

    @pytest.mark.parametrize("int8", [False, True])
    def test_session_survives_restart_bit_exact(
        self, devices, tmp_path, int8
    ):
        sd = str(tmp_path / "sess")
        eng1, dec, params, *_ = _tier_engine(
            devices, session_dir=sd, fingerprint={"t": 1},
            cache_int8=int8,
        )
        reqs = _conv_reqs(6)
        out1 = eng1.run([dataclasses.replace(r) for r in reqs])
        saved = {
            eng1.tier.paths[h]: {
                n: np.array(a) for n, a in eng1.tier.get(h).items()
            }
            for h in eng1.tier.store
        }
        assert saved  # the session banked host blocks
        eng2, *_ = _tier_engine(
            devices, session_dir=sd, fingerprint={"t": 1},
            cache_int8=int8,
        )
        assert eng2.stats["session_loaded"] == len(saved)
        # committed bytes reload bit-exactly, path for path
        for h, path in eng2.tier.paths.items():
            for n, a in eng2.tier.get(h).items():
                assert np.array_equal(a, saved[path][n]), (path, n)
        out2 = eng2.run([dataclasses.replace(r) for r in reqs])
        assert out2 == out1
        assert eng2.stats["onload_hits"] > 0
        assert eng2.stats["prompt_fresh_full_blocks"] == 0
        assert eng2.leaked_blocks() == 0

    def test_property_random_admit_retire_evict_restore_quarantine(
        self, devices
    ):
        """Satellite property test: a seeded random op sequence —
        admissions from a shared-prefix family, scheduler iterations,
        forced evictions, row quarantines — holds every tier invariant
        (refcounts == live references, free/host/retained disjoint,
        leaked == 0, host handles consistent) at every step, and the
        pool settles clean."""
        eng, dec, params, *_ = _tier_engine(devices, n_blocks=17)
        rng = np.random.RandomState(7)
        pending = _conv_reqs(8, n_gen=3) + _trace(4, n_gen=2, seed=11)
        for i, r in enumerate(pending):
            r.rid = i
        pending = pending[::-1]
        for _ in range(60):
            op = rng.randint(4)
            if op == 0 and pending:
                eng.submit(pending.pop())
            eng._retire()
            _assert_tier_invariants(eng)
            # (between _admit and _prefill the admitted slots hold
            # refs but are not yet in eng.active — the loop treats
            # admit+prefill as one transition, and so does this test)
            admitted = eng._admit()
            if admitted:
                eng._prefill(admitted)
                eng._retire()
            _assert_tier_invariants(eng)
            if op == 1 and eng.active:
                victim = eng.active.pop(
                    rng.randint(len(eng.active))
                )
                eng._quarantine([victim], "property-test")
                _assert_tier_invariants(eng)
            if op == 2:
                eng._evict_for(rng.randint(1, 4), set())
                _assert_tier_invariants(eng)
            if eng.active:
                eng._step()
                _assert_tier_invariants(eng)
            if not (pending or eng.queue or eng.active):
                break
        while eng.queue or eng.active:
            eng._retire()
            admitted = eng._admit()
            if admitted:
                eng._prefill(admitted)
                eng._retire()
            if eng.active:
                eng._step()
            _assert_tier_invariants(eng)
        assert not pending
        done = set(eng.done) | set(eng.failed)
        assert done == set(range(20))  # every rid accounted
        _assert_tier_invariants(eng)

    def test_evict_transient_error_retries(self, devices):
        from tpu_patterns import faults

        eng, *_ = _tier_engine(devices, n_blocks=15)
        try:
            faults.configure("serve.evict:error:count=1")
            out = eng.run(
                [dataclasses.replace(r) for r in _conv_reqs(6)]
            )
        finally:
            faults.configure(None)
        # one transient error, retried through: the ladder still ran
        assert eng.stats["evictions"] > 0
        assert eng.stats["tier_fallbacks"] == 0
        assert eng.stats["deferrals"] == 0
        assert sorted(out) == list(range(12))
        _assert_tier_invariants(eng)

    def test_evict_deterministic_error_falls_back_to_defer(
        self, devices
    ):
        from tpu_patterns import faults

        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(
            mesh, mcfg, n_blocks=15, block_len=8, max_len=40
        )
        reqs = _conv_reqs(6)
        want = ServeEngine(dec, params, slots=4).run(
            [dataclasses.replace(r) for r in reqs]
        )
        eng = ServeEngine(dec, params, slots=4, kv_host_tier=True)
        try:
            # every firing, forever: pressure re-attempts eviction on
            # each deferred iteration, so a small count would run out
            # and let a late wave through
            faults.configure("serve.evict:error:count=1000000")
            out = eng.run([dataclasses.replace(r) for r in reqs])
        finally:
            faults.configure(None)
        # every eviction attempt quarantined deterministically: the
        # engine degraded the blocks to the SEED lifetime model —
        # discarded, nothing on host, defer the only remaining rung —
        # and still served the whole trace exactly, corrupting nothing
        assert eng.stats["evictions"] == 0
        assert eng.stats["tier_fallbacks"] > 0
        assert len(eng.tier) == 0  # no host copy ever landed
        assert out == want
        _assert_tier_invariants(eng)

    def test_onload_deterministic_error_prefills_fresh(
        self, devices, tmp_path
    ):
        from tpu_patterns import faults

        sd = str(tmp_path / "sess")
        reqs = _conv_reqs(6)
        eng1, dec, params, *_ = _tier_engine(
            devices, session_dir=sd, fingerprint={"t": 2}
        )
        out1 = eng1.run([dataclasses.replace(r) for r in reqs])
        eng2, *_ = _tier_engine(
            devices, session_dir=sd, fingerprint={"t": 2}
        )
        assert eng2.stats["session_loaded"] > 0
        try:
            faults.configure("serve.onload:error:count=99")
            out2 = eng2.run([dataclasses.replace(r) for r in reqs])
        finally:
            faults.configure(None)
        # restores all failed deterministically: forgotten, prefilled
        # fresh — recompute, never corruption
        assert eng2.stats["onload_hits"] == 0
        assert eng2.stats["tier_fallbacks"] > 0
        assert eng2.stats["prompt_fresh_full_blocks"] > 0
        assert out2 == out1
        assert eng2.leaked_blocks() == 0

    def test_session_dir_requires_tier_and_replica_combo_rejected(
        self, devices
    ):
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(mesh, mcfg)
        with pytest.raises(ValueError, match="requires kv_host_tier"):
            ServeEngine(dec, params, slots=2, session_dir="/tmp/x")

    def test_tier_metrics_reach_the_registry(self, devices):
        from tpu_patterns import obs

        evict_c = obs.counter("tpu_patterns_serve_kv_evictions_total")
        onload_c = obs.counter(
            "tpu_patterns_serve_kv_onload_hits_total"
        )
        ev_h = obs.histogram("tpu_patterns_serve_kv_evict_bytes")
        on_h = obs.histogram("tpu_patterns_serve_kv_onload_bytes")
        before = (evict_c.value, onload_c.value, ev_h.count, on_h.count)
        eng, *_ = _tier_engine(devices, n_blocks=15)
        eng.run([dataclasses.replace(r) for r in _conv_reqs(6)])
        assert evict_c.value - before[0] == eng.stats["evictions"]
        assert onload_c.value - before[1] == eng.stats["onload_hits"]
        assert ev_h.count > before[2] and on_h.count > before[3]


class TestRunServeKVTier:
    def test_kv_tier_record_succeeds(self, devices):
        from tpu_patterns.core.results import ResultWriter

        mesh = _mesh(devices, (1, 4, 2))
        cfg = ServeConfig(
            vocab=VOCAB, embed=64, head_dim=8, depth=1, requests=12,
            gen=6, slots=4, block_len=8, kv_host_tier=True,
        )
        (rec,) = run_serve(mesh, cfg, ResultWriter())
        assert rec.verdict.value == "SUCCESS", rec.notes
        m = rec.metrics
        assert m["exact"] == 1.0
        assert m["defer_baseline_deferrals"] > 0 and m["deferrals"] == 0
        assert m["evictions"] > 0 and m["onload_hits"] > 0
        assert m["goodput_speedup"] > 1.0
        assert m["leaked_blocks"] == 0.0

    def test_kv_session_record_restarts_with_zero_history_prefill(
        self, devices, tmp_path
    ):
        from tpu_patterns.core.results import ResultWriter

        mesh = _mesh(devices, (1, 2, 1))
        cfg = ServeConfig(
            vocab=VOCAB, embed=64, head_dim=8, depth=1, requests=12,
            gen=6, slots=4, block_len=8, kv_host_tier=True,
            session_dir=str(tmp_path / "sess"),
        )
        (rec1,) = run_serve(mesh, cfg, ResultWriter())
        assert rec1.verdict.value == "SUCCESS", rec1.notes
        assert rec1.metrics["session_loaded"] == 0.0
        (rec2,) = run_serve(mesh, cfg, ResultWriter())
        assert rec2.verdict.value == "SUCCESS", rec2.notes
        m = rec2.metrics
        assert m["exact"] == 1.0
        assert m["session_loaded"] > 0
        assert m["onload_hits"] > 0
        assert m["prompt_fresh_full_blocks"] == 0.0

    def test_session_dir_without_tier_rejected(self, devices):
        from tpu_patterns.core.results import ResultWriter

        mesh = _mesh(devices, (1, 1, 1))
        cfg = ServeConfig(
            vocab=VOCAB, embed=64, head_dim=8, depth=1,
            session_dir="/tmp/nope",
        )
        with pytest.raises(ValueError, match="requires --kv_host_tier"):
            run_serve(mesh, cfg, ResultWriter())


class TestKVTierReviewRegressions:
    """Pinned fixes from the pre-commit review of the tier machinery."""

    def test_host_tier_put_copies_never_views(self):
        # a stored block must own its bytes: callers hand over slices
        # of a whole gathered wave, and keeping a view would pin the
        # full padded wave array per block
        from tpu_patterns.serve.kvtier import HostTier

        wave = np.arange(1 * 4 * 8 * 2 * 4, dtype=np.float32).reshape(
            1, 4, 8, 2, 4
        )
        tier = HostTier(
            {"k": ((1, 8, 2, 4), np.dtype(np.float32)),
             "v": ((1, 8, 2, 4), np.dtype(np.float32))},
            block_len=8,
        )
        h = tier.put({"k": wave[:, 1], "v": wave[:, 2]}, (1,))
        assert not np.shares_memory(tier.get(h)["k"], wave)
        assert np.array_equal(tier.get(h)["k"], wave[:, 1])

    def test_insert_never_indexes_beneath_a_host_node(self):
        # a failed onload leaves a host node mid-path; the fresh blocks
        # prefilled beneath it must NOT be indexed there (a device
        # child under a host parent breaks the leaf-first shape)
        idx = PrefixIndex(block_len=4)
        idx.insert(list(range(8)), [5, 6])
        idx.materialize([5, 6])
        idx.evict_block(6, handle=0)
        new = idx.insert(list(range(12)), [5, 7, 8])
        assert new == []  # nothing indexed past the host node
        assert idx.blocks() == {5}
        assert idx.plan(list(range(12))).restores == (0,)

    def test_drop_block_subtree_cascades_host_descendants(self):
        idx = PrefixIndex(block_len=4)
        idx.insert(list(range(8)), [5, 6])
        idx.materialize([5, 6])
        idx.evict_block(6, handle=3)
        assert sorted(idx.drop_block_subtree(5)) == [3]
        assert idx.blocks() == set() and idx.host_handles() == set()

    def test_bounded_host_tier_serves_whole_trace(self, devices):
        # host_tier_blocks=1: capacity drops constantly forget handles
        # — including ones a plan wanted to restore — and the engine
        # must truncate, prefill fresh, and stay exact (this path used
        # to KeyError inside _onload)
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(
            mesh, mcfg, n_blocks=15, block_len=8, max_len=40
        )
        reqs = _conv_reqs(6)
        want = ServeEngine(dec, params, slots=4).run(
            [dataclasses.replace(r) for r in reqs]
        )
        eng = ServeEngine(
            dec, params, slots=4, kv_host_tier=True,
            host_tier_blocks=1,
        )
        out = eng.run([dataclasses.replace(r) for r in reqs])
        assert out == want
        assert len(eng.tier) <= 1
        _assert_tier_invariants(eng)

    def test_pending_cow_donor_never_evicted(self, devices):
        # a retained ref-0 donor queued for a CoW boundary copy must be
        # ineligible for eviction until the copy flushes
        eng, *_ = _tier_engine(devices, n_blocks=33)
        eng._pending_cow = [(7, 9)]
        eng.retained = {7: 0, 8: 1}
        eng.index.insert([0] * 16, [7, 8])
        eng.index.materialize([7, 8])
        cands = eng._evict_candidates(set())
        assert 7 not in cands and 8 in cands
        eng._pending_cow = []
        eng.retained = {}


def _mixed_reqs(n_bulk=2, n_inter=2, n_gen_bulk=8, n_gen_inter=3,
                seed=9, vocab=VOCAB):
    """bulk first (they admit and run), interactive behind them (they
    arrive at a full fleet and must claim their slots)."""
    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(n_bulk + n_inter):
        bulk = i < n_bulk
        reqs.append(Request(
            rid=i,
            tokens=rng.randint(
                0, vocab, size=rng.randint(9, 14)
            ).tolist(),
            n_gen=n_gen_bulk if bulk else n_gen_inter,
            priority="bulk" if bulk else "interactive",
        ))
    return reqs


def _preempt_engine(devices, *, slots=2, n_blocks=21, **kw):
    mesh = _mesh(devices, (1, 1, 1))
    mcfg = ModelConfig(**CFG, depth=1)
    dec, params, _ = _decoder_and_params(
        mesh, mcfg, n_blocks=n_blocks, block_len=8, max_len=40
    )
    eng = ServeEngine(
        dec, params, slots=slots, kv_host_tier=True, preempt="bulk",
        **kw,
    )
    return eng, dec, params


class TestPreemption:
    """Priority classes + mid-flight preemption (``preempt="bulk"``):
    a running bulk row parks into the host KV tier and resumes with
    zero recompute — the stitched stream is bit-identical."""

    def test_preempt_requires_kv_host_tier(self, devices):
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(mesh, mcfg)
        with pytest.raises(ValueError, match="requires kv_host_tier"):
            ServeEngine(dec, params, slots=2, preempt="bulk")
        with pytest.raises(ValueError, match="preempt must be"):
            ServeEngine(dec, params, slots=2, preempt="sometimes")

    def test_interactive_preempts_bulk_and_resume_is_bit_identical(
        self, devices
    ):
        # slots full of running bulk; interactive arrivals claim their
        # slots by parking a bulk row.  Every request — including the
        # preempted-and-resumed bulk — must retire bit-identical to an
        # unpreempted run of the same trace.
        eng, dec, params = _preempt_engine(devices)
        reqs = _mixed_reqs()
        want = ServeEngine(dec, params, slots=2).run(
            [dataclasses.replace(r) for r in reqs]
        )
        out = eng.run([dataclasses.replace(r) for r in reqs])
        assert eng.stats["preempted"] >= 1
        assert eng.stats["preempted_resumed"] >= 1
        assert out == want  # stitched partial + resumed tail, exact
        assert not eng.failed and not eng.shed
        assert not eng.preempted_partial  # every banked partial retired
        assert not eng.preempted_first_ns
        # the lifecycle sees the WHOLE stream: a preempted-and-resumed
        # request's n_out counts its banked tokens too, so goodput
        # accounting never books a preemption as lost tokens
        assert {
            rid: lc["n_out"] for rid, lc in eng.lifecycle.items()
        } == {r.rid: r.n_gen for r in reqs}
        _assert_tier_invariants(eng)

    def test_preempt_fault_fails_open_victim_untouched(self, devices):
        # satellite gate: a deterministic serve.preempt failure aborts
        # THE PREEMPTION — the victim keeps running, the interactive
        # request waits for a natural slot, and nothing is lost
        from tpu_patterns import faults, obs

        eng, dec, params = _preempt_engine(devices)
        reqs = _mixed_reqs()
        want = ServeEngine(dec, params, slots=2).run(
            [dataclasses.replace(r) for r in reqs]
        )
        before = obs.counter(
            "tpu_patterns_faults_injected_total",
            site="serve.preempt", action="error",
        ).value
        try:
            faults.configure("serve.preempt:error:count=99")
            out = eng.run([dataclasses.replace(r) for r in reqs])
        finally:
            faults.configure(None)
        assert obs.counter(
            "tpu_patterns_faults_injected_total",
            site="serve.preempt", action="error",
        ).value > before
        assert eng.stats["preempted"] == 0
        assert out == want  # nobody lost, nobody corrupted
        assert not eng.failed and not eng.shed
        _assert_tier_invariants(eng)

    def test_mitigation_ladder_sheds_bulk_before_interactive(
        self, devices
    ):
        # rung order under an active burn episode: queued bulk sheds
        # FIRST (tagged "bulk first"), the interactive head only when
        # the bulk rungs exhaust
        eng, dec, params = _preempt_engine(
            devices, burn_mitigation="shed"
        )
        inter = _trace(2, n_gen=6, seed=3)
        for r in inter:
            eng.submit(r)
        adm = eng._admit()
        eng._prefill(adm)  # slots full of INTERACTIVE rows
        late = _mixed_reqs(n_bulk=1, n_inter=1, seed=5)
        i2, b3 = late[1], late[0]
        i2.rid, b3.rid = 2, 3
        eng.submit(i2)  # head of the queue
        eng.submit(b3)
        eng.slo.mitigating = lambda: True
        try:
            assert eng._admit() == []
        finally:
            del eng.slo.mitigating
        assert list(eng.shed) == [3, 2]  # bulk shed first
        assert "bulk first" in eng.shed[3]
        assert "bulk first" not in eng.shed[2]
        assert eng.stats["preempted"] == 0  # no bulk was running
        while eng.queue or eng.active:
            eng._retire()
            adm = eng._admit()
            if adm:
                eng._prefill(adm)
                eng._retire()
            if eng.active:
                eng._step()
        assert sorted(eng.done) == [0, 1]
        assert len(eng.done) + len(eng.shed) == 4  # identity closes
        _assert_tier_invariants(eng)

    def test_mitigation_preempt_rung_parks_bulk_then_resumes(
        self, devices
    ):
        # one mitigating poll with no queued bulk: the ladder's middle
        # rung preempts a RUNNING bulk row (work parked, not lost);
        # when the episode clears, the parked leg resumes and retires
        # bit-identical
        eng, dec, params = _preempt_engine(devices)
        reqs = _mixed_reqs()
        want = ServeEngine(dec, params, slots=2).run(
            [dataclasses.replace(r) for r in reqs]
        )
        for r in reqs[:2]:  # the two bulk rows admit and run
            eng.submit(dataclasses.replace(r))
        adm = eng._admit()
        eng._prefill(adm)
        for r in reqs[2:]:  # interactive arrivals find the fleet full
            eng.submit(dataclasses.replace(r))
        eng.burn_mitigation = "shed"
        episodes = iter([True])  # ONE mitigating poll, then clear
        eng.slo.mitigating = lambda: next(episodes, False)
        try:
            adm = eng._admit()  # rung 2 parks a bulk row, then admits
        finally:
            del eng.slo.mitigating
        assert eng.stats["preempted"] >= 1
        if adm:
            eng._prefill(adm)
        while eng.queue or eng.active:
            eng._retire()
            adm = eng._admit()
            if adm:
                eng._prefill(adm)
                eng._retire()
            if eng.active:
                eng._step()
        eng._retire()
        assert eng.stats["preempted_resumed"] >= 1
        assert eng.done == want
        assert not eng.shed and not eng.failed
        _assert_tier_invariants(eng)

    def test_mitigation_preempt_fault_degrades_to_shed(self, devices):
        # the satellite's ladder-degradation gate: serve.preempt fails
        # deterministically while mitigating -> the preempt rung fails
        # OPEN and the ladder falls through to the shed rung; running
        # bulk rows are untouched and still retire exactly
        from tpu_patterns import faults

        eng, dec, params = _preempt_engine(
            devices, burn_mitigation="shed"
        )
        reqs = _mixed_reqs()
        want = ServeEngine(dec, params, slots=2).run(
            [dataclasses.replace(r) for r in reqs]
        )
        for r in reqs[:2]:  # the two bulk rows
            eng.submit(dataclasses.replace(r))
        adm = eng._admit()
        eng._prefill(adm)
        assert all(s.priority == "bulk" for s in eng.active)
        eng.submit(dataclasses.replace(reqs[2]))  # interactive head
        eng.slo.mitigating = lambda: True
        try:
            faults.configure("serve.preempt:error:count=99")
            assert eng._admit() == []
        finally:
            faults.configure(None)
            del eng.slo.mitigating
        assert eng.stats["preempted"] == 0
        assert len(eng.active) == 2  # victims untouched, still running
        assert list(eng.shed) == [2]  # the head shed, loudly
        while eng.queue or eng.active:
            eng._retire()
            adm = eng._admit()
            if adm:
                eng._prefill(adm)
                eng._retire()
            if eng.active:
                eng._step()
        assert eng.done[0] == want[0] and eng.done[1] == want[1]
        assert len(eng.done) + len(eng.shed) == 3
        _assert_tier_invariants(eng)

    def test_preempted_state_survives_snapshot_restore(
        self, devices, tmp_path
    ):
        # a SIGTERM-style snapshot lands while a priority preemption is
        # in flight (banked partial, resumed leg queued): the restored
        # engine finishes the trace bit-identical — the preemption
        # state serializes round-trip
        from tpu_patterns import ckpt, faults

        eng, dec, params = _preempt_engine(
            devices, snapshot_dir=str(tmp_path / "snap"),
            fingerprint={"t": 16},
        )
        reqs = _mixed_reqs()
        want = ServeEngine(dec, params, slots=2).run(
            [dataclasses.replace(r) for r in reqs]
        )
        faults.configure("serve.step:preempt:after=3:count=1")
        try:
            eng.run([dataclasses.replace(r) for r in reqs])
        finally:
            faults.configure(None)
        assert eng.preempted_at is not None
        assert eng.stats["preempted"] >= 1
        assert eng.preempted_partial  # a banked partial is in flight
        eng2, *_ = _preempt_engine(
            devices, snapshot_dir=str(tmp_path / "snap"),
            fingerprint={"t": 16},
        )
        assert eng2.restore_snapshot() == eng.preempted_at
        assert eng2.preempted_partial == eng.preempted_partial
        got = eng2.run([])
        assert got == want
        assert eng2.stats["preempted_resumed"] >= 1
        _assert_tier_invariants(eng2)

    def test_property_random_preempt_shed_quarantine_interleavings(
        self, devices
    ):
        """Satellite property test: seeded random interleavings of
        admit / preempt / shed / quarantine / evict hold the tier +
        refcount invariants at every step, and the lifecycle identity
        done + failed + shed == scheduled closes at settlement with
        zero leaked blocks."""
        eng, dec, params = _preempt_engine(devices, slots=3,
                                           n_blocks=17)
        rng = np.random.RandomState(13)
        pending = []
        for i in range(14):
            pending.append(Request(
                rid=i,
                tokens=rng.randint(
                    0, VOCAB, size=rng.randint(9, 14)
                ).tolist(),
                n_gen=int(rng.randint(3, 7)),
                priority="bulk" if i % 2 else "interactive",
            ))
        pending = pending[::-1]
        scheduled = 14
        for _ in range(80):
            op = rng.randint(5)
            if op == 0 and pending:
                eng.submit(pending.pop())
            eng._retire()
            _assert_tier_invariants(eng)
            admitted = eng._admit()
            if admitted:
                eng._prefill(admitted)
                eng._retire()
            _assert_tier_invariants(eng)
            if op == 1:
                eng._try_preempt()
                _assert_tier_invariants(eng)
            if op == 2 and eng.queue:
                req, _t = eng.queue.pop(
                    rng.randint(len(eng.queue))
                )
                eng._shed_request(
                    req.rid, "property-test", priority=req.priority
                )
                _assert_tier_invariants(eng)
            if op == 3 and eng.active:
                victim = eng.active.pop(
                    rng.randint(len(eng.active))
                )
                eng._quarantine([victim], "property-test")
                _assert_tier_invariants(eng)
            if op == 4:
                eng._evict_for(rng.randint(1, 4), set())
                _assert_tier_invariants(eng)
            if eng.active:
                eng._step()
                _assert_tier_invariants(eng)
            if not (pending or eng.queue or eng.active):
                break
        while eng.queue or eng.active:
            eng._retire()
            admitted = eng._admit()
            if admitted:
                eng._prefill(admitted)
                eng._retire()
            if eng.active:
                eng._step()
            _assert_tier_invariants(eng)
        assert not pending
        assert eng.stats["preempted"] > 0  # the seed exercises the path
        terminal = set(eng.done) | set(eng.failed) | set(eng.shed)
        assert terminal == set(range(scheduled))
        assert (
            len(eng.done) + len(eng.failed) + len(eng.shed)
            == scheduled
        )
        assert not eng.preempted_partial  # nothing banked dangles
        assert eng.leaked_blocks() == 0
        _assert_tier_invariants(eng)


def _sampled_trace(n=8, n_gen=5, seed=21, priorities=False):
    """Mixed greedy/stochastic trace: every third request stays greedy
    (temperature 0), the rest draw seeded temperature/top-k/top-p."""
    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(n):
        temp = 0.0 if i % 3 == 0 else float(rng.uniform(0.5, 1.3))
        reqs.append(Request(
            rid=i,
            tokens=rng.randint(
                0, VOCAB, size=rng.randint(9, 14)
            ).tolist(),
            n_gen=n_gen,
            temperature=temp,
            top_k=int(rng.choice([0, 5, 16])),
            top_p=float(rng.choice([1.0, 0.9, 0.95])),
            seed=int(rng.randint(1 << 30)),
            priority=(
                "bulk" if priorities and i < n // 2 else "interactive"
            ),
        ))
    return reqs


def _sampled_setup(devices, *, attn="dense", shape=(1, 2, 2),
                   n_blocks=17):
    mesh = _mesh(devices, shape)
    mcfg = ModelConfig(**CFG, kv_heads=2, depth=1)
    dec = make_paged_lm_decoder(
        mesh, mcfg, VOCAB, n_blocks=n_blocks, block_len=8, max_len=40,
        attn=attn, sampling=True,
    )
    flat = init_lm_params(
        jax.random.key(0), mcfg, VOCAB, _n_experts(mesh, mcfg)
    )
    return mesh, mcfg, dec, dec.stack_params(flat), flat


class TestSampledDecode:
    """In-kernel seeded sampling: a request's n-th generated token is
    drawn with key fold_in(fold_in(key(0), seed), gen_offset + n) —
    independent of mesh, scheduler batching, attention backend, and
    preemption, so the sampled stream is REPLAYABLE.  These are the
    fixed-seed-oracle exactness gates."""

    def _run(self, dec, params, reqs, *, slots=3, spec_k=0, **kw):
        eng = ServeEngine(dec, params, slots=slots, spec_k=spec_k, **kw)
        out = eng.run([dataclasses.replace(r) for r in reqs])
        assert not eng.failed and eng.leaked_blocks() == 0
        return out, eng

    def test_restart_replay_and_oracle(self, devices):
        # same trace, two fresh engines: bit-identical; and both match
        # the per-request dense batch-1 oracle
        from tpu_patterns.serve.engine import _oracle_expected

        mesh, mcfg, dec, params, flat = _sampled_setup(devices)
        reqs = _sampled_trace()
        a, _ = self._run(dec, params, reqs)
        b, _ = self._run(dec, params, reqs)
        assert a == b
        want = _oracle_expected(
            mesh, int(mesh.shape["sp"]), mcfg, VOCAB, flat, reqs,
            max_prompt=16, max_gen=5,
        )
        assert a == want

    def test_backend_invariance(self, devices):
        # the sampling key never sees the attention backend: dense and
        # pallas engines retire the SAME stochastic ids
        _, _, d1, p1, _ = _sampled_setup(devices, attn="dense")
        _, _, d2, p2, _ = _sampled_setup(devices, attn="pallas")
        reqs = _sampled_trace()
        a, _ = self._run(d1, p1, reqs)
        b, _ = self._run(d2, p2, reqs)
        assert a == b

    def test_spec_decode_sampled_bit_identical(self, devices):
        # verify position t draws key gen_offset + t in-device: the
        # accepted stream equals plain sampled decode exactly
        _, _, dec, params, _ = _sampled_setup(devices)
        plain, _ = self._run(dec, params, _sampled_trace())
        wide, eng = self._run(
            dec, params, _sampled_trace(), spec_k=2
        )
        assert eng.stats.get("spec_accepted", 0) >= 0
        assert plain == wide

    def test_temperature_zero_rows_match_greedy_decoder(self, devices):
        # temp 0 through the sampling core IS greedy: identical ids to
        # the sampling=False decoder on an all-greedy trace
        mesh, mcfg, dec, params, flat = _sampled_setup(devices)
        greedy_dec = make_paged_lm_decoder(
            mesh, mcfg, VOCAB, n_blocks=17, block_len=8, max_len=40,
        )
        gparams = greedy_dec.stack_params(flat)
        reqs = [
            dataclasses.replace(
                r, temperature=0.0, top_k=0, top_p=1.0
            )
            for r in _sampled_trace()
        ]
        a, _ = self._run(dec, params, reqs)
        b, _ = self._run(greedy_dec, gparams, reqs)
        assert a == b

    def test_preemption_does_not_advance_sampling_key(self, devices):
        # a preempted bulk row banks its partial and re-queues with
        # gen_offset advanced by the BANKED length only — the resumed
        # tail continues the same key stream, so the stitched ids equal
        # an unpreempted run exactly
        mesh, mcfg, dec, params, _ = _sampled_setup(
            devices, shape=(1, 1, 1), n_blocks=21
        )
        reqs = _sampled_trace(n=4, n_gen=8, priorities=True)
        for r in reqs:
            if r.priority == "interactive":
                r.n_gen = 3
        want, _ = self._run(dec, params, reqs, slots=2)
        out, eng = self._run(
            dec, params, reqs, slots=2, kv_host_tier=True,
            preempt="bulk",
        )
        assert eng.stats["preempted"] >= 1
        assert eng.stats["preempted_resumed"] >= 1
        assert out == want
        _assert_tier_invariants(eng)

    def test_sampled_state_survives_snapshot_restore(
        self, devices, tmp_path
    ):
        # SNAPSHOT_FORMAT 3: sampling config + gen_offset serialize;
        # the restored engine finishes the stochastic trace
        # bit-identical to an uninterrupted run
        from tpu_patterns import faults
        from tpu_patterns.serve.engine import SNAPSHOT_FORMAT

        assert SNAPSHOT_FORMAT == 3
        mesh, mcfg, dec, params, _ = _sampled_setup(
            devices, shape=(1, 1, 1), n_blocks=21
        )
        reqs = _sampled_trace(n=4, n_gen=8, priorities=True)
        want, _ = self._run(dec, params, reqs, slots=2)
        kw = dict(
            slots=2, kv_host_tier=True, preempt="bulk",
            snapshot_dir=str(tmp_path / "snap"),
            fingerprint={"t": "sampled"},
        )
        eng = ServeEngine(dec, params, **kw)
        faults.configure("serve.step:preempt:after=3:count=1")
        try:
            eng.run([dataclasses.replace(r) for r in reqs])
        finally:
            faults.configure(None)
        assert eng.preempted_at is not None
        eng2 = ServeEngine(dec, params, **kw)
        assert eng2.restore_snapshot() == eng.preempted_at
        # the sampling state came back through the snapshot: every
        # restored row carries its config and a consistent gen_offset
        for s in eng2.active:
            assert s.gen_offset >= 0 and s.top_p > 0
        got = eng2.run([])
        assert got == want
        _assert_tier_invariants(eng2)
