"""Router + replica fleet: hashing, fail-over accounting, fault sites.

Parent-side machinery is tested against FAKE replica processes (no
subprocess, no backend): the fail-over contract is pure accounting —
quarantine releases every lease, every released lease reroutes or
fails loudly, the identity ``done + failed + rerouted == scheduled``
closes.  The real end-to-end fleet (two engine processes on disjoint
mesh slices) runs as a ``slow``-marked test here and as the
``replica-smoke`` / chaos-smoke case (f) CI jobs.
"""

import json
import os
import queue
import random
import sys

import pytest

from tpu_patterns import faults, rt
from tpu_patterns.obs.decisions import DecisionLedger
from tpu_patterns.obs.fleet import FleetObs
from tpu_patterns.serve.engine import Request
from tpu_patterns.serve.replica import (
    FleetResult,
    ReplicaHandle,
    ReplicaManager,
    _StdinSource,
)
from tpu_patterns.serve.router import (
    ConsistentHashRing,
    Router,
    prefix_fingerprint,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    yield
    faults.configure(None)


class TestSiteRegistry:
    def test_fleet_sites_are_registered_with_match_keys(self):
        for site in ("router.route", "replica.spawn", "replica.drain",
                     "replica.obs_ship"):
            assert site in faults.KNOWN_SITES
        assert "replica" in faults.MATCH_KEYS
        (spec,) = faults.parse_spec("replica.spawn:error:replica=1")
        assert spec.match == (("replica", "1"),)
        # PR 16 elastic-fleet sites, with their match keys
        for site in ("fleet.scale_out", "fleet.scale_in",
                     "serve.preempt"):
            assert site in faults.KNOWN_SITES
        (spec,) = faults.parse_spec("fleet.scale_out:error:replica=2")
        assert spec.match == (("replica", "2"),)
        (spec,) = faults.parse_spec("serve.preempt:error:rid=7")
        assert spec.match == (("rid", "7"),)
        (spec,) = faults.parse_spec("router.route:error:rid=3")
        assert spec.match == (("rid", "3"),)
        (spec,) = faults.parse_spec("replica.obs_ship:error:replica=1")
        assert spec.match == (("replica", "1"),)

    def test_store_sites_are_registered_with_match_keys(self):
        # PR 20 fleet prefix-store sites: every store round-trip is
        # injectable, scoped down to a single block's fingerprint
        for site in ("store.publish", "store.fetch", "store.prewarm"):
            assert site in faults.KNOWN_SITES
        assert "fingerprint" in faults.MATCH_KEYS
        (spec,) = faults.parse_spec("store.fetch:error:fingerprint=ab12")
        assert spec.match == (("fingerprint", "ab12"),)
        (spec,) = faults.parse_spec("store.publish:error:rid=3")
        assert spec.match == (("rid", "3"),)
        (spec,) = faults.parse_spec("store.prewarm:error:replica=1")
        assert spec.match == (("replica", "1"),)


class TestPrefixFingerprint:
    def test_same_block_prefix_same_fingerprint(self):
        bl = 8
        a = list(range(16)) + [7, 7]
        b = list(range(16)) + [9]
        assert prefix_fingerprint(a, bl) == prefix_fingerprint(b, bl)

    def test_divergence_inside_the_first_block_scatters(self):
        bl = 8
        a = [1] * 16
        b = [2] + [1] * 15
        assert prefix_fingerprint(a, bl) != prefix_fingerprint(b, bl)

    def test_short_prompts_key_on_raw_tokens(self):
        assert prefix_fingerprint([1, 2], 8) == prefix_fingerprint(
            [1, 2], 8
        )
        assert prefix_fingerprint([1, 2], 8) != prefix_fingerprint(
            [1, 3], 8
        )

    def test_depth_caps_the_key(self):
        bl = 4
        a = [1] * 8 + [5] * 4
        b = [1] * 8 + [6] * 4
        assert prefix_fingerprint(a, bl, 2) == prefix_fingerprint(
            b, bl, 2
        )
        assert prefix_fingerprint(a, bl, 3) != prefix_fingerprint(
            b, bl, 3
        )


class TestConsistentHashRing:
    def test_removal_remaps_only_the_lost_arc(self):
        ring = ConsistentHashRing(["0", "1", "2"], vnodes=64)
        fps = [prefix_fingerprint([i] * 8, 8) for i in range(200)]
        before = {fp: ring.lookup(fp) for fp in fps}
        ring.remove("1")
        for fp, owner in before.items():
            after = ring.lookup(fp)
            if owner != "1":
                # survivors keep their arcs: prefix affinity preserved
                assert after == owner
            else:
                assert after in ("0", "2")

    def test_restore_brings_the_arc_back(self):
        ring = ConsistentHashRing(["0", "1"], vnodes=32)
        fp = prefix_fingerprint([3] * 8, 8)
        owner = ring.lookup(fp)
        ring.remove(owner)
        assert ring.lookup(fp) != owner
        ring.restore(owner)
        assert ring.lookup(fp) == owner

    def test_empty_live_set_is_none(self):
        ring = ConsistentHashRing(["0"], vnodes=8)
        ring.remove("0")
        assert ring.lookup("deadbeef") is None


class TestRouter:
    def test_prefix_policy_co_locates_shared_prefixes(self):
        r = Router(["0", "1"], block_len=8, policy="prefix")
        shared = list(range(16))
        a = r.route(0, shared + [1])
        b = r.route(1, shared + [2, 3])
        assert a == b
        assert r.prefix_hits == 1  # the repeat fingerprint counted

    def test_round_robin_rotates_over_the_live_set(self):
        r = Router(["0", "1", "2"], block_len=8, policy="round_robin")
        picks = [r.route(i, [i] * 4) for i in range(6)]
        assert picks == ["0", "1", "2", "0", "1", "2"]

    def test_quarantined_replica_leaves_rotation(self):
        r = Router(["0", "1"], block_len=8, policy="prefix")
        shared = list(range(16))
        primary = r.route(0, shared)
        r.quarantine(primary)
        assert r.route(1, shared) != primary
        assert r.live() == {"0", "1"} - {primary}

    def test_fallback_counts_reroutes(self):
        r = Router(["0", "1"], block_len=8, policy="prefix")
        r.fallback(0, [1] * 8)
        assert r.reroutes == 1

    def test_no_live_replica_is_loud(self):
        r = Router(["0"], block_len=8)
        r.quarantine("0")
        with pytest.raises(RuntimeError, match="no live replica"):
            r.route(0, [1] * 8)

    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError, match="policy"):
            Router(["0"], block_len=8, policy="least_loaded")

    def test_route_site_fires_with_rid_and_replica_ctx(self):
        # the router.route fault site: error fails the primary choice
        faults.configure("router.route:error:rid=1:count=1")
        r = Router(["0", "1"], block_len=8, policy="round_robin")
        r.route(0, [1] * 8)  # rid mismatch: no firing
        with pytest.raises(faults.InjectedFault):
            r.route(1, [1] * 8)
        r.route(1, [1] * 8)  # count spent: flows again


class _FakeStdin:
    def __init__(self):
        self.sent = []
        self.broken = False

    def write(self, s):
        if self.broken:
            raise BrokenPipeError("gone")
        self.sent.append(json.loads(s))

    def flush(self):
        pass

    def close(self):
        pass


class _FakeProc:
    """A live 'process' whose stdout never speaks (the reader thread
    parks on a queue-backed line iterator)."""

    def __init__(self):
        self.stdin = _FakeStdin()
        self._lines: queue.Queue = queue.Queue()
        self.stdout = iter(self._lines.get, None)
        self.dead = False
        _FAKE_PROCS.append(self)

    def poll(self):
        return 1 if self.dead else None

    def wait(self, timeout=None):
        return 0


# every _FakeProc parks a real ReplicaHandle reader thread on its line
# iterator; without a release the full suite accumulates one blocked
# thread per handle ever created.  The autouse fixture below feeds each
# iterator its None sentinel at test teardown so the reader exits.
_FAKE_PROCS: list = []


@pytest.fixture(autouse=True)
def _release_fake_readers():
    yield
    while _FAKE_PROCS:
        _FAKE_PROCS.pop()._lines.put(None)


@pytest.fixture
def no_real_kill(monkeypatch):
    """ReplicaHandle.kill group-SIGKILLs proc.pid — lethal on a fake.
    Neutralize the syscall, keep the bookkeeping."""
    killed = []
    monkeypatch.setattr(
        "tpu_patterns.exec.proc.kill_process_group",
        lambda p: killed.append(p),
    )
    return killed


def _manager(n=2, policy="prefix", obs_base=None):
    mgr = ReplicaManager.__new__(ReplicaManager)
    mgr.n = n
    mgr.base_env = {}
    mgr.work_dir = ""
    mgr.child_cfg = {"block_len": 8}
    mgr.device_slices = [[i] for i in range(n)]
    mgr.sp, mgr.tp = 1, 1
    mgr.watchdog_s = 120.0
    mgr.obs_watchdog_s = 120.0
    mgr.warm = []
    mgr.retry_policy = rt.RetryPolicy(max_attempts=2, backoff_base_s=0.0)
    mgr.router = Router(
        [str(r) for r in range(n)], block_len=8, policy=policy
    )
    mgr.inbox = queue.Queue()
    mgr.handles = {}
    mgr.spawn_retries = 0
    mgr.drains = 0
    mgr.fleet_obs = FleetObs(obs_base)
    mgr.obs_stalls = 0
    mgr.elastic = None
    mgr._spare = []
    mgr.decisions = DecisionLedger()
    mgr.roles = {}
    mgr._decode_rr = 0
    for r in range(n):
        h = ReplicaHandle(str(r), _FakeProc(), mgr.inbox)
        h.state = "ready"
        mgr.handles[str(r)] = h
    return mgr


def _res(mgr, reqs):
    return FleetResult(
        scheduled=len(reqs),
        requests_by_rid={r.rid: r for r in reqs},
    )


def _reqs(n, bl=8):
    return [
        Request(rid=i, tokens=[i % 3] * bl + [i], n_gen=4)
        for i in range(n)
    ]


class TestFailover:
    def test_quarantine_releases_every_lease(self, no_real_kill):
        # the rt property the satellite pins: however many requests a
        # replica holds when it goes down, its ledger must empty and
        # every rid must land in rerouted/failed — never limbo
        for seed in range(5):
            rng = random.Random(seed)
            mgr = _manager(3)
            reqs = _reqs(rng.randint(1, 12))
            res = _res(mgr, reqs)
            for r in reqs:
                mgr._dispatch(r, res)
            victim = mgr.handles[rng.choice(["0", "1", "2"])]
            held_before = set(victim.leases.held())
            mgr._replica_down(victim, "test kill", res)
            assert len(victim.leases) == 0
            assert victim.state == "dead"
            for rid in held_before:
                assert rid in res.rerouted
                # rerouted rids re-lease on a SURVIVOR
                assert any(
                    rid in h.leases
                    for h in mgr.handles.values()
                    if h is not victim
                ) or rid in res.failed

    def test_survivors_are_told_to_checkpoint_on_death(
        self, no_real_kill
    ):
        mgr = _manager(2)
        res = _res(mgr, [])
        mgr._replica_down(mgr.handles["0"], "test", res)
        sent = mgr.handles["1"].proc.stdin.sent
        assert {"op": "checkpoint"} in sent

    def test_drained_handback_reroutes_pending(self, no_real_kill):
        mgr = _manager(2)
        reqs = _reqs(4)
        res = _res(mgr, reqs)
        for r in reqs:
            mgr._dispatch(r, res)
        victim = mgr.handles["0"]
        if not len(victim.leases):
            victim = mgr.handles["1"]
        held = set(victim.leases.held())
        mgr._handle(victim.id, {
            "op": "drained", "pending": sorted(held),
            "snapshot_step": 3,
            "stats": {"leaked_blocks": 0},
        }, res)
        assert victim.state == "drained"
        assert len(victim.leases) == 0
        assert held <= res.rerouted
        assert mgr.drains == 1

    def test_consecutive_failures_open_breaker_and_drain(
        self, no_real_kill
    ):
        mgr = _manager(2)
        reqs = _reqs(6)
        res = _res(mgr, reqs)
        for r in reqs:
            mgr._dispatch(r, res)
        victim = next(
            h for h in mgr.handles.values() if len(h.leases) >= 2
        )
        rids = sorted(victim.leases.held())[:2]
        for rid in rids:
            mgr._handle(
                victim.id,
                {"op": "failed", "rid": rid, "reason": "step died"},
                res,
            )
        assert victim.state == "quarantined"
        assert {"op": "drain"} in victim.proc.stdin.sent
        # the two failed rows rerouted instead of finalizing: the
        # replica was sick, not the requests
        assert set(rids) <= res.rerouted

    def test_single_failure_on_healthy_replica_finalizes(
        self, no_real_kill
    ):
        mgr = _manager(2)
        reqs = _reqs(2)
        res = _res(mgr, reqs)
        for r in reqs:
            mgr._dispatch(r, res)
        victim = next(
            h for h in mgr.handles.values() if len(h.leases)
        )
        rid = sorted(victim.leases.held())[0]
        mgr._handle(
            victim.id,
            {"op": "failed", "rid": rid, "reason": "poisoned row"},
            res,
        )
        for other in sorted(victim.leases.held()):
            # later successes prove the replica healthy (breaker reset)
            mgr._handle(
                victim.id, {"op": "done", "rid": other, "ids": [1]},
                res,
            )
        mgr._finalize_tentative(res)
        assert res.failed.get(rid) == "poisoned row"
        assert rid not in res.rerouted

    def test_reroute_budget_is_one(self, no_real_kill):
        mgr = _manager(3)
        reqs = _reqs(1)
        res = _res(mgr, reqs)
        mgr._dispatch(reqs[0], res)
        first = next(
            h for h in mgr.handles.values() if len(h.leases)
        )
        mgr._replica_down(first, "kill 1", res)
        second = next(
            h for h in mgr.handles.values() if len(h.leases)
        )
        mgr._replica_down(second, "kill 2", res)
        assert 0 in res.failed  # budget spent: loud, not limbo
        assert res.covered()

    def test_spawn_site_retries_then_succeeds(
        self, monkeypatch, tmp_path, no_real_kill
    ):
        faults.configure("replica.spawn:error:count=1")
        monkeypatch.setattr(
            "tpu_patterns.exec.proc.popen_in_group",
            lambda *a, **k: _FakeProc(),
        )
        mgr = _manager(1)
        mgr.work_dir = str(tmp_path)
        h = mgr._spawn_one(0)
        assert mgr.spawn_retries == 1  # attempt 1 faulted, 2 spawned
        assert h.proc.stdin.sent[0]["op"] == "init"

    def test_spawn_deterministic_failure_quarantines(
        self, monkeypatch, tmp_path, no_real_kill
    ):
        faults.configure("replica.spawn:error:count=99")
        monkeypatch.setattr(
            "tpu_patterns.exec.proc.popen_in_group",
            lambda *a, **k: _FakeProc(),
        )
        mgr = _manager(1)
        mgr.work_dir = str(tmp_path)
        with pytest.raises(faults.Quarantined):
            mgr._spawn_one(0)

    def test_drain_site_error_reads_as_unresponsive(self, no_real_kill):
        # replica.drain firing: the drain request fails -> the replica
        # is treated exactly like a dead one (killed, leases settled)
        faults.configure("replica.drain:error:count=1")
        mgr = _manager(2)
        reqs = _reqs(4)
        res = _res(mgr, reqs)
        for r in reqs:
            mgr._dispatch(r, res)
        victim = next(
            h for h in mgr.handles.values() if len(h.leases)
        )
        held = set(victim.leases.held())
        mgr._quarantine(victim, res)
        assert victim.state == "dead"
        assert len(victim.leases) == 0
        assert held <= (res.rerouted | set(res.failed))

    def test_counts_identity_closes(self, no_real_kill):
        mgr = _manager(2)
        reqs = _reqs(6)
        res = _res(mgr, reqs)
        for r in reqs:
            mgr._dispatch(r, res)
        victim = next(
            h for h in mgr.handles.values() if len(h.leases)
        )
        survivor = next(
            h for h in mgr.handles.values() if h is not victim
        )
        mgr._replica_down(victim, "chaos", res)
        # the survivor completes everything it now holds
        for rid in sorted(survivor.leases.held()):
            mgr._handle(
                survivor.id, {"op": "done", "rid": rid, "ids": [rid]},
                res,
            )
        mgr._finalize_tentative(res)
        c = res.counts()
        assert (
            c["done"] + c["failed"] + c["rerouted"] == res.scheduled
        )
        assert res.covered()


class _FakeEngine:
    """Just enough engine surface for _StdinSource.report()."""

    def __init__(self, replica="1"):
        self.done = {}
        self.failed = {}
        self.shed = {}
        self.stats = {"steps": 0, "tokens": 0}
        self.replica = replica
        self.queue = []
        self.active = []
        self.first_ns = {}
        self.handoffs = {}
        self.adopt_queue = []


@pytest.fixture(autouse=True)
def _isolated_obs(tmp_path):
    from tpu_patterns import obs

    obs.flight_recorder().clear()
    obs.metrics_registry().clear()
    obs.configure(str(tmp_path))
    yield
    obs.flight_recorder().clear()
    obs.metrics_registry().clear()
    obs.configure(None)


class TestFleetObsShipping:
    def _source(self, shipper):
        sent = []
        src = _StdinSource(
            iter([]), _FakeEngine(), sent.append, shipper=shipper
        )
        src._last_hb_ns = 0
        return src, sent

    def test_report_ships_bounded_batches_after_control_traffic(self):
        from tpu_patterns import obs
        from tpu_patterns.obs.fleet import ObsShipper

        shipper = ObsShipper(max_batch=4)
        for i in range(10):
            obs.event("spam", i=i)
        src, sent = self._source(shipper)
        src.report()
        ops = [m["op"] for m in sent]
        # hb first, obs last; the batch is bounded at max_batch
        assert ops.index("hb") < ops.index("obs")
        batch = next(m for m in sent if m["op"] == "obs")
        assert len(batch["entries"]) == 4
        assert batch["backlog"] == 6
        assert "clock_ns" in batch["clock"]
        # the tail drains the rest
        src.ship_tail()
        total = sum(
            len(m["entries"]) for m in sent if m["op"] == "obs"
        )
        assert total == 10

    def test_obs_ship_fault_suppresses_the_batch_not_the_heartbeat(
        self,
    ):
        from tpu_patterns import obs, rt
        from tpu_patterns.obs.fleet import ObsShipper

        faults.configure("replica.obs_ship:error:count=1")
        shipper = ObsShipper()
        obs.event("something")
        src, sent = self._source(shipper)
        src.report()
        assert any(m["op"] == "hb" for m in sent)
        assert not any(m["op"] == "obs" for m in sent)
        assert rt.metric_total(
            "tpu_patterns_faults_injected_total",
            site="replica.obs_ship",
        ) == 1.0
        # count spent: the suppressed entries ship at the next boundary
        src._last_hb_ns = 0
        src.report()
        batch = next(m for m in sent if m["op"] == "obs")
        assert any(
            e.get("name") == "something" for e in batch["entries"]
        )

    def test_obs_message_absorbs_into_fleet_series_and_disk(
        self, tmp_path, no_real_kill
    ):
        from tpu_patterns import rt

        mgr = _manager(2, obs_base=str(tmp_path))
        res = _res(mgr, [])
        mgr._handle("1", {
            "op": "obs",
            "entries": [
                {"kind": "span", "name": "req.queued", "t0_ns": 5,
                 "dur_ns": 2, "tid": 9, "span_id": 1,
                 "attrs": {"rid": 0}},
            ],
            "metrics": [
                {"metric": "tpu_patterns_serve_requests_total",
                 "type": "counter", "labels": {}, "value": 3.0},
            ],
            "clock": {"wall_ts": 100.0, "clock_ns": 50},
        }, res)
        # cumulative -> delta merge into the fleet namespace
        assert rt.metric_total(
            "tpu_patterns_fleet_serve_requests_total", replica="1"
        ) == 3.0
        mgr._handle("1", {
            "op": "obs", "entries": [],
            "metrics": [
                {"metric": "tpu_patterns_serve_requests_total",
                 "type": "counter", "labels": {}, "value": 5.0},
            ],
        }, res)
        assert rt.metric_total(
            "tpu_patterns_fleet_serve_requests_total", replica="1"
        ) == 5.0
        shipped = tmp_path / "replica-1" / "shipped.jsonl"
        lines = [
            json.loads(ln)
            for ln in shipped.read_text().splitlines() if ln.strip()
        ]
        assert any(ln.get("kind") == "meta" for ln in lines)
        assert any(ln.get("name") == "req.queued" for ln in lines)
        assert mgr.fleet_obs.total(
            "tpu_patterns_serve_requests_total"
        ) == 5.0
        mgr.fleet_obs.close()

    def test_dispatch_stamps_journey_id_and_route_anchor(
        self, no_real_kill
    ):
        from tpu_patterns import obs

        mgr = _manager(2)
        req = _reqs(1)[0]
        res = _res(mgr, [req])
        mgr._dispatch(req, res)
        assert req.jid.startswith("j")
        routes = [
            e for e in obs.flight_recorder().snapshot()
            if e["name"] == "journey.route"
        ]
        assert len(routes) == 1
        assert routes[0]["attrs"]["jid"] == req.jid
        # the dispatched protocol message carries the journey id
        sent = [
            m
            for h in mgr.handles.values()
            for m in h.proc.stdin.sent
            if m.get("op") == "req"
        ]
        assert sent[0]["jid"] == req.jid
        # a reroute keeps the SAME journey (one stitched flow)
        victim = next(
            h for h in mgr.handles.values() if len(h.leases)
        )
        mgr._replica_down(victim, "test", res)
        reroutes = [
            e for e in obs.flight_recorder().snapshot()
            if e["name"] == "journey.reroute"
        ]
        assert reroutes and reroutes[0]["attrs"]["jid"] == req.jid

    def test_obs_stall_watchdog_warns_once_without_killing(
        self, tmp_path, no_real_kill
    ):
        from tpu_patterns import obs, rt
        from tpu_patterns.core.timing import clock_ns

        mgr = _manager(2)
        mgr.obs_watchdog_s = 1.0
        res = _res(mgr, [])
        h = mgr.handles["1"]
        h.leases.acquire(0, meta=None)
        h.last_msg_ns = clock_ns()  # heartbeat fresh...
        h.last_obs_ns = clock_ns() - int(10e9)  # ...obs channel silent
        mgr._check_watchdogs(res)
        assert h.obs_stalled and h.state == "ready"  # WARN, not kill
        assert mgr.obs_stalls == 1
        assert rt.metric_total(
            "tpu_patterns_replica_obs_stalls_total", replica="1"
        ) == 1.0
        ring = [
            e["name"] for e in obs.flight_recorder().snapshot()
        ]
        assert "replica.obs_stall" in ring
        wd = tmp_path / "watchdog.jsonl"
        rec = json.loads(wd.read_text().splitlines()[-1])
        assert rec["mode"] == "watchdog_obs_stall"
        assert rec["verdict"] == "WARNING"
        # fires once: a second poll stays quiet
        mgr._check_watchdogs(res)
        assert mgr.obs_stalls == 1

    def test_mirrors_reconcile_against_shipped_truth(self):
        mgr = _manager(2)
        res = _res(mgr, [])
        # replica 0 checkpoints AND ships the counter: mirror must
        # match the shipped truth and NOT double into the fleet series
        mgr._handle("0", {"op": "obs", "entries": [], "metrics": [
            {"metric": "tpu_patterns_replica_drains_total",
             "type": "counter",
             "labels": {"replica": "0", "mode": "checkpoint"},
             "value": 1.0},
        ]}, res)
        mgr._handle("0", {"op": "checkpointed", "step": 3}, res)
        # replica 1 checkpoints but dies before its first ship: the
        # mirror is the fallback
        mgr._handle("1", {"op": "checkpointed", "step": 3}, res)
        notes = mgr.fleet_obs.reconcile()
        assert notes == []
        assert mgr.fleet_obs.total(
            "tpu_patterns_replica_drains_total", mode="checkpoint"
        ) == 2.0

    def test_mirror_mismatch_is_loud(self):
        mgr = _manager(2)
        res = _res(mgr, [])
        # replica 0 shipped (so mirrors are demoted to assertions) but
        # its shipped ledger never saw the drain counter
        mgr._handle("0", {"op": "obs", "entries": [], "metrics": []},
                    res)
        mgr._handle("0", {"op": "checkpointed", "step": 3}, res)
        notes = mgr.fleet_obs.reconcile()
        assert len(notes) == 1
        assert "mirror" in notes[0]


@pytest.mark.slow
class TestReplicaEndToEnd:
    def test_two_replica_fleet_serves_exactly(self, tmp_path):
        # the real thing: two engine processes on disjoint 4-device
        # slices through the CLI entry (CI runs this as replica-smoke)
        import subprocess as sp

        jsonl = tmp_path / "fleet.jsonl"
        env = {
            k: v for k, v in os.environ.items() if k != "PYTHONPATH"
        }
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env.pop("TPU_PATTERNS_FAULTS", None)
        rc = sp.run(
            [sys.executable, "-m", "tpu_patterns", "--jsonl",
             str(jsonl), "serve", "--dp", "1", "--tp", "2",
             "--vocab", "64", "--embed", "64", "--head_dim", "8",
             "--depth", "1", "--requests", "8", "--min_prompt", "4",
             "--max_prompt", "16", "--gen", "6", "--slots", "4",
             "--block_len", "8", "--replicas", "2",
             "--min_replica_speedup", "0",
             "--replica_dir", str(tmp_path / "work")],
            env=env,
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            )),
        ).returncode
        assert rc == 0
        rec = [
            json.loads(ln) for ln in jsonl.read_text().splitlines()
            if ln.strip()
        ][-1]
        m = rec["metrics"]
        assert rec["verdict"] == "SUCCESS"
        assert m["exact"] == 1.0 and m["covered"] == 1.0
        assert m["leaked_blocks"] == 0.0
        assert (
            m["done"] + m["failed"] + m["rerouted"] == m["scheduled"]
        )


def _elastic_manager(n=1, reserve=1, slots=2, **ecfg_kw):
    """A fake-process manager with the PR 16 elastic plane attached:
    router ring over ALL n + reserve ids, reserves quarantined, a
    zero-hysteresis policy (the policy's own hysteresis is pinned in
    test_elastic.py — these tests exercise the ACTIONS)."""
    from tpu_patterns.serve.elastic import ElasticConfig, ElasticPolicy

    mgr = _manager(n)
    n_total = n + reserve
    mgr.child_cfg = {"block_len": 8, "slots": slots}
    mgr.device_slices = [[i] for i in range(n_total)]
    mgr.router = Router(
        [str(r) for r in range(n_total)], block_len=8
    )
    ecfg_kw.setdefault("sustain_s", 0.0)
    ecfg_kw.setdefault("cooldown_s", 0.0)
    mgr.elastic = ElasticPolicy(
        ElasticConfig(reserve=reserve, **ecfg_kw)
    )
    mgr._spare = list(range(n, n_total))
    for r in mgr._spare:
        mgr.router.quarantine(str(r))
    return mgr


class TestElasticFleet:
    def test_scale_out_is_warm_up_masked(
        self, monkeypatch, tmp_path, no_real_kill
    ):
        # the spawn only forks + sends init; the reserve joins the
        # ring when its READY handshake lands — never before
        monkeypatch.setattr(
            "tpu_patterns.exec.proc.popen_in_group",
            lambda *a, **k: _FakeProc(),
        )
        mgr = _elastic_manager()
        mgr.work_dir = str(tmp_path)
        res = _res(mgr, [])
        assert mgr.router.live() == {"0"}
        mgr._scale_out(1.0, res)
        assert mgr._spare == []
        h = mgr.handles["1"]
        assert h.state == "spawning"
        assert h.proc.stdin.sent[0]["op"] == "init"
        assert mgr.router.live() == {"0"}  # not routable yet
        assert res.scale_events == [(1.0, "out", "1")]
        mgr._handle("1", {"ready": True, "pid": 1}, res)
        assert h.state == "ready"
        assert mgr.router.live() == {"0", "1"}

    def test_scale_out_fault_aborts_attempt(
        self, monkeypatch, tmp_path, no_real_kill
    ):
        # satellite firing test: fleet.scale_out error -> THIS attempt
        # aborts (no spawn, slice stays reserved); the policy simply
        # re-decides after its cooldown
        from tpu_patterns import obs

        monkeypatch.setattr(
            "tpu_patterns.exec.proc.popen_in_group",
            lambda *a, **k: _FakeProc(),
        )
        faults.configure("fleet.scale_out:error:count=1")
        before = obs.counter(
            "tpu_patterns_faults_injected_total",
            site="fleet.scale_out", action="error",
        ).value
        mgr = _elastic_manager()
        mgr.work_dir = str(tmp_path)
        res = _res(mgr, [])
        mgr._scale_out(1.0, res)
        assert obs.counter(
            "tpu_patterns_faults_injected_total",
            site="fleet.scale_out", action="error",
        ).value == before + 1
        assert mgr._spare == [1]  # slice kept
        assert "1" not in mgr.handles
        assert res.scale_events == []
        # the spec burned: the next attempt goes through
        mgr._scale_out(3.0, res)
        assert mgr._spare == [] and "1" in mgr.handles

    def test_spawn_failure_keeps_slice_reserved(
        self, monkeypatch, tmp_path, no_real_kill
    ):
        # replica.spawn exhausting its retries mid-scale-out must not
        # burn the reserve: the slice stays available for a later try
        faults.configure("replica.spawn:error:count=99")
        monkeypatch.setattr(
            "tpu_patterns.exec.proc.popen_in_group",
            lambda *a, **k: _FakeProc(),
        )
        mgr = _elastic_manager()
        mgr.work_dir = str(tmp_path)
        res = _res(mgr, [])
        mgr._scale_out(1.0, res)
        assert mgr._spare == [1] and "1" not in mgr.handles
        assert res.scale_events == []

    def test_scale_in_drains_coldest_and_retires_spawns_first(
        self, no_real_kill
    ):
        mgr = _elastic_manager(n=2, reserve=0)
        res = _res(mgr, [])
        # equal (zero) leases: the tie retires the HIGHER id — elastic
        # spawns go back before the core fleet shrinks
        mgr._scale_in(5.0, res)
        victim = mgr.handles["1"]
        assert victim.state == "quarantined"
        assert {"op": "drain"} in victim.proc.stdin.sent
        assert mgr.router.live() == {"0"}
        assert res.scale_events == [(5.0, "in", "1")]

    def test_scale_in_prefers_fewest_leases(self, no_real_kill):
        mgr = _elastic_manager(n=2, reserve=0)
        reqs = _reqs(3)
        res = _res(mgr, reqs)
        hot = mgr.handles["1"]
        for r in reqs:
            hot.leases.acquire(r.rid, r)
        mgr._scale_in(5.0, res)
        assert mgr.handles["0"].state == "quarantined"  # the cold one
        assert hot.state == "ready"

    def test_scale_in_fault_aborts_and_fleet_stays_put(
        self, no_real_kill
    ):
        # satellite firing test: fleet.scale_in error -> the fleet
        # never shrinks below its current size on a faulted drain
        faults.configure("fleet.scale_in:error:count=1")
        mgr = _elastic_manager(n=2, reserve=0)
        res = _res(mgr, [])
        mgr._scale_in(5.0, res)
        assert all(
            h.state == "ready" for h in mgr.handles.values()
        )
        assert mgr.router.live() == {"0", "1"}
        assert res.scale_events == []
        assert not any(
            {"op": "drain"} in h.proc.stdin.sent
            for h in mgr.handles.values()
        )

    def test_elastic_tick_scales_out_under_sustained_pressure(
        self, monkeypatch, tmp_path, no_real_kill
    ):
        monkeypatch.setattr(
            "tpu_patterns.exec.proc.popen_in_group",
            lambda *a, **k: _FakeProc(),
        )
        mgr = _elastic_manager(slots=2)
        mgr.work_dir = str(tmp_path)
        reqs = _reqs(5)  # 5 leases / (1 live * 2 slots) = 2.5 > 1.25
        res = _res(mgr, reqs)
        for r in reqs:
            mgr._dispatch(r, res)
        mgr._elastic_tick(1.0, res)
        assert [e[1] for e in res.scale_events] == ["out"]
        assert "1" in mgr.handles

    def test_elastic_tick_scales_in_when_idle(self, no_real_kill):
        mgr = _elastic_manager(n=2, reserve=0, slots=2)
        res = _res(mgr, [])
        mgr._elastic_tick(1.0, res)  # 0 leases: under the low water
        assert [e[1] for e in res.scale_events] == ["in"]

    def test_scale_events_book_the_fleet_counter(
        self, monkeypatch, tmp_path, no_real_kill
    ):
        from tpu_patterns import obs

        monkeypatch.setattr(
            "tpu_patterns.exec.proc.popen_in_group",
            lambda *a, **k: _FakeProc(),
        )
        before = obs.counter(
            "tpu_patterns_fleet_scale_events_total",
            action="out", replica="1",
        ).value
        mgr = _elastic_manager()
        mgr.work_dir = str(tmp_path)
        mgr._scale_out(1.0, res := _res(mgr, []))
        assert obs.counter(
            "tpu_patterns_fleet_scale_events_total",
            action="out", replica="1",
        ).value == before + 1


class TestFleetResultShed:
    def test_shed_op_releases_lease_and_books_terminal(
        self, no_real_kill
    ):
        mgr = _manager(2)
        reqs = _reqs(2)
        res = _res(mgr, reqs)
        for r in reqs:
            mgr._dispatch(r, res)
        h = next(x for x in mgr.handles.values() if len(x.leases))
        rid = sorted(h.leases.held())[0]
        fails_before = h.breaker.failures
        mgr._handle(
            h.id, {"op": "shed", "rid": rid, "reason": "burn"}, res
        )
        assert rid not in h.leases
        assert res.shed[rid] == "burn"
        # mitigation working is not replica sickness
        assert h.breaker.failures == fails_before

    def test_covered_and_counts_include_shed(self, no_real_kill):
        res = FleetResult(scheduled=3)
        res.done[0] = [1]
        res.failed[1] = "x"
        assert not res.covered()
        res.shed[2] = "burn"
        assert res.covered()
        c = res.counts()
        assert c["shed_total"] == 1.0
        assert (
            c["done_total"] + c["failed_total"] + c["shed_total"]
            == res.scheduled
        )

    def test_scale_event_accessors(self):
        res = FleetResult(scheduled=0)
        res.scale_events += [(1.0, "out", "2"), (2.0, "in", "2"),
                             (3.0, "out", "2")]
        assert res.scale_outs() == 2
        assert res.scale_ins() == 1


# -- disaggregated prefill/decode: the parent handoff plane ----------------


def _disagg_manager(n=3):
    """A fake-process fleet with replica 0 prefill and the rest decode:
    the ring carries ONLY the prefill pool (decode replicas never take
    admissions), exactly as ReplicaManager.__init__ builds it."""
    mgr = _manager(n)
    mgr.roles = {
        str(r): ("prefill" if r == 0 else "decode") for r in range(n)
    }
    mgr.router = Router(["0"], block_len=8)
    return mgr


def _manifest(rid, blocks=2, nbytes=2048, recompute=False):
    return {
        "rid": rid, "jid": f"j{rid}", "prompt": [1] * 9, "n_gen": 4,
        "scenario": "", "deadline_ms": 0.0, "priority": "bulk",
        "temperature": 0.0, "top_k": 0, "top_p": 1.0, "seed": 0,
        "gen_offset": 0, "tok0": 5, "t_submit_ns": 0, "t_first_ns": 0,
        "path": "" if recompute else f"/spool/kv-{rid}.npz",
        "blocks": 0 if recompute else blocks,
        "nbytes": 0 if recompute else nbytes,
        "recompute": recompute,
    }


class TestDisaggHandoffPlane:
    def test_roles_validation(self, tmp_path):
        # the real constructor: every id must carry a role, both pools
        # must be populated, and elastic+roles is rejected
        kw = dict(
            base_env={}, work_dir=str(tmp_path), child_cfg={},
            device_slices=[[0], [1]], sp=1, tp=1,
        )
        with pytest.raises(ValueError, match="at least one"):
            ReplicaManager(2, roles={"0": "prefill", "1": "prefill"},
                           **kw)
        with pytest.raises(ValueError, match="role"):
            ReplicaManager(2, roles={"0": "prefill"}, **kw)
        with pytest.raises(ValueError, match="role"):
            ReplicaManager(2, roles={"0": "prefill", "1": "router"},
                           **kw)

    def test_handoff_moves_lease_round_robin_and_books(
        self, no_real_kill
    ):
        from tpu_patterns import obs

        mgr = _disagg_manager(3)
        reqs = _reqs(2)
        res = _res(mgr, reqs)
        for r in reqs:
            mgr._dispatch(r, res)
        pre = mgr.handles["0"]
        assert set(pre.leases.held()) == {0, 1}
        t0 = rt.metric_total("tpu_patterns_disagg_transfers_total")
        b0 = rt.metric_total("tpu_patterns_disagg_adopted_blocks_total")
        y0 = rt.metric_total("tpu_patterns_disagg_transfer_bytes_total")
        for rid in (0, 1):
            mgr._handle(
                "0", {"op": "handoff", "rid": rid,
                      "m": _manifest(rid)}, res,
            )
        assert len(pre.leases) == 0
        # round-robin over the live decode pool: one rid each
        assert set(mgr.handles["1"].leases.held()) == {0}
        assert set(mgr.handles["2"].leases.held()) == {1}
        for d in ("1", "2"):
            (adopt,) = [
                m for m in mgr.handles[d].proc.stdin.sent
                if m.get("op") == "adopt"
            ]
            assert adopt["m"]["blocks"] == 2
        assert res.handoff_rids == {0, 1}
        assert rt.metric_total(
            "tpu_patterns_disagg_transfers_total"
        ) - t0 == 2.0
        assert rt.metric_total(
            "tpu_patterns_disagg_adopted_blocks_total"
        ) - b0 == 4.0
        assert rt.metric_total(
            "tpu_patterns_disagg_transfer_bytes_total"
        ) - y0 == 4096.0
        booked = [
            e for e in mgr.decisions.events if e["action"] == "handoff"
        ]
        assert len(booked) == 2
        assert booked[0]["inputs"]["dst"] == "1"
        ring = [e["name"] for e in obs.flight_recorder().snapshot()]
        assert "journey.handoff" in ring

    def test_recompute_handoff_counts_transfer_only(self, no_real_kill):
        mgr = _disagg_manager(2)
        reqs = _reqs(1)
        res = _res(mgr, reqs)
        mgr._dispatch(reqs[0], res)
        t0 = rt.metric_total("tpu_patterns_disagg_transfers_total")
        b0 = rt.metric_total("tpu_patterns_disagg_adopted_blocks_total")
        y0 = rt.metric_total("tpu_patterns_disagg_transfer_bytes_total")
        mgr._handle(
            "0", {"op": "handoff", "rid": 0,
                  "m": _manifest(0, recompute=True)}, res,
        )
        # counter identity: the transfers series ticks on EVERY booked
        # handoff (degradations included); payload series count real
        # bytes/blocks only
        assert rt.metric_total(
            "tpu_patterns_disagg_transfers_total"
        ) - t0 == 1.0
        assert rt.metric_total(
            "tpu_patterns_disagg_adopted_blocks_total"
        ) - b0 == 0.0
        assert rt.metric_total(
            "tpu_patterns_disagg_transfer_bytes_total"
        ) - y0 == 0.0
        assert set(mgr.handles["1"].leases.held()) == {0}

    def test_no_live_decode_fails_loudly(self, no_real_kill):
        mgr = _disagg_manager(2)
        reqs = _reqs(1)
        res = _res(mgr, reqs)
        mgr._dispatch(reqs[0], res)
        mgr.handles["1"].state = "dead"
        mgr._handle(
            "0", {"op": "handoff", "rid": 0, "m": _manifest(0)}, res,
        )
        assert "decode" in res.failed[0]
        assert len(mgr.handles["0"].leases) == 0
        assert res.covered()

    def test_first_op_stamps_parent_clock_once(self, no_real_kill):
        mgr = _disagg_manager(2)
        res = _res(mgr, _reqs(1))
        mgr._handle("0", {"op": "first", "rid": 0}, res)
        stamp = res.t_first_ns[0]
        assert stamp > 0
        # a recompute degradation may regenerate the first token later:
        # the front-door stamp must not move
        mgr._handle("1", {"op": "first", "rid": 0}, res)
        assert res.t_first_ns[0] == stamp

    def test_decode_death_mid_adopt_reroutes_via_prefill_ring(
        self, no_real_kill
    ):
        mgr = _disagg_manager(2)
        reqs = _reqs(1)
        res = _res(mgr, reqs)
        mgr._dispatch(reqs[0], res)
        mgr._handle(
            "0", {"op": "handoff", "rid": 0, "m": _manifest(0)}, res,
        )
        assert set(mgr.handles["1"].leases.held()) == {0}
        # the adopter dies holding the lease: standard fail-over sends
        # the rid back through the (prefill-only) ring — a fresh
        # prefill, a fresh handoff, never limbo
        mgr._replica_down(mgr.handles["1"], "test kill", res)
        assert 0 in res.rerouted
        assert set(mgr.handles["0"].leases.held()) == {0}


class TestScaleOutPrewarm:
    """PR 20: a just-joined elastic spawn is shipped its ring arc's
    hottest fleet-store prefixes — the parent picks PATHS (arc filter,
    hottest-first, ancestor closure, shallow-first order), the child
    fetches the bytes itself."""

    LEAVES = {"k": ((1, 8, 1, 2), __import__("numpy").dtype("float32"))}

    def _seed_store(self, root, paths):
        import numpy as np

        from tpu_patterns.serve.store import PrefixStore

        st = PrefixStore(str(root), self.LEAVES, block_len=8)
        for i, p in enumerate(paths):
            st.publish(
                {"k": np.full((1, 8, 1, 2), float(i), np.float32)},
                p,
            )
        return st

    def _ready_spawn(self, monkeypatch, tmp_path, store_paths):
        monkeypatch.setattr(
            "tpu_patterns.exec.proc.popen_in_group",
            lambda *a, **k: _FakeProc(),
        )
        mgr = _elastic_manager()
        mgr.work_dir = str(tmp_path)
        sd = tmp_path / "store"
        self._seed_store(sd, store_paths)
        mgr.child_cfg["prefix_store"] = str(sd)
        res = _res(mgr, [])
        mgr._scale_out(1.0, res)
        mgr._handle("1", {"ready": True, "pid": 1}, res)
        return mgr, mgr.handles["1"]

    def test_ready_ships_only_the_arc_shallow_first(
        self, monkeypatch, tmp_path, no_real_kill
    ):
        import numpy as np

        from tpu_patterns.serve.router import prefix_fingerprint

        rng = np.random.RandomState(7)
        paths = [
            tuple(int(t) for t in rng.randint(0, 64, size=8))
            for _ in range(12)
        ]
        # two deep children whose parents the store also holds — the
        # closure must ship parent before child
        paths += [
            paths[0] + tuple(int(t) for t in rng.randint(0, 64, size=8)),
            paths[1] + tuple(int(t) for t in rng.randint(0, 64, size=8)),
        ]
        mgr, h = self._ready_spawn(monkeypatch, tmp_path, paths)
        sent = [m for m in h.proc.stdin.sent if m.get("op") == "prewarm"]
        assert len(sent) == 1
        got = [tuple(p) for p in sent[0]["paths"]]
        # only paths whose fingerprint lands on the newcomer's arc
        want = {
            p for p in paths
            if mgr.router.ring.lookup(
                prefix_fingerprint(list(p), 8, mgr.router.route_blocks)
            ) == "1"
        }
        # ... closed over in-store ancestors
        want |= {
            p[:k] for p in want for k in range(8, len(p), 8)
            if p[:k] in set(paths)
        }
        assert set(got) == want
        assert got == sorted(got, key=lambda p: (len(p), p))
        # deep entries never precede their in-store parents
        seen = set()
        for p in got:
            if len(p) > 8 and p[:-8] in want:
                assert p[:-8] in seen
            seen.add(p)

    def test_empty_or_missing_store_is_a_cold_start(
        self, monkeypatch, tmp_path, no_real_kill
    ):
        monkeypatch.setattr(
            "tpu_patterns.exec.proc.popen_in_group",
            lambda *a, **k: _FakeProc(),
        )
        mgr = _elastic_manager()
        mgr.work_dir = str(tmp_path)
        mgr.child_cfg["prefix_store"] = str(tmp_path / "nowhere")
        res = _res(mgr, [])
        mgr._scale_out(1.0, res)
        mgr._handle("1", {"ready": True, "pid": 1}, res)
        h = mgr.handles["1"]
        assert h.state == "ready"
        assert not [
            m for m in h.proc.stdin.sent if m.get("op") == "prewarm"
        ]

    def test_no_store_configured_sends_nothing(
        self, monkeypatch, tmp_path, no_real_kill
    ):
        monkeypatch.setattr(
            "tpu_patterns.exec.proc.popen_in_group",
            lambda *a, **k: _FakeProc(),
        )
        mgr = _elastic_manager()
        mgr.work_dir = str(tmp_path)
        res = _res(mgr, [])
        mgr._scale_out(1.0, res)
        mgr._handle("1", {"ready": True, "pid": 1}, res)
        assert not [
            m for m in mgr.handles["1"].proc.stdin.sent
            if m.get("op") == "prewarm"
        ]

    def test_stdin_prewarm_op_reaches_the_engine(self):
        # the child half of the wire: a prewarm op calls
        # ServeEngine.prewarm_paths at the iteration boundary
        class _Eng(_FakeEngine):
            def __init__(self):
                super().__init__()
                self.prewarmed = []

            def prewarm_paths(self, paths):
                self.prewarmed.append(paths)
                return len(paths)

        eng = _Eng()
        sent = []
        src = _StdinSource(
            iter([json.dumps(
                {"op": "prewarm", "paths": [[1, 2], [3, 4]]}
            )]),
            eng, sent.append,
        )
        src._last_hb_ns = 0
        for _ in range(50):
            src()
            if eng.prewarmed:
                break
        assert eng.prewarmed == [[[1, 2], [3, 4]]]
