"""Tests for the one-sided (RMA) Pallas path (SURVEY.md C2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_patterns.comm import (
    OneSidedConfig,
    local_put,
    local_put_multi,
    ring_put,
    run_onesided,
)
from tpu_patterns.comm.onesided import _inplace_plan, local_put_inplace
from tpu_patterns.core.results import Verdict


class TestLocalPut:
    def test_roundtrip_interpret(self):
        x = jnp.arange(4 * 128, dtype=jnp.float32).reshape(4, 128)
        y = local_put(x, interpret=True)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


class TestRingPut:
    def test_ring_put_rotates_shards(self, mesh1d):
        n = 8
        rows, cols = 2, 128
        x = jax.device_put(
            jnp.arange(n * rows * cols, dtype=jnp.float32).reshape(n * rows, cols),
            NamedSharding(mesh1d, P("x")),
        )
        fn = jax.jit(
            jax.shard_map(
                lambda a: ring_put(a, "x", n, interpret=True),
                mesh=mesh1d,
                in_specs=P("x"),
                out_specs=P("x"),
                check_vma=False,
            )
        )
        out = np.asarray(fn(x))
        np.testing.assert_array_equal(out, np.roll(np.asarray(x), rows, axis=0))


class TestLocalPutMulti:
    def _roundtrip(self, shape, chunks):
        n = int(np.prod(shape))
        x = jnp.arange(n, dtype=jnp.float32).reshape(shape)
        out = local_put_multi(x, chunks=chunks, interpret=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    def test_exact_tiling(self):
        self._roundtrip((16, 256), chunks=8)

    def test_chunks_shrink_to_divisor(self):
        # rows=6, chunks=4: must walk down to 3 concurrent DMAs
        self._roundtrip((6, 256), chunks=4)

    def test_prime_rows(self):
        self._roundtrip((7, 256), chunks=4)

    def test_more_chunks_than_rows(self):
        self._roundtrip((2, 128), chunks=8)

    def test_single_chunk_is_monolithic(self):
        self._roundtrip((4, 128), chunks=1)

    def test_rows_zero_early_out(self):
        x = jnp.zeros((0, 128), jnp.float32)
        assert local_put_multi(x, interpret=True).shape == (0, 128)


class TestLocalPutInplace:
    """The aliased schedule: each chunk's first half duplicated into its
    tail, inside ONE buffer (VERDICT r4 #6's new schedule attempt)."""

    def _want(self, x, chunks):
        a = np.array(x, copy=True)
        n_c, c_r, half = _inplace_plan(a.shape[0], chunks)
        for i in range(n_c):
            lo = i * c_r
            a[lo + c_r - half: lo + c_r] = a[lo: lo + half]
        return a

    @pytest.mark.parametrize(
        "shape,chunks",
        [((16, 256), 8), ((6, 256), 4), ((7, 256), 4), ((2, 128), 8),
         ((4, 128), 1)],
    )
    def test_half_duplication(self, shape, chunks):
        n = int(np.prod(shape))
        x = jnp.arange(n, dtype=jnp.float32).reshape(shape)
        out = local_put_inplace(x, chunks=chunks, interpret=True)
        np.testing.assert_array_equal(
            np.asarray(out), self._want(x, chunks)
        )

    def test_regions_disjoint_and_nonempty(self):
        # every plan must give half >= 1 (a zero-length DMA would hang
        # Mosaic) and half <= chunk_rows - half (no read/write race)
        for rows in (2, 3, 6, 7, 16, 92160):
            for chunks in (1, 4, 8, 64):
                n_c, c_r, half = _inplace_plan(rows, chunks)
                assert n_c * c_r == rows
                assert 1 <= half <= c_r - half

    def test_tiny_rows_early_out(self):
        x = jnp.ones((1, 128), jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(local_put_inplace(x, interpret=True)), np.asarray(x)
        )

    def test_explicit_inplace_refuses_degenerate_rows(self, devices):
        # rows < 2 makes the schedule an identity no-op (half == 0): an
        # explicit request must raise, never record a 0-byte SUCCESS
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices[:1]), ("x",))
        with pytest.raises(ValueError, match="inplace"):
            run_onesided(
                mesh, OneSidedConfig(count=512, reps=1, kernel="inplace")
            )

    def test_auto_skips_inplace_on_degenerate_rows(self, devices):
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices[:1]), ("x",))
        (rec,) = run_onesided(
            mesh, OneSidedConfig(count=512, reps=2, warmup=1)
        )
        assert rec.verdict is Verdict.SUCCESS, rec.notes
        assert "bandwidth_GBps_inplace" not in rec.metrics

    def test_bytes_accounting_in_record(self, devices):
        # the record must credit the bytes the schedule MOVED (count/2-ish)
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices[:1]), ("x",))
        cfg = OneSidedConfig(count=2048, reps=2, warmup=1, kernel="inplace")
        (rec,) = run_onesided(mesh, cfg)
        assert rec.verdict is Verdict.SUCCESS, rec.notes
        rows = max(1, cfg.count // 512)
        n_c, c_r, half = _inplace_plan(rows, cfg.chunks)
        moved = n_c * half * 512 * 4
        assert rec.metrics["bytes_per_put"] == pytest.approx(moved)
        assert rec.metrics["bandwidth_GBps_inplace"] > 0


class TestRunOneSided:
    def test_multi_device(self, mesh1d):
        recs = run_onesided(mesh1d, OneSidedConfig(count=2048, reps=2, warmup=1))
        (rec,) = recs
        assert rec.mode == "ring_put"
        assert rec.verdict is Verdict.SUCCESS, rec.notes
        assert rec.metrics["bandwidth_GBps"] > 0
        # the HBM gate does not apply on the ICI path: no un-checked
        # "plausible" claim may appear in the record
        assert "hbm_plausible" not in rec.metrics

    def test_single_device(self, devices):
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices[:1]), ("x",))
        (rec,) = run_onesided(mesh, OneSidedConfig(count=2048, reps=2, warmup=1))
        assert rec.mode == "local_put"
        assert rec.verdict is Verdict.SUCCESS, rec.notes
        # auto mode measured both schedules and recorded the winner
        assert "bandwidth_GBps_streamed" in rec.metrics
        assert "bandwidth_GBps_multi" in rec.metrics
        assert "bandwidth_GBps_inplace" in rec.metrics
        assert any(n.startswith("auto-selected kernel:") for n in rec.notes)
        # CPU mesh: no HBM spec, so no unchecked plausibility claim
        assert "hbm_plausible" not in rec.metrics

    @pytest.mark.parametrize("kernel", ["streamed", "multi", "mono"])
    def test_single_device_explicit_kernel(self, devices, kernel):
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices[:1]), ("x",))
        (rec,) = run_onesided(
            mesh, OneSidedConfig(count=2048, reps=2, warmup=1, kernel=kernel)
        )
        assert rec.verdict is Verdict.SUCCESS, rec.notes
        assert rec.metrics[f"bandwidth_GBps_{kernel}"] > 0

    def test_auto_survives_one_broken_kernel(self, devices, monkeypatch):
        # a candidate the platform rejects must be skipped, not zero the
        # headline (the bench artifact depends on this)
        from jax.sharding import Mesh

        from tpu_patterns.comm import onesided as mod

        def boom(x, chunks=8, interpret=False):
            raise RuntimeError("lowering rejected")

        monkeypatch.setattr(mod, "local_put_multi", boom)
        mesh = Mesh(np.array(devices[:1]), ("x",))
        (rec,) = run_onesided(mesh, OneSidedConfig(count=2048, reps=2, warmup=1))
        assert rec.verdict is Verdict.SUCCESS, rec.notes
        assert any("multi failed: RuntimeError" in n for n in rec.notes)
        # one of the surviving candidates (streamed or the xla rotation)
        # wins; which one is a measurement, not a contract
        assert any(
            n in ("auto-selected kernel: streamed",
                  "auto-selected kernel: xla")
            for n in rec.notes
        )
        assert "bandwidth_GBps_multi" not in rec.metrics

    def test_explicit_xla_kernel_verifies_rotation(self, devices):
        # the compiler-scheduled candidate: a one-row rotation whose
        # output is checked against np.roll (the ring_put discipline) —
        # a wrong-offset "copy" fails the data gate
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices[:1]), ("x",))
        (rec,) = run_onesided(
            mesh,
            OneSidedConfig(count=2048, reps=2, warmup=1, kernel="xla"),
        )
        assert rec.verdict is Verdict.SUCCESS, rec.notes
        assert rec.metrics["checksum_ok"] == 1.0
        assert rec.metrics["bandwidth_GBps"] > 0

    def test_explicit_broken_kernel_raises(self, devices, monkeypatch):
        from jax.sharding import Mesh

        from tpu_patterns.comm import onesided as mod

        def boom(x, chunks=8, interpret=False):
            raise RuntimeError("lowering rejected")

        monkeypatch.setattr(mod, "local_put_multi", boom)
        mesh = Mesh(np.array(devices[:1]), ("x",))
        with pytest.raises(RuntimeError, match="lowering rejected"):
            run_onesided(
                mesh, OneSidedConfig(count=2048, reps=1, kernel="multi")
            )

    def test_unknown_kernel_raises(self, devices):
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices[:1]), ("x",))
        with pytest.raises(ValueError, match="unknown onesided kernel"):
            run_onesided(mesh, OneSidedConfig(count=2048, kernel="bogus"))

    def test_cli_kernel_choices_match_library(self):
        # the CLI's --put-kernel choices and run_onesided's validation
        # are two spellings of one contract; drift turns a valid library
        # kernel into an argparse rejection (caught live: "inplace")
        from tpu_patterns.cli import build_parser

        parser = build_parser()
        args = parser.parse_args(
            ["p2p", "--transport", "one_sided", "--put-kernel", "inplace"]
        )
        assert args.put_kernel == "inplace"
        for k in ("auto", "streamed", "multi", "mono", "xla"):
            parser.parse_args(["p2p", "--put-kernel", k])


class TestHbmPlausibility:
    """The copy rate must be carryable by HBM (every byte = 1 read + 1
    write).  Observed live on v5e: the bench quick tier's 4.7 MB buffer
    stayed VMEM-resident and 'copied' at 103 TB/s — SUCCESS with a
    126x-over-spec headline, which this gate now forbids."""

    def test_pure_function(self):
        from tpu_patterns.comm.onesided import hbm_plausible

        assert hbm_plausible(335.6, 819.0)  # the real v5e measurement
        assert not hbm_plausible(103523.6, 819.0)  # the VMEM artifact
        assert not hbm_plausible(475.0, 819.0)  # just past spec/2 * margin
        assert hbm_plausible(12345.0, None)  # unknown chip: no gate

    def _run(self, devices, spec, monkeypatch):
        from jax.sharding import Mesh

        from tpu_patterns import runtime

        monkeypatch.setattr(runtime, "chip_hbm_gbps", lambda: spec)
        mesh = Mesh(np.array(devices[:1]), ("x",))
        (rec,) = run_onesided(
            mesh, OneSidedConfig(count=2048, reps=2, warmup=1)
        )
        return rec

    def test_implausible_rate_fails_verdict(self, devices, monkeypatch):
        # a spec no real copy can stay under: every candidate is flagged,
        # the winner is recorded, but the verdict is FAILURE
        rec = self._run(devices, 1e-9, monkeypatch)
        assert rec.verdict is Verdict.FAILURE
        assert rec.metrics["hbm_plausible"] == 0.0
        assert any("faster tier" in n for n in rec.notes)

    def test_plausible_rate_passes(self, devices, monkeypatch):
        rec = self._run(devices, 1e12, monkeypatch)
        assert rec.verdict is Verdict.SUCCESS, rec.notes
        assert rec.metrics["hbm_plausible"] == 1.0


class TestLocalPutStreamedEdges:
    """The block-cap/divisor logic of local_put_streamed (VERDICT round-1
    gap): shrink-to-divisor, degenerate shapes, VMEM byte cap."""

    def _roundtrip(self, shape, dtype=jnp.float32, block_rows=1024):
        from tpu_patterns.comm.onesided import local_put_streamed

        n = int(np.prod(shape))
        x = jnp.arange(n, dtype=dtype).reshape(shape)
        out = local_put_streamed(x, block_rows=block_rows, interpret=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
        return out

    def test_rows_zero_early_out(self):
        from tpu_patterns.comm.onesided import local_put_streamed

        x = jnp.zeros((0, 128), jnp.float32)
        out = local_put_streamed(x, interpret=True)
        assert out.shape == (0, 128)

    def test_empty_trailing_dim_early_out(self):
        from tpu_patterns.comm.onesided import local_put_streamed

        x = jnp.zeros((8, 0), jnp.float32)
        out = local_put_streamed(x, interpret=True)
        assert out.shape == (8, 0)

    def test_block_shrinks_to_divisor(self):
        # rows=6 with block_rows=4: 6 % 4 != 0 -> the divisor loop must
        # walk down to 3 (not crash, not drop rows)
        self._roundtrip((6, 256), block_rows=4)

    def test_prime_rows(self):
        # prime row count: only divisors are 1 and itself
        self._roundtrip((7, 256), block_rows=4)

    def test_non_multiple_of_128_trailing_dim(self):
        # trailing dims that are not lane-aligned still round-trip (Mosaic
        # handles the padding; interpret mode checks the indexing math)
        self._roundtrip((16, 100))
        self._roundtrip((16, 3, 37))

    def test_vmem_byte_cap_bounds_block(self):
        # a single row of 2M f32 = 8 MB > the 4 MB cap: block_rows must
        # clamp to 1 (the max(1, ...) floor) and the copy still be exact
        self._roundtrip((4, 2 * 1024 * 1024), block_rows=1024)

    def test_1d_input(self):
        self._roundtrip((4096,))


class TestTunedDefaults:
    def test_tuned_defaults_resolve_lazily(self, tmp_path, monkeypatch):
        """Promoted/overridden tuned knobs must affect the NEXT config
        built in this process, not the next interpreter (ADVICE r3):
        defaults are default_factory-resolved, not baked at class
        definition."""
        import json

        tuned = tmp_path / "tuned.json"
        tuned.write_text(
            json.dumps({"block_rows": 7777, "chunks": 31})
        )
        monkeypatch.setenv("TPU_PATTERNS_TUNED", str(tuned))
        after = OneSidedConfig()
        assert (after.block_rows, after.chunks) == (7777, 31)
        # pointing at /dev/null disables tuning -> hand-picked fallbacks
        monkeypatch.setenv("TPU_PATTERNS_TUNED", "/dev/null")
        assert (OneSidedConfig().block_rows, OneSidedConfig().chunks) == (
            1024,
            8,
        )
        # explicit values always win over tuned defaults
        assert OneSidedConfig(block_rows=3, chunks=2).block_rows == 3
