"""Tests for the one-sided (RMA) Pallas path (SURVEY.md C2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_patterns.comm import OneSidedConfig, local_put, ring_put, run_onesided
from tpu_patterns.core.results import Verdict


class TestLocalPut:
    def test_roundtrip_interpret(self):
        x = jnp.arange(4 * 128, dtype=jnp.float32).reshape(4, 128)
        y = local_put(x, interpret=True)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


class TestRingPut:
    def test_ring_put_rotates_shards(self, mesh1d):
        n = 8
        rows, cols = 2, 128
        x = jax.device_put(
            jnp.arange(n * rows * cols, dtype=jnp.float32).reshape(n * rows, cols),
            NamedSharding(mesh1d, P("x")),
        )
        fn = jax.jit(
            jax.shard_map(
                lambda a: ring_put(a, "x", n, interpret=True),
                mesh=mesh1d,
                in_specs=P("x"),
                out_specs=P("x"),
                check_vma=False,
            )
        )
        out = np.asarray(fn(x))
        np.testing.assert_array_equal(out, np.roll(np.asarray(x), rows, axis=0))


class TestRunOneSided:
    def test_multi_device(self, mesh1d):
        recs = run_onesided(mesh1d, OneSidedConfig(count=2048, reps=2, warmup=1))
        (rec,) = recs
        assert rec.mode == "ring_put"
        assert rec.verdict is Verdict.SUCCESS, rec.notes
        assert rec.metrics["bandwidth_GBps"] > 0

    def test_single_device(self, devices):
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices[:1]), ("x",))
        (rec,) = run_onesided(mesh, OneSidedConfig(count=2048, reps=2, warmup=1))
        assert rec.mode == "local_put"
        assert rec.verdict is Verdict.SUCCESS, rec.notes
