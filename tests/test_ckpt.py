"""Checkpoint/resume: atomic sharded save, elastic restore, resumed
training equivalence (ckpt/checkpoint.py + models/train_loop.py)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_patterns import ckpt
from tpu_patterns.models.train_loop import TrainLoopConfig, train


@pytest.fixture(scope="module")
def mesh2d(devices):
    return Mesh(np.array(devices[:8]).reshape(4, 2), ("dp", "tp"))


def _tree(mesh):
    """Mixed pytree: sharded matrix, replicated vector, bf16, scalar."""
    w = jax.device_put(
        jnp.arange(64 * 32, dtype=jnp.float32).reshape(64, 32),
        NamedSharding(mesh, P("dp", "tp")),
    )
    b = jax.device_put(
        jnp.linspace(0, 1, 32, dtype=jnp.float32),
        NamedSharding(mesh, P()),
    )
    h = jax.device_put(
        (jnp.arange(16, dtype=jnp.bfloat16) / 7).reshape(4, 4),
        NamedSharding(mesh, P("dp", None)),
    )
    step = jax.device_put(
        jnp.asarray(3, jnp.int32), NamedSharding(mesh, P())
    )
    return {"w": w, "inner": {"b": b, "h": h}, "step": step}


def _assert_tree_equal(a, b):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = {jax.tree_util.keystr(p): v for p, v in
          jax.tree_util.tree_leaves_with_path(b)}
    assert {jax.tree_util.keystr(p) for p, _ in la} == set(lb)
    for p, va in la:
        vb = lb[jax.tree_util.keystr(p)]
        assert va.dtype == vb.dtype, p
        np.testing.assert_array_equal(
            np.atleast_1d(np.asarray(va)).view(np.uint8),
            np.atleast_1d(np.asarray(vb)).view(np.uint8),
        )


class TestRoundTrip:
    def test_same_mesh_bitwise(self, mesh2d, tmp_path):
        tree = _tree(mesh2d)
        ckpt.save(str(tmp_path), 3, tree)
        back = ckpt.restore(str(tmp_path), tree)
        _assert_tree_equal(tree, back)
        # restored leaves carry the template's sharding
        assert back["w"].sharding == tree["w"].sharding

    def test_elastic_restore_different_mesh(self, devices, tmp_path):
        save_mesh = Mesh(np.array(devices[:8]).reshape(4, 2), ("dp", "tp"))
        tree = _tree(save_mesh)
        ckpt.save(str(tmp_path), 1, tree)
        # new topology: 2x4, transposed layout for w
        new_mesh = Mesh(np.array(devices[:8]).reshape(2, 4), ("dp", "tp"))
        template = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(
                a.shape, a.dtype,
                sharding=NamedSharding(new_mesh, a.sharding.spec),
            ),
            tree,
        )
        back = ckpt.restore(str(tmp_path), template)
        _assert_tree_equal(tree, back)
        assert back["w"].sharding.mesh.shape["dp"] == 2

    def test_restore_subset_template_by_keypath(self, mesh2d, tmp_path):
        tree = _tree(mesh2d)
        ckpt.save(str(tmp_path), 1, tree)
        sub = {"inner": {"h": tree["inner"]["h"]}}
        back = ckpt.restore(str(tmp_path), sub)
        _assert_tree_equal(sub, back)

    def test_schema_mismatch_is_an_error(self, mesh2d, tmp_path):
        tree = _tree(mesh2d)
        ckpt.save(str(tmp_path), 1, tree)
        with pytest.raises(KeyError, match="not in checkpoint"):
            ckpt.restore(str(tmp_path), {"nope": tree["w"]})
        wrong = {"w": jax.ShapeDtypeStruct(
            (8, 8), jnp.float32, sharding=tree["w"].sharding
        )}
        with pytest.raises(ValueError, match="shape"):
            ckpt.restore(str(tmp_path), wrong)

    def test_replicated_leaves_written_once(self, mesh2d, tmp_path):
        # b is fully replicated over 8 devices: exactly ONE shard entry
        tree = _tree(mesh2d)
        path = ckpt.save(str(tmp_path), 1, tree)
        with open(os.path.join(path, "shards_proc0.json")) as f:
            table = json.load(f)
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        leaf_of = {info["key"]: info["leaf"] for info in manifest["leaves"]}
        b_shards = [e for e in table
                    if e["leaf"] == leaf_of["['inner']['b']"]]
        assert len(b_shards) == 1
        # w is fully sharded 4x2: all 8 shards present
        w_shards = [e for e in table if e["leaf"] == leaf_of["['w']"]]
        assert len(w_shards) == 8


class TestAtomicity:
    def test_crashed_save_is_invisible_and_swept(self, mesh2d, tmp_path):
        tree = _tree(mesh2d)
        ckpt.save(str(tmp_path), 1, tree)
        # simulate a crash mid-save: a torn tmp dir with partial files
        torn = tmp_path / ".tmp.step_2"
        torn.mkdir()
        (torn / "proc0.npz").write_bytes(b"garbage")
        assert ckpt.latest_step(str(tmp_path)) == 1
        back = ckpt.restore(str(tmp_path), tree)
        _assert_tree_equal(tree, back)
        # next save sweeps the torn dir
        ckpt.save(str(tmp_path), 2, tree)
        assert not torn.exists()
        assert ckpt.latest_step(str(tmp_path)) == 2

    def test_manifest_is_the_commit_marker(self, mesh2d, tmp_path):
        tree = _tree(mesh2d)
        path = ckpt.save(str(tmp_path), 5, tree)
        os.unlink(os.path.join(path, "manifest.json"))
        assert ckpt.available_steps(str(tmp_path)) == []
        with pytest.raises(FileNotFoundError):
            ckpt.restore(str(tmp_path), tree)

    def test_partial_shard_coverage_detected(self, mesh2d, tmp_path):
        tree = _tree(mesh2d)
        path = ckpt.save(str(tmp_path), 1, tree)
        # drop half of w's shards from the table: restore must refuse
        with open(os.path.join(path, "shards_proc0.json")) as f:
            table = json.load(f)
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        w_leaf = next(i["leaf"] for i in manifest["leaves"]
                      if i["key"] == "['w']")
        kept = [e for e in table
                if e["leaf"] != w_leaf or e["index"][0][0] == 0]
        with open(os.path.join(path, "shards_proc0.json"), "w") as f:
            json.dump(kept, f)
        with pytest.raises(ValueError, match="missing shards"):
            ckpt.restore(str(tmp_path), tree)

    def test_same_step_overwrite_never_deletes_before_commit(
        self, mesh2d, tmp_path
    ):
        # a resumed run re-saving its own step: new content wins, the old
        # dir was renamed aside (never rmtree'd pre-commit) and swept
        tree = _tree(mesh2d)
        ckpt.save(str(tmp_path), 1, tree)
        bumped = dict(tree, w=tree["w"] + 1)
        ckpt.save(str(tmp_path), 1, bumped)
        back = ckpt.restore(str(tmp_path), tree)
        _assert_tree_equal(bumped, back)
        leftovers = [n for n in os.listdir(tmp_path) if n.startswith(".old.")]
        assert leftovers == []

    def test_retention_prunes_oldest(self, mesh2d, tmp_path):
        tree = _tree(mesh2d)
        for s in (1, 2, 3, 4):
            ckpt.save(str(tmp_path), s, tree, keep=2)
        assert ckpt.available_steps(str(tmp_path)) == [3, 4]
        assert ckpt.latest_step(str(tmp_path)) == 4

    def test_non_array_leaf_rejected(self, mesh2d, tmp_path):
        with pytest.raises(TypeError, match="jax.Array"):
            ckpt.save(str(tmp_path), 1, {"x": 3.14})


class TestDescribe:
    def test_describe_lists_steps_and_leaves(self, mesh2d, tmp_path):
        tree = _tree(mesh2d)
        ckpt.save(str(tmp_path), 1, tree)
        ckpt.save(str(tmp_path), 5, tree)
        info = ckpt.describe(str(tmp_path))
        assert [s["step"] for s in info["steps"]] == [1, 5]
        s = info["steps"][0]
        assert s["bytes"] > 0 and s["process_count"] == 1
        keys = {leaf["key"] for leaf in s["leaves"]}
        assert keys == {"['w']", "['inner']['b']", "['inner']['h']", "['step']"}
        w = next(x for x in s["leaves"] if x["key"] == "['w']")
        assert w["shape"] == [64, 32] and w["dtype"] == "float32"
        assert w["spec"] == ["dp", "tp"]

    def test_describe_empty_dir(self, tmp_path):
        assert ckpt.describe(str(tmp_path))["steps"] == []

    def test_cli_ckpt_inspector(self, mesh2d, tmp_path, capsys):
        from tpu_patterns.cli import main

        tree = _tree(mesh2d)
        ckpt.save(str(tmp_path), 3, tree)
        assert main(["ckpt", str(tmp_path), "--leaves"]) == 0
        out = capsys.readouterr().out
        assert "step_3" in out and "latest: step_3" in out
        assert "['w']: (64, 32) float32 spec=(dp,tp)" in out


class TestAsyncSaver:
    def test_async_commit_matches_sync(self, mesh2d, tmp_path):
        tree = _tree(mesh2d)
        ckpt.save(str(tmp_path / "sync"), 3, tree)
        with ckpt.AsyncSaver() as saver:
            saver.save(str(tmp_path / "async"), 3, tree)
        a = ckpt.restore(str(tmp_path / "sync"), tree)
        b = ckpt.restore(str(tmp_path / "async"), tree)
        _assert_tree_equal(a, b)

    def test_snapshot_detaches_from_later_mutation(self, mesh2d, tmp_path):
        # the committed bytes must be the values AT save() time even if
        # the caller rebinds/mutates device state while IO is in flight
        tree = _tree(mesh2d)
        want = np.asarray(tree["w"]).copy()
        with ckpt.AsyncSaver() as saver:
            saver.save(str(tmp_path), 1, tree)
            tree = dict(tree, w=tree["w"] * 0 - 7)  # new device values
        back = ckpt.restore(str(tmp_path), tree, step=1)
        np.testing.assert_array_equal(np.asarray(back["w"]), want)

    def test_error_from_thread_surfaces_on_wait(
        self, mesh2d, tmp_path, monkeypatch
    ):
        # an IO failure inside the worker thread must surface on wait(),
        # not vanish (chmod-denial doesn't work under root, so inject).
        # The same signature on every attempt classifies as deterministic
        # under the ckpt RetryPolicy, so the surfaced error is Quarantined
        # (chained from the OSError, message preserved).
        from tpu_patterns import faults
        from tpu_patterns.ckpt import checkpoint as ckpt_mod

        def boom(*a, **k):
            raise OSError("disk full (injected)")

        monkeypatch.setattr(ckpt_mod, "_write_and_commit", boom)
        tree = _tree(mesh2d)
        saver = ckpt.AsyncSaver()
        saver.save(str(tmp_path), 1, tree)
        with pytest.raises((OSError, faults.Quarantined), match="injected"):
            saver.wait()
        # the saver is reusable after a failed save
        monkeypatch.undo()
        saver.save(str(tmp_path), 2, tree)
        saver.wait()
        assert ckpt.available_steps(str(tmp_path)) == [2]

    def test_sequential_saves_serialize(self, mesh2d, tmp_path):
        tree = _tree(mesh2d)
        with ckpt.AsyncSaver() as saver:
            for s in (1, 2, 3):
                saver.save(str(tmp_path), s, tree, keep=2)
        assert ckpt.available_steps(str(tmp_path)) == [2, 3]

    def test_train_loop_async_resume_bit_exact(self, devices, tmp_path):
        from jax.sharding import Mesh

        from tpu_patterns.models.train_loop import TrainLoopConfig, train

        mesh = Mesh(
            np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp")
        )

        def cfg(tmp, **kw):
            base = dict(
                embed=64, heads=8, head_dim=8, seq=32, batch=4, steps=6,
                lr=1e-4, ckpt_dir=str(tmp), ckpt_every=2, ckpt_async=True,
            )
            base.update(kw)
            return TrainLoopConfig(**base)

        ref = train(mesh, cfg(tmp_path / "a"))
        train(mesh, cfg(tmp_path / "b", steps=4))
        res = train(mesh, cfg(tmp_path / "b", resume=True))
        assert res["start_step"] == 4
        assert np.isfinite(res["loss"]) and ref["loss"] == res["loss"]
        _assert_tree_equal(ref["state"], res["state"])


MESH_AXES = ("dp", "sp", "tp")


@pytest.fixture(scope="module")
def mesh3d(devices):
    return Mesh(np.array(devices[:8]).reshape(2, 2, 2), MESH_AXES)


def _loop_cfg(tmp, **kw):
    base = dict(
        embed=64, heads=8, head_dim=8, seq=32, batch=4, steps=6,
        lr=1e-4, ckpt_dir=str(tmp), ckpt_every=2,
    )
    base.update(kw)
    return TrainLoopConfig(**base)


class TestResume:
    @pytest.mark.parametrize("opt", ["sgd", "zero-adam"])
    def test_killed_run_resumes_bit_exact(self, mesh3d, tmp_path, opt):
        # straight 6-step run (checkpointing on: saves must not perturb)
        ref = train(mesh3d, _loop_cfg(tmp_path / "a", optimizer=opt))
        # "killed" after 4 steps...
        train(mesh3d, _loop_cfg(tmp_path / "b", optimizer=opt, steps=4))
        # ...resumed to 6
        res = train(
            mesh3d,
            _loop_cfg(tmp_path / "b", optimizer=opt, resume=True),
        )
        assert res["start_step"] == 4
        # finite FIRST: two nan-diverged runs would match bitwise too
        assert np.isfinite(res["loss"]), res["loss"]
        assert ref["loss"] == res["loss"]
        _assert_tree_equal(ref["state"], res["state"])

    def test_elastic_resume_on_a_different_mesh(
        self, devices, mesh3d, tmp_path
    ):
        # the elastic story end to end: a run killed on the (2,2,2) mesh
        # resumes on (4,2,1) — restore reshards the state, training
        # continues, and EVERY param tracks the same-mesh continuation
        # closely (bitwise equality is a same-mesh property; across
        # meshes reduction orders differ)
        mesh_b = Mesh(np.array(devices[:8]).reshape(4, 2, 1), MESH_AXES)
        train(mesh3d, _loop_cfg(tmp_path, steps=4))
        res_b = train(mesh_b, _loop_cfg(tmp_path, steps=6, resume=True))
        assert res_b["start_step"] == 4
        assert np.isfinite(res_b["loss"])
        ref = train(mesh3d, _loop_cfg(tmp_path / "ref", steps=6))
        for k, want in ref["state"]["params"].items():
            got = res_b["state"]["params"][k]
            np.testing.assert_allclose(
                np.asarray(got, np.float32),
                np.asarray(want, np.float32),
                rtol=0, atol=1e-5, err_msg=k,
            )
            # and the restored placement is mesh B's
            assert got.sharding.mesh.shape["dp"] == 4, k

    def test_resume_without_checkpoint_starts_fresh(self, mesh3d, tmp_path):
        out = train(
            mesh3d,
            _loop_cfg(tmp_path, steps=2, resume=True, ckpt_every=0),
        )
        assert out["start_step"] == 0
        assert np.isfinite(out["loss"])

    def test_fresh_run_into_used_dir_refused(self, mesh3d, tmp_path):
        # without resume, a dir holding another run's committed steps
        # must be an error (stale steps would poison retention + resume)
        train(mesh3d, _loop_cfg(tmp_path, steps=2))
        with pytest.raises(ValueError, match="already holds committed"):
            train(mesh3d, _loop_cfg(tmp_path, steps=2))

    def test_noop_resume_of_complete_run(self, mesh3d, tmp_path):
        # resuming a finished run must not fabricate a loss
        train(mesh3d, _loop_cfg(tmp_path, steps=2))
        out = train(mesh3d, _loop_cfg(tmp_path, steps=2, resume=True))
        assert out["start_step"] == 2
        assert out["loss"] is None

    def test_training_moves_params(self, mesh3d, tmp_path):
        cfg = _loop_cfg(tmp_path, steps=2, ckpt_every=0)
        out = train(mesh3d, cfg)
        assert int(np.asarray(out["state"]["step"])) == 2
        assert np.isfinite(out["loss"])

    def test_log_every_emits_step_records(self, mesh3d, tmp_path):
        from tpu_patterns.core.results import Record, ResultWriter

        jsonl = tmp_path / "train.jsonl"
        writer = ResultWriter(jsonl_path=str(jsonl))
        cfg = _loop_cfg(
            tmp_path / "ck", steps=4, ckpt_every=0, log_every=2
        )
        train(mesh3d, cfg, writer)
        recs = [
            Record.from_json(line)
            for line in jsonl.read_text().splitlines()
            if line.strip()
        ]
        steps = [r for r in recs if r.pattern == "train_step"]
        assert [int(r.metrics["step"]) for r in steps] == [2, 4]
        assert all(np.isfinite(r.metrics["loss"]) for r in steps)
        assert any(r.pattern == "train" for r in recs)  # final summary
