"""Disaggregated prefill/decode serving: the engine role split
(serve/engine.py handoff/adopt waves), the KV-block wire built on the
comm/p2p block stream (value-preserving involution, TRASH never
shipped, adopted bytes bit-identical — int8 scales included), the
refcount/free-list invariants across the wire, and the
``disagg.transfer`` / ``disagg.adopt`` fault sites (transient ->
retried; deterministic -> bounded recompute, never a torn block).

Parent-side plumbing (lease movement, decode round-robin, the handoff
decision/counters) is tested against fake replicas in test_replica.py;
the CLI flag surface in test_cli.py; the metric names in test_obs.py.
"""

import dataclasses
import tempfile

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from tpu_patterns import faults
from tpu_patterns.models.lm import init_lm_params
from tpu_patterns.models.transformer import ModelConfig, _n_experts
from tpu_patterns.serve import (
    Request,
    ServeEngine,
    TRASH_BLOCK,
    make_paged_lm_decoder,
)

CFG = dict(embed=64, heads=8, head_dim=8, causal=True, dtype="float32")
VOCAB = 64


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    yield
    faults.configure(None)


def _mesh(devices, shape):
    n = int(np.prod(shape))
    return Mesh(np.array(devices[:n]).reshape(shape), ("dp", "sp", "tp"))


def _decoder_and_params(
    mesh, mcfg, *, n_blocks=13, block_len=8, max_len=40,
    cache_int8=False, seed=0,
):
    dec = make_paged_lm_decoder(
        mesh, mcfg, VOCAB, n_blocks=n_blocks, block_len=block_len,
        max_len=max_len, cache_int8=cache_int8,
    )
    flat = init_lm_params(
        jax.random.key(seed), mcfg, VOCAB, _n_experts(mesh, mcfg)
    )
    return dec, dec.stack_params(flat)


def _trace(n, min_p=3, max_p=20, max_gen=6, seed=1):
    rng = np.random.RandomState(seed)
    return [
        Request(
            rid=i,
            tokens=rng.randint(
                0, VOCAB, size=rng.randint(min_p, max_p + 1)
            ).tolist(),
            n_gen=int(rng.randint(1, max_gen + 1)),
        )
        for i in range(n)
    ]


def _copy(reqs):
    return [dataclasses.replace(r) for r in reqs]


class TestSiteRegistry:
    def test_disagg_sites_registered_with_blocks_ctx(self):
        for site in ("disagg.transfer", "disagg.adopt"):
            assert site in faults.KNOWN_SITES
        assert "blocks" in faults.MATCH_KEYS
        (spec,) = faults.parse_spec("disagg.transfer:error:rid=3")
        assert spec.match == (("rid", "3"),)
        (spec,) = faults.parse_spec("disagg.adopt:error:replica=1")
        assert spec.match == (("replica", "1"),)


class TestRoleValidation:
    def test_bad_role_rejected(self, devices):
        mesh = _mesh(devices, (1, 1, 1))
        dec, params = _decoder_and_params(mesh, ModelConfig(**CFG))
        with pytest.raises(ValueError, match="role"):
            ServeEngine(dec, params, slots=2, role="router")

    def test_prefill_role_requires_spool_dir(self, devices):
        mesh = _mesh(devices, (1, 1, 1))
        dec, params = _decoder_and_params(mesh, ModelConfig(**CFG))
        with pytest.raises(ValueError, match="spool_dir"):
            ServeEngine(dec, params, slots=2, role="prefill")


class TestBlockStream:
    @pytest.mark.parametrize("cache_int8", [False, True])
    def test_stream_round_trip_is_bit_identical(
        self, devices, cache_int8
    ):
        # the wire collective: gathered blocks ride a donated
        # double-ppermute around the sp ring — a real declared
        # collective whose net permutation is the identity, so the
        # payload lands bit-identical (int8 scale planes included)
        mesh = _mesh(devices, (1, 2, 2))
        dec, _ = _decoder_and_params(
            mesh, ModelConfig(**CFG), cache_int8=cache_int8
        )
        k = 4
        rng = np.random.RandomState(7)
        vals = {}
        for name, (shape, dt) in dec._pool_leaves().items():
            s = (shape[0], k, *shape[2:])
            if np.dtype(dt) == np.int8:
                vals[name] = rng.randint(
                    -128, 128, size=s
                ).astype(np.int8)
            else:
                vals[name] = rng.randn(*s).astype(dt)
        wire = dec.stream_jit(k)(
            {n: np.asarray(v) for n, v in vals.items()}
        )
        for name, v in vals.items():
            got = np.asarray(wire[name])
            assert got.dtype == v.dtype
            assert np.array_equal(got, v), name


def _engine_pair(dec, params, spool, slots=3):
    pre = ServeEngine(
        dec, params, slots=slots, role="prefill", spool_dir=spool
    )
    de = ServeEngine(dec, params, slots=slots, role="decode")
    return pre, de


class TestEnginePairExactness:
    """The tentpole invariant: prefill -> ship -> adopt -> decode ->
    retire produces the SAME ids the unified engine produces, with the
    refcount identity closed and nothing leaked on either side."""

    @pytest.mark.parametrize("cache_int8", [False, True])
    def test_split_matches_unified_bit_identically(
        self, devices, cache_int8
    ):
        mesh = _mesh(devices, (1, 2, 2))
        dec, params = _decoder_and_params(
            mesh, ModelConfig(**CFG), cache_int8=cache_int8
        )
        reqs = _trace(6)
        want = ServeEngine(dec, params, slots=3).run(_copy(reqs))
        with tempfile.TemporaryDirectory() as spool:
            pre, de = _engine_pair(dec, params, spool)
            got = pre.run(_copy(reqs))
            assert pre.leaked_blocks() == 0
            # every multi-token request crossed the wire as a REAL
            # payload; single-token rows retired at prefill
            assert set(pre.handoffs) == {
                r.rid for r in reqs if r.n_gen > 1
            }
            for m in pre.handoffs.values():
                assert not m["recompute"]
                assert m["blocks"] >= 1 and m["nbytes"] > 0
            de.adopt_queue.extend(
                pre.handoffs[r] for r in sorted(pre.handoffs)
            )
            got.update(de.run([]))
        assert de.leaked_blocks() == 0
        assert de.stats["adopts"] == len(pre.handoffs)
        assert got == want

    def test_shipped_payload_covers_exactly_the_prompt_blocks(
        self, devices
    ):
        # TRASH is never shipped: the wire file holds exactly
        # blocks_for(len(prompt)) blocks per leaf — the gather pads its
        # bucket with TRASH reads, and the ship truncates them off
        mesh = _mesh(devices, (1, 2, 1))
        dec, params = _decoder_and_params(mesh, ModelConfig(**CFG))
        reqs = _trace(4, max_gen=4, seed=3)
        with tempfile.TemporaryDirectory() as spool:
            pre, _ = _engine_pair(dec, params, spool)
            pre.run(_copy(reqs))
            lay = dec.layout
            by_rid = {r.rid: r for r in reqs}
            for rid, m in pre.handoffs.items():
                n_ship = lay.blocks_for(len(by_rid[rid].tokens))
                assert m["blocks"] == n_ship
                with np.load(m["path"]) as data:
                    for name in data.files:
                        assert data[name].shape[1] == n_ship

    def test_adopted_bytes_bit_identical_and_refcounts_close(
        self, devices
    ):
        # int8 pool: the strictest wire — quantized planes AND float32
        # scale planes must land bit-identical, and adoption must seat
        # refcounts/free-list exactly like a local admission
        mesh = _mesh(devices, (1, 2, 2))
        dec, params = _decoder_and_params(
            mesh, ModelConfig(**CFG), cache_int8=True
        )
        reqs = _trace(4, max_gen=5, seed=5)
        with tempfile.TemporaryDirectory() as spool:
            pre, de = _engine_pair(dec, params, spool)
            pre.run(_copy(reqs))
            shipped = {}
            for rid, m in pre.handoffs.items():
                with np.load(m["path"]) as data:
                    shipped[rid] = {
                        n: data[n].copy() for n in data.files
                    }
            de.adopt_queue.extend(
                pre.handoffs[r] for r in sorted(pre.handoffs)
            )
            de._admit_adopts()
            assert not de.adopt_queue
            lay = dec.layout
            adopted = set()
            for s in de.active:
                n_ship = lay.blocks_for(s.lens)
                table = list(s.table[:n_ship])
                adopted.update(table)
                # refcount identity: every adopted block referenced
                # exactly once, absent from the free list, never TRASH
                for b in table:
                    assert b != TRASH_BLOCK
                    assert de.ref[b] == 1
                # re-gather the adopted blocks: bytes across the wire
                # must equal the spooled payload bit-for-bit
                k = n_ship
                src = np.asarray(table, np.int32)
                back = dec.gather_jit(k)(de.pool, src)
                for name, v in shipped[s.rid].items():
                    assert np.array_equal(np.asarray(back[name]), v), (
                        s.rid, name
                    )
            assert not (adopted & set(de.free))
            assert TRASH_BLOCK not in set(de.free)
            # finish the decode leg: nothing leaks, everything retires
            de.run([])
            assert de.leaked_blocks() == 0


class TestDisaggFaultSites:
    def _run_pair(self, devices, transfer_spec=None, adopt_spec=None):
        mesh = _mesh(devices, (1, 2, 1))
        dec, params = _decoder_and_params(mesh, ModelConfig(**CFG))
        reqs = _trace(5, max_gen=5, seed=9)
        want = ServeEngine(dec, params, slots=3).run(_copy(reqs))
        with tempfile.TemporaryDirectory() as spool:
            pre, de = _engine_pair(dec, params, spool)
            try:
                faults.configure(transfer_spec)
                got = pre.run(_copy(reqs))
            finally:
                faults.configure(None)
            de.adopt_queue.extend(
                pre.handoffs[r] for r in sorted(pre.handoffs)
            )
            try:
                faults.configure(adopt_spec)
                got.update(de.run([]))
            finally:
                faults.configure(None)
        assert pre.leaked_blocks() == 0
        assert de.leaked_blocks() == 0
        return pre, de, got, want

    def test_transfer_transient_error_retries_and_ships(self, devices):
        pre, de, got, want = self._run_pair(
            devices, transfer_spec="disagg.transfer:error:count=1"
        )
        # one transient wire error, retried through: every handoff
        # still carried a real payload
        assert pre.stats["handoff_recomputes"] == 0
        assert all(not m["recompute"] for m in pre.handoffs.values())
        assert de.stats["adopts"] == len(pre.handoffs)
        assert got == want

    def test_transfer_deterministic_error_degrades_to_recompute(
        self, devices
    ):
        pre, de, got, want = self._run_pair(
            devices, transfer_spec="disagg.transfer:error:count=99"
        )
        # the wire is down for good: every handoff crosses as a
        # no-payload manifest, the decode pool re-prefills from the
        # prompt — bounded recompute, bit-identical ids, never torn
        assert pre.stats["handoff_recomputes"] == len(pre.handoffs)
        assert all(
            m["recompute"] and m["path"] == "" and m["blocks"] == 0
            for m in pre.handoffs.values()
        )
        assert de.stats["adopts"] == 0
        assert de.stats["adopt_recomputes"] == len(pre.handoffs)
        assert got == want

    def test_adopt_transient_error_retries_and_adopts(self, devices):
        pre, de, got, want = self._run_pair(
            devices, adopt_spec="disagg.adopt:error:count=1"
        )
        assert de.stats["adopt_recomputes"] == 0
        assert de.stats["adopts"] == len(pre.handoffs)
        assert got == want

    def test_adopt_deterministic_error_reprefills_locally(
        self, devices
    ):
        pre, de, got, want = self._run_pair(
            devices, adopt_spec="disagg.adopt:error:count=99"
        )
        # the target blocks came off the free list holding garbage; a
        # failed adopt returns them and re-queues the prompt — an
        # adopted block is never half-written
        assert de.stats["adopts"] == 0
        assert de.stats["adopt_recomputes"] == len(pre.handoffs)
        assert got == want


class TestAdoptedSampling:
    def test_adopted_row_continues_the_sampled_stream(self, devices):
        # the (seed, gen_offset + position) key stream depends only on
        # the request's own identity, so a sampled row decoded on the
        # adopting pool matches the unified engine draw for draw
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG)
        dec = make_paged_lm_decoder(
            mesh, mcfg, VOCAB, n_blocks=13, block_len=8, max_len=40,
            sampling=True,
        )
        flat = init_lm_params(
            jax.random.key(0), mcfg, VOCAB, _n_experts(mesh, mcfg)
        )
        params = dec.stack_params(flat)
        reqs = [
            Request(
                rid=i, tokens=[(i * 3 + j) % VOCAB for j in range(7)],
                n_gen=5, temperature=0.8, top_k=8, seed=17 + i,
            )
            for i in range(3)
        ]
        want = ServeEngine(dec, params, slots=2).run(_copy(reqs))
        with tempfile.TemporaryDirectory() as spool:
            pre = ServeEngine(
                dec, params, slots=2, role="prefill", spool_dir=spool,
            )
            de = ServeEngine(dec, params, slots=2, role="decode")
            got = pre.run(_copy(reqs))
            de.adopt_queue.extend(
                pre.handoffs[r] for r in sorted(pre.handoffs)
            )
            got.update(de.run([]))
        assert got == want
        assert de.leaked_blocks() == 0
