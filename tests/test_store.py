"""Fleet-scoped shared prefix store (serve/store.py + engine wiring):
commit-protocol atomicity (tmp + os.replace, last-commit-wins, never a
torn read), round-trip bit-identity (f32 and int8 scale planes), loud
rejection of foreign/corrupt entries, the admission-miss fetch path
(indistinguishable from a local host-tier hit), scale-out pre-warm,
fault-site degradation (retry transients, recompute on deterministic
failure — never a half-adopted block), and the randomized concurrent
publish/fetch/evict/death property with per-engine AND fleet-wide
refcount/leak invariants."""

import dataclasses
import os
import threading

import numpy as np
import pytest

from test_serve import (
    CFG,
    _assert_tier_invariants,
    _conv_reqs,
    _decoder_and_params,
    _mesh,
)
from tpu_patterns import faults
from tpu_patterns.models.transformer import ModelConfig
from tpu_patterns.serve import ServeEngine
from tpu_patterns.serve.store import (
    META_MEMBER,
    PrefixStore,
    block_fingerprint,
    scan,
)

LEAVES_F32 = {
    "k": ((1, 8, 2, 4), np.dtype(np.float32)),
    "v": ((1, 8, 2, 4), np.dtype(np.float32)),
}
# the int8 pool shape: quantized planes plus their f32 scales — the
# bit-identity contract covers BOTH (a store that round-trips the int8
# payload but perturbs a scale plane corrupts every adopted block)
LEAVES_I8 = {
    "k": ((1, 8, 2, 4), np.dtype(np.int8)),
    "k_scale": ((1, 8, 2, 1), np.dtype(np.float32)),
    "v": ((1, 8, 2, 4), np.dtype(np.int8)),
    "v_scale": ((1, 8, 2, 1), np.dtype(np.float32)),
}


def _block(leaves, seed):
    rng = np.random.RandomState(seed)
    out = {}
    for name, (shape, dt) in leaves.items():
        if dt == np.int8:
            out[name] = rng.randint(-128, 128, size=shape).astype(dt)
        else:
            out[name] = rng.randn(*shape).astype(dt)
    return out


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.configure(None)
    yield
    faults.configure(None)


class TestPrefixStoreUnit:
    @pytest.mark.parametrize(
        "leaves", [LEAVES_F32, LEAVES_I8], ids=["f32", "int8"]
    )
    def test_round_trip_bit_identical(self, tmp_path, leaves):
        st = PrefixStore(
            str(tmp_path / "s"), leaves, block_len=8,
            fingerprint={"cfg": 1},
        )
        path = tuple(range(16))
        data = _block(leaves, 3)
        nbytes = st.publish(
            {n: a.copy() for n, a in data.items()}, path
        )
        assert nbytes == st.block_nbytes() == sum(
            a.nbytes for a in data.values()
        )
        got = st.fetch(path)
        assert set(got) == set(leaves)
        for name, a in data.items():
            assert got[name].dtype == a.dtype, name
            assert np.array_equal(got[name], a), name

    def test_fetch_miss_is_none_and_missing_dir_scans_empty(
        self, tmp_path
    ):
        st = PrefixStore(str(tmp_path / "s"), LEAVES_F32, block_len=8)
        assert st.fetch((1,) * 8) is None
        assert len(st) == 0
        assert scan(str(tmp_path / "nowhere")) == []

    def test_last_commit_wins_and_no_tmp_litter(self, tmp_path):
        # two handles on the SAME directory (two publishers): both
        # commit the same path, the later os.replace wins whole
        root = str(tmp_path / "s")
        a = PrefixStore(root, LEAVES_F32, block_len=8)
        b = PrefixStore(root, LEAVES_F32, block_len=8)
        path = tuple(range(8))
        first, second = _block(LEAVES_F32, 1), _block(LEAVES_F32, 2)
        a.publish({n: x.copy() for n, x in first.items()}, path)
        b.publish({n: x.copy() for n, x in second.items()}, path)
        got = a.fetch(path)
        for name in second:
            assert np.array_equal(got[name], second[name])
        assert len(a) == 1
        assert not [f for f in os.listdir(root) if f.endswith(".tmp")]

    def test_publish_validation_is_loud(self, tmp_path):
        st = PrefixStore(str(tmp_path / "s"), LEAVES_F32, block_len=8)
        data = _block(LEAVES_F32, 0)
        with pytest.raises(ValueError, match="whole number"):
            st.publish(data, tuple(range(5)))
        with pytest.raises(ValueError, match="whole number"):
            st.publish(data, ())
        with pytest.raises(ValueError, match="leaves"):
            st.publish({"k": data["k"]}, tuple(range(8)))
        with pytest.raises(ValueError, match="shape"):
            st.publish(
                {"k": np.zeros((2, 8, 2, 4), np.float32),
                 "v": np.zeros((2, 8, 2, 4), np.float32)},
                tuple(range(8)),
            )
        with pytest.raises(ValueError, match="shadows"):
            PrefixStore(
                str(tmp_path / "t"),
                {**LEAVES_F32, META_MEMBER: ((1,), np.dtype(np.int8))},
                block_len=8,
            )

    def test_fetch_rejects_foreign_fingerprint(self, tmp_path):
        root = str(tmp_path / "s")
        st = PrefixStore(
            root, LEAVES_F32, block_len=8, fingerprint={"cfg": 1}
        )
        path = tuple(range(8))
        st.publish(_block(LEAVES_F32, 0), path)
        other = PrefixStore(
            root, LEAVES_F32, block_len=8, fingerprint={"cfg": 2}
        )
        with pytest.raises(ValueError, match="different pool/model"):
            other.fetch(path)

    def test_fetch_rejects_mismatched_block_len_and_leaf_table(
        self, tmp_path
    ):
        root = str(tmp_path / "s")
        st = PrefixStore(root, LEAVES_F32, block_len=8)
        path = tuple(range(8))
        st.publish(_block(LEAVES_F32, 0), path)
        with pytest.raises(ValueError, match="block_len"):
            PrefixStore(root, LEAVES_F32, block_len=4).fetch(path)
        with pytest.raises(ValueError, match="leaf table"):
            PrefixStore(root, LEAVES_I8, block_len=8).fetch(path)

    def test_fetch_refuses_corrupt_payload(self, tmp_path):
        # tamper with a committed entry's payload without updating the
        # digest: fetch must refuse the block, never adopt wrong bytes
        import json

        st = PrefixStore(str(tmp_path / "s"), LEAVES_F32, block_len=8)
        path = tuple(range(8))
        st.publish(_block(LEAVES_F32, 0), path)
        entry = st.entry_path(path)
        with np.load(entry) as z:
            meta = bytes(z[META_MEMBER])
            payload = {
                n: np.array(z[n]) for n in z.files if n != META_MEMBER
            }
        payload["k"] = payload["k"] + 1.0
        with open(entry, "wb") as f:
            np.savez(
                f,
                **{META_MEMBER: np.frombuffer(meta, np.uint8)},
                **payload,
            )
        with pytest.raises(ValueError, match="digest"):
            st.fetch(path)
        # and the entry under the WRONG fingerprint key is refused too
        ok = _block(LEAVES_F32, 1)
        st.publish(ok, path)
        os.replace(
            st.entry_path(path),
            os.path.join(
                st.root, block_fingerprint(tuple(range(8, 16))) + ".npz"
            ),
        )
        with pytest.raises(ValueError, match="does not match"):
            st.fetch(tuple(range(8, 16)))

    def test_scan_shallow_first_skips_foreign_and_inflight(
        self, tmp_path
    ):
        root = str(tmp_path / "s")
        st = PrefixStore(
            root, LEAVES_F32, block_len=8, fingerprint={"cfg": 1}
        )
        deep = tuple(range(16))
        st.publish(_block(LEAVES_F32, 1), deep)
        st.publish(_block(LEAVES_F32, 0), deep[:8])
        # garbage and an in-flight tmp sibling are not entries
        with open(os.path.join(root, "junk.npz"), "wb") as f:
            f.write(b"not an npz")
        with open(os.path.join(root, "x.npz.1.0.tmp"), "wb") as f:
            f.write(b"partial")
        # a foreign-fingerprint entry is skipped quietly (scan is the
        # advisory plane; fetch stays the loud path)
        PrefixStore(
            root, LEAVES_F32, block_len=8, fingerprint={"cfg": 2}
        ).publish(_block(LEAVES_F32, 2), tuple(range(100, 108)))
        got = [p for p, _ in st.scan()]
        assert got == [deep[:8], deep]

    def test_concurrent_publishers_never_tear_a_reader(self, tmp_path):
        """Threaded hammer on ONE path: publishers race os.replace
        while readers fetch continuously — every fetch must return a
        COMPLETE committed payload (the digest check turns any torn
        read into a loud error) that equals one of the published
        variants bit-for-bit."""
        root = str(tmp_path / "s")
        path = tuple(range(8))
        variants = [_block(LEAVES_F32, s) for s in range(4)]
        errors: list = []
        stop = threading.Event()

        def publisher(seed):
            st = PrefixStore(root, LEAVES_F32, block_len=8)
            rng = np.random.RandomState(seed)
            try:
                for _ in range(25):
                    v = variants[rng.randint(len(variants))]
                    st.publish({n: a.copy() for n, a in v.items()}, path)
            except Exception as e:  # noqa: BLE001 - failing the test
                errors.append(e)

        def reader():
            st = PrefixStore(root, LEAVES_F32, block_len=8)
            try:
                while not stop.is_set():
                    got = st.fetch(path)
                    if got is None:
                        continue
                    assert any(
                        all(
                            np.array_equal(got[n], v[n]) for n in v
                        )
                        for v in variants
                    ), "fetched payload matches no published variant"
            except Exception as e:  # noqa: BLE001 - failing the test
                errors.append(e)

        threads = [
            threading.Thread(target=publisher, args=(s,))
            for s in range(3)
        ] + [threading.Thread(target=reader) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads[:3]:
            t.join()
        stop.set()
        for t in threads[3:]:
            t.join()
        assert not errors, errors
        assert not [
            f for f in os.listdir(root) if f.endswith(".tmp")
        ], "a publisher left tmp litter"


def _store_engine(devices, store_dir, *, n_blocks=15, slots=4,
                  cache_int8=False, prefix_store=True, seed=0,
                  fingerprint=None):
    mesh = _mesh(devices, (1, 1, 1))
    mcfg = ModelConfig(**CFG, depth=1)
    dec, params, flat = _decoder_and_params(
        mesh, mcfg, n_blocks=n_blocks, block_len=8, max_len=40,
        cache_int8=cache_int8, seed=seed,
    )
    eng = ServeEngine(
        dec, params, slots=slots, kv_host_tier=True,
        prefix_store=(str(store_dir) if prefix_store else None),
        fingerprint=fingerprint or {"t": 1, "int8": cache_int8},
    )
    return eng, dec, params


class TestStoreEngineIntegration:
    def test_requires_kv_host_tier_and_rejects_roles(self, devices):
        mesh = _mesh(devices, (1, 1, 1))
        mcfg = ModelConfig(**CFG, depth=1)
        dec, params, _ = _decoder_and_params(mesh, mcfg)
        with pytest.raises(ValueError, match="requires kv_host_tier"):
            ServeEngine(dec, params, slots=2, prefix_store="/tmp/x")
        with pytest.raises(ValueError, match="disaggregated"):
            ServeEngine(
                dec, params, slots=2, kv_host_tier=True,
                prefix_store="/tmp/x", role="decode",
            )

    def test_store_off_is_free(self, devices):
        eng, *_ = _store_engine(devices, None, prefix_store=False)
        eng.run([dataclasses.replace(r) for r in _conv_reqs(4)])
        assert eng.store is None
        assert eng.stats["store_publishes"] == 0
        assert eng.stats["store_hits"] == 0
        _assert_tier_invariants(eng)

    @pytest.mark.parametrize("int8", [False, True])
    def test_second_engine_fetches_what_first_published(
        self, devices, tmp_path, int8
    ):
        """The tentpole miss path: engine B's admission miss consults
        the store engine A populated and serves with ZERO fresh full
        prompt blocks — outputs bit-identical, both pools leak-free,
        and every store round-trip bit-identical to A's host copy."""
        sd = tmp_path / "store"
        reqs = _conv_reqs(6)
        e1, *_ = _store_engine(devices, sd, cache_int8=int8)
        out1 = e1.run([dataclasses.replace(r) for r in reqs])
        assert e1.stats["store_publishes"] > 0
        assert len(e1.store) == e1.stats["store_publishes"]
        assert e1.stats["store_publish_bytes"] == (
            e1.stats["store_publishes"] * e1.store.block_nbytes()
        )
        _assert_tier_invariants(e1)
        # bit-identity against the publisher's own host copies
        for h, path in e1.tier.paths.items():
            got = e1.store.fetch(path)
            if got is None:
                continue
            for name, a in e1.tier.get(h).items():
                assert got[name].dtype == a.dtype
                assert np.array_equal(got[name], a), (path, name)
        e2, *_ = _store_engine(devices, sd, cache_int8=int8, seed=0)
        out2 = e2.run([dataclasses.replace(r) for r in reqs])
        assert out2 == out1
        assert e2.stats["store_hits"] > 0
        assert e2.stats["prompt_fresh_full_blocks"] == 0
        assert e2.stats["store_fetch_bytes"] == (
            e2.stats["store_hits"] * e2.store.block_nbytes()
        )
        _assert_tier_invariants(e2)
        # fleet-wide: both engines' ledgers balance
        assert e1.leaked_blocks() + e2.leaked_blocks() == 0

    def test_fetch_degrades_to_fresh_prefill_on_foreign_store(
        self, devices, tmp_path
    ):
        """A store directory committed under a DIFFERENT model
        fingerprint: every fetch hits a real entry, loud-rejects in
        validation, and the engine degrades to fresh prefill
        (store_fallbacks trail) — the trace still serves exactly."""
        sd = tmp_path / "store"
        reqs = _conv_reqs(4)
        e1, *_ = _store_engine(devices, sd, seed=0)
        want = e1.run([dataclasses.replace(r) for r in reqs])
        assert e1.stats["store_publishes"] > 0
        eng, *_ = _store_engine(
            devices, sd, seed=0, fingerprint={"t": 999}
        )
        out = eng.run([dataclasses.replace(r) for r in reqs])
        assert out == want
        assert eng.stats["store_hits"] == 0
        assert eng.stats["store_fallbacks"] > 0
        assert eng.leaked_blocks() == 0
        _assert_tier_invariants(eng)

    def test_prewarm_adopts_into_host_tier(self, devices, tmp_path):
        sd = tmp_path / "store"
        reqs = _conv_reqs(6)
        e1, *_ = _store_engine(devices, sd)
        out1 = e1.run([dataclasses.replace(r) for r in reqs])
        entries = e1.store.scan()
        assert entries
        e2, *_ = _store_engine(devices, sd, seed=0)
        n = e2.prewarm_paths([list(p) for p, _ in entries])
        assert n == len(entries) == e2.stats["store_prewarmed"]
        assert len(e2.tier) == n
        # non-block-aligned and unknown paths are skipped, not fatal
        assert e2.prewarm_paths([[1, 2, 3], list(range(64, 72))]) == 0
        out2 = e2.run([dataclasses.replace(r) for r in reqs])
        assert out2 == out1
        # the pre-warmed set answered the whole history: zero store
        # round-trips at admission, zero fresh full prompt blocks
        assert e2.stats["prompt_fresh_full_blocks"] == 0
        assert e2.stats["onload_hits"] > 0
        _assert_tier_invariants(e2)

    def test_property_concurrent_publish_fetch_evict_death(
        self, devices, tmp_path
    ):
        """Satellite property test: two engines share one store under
        a seeded random op schedule — admissions (each engine sees a
        random half of a shared-prefix family), scheduler iterations,
        forced evictions, row quarantines, and DEATH (an engine is
        dropped mid-trace and replaced by a fresh one on the same
        store, like a SIGKILLed replica's slot respawning).  Every
        step holds each engine's refcount/host/free invariants
        (``sum(refcounts) == live table references`` via
        ``_assert_tier_invariants``) and the fleet identity
        ``sum(leaked_blocks) == 0``; every fetch that lands adopted a
        complete committed block (digest-checked upstream)."""
        sd = tmp_path / "store"
        rng = np.random.RandomState(13)
        all_reqs = _conv_reqs(8, n_gen=2)
        engines = {}
        for name in ("a", "b"):
            eng, *_ = _store_engine(devices, sd, n_blocks=17)
            engines[name] = eng
        pending = {
            "a": [r for i, r in enumerate(all_reqs) if i % 2 == 0][::-1],
            "b": [r for i, r in enumerate(all_reqs) if i % 2 == 1][::-1],
        }
        deaths = 0
        for step in range(80):
            name = ("a", "b")[rng.randint(2)]
            eng = engines[name]
            op = rng.randint(5)
            if op == 0 and pending[name]:
                eng.submit(dataclasses.replace(pending[name].pop()))
            eng._retire()
            admitted = eng._admit()
            if admitted:
                eng._prefill(admitted)
                eng._retire()
            if op == 1 and eng.active:
                eng._quarantine(
                    [eng.active.pop(rng.randint(len(eng.active)))],
                    "property-test",
                )
            if op == 2:
                eng._evict_for(rng.randint(1, 3), set())
            if op == 3 and deaths < 2 and step > 20:
                # death: the engine vanishes mid-trace (its un-served
                # half re-queues, like a parent rerouting leases) and
                # a cold replacement joins on the same store
                deaths += 1
                dead = engines[name]
                requeue = [
                    dataclasses.replace(s.req)
                    for s in dead.active
                ] + [dataclasses.replace(r) for r in dead.queue]
                fresh, *_ = _store_engine(devices, sd, n_blocks=17)
                engines[name] = fresh
                pending[name].extend(requeue)
                eng = fresh
            if eng.active:
                eng._step()
            eng._store_publish_wave()
            for e in engines.values():
                _assert_tier_invariants(e)
            assert sum(
                e.leaked_blocks() for e in engines.values()
            ) == 0
        # drain both engines clean
        for name, eng in engines.items():
            while pending[name] or eng.queue or eng.active:
                if pending[name]:
                    eng.submit(dataclasses.replace(pending[name].pop()))
                eng._retire()
                admitted = eng._admit()
                if admitted:
                    eng._prefill(admitted)
                    eng._retire()
                if eng.active:
                    eng._step()
                _assert_tier_invariants(eng)
            eng._store_flush()
            _assert_tier_invariants(eng)
        assert sum(e.leaked_blocks() for e in engines.values()) == 0
        # the survivors collectively used the store: blocks crossed
        total_store_traffic = sum(
            e.stats["store_publishes"] + e.stats["store_hits"]
            for e in engines.values()
        )
        assert total_store_traffic > 0
        assert len(engines["a"].store) > 0


class TestStoreFaults:
    def test_sites_registered_with_match_keys(self):
        for site in ("store.publish", "store.fetch", "store.prewarm"):
            assert site in faults.KNOWN_SITES
        for key in ("rid", "replica", "fingerprint"):
            assert key in faults.MATCH_KEYS

    def test_publish_transient_error_retries_through(
        self, devices, tmp_path
    ):
        faults.configure("store.publish:error:count=1")
        eng, *_ = _store_engine(devices, tmp_path / "s")
        eng.run([dataclasses.replace(r) for r in _conv_reqs(6)])
        assert eng.stats["store_fallbacks"] == 0
        assert eng.stats["store_publishes"] > 0
        assert len(eng.store) == eng.stats["store_publishes"]
        _assert_tier_invariants(eng)

    def test_publish_deterministic_error_skips_never_tears(
        self, devices, tmp_path
    ):
        sd = tmp_path / "s"
        reqs = _conv_reqs(6)
        clean, *_ = _store_engine(
            devices, tmp_path / "clean", seed=0
        )
        want = clean.run([dataclasses.replace(r) for r in reqs])
        faults.configure("store.publish:error:count=1000000")
        eng, *_ = _store_engine(devices, sd, seed=0)
        out = eng.run([dataclasses.replace(r) for r in reqs])
        faults.configure(None)
        # every publish quarantined: local serving untouched, the
        # store holds NOTHING (no entry, no tmp litter) — degraded,
        # never torn
        assert out == want
        assert eng.stats["store_publishes"] == 0
        assert eng.stats["store_fallbacks"] > 0
        assert len(eng.store) == 0
        assert not [
            f for f in os.listdir(sd) if f.endswith(".tmp")
        ]
        _assert_tier_invariants(eng)

    def test_fetch_transient_error_retries_through(
        self, devices, tmp_path
    ):
        sd = tmp_path / "s"
        reqs = _conv_reqs(6)
        e1, *_ = _store_engine(devices, sd)
        out1 = e1.run([dataclasses.replace(r) for r in reqs])
        faults.configure("store.fetch:error:count=1")
        e2, *_ = _store_engine(devices, sd, seed=0)
        out2 = e2.run([dataclasses.replace(r) for r in reqs])
        assert out2 == out1
        assert e2.stats["store_fallbacks"] == 0
        assert e2.stats["store_hits"] > 0
        _assert_tier_invariants(e2)

    def test_fetch_deterministic_error_prefills_fresh(
        self, devices, tmp_path
    ):
        """The satellite contract: deterministic store failure means
        recompute, never a torn or half-adopted block — ids identical
        to the publisher's run, zero store hits, zero leaks."""
        sd = tmp_path / "s"
        reqs = _conv_reqs(6)
        e1, *_ = _store_engine(devices, sd)
        out1 = e1.run([dataclasses.replace(r) for r in reqs])
        faults.configure("store.fetch:error:count=1000000")
        e2, *_ = _store_engine(devices, sd, seed=0)
        out2 = e2.run([dataclasses.replace(r) for r in reqs])
        assert out2 == out1
        assert e2.stats["store_hits"] == 0
        assert e2.stats["store_fallbacks"] > 0
        assert e2.stats["prompt_fresh_full_blocks"] > 0
        assert e2.leaked_blocks() == 0
        _assert_tier_invariants(e2)

    def test_fetch_scoped_to_one_fingerprint_spares_the_rest(
        self, devices, tmp_path
    ):
        """The fingerprint match key: fail exactly ONE prefix's
        migration — the victim recomputes fresh, every other path
        still fetches warm, outputs stay exact."""
        sd = tmp_path / "s"
        reqs = _conv_reqs(6)
        e1, *_ = _store_engine(devices, sd)
        out1 = e1.run([dataclasses.replace(r) for r in reqs])
        victim = e1.store.scan()[0][0]
        faults.configure(
            "store.fetch:error:count=1000000:"
            f"fingerprint={block_fingerprint(victim)}"
        )
        e2, *_ = _store_engine(devices, sd, seed=0)
        out2 = e2.run([dataclasses.replace(r) for r in reqs])
        assert out2 == out1
        assert e2.stats["store_fallbacks"] > 0
        assert e2.stats["store_hits"] > 0  # the others still landed
        _assert_tier_invariants(e2)

    def test_corrupt_entry_degrades_loudly_not_fatally(
        self, devices, tmp_path
    ):
        sd = tmp_path / "s"
        reqs = _conv_reqs(6)
        e1, *_ = _store_engine(devices, sd)
        out1 = e1.run([dataclasses.replace(r) for r in reqs])
        # truncate one committed entry in place: a real torn write
        # cannot happen through os.replace, so simulate disk rot
        victim = e1.store.entry_path(e1.store.scan()[0][0])
        with open(victim, "r+b") as f:
            f.truncate(32)
        e2, *_ = _store_engine(devices, sd, seed=0)
        out2 = e2.run([dataclasses.replace(r) for r in reqs])
        assert out2 == out1
        assert e2.stats["store_fallbacks"] > 0
        assert e2.leaked_blocks() == 0
        _assert_tier_invariants(e2)

    def test_prewarm_deterministic_error_leaves_no_partial_adopt(
        self, devices, tmp_path
    ):
        sd = tmp_path / "s"
        e1, *_ = _store_engine(devices, sd)
        e1.run([dataclasses.replace(r) for r in _conv_reqs(6)])
        entries = e1.store.scan()
        faults.configure("store.prewarm:error:count=1000000")
        e2, *_ = _store_engine(devices, sd, seed=0)
        assert e2.prewarm_paths([list(p) for p, _ in entries]) == 0
        assert e2.stats["store_prewarmed"] == 0
        assert e2.stats["store_fallbacks"] > 0
        assert len(e2.tier) == 0
        assert not e2.index.host_handles()
        _assert_tier_invariants(e2)

    def test_prewarm_transient_error_retries_through(
        self, devices, tmp_path
    ):
        sd = tmp_path / "s"
        e1, *_ = _store_engine(devices, sd)
        e1.run([dataclasses.replace(r) for r in _conv_reqs(6)])
        entries = e1.store.scan()
        faults.configure("store.prewarm:error:count=1")
        e2, *_ = _store_engine(devices, sd, seed=0)
        assert e2.prewarm_paths(
            [list(p) for p, _ in entries]
        ) == len(entries)
        assert e2.stats["store_fallbacks"] == 0
        _assert_tier_invariants(e2)
