"""Compiled-program structural assertions (core/hlo.py).

The tunnel-independent perf-evidence tier (VERDICT r3 next #2): these
tests fail — with no TPU attached — if XLA ever serializes the
decomposed collective-matmul ring into collect-then-compute, or if
remat stops shrinking the compiled buffer assignment at long-context
shapes.  The async start/done overlap check is exercised against a
synthetic scheduled module here (CPU keeps collective-permute
synchronous); the hardware ladder runs the same helper on real TPU HLO.
"""

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_patterns.core import hlo
from tpu_patterns.parallel.overlap import (
    allgather_matmul,
    matmul_reducescatter,
)

N = 8


@pytest.fixture(scope="module")
def mesh(devices):
    return Mesh(np.array(devices[:N]), ("tp",))


def _ag(mesh, decomposed):
    return shard_map(
        partial(
            allgather_matmul, axis_name="tp", axis_size=N,
            decomposed=decomposed,
        ),
        mesh=mesh,
        in_specs=(P("tp", None), P(None, "tp")),
        out_specs=P(None, "tp"),
    )


def _rs(mesh, decomposed):
    return shard_map(
        partial(
            matmul_reducescatter, axis_name="tp", axis_size=N,
            decomposed=decomposed,
        ),
        mesh=mesh,
        in_specs=(P(None, "tp"), P("tp", None)),
        out_specs=P("tp", None),
    )


class TestRingInterleaved:
    """The decomposed collective matmul must keep transfer and matmul in
    ONE loop body — the structure the overlap claim rests on."""

    X_AG = jax.ShapeDtypeStruct((N * 16, 64), jnp.float32)
    W_AG = jax.ShapeDtypeStruct((64, N * 32), jnp.float32)
    X_RS = jax.ShapeDtypeStruct((N * 16, N * 64), jnp.float32)
    W_RS = jax.ShapeDtypeStruct((N * 64, 32), jnp.float32)

    def test_allgather_matmul_ring_survives_compilation(self, mesh):
        txt = hlo.optimized_hlo(_ag(mesh, True), self.X_AG, self.W_AG)
        assert hlo.ring_interleaved(txt), (
            "XLA serialized the decomposed all-gather matmul: no loop "
            "body carries both a collective-permute and a dot"
        )
        # and the collective really was decomposed away
        assert hlo.opcode_counts(txt, ["all-gather"])["all-gather"] == 0

    def test_reducescatter_matmul_ring_survives_compilation(self, mesh):
        txt = hlo.optimized_hlo(_rs(mesh, True), self.X_RS, self.W_RS)
        assert hlo.ring_interleaved(txt)
        assert (
            hlo.opcode_counts(txt, ["reduce-scatter"])["reduce-scatter"]
            == 0
        )

    def test_baselines_are_not_interleaved(self, mesh):
        """The undecomposed forms must NOT satisfy the predicate — that
        is what makes a True from the decomposed form evidence rather
        than vacuity."""
        ag = hlo.optimized_hlo(_ag(mesh, False), self.X_AG, self.W_AG)
        rs = hlo.optimized_hlo(_rs(mesh, False), self.X_RS, self.W_RS)
        assert not hlo.ring_interleaved(ag)
        assert not hlo.ring_interleaved(rs)
        assert hlo.opcode_counts(ag, ["all-gather"])["all-gather"] >= 1
        assert (
            hlo.opcode_counts(rs, ["reduce-scatter"])["reduce-scatter"]
            >= 1
        )


# A hand-written scheduled module in the two shapes that matter: the
# start/done pair with compute between (overlap) and without (serial).
# Shapes/operands mimic real TPU scheduled dumps, incl. tuple types with
# /*index=N*/ comments that contain '=' inside the type expression.
_OVERLAPPED = """\
HloModule m

%body (p: (f32[128,64], f32[128,64])) -> (f32[128,64], f32[128,64]) {
  %p = (f32[128,64]{1,0}, f32[128,64]{1,0}) parameter(0)
  %gte.0 = f32[128,64]{1,0} get-tuple-element(%p), index=0
  %gte.1 = f32[128,64]{1,0} get-tuple-element(%p), index=1
  %cp-start = (f32[128,64]{1,0}, f32[128,64]{1,0}, u32[], /*index=3*/u32[]) collective-permute-start(%gte.0), source_target_pairs={{0,1},{1,0}}
  %dot.0 = f32[128,64]{1,0} dot(%gte.1, %gte.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %fusion.0 = f32[128,64]{1,0} fusion(%dot.0), kind=kLoop, calls=%fc
  %cp-done = f32[128,64]{1,0} collective-permute-done(%cp-start)
  ROOT %tuple.0 = (f32[128,64]{1,0}, f32[128,64]{1,0}) tuple(%cp-done, %fusion.0)
}

ENTRY %main (a: f32[128,64], b: f32[128,64]) -> (f32[128,64], f32[128,64]) {
  %a = f32[128,64]{1,0} parameter(0)
  %b = f32[128,64]{1,0} parameter(1)
  %t = (f32[128,64]{1,0}, f32[128,64]{1,0}) tuple(%a, %b)
  ROOT %call.0 = (f32[128,64]{1,0}, f32[128,64]{1,0}) call(%t), to_apply=%body
}
"""

_SERIALIZED = _OVERLAPPED.replace(
    """%cp-start = (f32[128,64]{1,0}, f32[128,64]{1,0}, u32[], /*index=3*/u32[]) collective-permute-start(%gte.0), source_target_pairs={{0,1},{1,0}}
  %dot.0 = f32[128,64]{1,0} dot(%gte.1, %gte.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %fusion.0 = f32[128,64]{1,0} fusion(%dot.0), kind=kLoop, calls=%fc
  %cp-done = f32[128,64]{1,0} collective-permute-done(%cp-start)""",
    """%cp-start = (f32[128,64]{1,0}, f32[128,64]{1,0}, u32[], /*index=3*/u32[]) collective-permute-start(%gte.0), source_target_pairs={{0,1},{1,0}}
  %cp-done = f32[128,64]{1,0} collective-permute-done(%cp-start)
  %dot.0 = f32[128,64]{1,0} dot(%gte.1, %gte.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %fusion.0 = f32[128,64]{1,0} fusion(%dot.0), kind=kLoop, calls=%fc""",
)


class TestAsyncOverlapSpans:
    def test_overlapped_schedule_counts_compute(self):
        spans = hlo.async_overlap_spans(_OVERLAPPED)
        assert spans == [("%cp-start", 2)]

    def test_serialized_schedule_counts_zero(self):
        spans = hlo.async_overlap_spans(_SERIALIZED)
        assert spans == [("%cp-start", 0)]
        assert not any(n > 0 for _, n in spans), (
            "a start immediately awaited hides nothing"
        )

    def test_sync_modules_have_no_spans(self):
        # CPU modules (sync collective-permute) -> "not applicable"
        assert hlo.async_overlap_spans(_OVERLAPPED.replace("-start", "")
                                       .replace("-done", "")) == []

    def test_prefix_names_pair_correctly(self):
        """'%cp-start.1' must not close on the done of '%cp-start.12' —
        pairing is by whole operand name, not substring."""
        mod = """\
HloModule m

ENTRY %main (a: f32[8,128]) -> f32[8,128] {
  %a = f32[8,128]{1,0} parameter(0)
  %cp-start.1 = (f32[8,128]{1,0}, f32[8,128]{1,0}) collective-permute-start(%a), source_target_pairs={{0,1}}
  %cp-start.12 = (f32[8,128]{1,0}, f32[8,128]{1,0}) collective-permute-start(%a), source_target_pairs={{1,0}}
  %cp-done.12 = f32[8,128]{1,0} collective-permute-done(%cp-start.12)
  %dot.1 = f32[8,128]{1,0} dot(%a, %a), lhs_contracting_dims={1}, rhs_contracting_dims={1}
  %cp-done.1 = f32[8,128]{1,0} collective-permute-done(%cp-start.1)
  ROOT %add.1 = f32[8,128]{1,0} add(%cp-done.1, %dot.1)
}
"""
        spans = dict(hlo.async_overlap_spans(mod))
        assert spans == {"%cp-start.1": 1, "%cp-start.12": 0}


class TestRematBufferAssignment:
    def test_remat_shrinks_temp_at_longctx_shapes(self, mesh):
        """depth=4, L=4096: the compiled buffer assignment itself must
        shrink under remat — the claim is about the executable, not a
        runtime sample, so an XLA regression that silently keeps the
        full activation stash fails CI with no TPU (VERDICT r3 next #2b).
        AOT: lower on ShapeDtypeStructs, nothing is executed."""
        from tpu_patterns.models import (
            ModelConfig,
            init_params,
            make_train_step,
            shard_params,
        )

        mesh3d = Mesh(
            np.asarray(mesh.devices).reshape(2, 2, 2), ("dp", "sp", "tp")
        )
        L = 4096
        temps = {}
        for remat in (False, True):
            cfg = ModelConfig(
                embed=128, heads=4, head_dim=32, depth=4, remat=remat
            )
            step, _ = make_train_step(mesh3d, cfg, lr=1e-3)
            p = shard_params(
                init_params(jax.random.key(0), cfg), mesh3d, cfg
            )
            x = jax.device_put(
                jnp.zeros((2, L, cfg.embed), jnp.float32),
                NamedSharding(mesh3d, P("dp", "sp", None)),
            )
            temps[remat] = hlo.temp_bytes(step, p, x)
        if temps[False] is None or temps[True] is None:
            pytest.skip("backend exposes no memory analysis")
        # the stash is O(depth * L * E); remat must reclaim most of it,
        # not merely win a rounding error
        assert temps[True] < 0.8 * temps[False], temps


class TestHloCheckPattern:
    def test_cells_emit_expected_verdicts(self, tmp_path):
        """The CLI-facing pattern: ring cells pass on the CPU mesh, the
        TPU-oracle cells are SKIPPED (never silently passed)."""
        from tpu_patterns.core.results import ResultWriter, Verdict
        from tpu_patterns.hlocheck import HloCheckConfig, run_hlocheck

        writer = ResultWriter(jsonl_path=tmp_path / "hlo.jsonl")
        records = run_hlocheck(
            None,
            HloCheckConfig(
                rows=8, contract=128, cols=128, seq=512, depth=2, embed=64
            ),
            writer,
        )
        verdicts = {r.mode: r.verdict for r in records}
        assert verdicts["ring_ag"] is Verdict.SUCCESS
        assert verdicts["ring_rs"] is Verdict.SUCCESS
        assert verdicts["remat_temp"] is Verdict.SUCCESS
        assert verdicts["async_overlap"] is Verdict.SKIPPED
        assert verdicts["vmem_boundary"] is Verdict.SKIPPED
        # grad-chain FLOP crosscheck: the honest chain matches the
        # single grad AND the dq-only DCE twin counts measurably fewer
        assert verdicts["grad_flops"] is Verdict.SUCCESS
        by_mode = {r.mode: r for r in records}
        gf = by_mode["grad_flops"].metrics
        assert gf["discriminates"] == 1.0
        assert gf["twin_over_chain"] <= 0.8
        assert 0.5 <= gf["chain_per_op_ratio"] <= 1.6
        # Mosaic-call counting needs a TPU
        assert verdicts["flash_chain_calls"] is Verdict.SKIPPED
        assert writer.exit_code == 0
