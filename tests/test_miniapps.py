"""Miniapp framework + ring-allreduce app (SURVEY.md C15-C17).

The parametrized matrix below IS the CTest registration: every discovered
<app>/<variant> x dtype x algorithm runs as its own self-validating test,
exactly how add_typed_mpi_app turns builds into `mpirun -np 4` CTest runs
(src/CMakeLists.txt:39-50)."""

import numpy as np
import pytest
from jax.sharding import Mesh

from tpu_patterns.core.results import ResultWriter, Verdict
from tpu_patterns.miniapps import framework
from tpu_patterns.miniapps.apps import allreduce as core

N = 512  # small per-rank buffer for CPU-simulated runs
FAST = dict(elements=N, reps=2, warmup=1)


def _mesh(devices, n):
    return Mesh(np.array(devices[:n]), ("ranks",))


def test_discovery_finds_allreduce_variants():
    specs = framework.discover()
    names = {s.name for s in specs}
    assert {"allreduce/xla", "allreduce/pallas"} <= names
    x = framework.get_variant("allreduce", "xla")
    assert "float32" in x.dtypes and "int32" in x.dtypes  # typed matrix
    with pytest.raises(KeyError):
        framework.get_variant("allreduce", "cuda")


def test_typed_runs_expand_dtypes():
    pairs = list(framework.typed_runs())
    assert ("allreduce/xla", "int32") in {(s.name, d) for s, d in pairs}
    assert len(pairs) >= 5


# The full matrix: variant x dtype x algorithm (≙ CTest's app list).
MATRIX = [
    (spec, dt, alg)
    for spec, dt in framework.typed_runs()
    for alg in spec.axes.get("algorithm", ("ring",))
]


@pytest.mark.parametrize(
    "spec,dtype,alg", MATRIX, ids=[f"{s.name}.{d}.{a}" for s, d, a in MATRIX]
)
def test_allreduce_matrix(devices, spec, dtype, alg):
    mesh = _mesh(devices, 4)
    rec = spec.run(mesh=mesh, dtype=dtype, algorithm=alg, **FAST)
    assert rec.verdict is Verdict.SUCCESS
    assert rec.metrics["validated"] == 1.0
    assert rec.metrics["wall_s"] > 0
    assert rec.config["world"] == 4


def test_allreduce_eight_ranks(devices):
    rec = framework.get_variant("allreduce", "xla").run(
        mesh=_mesh(devices, 8), dtype="float32", algorithm="ring_opt", **FAST
    )
    assert rec.verdict is Verdict.SUCCESS


def test_world_size_requirement(devices):
    # ≙ allreduce-mpi-sycl.cpp:95-97: even size >= 4 or error out.
    spec = framework.get_variant("allreduce", "xla")
    with pytest.raises(ValueError, match="even world size"):
        spec.run(mesh=_mesh(devices, 2), dtype="float32", **FAST)
    rec = spec.run(
        mesh=_mesh(devices, 2), dtype="float32", require_even_ge4=False, **FAST
    )
    assert rec.verdict is Verdict.SUCCESS  # override for reduced CI meshes


def test_pallas_rejects_library_path(devices):
    with pytest.raises(ValueError, match="manual ring"):
        framework.get_variant("allreduce", "pallas").run(
            mesh=_mesh(devices, 4), dtype="float32", algorithm="psum", **FAST
        )


def test_ring_opt_divisibility(devices):
    with pytest.raises(ValueError, match="elements % world"):
        framework.get_variant("allreduce", "xla").run(
            mesh=_mesh(devices, 4),
            dtype="float32",
            algorithm="ring_opt",
            elements=130,
            reps=1,
        )


@pytest.mark.parametrize("kind", sorted(core.MEM_KINDS))
def test_allocator_matrix(devices, kind):
    # ≙ the -H/-D/-S allocator choices (allreduce-mpi-sycl.cpp:104-131).
    # Host kinds may be unsupported on a backend -> clean SKIPPED, never an
    # exception (the reference instead #ifdef-gates its USM allocators).
    rec = framework.get_variant("allreduce", "xla").run(
        mesh=_mesh(devices, 4), dtype="float32", mem_kind=kind, **FAST
    )
    assert rec.verdict in (Verdict.SUCCESS, Verdict.SKIPPED)
    if kind == "D":
        assert rec.verdict is Verdict.SUCCESS


def test_run_all_aggregates(devices, tmp_path):
    writer = ResultWriter(jsonl_path=tmp_path / "miniapps.jsonl")
    records = framework.run_all(writer=writer, mesh=_mesh(devices, 4), **FAST)
    assert len(records) == len(list(framework.typed_runs()))
    assert writer.exit_code == 0  # ≙ ctest all green
    lines = (tmp_path / "miniapps.jsonl").read_text().splitlines()
    assert len(lines) == len(records)


def test_wire_bytes_model():
    nb = 1000
    assert core.wire_bytes_per_rank("ring", nb, 4) == 3000
    assert core.wire_bytes_per_rank("ring_opt", nb, 4) == 1500
    assert core.wire_bytes_per_rank("psum", nb, 4) == 1500
