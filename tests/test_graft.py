"""Driver contracts: __graft_entry__.entry / dryrun_multichip + bench.py."""

import json
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

from conftest import load_root_module as _load

ROOT = Path(__file__).resolve().parent.parent


class TestGraftEntry:
    def test_entry_jits(self):
        import numpy as np

        graft = _load("__graft_entry__")
        fn, args = graft.entry()
        out = np.asarray(jax.jit(fn)(*args))
        # flagship forward: activation tensor shaped like the input batch
        assert out.shape == args[-1].shape
        assert np.isfinite(out).all() and np.abs(out).max() > 0

    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_dryrun_multichip(self, devices, n):
        graft = _load("__graft_entry__")
        graft.dryrun_multichip(n)  # raises on compile or numeric failure

    @pytest.mark.parametrize("n", [16, 32])
    def test_dryrun_multichip_large_fresh_process(self, n):
        # 16 (the v5p-16 target shape) and 32 need more virtual devices
        # than the pytest backend holds — run in a fresh process, where
        # _force_cpu_platform provisions them.  Each n runs ALL its mesh
        # factorizations (VERDICT r2 next #7).
        import os

        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, str(ROOT / "__graft_entry__.py"), "dryrun", str(n)],
            env=env,
            capture_output=True,
            text=True,
            timeout=560,
            cwd=ROOT,
        )
        assert proc.returncode == 0, proc.stderr[-3000:]
        assert f"dryrun_multichip({n}) ok" in proc.stdout

    def test_factorizations_cover_multiple_splits(self):
        graft = _load("__graft_entry__")
        for n in (8, 16, 32):
            facts = graft._factorizations(n)
            assert len(facts) >= 2, n
            for dp, sp, tp, pp in facts:
                assert dp * sp * tp * pp == n
                assert 8 % tp == 0  # probe heads/vocab divide over tp
            assert len(set(facts)) == len(facts)
        # unknown n: greedy single split, still a valid factorization
        (f,) = graft._factorizations(6)
        assert int(np.prod(f)) == 6

    def test_dryrun_too_many_devices(self, devices):
        graft = _load("__graft_entry__")
        # Backend is live at 8 CPU devices under pytest: provisioning is
        # impossible, so both requests get the honest shortfall error.
        with pytest.raises(RuntimeError, match="only"):
            graft.dryrun_multichip(1024)
        with pytest.raises(RuntimeError, match="only"):
            graft.dryrun_multichip(16)

    def test_dryrun_provisioning_cap_fresh_process(self):
        # In a fresh process the dryrun provisions virtual CPU devices on
        # demand; absurd requests must fail fast BEFORE any compile and
        # before mutating global config.
        import os

        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import __graft_entry__ as g; g.dryrun_multichip(1024)",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
            cwd=ROOT,
        )
        assert proc.returncode != 0
        assert "refusing to provision" in proc.stderr


class TestBench:
    def test_spec_lookup(self):
        bench = _load("bench")
        hbm, ici = bench._spec_tables()
        assert bench._spec(hbm, "TPU v5 lite") == 819.0
        assert bench._spec(hbm, "TPU v5p chip") == 2765.0
        assert bench._spec(hbm, "unknown") is None
        assert bench._spec(ici, "TPU v5 lite") == 50.0

    @pytest.mark.parametrize("watchdog", [True, False])
    def test_bench_emits_one_json_line(self, watchdog):
        # Subprocess on the CPU-simulated mesh: stdout must be exactly one
        # parsable JSON line with the driver's schema — with the watchdog
        # parent filtering (default) AND with the watchdog disabled, where
        # _child_main runs in-process and must not emit the quick line.
        import os

        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env["TPU_PATTERNS_COUNT"] = "65536"  # small workload for CI
        # fallback OFF: a broken measurement must FAIL here, not be
        # masked by the repo's committed banked records
        env["TPU_PATTERNS_BENCH_BANKED"] = "/nonexistent"
        if not watchdog:
            env["TPU_PATTERNS_BENCH_TIMEOUT"] = "0"
        proc = subprocess.run(
            [sys.executable, str(ROOT / "bench.py")],
            env=env,
            capture_output=True,
            text=True,
            timeout=300,
            cwd=ROOT,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, proc.stdout
        rec = json.loads(lines[0])
        assert set(rec) >= {"metric", "value", "unit", "vs_baseline"}
        assert rec["metric"] != "bench_error", rec
        assert rec["value"] > 0
        assert "stale" not in rec, "live run must not emit banked data"

    def test_last_metric_line_selection(self):
        # The parent's salvage helper must pick the LAST driver-schema
        # line and ignore non-JSON chatter and schema-less scalars.
        bench = _load("bench")
        sample = "\n".join(
            [
                "42",  # parseable but schema-less: must be skipped
                json.dumps({"metric": "m", "value": 1, "stage": "quick"}),
                "not json",
                json.dumps({"metric": "m", "value": 2}),
                "trailing noise",
            ]
        )
        assert json.loads(bench.last_metric_line(sample)) == {
            "metric": "m",
            "value": 2,
        }
        assert bench.last_metric_line("chatter\n42\n") is None
        assert bench.last_metric_line("") is None

    def test_bench_salvages_provisional_line_on_hang(self, tmp_path):
        # A child that prints a provisional quick-pass line and then hangs
        # must yield that line (plus a hang note), not a bare error.
        import os
        import textwrap

        fake_repo = tmp_path / "fakebench"
        fake_repo.mkdir()
        bench_src = (ROOT / "bench.py").read_text()
        # swap the real measurement for a scripted child: the watchdog
        # machinery (preflight, ladder salvage) is what's under test
        stub = textwrap.dedent(
            '''
            def _child_main() -> int:
                import json, sys, time
                print(json.dumps({"metric": "hbm_copy", "value": 12.3,
                                  "unit": "GB/s", "vs_baseline": 0.5,
                                  "stage": "quick"}), flush=True)
                time.sleep(3600)  # full-size pass "hangs"
                return 0

            def _preflight_main() -> int:
                print("preflight_ok stub")
                return 0
            '''
        )
        marker = "def main() -> int:"
        head, tail = bench_src.split(marker, 1)
        (fake_repo / "bench.py").write_text(head + stub + marker + tail)
        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env["TPU_PATTERNS_BENCH_PREFLIGHT"] = "30"
        env["TPU_PATTERNS_BENCH_TIMEOUT"] = "6"
        proc = subprocess.run(
            [sys.executable, str(fake_repo / "bench.py")],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
            cwd=fake_repo,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, proc.stdout
        rec = json.loads(lines[0])
        assert rec["metric"] == "hbm_copy" and rec["value"] == 12.3
        assert "provisional" in rec["error"]

    def test_bench_preflight_failure_is_fast_and_distinguishable(self):
        # A broken device backend must cost ~2 preflight deadlines, not the
        # whole measurement budget, and the error must say "preflight".
        import os
        import time

        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env["JAX_PLATFORMS"] = "no_such_platform"  # preflight child dies
        env["TPU_PATTERNS_BENCH_PREFLIGHT"] = "20"
        env["TPU_PATTERNS_BENCH_TIMEOUT"] = "900"
        # pin the banked-result fallback OFF: this test is about the pure
        # error path (the repo's docs/measured/ holds real banked records)
        env["TPU_PATTERNS_BENCH_BANKED"] = "/nonexistent"
        t0 = time.monotonic()
        proc = subprocess.run(
            [sys.executable, str(ROOT / "bench.py")],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
            cwd=ROOT,
        )
        elapsed = time.monotonic() - t0
        assert proc.returncode == 0
        rec = json.loads(proc.stdout.strip().splitlines()[-1])
        assert rec["metric"] == "bench_error"
        assert "preflight" in rec["error"]
        assert elapsed < 60, f"preflight failure took {elapsed:.0f}s"

    def test_banked_fallback_prefers_clean_then_newest(self, tmp_path):
        # The fallback must skip error-only and already-stale records,
        # prefer a clean banked number over a newer salvaged one, and
        # attach full staleness provenance.
        import os

        bench = _load("bench")
        banked = tmp_path / "rXlive"
        banked.mkdir()

        def put(name, rec, mtime):
            p = banked / name
            p.write_text(json.dumps(rec) + "\n")
            os.utime(p, (mtime, mtime))
            return p

        put("bench_pre_20260101_000000.json",
            {"metric": "bench_error", "value": 0.0, "unit": "",
             "vs_baseline": 0.0, "error": "dead"}, 1000.0)
        put("bench_pre_20260102_000000.json",
            {"metric": "hbm_copy_bandwidth_x", "value": 300.0,
             "unit": "GB/s", "vs_baseline": 0.8, "stale": True}, 2000.0)
        put("bench_pre_20260103_000000.json",
            {"metric": "hbm_copy_bandwidth_x", "value": 335.556,
             "unit": "GB/s", "vs_baseline": 0.9105}, 3000.0)
        put("bench_post_20260104_000000.json",
            {"metric": "hbm_copy_bandwidth_x", "value": 12.3,
             "unit": "GB/s", "vs_baseline": 0.1, "stage": "quick",
             "error": "salvaged after hang"}, 4000.0)
        # clean but OLDER by filename stamp, with the NEWEST mtime: a git
        # checkout resets mtimes, so ordering must follow the filename
        put("bench_post_20260102_120000.json",
            {"metric": "hbm_copy_bandwidth_x", "value": 111.0,
             "unit": "GB/s", "vs_baseline": 0.3}, 99999.0)

        line = bench.banked_fallback("preflight failed", str(tmp_path))
        rec = json.loads(line)
        assert rec["value"] == 335.556  # clean beats newer-but-salvaged,
        # and filename stamp (not mtime) orders the clean tier
        assert rec["stale"] is True
        assert rec["error"] == "preflight failed"
        assert rec["captured_at"].startswith("2026-01-03")
        assert "capture_commit" in rec
        assert rec["metric"] == "hbm_copy_bandwidth_x"

        # two clean records with the SAME capture stamp must not crash
        # max() by falling through to dict comparison
        put("bench_pre_20260103_000000_b.json",  # no parsable stamp ->
            {"metric": "hbm_copy_bandwidth_x", "value": 1.0,  # mtime tier
             "unit": "GB/s", "vs_baseline": 0.1}, 3000.0)
        dup = banked / "dup"
        dup.mkdir()
        (dup / "bench_pre_20260103_000000.json").write_text(
            json.dumps({"metric": "hbm_copy_bandwidth_x", "value": 222.0,
                        "unit": "GB/s", "vs_baseline": 0.6}) + "\n")
        rec = json.loads(bench.banked_fallback("m", str(tmp_path)))
        assert rec["value"] in (335.556, 222.0)  # tie resolved, no crash

        # nothing banked -> None (caller falls back to the error line)
        empty = tmp_path / "empty"
        empty.mkdir()
        assert bench.banked_fallback("msg", str(empty)) is None

    def test_bench_preflight_failure_surfaces_banked_result(self, tmp_path):
        # VERDICT r4 next #2: dead preflight + a banked in-window result
        # must emit the banked NUMBER with stale provenance in the driver
        # schema — never an empty bench_error record.
        import os

        banked = tmp_path / "r5live"
        banked.mkdir()
        (banked / "bench_pre_20260731_034644.json").write_text(
            json.dumps({"metric": "hbm_copy_bandwidth_TPU_v5_lite",
                        "value": 335.556, "unit": "GB/s",
                        "vs_baseline": 0.9105}) + "\n"
        )
        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env["JAX_PLATFORMS"] = "no_such_platform"  # preflight child dies
        env["TPU_PATTERNS_BENCH_PREFLIGHT"] = "20"
        env["TPU_PATTERNS_BENCH_TIMEOUT"] = "900"
        env["TPU_PATTERNS_BENCH_BANKED"] = str(tmp_path)
        proc = subprocess.run(
            [sys.executable, str(ROOT / "bench.py")],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
            cwd=ROOT,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, proc.stdout
        rec = json.loads(lines[0])
        assert rec["metric"] == "hbm_copy_bandwidth_TPU_v5_lite"
        assert rec["value"] == 335.556
        assert rec["vs_baseline"] == 0.9105
        assert rec["stale"] is True  # never presented as live
        assert "preflight" in rec["error"]
        assert rec["capture_file"].endswith(
            "bench_pre_20260731_034644.json"
        )
