"""Long-context layer: ring attention and Ulysses vs ground truth.

Validation philosophy per SURVEY.md §4: every distributed variant must
reproduce the library/single-device result exactly (the allreduce miniapp's
ring-vs-MPI_Allreduce check, applied to attention).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_patterns.longctx import attention as att
from tpu_patterns.longctx.ring_attention import (
    ring_attention as ring_attention_fn,
    run_sharded as ring_run_sharded,
)
from tpu_patterns.longctx.ulysses import run_sharded as ulysses_run_sharded

SP = 8
L, H, D = 64, 8, 16  # global seq, heads, head_dim


def _qkv(seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (L, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.fixture(scope="module")
def qkv():
    return _qkv()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(mesh1d, qkv, causal):
    q, k, v = qkv
    want = att.attention_reference(q, k, v, causal=causal)
    got = ring_run_sharded(q, k, v, mesh1d, "x", causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(mesh1d, qkv, causal):
    q, k, v = qkv
    want = att.attention_reference(q, k, v, causal=causal)
    got = ulysses_run_sharded(q, k, v, mesh1d, "x", causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_strategies_agree(mesh1d, qkv):
    q, k, v = qkv
    a = ring_run_sharded(q, k, v, mesh1d, "x", causal=True)
    b = ulysses_run_sharded(q, k, v, mesh1d, "x", causal=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_block_monoid_associative():
    """combine_blocks must be order-insensitive up to float error — the
    property that lets the ring accumulate blocks in rank order."""
    q, k, v = _qkv(1)
    blocks = [
        att.block_attention(q[:16], k[i * 16 : (i + 1) * 16], v[i * 16 : (i + 1) * 16])
        for i in range(4)
    ]
    left = att.empty_state(q[:16])
    for b in blocks:
        left = att.combine_blocks(left, b)
    right = att.combine_blocks(
        att.combine_blocks(blocks[0], blocks[1]),
        att.combine_blocks(blocks[2], blocks[3]),
    )
    np.testing.assert_allclose(
        np.asarray(att.finalize(left)), np.asarray(att.finalize(right)), atol=2e-5
    )


def test_fully_masked_rows_are_zero():
    """A block whose mask kills every key must contribute nothing (the
    NEG_INF guard in block_attention)."""
    q, k, v = _qkv(2)
    mask = jnp.zeros((16, 16), bool)
    o, m, l = att.block_attention(q[:16], k[:16], v[:16], mask=mask)
    assert float(jnp.max(jnp.abs(o))) == 0.0
    assert float(jnp.max(l)) == 0.0
    out = att.finalize(att.combine_blocks(att.empty_state(q[:16]), (o, m, l)))
    assert np.isfinite(np.asarray(out)).all()


def test_fp16_fully_masked_stays_finite():
    """neg_inf() must clamp per-dtype: -1e30 overflows fp16 to -inf and
    would NaN the fully-masked guard."""
    q, k, v = (a.astype(jnp.float16) for a in _qkv(4))
    mask = jnp.zeros((16, 16), bool)
    o, m, l = att.block_attention(q[:16], k[:16], v[:16], mask=mask)
    out = att.finalize(att.combine_blocks(att.empty_state(q[:16]), (o, m, l)))
    assert np.isfinite(np.asarray(out)).all()


def test_scale_plumbs_through_launcher(mesh1d, qkv):
    q, k, v = qkv
    want = att.attention_reference(q, k, v, scale=0.01)
    got = ring_run_sharded(q, k, v, mesh1d, "x", scale=0.01)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    """The fused Mosaic kernel (interpret mode here, Mosaic on TPU) must
    reproduce the XLA reference blockwise."""
    from tpu_patterns.longctx.flash import flash_attention

    q, k, v = _qkv(5)
    want = att.attention_reference(q, k, v, causal=causal)
    got = flash_attention(
        q, k, v, causal=causal, block_q=16, block_k=16, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_flash_diff_gradients_match_reference():
    """custom_vjp flash: forward is the kernel, backward must equal the
    XLA reference gradients."""
    from tpu_patterns.longctx.flash import flash_attention_diff

    q, k, v = _qkv(7)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention_diff(
                q, k, v, True, None, 16, 16, True
            ).astype(jnp.float32) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(
            att.attention_reference(q, k, v, causal=True).astype(jnp.float32)
            ** 2
        )

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_rejects_indivisible_blocks():
    from tpu_patterns.longctx.flash import flash_attention

    q, k, v = _qkv(6)
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, k, v, block_q=48, block_k=48, interpret=True)


def test_flash_strategy_single_device():
    """The pattern runner's flash strategy on a 1-device mesh."""
    from jax.sharding import Mesh

    from tpu_patterns.core.results import Verdict
    from tpu_patterns.longctx.pattern import LongCtxConfig, run_longctx

    mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
    cfg = LongCtxConfig(
        seq=64, heads=8, head_dim=16, reps=2, warmup=1, strategies=("flash",)
    )
    recs = run_longctx(mesh, cfg)
    assert recs[0].mode == "flash"
    assert recs[0].verdict is Verdict.SUCCESS


@pytest.mark.parametrize("bq,bk", [(16, 32), (32, 16)])
def test_flash_asymmetric_blocks_match_reference(bq, bk):
    """The block-aspect lever (measured.flash_blocks cells): asymmetric
    (block_q, block_k) tiles must be exactly as correct as the square
    default, forward and backward."""
    from tpu_patterns.longctx.flash import flash_attention_diff

    q, k, v = _qkv(11)
    want = att.attention_reference(q, k, v, causal=True)
    got = flash_attention_diff(q, k, v, True, None, bq, bk, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    g_flash = jax.grad(
        lambda a, b, c: jnp.sum(
            flash_attention_diff(a, b, c, True, None, bq, bk, True).astype(
                jnp.float32
            )
            ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda a, b, c: jnp.sum(
            att.attention_reference(a, b, c, causal=True).astype(jnp.float32)
            ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_strategy_block_shape_config():
    """LongCtxConfig.block_q/block_k thread through the pattern runner to
    the kernel (the CLI surface the measured block cells drive)."""
    from jax.sharding import Mesh

    from tpu_patterns.core.results import Verdict
    from tpu_patterns.longctx.pattern import LongCtxConfig, run_longctx

    mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
    cfg = LongCtxConfig(
        seq=64, heads=8, head_dim=16, reps=2, warmup=1,
        strategies=("flash",), block_q=16, block_k=32,
    )
    recs = run_longctx(mesh, cfg)
    assert recs[0].verdict is Verdict.SUCCESS


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_pallas_block(mesh1d, qkv, causal):
    """The fused flash_block inside the ring (interpret mode on CPU) must
    match the single-device reference — same check as the XLA block."""
    import functools

    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = qkv
    spec = P("x", None, None)
    fn = jax.jit(
        jax.shard_map(
            functools.partial(
                ring_attention_fn,
                axis_name="x",
                axis_size=SP,
                causal=causal,
                block_impl="pallas",
                interpret=True,
            ),
            mesh=mesh1d,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            # interpret-mode pallas discharge can't track varying axes
            # (same limitation as comm.onesided.ring_put)
            check_vma=False,
        )
    )
    sharding = NamedSharding(mesh1d, spec)
    args = tuple(jax.device_put(np.asarray(a), sharding) for a in (q, k, v))
    want = att.attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(fn(*args)), np.asarray(want), atol=2e-5
    )


@pytest.mark.parametrize("block_impl", ["xla", "pallas"])
def test_ring_attention_striped_layout(mesh1d, qkv, block_impl):
    """Striped layout: shard r holds tokens r::sp.  Causal ring attention
    over striped shards must reproduce the reference after unstriping —
    this is the load-balanced causal schedule."""
    import functools

    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_patterns.longctx.attention import stripe as _stripe
    from tpu_patterns.longctx.pattern import _unstripe

    q, k, v = qkv
    # stripe: concatenate [x[r::sp] for r] so contiguous shard r == stripe r
    stripe = lambda x: _stripe(np.asarray(x), SP)  # noqa: E731
    unstripe = lambda x: _unstripe(np.asarray(x), SP)  # noqa: E731

    spec = P("x", None, None)
    fn = jax.jit(
        jax.shard_map(
            functools.partial(
                ring_attention_fn,
                axis_name="x",
                axis_size=SP,
                causal=True,
                layout="striped",
                block_impl=block_impl,
                interpret=True,
            ),
            mesh=mesh1d,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=block_impl == "xla",
        )
    )
    sharding = NamedSharding(mesh1d, spec)
    args = tuple(
        jax.device_put(stripe(a), sharding) for a in (q, k, v)
    )
    got = unstripe(np.asarray(fn(*args)))
    want = np.asarray(att.attention_reference(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("q_off,k_off,stride", [(0, 0, 1), (16, 32, 1), (2, 5, 8)])
def test_flash_block_kernels_match_xla_twins(causal, q_off, k_off, stride):
    """The Mosaic block kernels (fwd partial triple + dq/dk/dv backward)
    against their XLA twins at shard offsets/strides — the unit the ring
    composes on hardware (interpret-mode rings swap in the twins, so this
    is where the kernels' offset arithmetic is pinned down)."""
    from tpu_patterns.longctx.flash import (
        _delta,
        _row_stats,
        flash_block,
        flash_block_bwd,
    )
    from tpu_patterns.longctx.ring_attention import (
        _block_bwd_xla,
        _block_fwd_xla,
    )

    q, k, v = _qkv(11)
    o_p, m_p, l_p = flash_block(
        q, k, v, q_off, k_off, causal=causal, block_q=16, block_k=16,
        interpret=True, pos_stride=stride,
    )
    o_x, m_x, l_x = _block_fwd_xla(q, k, v, q_off, k_off, causal, None, stride)
    np.testing.assert_allclose(np.asarray(o_p), np.asarray(o_x), atol=2e-5)
    np.testing.assert_allclose(np.asarray(l_p), np.asarray(l_x), atol=2e-5)

    out, lse = _row_stats(o_x, m_x, l_x)
    g = jax.random.normal(jax.random.key(3), q.shape, jnp.float32)
    delta = _delta(g, out)
    grads_p = flash_block_bwd(
        q, k, v, g, lse, delta, q_off, k_off, causal=causal,
        block_q=16, block_k=16, interpret=True, pos_stride=stride,
    )
    grads_x = _block_bwd_xla(
        q, k, v, g, lse, delta, q_off, k_off, causal, None, stride
    )
    for name, a, b in zip("dq dk dv".split(), grads_p, grads_x):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, err_msg=name
        )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("layout", ["contiguous", "striped"])
def test_ring_flash_gradients_match_reference(mesh1d, qkv, causal, layout):
    """The fused ring backward (second ring pass carrying dK/dV with their
    shards) must equal the single-device reference gradients in every
    layout — the long-context analogue of the allreduce two-paths check."""
    import functools

    from jax.sharding import PartitionSpec as P

    from tpu_patterns.longctx.attention import stripe as _stripe
    from tpu_patterns.longctx.pattern import _unstripe

    q, k, v = qkv
    stripe = lambda x: jnp.asarray(_stripe(np.asarray(x), SP))  # noqa: E731
    unstripe = lambda x: _unstripe(np.asarray(x), SP)  # noqa: E731

    def loss(q, k, v):
        fn = jax.shard_map(
            functools.partial(
                ring_attention_fn,
                axis_name="x",
                axis_size=SP,
                causal=causal,
                block_impl="pallas",
                interpret=True,
                layout=layout,
            ),
            mesh=mesh1d,
            in_specs=(P("x"),) * 3,
            out_specs=P("x"),
            check_vma=False,
        )
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    args = (
        tuple(stripe(a) for a in (q, k, v)) if layout == "striped" else (q, k, v)
    )
    grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(*args)
    ref = jax.grad(
        lambda q, k, v: jnp.sum(
            att.attention_reference(q, k, v, causal=causal).astype(jnp.float32)
            ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for got, want in zip(grads, ref):
        got = np.asarray(got)
        if layout == "striped":
            got = unstripe(got)
        np.testing.assert_allclose(got, np.asarray(want), atol=2e-4)


def test_pattern_runner_verdicts(mesh1d):
    """The measured pattern: both strategies SUCCESS with positive
    throughput and the reference-match gate enforced."""
    from tpu_patterns.core.results import Verdict
    from tpu_patterns.longctx.pattern import LongCtxConfig, run_longctx

    cfg = LongCtxConfig(seq=64, heads=8, head_dim=16, reps=2, warmup=1)
    recs = run_longctx(mesh1d, cfg)
    assert [r.mode for r in recs] == ["ring", "ulysses", "agreement"]
    for r in recs:
        assert r.verdict is Verdict.SUCCESS
    assert all(r.metrics["tflops"] > 0 for r in recs[:2])
    assert all(r.metrics["max_abs_err"] < 1e-4 for r in recs[:2])
    assert recs[2].metrics["cross_max_err"] < 1e-4


class TestUlyssesPallas:
    """Ulysses with the fused kernel as the per-rank hot op: after the
    all-to-all each rank holds the full sequence (the single-shard flash
    case), so the Mosaic fwd+bwd — and the compact causal grid — apply."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_xla_ulysses_and_reference(self, mesh1d, causal):
        from tpu_patterns.core.results import Verdict
        from tpu_patterns.longctx.pattern import LongCtxConfig, run_longctx

        cfg = LongCtxConfig(
            seq=128, heads=8, head_dim=16, reps=2, warmup=1,
            causal=causal, block_q=16, block_k=16,
            strategies=("ulysses", "ulysses_pallas"),
        )
        recs = run_longctx(mesh1d, cfg)
        assert [r.mode for r in recs] == [
            "ulysses", "ulysses_pallas", "agreement"
        ]
        for r in recs:
            assert r.verdict is Verdict.SUCCESS, (r.mode, r.notes)

    def test_grad_runner(self, mesh1d):
        from tpu_patterns.core.results import ResultWriter, Verdict
        from tpu_patterns.longctx.pattern import (
            LongCtxConfig,
            run_longctx_grad,
        )

        cfg = LongCtxConfig(
            seq=128, heads=8, head_dim=16, reps=2, warmup=1,
            block_q=16, block_k=16, strategies=("ulysses_pallas",),
        )
        recs = run_longctx_grad(mesh1d, cfg, ResultWriter())
        assert recs[0].mode == "ulysses_pallas_grad"
        assert recs[0].verdict is Verdict.SUCCESS, recs[0].notes

    def test_grad_runner_compact_grid(self, mesh1d):
        from tpu_patterns.core.results import ResultWriter, Verdict
        from tpu_patterns.longctx.pattern import (
            LongCtxConfig,
            run_longctx_grad,
        )

        cfg = LongCtxConfig(
            seq=128, heads=8, head_dim=16, reps=2, warmup=1,
            block_q=16, block_k=16, strategies=("ulysses_pallas",),
            causal_grid="compact",
        )
        recs = run_longctx_grad(mesh1d, cfg, ResultWriter())
        assert recs[0].verdict is Verdict.SUCCESS, recs[0].notes


def test_cli_longctx(tmp_path):
    import json

    from tpu_patterns.cli import main

    jl = tmp_path / "lc.jsonl"
    rc = main(
        [
            "--jsonl", str(jl), "longctx", "--devices", "8",
            "--seq", "64", "--heads", "8", "--head_dim", "16",
            "--reps", "2", "--warmup", "1",
        ]
    )
    assert rc == 0
    with open(jl) as f:
        recs = [json.loads(ln) for ln in f]
    assert {r["mode"] for r in recs} == {"ring", "ulysses", "agreement"}
    assert all(r["verdict"] == "SUCCESS" for r in recs)


def test_ring_attention_grad_finite(mesh1d):
    """The ring is differentiable end-to-end (what a training step needs);
    use mean-square loss over the sharded output."""
    import functools

    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = _qkv(3)
    spec = P("x", None, None)
    sharding = NamedSharding(mesh1d, spec)
    args = tuple(jax.device_put(np.asarray(a), sharding) for a in (q, k, v))

    def loss(q, k, v):
        f = jax.shard_map(
            functools.partial(
                ring_attention_fn,
                axis_name="x",
                axis_size=SP,
                causal=True,
            ),
            mesh=mesh1d,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
        return jnp.mean(f(q, k, v) ** 2)

    grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(*args)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.max(jnp.abs(g))) > 0.0


def test_grad_pattern_runner_ulysses(mesh1d):
    """Ulysses' backward (the all_to_all transpose, free from autodiff)
    passes the measured fwd+bwd pattern's dq/dk/dv gates."""
    from tpu_patterns.core.results import ResultWriter, Verdict
    from tpu_patterns.longctx.pattern import LongCtxConfig, run_longctx_grad

    cfg = LongCtxConfig(
        seq=64, heads=8, head_dim=16, reps=2, warmup=1,
        strategies=("ulysses",),
    )
    recs = run_longctx_grad(mesh1d, cfg, ResultWriter())
    assert [r.mode for r in recs] == ["ulysses_grad"]
    assert recs[0].verdict is Verdict.SUCCESS, recs[0].notes


def test_grad_records_carry_model_and_hardware_rates(mesh1d):
    """Every grad Record reports BOTH accounting bases (VERDICT r2 weak
    #1): `tflops` under the cross-implementation model count (3.5x fwd)
    and `tflops_hw` under the per-strategy silicon count, with the ratio
    pinned to the documented multipliers."""
    from tpu_patterns.core.results import ResultWriter
    from tpu_patterns.longctx.pattern import (
        GRAD_FLOP_MULT,
        GRAD_HW_FLOP_MULT,
        GRAD_HW_FLOP_MULT_DEFAULT,
        LongCtxConfig,
        run_longctx_grad,
    )

    cfg = LongCtxConfig(
        seq=64, heads=8, head_dim=16, reps=2, warmup=1,
        strategies=("ring",),
    )
    rec = run_longctx_grad(mesh1d, cfg, ResultWriter())[0]
    m = rec.metrics
    assert m["hw_flop_mult"] == GRAD_HW_FLOP_MULT_DEFAULT
    assert m["tflops_hw"] == pytest.approx(
        m["tflops"] * m["hw_flop_mult"] / GRAD_FLOP_MULT
    )
    assert GRAD_HW_FLOP_MULT["flash"] == 4.5  # 2 fwd + 7 executed bwd


def test_grad_gate_metrics_deterministic_across_runs(mesh1d):
    """Two consecutive grad pattern runs must agree EXACTLY on the data
    metrics (violation/rms): the committed FAILURE->retry->SUCCESS
    pattern (VERDICT r2 weak #2) must never come from the measurement
    pipeline itself — seeds are fixed, references recomputed, and any
    run-to-run drift here would be an RNG or state leak."""
    from tpu_patterns.core.results import ResultWriter
    from tpu_patterns.longctx.pattern import LongCtxConfig, run_longctx_grad

    cfg = LongCtxConfig(
        seq=64, heads=8, head_dim=16, reps=2, warmup=1,
        strategies=("ring",),
    )
    a = run_longctx_grad(mesh1d, cfg, ResultWriter())[0]
    b = run_longctx_grad(mesh1d, cfg, ResultWriter())[0]
    assert a.verdict == b.verdict
    assert a.metrics["gate_violation"] == b.metrics["gate_violation"]
    assert a.metrics["rms_err"] == b.metrics["rms_err"]


def test_grad_chain_keeps_all_three_gradients_live():
    """The timed chain must depend on dq, dk AND dv — feeding back only dq
    lets XLA dead-code-eliminate the dk/dv kernel from the measured
    program (the committed >chip-peak record's cause).  Structural check:
    chaining a probe counting cotangent uses sees all three."""
    import jax
    import jax.numpy as jnp

    from tpu_patterns.core import timing

    calls = []

    @jax.custom_vjp
    def probe(q, k, v):
        return q

    def probe_fwd(q, k, v):
        return q, (k, v)

    def probe_bwd(res, g):
        calls.append("bwd")
        k, v = res
        return g, k * 0 + 1.0, v * 0 + 2.0

    probe.defvjp(probe_fwd, probe_bwd)

    def grad_probe(x, b, c):
        return jax.grad(
            lambda a, b, c: jnp.sum(probe(a, b, c)), argnums=(0, 1, 2)
        )(x, b, c)

    # mirror pattern.py's _step: the carry folds in all three grads
    def step(x, b, c):
        dq, dk, dv = grad_probe(x, b, c)
        return dq + dk + dv

    x = jnp.ones((4, 4))
    out = jax.jit(
        lambda a, b, c, n: timing.unrolled_chain(
            lambda y: step(y, b, c), a, n
        )
    )(x, x, x, jnp.int32(1))
    # Each step returns dq + dk + dv = 1 + 1 + 2 (dq = ones: grad of sum);
    # a dq-only chain would end at 1.0 — the 4.0 proves dk and dv stayed
    # live through the fori_loop body.
    assert float(out[0, 0]) == pytest.approx(4.0)


@pytest.mark.parametrize("name", ["ring_pallas", "ring_striped"])
def test_pattern_runner_ring_variants(mesh1d, name):
    """The fused-kernel and striped-layout ring variants run through the
    measured pattern with the same reference-match gate."""
    from tpu_patterns.core.results import Verdict
    from tpu_patterns.longctx.pattern import LongCtxConfig, run_longctx

    cfg = LongCtxConfig(
        seq=64, heads=8, head_dim=16, reps=2, warmup=1,
        strategies=("ring", name),
    )
    recs = run_longctx(mesh1d, cfg)
    assert [r.mode for r in recs] == ["ring", name, "agreement"]
    for r in recs:
        assert r.verdict is Verdict.SUCCESS, (r.mode, r.notes)


class TestCompactCausalGrid:
    """grid_mode="compact": the scalar-prefetch pair grid must be exactly
    as correct as the dense grid it outruns (masked tiles' k/v DMAs
    never issue on it)."""

    def test_pair_table_shape_and_flags(self):
        from tpu_patterns.longctx.flash import _causal_pair_table

        tab = _causal_pair_table(4, 4, 16, 16)
        # 1+2+3+4 live tiles of the 16-tile rectangle
        assert tab.shape == (4, 10)
        iq, ik, first, last = tab
        # every pair is causally live, rows iq-major/ik-ascending
        assert all(k <= q for q, k in zip(iq, ik))
        assert list(iq) == sorted(iq)
        # one first and one last per q row
        assert sum(first) == 4 and sum(last) == 4

    def test_pair_table_mixed_blocks(self):
        from tpu_patterns.longctx.flash import _causal_pair_table

        # bq=32, bk=16, 64x64: q row 0 covers k blocks 0..1, row 1 0..3
        tab = _causal_pair_table(2, 4, 32, 16)
        assert tab.shape == (4, 6)
        assert list(tab[1]) == [0, 1, 0, 1, 2, 3]

    @pytest.mark.parametrize("bq,bk", [(16, 16), (32, 16), (16, 32)])
    def test_matches_reference(self, bq, bk):
        from tpu_patterns.longctx.flash import flash_attention

        q, k, v = _qkv(13)
        want = att.attention_reference(q, k, v, causal=True)
        got = flash_attention(
            q, k, v, causal=True, block_q=bq, block_k=bk,
            interpret=True, grid_mode="compact",
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5
        )

    def test_noncausal_falls_back_to_dense(self):
        from tpu_patterns.longctx.flash import flash_attention

        q, k, v = _qkv(14)
        want = att.attention_reference(q, k, v, causal=False)
        got = flash_attention(
            q, k, v, causal=False, block_q=16, block_k=16,
            interpret=True, grid_mode="compact",
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5
        )

    def test_rejects_unknown_grid_mode(self):
        from tpu_patterns.longctx.flash import flash_attention

        q, k, v = _qkv(15)
        with pytest.raises(ValueError, match="grid_mode"):
            flash_attention(q, k, v, grid_mode="sparse", interpret=True)

    def test_pattern_runner_compact_strategy(self):
        from jax.sharding import Mesh

        from tpu_patterns.core.results import Verdict
        from tpu_patterns.longctx.pattern import LongCtxConfig, run_longctx

        mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
        cfg = LongCtxConfig(
            seq=64, heads=8, head_dim=16, reps=2, warmup=1,
            strategies=("flash",), block_q=16, block_k=16,
            causal_grid="compact",
        )
        recs = run_longctx(mesh, cfg)
        assert recs[0].verdict is Verdict.SUCCESS


def test_longctx_cli_threads_kernel_flags():
    """The CLI must deliver --block_q/--block_k to the kernel: an
    indivisible block size can only raise if the flag actually arrived
    (this exact wiring was silently dropped once)."""
    from tpu_patterns.cli import main

    with pytest.raises(ValueError, match="divide"):
        main(
            ["longctx", "--devices", "1", "--strategy", "flash",
             "--seq", "64", "--heads", "8", "--head_dim", "16",
             "--reps", "2", "--warmup", "1",
             "--block_q", "48", "--block_k", "48"]
        )


class TestCompactCausalGridBackward:
    """grid_mode="compact" on the grad path: the live-tile tables reach
    the stats-emitting forward AND the dq/dk/dv kernels, with the dense
    nest's accumulation order — gradients must be bit-identical to the
    dense grid's."""

    def test_kmajor_pair_table_shape_and_flags(self):
        from tpu_patterns.longctx.flash import _causal_pair_table_kmajor

        tab = _causal_pair_table_kmajor(4, 4, 16, 16)
        # k row jk is live for iq >= jk: 4+3+2+1 tiles
        assert tab.shape == (4, 10)
        jk, iq, first, last = tab
        assert all(q >= k for k, q in zip(jk, iq))
        assert list(jk) == sorted(jk)  # jk-major
        assert sum(first) == 4 and sum(last) == 4

    def test_kmajor_pair_table_mixed_blocks(self):
        from tpu_patterns.longctx.flash import _causal_pair_table_kmajor

        # bq=32, bk=16, 64x64: k blocks 0..1 live for both q rows,
        # k blocks 2..3 only for q row 1
        tab = _causal_pair_table_kmajor(2, 4, 32, 16)
        assert tab.shape == (4, 6)
        assert list(tab[0]) == [0, 0, 1, 1, 2, 3]
        assert list(tab[1]) == [0, 1, 0, 1, 1, 1]

    @pytest.mark.parametrize("nq,nk,bq,bk", [
        (1, 1, 16, 16), (1, 4, 64, 16), (4, 1, 16, 64), (8, 8, 16, 16),
        (3, 5, 40, 24), (5, 3, 24, 40), (2, 8, 128, 32), (8, 2, 32, 128),
        (7, 7, 16, 16), (1, 8, 256, 32),
    ])
    def test_pair_tables_exactly_cover_live_tiles(self, nq, nk, bq, bk):
        # Both tables must enumerate EXACTLY the dense grids' live tiles
        # (the pl.when predicate), each once, with one first and one
        # last flag per row — for any block aspect, including ragged
        # ones.  The compact grid's correctness is this property.
        from tpu_patterns.longctx.flash import (
            _causal_pair_table,
            _causal_pair_table_kmajor,
        )

        live = {
            (iq, ik)
            for iq in range(nq)
            for ik in range(nk)
            if (iq + 1) * bq - 1 >= ik * bk  # the dense kernels' predicate
        }
        tq = _causal_pair_table(nq, nk, bq, bk)
        tk = _causal_pair_table_kmajor(nq, nk, bq, bk)
        assert {(q, k) for q, k in zip(tq[0], tq[1])} == live
        assert tq.shape[1] == len(live)  # each exactly once
        assert {(q, k) for k, q in zip(tk[0], tk[1])} == live
        assert tk.shape[1] == len(live)
        # per-row flags: exactly one first and one last per live q row
        # (iq-major) / per live k row (jk-major), and the flagged pairs
        # bound each row's ascending run
        for tab in (tq, tk):  # both store the major index in row 0
            rows = {}
            for j in range(tab.shape[1]):
                rows.setdefault(int(tab[0, j]), []).append(j)
            for _, idxs in rows.items():
                assert idxs == list(range(idxs[0], idxs[-1] + 1))  # contiguous
                assert [int(tab[2, j]) for j in idxs].count(1) == 1
                assert int(tab[2, idxs[0]]) == 1
                assert [int(tab[3, j]) for j in idxs].count(1) == 1
                assert int(tab[3, idxs[-1]]) == 1
                # minor index ascends within the row (dense accumulation
                # order — the bit-identity precondition)
                minors = [int(tab[1, j]) for j in idxs]
                assert minors == sorted(minors)

    def test_compact_grads_bit_identical_to_dense(self):
        from tpu_patterns.longctx.flash import flash_attention_diff

        q, k, v = _qkv(21)

        def loss(mode):
            def f(q, k, v):
                out = flash_attention_diff(
                    q, k, v, True, None, 16, 16, True, mode
                )
                return jnp.sum(out * jnp.cos(out))

            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        dense = loss("dense")
        compact = loss("compact")
        for d, c in zip(dense, compact):
            np.testing.assert_array_equal(np.asarray(d), np.asarray(c))

    def test_compact_block_stats_match_dense(self):
        from tpu_patterns.longctx.flash import flash_block

        q, k, v = _qkv(22)
        args = dict(causal=True, block_q=16, block_k=16, interpret=True)
        od, md, ld = flash_block(q, k, v, 0, 0, **args)
        oc, mc, lc = flash_block(q, k, v, 0, 0, grid_mode="compact", **args)
        np.testing.assert_array_equal(np.asarray(od), np.asarray(oc))
        np.testing.assert_array_equal(np.asarray(md), np.asarray(mc))
        np.testing.assert_array_equal(np.asarray(ld), np.asarray(lc))

    def test_compact_bwd_rejects_traced_offsets(self):
        from tpu_patterns.longctx.flash import flash_block_bwd

        q, k, v = _qkv(23)
        lse = jnp.zeros((H, L), jnp.float32)
        delta = jnp.zeros((H, L), jnp.float32)
        with pytest.raises(ValueError, match="static zero shard offsets"):
            flash_block_bwd(
                q, k, v, q, lse, delta, q_off=jnp.int32(0), causal=True,
                grid_mode="compact", interpret=True,
            )

    def test_runner_refuses_noncausal_compact(self):
        # the kernels fall back to dense when non-causal; a compact-
        # labeled Record must never time that fallback
        from jax.sharding import Mesh

        from tpu_patterns.core.results import ResultWriter
        from tpu_patterns.longctx.pattern import (
            LongCtxConfig,
            run_longctx_grad,
        )

        mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
        cfg = LongCtxConfig(
            seq=64, heads=8, head_dim=16, reps=2, warmup=1, causal=False,
            strategies=("flash",), causal_grid="compact",
        )
        with pytest.raises(ValueError, match="requires --causal true"):
            run_longctx_grad(mesh, cfg, ResultWriter())

    def test_flagship_refuses_noncausal_compact(self):
        from jax.sharding import Mesh

        from tpu_patterns.core.results import ResultWriter
        from tpu_patterns.models.transformer import (
            FlagshipConfig,
            run_flagship,
        )

        mesh = Mesh(
            np.array(jax.devices()[:1]).reshape(1, 1, 1), ("dp", "sp", "tp")
        )
        cfg = FlagshipConfig(
            embed=64, heads=4, head_dim=16, seq=128, batch=2, depth=1,
            causal=False, attn="pallas", attn_grid="compact", reps=1,
            warmup=0,
        )
        with pytest.raises(ValueError, match="requires --causal true"):
            run_flagship(mesh, cfg, ResultWriter())

    def test_flagship_refuses_compact_off_fused_path(self):
        # attn='xla' and sp>1 (the ring) would silently ignore the flag
        # — a compact-labeled Record must never time those paths
        import dataclasses

        from jax.sharding import Mesh

        from tpu_patterns.core.results import ResultWriter
        from tpu_patterns.models.transformer import (
            FlagshipConfig,
            run_flagship,
        )

        cfg = FlagshipConfig(
            embed=64, heads=4, head_dim=16, seq=128, batch=2, depth=1,
            causal=True, attn="pallas", attn_grid="compact", reps=1,
            warmup=0,
        )
        mesh1 = Mesh(
            np.array(jax.devices()[:1]).reshape(1, 1, 1), ("dp", "sp", "tp")
        )
        with pytest.raises(ValueError, match="fused pallas"):
            run_flagship(
                mesh1, dataclasses.replace(cfg, attn="xla"), ResultWriter()
            )
        mesh_sp2 = Mesh(
            np.array(jax.devices()[:2]).reshape(1, 2, 1), ("dp", "sp", "tp")
        )
        with pytest.raises(ValueError, match="single-chip"):
            run_flagship(mesh_sp2, cfg, ResultWriter())

    def test_width_needed_is_width_independent(self):
        # the refit quantity must not move with the promoted width, even
        # where cfg.tol floors the atol (there the violation RATIO is
        # width-independent and violation*width would ratchet)
        import dataclasses as dc

        from tpu_patterns.longctx.pattern import _Gates

        ref = np.zeros((4,), np.float32)
        ref[0] = 1.0
        diff = np.array([0.0, 3e-4, 0.0, 0.0], np.float32)
        g8 = _Gates(rtol=1e-6, atol=1e-4, rms=1.0, unit_atol=5e-5)
        g4 = dc.replace(g8, atol=2e-4)  # a different promoted width
        assert g8.width_needed(diff, ref) == pytest.approx(6.0)
        assert g4.width_needed(diff, ref) == pytest.approx(6.0)
        # violation ratios DO differ across the widths — the old
        # violation*width refit would have disagreed with itself
        assert g8.check_elem(diff, ref) != g4.check_elem(diff, ref)
        # forward gates carry no unit: quantity not claimed
        assert _Gates(rtol=1e-6, atol=1e-4, rms=1.0).width_needed(
            diff, ref
        ) is None

    def test_pattern_grad_runner_compact(self):
        from jax.sharding import Mesh

        from tpu_patterns.core.results import ResultWriter, Verdict
        from tpu_patterns.longctx.pattern import (
            LongCtxConfig,
            run_longctx_grad,
        )

        mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
        cfg = LongCtxConfig(
            seq=64, heads=8, head_dim=16, reps=2, warmup=1,
            strategies=("flash",), block_q=16, block_k=16,
            causal_grid="compact",
        )
        recs = run_longctx_grad(mesh, cfg, ResultWriter())
        assert recs[0].verdict is Verdict.SUCCESS, recs[0].notes


class TestSharedTuning:
    """The block-size auto-tuner moved to longctx/tuning.py (shared
    with serve/paged_kernel.py): flash's re-exports stay the same
    objects and the tuned choices are pinned — an extraction, not a
    behavior change."""

    def test_flash_reexports_are_the_tuning_objects(self):
        from tpu_patterns.longctx import flash, tuning

        for name in ("LANES", "NEG_INF", "VMEM_BUDGET", "DEFAULT_BLOCK_Q",
                     "DEFAULT_BLOCK_K", "FLASH_TUNED_PATH", "_auto_block",
                     "_vmem_estimate", "load_tuned_blocks"):
            assert getattr(flash, name) is getattr(tuning, name), name

    def test_auto_block_choices_pinned(self):
        from tpu_patterns.longctx.tuning import _auto_block

        # the documented v5e ladder: the (1024, 1024) d=128 bf16 forward
        # fits (13.1 MB < 14 MB); a 2048-square request shrinks back to
        # it; tiny shapes pass through unclamped
        assert _auto_block(4096, 4096, 128, 2, 2, 1024, 1024) == (
            1024, 1024,
        )
        assert _auto_block(4096, 4096, 128, 2, 2, 2048, 2048) == (
            1024, 1024,
        )
        assert _auto_block(8, 8, 64, 4, 2, 1024, 1024) == (8, 8)
        # the backward's 4 score tiles tighten the ladder one rung
        # (the q side halves first — bq >= bk breaks toward bq)
        assert _auto_block(4096, 4096, 128, 2, 4, 1024, 1024) == (
            512, 1024,
        )
        # blocks never shrink below the 128-lane floor when the problem
        # is at least that large
        bq, bk = _auto_block(4096, 4096, 512, 4, 4, 2048, 2048)
        assert bq >= 128 and bk >= 128

    def test_vmem_estimate_monotone_and_calibrated(self):
        from tpu_patterns.longctx.tuning import (
            VMEM_BUDGET,
            _vmem_estimate,
        )

        # the two calibration anchors from the hardware ladder
        assert _vmem_estimate(1024, 1024, 128, 2, 2) < VMEM_BUDGET
        assert _vmem_estimate(2048, 2048, 128, 2, 2) > VMEM_BUDGET
        # monotone in every argument the ladder moves
        base = _vmem_estimate(512, 512, 64, 2, 2)
        assert _vmem_estimate(1024, 512, 64, 2, 2) > base
        assert _vmem_estimate(512, 1024, 64, 2, 2) > base
        assert _vmem_estimate(512, 512, 128, 2, 2) > base
        assert _vmem_estimate(512, 512, 64, 4, 2) > base
        assert _vmem_estimate(512, 512, 64, 2, 4) > base
