"""Tests for hierarchical (ICI-inner, DCN-outer) collectives."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_patterns.comm import (
    HierConfig,
    hierarchical_allreduce,
    run_hierarchical,
    traffic_model,
)
from tpu_patterns.core.results import Verdict


def _mesh2d(devices, dcn, ici):
    return Mesh(np.array(devices[: dcn * ici]).reshape(dcn, ici), ("dcn", "ici"))


class TestHierarchicalAllreduce:
    @pytest.mark.parametrize("dcn,ici", [(2, 4), (4, 2)])
    def test_matches_global_sum(self, devices, dcn, ici):
        m = _mesh2d(devices, dcn, ici)
        n = 64
        x = jnp.arange(dcn * ici * n, dtype=jnp.float32).reshape(dcn, ici, n)
        xs = jax.device_put(x, NamedSharding(m, P("dcn", "ici", None)))

        fn = jax.jit(
            jax.shard_map(
                lambda a: hierarchical_allreduce(a[0, 0], "ici", ici, "dcn")[
                    None, None
                ],
                mesh=m,
                in_specs=P("dcn", "ici", None),
                out_specs=P("dcn", "ici", None),
            )
        )
        out = np.asarray(fn(xs))
        want = np.asarray(x).sum(axis=(0, 1))
        for i in range(dcn):
            for j in range(ici):
                np.testing.assert_allclose(out[i, j], want, rtol=1e-6)

    def test_indivisible_leading_dim_raises(self, devices):
        m = _mesh2d(devices, 2, 4)
        x = jnp.ones((2, 4, 10), jnp.float32)  # 10 % 4 != 0
        xs = jax.device_put(x, NamedSharding(m, P("dcn", "ici", None)))
        fn = jax.jit(
            jax.shard_map(
                lambda a: hierarchical_allreduce(a[0, 0], "ici", 4, "dcn")[
                    None, None
                ],
                mesh=m,
                in_specs=P("dcn", "ici", None),
                out_specs=P("dcn", "ici", None),
            )
        )
        with pytest.raises(ValueError, match="not divisible"):
            fn(xs)


class TestDetectHierarchy:
    class FakeDev:
        def __init__(self, process_index, slice_index=None, platform="cpu"):
            self.process_index = process_index
            self.platform = platform
            if slice_index is not None:
                self.slice_index = slice_index

    def test_tpu_groups_by_slice_index(self):
        from tpu_patterns.comm.hierarchical import detect_hierarchy

        devs = [self.FakeDev(0, s, platform="tpu") for s in (1, 0, 1, 0)]
        n, ordered = detect_hierarchy(devs)
        assert n == 2
        assert [d.slice_index for d in ordered] == [0, 0, 1, 1]

    def test_tpu_single_slice_multihost_is_one_tier(self):
        from tpu_patterns.comm.hierarchical import detect_hierarchy

        # a single-slice multi-host pod has ICI between its hosts: the
        # constant slice_index means ONE tier, never a process split
        devs = [
            self.FakeDev(p, slice_index=0, platform="tpu")
            for p in (0, 0, 1, 1)
        ]
        n, _ = detect_hierarchy(devs)
        assert n == 1

    def test_non_tpu_constant_slice_uses_process(self):
        from tpu_patterns.comm.hierarchical import detect_hierarchy

        # CPU/GPU platforms report a stub slice_index=0 everywhere: the
        # process boundary is the real slow tier there
        devs = [self.FakeDev(p, slice_index=0) for p in (0, 0, 1, 1)]
        n, ordered = detect_hierarchy(devs)
        assert n == 2
        assert [d.process_index for d in ordered] == [0, 0, 1, 1]

    def test_falls_back_to_process_index(self):
        from tpu_patterns.comm.hierarchical import detect_hierarchy

        devs = [self.FakeDev(p) for p in (0, 0, 1, 1, 2, 2)]
        n, ordered = detect_hierarchy(devs)
        assert n == 3
        assert [d.process_index for d in ordered] == [0, 0, 1, 1, 2, 2]

    def test_unequal_groups_raise(self):
        from tpu_patterns.comm.hierarchical import detect_hierarchy

        devs = [self.FakeDev(p) for p in (0, 0, 1)]
        with pytest.raises(ValueError, match="unequal slice sizes"):
            detect_hierarchy(devs)


class TestTrafficModel:
    def test_dcn_reduction_factor(self):
        # the decomposition's point: DCN bytes shrink by the ici factor
        n_bytes = 1 << 20
        m = traffic_model(n_bytes, ici=4, dcn=2)
        flat_dcn_chunk = (2 - 1) / 2 * 2 * n_bytes  # dcn share at full size
        assert m["dcn_bytes_per_device"] == pytest.approx(flat_dcn_chunk / 4)

    def test_single_slice_no_dcn_traffic(self):
        m = traffic_model(1 << 20, ici=8, dcn=1)
        assert m["dcn_bytes_per_device"] == 0.0


class TestRunHierarchical:
    @pytest.mark.parametrize("dtype", ["float32", "int32"])
    def test_both_variants_succeed(self, mesh1d, dtype):
        recs = run_hierarchical(
            mesh1d, HierConfig(count=512, dcn=2, dtype=dtype, reps=2, warmup=1)
        )
        assert [r.mode for r in recs] == ["flat", "hier"]
        for r in recs:
            assert r.verdict is Verdict.SUCCESS, (r.mode, r.notes)
            assert r.metrics["checksum_ok"] == 1.0
            assert r.metrics["time_us"] > 0

    @pytest.mark.parametrize("dtype", ["bfloat16", "int32"])
    def test_amortized_chain_mode(self, mesh1d, monkeypatch, dtype):
        # The TPU-default timing path: the chained fori_loop must keep its
        # varying-manual-axes carry type (psum drops axes, all_gather keeps
        # one) and run in the wire dtype — both broke before being driven.
        monkeypatch.setenv("TPU_PATTERNS_TIMING", "amortized")
        recs = run_hierarchical(
            mesh1d, HierConfig(count=512, dcn=2, dtype=dtype, reps=2, warmup=1)
        )
        for r in recs:
            assert r.verdict is Verdict.SUCCESS, (r.mode, r.notes)

    def test_count_rounds_down_to_ici_multiple(self, mesh1d):
        # count=515 on ici=4 must round to 512, not crash the scatter
        recs = run_hierarchical(
            mesh1d, HierConfig(count=515, dcn=2, reps=1, warmup=0)
        )
        assert all(r.verdict is Verdict.SUCCESS for r in recs)

    def test_dcn_must_divide_devices(self, mesh1d):
        with pytest.raises(ValueError, match="must divide"):
            run_hierarchical(mesh1d, HierConfig(count=512, dcn=3))

    def test_auto_dcn_single_process_runs_flat_hierarchy(self, mesh1d):
        # dcn=0 auto-detect: one CPU process -> one group -> dcn=1, ici=8;
        # the pattern still runs (DCN tier carries zero bytes)
        recs = run_hierarchical(
            mesh1d, HierConfig(count=512, dcn=0, reps=1, warmup=0)
        )
        assert [r.mode for r in recs] == ["flat", "hier"]
        for r in recs:
            assert r.verdict is Verdict.SUCCESS
            assert r.commands.startswith("1x8dev")
            assert r.metrics["dcn_bytes_per_device"] == 0.0

    def test_degenerate_ici_skips(self, devices):
        # dcn = all devices -> ici=1: nothing to scatter over, SKIPPED
        m = Mesh(np.array(devices[:8]).reshape(8), ("x",))
        recs = run_hierarchical(m, HierConfig(count=512, dcn=8))
        (rec,) = recs
        assert rec.verdict is Verdict.SKIPPED
