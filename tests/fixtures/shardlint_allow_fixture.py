# Committed anchor for the Tier-C suppression tests: a shardlint
# finding whose SpmdEntry anchors here (line 5, below the allow) must be
# suppressed by the standalone allow comment through the same
# scan_finding_allows path the engine uses for registry-anchored debt.
# graftlint: allow[collective-axis-discipline] -- fixture: committed Tier-C suppression anchor
ANCHOR_LINE = 6  # the allow above covers this statement
