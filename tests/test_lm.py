"""Token-level LM (models/lm.py): vocab-parallel embedding/CE/argmax,
sharded-vs-single-device loss equality, learnability, greedy decode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_patterns.models import lm
from tpu_patterns.models.transformer import ModelConfig

CFG = dict(embed=64, heads=8, head_dim=8, dtype="float32", causal=True)
V = 64


@pytest.fixture(scope="module")
def mesh3d(devices):
    return Mesh(np.array(devices[:8]).reshape(2, 2, 2), ("dp", "sp", "tp"))


def _shard_map1(fn, mesh, in_specs, out_specs):
    import functools

    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


class TestVocabParallelPrimitives:
    def test_embedding_matches_plain_lookup(self, devices):
        mesh = Mesh(np.array(devices[:4]), ("tp",))
        wemb = jax.random.normal(jax.random.key(0), (V, 16))
        toks = jax.random.randint(jax.random.key(1), (3, 8), 0, V)
        got = _shard_map1(
            lambda w, t: lm.embed_tokens(w, t, "tp"),
            mesh, (P("tp", None), P()), P(),
        )(
            jax.device_put(wemb, NamedSharding(mesh, P("tp", None))),
            jax.device_put(toks, NamedSharding(mesh, P())),
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(wemb)[np.asarray(toks)],
            rtol=0, atol=1e-6,
        )

    def test_ce_matches_log_softmax_reference(self, devices):
        mesh = Mesh(np.array(devices[:4]), ("tp",))
        logits = jax.random.normal(jax.random.key(2), (3, 8, V)) * 3
        targets = jax.random.randint(jax.random.key(3), (3, 8), 0, V)
        want = -np.take_along_axis(
            np.asarray(jax.nn.log_softmax(logits, axis=-1)),
            np.asarray(targets)[..., None], axis=-1,
        )[..., 0]
        got = _shard_map1(
            lambda lg, t: lm.vocab_parallel_ce(lg, t, "tp"),
            mesh, (P(None, None, "tp"), P()), P(),
        )(
            jax.device_put(logits, NamedSharding(mesh, P(None, None, "tp"))),
            jax.device_put(targets, NamedSharding(mesh, P())),
        )
        np.testing.assert_allclose(np.asarray(got), want, rtol=0, atol=1e-5)

    def test_sharded_argmax_matches_global(self, devices):
        mesh = Mesh(np.array(devices[:4]), ("tp",))
        logits = jax.random.normal(jax.random.key(4), (6, V))
        want = np.argmax(np.asarray(logits), axis=-1)
        got = _shard_map1(
            lambda lg: lm.sharded_argmax(lg, "tp"),
            mesh, (P(None, "tp"),), P(),
        )(jax.device_put(logits, NamedSharding(mesh, P(None, "tp"))))
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_argmax_tie_breaks_to_lowest_id(self, devices):
        mesh = Mesh(np.array(devices[:4]), ("tp",))
        logits = np.zeros((2, V), np.float32)
        logits[0, 5] = logits[0, 37] = 7.0  # tie across shards
        logits[1, 63] = 1.0
        got = _shard_map1(
            lambda lg: lm.sharded_argmax(lg, "tp"),
            mesh, (P(None, "tp"),), P(),
        )(jax.device_put(jnp.asarray(logits),
                         NamedSharding(mesh, P(None, "tp"))))
        assert list(np.asarray(got)) == [5, 63]


class TestLMTraining:
    @pytest.mark.parametrize(
        "shape", [(2, 2, 2), (1, 1, 1), (1, 2, 1)]
    )
    def test_sharded_loss_matches_single_device(self, devices, shape):
        # includes the DEGENERATE (1,1,1) mesh: size-1 axes must not trip
        # the varying-axes check (psum over them is a no-op, not skipped)
        n = int(np.prod(shape))
        mesh = Mesh(np.array(devices[:n]).reshape(shape), ("dp", "sp", "tp"))
        cfg = ModelConfig(**CFG, rope=True)
        params = lm.init_lm_params(jax.random.key(0), cfg, V)
        toks = jax.random.randint(jax.random.key(1), (4, 32), 0, V)
        ref = float(lm.lm_loss_shard(params, toks, cfg))
        step, _ = lm.make_lm_train_step(mesh, cfg, V, lr=0.0)
        _, loss = step(
            lm.shard_lm_params(params, mesh, cfg),
            jax.device_put(toks, NamedSharding(mesh, P("dp", "sp"))),
        )
        assert np.isclose(ref, float(loss), rtol=1e-5)
        # sanity: the loss is in the right ballpark of ln(V) at init
        assert 0.5 * np.log(V) < ref < 2.0 * np.log(V)

    def test_lm_learns(self, mesh3d):
        cfg = ModelConfig(**CFG, rope=True, depth=2)
        params = lm.init_lm_params(jax.random.key(0), cfg, V)
        toks = jax.random.randint(jax.random.key(1), (4, 32), 0, V)
        step, _ = lm.make_lm_train_step(mesh3d, cfg, V, lr=0.5)
        p = lm.shard_lm_params(params, mesh3d, cfg)
        st = jax.device_put(toks, NamedSharding(mesh3d, P("dp", "sp")))
        _, first = step(p, st)
        for _ in range(30):
            p, loss = step(p, st)
        assert float(loss) < 0.7 * float(first)

    def test_striped_layout_loss_matches_contiguous(self, mesh3d):
        # the striped halo (whole-block permute + last-stripe shift) must
        # compute the SAME mean CE as the contiguous layout on the same
        # global token stream — rope makes positions load-bearing too
        cfg_c = ModelConfig(**CFG, rope=True)
        cfg_s = ModelConfig(**CFG, rope=True, attn_layout="striped")
        params = lm.init_lm_params(jax.random.key(0), cfg_c, V)
        toks = jax.random.randint(jax.random.key(1), (4, 32), 0, V)
        ref = float(lm.lm_loss_shard(params, toks, cfg_c))
        step, _ = lm.make_lm_train_step(mesh3d, cfg_s, V, lr=0.0)
        sp = 2
        striped = jnp.concatenate(
            [toks[:, r::sp] for r in range(sp)], axis=1
        )
        _, loss = step(
            lm.shard_lm_params(params, mesh3d, cfg_s),
            jax.device_put(striped, NamedSharding(mesh3d, P("dp", "sp"))),
        )
        assert np.isclose(ref, float(loss), rtol=1e-5), (
            ref, float(loss)
        )

    def test_vocab_indivisible_rejected(self, mesh3d):
        with pytest.raises(ValueError, match="vocab"):
            lm.make_lm_train_step(mesh3d, ModelConfig(**CFG), 63)

    def test_moe_lm_loss_matches_single_device(self, mesh3d):
        # the MoE FFN composes with the vocab patterns: experts over
        # the tp axis (ep ≙ tp), one per rank, same global CE as the
        # single device running every expert
        cfg = ModelConfig(**CFG, moe=True, rope=True)
        params = lm.init_lm_params(jax.random.key(0), cfg, V, n_experts=2)
        toks = jax.random.randint(jax.random.key(1), (4, 32), 0, V)
        ref = float(lm.lm_loss_shard(params, toks, cfg))
        step, _ = lm.make_lm_train_step(mesh3d, cfg, V, lr=0.0)
        _, loss = step(
            lm.shard_lm_params(params, mesh3d, cfg),
            jax.device_put(toks, NamedSharding(mesh3d, P("dp", "sp"))),
        )
        assert np.isclose(ref, float(loss), rtol=1e-4)

    def test_moe_lm_generation_mesh_invariant(self, devices):
        # moe generation (VERDICT r2 #4): the 2-expert model produces
        # the SAME greedy ids on the dp x sp x tp mesh (one expert per
        # tp rank) as on one device running every expert
        cfg = ModelConfig(**CFG, moe=True, rope=True)
        params = lm.init_lm_params(jax.random.key(0), cfg, V, n_experts=2)
        toks = jax.random.randint(jax.random.key(1), (4, 16), 0, V)
        outs = {}
        for shape in [(2, 2, 2), (1, 1, 1)]:
            n = int(np.prod(shape))
            mesh = Mesh(
                np.array(devices[:n]).reshape(shape), ("dp", "sp", "tp")
            )
            pre, gen = lm.make_lm_decoder(mesh, cfg, V, 4, 16, 8)
            specs = lm.lm_param_specs(cfg, n_experts=2)
            sp_p = {
                k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                for k, v in params.items()
            }
            tk = jax.device_put(toks, NamedSharding(mesh, P("dp", "sp")))
            caches, t0 = pre(sp_p, tk)
            _, out = gen(sp_p, caches, t0, jnp.asarray(16), 8)
            outs[shape] = (np.asarray(t0), np.asarray(out))
        np.testing.assert_array_equal(outs[(2, 2, 2)][0], outs[(1, 1, 1)][0])
        np.testing.assert_array_equal(outs[(2, 2, 2)][1], outs[(1, 1, 1)][1])
        assert ((outs[(1, 1, 1)][1] >= 0) & (outs[(1, 1, 1)][1] < V)).all()

    def test_striped_lm_generation_mesh_invariant(self, devices):
        # striped generation (VERDICT r2 #4): prompts arrive pre-striped
        # (shard r holds tokens r::sp, the training contract); greedy
        # ids must equal the single-device rollout
        cfg = ModelConfig(**CFG, rope=True, attn_layout="striped")
        params = lm.init_lm_params(jax.random.key(0), cfg, V)
        toks = jax.random.randint(jax.random.key(1), (4, 16), 0, V)
        outs = {}
        for shape in [(2, 2, 2), (1, 1, 1)]:
            n = int(np.prod(shape))
            sp = shape[1]
            mesh = Mesh(
                np.array(devices[:n]).reshape(shape), ("dp", "sp", "tp")
            )
            pre, gen = lm.make_lm_decoder(mesh, cfg, V, 4, 16, 8)
            specs = lm.lm_param_specs(cfg)
            sp_p = {
                k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                for k, v in params.items()
            }
            tks = (
                jnp.concatenate([toks[:, r::sp] for r in range(sp)], axis=1)
                if sp > 1
                else toks
            )
            tk = jax.device_put(tks, NamedSharding(mesh, P("dp", "sp")))
            caches, t0 = pre(sp_p, tk)
            _, out = gen(sp_p, caches, t0, jnp.asarray(16), 8)
            outs[shape] = (np.asarray(t0), np.asarray(out))
        np.testing.assert_array_equal(outs[(2, 2, 2)][0], outs[(1, 1, 1)][0])
        np.testing.assert_array_equal(outs[(2, 2, 2)][1], outs[(1, 1, 1)][1])


class TestLMDecode:
    @pytest.mark.parametrize("kv,int8", [(0, False), (2, True)])
    def test_greedy_rollout_mesh_invariant(self, devices, kv, int8):
        # the end-to-end LM gate: greedy generation must produce the
        # SAME token ids on the full dp x sp x tp mesh as on one device
        # (int8 cache included — argmax over well-separated logits is
        # robust to quantization noise at this scale)
        cfg = ModelConfig(**CFG, rope=True, kv_heads=kv)
        params = lm.init_lm_params(jax.random.key(0), cfg, V)
        toks = jax.random.randint(jax.random.key(1), (4, 16), 0, V)
        outs = {}
        for shape in [(2, 2, 2), (1, 1, 1)]:
            n = int(np.prod(shape))
            mesh = Mesh(
                np.array(devices[:n]).reshape(shape), ("dp", "sp", "tp")
            )
            pre, gen = lm.make_lm_decoder(
                mesh, cfg, V, 4, 16, 8, cache_int8=int8
            )
            specs = lm.lm_param_specs(cfg)
            sp_p = {
                k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                for k, v in params.items()
            }
            tk = jax.device_put(
                toks, NamedSharding(mesh, P("dp", "sp"))
            )
            caches, t0 = pre(sp_p, tk)
            _, out = gen(sp_p, caches, t0, jnp.asarray(16), 8)
            outs[shape] = (np.asarray(t0), np.asarray(out))
        np.testing.assert_array_equal(outs[(2, 2, 2)][0], outs[(1, 1, 1)][0])
        np.testing.assert_array_equal(outs[(2, 2, 2)][1], outs[(1, 1, 1)][1])
        assert ((outs[(1, 1, 1)][1] >= 0) & (outs[(1, 1, 1)][1] < V)).all()

    def test_sampled_rollout_deterministic_and_varied(self, mesh3d):
        # Gumbel-max sampling: same seed -> same tokens; different seeds
        # -> (almost surely) different tokens; T->0 recovers greedy
        cfg = ModelConfig(**CFG, rope=True)
        params = lm.init_lm_params(jax.random.key(0), cfg, V)
        toks = jax.random.randint(jax.random.key(1), (4, 16), 0, V)
        pre, gen = lm.make_lm_decoder(mesh3d, cfg, V, 4, 16, 8)
        specs = lm.lm_param_specs(cfg)
        sp_p = {
            k: jax.device_put(v, NamedSharding(mesh3d, specs[k]))
            for k, v in params.items()
        }
        tk = jax.device_put(toks, NamedSharding(mesh3d, P("dp", "sp")))
        caches, t0 = pre(sp_p, tk)
        args = (sp_p, caches, t0, jnp.asarray(16), 8)
        a1 = np.asarray(gen(*args, temperature=1.0, seed=7)[1])
        a2 = np.asarray(gen(*args, temperature=1.0, seed=7)[1])
        b = np.asarray(gen(*args, temperature=1.0, seed=8)[1])
        greedy = np.asarray(gen(*args)[1])
        cold = np.asarray(gen(*args, temperature=1e-4, seed=7)[1])
        np.testing.assert_array_equal(a1, a2)
        assert not np.array_equal(a1, b)
        np.testing.assert_array_equal(cold, greedy)
        assert ((a1 >= 0) & (a1 < V)).all()

    def test_topk_sample_matches_truncated_softmax(self, devices):
        # top-2 of 8: only the two highest-probability ids may appear,
        # with frequencies matching the RENORMALIZED softmax over them
        mesh = Mesh(np.array(devices[:4]), ("tp",))
        logits = jnp.log(
            jnp.asarray([0.4, 0.2, 0.1, 0.1, 0.05, 0.05, 0.05, 0.05])
        )
        n_draws = 4096
        lg = jnp.broadcast_to(logits, (n_draws, 8))

        def body(lg_local, seeds):
            return lm.sharded_topk_sample(
                lg_local, jax.random.key(seeds[0]), 1.0, 2, "tp"
            )

        # check_vma off: the all_gathered candidates ARE tp-replicated,
        # but the checker cannot infer it (same setting as the decode
        # shard_maps that host this sampler in production)
        draws = np.asarray(jax.jit(
            jax.shard_map(
                body, mesh=mesh, in_specs=(P(None, "tp"), P()),
                out_specs=P(), check_vma=False,
            )
        )(
            jax.device_put(lg, NamedSharding(mesh, P(None, "tp"))),
            jax.device_put(
                jnp.asarray([9], jnp.uint32), NamedSharding(mesh, P())
            ),
        ))
        assert set(np.unique(draws)) <= {0, 1}
        freq0 = (draws == 0).mean()
        assert abs(freq0 - 0.4 / 0.6) < 0.05

    def test_topk_rollout_layout_invariant(self, devices):
        # the id-canonicalized candidate order makes top-k draws
        # bit-identical across sp/tp layouts given the same seed.  (dp
        # layouts legitimately differ: the noise key folds the dp rank
        # so batch shards draw independently — "deterministic in
        # (key, mesh)", not across dp re-shardings.)
        cfg = ModelConfig(**CFG, rope=True)
        params = lm.init_lm_params(jax.random.key(0), cfg, V)
        toks = jax.random.randint(jax.random.key(1), (4, 16), 0, V)
        outs = {}
        for shape in [(1, 2, 4), (1, 1, 1)]:
            n = int(np.prod(shape))
            mesh = Mesh(
                np.array(devices[:n]).reshape(shape), ("dp", "sp", "tp")
            )
            pre, gen = lm.make_lm_decoder(mesh, cfg, V, 4, 16, 8)
            specs = lm.lm_param_specs(cfg)
            sp_p = {
                k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                for k, v in params.items()
            }
            tk = jax.device_put(toks, NamedSharding(mesh, P("dp", "sp")))
            caches, t0 = pre(sp_p, tk, temperature=0.7, seed=5, top_k=4)
            _, out = gen(
                sp_p, caches, t0, jnp.asarray(16), 8,
                temperature=0.7, seed=5, top_k=4,
            )
            outs[shape] = (np.asarray(t0), np.asarray(out))
        np.testing.assert_array_equal(outs[(1, 2, 4)][0], outs[(1, 1, 1)][0])
        np.testing.assert_array_equal(outs[(1, 2, 4)][1], outs[(1, 1, 1)][1])

    def test_sharded_sample_matches_softmax_frequencies(self, devices):
        # the Gumbel trick over a SHARDED vocab must sample the true
        # softmax: 4k draws from a known 8-way distribution
        mesh = Mesh(np.array(devices[:4]), ("tp",))
        logits = jnp.log(
            jnp.asarray([0.4, 0.2, 0.1, 0.1, 0.05, 0.05, 0.05, 0.05])
        )
        n_draws = 4096
        lg = jnp.broadcast_to(logits, (n_draws, 8))

        def body(lg_local, seeds):
            return lm.sharded_sample(
                lg_local, jax.random.key(seeds[0]), 1.0, "tp"
            )

        draws = _shard_map1(
            body, mesh, (P(None, "tp"), P()), P(),
        )(
            jax.device_put(lg, NamedSharding(mesh, P(None, "tp"))),
            jax.device_put(
                jnp.asarray([123], jnp.uint32), NamedSharding(mesh, P())
            ),
        )
        # NOTE: one key for all rows here — but gumbel noise is drawn per
        # row of the [n_draws, 2]-per-rank slice, so rows are iid draws
        freq = np.bincount(np.asarray(draws), minlength=8) / n_draws
        want = np.exp(np.asarray(logits))
        assert np.abs(freq - want).max() < 0.05

    def test_prefill_token_matches_forward_argmax(self, mesh3d):
        # the first sampled token == argmax of the training forward's
        # logits at the last prompt position
        cfg = ModelConfig(**CFG, rope=True)
        params = lm.init_lm_params(jax.random.key(5), cfg, V)
        toks = jax.random.randint(jax.random.key(6), (4, 16), 0, V)
        x = np.asarray(params["wemb"])[np.asarray(toks)]
        from tpu_patterns.models.transformer import forward_shard

        y = forward_shard(
            {k: v for k, v in params.items() if k != "wemb"},
            jnp.asarray(x), cfg,
        )
        logits = np.asarray(y[:, -1]) @ np.asarray(params["wemb"]).T
        want = np.argmax(logits, axis=-1)
        pre, _ = lm.make_lm_decoder(mesh3d, cfg, V, 4, 16, 8)
        specs = lm.lm_param_specs(cfg)
        sp_p = {
            k: jax.device_put(v, NamedSharding(mesh3d, specs[k]))
            for k, v in params.items()
        }
        _, t0 = pre(
            sp_p, jax.device_put(toks, NamedSharding(mesh3d, P("dp", "sp")))
        )
        np.testing.assert_array_equal(np.asarray(t0), want)
