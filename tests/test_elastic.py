"""Elastic fleet policy (tpu_patterns/serve/elastic.py).

The decision half of the self-sizing fleet is PURE — no mesh, no
processes, no wall clock — so every hysteresis property the serving
doc promises is pinned here directly: separate high/low waters, the
sustain window, the cooldown, the scale-in floor, and the
shrink-must-fit guard.
"""

import pytest

from tpu_patterns.serve.elastic import (
    ElasticConfig,
    ElasticPolicy,
    FleetSignals,
)


def _sig(leases, *, live=2, spare=1, slots=4, pending=0):
    return FleetSignals(
        leases=leases, pending=pending, live=live, spare=spare,
        slots=slots,
    )


def _cfg(**kw):
    kw.setdefault("reserve", 1)
    kw.setdefault("sustain_s", 0.5)
    kw.setdefault("cooldown_s", 2.0)
    return ElasticConfig(**kw)


class TestConfigValidation:
    @pytest.mark.parametrize(
        "bad",
        [
            dict(reserve=-1),
            dict(in_occupancy=1.5, out_occupancy=1.25),  # inverted
            dict(in_occupancy=-0.1),
            dict(sustain_s=-1.0),
            dict(cooldown_s=-1.0),
            dict(min_live=0),
        ],
    )
    def test_bad_knobs_rejected(self, bad):
        with pytest.raises(ValueError):
            _cfg(**bad)

    def test_occupancy_is_per_live_slot(self):
        assert _sig(8, live=2, slots=4).occupancy() == 1.0
        assert _sig(8, live=1, slots=4).occupancy() == 2.0
        assert _sig(6, live=2, slots=4, pending=2).occupancy() == 1.0
        # degenerate fleets never divide by zero
        assert _sig(4, live=0, slots=4).occupancy() == 1.0


class TestScaleOut:
    def test_sustained_pressure_scales_out(self):
        pol = ElasticPolicy(_cfg())
        hot = _sig(16, live=2, slots=4)  # occ 2.0 > 1.25
        assert pol.decide(0.0, hot) is None  # sustain not met yet
        assert pol.decide(0.2, hot) is None
        assert pol.decide(0.6, hot) == "out"
        assert pol.decisions == [(0.6, "out")]

    def test_bursty_pressure_never_scales(self):
        pol = ElasticPolicy(_cfg())
        hot, calm = _sig(16), _sig(4)
        for t in (0.0, 0.4, 0.8, 1.2):
            assert pol.decide(t, hot if int(t * 10) % 8 == 0 else calm
                              ) is None
        assert pol.decisions == []

    def test_no_spare_no_scale_out(self):
        pol = ElasticPolicy(_cfg())
        hot = _sig(16, spare=0)
        assert pol.decide(0.0, hot) is None
        assert pol.decide(1.0, hot) is None  # sustained, but no slice

    def test_cooldown_gates_consecutive_actions(self):
        pol = ElasticPolicy(_cfg(reserve=2))
        hot = _sig(16, spare=2)
        pol.decide(0.0, hot)
        assert pol.decide(0.5, hot) == "out"
        # still over-water and sustained, but inside the cooldown
        assert pol.decide(1.0, hot) is None
        assert pol.decide(2.0, hot) is None
        # past the cooldown the (re-started) sustain window acts again
        assert pol.decide(3.1, hot) == "out"

    def test_sustain_tracks_through_cooldown(self):
        # a burst that STARTS during cooldown counts its full duration:
        # at cooldown expiry the policy acts immediately, it does not
        # restart the sustain clock
        pol = ElasticPolicy(_cfg(reserve=2, cooldown_s=5.0))
        hot = _sig(16, spare=2)
        pol.decide(0.0, hot)
        assert pol.decide(0.5, hot) == "out"  # action at t=0.5
        assert pol.decide(1.0, hot) is None  # cooling; over since 1.0
        assert pol.decide(5.6, hot) == "out"  # sustained 4.6s >= 0.5s


class TestScaleIn:
    def test_sustained_idle_scales_in(self):
        pol = ElasticPolicy(_cfg())
        idle = _sig(1, live=2, slots=4)  # occ 0.125 < 0.25
        assert pol.decide(0.0, idle) is None
        assert pol.decide(0.6, idle) == "in"

    def test_min_live_floor_holds(self):
        pol = ElasticPolicy(_cfg(min_live=2))
        idle = _sig(0, live=2)
        assert pol.decide(0.0, idle) is None
        assert pol.decide(1.0, idle) is None  # at the floor: never "in"

    def test_shrink_must_fit_survivors(self):
        # occupancy is under the low water but the surviving slots
        # could not hold the in-flight work: the drain would only
        # re-queue the pressure it claims to relieve
        pol = ElasticPolicy(_cfg(in_occupancy=0.9, out_occupancy=1.0))
        tight = _sig(7, live=2, slots=4)  # occ 0.875; survivors hold 4
        assert pol.decide(0.0, tight) is None
        assert pol.decide(1.0, tight) is None
        # the under-water window was already sustained — the moment
        # the in-flight work fits the survivors, the shrink goes
        loose = _sig(3, live=2, slots=4)  # fits one replica
        assert pol.decide(2.0, loose) == "in"

    def test_out_and_in_waters_are_disjoint(self):
        # between the waters the policy holds steady in BOTH directions
        pol = ElasticPolicy(_cfg())
        mid = _sig(4, live=2, slots=4)  # occ 0.5: 0.25 < occ < 1.25
        for t in (0.0, 1.0, 2.0, 3.0):
            assert pol.decide(t, mid) is None
        assert pol.decisions == []
