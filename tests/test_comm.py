"""Tests for comm: dtypes, verify, p2p, rings (SURVEY.md §7 step 3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_patterns.comm import (
    DTYPES,
    P2PConfig,
    checksum_device,
    expected_checksum,
    fill_randomly,
    get_dtype,
    library_allreduce,
    pair_permutation,
    ring_allreduce_naive,
    ring_allreduce_optimal,
    ring_shift,
    run_p2p,
    wire_bytes,
)
from tpu_patterns.comm.verify import checksum_ok
from tpu_patterns.core.results import Verdict


class TestDtypes:
    def test_reference_parity_10_types(self):
        # mpi_datatype.hpp:27-51 specializes 10 scalar types + BYTE fallback
        for name in ("float32", "float64", "int32", "uint32", "int64",
                     "uint64", "int16", "int8", "uint8", "bool", "byte"):
            assert name in DTYPES

    def test_tpu_native_types(self):
        assert get_dtype("bfloat16").exact_modulus == 2**8
        assert get_dtype("float32").exact_modulus == 2**24

    def test_wire_bytes(self):
        assert wire_bytes("float32", 10) == 40
        assert wire_bytes("int8", 10) == 10

    def test_unknown_dtype_lists_options(self):
        with pytest.raises(KeyError, match="float32"):
            get_dtype("quaternion")


class TestVerify:
    @pytest.mark.parametrize("dtype", sorted(DTYPES))
    def test_fill_checksum_all_dtypes(self, dtype):
        # wide dtypes (uint32/int64/uint64/float64) must work under the
        # default x64-disabled config: moduli are clamped/canonicalized
        x = fill_randomly(512, dtype, seed=1)
        assert x.shape == (512,)
        assert checksum_ok(x, 512, dtype)

    @pytest.mark.parametrize("dtype", ["float32", "int32", "bfloat16", "uint8"])
    def test_fill_checksum_roundtrip(self, dtype):
        n = 10_000
        x = fill_randomly(n, dtype, seed=3)
        assert x.shape == (n,)
        assert checksum_ok(x, n, dtype)

    def test_checksum_detects_corruption(self):
        n = 10_000
        x = fill_randomly(n, "float32")
        x = x.at[17].add(1.0)
        assert not checksum_ok(x, n, "float32")

    def test_checksum_detects_dropped_element(self):
        n = 1_000
        x = fill_randomly(n, "int32")
        assert not checksum_ok(x.at[5].set(0), n, "int32") or int(x[5]) == 0

    def test_expected_checksum_small_exact(self):
        # n below every modulus: plain N(N-1)/2, the reference's invariant
        # (peer2pear.cpp:59-62)
        assert expected_checksum(100, "float32") == 100 * 99 // 2

    def test_values_exactly_representable(self):
        x = fill_randomly(100_000, "bfloat16")
        # cast to int and back must be lossless
        assert (x.astype(jnp.int32).astype(jnp.bfloat16) == x).all()


class TestPairPermutation:
    def test_uni(self):
        assert pair_permutation(4) == [(0, 1), (2, 3)]

    def test_bi(self):
        assert pair_permutation(4, True) == [(0, 1), (2, 3), (1, 0), (3, 2)]


class TestP2P:
    def test_run_p2p_8dev(self, mesh1d):
        cfg = P2PConfig(count=4096, reps=3, warmup=1)
        recs = run_p2p(mesh1d, cfg)
        assert len(recs) == 2
        uni, bi = recs
        assert uni.mode == "unidirectional" and bi.mode == "bidirectional"
        for r in recs:
            assert r.verdict is Verdict.SUCCESS, r.notes
            assert r.metrics["bandwidth_GBps"] > 0
            assert r.metrics["checksum_ok"] == 1.0
        assert bi.metrics["num_transfers"] == 2 * uni.metrics["num_transfers"]

    def test_min_bandwidth_gate_fails(self, mesh1d):
        cfg = P2PConfig(count=1024, reps=2, warmup=1, min_bandwidth=1e12,
                        bidirectional=False)
        (rec,) = run_p2p(mesh1d, cfg)
        assert rec.verdict is Verdict.FAILURE

    def test_odd_device_count_rejected(self, devices):
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices[:3]), ("x",))
        with pytest.raises(ValueError, match="even"):
            run_p2p(mesh, P2PConfig(count=16))

    def test_per_pair_rate_recorded(self, mesh1d):
        (rec,) = run_p2p(
            mesh1d, P2PConfig(count=2048, reps=2, warmup=1,
                              bidirectional=False)
        )
        pairs = rec.metrics["num_transfers"]
        assert rec.metrics["bandwidth_GBps_per_pair"] == pytest.approx(
            rec.metrics["bandwidth_GBps"] / pairs
        )
        # CPU mesh: no ICI spec, so no unchecked plausibility claim
        assert "ici_plausible" not in rec.metrics

    def test_ici_plausibility_gate(self, mesh1d, monkeypatch):
        # ≙ the HBM gate of onesided: a per-pair rate no link can carry
        # (spec forced to ~0) must fail the verdict with a diagnostic
        from tpu_patterns import runtime

        monkeypatch.setattr(runtime, "chip_ici_gbps", lambda: 1e-9)
        (rec,) = run_p2p(
            mesh1d, P2PConfig(count=2048, reps=2, warmup=1,
                              bidirectional=False)
        )
        assert rec.verdict is Verdict.FAILURE
        assert rec.metrics["ici_plausible"] == 0.0
        assert any("never crossed chips" in n for n in rec.notes)


def _shard_mapped(mesh, fn, *args):
    out = jax.shard_map(fn, mesh=mesh, in_specs=P("x"), out_specs=P("x"))(*args)
    return np.asarray(out)


class TestRings:
    def test_ring_shift_rotates(self, mesh1d):
        n = 8
        x = jax.device_put(
            jnp.arange(n, dtype=jnp.float32), NamedSharding(mesh1d, P("x"))
        )
        out = _shard_mapped(mesh1d, lambda a: ring_shift(a, "x", n), x)
        # device i's value moves to device i+1
        np.testing.assert_array_equal(out, np.roll(np.arange(n), 1))

    @pytest.mark.parametrize("variant", ["naive", "optimal"])
    def test_ring_allreduce_matches_psum(self, mesh1d, variant):
        n = 8
        per_dev = 64
        x = fill_randomly(n * per_dev, "float32", seed=7)
        xs = jax.device_put(x, NamedSharding(mesh1d, P("x")))
        impl = ring_allreduce_naive if variant == "naive" else ring_allreduce_optimal
        got = _shard_mapped(mesh1d, lambda a: impl(a, "x", n), xs)
        want = _shard_mapped(mesh1d, lambda a: library_allreduce(a, "x"), xs)
        np.testing.assert_allclose(got, want, rtol=1e-6)
        # every shard holds the same reduced vector
        got2 = got.reshape(n, per_dev)
        for i in range(1, n):
            np.testing.assert_allclose(got2[i], got2[0], rtol=1e-6)

    def test_ring_allreduce_int_exact(self, mesh1d):
        n = 8
        per_dev = 32
        x = jnp.arange(n * per_dev, dtype=jnp.int32)
        xs = jax.device_put(x, NamedSharding(mesh1d, P("x")))
        got = _shard_mapped(mesh1d, lambda a: ring_allreduce_optimal(a, "x", n), xs)
        want = x.reshape(n, per_dev).sum(0)
        np.testing.assert_array_equal(got.reshape(n, per_dev)[3], np.asarray(want))

    def test_ring_optimal_requires_divisible(self, mesh1d):
        with pytest.raises(ValueError, match="divisible"):
            _shard_mapped(
                mesh1d,
                lambda a: ring_allreduce_optimal(a, "x", 8),
                jax.device_put(
                    jnp.zeros(8 * 9), NamedSharding(mesh1d, P("x"))
                ),
            )

    def test_axis_size_one_identity(self, devices):
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices[:1]), ("x",))
        x = jnp.arange(16, dtype=jnp.float32)
        xs = jax.device_put(x, NamedSharding(mesh, P("x")))
        got = _shard_mapped(mesh, lambda a: ring_allreduce_naive(a, "x", 1), xs)
        np.testing.assert_array_equal(got, np.asarray(x))
