// tpu_patterns native module: XLA-FFI handlers + direct host entry points.
//
// Native (C++) parity with the reference's native layers (SURVEY.md §2.2):
//   * monotonic clock            — the distributed timing core's clock
//                                  (≙ the std::chrono timing in
//                                  p2p/peer2pear.cpp:26-28 and
//                                  concurency/bench_sycl.cpp:84-121)
//   * wrapped-int32 checksum     — the data-integrity verifier's reduction
//                                  (≙ sort+sum validation, peer2pear.cpp:55-63)
//   * saxpy (high-level interop) — typed zero-copy buffer sharing between
//                                  the framework and custom C++
//                                  (≙ OMP<->SYCL pointer sharing proof,
//                                  interop_omp_sycl.cpp:51-72)
//   * raw_info (low-level interop)— hand-parsed XLA_FFI_CallFrame: raw API
//                                  version, stage, buffer handles
//                                  (≙ native Level-Zero handle extraction,
//                                  interop_omp_ze_sycl.cpp:25-46)
//
// Built as one shared library; loaded with ctypes; handlers registered via
// jax.ffi.register_ffi_target (tpu_patterns/interop/native.py).

#include <cstdint>
#include <cstring>
#include <ctime>

#include "xla/ffi/api/c_api.h"
#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static uint64_t MonotonicNs() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
         static_cast<uint64_t>(ts.tv_nsec);
}

// Direct host entry point (no XLA involved): the framework's clock_ns()
// calls this through ctypes when the library is built.
extern "C" uint64_t tp_clock_ns() { return MonotonicNs(); }

// --------------------------------------------------------------------------
// Direct (ctypes) entry points for the host-offload interop path.  On TPU
// platforms where custom-call handlers cannot live inside the compiled
// program (the compile happens in a separate runtime process — e.g. a
// remote-tunneled libtpu — so client-registered handler pointers do not
// exist there), the framework reaches this C++ through jax.pure_callback:
// XLA stages the device buffer to a host array, C++ borrows that buffer
// zero-copy for the call duration, and the result is staged back.
// Ownership: the caller (NumPy) owns every buffer; C++ must not retain
// pointers past the call (≙ sycl ownership::keep semantics — borrow the
// native handle, never adopt it; interop_omp_ze_sycl.cpp:56-73).
extern "C" int32_t tp_checksum_f32_direct(const float* x, uint64_t n) {
  uint32_t acc = 0;
  for (uint64_t i = 0; i < n; ++i) {
    acc += static_cast<uint32_t>(static_cast<int32_t>(x[i]));
  }
  return static_cast<int32_t>(acc);
}

extern "C" void tp_saxpy_direct(float alpha, const float* x, const float* y,
                                float* out, uint64_t n) {
  for (uint64_t i = 0; i < n; ++i) out[i] = alpha * x[i] + y[i];
}

// --------------------------------------------------------------------------
// FFI: clock -> u64[] (1 element).  R1 rather than R0 keeps jax.ffi output
// shapes trivial.
static ffi::Error ClockNsImpl(ffi::Result<ffi::Buffer<ffi::U64>> out) {
  out->typed_data()[0] = MonotonicNs();
  return ffi::Error::Success();
}
XLA_FFI_DEFINE_HANDLER_SYMBOL(TpClockNs, ClockNsImpl,
                              ffi::Ffi::Bind().Ret<ffi::Buffer<ffi::U64>>());

// --------------------------------------------------------------------------
// FFI: checksum(f32[n]) -> s32[] — wrapped int32 sum, the exact invariant
// comm/verify.py computes on device (unsigned arithmetic = defined wraparound).
static ffi::Error ChecksumF32Impl(ffi::Buffer<ffi::F32> x,
                                  ffi::Result<ffi::Buffer<ffi::S32>> out) {
  const float* d = x.typed_data();
  uint32_t acc = 0;
  const size_t n = x.element_count();
  for (size_t i = 0; i < n; ++i) {
    acc += static_cast<uint32_t>(static_cast<int32_t>(d[i]));
  }
  out->typed_data()[0] = static_cast<int32_t>(acc);
  return ffi::Error::Success();
}
XLA_FFI_DEFINE_HANDLER_SYMBOL(TpChecksumF32, ChecksumF32Impl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::S32>>());

// --------------------------------------------------------------------------
// FFI high-level interop: out = alpha*x + y, computed by C++ directly on the
// XLA-owned buffers (zero copy both directions).
static ffi::Error SaxpyImpl(float alpha, ffi::Buffer<ffi::F32> x,
                            ffi::Buffer<ffi::F32> y,
                            ffi::Result<ffi::Buffer<ffi::F32>> out) {
  const size_t n = x.element_count();
  if (y.element_count() != n || out->element_count() != n) {
    return ffi::Error::InvalidArgument("saxpy: shape mismatch");
  }
  const float* xd = x.typed_data();
  const float* yd = y.typed_data();
  float* od = out->typed_data();
  for (size_t i = 0; i < n; ++i) od[i] = alpha * xd[i] + yd[i];
  return ffi::Error::Success();
}
XLA_FFI_DEFINE_HANDLER_SYMBOL(TpSaxpy, SaxpyImpl,
                              ffi::Ffi::Bind()
                                  .Attr<float>("alpha")
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());

// --------------------------------------------------------------------------
// FFI low-level interop: a raw XLA_FFI_Handler working straight on the C
// call frame — no C++ binding layer.  Reports what it can see of the
// runtime: API version, execution stage, argument metadata, and echoes the
// device pointer of its input (proving the handle is shared, not copied).
// Output: s32[8] = {api_major, api_minor, stage, nargs, arg0_dtype,
//                   arg0_rank, data_ptr_lo16, copied_flag}.
extern "C" XLA_FFI_Error* TpRawInfo(XLA_FFI_CallFrame* frame) {
  // Metadata-query stage: XLA probes the handler's API version before use.
  for (XLA_FFI_Extension_Base* ext = frame->extension_start; ext;
       ext = ext->next) {
    if (ext->type == XLA_FFI_Extension_Metadata) {
      auto* m = reinterpret_cast<XLA_FFI_Metadata_Extension*>(ext);
      m->metadata->api_version.major_version = XLA_FFI_API_MAJOR;
      m->metadata->api_version.minor_version = XLA_FFI_API_MINOR;
      return nullptr;
    }
  }
  if (frame->rets.size < 1 || frame->args.size < 1) return nullptr;
  auto* in = reinterpret_cast<XLA_FFI_Buffer*>(frame->args.args[0]);
  auto* out = reinterpret_cast<XLA_FFI_Buffer*>(frame->rets.rets[0]);
  int32_t* o = reinterpret_cast<int32_t*>(out->data);
  o[0] = frame->api ? frame->api->api_version.major_version : -1;
  o[1] = frame->api ? frame->api->api_version.minor_version : -1;
  o[2] = static_cast<int32_t>(frame->stage);
  o[3] = static_cast<int32_t>(frame->args.size);
  o[4] = static_cast<int32_t>(in->dtype);
  o[5] = static_cast<int32_t>(in->rank);
  o[6] = static_cast<int32_t>(reinterpret_cast<uintptr_t>(in->data) & 0xFFFF);
  // Write through the raw input pointer's data to prove shared (not copied)
  // access: checksum of first element must match what the caller sees.
  o[7] = in->rank > 0 && in->data
             ? static_cast<int32_t>(reinterpret_cast<float*>(in->data)[0])
             : -1;
  return nullptr;
}
