// Native topology core: ICI plane (ring) discovery + one-hop adjacency.
//
// TPU-native twin of the reference fabric prober's ALGORITHM
// (p2p/topology.cpp:28-107): the reference unions fabric-port endpoint
// pairs into disjoint connection sets (:52-73) and merges them into
// fully-connected "planes" (:76-89).  Here the fabric is the ICI torus
// and the "ports" are implied by coordinates: two devices are linked
// along an axis when they agree on every OTHER coordinate and on the
// core index.  Union-find over those links yields per-axis connected
// sets — the rings — exactly the sets tpu_patterns/topo/topology.py's
// Python implementation builds by hash-grouping; the two must agree
// bit-for-bit (tests/test_topo.py drives both on the same topologies).
//
// Plain C++ (no XLA headers), called directly over ctypes like
// tp_checksum_f32_direct — this is host-side launcher logic, not device
// code (SURVEY.md §2.2 item 2: the C++ FFI topology module).

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

struct UnionFind {
  std::vector<int32_t> parent;
  explicit UnionFind(int32_t n) : parent(n) {
    for (int32_t i = 0; i < n; ++i) parent[i] = i;
  }
  int32_t find(int32_t x) {
    while (parent[x] != x) {
      parent[x] = parent[parent[x]];
      x = parent[x];
    }
    return x;
  }
  void unite(int32_t a, int32_t b) { parent[find(a)] = find(b); }
};

// Per-axis extent = number of DISTINCT coordinate values (the Python
// torus_shape), not max+1 — synthetic/sparse coords must agree.
static void extents(const int32_t* coords, int32_t n, int32_t ndim,
                    std::vector<int32_t>* out) {
  out->assign(ndim, 0);
  std::vector<int32_t> vals;
  for (int32_t ax = 0; ax < ndim; ++ax) {
    vals.clear();
    for (int32_t i = 0; i < n; ++i) vals.push_back(coords[i * ndim + ax]);
    std::sort(vals.begin(), vals.end());
    (*out)[ax] = static_cast<int32_t>(
        std::unique(vals.begin(), vals.end()) - vals.begin());
  }
}

// Linked along `ax`: same core, same every-other-coordinate.
static bool linked(const int32_t* coords, const int32_t* cores,
                   int32_t ndim, int32_t ax, int32_t i, int32_t j) {
  if (cores[i] != cores[j]) return false;
  for (int32_t d = 0; d < ndim; ++d) {
    if (d == ax) continue;
    if (coords[i * ndim + d] != coords[j * ndim + d]) return false;
  }
  return true;
}

}  // namespace

// Rings of the torus, flattened: ring r spans
// out_members[out_offsets[r] .. out_offsets[r+1]).  Returns the ring
// count, or -1 on bad args / buffer overflow (callers size generously:
// total membership <= n * ndim + n).
extern "C" int32_t tp_topo_planes(const int32_t* coords,
                                  const int32_t* cores, int32_t n,
                                  int32_t ndim, int32_t* out_members,
                                  int32_t* out_offsets,
                                  int32_t cap_members, int32_t cap_rings) {
  if (n <= 0 || ndim <= 0 || !coords || !cores || !out_members ||
      !out_offsets)
    return -1;
  std::vector<int32_t> ext;
  extents(coords, n, ndim, &ext);
  int32_t n_rings = 0, n_members = 0;
  auto emit = [&](const std::vector<int32_t>& ring) -> bool {
    if (n_rings + 1 > cap_rings ||
        n_members + static_cast<int32_t>(ring.size()) > cap_members)
      return false;
    out_offsets[n_rings] = n_members;
    for (int32_t idx : ring) out_members[n_members++] = idx;
    out_offsets[++n_rings] = n_members;
    return true;
  };
  for (int32_t ax = 0; ax < ndim; ++ax) {
    // degenerate axis on a multi-axis torus contributes no rings (the
    // 1-extent axis of an 8x1 mesh); a 1-D "torus" keeps its chain
    if (ext[ax] <= 1 && ndim > 1) continue;
    UnionFind uf(n);
    for (int32_t i = 0; i < n; ++i)
      for (int32_t j = i + 1; j < n; ++j)
        if (linked(coords, cores, ndim, ax, i, j)) uf.unite(i, j);
    // components in first-seen order; members in device order, then
    // stably sorted along the ring axis — byte-compatible with the
    // Python hash-group + stable sort
    std::vector<int32_t> root_order;
    std::vector<std::vector<int32_t>> comps(n);
    for (int32_t i = 0; i < n; ++i) {
      int32_t r = uf.find(i);
      if (comps[r].empty()) root_order.push_back(r);
      comps[r].push_back(i);
    }
    for (int32_t r : root_order) {
      std::vector<int32_t>& m = comps[r];
      if (static_cast<int32_t>(m.size()) < 2 && n > 1) continue;
      std::stable_sort(m.begin(), m.end(), [&](int32_t a, int32_t b) {
        return coords[a * ndim + ax] < coords[b * ndim + ax];
      });
      if (!emit(m)) return -1;
    }
  }
  if (n_rings == 0) {
    // single device / fully degenerate: one plane of everything
    std::vector<int32_t> all(n);
    for (int32_t i = 0; i < n; ++i) all[i] = i;
    if (!emit(all)) return -1;
  }
  return n_rings;
}

// Devices one ICI hop from `index`: same core, torus-wrapped coordinate
// distance summing to exactly 1.  Returns the neighbor count written to
// out (sorted ascending), or -1 on bad args / overflow.
extern "C" int32_t tp_topo_neighbors(const int32_t* coords,
                                     const int32_t* cores, int32_t n,
                                     int32_t ndim, int32_t index,
                                     int32_t* out, int32_t cap) {
  if (n <= 0 || ndim <= 0 || index < 0 || index >= n || !coords ||
      !cores || !out)
    return -1;
  std::vector<int32_t> ext;
  extents(coords, n, ndim, &ext);
  int32_t count = 0;
  for (int32_t j = 0; j < n; ++j) {
    if (j == index || cores[j] != cores[index]) continue;
    int64_t dist = 0;
    for (int32_t ax = 0; ax < ndim; ++ax) {
      int32_t a = coords[index * ndim + ax], b = coords[j * ndim + ax];
      int32_t d = a > b ? a - b : b - a;
      if (ext[ax] > 1) d = std::min(d, ext[ax] - d);  // wrap
      dist += d;
    }
    if (dist == 1) {
      if (count >= cap) return -1;
      out[count++] = j;  // j ascends, so the output is already sorted
    }
  }
  return count;
}
