// Native prefetch data loader: a producer thread pool fills a ring of
// host batch buffers AHEAD of the consumer, so batch synthesis (or, in a
// real deployment, file IO + decode) overlaps device compute — the
// double-buffered host side of an input pipeline.  The reference suite
// has no loader; this is the runtime-layer analogue of its pinned-host
// buffer discipline (concurency/bench_omp.cpp:42-44) applied to input
// data: host buffers live outside the accelerator framework entirely and
// cross the boundary as raw pointers (ctypes, zero-copy numpy views).
//
// DETERMINISM CONTRACT (what makes this compose with checkpoint/resume):
// batch t is a pure function of (seed, t) — splitmix64 keyed by
// (seed, t, element index) — and tpl_seek(t) repositions the stream, so
// a resumed training run replays exactly the batches the killed run
// would have seen.  tpu_patterns/io/loader.py holds the Python side;
// tests/test_io.py pins the contract (cross-instance determinism, seek
// equivalence, prefetch-ahead behavior).
//
// Concurrency model: one mutex + two condvars around a ring of
// `n_buffers` slots; `workers` producer threads claim step numbers and
// fill slot (step % n_buffers) with the slot's generation gate keeping
// writers exactly n_buffers ahead of the consumer.  tpl_next() blocks
// until the NEXT sequential step's slot is filled and releases the slot
// the consumer previously held.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// splitmix64: tiny, well-mixed, and trivially portable — the point is a
// deterministic stream, not cryptography.
static inline uint64_t mix64(uint64_t z) {
  z += 0x9e3779b97f4a7c15ull;
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

// uniform in [-1, 1): 53-bit mantissa path, cast to float at the end
static inline float to_unit(uint64_t bits) {
  const double u = (double)(bits >> 11) * (1.0 / 9007199254740992.0);
  return (float)(2.0 * u - 1.0);
}

struct Loader {
  uint64_t seed;
  int64_t elems;        // floats per batch
  int n_buffers;
  std::vector<std::vector<float>> ring;
  std::vector<int64_t> slot_step;  // which step each slot holds; -1 empty

  std::mutex mu;
  std::condition_variable cv_produce, cv_consume;
  int64_t next_fill;     // next step a producer may claim
  int64_t next_consume;  // next step tpl_next will hand out
  std::atomic<int64_t> filled_total{0};
  uint64_t epoch;  // bumped by seek: stale fills are discarded
  bool stop;
  std::vector<std::thread> workers;

  Loader(uint64_t seed_, int64_t elems_, int n_buffers_, int n_threads)
      : seed(seed_),
        elems(elems_),
        n_buffers(n_buffers_),
        ring(n_buffers_),
        slot_step(n_buffers_, -1),
        next_fill(0),
        next_consume(0),
        epoch(0),
        stop(false) {
    for (auto& b : ring) b.resize((size_t)elems);
    for (int i = 0; i < n_threads; ++i)
      workers.emplace_back([this] { work(); });
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> l(mu);
      stop = true;
    }
    cv_produce.notify_all();
    cv_consume.notify_all();
    for (auto& w : workers) w.join();
  }

  void fill(float* dst, int64_t step) const {
    const uint64_t key = mix64(seed ^ mix64((uint64_t)step));
    for (int64_t i = 0; i < elems; ++i)
      dst[i] = to_unit(mix64(key ^ (uint64_t)i));
  }

  void work() {
    // Producers synthesize into thread-LOCAL scratch and commit to the
    // ring under the lock only if their epoch is still current.  A
    // stale producer (seek raced its fill) therefore never touches the
    // ring at all — without the scratch, an in-flight stale fill would
    // keep writing its slot unlocked while a new-epoch producer or the
    // consumer uses it (a torn-data race, not just a dropped batch).
    // The commit memcpy is serialized by the lock; synthesis (the slow
    // part) stays parallel.
    std::vector<float> scratch((size_t)elems);
    std::unique_lock<std::mutex> l(mu);
    while (true) {
      // claim the next step whose slot is free.  The bound is
      // n_buffers - 1, NOT n_buffers: the consumer still READS the slot
      // of step next_consume-1 until its next tpl_next call, and step
      // next_consume-1 + n_buffers maps to that same slot — one slot of
      // the ring is always reserved for the outstanding pointer.
      while (!stop && next_fill >= next_consume + n_buffers - 1)
        cv_produce.wait(l);
      if (stop) return;
      const int64_t step = next_fill++;
      const uint64_t my_epoch = epoch;
      l.unlock();
      fill(scratch.data(), step);
      l.lock();
      if (my_epoch == epoch && !stop) {
        std::memcpy(ring[(size_t)(step % n_buffers)].data(),
                    scratch.data(), (size_t)elems * sizeof(float));
        slot_step[(size_t)(step % n_buffers)] = step;
        filled_total.fetch_add(1, std::memory_order_relaxed);
        cv_consume.notify_all();
      }
    }
  }

  const float* next(int64_t* step_out) {
    std::unique_lock<std::mutex> l(mu);
    const int64_t want = next_consume;
    while (!stop && slot_step[(size_t)(want % n_buffers)] != want)
      cv_consume.wait(l);
    if (stop) return nullptr;
    // handing out slot (want % n_buffers): the buffer the consumer held
    // before (want-1) becomes reclaimable via next_consume++; the slot
    // handed out NOW stays safe because producers stop n_buffers-1
    // ahead (see work()).  Single consumer assumed: tpl_next/tpl_seek
    // must not race each other (the Python wrapper is one thread).
    next_consume = want + 1;
    slot_step[(size_t)(want % n_buffers)] = -1;
    if (step_out) *step_out = want;
    cv_produce.notify_all();
    return ring[(size_t)(want % n_buffers)].data();
  }

  void seek(int64_t step) {
    std::lock_guard<std::mutex> l(mu);
    epoch++;
    next_fill = step;
    next_consume = step;
    for (auto& s : slot_step) s = -1;
    cv_produce.notify_all();
  }
};

}  // namespace

extern "C" {

void* tpl_create(uint64_t seed, int64_t elems, int n_buffers,
                 int n_threads) {
  if (elems <= 0 || n_buffers < 2 || n_threads < 1) return nullptr;
  return new Loader(seed, elems, n_buffers, n_threads);
}

void tpl_destroy(void* p) { delete (Loader*)p; }

const float* tpl_next(void* p, int64_t* step_out) {
  return ((Loader*)p)->next(step_out);
}

void tpl_seek(void* p, int64_t step) { ((Loader*)p)->seek(step); }

int64_t tpl_filled_total(void* p) {
  return ((Loader*)p)->filled_total.load(std::memory_order_relaxed);
}

// Synchronous reference: fill one buffer for `step` without any loader
// state — the oracle the tests compare the prefetched stream against.
void tpl_fill_reference(uint64_t seed, int64_t elems, int64_t step,
                        float* dst) {
  const uint64_t key = mix64(seed ^ mix64((uint64_t)step));
  for (int64_t i = 0; i < elems; ++i)
    dst[i] = to_unit(mix64(key ^ (uint64_t)i));
}

}  // extern "C"
