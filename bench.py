#!/usr/bin/env python
"""Driver benchmark: prints exactly ONE JSON line on stdout.

Headline metric, by what the hardware offers (BASELINE.md north star —
"measured ICI bandwidth >= 90% of spec"):
  * >= 2 devices: uni-directional p2p ICI bandwidth (GB/s) via the
    pair-exchange pattern (comm/p2p.py ≙ peer2pear.cpp's headline number);
    vs_baseline = measured / (0.9 * per-link ICI spec).
  * 1 device: on-chip HBM copy bandwidth (GB/s) via the Pallas one-sided
    local put (comm/onesided.py); a DMA copy reads + writes HBM, so
    vs_baseline = 2 * measured / (0.9 * HBM spec) — the fraction of the
    90%-of-spec target actually achieved.

All pattern chatter goes to stderr; stdout carries only the JSON line.
"""

from __future__ import annotations

import json
import os
import sys

def _spec_tables():
    # Single source: runtime.py owns the chip spec tables (the bandwidth
    # plausibility gate in comm/onesided.py reads the same numbers).
    from tpu_patterns.runtime import HBM_SPEC_GBPS, ICI_SPEC_PER_LINK_GBPS

    return HBM_SPEC_GBPS, ICI_SPEC_PER_LINK_GBPS


# Quick-pass workload: enough elements (~4.7 MB f32) for a meaningful DMA
# number in seconds; clamped so the env tier (TPU_PATTERNS_COUNT) can only
# shrink it further.
QUICK_COUNT = 1179648


def _quick_cfg(cls, **overrides):
    """Config for the provisional pass: env-clamped size, minimal reps."""
    import dataclasses

    from tpu_patterns.core.config import config_from_tiers

    base = config_from_tiers(cls, argv=[])
    return dataclasses.replace(
        base, count=min(base.count, QUICK_COUNT), reps=2, warmup=1,
        **overrides,
    )


def _spec(table: dict[str, float], device_kind: str) -> float | None:
    # one shared matcher for every chip-keyed table (HBM/ICI here, the
    # TFLOP/s peak gate in runtime.py)
    from tpu_patterns.runtime import match_device_spec

    return match_device_spec(table, device_kind)


def run(quick: bool = False) -> dict:
    """One measurement pass.

    ``quick=True`` shrinks the workload (~5 MB, 2 reps, single kernel
    schedule) so a number lands in seconds; the child emits it as a
    provisional line before the full-size pass, and the watchdog parent
    salvages it if the full pass hangs mid-run — the failure mode observed
    on the axon tunnel is a hang *after* a clean preflight, which
    previously zeroed the whole artifact.
    """
    import numpy as np

    import jax

    from tpu_patterns.core.config import config_from_tiers
    from tpu_patterns.core.results import ResultWriter
    from tpu_patterns.runtime import setup_jax

    setup_jax()
    devs = jax.devices()
    kind = getattr(devs[0], "device_kind", devs[0].platform)
    writer = ResultWriter(stream=sys.stderr)

    if len(devs) >= 2 and len(devs) % 2 == 0:
        from jax.sharding import Mesh

        from tpu_patterns.comm.p2p import P2PConfig, run_p2p

        mesh = Mesh(np.array(devs), ("x",))
        # env tier applies (e.g. TPU_PATTERNS_COUNT shrinks CI workloads)
        if quick:
            cfg = _quick_cfg(P2PConfig, bidirectional=False)
        else:
            cfg = config_from_tiers(P2PConfig, argv=[], reps=5, warmup=2)
        recs = run_p2p(mesh, cfg, writer=writer)
        uni = next(r for r in recs if r.mode == "unidirectional")
        # Per-pair rate: the baseline ("ICI bandwidth >= 90% of spec") is
        # per-LINK, so the aggregate over concurrent pairs must not be
        # compared against one link's spec (inflated num_pairs/1-fold).
        value = uni.metrics.get(
            "bandwidth_GBps_per_pair", uni.metrics["bandwidth_GBps"]
        )
        spec = _spec(_spec_tables()[1], kind)
        vs = value / (0.9 * spec) if spec else 0.0
        return {
            "metric": f"p2p_ici_bandwidth_{len(devs)}x_{kind.replace(' ', '_')}",
            "value": round(value, 3),
            "unit": "GB/s",
            "vs_baseline": round(vs, 4),
        }

    from tpu_patterns.comm.onesided import OneSidedConfig, run_onesided

    if quick:
        # one schedule only: measuring both doubles compile time, and the
        # provisional number just needs to exist, not to be the winner
        cfg = _quick_cfg(OneSidedConfig, kernel="streamed")
    else:
        cfg = config_from_tiers(OneSidedConfig, argv=[], reps=5, warmup=2)
    (rec,) = run_onesided(None, cfg, writer=writer)
    value = rec.metrics["bandwidth_GBps"]  # bytes copied / time
    spec = _spec(_spec_tables()[0], kind)
    if spec and not rec.metrics.get("hbm_plausible", 1.0):
        # A shrunken buffer (quick tier, or an env-tier-clamped full pass)
        # can stay VMEM-resident — measured live: 4.7 MB "copying" at
        # 103 TB/s.  A number that never touched HBM must not become the
        # headline in ANY pass; raising turns it into a bench_error line
        # (full pass) or a skipped provisional (quick pass).
        raise RuntimeError(
            f"copy rate {value:.0f} GB/s implies {2 * value:.0f} GB/s of "
            f"HBM traffic, above the {spec:.0f} GB/s spec — buffer "
            "resident in a faster tier; discarding measurement"
        )
    vs = (2.0 * value) / (0.9 * spec) if spec else 0.0  # DMA = read + write
    return {
        "metric": f"hbm_copy_bandwidth_{kind.replace(' ', '_')}",
        "value": round(value, 3),
        "unit": "GB/s",
        "vs_baseline": round(vs, 4),
    }


def last_metric_line(text: str) -> str | None:
    """Last stdout line that parses as a driver-schema record.

    Skips non-JSON chatter AND schema-less parseables — a stray scalar
    from a crashing child must not become the headline.
    """
    for line in reversed(text.strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            return line
    return None


def banked_fallback(error_msg: str, search_dir: str | None = None) -> str | None:
    """Driver-schema line from the newest banked in-window bench result.

    A dead tunnel at snapshot time must not erase a same-round live
    capture: BENCH_r04 said ``bench_error`` while 335.556 GB/s from that
    round's 31-minute window sat in ``docs/measured/r4live/``.  The
    capture ladder banks every bench pass as ``bench_{pre,post}_*.json``;
    when the live measurement fails, the newest banked NUMBER is emitted
    instead — with explicit staleness provenance (``stale``,
    ``captured_at``, ``capture_commit``) plus the live failure detail, so
    a stale number can never read as a clean live run (the reference's
    contract is number-plus-verdict, never verdict-alone:
    /root/reference/concurency/main.cpp:270,321).

    Returns ``None`` when no banked record exists (the caller falls back
    to the plain error line).  ``TPU_PATTERNS_BENCH_BANKED`` overrides the
    search root (set it to an empty/missing dir to disable).
    """
    import datetime
    import glob
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    root = search_dir if search_dir is not None else os.environ.get(
        "TPU_PATTERNS_BENCH_BANKED", os.path.join(repo, "docs", "measured")
    )
    if not root:  # TPU_PATTERNS_BENCH_BANKED="" means disabled, not cwd
        return None

    def capture_ts(path: str) -> float:
        # The ladder stamps filenames bench_{pre,post}_YYYYmmdd_HHMMSS —
        # the authoritative capture time (git checkouts reset mtimes, so
        # a clone would otherwise date every banked record "today" and
        # order same-tier records arbitrarily).  Stamps are UTC by
        # contract: the r5+ ladder uses `date -u`, and the r4 files were
        # stamped on a UTC host; a hand-placed file stamped in another
        # timezone would carry that offset into captured_at.  mtime is
        # the fallback for stamp-less files.
        stem = os.path.splitext(os.path.basename(path))[0]
        try:
            stamp = datetime.datetime.strptime(
                "_".join(stem.split("_")[-2:]), "%Y%m%d_%H%M%S"
            )
            return stamp.replace(tzinfo=datetime.timezone.utc).timestamp()
        except ValueError:
            return os.path.getmtime(path)

    candidates = []  # (clean, capture_ts, rec, path)
    for path in glob.glob(os.path.join(root, "**", "bench_*.json"),
                          recursive=True):
        try:
            with open(path) as f:
                line = last_metric_line(f.read())
            ts = capture_ts(path)
        except OSError:  # deleted mid-scan (ladder rotating files)
            continue
        except UnicodeDecodeError:  # truncated by a SIGKILLed ladder stage
            continue
        if line is None:
            continue
        rec = json.loads(line)
        value = rec.get("value")
        if (
            rec.get("metric") == "bench_error"
            or rec.get("stale")  # never chain stale-on-stale provenance
            or not isinstance(value, (int, float))
            or not value > 0
        ):
            continue
        # a clean record beats a salvaged one (quick-pass / teardown-hang
        # lines carry an "error" annotation); within a tier, newest wins
        candidates.append(("error" not in rec, ts, rec, path))
    if not candidates:
        return None
    clean, ts, rec, path = max(candidates, key=lambda c: (c[0], c[1]))
    if "error" in rec:
        rec["banked_error"] = rec.pop("error")
    rec["stale"] = True
    rec["captured_at"] = datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).isoformat(timespec="seconds")
    rec["capture_file"] = os.path.relpath(path, repo)
    try:
        commit = subprocess.run(
            ["git", "log", "-1", "--format=%H", "--", path],
            cwd=repo, capture_output=True, text=True, timeout=10,
        ).stdout.strip()
    except (OSError, subprocess.SubprocessError):
        commit = ""
    rec["capture_commit"] = commit or "uncommitted"
    rec["error"] = error_msg
    return json.dumps(rec)


def _child_main() -> int:
    # Provisional quick pass first (seconds): its line is salvaged by the
    # parent if the full-size pass below hangs.  The parent forwards only
    # the LAST parseable line, so a completed full pass supersedes it.
    # Only under the watchdog parent (_TPU_PATTERNS_BENCH_CHILD): with the
    # watchdog disabled nothing filters stdout, and the driver contract is
    # exactly ONE line.
    if os.environ.get("_TPU_PATTERNS_BENCH_CHILD") and os.environ.get(
        "TPU_PATTERNS_BENCH_QUICK", "1"
    ) != "0":
        try:
            out = dict(run(quick=True), stage="quick")
            print(json.dumps(out), flush=True)
        except Exception as e:
            print(
                f"# quick pass failed ({type(e).__name__}: {e}); "
                "continuing to full pass",
                file=sys.stderr,
                flush=True,
            )
    try:
        out = run()
    except Exception as e:  # never die silently: the driver needs its line
        out = {
            "metric": "bench_error",
            "value": 0.0,
            "unit": "",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }
    print(json.dumps(out), flush=True)
    return 0


def _preflight_main() -> int:
    """Touch the device: backend init + one tiny compiled op.

    Runs in a short-deadline child so a hung device tunnel (native hang in
    backend init, uninterruptible from Python) is detected in seconds and
    can be retried, instead of eating the whole measurement budget — the
    round-1 failure mode where one dead tunnel zeroed the round's perf
    evidence.
    """
    from tpu_patterns.runtime import setup_jax

    setup_jax()  # persistent compile cache — warm preflights cost seconds

    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    x = jnp.ones((128, 128), jnp.float32)
    jax.block_until_ready(jnp.dot(x, x))
    print(
        f"preflight_ok {getattr(devs[0], 'device_kind', devs[0].platform)}",
        flush=True,
    )
    return 0


def _server_main() -> int:
    """Warm bench server: preflight, then measure IN THE SAME PROCESS.

    The old ladder paid cold JAX init three times — once per preflight
    attempt, once for the measurement child — and the round-5 outage
    JSON shows both preflight attempts timing out at exactly the 60 s
    boundary: the init tax alone ate the deadline.  Here one child does
    the preflight and then waits on stdin; the parent's ``run`` line
    starts the measurement on the already-initialized backend, so a
    clean preflight's init is never re-paid (the sweep engine's warm-
    worker idea, applied to the bench).
    """
    try:
        rc = _preflight_main()
    except Exception as e:
        print(f"# preflight error: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        return 1
    if rc != 0:
        return rc
    line = sys.stdin.readline()
    if line.strip() != "run":
        return 0  # parent went away / declined: exit quietly
    return _child_main()


def main() -> int:
    """Watchdog wrapper: the measurement runs in a child process.

    A dead device tunnel hangs inside native PJRT code with the GIL held —
    no Python exception, and SIGALRM handlers never run — so the only
    reliable timeout is a parent that can SIGKILL.  Without it the driver
    would wait on this process forever instead of reading its line.

    The child is ONE warm server (``_server_main``): preflight and
    measurement share a process, so the init a clean preflight paid is
    reused by the measurement instead of being paid again — the round-5
    outage shape (both preflight attempts timing out at exactly the 60 s
    boundary) was the cold-init tax, not the device.
    """
    import subprocess

    if os.environ.get("_TPU_PATTERNS_BENCH_SERVER"):
        return _server_main()
    if os.environ.get("_TPU_PATTERNS_BENCH_CHILD"):
        return _child_main()
    if os.environ.get("_TPU_PATTERNS_BENCH_PREFLIGHT"):
        # standalone device-probe mode: the warm-server flow above made
        # this parent-internal path obsolete, but capture ladders can
        # still invoke it directly as a cheap is-the-tunnel-up check
        return _preflight_main()
    try:
        timeout_s = int(os.environ.get("TPU_PATTERNS_BENCH_TIMEOUT", "900"))
    except ValueError:
        timeout_s = 900
    if timeout_s <= 0:
        return _child_main()
    try:
        preflight_s = int(os.environ.get("TPU_PATTERNS_BENCH_PREFLIGHT", "60"))
    except ValueError:
        preflight_s = 60

    def error_line(msg: str) -> str:
        return json.dumps(
            {
                "metric": "bench_error",
                "value": 0.0,
                "unit": "",
                "vs_baseline": 0.0,
                "error": msg,
            }
        )

    def run_child(
        flag: str, deadline: int
    ) -> tuple[subprocess.CompletedProcess | None, str]:
        """(proc, stdout-so-far); proc is None on timeout (child SIGKILLed).

        The partial stdout matters: the measurement child prints a
        provisional quick-pass line before the full-size pass, so a hang
        mid-measurement still leaves a salvageable numeric headline.
        """
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=dict(os.environ, **{flag: "1"}),
                stdout=subprocess.PIPE,
                text=True,
                timeout=deadline,
            )
            return proc, proc.stdout or ""
        except subprocess.TimeoutExpired as e:
            partial = e.stdout or ""
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            return None, partial

    def annotate_salvaged(line: str, quick_msg: str, full_msg: str) -> str:
        """Mark a salvaged line so it never reads as a clean run; a line
        already carrying structured error detail (a child bench_error
        printed before the hang/crash) passes through verbatim."""
        rec = json.loads(line)
        if "error" not in rec:
            rec["error"] = (
                quick_msg if rec.get("stage") == "quick" else full_msg
            )
            return json.dumps(rec)
        return line

    if preflight_s > 0:
        # Warm-server flow: spawn ONE child that preflights then waits
        # for "run".  Each preflight attempt costs at most preflight_s
        # (a hung tunnel is reported in ~2*preflight_s with a
        # distinguishable error, a transient hang is absorbed by the
        # retry) — and a PASSING preflight's backend init is reused by
        # the measurement instead of re-paid by a second cold child.
        import signal
        import threading
        import time

        # deliberately NOT exec/proc.kill_process_group: the parent half
        # of bench.py must run standalone from any cwd with tpu_patterns
        # unimportable (the fake-repo harness test exercises exactly
        # that) — only the measurement children import the package
        def kill_server(proc) -> None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                proc.kill()

        def spawn_server():
            proc = subprocess.Popen(
                # -u: the preflight_ok / provisional lines must cross
                # the pipe live, not sit in a block buffer past deadlines
                [sys.executable, "-u", os.path.abspath(__file__)],
                env=dict(
                    os.environ,
                    _TPU_PATTERNS_BENCH_SERVER="1",
                    _TPU_PATTERNS_BENCH_CHILD="1",
                ),
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                text=True,
                start_new_session=True,
            )
            lines: list[str] = []
            seen = threading.Event()
            eof = threading.Event()

            def read():
                for ln in proc.stdout:
                    lines.append(ln)
                    if "preflight_ok" in ln:
                        seen.set()
                eof.set()

            threading.Thread(target=read, daemon=True).start()
            return proc, lines, seen, eof

        server = None
        for attempt in (1, 2):
            proc, lines, seen, eof = spawn_server()
            deadline = time.monotonic() + preflight_s
            status = "timeout"
            while time.monotonic() < deadline:
                if seen.wait(timeout=0.2):
                    status = "ok"
                    break
                if eof.is_set() or proc.poll() is not None:
                    status = f"rc={proc.poll()}"
                    break
            if status == "ok":
                server = (proc, lines, eof)
                break
            kill_server(proc)
            print(
                f"# preflight attempt {attempt} failed ({status})",
                file=sys.stderr,
                flush=True,
            )
        if server is None:
            msg = (
                f"preflight failed twice within {preflight_s}s each: "
                "device backend unreachable (hung tunnel?)"
            )
            print(banked_fallback(msg) or error_line(msg), flush=True)
            return 0
        proc, lines, eof = server
        try:
            proc.stdin.write("run\n")
            proc.stdin.flush()
        except OSError:
            pass  # died after preflight: surfaces as child-exit below
        try:
            proc.wait(timeout=timeout_s)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            kill_server(proc)
            rc = None
        eof.wait(timeout=30)  # reader drains the pipe after exit/kill
        stdout = "".join(lines)
    else:
        # preflight disabled: the legacy single measurement child
        proc, stdout = run_child("_TPU_PATTERNS_BENCH_CHILD", timeout_s)
        rc = None if proc is None else proc.returncode

    salvaged = last_metric_line(stdout)
    if rc is None:
        if salvaged is not None:
            # a measurement landed before the hang — a real number beats
            # an error line.  Distinguish a salvaged small-workload quick
            # pass from a full measurement whose process hung at teardown.
            out = annotate_salvaged(
                salvaged,
                f"full-size pass exceeded {timeout_s}s; provisional "
                "quick-pass measurement salvaged",
                f"child hung past {timeout_s}s after completing the "
                "full measurement (teardown hang); result salvaged",
            )
        else:
            out = error_line(
                f"bench exceeded {timeout_s}s after a clean preflight "
                "(hang during measurement)"
            )
    else:
        # Forward the child's last parseable stdout line verbatim
        # regardless of exit code — _child_main prints a well-formed
        # bench_error line on failure and exits nonzero via native
        # crashes only; truncating it would lose the structured detail.
        out = salvaged
        if out is None:
            tail = stdout.strip().splitlines()
            out = error_line(
                f"child exited {rc}; last output "
                f"{tail[-1][:120] if tail else '<none>'!r}"
            )
        elif rc != 0:
            # native crash after the last good line: never present a
            # salvaged (possibly quick-pass) number as a clean run
            out = annotate_salvaged(
                out,
                f"child exited {rc} after this line; "
                "provisional quick-pass measurement salvaged",
                f"child exited {rc} after this line; "
                "crash after measurement; result salvaged",
            )
    # Any error-only outcome (hang with nothing salvaged, child crash
    # without a line, or a child-reported measurement error) defers to a
    # banked in-window number before shipping an empty record.
    rec = json.loads(out)
    if rec.get("metric") == "bench_error":
        out = banked_fallback(rec.get("error", "bench_error")) or out
    print(out, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
