"""The FMA busy-wait kernel: a pure-compute knob with linear runtime.

Reference: concurency/bench.hpp:7-31 — a MAD_4/MAD_16/MAD_64 macro ladder;
each work-item performs ``64 * tripcount`` fused multiply-adds, giving a
device busy-loop whose duration scales linearly with ``tripcount``.

Two implementations with identical FLOP counts:
* ``busy_wait_pallas`` — Mosaic kernel, the native-device-code parity path
  (the FMAs run on the VPU out of VMEM, blocked (8, 128) to match the
  native tile);
* ``busy_wait_xla``    — plain ``lax.fori_loop`` version, the calibration
  reference (SURVEY.md C10) and the portable fallback.

The iteration ``x = x*a + b`` with a<1 contracts toward b/(1-a), so values
stay finite and nonzero for any tripcount — the result must stay
data-dependent or XLA would fold the loop away.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_A = 0.999999
_B = 1e-6
FMAS_PER_TRIP = 64  # ≙ MAD_64 (bench.hpp:24-26)


def _mad64(x):
    # 64 unrolled FMAs per trip, the MAD_64 ladder flattened at trace time.
    for _ in range(FMAS_PER_TRIP):
        x = x * _A + _B
    return x


def _busy_wait_body(tripcount: int, x):
    return lax.fori_loop(0, tripcount, lambda _, v: _mad64(v), x)


def busy_wait_xla(x: jax.Array, tripcount: int) -> jax.Array:
    """Pure-XLA busy wait: 64*tripcount FMAs per element."""
    return _busy_wait_body(tripcount, x)


def _busy_wait_kernel(tripcount: int, x_ref, o_ref):
    o_ref[...] = _busy_wait_body(tripcount, x_ref[...])


def busy_wait_pallas(
    x: jax.Array, tripcount: int, interpret: bool = False
) -> jax.Array:
    """Pallas busy wait; input must be 2-D with a 128-multiple minor dim."""
    rows, cols = x.shape
    block_rows = 8 if rows % 8 == 0 else rows
    grid = (rows // block_rows,)
    return pl.pallas_call(
        functools.partial(_busy_wait_kernel, tripcount),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[pl.BlockSpec((block_rows, cols), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, cols), lambda i: (i, 0)),
        interpret=interpret,
    )(x)


def flops(n_elements: int, tripcount: int) -> int:
    """2 FLOPs per FMA."""
    return 2 * FMAS_PER_TRIP * tripcount * n_elements
