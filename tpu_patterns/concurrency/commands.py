"""The command language: ``C`` (compute) and ``X2Y`` copies over M/D/H/S.

Reference: concurency/main.cpp:84-89 defines the one-letter memory taxonomy
— M(host malloc), D(device), H(pinned host), S(shared/USM) — and commands
are either ``C`` (busy-wait kernel) or ``X2Y`` (copy from kind X to kind Y),
given as repeated groups ``--commands "C M2D" ...`` (:143-196).

TPU mapping of the taxonomy (probed from PJRT memory kinds):
  M -> host numpy, outside the runtime     (pageable host, eager path only)
  D -> ``device`` memory kind              (HBM)
  H -> ``pinned_host`` memory kind         (DMA-able host, jit-addressable)
  S -> ``unpinned_host`` memory kind       (host memory the device can reach
                                            lazily — the USM-shared analogue)

D/H/S copies compile into the program (device_put with a memory-kind
sharding); M copies are host-runtime calls, so backends that time inside one
compiled program reject them (validate_mode analogue, bench_omp.cpp:15-19).
"""

from __future__ import annotations

import dataclasses
import enum
import re

import jax
import numpy as np


class MemKind(enum.Enum):
    M = "host_malloc"  # pageable host numpy
    D = "device"  # HBM
    H = "pinned_host"
    S = "unpinned_host"  # shared/USM analogue


@dataclasses.dataclass
class Command:
    """One parsed command with its workload knobs (auto-tunable)."""

    text: str  # canonical text, e.g. "C" or "H2D"
    kind: str  # "compute" | "copy"
    src: MemKind | None = None
    dst: MemKind | None = None
    tripcount: int = 40_000  # compute knob (ref default, main.cpp:99)
    elements: int = 1024  # compute buffer elements (rows*128)
    copy_elements: int = 1 << 22  # copy buffer elements

    @property
    def bytes(self) -> int:
        n = self.elements if self.kind == "compute" else self.copy_elements
        return 4 * n  # float32 buffers throughout, as the reference

    # Tuning caps: the linear rescale must not explode a fast command into
    # an absurd workload (a VMEM-resident copy is ~1000x faster than HBM, so
    # matching a long compute would otherwise demand GB-scale buffers).
    MAX_TRIPCOUNT = 10_000_000
    MAX_COPY_ELEMENTS = 1 << 25  # 128 MiB float32

    def scaled(self, factor: float) -> "Command":
        """Linear workload rescale (≙ commands_to_parameters_tunned,
        main.cpp:248-257): compute scales tripcount, copies scale size."""
        c = dataclasses.replace(self)
        if self.kind == "compute":
            c.tripcount = min(
                self.MAX_TRIPCOUNT, max(1, int(round(self.tripcount * factor)))
            )
        else:
            # keep the (rows, 128) layout: round to 128-element multiples
            c.copy_elements = min(
                self.MAX_COPY_ELEMENTS,
                max(128, 128 * int(round(self.copy_elements * factor / 128))),
            )
        return c


_COPY_RE = re.compile(r"^([MDHS])2([MDHS])$")


def parse_command(tok: str) -> Command:
    """≙ sanitize_command (main.cpp:14-19): 'C' or 'X2Y' over {M,D,H,S}."""
    tok = tok.strip().upper()
    if tok == "C":
        return Command(text="C", kind="compute")
    m = _COPY_RE.match(tok)
    if not m:
        raise ValueError(
            f"bad command {tok!r}: expected 'C' or 'X2Y' with X,Y in M/D/H/S "
            "(e.g. 'M2D', 'H2D', 'D2S')"
        )
    src, dst = MemKind[m.group(1)], MemKind[m.group(2)]
    if src is dst and src is not MemKind.D:
        # D2D (HBM->HBM DMA) is a real on-chip transfer; same-kind host
        # copies are not a device pattern
        raise ValueError(f"copy {tok!r} has identical source and destination")
    return Command(text=tok, kind="copy", src=src, dst=dst)


def parse_group(group: str) -> list[Command]:
    """One ``--commands`` group: whitespace-separated command list."""
    cmds = [parse_command(t) for t in group.split()]
    if not cmds:
        raise ValueError("empty command group")
    return cmds


def host_sharding(kind: MemKind, device=None):
    """Sharding that pins a buffer to the given memory kind on one device."""
    from jax.sharding import SingleDeviceSharding

    device = device or jax.devices()[0]
    return SingleDeviceSharding(device, memory_kind=kind.value)


def alloc(cmd: Command, device=None, seed: int = 0):
    """Source buffer for a command, resident in its source memory kind
    (≙ per-command USM allocation, bench_sycl.cpp:54-72)."""
    rng = np.random.default_rng(seed)
    if cmd.kind == "compute":
        rows = max(1, cmd.elements // 128)
        arr = rng.random((rows, 128), dtype=np.float32)
        return jax.device_put(arr, host_sharding(MemKind.D, device))
    rows = max(1, cmd.copy_elements // 128)
    arr = rng.random((rows, 128), dtype=np.float32)
    if cmd.src is MemKind.M:
        return arr  # plain numpy: pageable host memory
    return jax.device_put(arr, host_sharding(cmd.src, device))
