"""Concurrency backends: XLA dispatch and Pallas explicit-DMA overlap.

The reference measures the same question through two runtimes (SURVEY.md
C9a/C9b): OpenMP offload (serial | host_threads | nowait modes,
bench_omp.cpp:21-143) and SYCL (serial | in_order | out_of_order queues,
bench_sycl.cpp:19-144), both behind one ``bench()`` extern interface
(bench.hpp:37-40).

TPU equivalents:
* ``XLABackend`` — commands compiled into ONE program; "serial" forces a
  sequential schedule by threading ``lax.optimization_barrier`` between
  commands (the XLA analogue of an in-order queue), "concurrent" leaves
  them independent so XLA's scheduler may overlap them (out-of-order
  queue).  ``dispatch_serial``/``dispatch_async`` run each command as its
  own dispatched program, blocking after each vs once at the end — the
  direct analogue of per-queue wait vs nowait+taskwait; host-timed, so
  only meaningful where host timing is (DIRECT mode platforms).
* ``PallasBackend`` — one Mosaic kernel per group; copies become explicit
  async DMAs, compute runs on the VPU; "dma_serial" waits each DMA before
  compute, "dma_overlap" starts DMAs, computes while they fly, then waits
  — the in-kernel copy-engine/compute overlap the reference probes with
  separate queues.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_patterns.concurrency.commands import Command, MemKind, alloc, host_sharding
from tpu_patterns.concurrency.kernels import busy_wait_pallas, busy_wait_xla
from tpu_patterns.runtime import use_interpret


def _use_pallas_kernel() -> bool:
    return not use_interpret()


@dataclasses.dataclass
class BuiltGroup:
    """What a backend hands the harness for one command group x mode."""

    build_chain: Callable[[int], Callable[[], object]]  # for measure_chain
    direct_fn: Callable[[], object]  # plain run, host-fenced
    n_bytes_per_iter: int
    cmd_bytes: list[int] = dataclasses.field(default_factory=list)
    # bytes each command moves per measured iteration, in input order
    # (copies chained as round trips count both directions)


class XLABackend:
    name = "xla"
    modes = ("serial", "concurrent", "dispatch_serial", "dispatch_async")

    def solo_mode(self, mode: str) -> str:
        """Mode used for single-command serial probes: must share the
        group's execution path (in-program vs dispatched) so M commands
        stay legal and times stay comparable."""
        return "dispatch_serial" if mode.startswith("dispatch") else "serial"

    def validate(self, mode: str, cmds: Sequence[Command]) -> None:
        """≙ validate_mode (bench_omp.cpp:15-19 / bench_sycl.cpp:14-17)."""
        if mode not in self.modes:
            raise ValueError(f"backend {self.name}: unknown mode {mode!r}; "
                             f"modes: {self.modes}")
        if not mode.startswith("dispatch"):
            bad = [c.text for c in cmds if MemKind.M in (c.src, c.dst)]
            if bad:
                raise ValueError(
                    f"commands {bad} touch pageable host memory (M), which "
                    "cannot live inside a compiled program; use the "
                    "dispatch_* modes or the S (unpinned_host) kind"
                )
        if any(c.kind == "copy" and c.src is c.dst for c in cmds):
            raise ValueError(
                "D2D under the xla backend would be elided by the compiler "
                "(same memory space); use the pallas backend, whose explicit "
                "DMA materializes the copy"
            )

    # -- single command as a traced computation ---------------------------

    def _apply(self, cmd: Command, buf):
        """One-way application (eager/dispatch path)."""
        if cmd.kind == "compute":
            if _use_pallas_kernel():
                return busy_wait_pallas(buf, cmd.tripcount)
            return busy_wait_xla(buf, cmd.tripcount)
        return jax.device_put(buf, host_sharding(cmd.dst))

    def _step(self, cmd: Command, buf):
        """One measured unit whose OUTPUT feeds the next iteration's input
        — a genuine loop-carried data dependence, which is the only thing
        that stops XLA from hoisting the work out of the chain loop
        (scheduling-only barriers get elided; measured empirically).
        Compute feeds through directly; copies chain as round trips
        (X2Y then Y2X), so a copy command moves 2x its bytes per iteration
        — the reference's sweep mixes are round-trip pairs anyway
        ("M2D D2M", "H2D D2H", run_omp.sh:9).
        """
        if cmd.kind == "compute":
            if _use_pallas_kernel():
                return busy_wait_pallas(buf, cmd.tripcount)
            return busy_wait_xla(buf, cmd.tripcount)
        out = jax.device_put(buf, host_sharding(cmd.dst))
        return jax.device_put(out, host_sharding(cmd.src))

    def _force_scalar(self, outs):
        # One small data-dependent scalar; host-kind outputs are pulled to
        # device once at the chain tail (fixed cost, cancels in differential
        # timing).
        parts = []
        for o in outs:
            od = jax.device_put(o, jax.memory.Space.Device)
            parts.append(jnp.sum(od[..., :1, :1]))
        return jnp.stack(parts).sum()

    @staticmethod
    def _iter_bytes(cmd: Command) -> int:
        return cmd.bytes * (2 if cmd.kind == "copy" else 1)

    def build(self, cmds: Sequence[Command], mode: str) -> BuiltGroup:
        if mode.startswith("dispatch"):
            return self._build_dispatch(cmds, mode)
        bufs = [alloc(c, seed=i) for i, c in enumerate(cmds)]

        def group_once(ins):
            # serial: optimization_barrier orders command j after j-1's
            # output WITHIN the iteration (per-command data already chains
            # across iterations, so ordering is the barrier's only job here
            # and it cannot be elided away without reordering).
            outs = []
            prev = None
            for cmd, b in zip(cmds, ins):
                if serial and prev is not None:
                    b, _ = lax.optimization_barrier((b, prev))
                o = self._step(cmd, b)
                prev = o
                outs.append(o)
            return tuple(outs)

        serial = mode == "serial"

        # k is a traced loop bound: one compilation serves every chain
        # length the adaptive timer probes.  Outputs ARE the next inputs
        # (same shape and memory kind by construction of _step).
        @jax.jit
        def chained(k):
            ins = lax.fori_loop(0, k, lambda _, t: group_once(t), tuple(bufs))
            return self._force_scalar(ins)

        def make(k: int):
            return lambda: chained(k)

        one = jax.jit(lambda: group_once(tuple(bufs)))
        direct = lambda: jax.block_until_ready(one())  # noqa: E731
        return BuiltGroup(
            build_chain=make,
            direct_fn=direct,
            n_bytes_per_iter=sum(self._iter_bytes(c) for c in cmds),
            cmd_bytes=[self._iter_bytes(c) for c in cmds],
        )

    # -- eagerly dispatched programs --------------------------------------

    def _build_dispatch(self, cmds: Sequence[Command], mode: str) -> BuiltGroup:
        block_each = mode == "dispatch_serial"
        bufs = [alloc(c, seed=i) for i, c in enumerate(cmds)]
        fns = []
        for cmd, buf in zip(cmds, bufs):
            if cmd.kind == "copy" and cmd.src is MemKind.M:
                # pageable host -> device: a runtime transfer, like the
                # reference's H2D `target update to` from malloc'd memory
                fns.append(functools.partial(
                    jax.device_put, buf, host_sharding(cmd.dst)))
            elif cmd.kind == "copy" and cmd.dst is MemKind.M:
                dev_buf = jax.device_put(buf, host_sharding(cmd.src))
                fns.append(functools.partial(np.asarray, dev_buf))
            else:
                jitted = jax.jit(functools.partial(self._apply, cmd))
                fns.append(functools.partial(jitted, buf))

        def run_once():
            outs = []
            for f in fns:
                o = f()
                if block_each:
                    o = jax.block_until_ready(o)
                outs.append(o)
            return jax.block_until_ready(outs)

        def make(k: int):
            def run_k():
                out = None
                for _ in range(k):
                    out = run_once()
                return out

            return run_k

        return BuiltGroup(
            build_chain=make,
            direct_fn=run_once,
            n_bytes_per_iter=sum(c.bytes for c in cmds),
            cmd_bytes=[c.bytes for c in cmds],
        )


class PallasBackend:
    name = "pallas"
    modes = ("dma_serial", "dma_overlap")

    def solo_mode(self, mode: str) -> str:
        return "dma_serial"

    def validate(self, mode: str, cmds: Sequence[Command]) -> None:
        if mode not in self.modes:
            raise ValueError(f"backend {self.name}: unknown mode {mode!r}; "
                             f"modes: {self.modes}")
        for c in cmds:
            if c.kind == "copy" and not (c.src is MemKind.D and c.dst is MemKind.D):
                raise ValueError(
                    f"pallas backend overlaps on-chip DMA with compute; "
                    f"command {c.text!r} is not a D2D copy (Mosaic kernels "
                    "cannot address host memory kinds)"
                )

    def build(self, cmds: Sequence[Command], mode: str) -> BuiltGroup:
        overlap = mode == "dma_overlap"
        copies = [c for c in cmds if c.kind == "copy"]
        computes = [c for c in cmds if c.kind == "compute"]
        copy_bufs = [alloc(c, seed=10 + i) for i, c in enumerate(copies)]
        comp_bufs = [alloc(c, seed=20 + i) for i, c in enumerate(computes)]
        interpret = use_interpret()

        n_copy = len(copies)

        n_comp = len(computes)

        def kernel(*refs):
            # ref order: in_refs (copy_srcs, comp_ins), out_refs (copy_dsts,
            # comp_outs), scratch (sems)
            copy_srcs = refs[0:n_copy]
            comp_ins = refs[n_copy : n_copy + n_comp]
            copy_dsts = refs[n_copy + n_comp : 2 * n_copy + n_comp]
            comp_outs = refs[2 * n_copy + n_comp : 2 * n_copy + 2 * n_comp]
            sems = refs[-1]
            dmas = [
                pltpu.make_async_copy(src, dst, sems.at[i])
                for i, (src, dst) in enumerate(zip(copy_srcs, copy_dsts))
            ]
            if overlap:
                for d in dmas:
                    d.start()
                for cmd, i_ref, o_ref in zip(computes, comp_ins, comp_outs):
                    o_ref[...] = busy_wait_xla(i_ref[...], cmd.tripcount)
                for d in dmas:
                    d.wait()
            else:
                for d in dmas:
                    d.start()
                    d.wait()
                for cmd, i_ref, o_ref in zip(computes, comp_ins, comp_outs):
                    o_ref[...] = busy_wait_xla(i_ref[...], cmd.tripcount)

        in_specs = [pl.BlockSpec(memory_space=pl.ANY)] * n_copy + [
            pl.BlockSpec(memory_space=pltpu.VMEM)
        ] * len(computes)
        out_specs = [pl.BlockSpec(memory_space=pl.ANY)] * n_copy + [
            pl.BlockSpec(memory_space=pltpu.VMEM)
        ] * len(computes)
        out_shape = [jax.ShapeDtypeStruct(b.shape, b.dtype) for b in copy_bufs] + [
            jax.ShapeDtypeStruct(b.shape, b.dtype) for b in comp_bufs
        ]

        call = pl.pallas_call(
            kernel,
            out_shape=tuple(out_shape),
            in_specs=in_specs,
            out_specs=tuple(out_specs),
            scratch_shapes=[pltpu.SemaphoreType.DMA((max(n_copy, 1),))],
            interpret=interpret,
        )

        args = tuple(copy_bufs) + tuple(comp_bufs)

        @jax.jit
        def chained(k):
            def body(_, ins):
                # outputs mirror inputs (copy dsts + compute outs, same
                # shapes), so they feed the next iteration directly: true
                # data chaining
                return call(*ins)

            ins = lax.fori_loop(0, k, body, args)
            return jnp.stack([jnp.sum(o[..., :1, :1]) for o in ins]).sum()

        def make(k: int):
            return lambda: chained(k)

        one = jax.jit(lambda: call(*args))
        return BuiltGroup(
            build_chain=make,
            direct_fn=lambda: jax.block_until_ready(one()),
            n_bytes_per_iter=sum(c.bytes for c in cmds),
            cmd_bytes=[c.bytes for c in cmds],
        )


BACKENDS = {b.name: b for b in (XLABackend(), PallasBackend())}


def get_backend(name: str):
    try:
        return BACKENDS[name]
    except KeyError:
        raise KeyError(
            f"unknown backend {name!r}; available: {', '.join(BACKENDS)}"
        ) from None
