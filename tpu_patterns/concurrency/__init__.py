"""Dispatch-concurrency suite (ref: concurency/ — harness, backends, kernel).

Answers the reference's question — "does submitting independent device
commands concurrently beat serial?" (concurency/README.md) — in XLA terms:
does one compiled program with *independent* ops beat the same program with
a forced sequential chain, and does an explicit Pallas kernel overlap DMA
with compute?
"""

from tpu_patterns.concurrency.kernels import busy_wait_pallas, busy_wait_xla  # noqa: F401
from tpu_patterns.concurrency.commands import (  # noqa: F401
    Command,
    MemKind,
    parse_command,
    parse_group,
)
from tpu_patterns.concurrency.backends import BACKENDS, get_backend  # noqa: F401
from tpu_patterns.concurrency.harness import (  # noqa: F401
    ConcurrencyConfig,
    run_concurrency,
)
