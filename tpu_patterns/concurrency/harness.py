"""The concurrency harness: auto-tune, serial baseline, verdict.

Reference: concurency/main.cpp:115-322 — backend-agnostic driver that
parses a mode plus repeated ``--commands`` groups (:143-196), auto-tunes
workloads so every command takes equal time via a linear rescale after a
serial probe (:226-258), measures a serial reference giving per-command
minima and the max theoretical speedup (:281-293), runs the requested
concurrent mode (:299-300), and prints a SUCCESS/FAILURE verdict: FAILURE
when the measured speedup is >30% off the theoretical maximum
(TOL_SPEEDUP=0.3, :12,:314-318) or a transfer's bandwidth is below
``--min_bandwidth`` (:36-41,:311-313); the process exit code aggregates
failures (:270,321).
"""

from __future__ import annotations

import dataclasses

from tpu_patterns.concurrency.backends import get_backend
from tpu_patterns.concurrency.commands import Command, parse_group
from tpu_patterns.core import timing
from tpu_patterns.core.results import Record, ResultWriter, Verdict

TOL_SPEEDUP = 0.3  # ≙ main.cpp:12


@dataclasses.dataclass
class ConcurrencyConfig:
    backend: str = "xla"
    mode: str = "concurrent"
    commands: tuple[str, ...] = ("C C",)  # one string per group
    reps: int = 5
    warmup: int = 1
    auto_tune: bool = True  # ≙ the :226-258 tuning pass (on unless --no_tuning)
    min_bandwidth: float = -1.0  # GB/s floor for copy commands; <0 disables
    tripcount: int = 40_000  # default compute knob (main.cpp:99)
    elements: int = 1024  # compute buffer elements
    copy_elements: int = 1 << 22  # copy buffer elements (16 MiB float32)
    chain_lengths: tuple[int, int] | None = None  # None = adaptive length


def _apply_defaults(cmds: list[Command], cfg: ConcurrencyConfig) -> list[Command]:
    """≙ get_default_command_parameter / fill defaults (main.cpp:207-214)."""
    out = []
    for c in cmds:
        c = dataclasses.replace(
            c,
            tripcount=cfg.tripcount,
            elements=cfg.elements,
            copy_elements=cfg.copy_elements,
        )
        out.append(c)
    return out


def _solo_key(cmd: Command) -> tuple:
    return (cmd.text, cmd.tripcount, cmd.elements, cmd.copy_elements)


def _measure_solo(
    backend,
    cmd: Command,
    cfg: ConcurrencyConfig,
    cache: dict[tuple, tuple[float, int, bool]] | None = None,
) -> tuple[float, int, bool]:
    """Per-command (time alone [ns], bytes per iteration, converged)
    (serial probe, main.cpp:236-238).  Cached by workload so the tuning
    probe and the serial reference don't re-measure (and re-compile) the
    unchanged slowest command."""
    key = _solo_key(cmd)
    if cache is not None and key in cache:
        return cache[key]
    built = backend.build([cmd], backend.solo_mode(cfg.mode))
    m = timing.measure_chain(
        built.build_chain,
        reps=cfg.reps,
        warmup=cfg.warmup,
        lengths=cfg.chain_lengths,
        direct_fn=built.direct_fn,
        label=f"solo:{cmd.text}",
    )
    out = (m.per_op_ns, built.cmd_bytes[0], m.converged)
    if cache is not None:
        cache[key] = out
    return out


def auto_tune(
    backend,
    cmds: list[Command],
    cfg: ConcurrencyConfig,
    writer: ResultWriter,
    solo_cache: dict[tuple, tuple[float, int, bool]] | None = None,
) -> list[Command]:
    """Linear workload rescale so all commands take ~equal time
    (≙ commands_to_parameters_tunned, main.cpp:248-257: time ∝ knob)."""
    uniq: dict[str, Command] = {}
    for c in cmds:
        uniq.setdefault(c.text, c)
    times = {t: _measure_solo(backend, c, cfg, solo_cache)[0] for t, c in uniq.items()}
    target = max(times.values())
    writer.progress(
        "auto-tune: "
        + ", ".join(f"{t}={ns / 1e3:.0f}us" for t, ns in times.items())
        + f" -> target {target / 1e3:.0f}us"
    )
    factors = {t: target / ns for t, ns in times.items()}
    tuned = [c.scaled(factors[c.text]) for c in cmds]
    capped = [
        c.text
        for c, f in zip(tuned, (factors[c.text] for c in tuned))
        if f > 1
        and (
            (c.kind == "compute" and c.tripcount >= Command.MAX_TRIPCOUNT)
            or (c.kind == "copy" and c.copy_elements >= Command.MAX_COPY_ELEMENTS)
        )
    ]
    if capped:
        writer.progress(
            f"auto-tune: {sorted(set(capped))} hit workload caps; commands "
            "stay unbalanced (theoretical speedup accounts for it)"
        )
    return tuned


def run_group(
    backend_name: str,
    group: str,
    cfg: ConcurrencyConfig,
    writer: ResultWriter,
) -> Record:
    """One command group through the full harness pipeline."""
    backend = get_backend(backend_name)
    cmds = _apply_defaults(parse_group(group), cfg)
    backend.validate(cfg.mode, cmds)

    solo_cache: dict[tuple, tuple[float, int, bool]] = {}
    if cfg.auto_tune:
        cmds = auto_tune(backend, cmds, cfg, writer, solo_cache)

    # Serial reference: per-command minima (main.cpp:281-289), measured once
    # per unique workload (identical commands share one workload after
    # tuning, and the tuning probe of the unchanged slowest command reuses).
    for c in cmds:
        _measure_solo(backend, c, cfg, solo_cache)
    solo_ns = [solo_cache[_solo_key(c)][0] for c in cmds]
    solo_bytes = [solo_cache[_solo_key(c)][1] for c in cmds]
    solo_converged = all(solo_cache[_solo_key(c)][2] for c in cmds)
    serial_total_ns = sum(solo_ns)
    # Max theoretical speedup: perfect overlap leaves the slowest command
    # (main.cpp:290-293).
    theoretical = serial_total_ns / max(solo_ns)
    imbalance = (max(solo_ns) - min(solo_ns)) / max(solo_ns)
    if imbalance > TOL_SPEEDUP:
        writer.progress(
            f"WARNING: unbalanced commands (spread {imbalance:.0%}); "
            "speedup verdict may be pessimistic"  # ≙ main.cpp:295-296
        )

    # The measured mode (main.cpp:299-300).
    built = backend.build(cmds, cfg.mode)
    m = timing.measure_chain(
        built.build_chain,
        reps=cfg.reps,
        warmup=cfg.warmup,
        lengths=cfg.chain_lengths,
        direct_fn=built.direct_fn,
        label=f"{backend_name}:{cfg.mode}",
    )
    speedup = serial_total_ns / m.per_op_ns
    ok_speedup = speedup >= theoretical / (1.0 + TOL_SPEEDUP)  # ≙ :314-318

    # Bandwidth floor per copy command from its solo time (≙ :311-313).
    notes = []
    ok_bw = True
    for c, ns, nbytes in zip(cmds, solo_ns, solo_bytes):
        if c.kind == "copy":
            gbps = nbytes / ns
            if 0 <= cfg.min_bandwidth and gbps < cfg.min_bandwidth:
                ok_bw = False
                notes.append(
                    f"{c.text}: {gbps:.2f} GB/s below floor {cfg.min_bandwidth}"
                )

    verdict = Verdict.SUCCESS if (ok_speedup and ok_bw) else Verdict.FAILURE
    if not ok_speedup:
        notes.append(
            f"speedup {speedup:.2f} < theoretical {theoretical:.2f} / "
            f"{1 + TOL_SPEEDUP}"
        )
    writer.metric(f"{cfg.mode} [{group}] speedup", speedup,
                  f"(theoretical {theoretical:.2f})")
    rec = Record(
        pattern="concurrency",
        mode=f"{backend_name}:{cfg.mode}",
        commands=group,
        metrics={
            "speedup": speedup,
            "theoretical_speedup": theoretical,
            "serial_total_us": serial_total_ns / 1e3,
            "mode_us": m.per_op_ns / 1e3,
            "bytes_per_iter": float(built.n_bytes_per_iter),
            "timing_converged": float(solo_converged and m.converged),
        },
        verdict=verdict,
        notes=notes,
    )
    if not (solo_converged and m.converged):
        rec.notes.append(timing.noise_bound_note("speedup"))
    return writer.record(rec)


def run_concurrency(
    cfg: ConcurrencyConfig | None = None, writer: ResultWriter | None = None
) -> list[Record]:
    """All groups (≙ the per-group loop, main.cpp:271-320)."""
    from tpu_patterns.runtime import setup_jax

    setup_jax()
    cfg = cfg or ConcurrencyConfig()
    writer = writer or ResultWriter()
    return [run_group(cfg.backend, g, cfg, writer) for g in cfg.commands]
