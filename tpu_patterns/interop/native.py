"""Build/load/register the native module (csrc/tpu_patterns_ffi.cc).

Build is lazy (make on first use, cached by mtime) so the repo carries no
binaries; registration targets the CPU platform — the C++ handlers are
host-side modules (timing core, verification, interop demos), while device
kernels are Pallas (SURVEY.md §2.2 decision).  TPU programs can still call
them through host offloading where supported.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_BUILD = os.path.abspath(os.path.join(_CSRC, "..", "build"))
_SO = os.path.join(_BUILD, "libtpu_patterns_ffi.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_registered = False
_build_error: str | None = None

HANDLERS = ("TpClockNs", "TpChecksumF32", "TpSaxpy", "TpRawInfo")
TARGETS = {
    "tp_clock_ns": "TpClockNs",
    "tp_checksum_f32": "TpChecksumF32",
    "tp_saxpy": "TpSaxpy",
    "tp_raw_info": "TpRawInfo",
}


def build_shared_object(src_name: str, so_path: str) -> str | None:
    """Lazy-build one csrc/ target: make on first use, cached by mtime.

    Passes the .so as an EXPLICIT make target so one module's build
    breakage cannot take down another's (the untargeted default builds
    everything).  Returns an error string, or None on success — the
    shared scaffolding for every native module (this FFI one,
    io/loader.py's prefetch loader).
    """
    src = os.path.join(_CSRC, src_name)
    if not os.path.exists(src):
        return f"source missing: {src}"
    if os.path.exists(so_path) and (
        os.path.getmtime(so_path) >= os.path.getmtime(src)
    ):
        return None
    try:
        proc = subprocess.run(
            ["make", "-C", _CSRC, "BUILD=" + _BUILD, so_path],
            capture_output=True,
            text=True,
            timeout=300,
        )
    except (OSError, subprocess.TimeoutExpired) as e:  # no toolchain
        return str(e)
    if proc.returncode != 0:
        return proc.stderr[-2000:]
    return None


class LazyLib:
    """Shared lazy build+dlopen scaffold for csrc/ native modules: make
    on first use, cache the CDLL (or the failure), run ``configure``
    once to set argtypes.  Third module in, the pattern graduated from
    copy-paste to this helper — new bindings (topo/native.py) use it;
    the two older modules keep their hand-rolled twins until a
    behavioral change forces the migration."""

    def __init__(self, src_name: str, so_path: str, configure):
        self._src, self._so, self._configure = src_name, so_path, configure
        self._lock = threading.Lock()
        self._lib: ctypes.CDLL | None = None
        self._error: str | None = None

    def load(self) -> ctypes.CDLL | None:
        with self._lock:
            if self._lib is not None or self._error is not None:
                return self._lib
            err = build_shared_object(self._src, self._so)
            if err is not None:
                self._error = err
                return None
            try:
                lib = ctypes.CDLL(self._so)
            except OSError as e:
                self._error = str(e)
                return None
            self._configure(lib)
            self._lib = lib
            return self._lib

    @property
    def error(self) -> str | None:
        return self._error


def _build() -> bool:
    global _build_error
    err = build_shared_object("tpu_patterns_ffi.cc", _SO)
    if err is not None:
        _build_error = err
        return False
    return True


def load() -> ctypes.CDLL | None:
    """Build if needed and dlopen; None when the toolchain is unavailable."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not _build():
            return None
        _lib = ctypes.CDLL(_SO)
        _lib.tp_clock_ns.restype = ctypes.c_uint64
        _lib.tp_clock_ns.argtypes = []
        _lib.tp_checksum_f32_direct.restype = ctypes.c_int32
        _lib.tp_checksum_f32_direct.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64
        ]
        _lib.tp_saxpy_direct.restype = None
        _lib.tp_saxpy_direct.argtypes = [
            ctypes.c_float, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_uint64,
        ]
        return _lib


def available() -> bool:
    return load() is not None


def build_error() -> str | None:
    return _build_error


def clock_ns() -> int:
    """Direct (non-XLA) native monotonic clock."""
    lib = load()
    if lib is None:
        raise RuntimeError(f"native module unavailable: {_build_error}")
    return int(lib.tp_clock_ns())


def register(platform: str = "cpu") -> bool:
    """Register every FFI handler with JAX (idempotent)."""
    global _registered
    lib = load()
    if lib is None:
        return False
    with _lock:
        if _registered:
            return True
        import jax.ffi

        for target, symbol in TARGETS.items():
            fn = getattr(lib, symbol)
            jax.ffi.register_ffi_target(
                target, jax.ffi.pycapsule(fn), platform=platform
            )
        _registered = True
        return True
