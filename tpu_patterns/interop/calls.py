"""jit-callable wrappers over the native FFI handlers.

Each wrapper is the "pointer sharing proof" of the reference's interop
suite (interop_omp_sycl.cpp:51-72 / interop_omp_ze_sycl.cpp:92-113): data
produced inside the XLA runtime (possibly by a Pallas kernel) flows into
C++ without a copy, and C++ results flow back into the compiled program.
CPU-platform handlers; call under ``jax.jit`` on the CPU backend or eagerly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpu_patterns.interop import native


def _ensure_registered():
    if not native.register():
        raise RuntimeError(
            f"native FFI module unavailable: {native.build_error()}"
        )


def ffi_clock_ns():
    """Monotonic timestamp taken inside the XLA program (C4 native clock)."""
    _ensure_registered()
    call = jax.ffi.ffi_call(
        "tp_clock_ns", jax.ShapeDtypeStruct((1,), jnp.uint64)
    )
    return call()


def ffi_checksum(x: jax.Array) -> jax.Array:
    """Wrapped-int32 checksum computed by C++ on the XLA buffer (C5)."""
    _ensure_registered()
    call = jax.ffi.ffi_call(
        "tp_checksum_f32", jax.ShapeDtypeStruct((1,), jnp.int32)
    )
    return call(x.astype(jnp.float32).reshape(-1))


def ffi_saxpy(alpha: float, x: jax.Array, y: jax.Array) -> jax.Array:
    """alpha*x + y computed by C++ zero-copy on XLA buffers (C13)."""
    _ensure_registered()
    import numpy as np

    call = jax.ffi.ffi_call("tp_saxpy", jax.ShapeDtypeStruct(x.shape, jnp.float32))
    return call(x.astype(jnp.float32), y.astype(jnp.float32),
                alpha=np.float32(alpha))


def raw_info(x: jax.Array) -> jax.Array:
    """Low-level raw-call-frame probe (C14): returns s32[8] =
    {api_major, api_minor, stage, nargs, arg0_dtype, arg0_rank,
    data_ptr_lo16, first_element_as_int}."""
    _ensure_registered()
    call = jax.ffi.ffi_call("tp_raw_info", jax.ShapeDtypeStruct((8,), jnp.int32))
    return call(x.astype(jnp.float32))
