"""jit-callable wrappers over the native FFI handlers.

Each wrapper is the "pointer sharing proof" of the reference's interop
suite (interop_omp_sycl.cpp:51-72 / interop_omp_ze_sycl.cpp:92-113): data
produced inside the XLA runtime (possibly by a Pallas kernel) flows into
C++ without a copy, and C++ results flow back into the compiled program.
CPU-platform handlers; call under ``jax.jit`` on the CPU backend or eagerly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpu_patterns.interop import native


def _ensure_registered():
    if not native.register():
        raise RuntimeError(
            f"native FFI module unavailable: {native.build_error()}"
        )


def ffi_clock_ns():
    """Monotonic timestamp taken inside the XLA program (C4 native clock)."""
    _ensure_registered()
    call = jax.ffi.ffi_call(
        "tp_clock_ns", jax.ShapeDtypeStruct((1,), jnp.uint64)
    )
    return call()


def ffi_checksum(x: jax.Array) -> jax.Array:
    """Wrapped-int32 checksum computed by C++ on the XLA buffer (C5)."""
    _ensure_registered()
    call = jax.ffi.ffi_call(
        "tp_checksum_f32", jax.ShapeDtypeStruct((1,), jnp.int32)
    )
    return call(x.astype(jnp.float32).reshape(-1))


def ffi_saxpy(alpha: float, x: jax.Array, y: jax.Array) -> jax.Array:
    """alpha*x + y computed by C++ zero-copy on XLA buffers (C13)."""
    _ensure_registered()
    import numpy as np

    call = jax.ffi.ffi_call("tp_saxpy", jax.ShapeDtypeStruct(x.shape, jnp.float32))
    return call(x.astype(jnp.float32), y.astype(jnp.float32),
                alpha=np.float32(alpha))


def raw_info(x: jax.Array) -> jax.Array:
    """Low-level raw-call-frame probe (C14): returns s32[8] =
    {api_major, api_minor, stage, nargs, arg0_dtype, arg0_rank,
    data_ptr_lo16, first_element_as_int}."""
    _ensure_registered()
    call = jax.ffi.ffi_call("tp_raw_info", jax.ShapeDtypeStruct((8,), jnp.int32))
    return call(x.astype(jnp.float32))


# ---------------------------------------------------------------------------
# Host-offload interop: the TPU-platform depth of C14.
#
# On TPU the compiled program runs in a runtime the client process does not
# share an address space with (libtpu, possibly behind a remote tunnel), so
# a client-registered custom-call handler POINTER cannot exist inside the
# program — the registration probe confirms it: ffi_call on the tpu
# platform fails at compile with an unresolved custom-call target.  The
# supported native boundary is the host-offload round trip, at two depths:
#
#   * host_checksum / host_saxpy — jax.pure_callback INSIDE the compiled
#     program: XLA inserts device->host staging for the operands, C++
#     borrows the staged buffer zero-copy, output staged back.  Works on
#     CPU and standard libtpu; remote-tunneled runtimes without host
#     send/recv support raise UNIMPLEMENTED at execute
#     (supports_host_callbacks() probes this).
#   * offload_checksum / offload_saxpy — EAGER staging through PJRT
#     transfers: explicit device->host fetch of the real device buffer,
#     zero-copy C++ call on the staged host array, device_put back.  Works
#     on every runtime (the tunnel ships buffers either way).
#
# Ownership rules (also in csrc/tpu_patterns_ffi.cc): the runtime/NumPy
# owns every buffer; C++ borrows for the call duration only — the
# ownership::keep discipline of interop_omp_ze_sycl.cpp:56-73.
# ---------------------------------------------------------------------------


def _ensure_loaded():
    if native.load() is None:
        raise RuntimeError(
            f"native module unavailable: {native.build_error()}"
        )


_callback_support: bool | None = None


def supports_host_callbacks() -> bool:
    """Whether the default backend can run host callbacks inside a compiled
    program (standard CPU/TPU runtimes: yes; some remote-tunneled PJRT
    plugins: no — they raise UNIMPLEMENTED at execute time, so probe with a
    throwaway program rather than trusting the platform name."""
    global _callback_support
    if _callback_support is None:
        import numpy as np

        try:
            out = jax.jit(
                lambda x: jax.pure_callback(
                    lambda a: np.asarray(a) + 1,
                    jax.ShapeDtypeStruct((), jnp.float32),
                    x,
                )
            )(jnp.float32(1.0))
            _callback_support = float(out) == 2.0
        except Exception:
            _callback_support = False
    return _callback_support


def _stage_to_host(x: jax.Array):
    """Explicit PJRT device->host transfer of a REAL device buffer."""
    import numpy as np

    return np.ascontiguousarray(jax.device_get(x), np.float32)


def offload_checksum(x: jax.Array) -> jax.Array:
    """Eager host-offload checksum: PJRT-stage the device buffer, C++
    reduces the staged host array zero-copy, result returns to device."""
    import numpy as np

    _ensure_loaded()
    arr = _stage_to_host(x.astype(jnp.float32).reshape(-1))
    cs = native.load().tp_checksum_f32_direct(arr.ctypes.data, arr.size)
    return jax.device_put(np.array([cs], np.int32))


def offload_saxpy(alpha: float, x: jax.Array, y: jax.Array) -> jax.Array:
    """Eager host-offload saxpy; C++ writes into the staging buffer that
    device_put then uploads — one copy each direction, none on the host."""
    import numpy as np

    _ensure_loaded()
    xa = _stage_to_host(x.astype(jnp.float32))
    ya = _stage_to_host(y.astype(jnp.float32))
    out = np.empty_like(xa)
    native.load().tp_saxpy_direct(
        float(alpha), xa.ctypes.data, ya.ctypes.data, out.ctypes.data, out.size
    )
    return jax.device_put(out)


def host_checksum(x: jax.Array) -> jax.Array:
    """Wrapped-int32 checksum via host offload — works under jit on ANY
    platform (TPU included): pure_callback stages the operand to host,
    C++ reduces it in place."""
    import numpy as np

    _ensure_loaded()

    def _cb(arr):
        arr = np.ascontiguousarray(arr, np.float32)
        lib = native.load()
        return np.array(
            [lib.tp_checksum_f32_direct(arr.ctypes.data, arr.size)], np.int32
        )

    return jax.pure_callback(
        _cb,
        jax.ShapeDtypeStruct((1,), jnp.int32),
        x.astype(jnp.float32).reshape(-1),
        vmap_method="sequential",
    )


def host_saxpy(alpha: float, x: jax.Array, y: jax.Array) -> jax.Array:
    """alpha*x + y computed by C++ on host-staged buffers (TPU-compatible
    twin of ffi_saxpy); C++ writes straight into the result array the
    runtime hands back to the device."""
    import numpy as np

    _ensure_loaded()
    alpha = float(alpha)

    def _cb(xa, ya):
        xa = np.ascontiguousarray(xa, np.float32)
        ya = np.ascontiguousarray(ya, np.float32)
        out = np.empty_like(xa)
        native.load().tp_saxpy_direct(
            alpha, xa.ctypes.data, ya.ctypes.data, out.ctypes.data, out.size
        )
        return out

    return jax.pure_callback(
        _cb,
        jax.ShapeDtypeStruct(x.shape, jnp.float32),
        x.astype(jnp.float32),
        y.astype(jnp.float32),
        vmap_method="sequential",
    )
