"""Framework <-> native C++ interop via XLA FFI (ref: sycl_omp_ze_interopt/).

The reference demonstrates two interop depths between runtimes sharing one
device context (SURVEY.md C13/C14): a high-level typed path (OpenMP 5.1
``interop`` pragma yielding SYCL objects, interop_omp_sycl.cpp:13-75) and a
low-level native-handle path (raw ze_driver/context/device extraction,
interop_omp_ze_sycl.cpp:14-117), each proving bidirectional pointer sharing.

Here the two depths are: typed C++ FFI handlers bound through
``xla::ffi::Ffi::Bind`` (high-level), and a hand-parsed raw
``XLA_FFI_CallFrame`` handler (low-level) — both registered into the same
XLA runtime the framework's jitted programs execute in, operating zero-copy
on XLA-owned buffers.
"""

from tpu_patterns.interop import native  # noqa: F401
from tpu_patterns.interop.calls import (  # noqa: F401
    ffi_checksum,
    ffi_clock_ns,
    ffi_saxpy,
    raw_info,
)
