"""Failure detection: deadline-bounded health probes of the runtime.

The suite's measurements die in characteristic ways — a dead device
tunnel hangs INSIDE native backend init with the GIL held (no Python
timeout fires), a half-alive one passes a tiny op then stalls on real
work, a missing toolchain silently disables the native modules.  The
``doctor`` subcommand turns the countermeasures bench.py grew
(subprocess probes a parent can SIGKILL, escalating workload sizes)
into a first-class diagnostic: every probe runs in a child with a hard
deadline, so the doctor itself can NEVER hang, and the report says
which layer broke — backend init, tiny compile, real compute, native
build — instead of a generic timeout.

Reference analogue: the exit-code-is-the-verdict discipline
(`/root/reference/concurency/main.cpp:270,321`) applied to the runtime
itself rather than a measurement.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys

from tpu_patterns.core.timing import clock_ns, wall_time_s


@dataclasses.dataclass
class DoctorConfig:
    """CLI ``doctor`` subcommand."""

    probe_timeout: int = 60  # per-probe deadline (s)
    # escalate to a real-workload probe (a matmul large enough to catch
    # the passes-preflight-then-hangs failure mode)
    deep: bool = True
    deep_timeout: int = 120
    # probe the sweep engine's warm-worker path: spawn one worker, wait
    # for backend-warm readiness, round-trip a ping (opt-in — it costs a
    # full JAX init, ~seconds, so the default doctor stays fast)
    workers: bool = False
    # watch mode: coalesce consecutive failing polls into ONE open/close
    # episode entry in this JSONL file instead of a line per poll (the
    # round-5 outage log was ~20 commits of per-poll noise)
    watch_jsonl: str = ""
    # hang dumps younger than this count as live evidence in the
    # watchdog probe (healthy runtime + recent dump -> WARNING verdict)
    watchdog_window_s: float = 3600.0


# Probe scripts run in children: each prints ONE json line on success.
# They test whatever backend the environment selects — with the caveat
# that site-installed platform plugins can intercept backend init even
# when JAX_PLATFORMS is set in the env, so an explicit env pin is
# re-applied IN-PROCESS via jax.config (the only override that always
# wins); with no pin, the default (production) backend is probed.
_PLATFORM_PRELUDE = """
import json, os
import jax
# monotonic timing through the suite's clock discipline; the probe must
# still run when the package itself is what broke
try:
    from tpu_patterns.core.timing import clock_ns as _clock_ns
except Exception:
    from time import perf_counter_ns as _clock_ns
try:
    # the environment the REAL runs use: TPU_PATTERNS_PLATFORM pin,
    # simulated-mesh device count, persistent compile cache
    from tpu_patterns.runtime import setup_jax
    setup_jax()
except Exception:
    pass  # package not importable in this child: pin below still applies
# setup_jax honors only TPU_PATTERNS_PLATFORM; a bare JAX_PLATFORMS env
# pin must ALSO be applied in-process (site plugins intercept the env var)
_p = os.environ.get("TPU_PATTERNS_PLATFORM") or os.environ.get(
    "JAX_PLATFORMS"
)
if _p:
    try:
        jax.config.update("jax_platforms", _p)
    except Exception:
        pass
"""

_PROBE_INIT = _PLATFORM_PRELUDE + """
t0 = _clock_ns()
devs = jax.devices()
print(json.dumps({
    "platform": devs[0].platform,
    "device_kind": getattr(devs[0], "device_kind", devs[0].platform),
    "device_count": len(devs),
    "init_s": round((_clock_ns() - t0) / 1e9, 2),
}))
"""

_PROBE_TINY = _PLATFORM_PRELUDE + """
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.float32)
t0 = _clock_ns()
jax.block_until_ready(jnp.dot(x, x))
compile_s = (_clock_ns() - t0) / 1e9
t0 = _clock_ns()
for _ in range(3):
    y = jnp.dot(x, x)
jax.block_until_ready(y)
print(json.dumps({
    "compile_s": round(compile_s, 2),
    "warm_3x_ms": round((_clock_ns() - t0) / 1e6, 2),
}))
"""

_PROBE_DEEP = _PLATFORM_PRELUDE + """
import jax.numpy as jnp
# large enough that a half-alive tunnel stalls here, small enough to be
# cheap on a healthy chip (~0.5 GFLOP + a 64 MB transfer)
x = jnp.ones((4096, 2048), jnp.bfloat16)
t0 = _clock_ns()
y = x @ x.T
jax.block_until_ready(y)
import numpy as np
s = float(np.asarray(y[0, 0], np.float32))
print(json.dumps({
    "deep_s": round((_clock_ns() - t0) / 1e9, 2),
    "checksum_ok": s == 2048.0,
}))
"""


def _probe(script: str, timeout: int) -> dict:
    """Run one probe in a SIGKILL-able child; classify the outcome."""
    t0 = clock_ns()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=dict(os.environ),
        )
    except subprocess.TimeoutExpired:
        return {
            "ok": False,
            "error": f"hang (killed after {timeout}s)",
            "elapsed_s": round((clock_ns() - t0) / 1e9, 1),
        }
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()
        return {
            "ok": False,
            "error": f"rc={proc.returncode}: {tail[-1][:200] if tail else ''}",
            "elapsed_s": round((clock_ns() - t0) / 1e9, 1),
        }
    for line in reversed((proc.stdout or "").strip().splitlines()):
        try:
            out = json.loads(line)
            break
        except ValueError:
            continue
    else:
        return {"ok": False, "error": "no parseable probe output"}
    out["ok"] = True
    out["elapsed_s"] = round((clock_ns() - t0) / 1e9, 1)
    return out


def run_doctor(cfg: DoctorConfig, writer) -> list:
    """Layered health report; verdict FAILURE iff a layer is broken.

    Layers (each subsumes the previous): backend init -> tiny
    compile+run -> real-workload compute (``deep``) -> native modules
    (build-on-demand FFI + loader).  The first broken layer names the
    failure; later layers are skipped (their result would be noise).
    """
    from tpu_patterns.core.results import Record, Verdict

    checks: dict[str, dict] = {}
    broken: str | None = None

    for name, script, deadline, gated in (
        ("backend_init", _PROBE_INIT, cfg.probe_timeout, True),
        ("tiny_op", _PROBE_TINY, cfg.probe_timeout, True),
        ("deep_compute", _PROBE_DEEP, cfg.deep_timeout, cfg.deep),
    ):
        if not gated or broken is not None:
            if gated and broken is not None:
                checks[name] = {"ok": False, "error": f"skipped: {broken}"}
            continue
        checks[name] = _probe(script, deadline)
        if checks[name].get("checksum_ok") is False:
            # completed but computed GARBAGE: the worst failure mode —
            # never certify a runtime that returns wrong answers
            checks[name]["ok"] = False
            checks[name]["error"] = "checksum mismatch (wrong results)"
        if not checks[name]["ok"]:
            broken = f"{name} failed"

    # native modules never touch the device: always probed
    from tpu_patterns.interop import native
    from tpu_patterns.io import loader as io_loader

    # call availability ONCE each: on a broken toolchain every call
    # re-runs make (bounded by its 300s timeout), and "never hangs"
    # must include the build probes
    ffi_ok = native.available()
    checks["native_ffi"] = {
        "ok": ffi_ok,
        **({} if ffi_ok else {"error": str(native.build_error())}),
    }
    loader_ok = io_loader.native_available()
    checks["native_loader"] = {
        "ok": loader_ok,
        **({} if loader_ok else {"error": str(io_loader.build_error())}),
    }

    # warm-worker probe (opt-in): the sweep engine's fast path is a
    # pre-initialized `python -m tpu_patterns` server — if IT cannot
    # come up, `sweep --jobs N` silently degrades to cold subprocesses
    # and every cell pays the init tax again.  Spawn one, time
    # ready+ping, kill it.  Gated on the earlier layers like every
    # device probe: a worker's warm_backend() would just wedge on the
    # same broken backend for another probe_timeout of redundant noise.
    if cfg.workers and broken is not None:
        checks["warm_worker"] = {"ok": False, "error": f"skipped: {broken}"}
    elif cfg.workers:
        from tpu_patterns.exec.workers import WarmWorker, WorkerError

        t0 = clock_ns()
        w = None
        try:
            w = WarmWorker(dict(os.environ))
            if w.wait_ready(timeout=cfg.probe_timeout):
                spawn_s = (clock_ns() - t0) / 1e9
                t1 = clock_ns()
                resp = w.request({"op": "ping"}, timeout=cfg.probe_timeout)
                checks["warm_worker"] = {
                    "ok": resp.get("rc") == 0,
                    "spawn_s": round(spawn_s, 2),
                    "ping_ms": round((clock_ns() - t1) / 1e6, 1),
                    **(
                        {}
                        if resp.get("rc") == 0
                        else {"error": f"ping rc={resp.get('rc')}"}
                    ),
                }
            else:
                checks["warm_worker"] = {
                    "ok": False,
                    "error": (
                        f"worker not ready within {cfg.probe_timeout}s "
                        "(backend init wedged?)"
                    ),
                }
        except (WorkerError, OSError) as e:
            checks["warm_worker"] = {
                "ok": False,
                "error": f"{type(e).__name__}: {e}",
            }
        finally:
            # ALWAYS reap: a protocol error mid-request must not leak a
            # live backend-initialized worker (on TPU it holds the chip)
            if w is not None:
                w.kill()

    # watchdog probe: the obs layer's live hang evidence folded into the
    # health report.  A runtime can pass every probe NOW yet have wedged
    # ten minutes ago — the watchdog's flight-recorder dumps say so, and
    # here they become a doctor layer instead of files nobody reads.
    from tpu_patterns import obs

    recent_dumps = []
    try:
        now = wall_time_s()
        recent_dumps = [
            p
            for p in obs.find_dumps()
            if now - os.path.getmtime(p) <= cfg.watchdog_window_s
        ]
    except OSError:
        pass  # a dump deleted mid-scan must not fail the doctor
    checks["watchdog"] = {"ok": True, "recent_dumps": len(recent_dumps)}

    # the layer-by-layer diagnosis is the product: print it, don't bury
    # it in the JSONL notes
    for name, c in checks.items():
        status = "ok" if c.get("ok") else f"FAILED ({c.get('error', '?')})"
        detail = " ".join(
            f"{k}={c[k]}"
            for k in ("platform", "device_kind", "device_count", "init_s",
                      "compile_s", "warm_3x_ms", "deep_s", "spawn_s",
                      "ping_ms", "recent_dumps")
            if k in c
        )
        print(
            f"# doctor {name}: {status}" + (f" [{detail}]" if detail else ""),
            file=writer.stream,
            flush=True,
        )

    healthy = all(c.get("ok") for c in checks.values())
    metrics: dict[str, float] = {}
    for name, c in checks.items():
        metrics[f"{name}_ok"] = 1.0 if c.get("ok") else 0.0
        for k in ("init_s", "compile_s", "warm_3x_ms", "deep_s", "elapsed_s",
                  "spawn_s", "ping_ms", "recent_dumps"):
            if k in c:
                metrics[f"{name}_{k}"] = float(c[k])
    # broken layer -> FAILURE; healthy but recent hang evidence ->
    # WARNING (truthy: the runtime IS up, but someone should read the
    # dump before trusting a long unattended run)
    verdict = (
        Verdict.FAILURE
        if not healthy
        else (Verdict.WARNING if recent_dumps else Verdict.SUCCESS)
    )
    rec = Record(
        pattern="doctor",
        mode=str(checks.get("backend_init", {}).get("device_kind", "down")),
        commands=f"probe_timeout={cfg.probe_timeout}s deep={cfg.deep}",
        metrics=metrics,
        verdict=verdict,
        notes=[
            f"{name}: {c['error']}"
            for name, c in checks.items()
            if not c.get("ok") and "error" in c
        ]
        + [f"watchdog hang dump: {p}" for p in recent_dumps],
    )
    writer.record(rec)
    if cfg.watch_jsonl:
        action = record_watch_poll(cfg.watch_jsonl, rec)
        print(
            f"# doctor watch: episode {action} -> {cfg.watch_jsonl}",
            file=writer.stream,
            flush=True,
        )
    return [rec]


# ---------------------------------------------------------------------------
# Watch mode: per-EPISODE outage records, not per-poll.
#
# Round 5's capture watcher appended one doctor Record (and committed one
# "doctor outage record") per failing poll — ~20 commits saying the same
# thing (VERDICT weak #7).  Watch mode coalesces: consecutive failing
# polls with the same broken-layer signature update ONE open episode
# entry in place (poll count + last-seen time); the first healthy poll
# closes it.  The file stays JSONL of Record-shaped objects, so
# ``parse_log``/``report`` read it unchanged.
# ---------------------------------------------------------------------------


def _failure_signature(rec) -> str:
    """Which layers are broken — the identity of an outage episode."""
    failing = sorted(
        k[: -len("_ok")]
        for k, v in rec.metrics.items()
        if k.endswith("_ok") and v == 0.0
    )
    return ",".join(failing) or "unknown"


def record_watch_poll(jsonl_path: str, rec) -> str:
    """Fold one doctor poll into the episode log; returns the action
    taken: ``opened`` (new failing episode), ``extended`` (same episode,
    count bumped in place), ``closed`` (healthy poll closed the open
    episode), or ``recorded`` (healthy poll, nothing open)."""
    from tpu_patterns.core.results import Verdict

    d = os.path.dirname(jsonl_path)
    if d:
        os.makedirs(d, exist_ok=True)
    last = _read_last_entry(jsonl_path)
    last_is_open = (
        isinstance(last, dict)
        and last.get("pattern") == "doctor_episode"
        and last.get("metrics", {}).get("open") == 1.0
    )
    now = wall_time_s()
    failing = rec.verdict is Verdict.FAILURE

    if failing:
        sig = _failure_signature(rec)
        if last_is_open and last.get("mode") == sig:
            _mutate_last(jsonl_path, _extend(last, now))
            return "extended"
        episode = json.loads(rec.to_json())
        episode["pattern"] = "doctor_episode"
        episode["mode"] = sig
        episode["commands"] = f"episode:{sig}"
        episode["metrics"] = dict(
            rec.metrics, polls=1.0, opened_ts=now, last_ts=now, open=1.0
        )
        ep_line = json.dumps(episode, sort_keys=True) + "\n"
        if last_is_open:  # different signature: close it, open anew
            _close(last, now)
            _mutate_last(jsonl_path, last, append=ep_line)
        else:  # nothing to mutate: plain O(1) append
            _append(jsonl_path, ep_line)
        return "opened"

    if last_is_open:
        _close(last, now)
        _mutate_last(jsonl_path, last, append=rec.to_json() + "\n")
        return "closed"
    _append(jsonl_path, rec.to_json() + "\n")  # the common healthy poll
    return "recorded"


def _extend(episode: dict, now: float) -> dict:
    episode["metrics"]["polls"] += 1.0
    episode["metrics"]["last_ts"] = now
    return episode


def _close(episode: dict, now: float) -> None:
    episode["metrics"]["open"] = 0.0
    episode["metrics"]["closed_ts"] = now
    m = episode["metrics"]
    episode.setdefault("notes", []).append(
        f"episode closed after {m['polls']:.0f} poll(s), "
        f"{m['closed_ts'] - m['opened_ts']:.0f}s"
    )


def _read_last_entry(path: str) -> dict | None:
    """Parse the file's last line without reading the whole file (the
    healthy-watch common case is a multi-day append-only log)."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 65536))
            tail = f.read().decode("utf-8", "replace")
    except OSError:
        return None
    for line in reversed(tail.strip().splitlines()):
        if line.strip():
            try:
                return json.loads(line)
            except ValueError:
                return None  # torn write: treat as no open episode
    return None


def _append(path: str, line: str) -> None:
    with open(path, "a") as f:
        f.write(line)
        f.flush()
        os.fsync(f.fileno())


def _mutate_last(path: str, entry: dict, append: str = "") -> None:
    """Replace the file's last line with ``entry`` (plus optional
    appended lines) via atomic whole-file rewrite — only episode
    boundaries and extensions pay this; plain polls use :func:`_append`.
    A kill mid-update must not tear the log (tmp+replace, the same
    discipline as sweep state)."""
    try:
        with open(path) as f:
            lines = [ln for ln in f.readlines() if ln.strip()]
    except OSError:
        lines = []
    if lines:
        lines[-1] = json.dumps(entry, sort_keys=True) + "\n"
    else:
        lines = [json.dumps(entry, sort_keys=True) + "\n"]
    if append:
        lines.append(append)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.writelines(lines)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
