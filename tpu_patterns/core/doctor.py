"""Failure detection: deadline-bounded health probes of the runtime.

The suite's measurements die in characteristic ways — a dead device
tunnel hangs INSIDE native backend init with the GIL held (no Python
timeout fires), a half-alive one passes a tiny op then stalls on real
work, a missing toolchain silently disables the native modules.  The
``doctor`` subcommand turns the countermeasures bench.py grew
(subprocess probes a parent can SIGKILL, escalating workload sizes)
into a first-class diagnostic: every probe runs in a child with a hard
deadline, so the doctor itself can NEVER hang, and the report says
which layer broke — backend init, tiny compile, real compute, native
build — instead of a generic timeout.

Reference analogue: the exit-code-is-the-verdict discipline
(`/root/reference/concurency/main.cpp:270,321`) applied to the runtime
itself rather than a measurement.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time


@dataclasses.dataclass
class DoctorConfig:
    """CLI ``doctor`` subcommand."""

    probe_timeout: int = 60  # per-probe deadline (s)
    # escalate to a real-workload probe (a matmul large enough to catch
    # the passes-preflight-then-hangs failure mode)
    deep: bool = True
    deep_timeout: int = 120


# Probe scripts run in children: each prints ONE json line on success.
# They test whatever backend the environment selects — with the caveat
# that site-installed platform plugins can intercept backend init even
# when JAX_PLATFORMS is set in the env, so an explicit env pin is
# re-applied IN-PROCESS via jax.config (the only override that always
# wins); with no pin, the default (production) backend is probed.
_PLATFORM_PRELUDE = """
import json, os, time
import jax
try:
    # the environment the REAL runs use: TPU_PATTERNS_PLATFORM pin,
    # simulated-mesh device count, persistent compile cache
    from tpu_patterns.runtime import setup_jax
    setup_jax()
except Exception:
    pass  # package not importable in this child: pin below still applies
# setup_jax honors only TPU_PATTERNS_PLATFORM; a bare JAX_PLATFORMS env
# pin must ALSO be applied in-process (site plugins intercept the env var)
_p = os.environ.get("TPU_PATTERNS_PLATFORM") or os.environ.get(
    "JAX_PLATFORMS"
)
if _p:
    try:
        jax.config.update("jax_platforms", _p)
    except Exception:
        pass
"""

_PROBE_INIT = _PLATFORM_PRELUDE + """
t0 = time.perf_counter()
devs = jax.devices()
print(json.dumps({
    "platform": devs[0].platform,
    "device_kind": getattr(devs[0], "device_kind", devs[0].platform),
    "device_count": len(devs),
    "init_s": round(time.perf_counter() - t0, 2),
}))
"""

_PROBE_TINY = _PLATFORM_PRELUDE + """
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.float32)
t0 = time.perf_counter()
jax.block_until_ready(jnp.dot(x, x))
compile_s = time.perf_counter() - t0
t0 = time.perf_counter()
for _ in range(3):
    y = jnp.dot(x, x)
jax.block_until_ready(y)
print(json.dumps({
    "compile_s": round(compile_s, 2),
    "warm_3x_ms": round(1e3 * (time.perf_counter() - t0), 2),
}))
"""

_PROBE_DEEP = _PLATFORM_PRELUDE + """
import jax.numpy as jnp
# large enough that a half-alive tunnel stalls here, small enough to be
# cheap on a healthy chip (~0.5 GFLOP + a 64 MB transfer)
x = jnp.ones((4096, 2048), jnp.bfloat16)
t0 = time.perf_counter()
y = x @ x.T
jax.block_until_ready(y)
import numpy as np
s = float(np.asarray(y[0, 0], np.float32))
print(json.dumps({
    "deep_s": round(time.perf_counter() - t0, 2),
    "checksum_ok": s == 2048.0,
}))
"""


def _probe(script: str, timeout: int) -> dict:
    """Run one probe in a SIGKILL-able child; classify the outcome."""
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=dict(os.environ),
        )
    except subprocess.TimeoutExpired:
        return {
            "ok": False,
            "error": f"hang (killed after {timeout}s)",
            "elapsed_s": round(time.perf_counter() - t0, 1),
        }
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()
        return {
            "ok": False,
            "error": f"rc={proc.returncode}: {tail[-1][:200] if tail else ''}",
            "elapsed_s": round(time.perf_counter() - t0, 1),
        }
    for line in reversed((proc.stdout or "").strip().splitlines()):
        try:
            out = json.loads(line)
            break
        except ValueError:
            continue
    else:
        return {"ok": False, "error": "no parseable probe output"}
    out["ok"] = True
    out["elapsed_s"] = round(time.perf_counter() - t0, 1)
    return out


def run_doctor(cfg: DoctorConfig, writer) -> list:
    """Layered health report; verdict FAILURE iff a layer is broken.

    Layers (each subsumes the previous): backend init -> tiny
    compile+run -> real-workload compute (``deep``) -> native modules
    (build-on-demand FFI + loader).  The first broken layer names the
    failure; later layers are skipped (their result would be noise).
    """
    from tpu_patterns.core.results import Record, Verdict

    checks: dict[str, dict] = {}
    broken: str | None = None

    for name, script, deadline, gated in (
        ("backend_init", _PROBE_INIT, cfg.probe_timeout, True),
        ("tiny_op", _PROBE_TINY, cfg.probe_timeout, True),
        ("deep_compute", _PROBE_DEEP, cfg.deep_timeout, cfg.deep),
    ):
        if not gated or broken is not None:
            if gated and broken is not None:
                checks[name] = {"ok": False, "error": f"skipped: {broken}"}
            continue
        checks[name] = _probe(script, deadline)
        if checks[name].get("checksum_ok") is False:
            # completed but computed GARBAGE: the worst failure mode —
            # never certify a runtime that returns wrong answers
            checks[name]["ok"] = False
            checks[name]["error"] = "checksum mismatch (wrong results)"
        if not checks[name]["ok"]:
            broken = f"{name} failed"

    # native modules never touch the device: always probed
    from tpu_patterns.interop import native
    from tpu_patterns.io import loader as io_loader

    # call availability ONCE each: on a broken toolchain every call
    # re-runs make (bounded by its 300s timeout), and "never hangs"
    # must include the build probes
    ffi_ok = native.available()
    checks["native_ffi"] = {
        "ok": ffi_ok,
        **({} if ffi_ok else {"error": str(native.build_error())}),
    }
    loader_ok = io_loader.native_available()
    checks["native_loader"] = {
        "ok": loader_ok,
        **({} if loader_ok else {"error": str(io_loader.build_error())}),
    }

    # the layer-by-layer diagnosis is the product: print it, don't bury
    # it in the JSONL notes
    for name, c in checks.items():
        status = "ok" if c.get("ok") else f"FAILED ({c.get('error', '?')})"
        detail = " ".join(
            f"{k}={c[k]}"
            for k in ("platform", "device_kind", "device_count", "init_s",
                      "compile_s", "warm_3x_ms", "deep_s")
            if k in c
        )
        print(
            f"# doctor {name}: {status}" + (f" [{detail}]" if detail else ""),
            file=writer.stream,
            flush=True,
        )

    healthy = all(c.get("ok") for c in checks.values())
    metrics: dict[str, float] = {}
    for name, c in checks.items():
        metrics[f"{name}_ok"] = 1.0 if c.get("ok") else 0.0
        for k in ("init_s", "compile_s", "warm_3x_ms", "deep_s", "elapsed_s"):
            if k in c:
                metrics[f"{name}_{k}"] = float(c[k])
    rec = Record(
        pattern="doctor",
        mode=str(checks.get("backend_init", {}).get("device_kind", "down")),
        commands=f"probe_timeout={cfg.probe_timeout}s deep={cfg.deep}",
        metrics=metrics,
        verdict=Verdict.SUCCESS if healthy else Verdict.FAILURE,
        notes=[
            f"{name}: {c['error']}"
            for name, c in checks.items()
            if not c.get("ok") and "error" in c
        ],
    )
    writer.record(rec)
    return [rec]
