"""Read the profiles the suite records (VERDICT r2 weak #6: traces were
write-only, matching the reference's vestigial ``enable_profiling``
queue property whose event timestamps are never read —
``/root/reference/concurency/main.cpp:123``, ``bench_sycl.cpp:39-45``).

``jax.profiler.trace`` writes TensorBoard ``*.xplane.pb`` files — the
XSpace protobuf (planes -> lines -> timed events).  This module parses
them with a self-contained protobuf *wire-format* reader (the schema is
the public, stable ``tsl/profiler/protobuf/xplane.proto``; depending on
tensorflow just to read 5 message types would drag a framework into a
patterns suite), classifies device-plane events into

    compute | collective | dma | infeed_outfeed | other

by XLA op-name conventions, and turns a trace directory into Record
metrics: per-category busy time, idle time, and fractions — the
breakdown that says WHERE a step's time went (MXU compute vs ICI
collectives vs HBM DMA vs waiting), i.e. what to optimize next.
"""

from __future__ import annotations

import dataclasses
import glob
import os
import re


# ---------------------------------------------------------------------------
# Minimal protobuf wire-format reader (no generated code, no deps)
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message's bytes.
    Length-delimited values come back as raw bytes; varints as ints;
    fixed32/64 as ints.  Unknown/irrelevant fields are safely skipped —
    exactly the forward-compatibility protobuf promises."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:  # varint
            val, i = _read_varint(buf, i)
        elif wire == 1:  # fixed64
            if i + 8 > n:
                raise ValueError(f"truncated fixed64 field {field}")
            val = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wire == 2:  # length-delimited
            ln, i = _read_varint(buf, i)
            val = buf[i:i + ln]
            if len(val) < ln:  # truncated file: python slicing would
                # silently hand back a short payload — fail loudly
                raise ValueError(
                    f"truncated length-delimited field {field}: "
                    f"{len(val)} of {ln} bytes"
                )
            i += ln
        elif wire == 5:  # fixed32
            if i + 4 > n:
                raise ValueError(f"truncated fixed32 field {field}")
            val = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:  # group wires (3/4): not produced by xplane writers
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


@dataclasses.dataclass
class XEvent:
    name: str
    offset_ps: int
    duration_ps: int


@dataclasses.dataclass
class XLine:
    name: str
    events: list
    timestamp_ns: int = 0  # event offsets are relative to this


@dataclasses.dataclass
class XPlane:
    name: str
    lines: list


def _parse_event(buf: bytes, metadata: dict) -> XEvent:
    mid = off = dur = 0
    for field, _, val in _fields(buf):
        if field == 1:
            mid = val
        elif field == 2:
            off = val
        elif field == 3:
            dur = val
    return XEvent(metadata.get(mid, ""), off, dur)


def _parse_line(buf: bytes, metadata: dict) -> XLine:
    name, events, ts = "", [], 0
    for field, _, val in _fields(buf):
        if field == 2:
            name = val.decode("utf-8", "replace")
        elif field == 11 and val:  # display_name wins when present
            name = val.decode("utf-8", "replace")
        elif field == 3:
            ts = val
        elif field == 4:
            events.append(_parse_event(val, metadata))
    return XLine(name, events, ts)


def _parse_event_metadata(buf: bytes) -> tuple[int, str]:
    mid, name = 0, ""
    for field, _, val in _fields(buf):
        if field == 1:
            mid = val
        elif field == 2:
            name = val.decode("utf-8", "replace")
        elif field == 4 and val:  # display_name wins
            name = val.decode("utf-8", "replace")
    return mid, name


def _parse_plane(buf: bytes) -> XPlane:
    name = ""
    metadata: dict[int, str] = {}
    line_bufs: list[bytes] = []
    for field, _, val in _fields(buf):
        if field == 2:
            name = val.decode("utf-8", "replace")
        elif field == 3:
            line_bufs.append(val)
        elif field == 4:
            # map<int64, XEventMetadata> entry: key=1, value=2
            k, meta = 0, b""
            for f2, _, v2 in _fields(val):
                if f2 == 1:
                    k = v2
                elif f2 == 2:
                    meta = v2
            mid, mname = _parse_event_metadata(meta)
            metadata[mid or k] = mname
    return XPlane(name, [_parse_line(b, metadata) for b in line_bufs])


def parse_xspace(path: str) -> list[XPlane]:
    """Parse one ``*.xplane.pb`` file into planes of lines of events."""
    with open(path, "rb") as f:
        buf = f.read()
    return [
        _parse_plane(val) for field, _, val in _fields(buf) if field == 1
    ]


# ---------------------------------------------------------------------------
# Classification: XLA op/event names -> where the time went
# ---------------------------------------------------------------------------

# Token rules in priority order (first hit wins).  Names follow XLA's
# HLO naming: collectives keep their HLO opcode in the (possibly fused)
# event name; device copies show up as copy ops; infeed/outfeed and host
# transfers are their own ops.  Attribution is a FIRST-TOKEN heuristic:
# a fusion is booked as compute even when its name mentions the ops it
# fuses (a `...copy_fusion` loop is an in-place compute loop on TPU, and
# transposes run on the VPU — neither is DMA-engine time; VERDICT r3
# weak #4 / ADVICE r3).  Tokens match on word boundaries — letters may
# not flank a match, digits/dashes/dots may — so `send` cannot fire
# inside an unrelated word while `all-reduce.1` still hits `all-reduce`.
_RULES: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("collective", (
        "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
        "collective-permute", "collective-broadcast", "send", "recv",
        "psum", "ppermute",
    )),
    ("infeed_outfeed", ("infeed", "outfeed", "host-transfer")),
    # fused loops are compute on TPU even when the fused op's name
    # (copy, transpose, dynamic-update-slice) survives in the event name
    ("compute", ("fusion", "dynamic-update-slice", "transpose")),
    ("dma", ("copy", "dma", "memset")),
    # Pallas/Mosaic kernels surface as custom calls in the trace — and
    # they ARE this framework's hot compute ops (fused flash fwd/bwd,
    # FMA busy-wait).  Without this rule a profiled flagship-pallas run
    # books its own main kernel as "other" and fails the unclassified-
    # time gate on first silicon contact (caught by a pre-capture
    # dry-fire of the fixture tier).  Ordered AFTER the dma rule so a
    # DMA-flavored kernel name (dma_overlap, async copy) keeps its
    # engine bucket.
    ("compute", ("custom-call", "custom_call", "mosaic", "pallas")),
    ("compute", (
        "dot", "conv", "matmul", "fma", "loop", "scan", "while",
        "reduce", "select", "add", "multiply", "exp", "iota", "broadcast",
        "compare", "scatter", "gather", "rsqrt", "subtract", "divide",
    )),
)

_TOKEN_RE: dict[str, "re.Pattern[str]"] = {}


def _token_matches(token: str, low: str) -> bool:
    pat = _TOKEN_RE.get(token)
    if pat is None:
        pat = re.compile(
            "(?<![a-z])" + re.escape(token) + "(?![a-z])"
        )
        _TOKEN_RE[token] = pat
    return pat.search(low) is not None


def classify(name: str) -> str:
    low = name.lower()
    for category, keys in _RULES:
        if any(_token_matches(k, low) for k in keys):
            return category
    return "other"


# TPU only: the breakdown's serial-op-line model (busy = sum of event
# durations) holds for the TPU device plane; GPU planes carry one line
# per stream with OVERLAPPING events, where that sum would exceed wall
# and clamp idle to a silently wrong 0 — better no Record than a wrong
# one on a platform this suite does not target.
_DEVICE_PLANE_MARKERS = ("/device:tpu",)
# lines that re-aggregate the same ops (steps, modules, scopes) — summing
# them alongside the op line would double-count
_SKIP_LINES = ("step", "module", "scope", "framework", "source")


def device_planes(planes: list) -> list:
    return [
        p for p in planes
        if any(m in p.name.lower() for m in _DEVICE_PLANE_MARKERS)
    ]


def breakdown_planes(planes: list) -> dict[str, float]:
    """Aggregate device-plane events into per-category busy ms + idle.

    Per plane (= per chip): wall = the span from the earliest event
    start to the latest event end over its op lines; idle = that
    plane's wall - its busy sum (the TPU op line is effectively serial,
    so the sum IS the busy time).  Across planes, category/busy times
    SUM (total chip-time per category) and idle SUMS PER PLANE — a
    multi-chip host whose chips are each half-idle must report that
    idle, not hide it behind one shared wall span."""
    cats = {"compute": 0, "collective": 0, "dma": 0, "infeed_outfeed": 0,
            "other": 0}
    idle_ps, wall_ps = 0, 0
    for plane in planes:
        p_busy, t0, t1 = 0, None, None
        for line in plane.lines:
            lname = line.name.lower()
            if any(s in lname for s in _SKIP_LINES):
                continue
            base = line.timestamp_ns * 1000  # offsets are line-relative
            for ev in line.events:
                cats[classify(ev.name)] += ev.duration_ps
                p_busy += ev.duration_ps
                s = base + ev.offset_ps
                e = s + ev.duration_ps
                t0 = s if t0 is None else min(t0, s)
                t1 = e if t1 is None else max(t1, e)
        p_wall = (t1 - t0) if (t0 is not None and t1 is not None) else 0
        idle_ps += max(0, p_wall - p_busy)
        wall_ps = max(wall_ps, p_wall)
    busy_ps = sum(cats.values())
    out = {f"{k}_ms": v / 1e9 for k, v in cats.items()}
    out["busy_ms"] = busy_ps / 1e9
    out["wall_ms"] = wall_ps / 1e9
    out["idle_ms"] = idle_ps / 1e9
    if busy_ps:
        for k, v in cats.items():
            out[f"{k}_frac"] = round(v / busy_ps, 4)
    return out


def breakdown(trace_dir: str) -> dict[str, float] | None:
    """Per-category time breakdown of the NEWEST trace under a
    ``jax.profiler.trace`` output directory, or None when no xplane file
    or no device plane exists (host-only traces explain nothing about
    the chip and must not masquerade as a device breakdown)."""
    files = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not files:
        return None
    newest = max(files, key=os.path.getmtime)
    planes = device_planes(parse_xspace(newest))
    if not planes or not any(
        ln.events for p in planes for ln in p.lines
    ):
        return None
    out = breakdown_planes(planes)
    out["n_device_planes"] = float(len(planes))
    return out


def op_name_snapshot(trace_dir: str) -> dict | None:
    """Unique device-plane op names of the newest trace, with count,
    total duration, and the category :func:`classify` books them under.

    Two consumers: the hardware ladder snapshots REAL op names into a
    committed fixture so the classifier is tested against silicon
    vocabulary instead of synthetic strings (VERDICT r3 next #6), and
    ``profilecheck`` gates on the share of busy time falling into
    ``other`` (an unclassified hot op would silently skew every
    breakdown fraction).  None when the dir has no device plane."""
    files = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not files:
        return None
    newest = max(files, key=os.path.getmtime)
    planes = device_planes(parse_xspace(newest))
    names: dict[str, dict] = {}
    for plane in planes:
        for line in plane.lines:
            if any(s in line.name.lower() for s in _SKIP_LINES):
                continue
            for ev in line.events:
                d = names.setdefault(
                    ev.name,
                    {"count": 0, "duration_ps": 0,
                     "category": classify(ev.name)},
                )
                d["count"] += 1
                d["duration_ps"] += ev.duration_ps
    return names or None


def crosscheck_rate(
    tflops_hw: float,
    bd: dict[str, float],
    peak_tflops: float | None,
    n_chips: int = 1,
) -> dict[str, float]:
    """Do the wall-clock FLOP accounting and the profile's measured
    compute time cohere?  (VERDICT r3 next #3's cross-check.)

    ``tflops_hw`` is silicon FLOPs over wall time; the breakdown's
    ``compute_frac`` bounds how much of that wall was MXU-busy.  The
    implied on-compute rate ``tflops_hw / compute_frac`` must fit under
    the participating chips' peak (with 10% tolerance for trace skew) —
    above it, either the FLOP multiplier overcounts or the classifier
    is booking compute time elsewhere; one of the two accountings is
    wrong."""
    busy = bd.get("busy_ms", 0.0)
    wall = bd.get("wall_ms", 0.0)
    # compute share of WALL, not of busy: idle wall still elapsed, and
    # the rate under test divided by wall time
    compute_frac_of_wall = (
        min(1.0, bd.get("compute_ms", 0.0) / wall) if wall else 0.0
    )
    out = {
        "tflops_hw": tflops_hw,
        "compute_frac_of_wall": compute_frac_of_wall,
        "busy_ms": busy,
        "wall_ms": wall,
    }
    if compute_frac_of_wall > 0:
        implied = tflops_hw / compute_frac_of_wall
        out["implied_mxu_tflops"] = implied
        if peak_tflops is not None:
            bound = 1.1 * peak_tflops * n_chips
            out["peak_bound_tflops"] = bound
            out["coherent"] = float(implied <= bound)
    elif tflops_hw > 0:
        # a positive FLOP rate with ZERO classified compute time is the
        # maximal incoherence this check exists for (every hot op booked
        # outside 'compute') — incoherent regardless of peak knowledge
        out["coherent"] = 0.0
    return out
