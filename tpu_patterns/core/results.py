"""Result records, verdict markers, and log parsing.

The reference's observability is a stdout protocol (SURVEY.md §5): ``# ...``
progress lines (concurency/main.cpp:233,277), ``## mode | commands |
SUCCESS/FAILURE`` verdict markers (main.cpp:310-318), and ``export KEY=VAL``
lines giving each log section its environment context (run_omp.sh:2,
parse.py:18-19); concurency/parse.py:12-31 scrapes those into tabulate
tables.  Here every run additionally emits a machine-readable JSON-lines
record, while keeping the exact human markers so logs stay grep/parse
compatible with the reference's convention.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import re
import sys
from typing import Any, Iterable, TextIO

from tpu_patterns.core.timing import wall_time_s


class Verdict(enum.Enum):
    SUCCESS = "SUCCESS"
    FAILURE = "FAILURE"
    WARNING = "WARNING"
    SKIPPED = "SKIPPED"

    def __bool__(self) -> bool:  # truthy iff the run passed
        return self is not Verdict.FAILURE


@dataclasses.dataclass
class Record:
    """One benchmark result: pattern x mode x workload -> metrics + verdict."""

    pattern: str  # e.g. "p2p", "concurrency", "allreduce"
    mode: str  # e.g. "serial", "async", "ring", "psum"
    commands: str = ""  # command-group string, e.g. "C M2D"
    metrics: dict[str, float] = dataclasses.field(default_factory=dict)
    verdict: Verdict = Verdict.SUCCESS
    config: dict[str, Any] = dataclasses.field(default_factory=dict)
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    timestamp: float = dataclasses.field(default_factory=wall_time_s)
    notes: list[str] = dataclasses.field(default_factory=list)
    # Run provenance (perf/provenance.py): run_id + git_sha + mesh_fp.
    # Stamped by ResultWriter.record for every banked Record so runs
    # are joinable across time; {} only on legacy records parsed from
    # pre-stamp archives.
    run: dict[str, str] = dataclasses.field(default_factory=dict)
    # True marks a committed record whose number was invalidated by a
    # later accounting/measurement fix: it stays in the archive as
    # provenance but must never be tabulated as a result.
    superseded: bool = False

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["verdict"] = self.verdict.value
        return json.dumps(d, sort_keys=True)

    @classmethod
    def from_json(cls, line: str) -> "Record":
        d = json.loads(line)
        d["verdict"] = Verdict(d.get("verdict", "SUCCESS"))
        return cls(**d)


# Environment variables that identify a sweep configuration, the analogue of
# the ``export``-echo lines parse.py keys tables by (run_omp.sh:14-27).
_CONTEXT_ENV_VARS = (
    "JAX_PLATFORMS",
    "XLA_FLAGS",
    "LIBTPU_INIT_ARGS",
    "JAX_DEFAULT_MATMUL_PRECISION",
    "JAX_ENABLE_COMPILATION_CACHE",
    "TPU_PATTERNS_SWEEP_CONFIG",
    "TPU_PATTERNS_SWEEP_TIER",
)


def context_env() -> dict[str, str]:
    return {k: os.environ[k] for k in _CONTEXT_ENV_VARS if k in os.environ}


class ResultWriter:
    """Emits human markers to ``stream`` and JSONL records to ``jsonl_path``.

    Marker grammar (reference-compatible, concurency/main.cpp:310-318):
        ``# <progress text>``
        ``## <mode> | <commands> | <SUCCESS|FAILURE>``
    """

    def __init__(
        self, jsonl_path: str | os.PathLike | None = None, stream: TextIO | None = None
    ):
        self.jsonl_path = os.fspath(jsonl_path) if jsonl_path else None
        self.stream = stream if stream is not None else sys.stdout
        self._failures = 0
        if self.jsonl_path:
            d = os.path.dirname(self.jsonl_path)
            if d:
                os.makedirs(d, exist_ok=True)

    def progress(self, text: str) -> None:
        print(f"# {text}", file=self.stream, flush=True)

    def metric(self, name: str, value: float, unit: str) -> None:
        # Pretty-print in the spirit of time_info (main.cpp:21-44) /
        # "mode Uni/Bidirectional Bandwidth: X GB/s" (peer2pear.cpp:137-139).
        print(f"{name}: {value:.6g} {unit}", file=self.stream, flush=True)

    def record(self, rec: Record) -> Record:
        if not rec.env:
            rec.env = context_env()
        if not rec.run:
            # lazy import: stamping must not pull perf/ into every
            # results consumer at module load
            from tpu_patterns.perf.provenance import stamp_dict

            rec.run = stamp_dict()
        if rec.verdict is Verdict.FAILURE:
            self._failures += 1
        if not rec.commands:
            rec.commands = rec.pattern  # marker and JSON record must agree
        print(
            f"## {rec.mode} | {rec.commands} | {rec.verdict.value}",
            file=self.stream,
            flush=True,
        )
        if self.jsonl_path:
            with open(self.jsonl_path, "a") as f:
                f.write(rec.to_json() + "\n")
        return rec

    @property
    def exit_code(self) -> int:
        """Aggregated process exit code (ref: main.cpp:270,321)."""
        return 1 if self._failures else 0


# Commit epoch of the flash-grad FLOP-accounting fix (the amortized
# timing chain used to feed back only dq, so dk/dv were dead-code
# eliminated from the timed program — every *_grad rate captured before
# this instant credits FLOPs silicon never ran).  Grad records older
# than this are quotable only as provenance, never as results: `report`
# refuses to tabulate them unless they carry ``superseded: true``
# (VERDICT r3 next #8).
GRAD_ACCOUNTING_FIX_TS = 1785446857.0


def stale_grad_records(records: Iterable[Record]) -> list[Record]:
    """Grad records that predate the accounting fix and are not marked
    superseded — the rows ``report`` must refuse."""
    return [
        r
        for r in records
        if r.mode.endswith("_grad")
        and r.timestamp < GRAD_ACCOUNTING_FIX_TS
        and not r.superseded
    ]


def prefer_refined(records: Iterable[Record]) -> list[Record]:
    """Drop first-pass-tier records shadowed by a refined record.

    The measured sweep's two-phase ordering banks every cell at the
    minimum repetition count first (records tagged
    ``TPU_PATTERNS_SWEEP_TIER=first_pass`` in their env context), then
    refines at full reps.  The supersede key is the sweep CELL (both
    tiers of a cell carry the same ``TPU_PATTERNS_SWEEP_CONFIG`` value,
    the cell name) PLUS the record's (pattern, mode) — but NOT its
    ``commands``.  Each piece earns its place: commands is excluded
    because the lm cell prints its steps count inside it, so the tiers'
    records would never match; the cell tag is included because sibling
    lever cells emit byte-identical record surfaces, so a surface key
    would let one cell's refined record retire another cell's banked
    breadth; and (pattern, mode) is included because a cell can emit
    SEVERAL records and a slice-killed refined run may have flushed
    only some of them — a cell-only key would let that partial flush
    retire first-pass records whose refined twin never landed.  Records
    without a cell tag fall back to the full (pattern, mode, commands)
    surface.  An UNshadowed quick record still tabulates — breadth
    banked in a short tunnel window is a result, just a provisional
    one, and its tier rides visibly in the table's env key.
    """

    records = list(records)  # may be a generator; it is walked twice

    def key(r: Record) -> tuple:
        cell = r.env.get("TPU_PATTERNS_SWEEP_CONFIG")
        if cell:
            return ("cell", cell, r.pattern, r.mode)
        return ("record", r.pattern, r.mode, r.commands)

    def is_fp(r: Record) -> bool:
        return r.env.get("TPU_PATTERNS_SWEEP_TIER") == "first_pass"

    refined = {key(r) for r in records if not is_fp(r)}
    return [r for r in records if not is_fp(r) or key(r) not in refined]


_VERDICT_RE = re.compile(
    r"^##\s*(?P<mode>[^|]+?)\s*\|\s*(?P<commands>[^|]+?)\s*\|\s*(?P<verdict>SUCCESS|FAILURE|WARNING|SKIPPED)\s*$"
)
_EXPORT_RE = re.compile(r"^\+*\s*export\s+(?P<key>[A-Za-z_][A-Za-z0-9_]*)=(?P<val>.*)$")


def parse_log(lines: Iterable[str]) -> list[Record]:
    """Parse a mixed log: JSONL records, ``##`` markers, ``export`` context.

    Reference parity with concurency/parse.py:12-31 — ``export`` lines update
    the current env context; each ``##`` marker yields one record keyed by it.
    JSON lines (from ResultWriter) are parsed directly and take precedence
    over marker lines with the same (mode, commands) anywhere in the input —
    concatenation order of stdout log and JSONL file does not matter.
    """
    lines = [ln.rstrip("\n") for ln in lines]
    records: list[Record] = []
    seen: set[tuple[str, str]] = set()
    # Pass 1: JSON records (and their dedup keys).
    json_records: dict[int, Record] = {}
    for i, line in enumerate(lines):
        if line.startswith("{"):
            try:
                rec = Record.from_json(line)
            except (json.JSONDecodeError, TypeError, ValueError):
                continue
            json_records[i] = rec
            seen.add((rec.mode, rec.commands))
    # Pass 2: markers (skipping those shadowed by a JSON record) with
    # export-line env context, preserving input order.
    env: dict[str, str] = {}
    for i, line in enumerate(lines):
        if i in json_records:
            records.append(json_records[i])
            continue
        m = _EXPORT_RE.match(line)
        if m:
            env[m.group("key")] = m.group("val").strip("\"'")
            continue
        m = _VERDICT_RE.match(line)
        if m:
            key = (m.group("mode"), m.group("commands"))
            if key in seen:
                continue
            records.append(
                Record(
                    pattern="",
                    mode=m.group("mode"),
                    commands=m.group("commands"),
                    verdict=Verdict(m.group("verdict")),
                    env=dict(env),
                )
            )
    return records


# zero-valued gate metric -> human tag; ONE list shared by every table
# renderer (report's tabulate + the capture watcher's summarize), so a
# future fourth plausibility gate cannot flag in one and pass in the
# other.
_INTEGRITY_FLAG_TAGS = (
    ("timing_converged", "NOISE-BOUND"),
    ("hbm_plausible", "NOT-HBM"),
    ("ici_plausible", "NOT-ICI"),
)


def integrity_flags(rec: Record) -> list[str]:
    """Human-readable tags for every failed integrity gate on a record."""
    return [
        tag
        for key, tag in _INTEGRITY_FLAG_TAGS
        if rec.metrics.get(key, 1.0) == 0.0
    ]


def tabulate_records(records: list[Record]) -> str:
    """Render records as per-env tables: rows=commands, cols=modes.

    Same shape as concurency/parse.py's output (one table per env config).
    """
    from tabulate import tabulate  # deferred; baked into the image

    by_env: dict[str, dict[str, dict[str, str]]] = {}
    for rec in records:
        env_key = ", ".join(f"{k}={v}" for k, v in sorted(rec.env.items())) or "(default env)"
        cell = rec.verdict.value
        if rec.metrics:
            main_metric = next(iter(rec.metrics.items()))
            cell = f"{rec.verdict.value} ({main_metric[0]}={main_metric[1]:.4g})"
        # measurement-integrity flags ride with the number: a reader of
        # the table must see a noise-bound or implausible rate AS such,
        # not discover it three columns deep in the raw JSONL
        flags = integrity_flags(rec)
        if flags:
            cell = f"{cell} [{','.join(flags)}]"
        if rec.superseded:
            # provenance, not a result: the number stays visible but can
            # never be quoted as a current measurement
            cell = f"SUPERSEDED [{cell}]"
        by_env.setdefault(env_key, {}).setdefault(rec.commands, {})[rec.mode] = cell
    chunks = []
    for env_key, rows in by_env.items():
        modes = sorted({m for cells in rows.values() for m in cells})
        table = [
            [cmds] + [cells.get(m, "") for m in modes] for cmds, cells in rows.items()
        ]
        chunks.append(env_key)
        chunks.append(tabulate(table, headers=["commands"] + modes, tablefmt="github"))
        chunks.append("")
    return "\n".join(chunks)
