"""Core services: configuration, result records/verdicts, timing discipline."""

from tpu_patterns.core.config import config_from_tiers, add_config_args  # noqa: F401
from tpu_patterns.core.results import (  # noqa: F401
    Record,
    ResultWriter,
    Verdict,
    parse_log,
)
from tpu_patterns.core.timing import (  # noqa: F401
    ChainMeasurement,
    TimingMode,
    TimingResult,
    clock_ns,
    default_timing_mode,
    device_barrier,
    global_interval_ns,
    measure_chain,
    min_over_reps,
)
