"""The ratchet-baseline core shared by graftlint and perfwatch.

A *ratchet baseline* is a committed JSON file pinning the accepted
current state of some fingerprinted debt — lint findings
(``analysis/baseline.json``), performance metrics
(``perf/baseline.json``).  CI fails only on entries NOT in the baseline
(the ratchet: things can only get cleaner/faster), ``--update-baseline``
re-pins, and hand-written per-entry ``justification`` strings survive
every re-pin because they are triage notes, not tool output.

graftlint (PR 6) proved the shape for lint debt; perfwatch applies the
same contract to performance.  This module holds the part both share —
the file format, the version gate, the justification survival, and the
NEW-vs-baselined-vs-stale split — so the contract cannot drift between
consumers.  What a *fingerprint* hashes and what makes an entry a
*violation* stay domain-owned (analysis/findings.py, perf/baseline.py).

File shape (one per consumer, committed)::

    {"version": N, "entries": [{"fingerprint": "...",
                                "justification": "...", ...}, ...]}

Entries are plain dicts; the only keys this module interprets are
``fingerprint`` (the identity) and ``justification`` (the survivor).
"""

from __future__ import annotations

import json
import os
from typing import Iterable


def load_entries(path: str, *, version: int) -> dict[str, dict]:
    """Baseline entries keyed by fingerprint ({} when the file is absent).

    A version mismatch raises — a silently-misread baseline would either
    fail CI on long-accepted debt or pass new debt as baselined.
    """
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != version:
        raise ValueError(
            f"{path}: baseline version {data.get('version')!r} != "
            f"{version} — regenerate with --update-baseline"
        )
    return {e["fingerprint"]: e for e in data.get("entries", [])}


def preserve_justifications(
    entries: Iterable[dict], old: dict[str, dict]
) -> list[dict]:
    """Carry per-entry ``justification`` strings across a re-pin (matched
    by fingerprint).  An entry that already spells its own justification
    keeps it; one without inherits the old entry's (or "")."""
    out = []
    for e in entries:
        e = dict(e)
        if not e.get("justification"):
            e["justification"] = old.get(e["fingerprint"], {}).get(
                "justification", ""
            )
        out.append(e)
    return out


def save_entries(
    path: str, entries: list[dict], *, version: int
) -> int:
    """Write the baseline file (caller orders + shapes the entries;
    justification survival via :func:`preserve_justifications`).
    Returns the entry count."""
    with open(path, "w") as f:
        json.dump(
            {"version": version, "entries": entries},
            f,
            indent=1,
            sort_keys=True,
        )
        f.write("\n")
    return len(entries)


def prune_stale(
    path: str,
    seen: Iterable[str],
    *,
    version: int,
    stale_filter=None,
) -> tuple[int, list[dict]]:
    """Drop stale entries (fingerprints ``seen`` no longer produces) from
    the committed baseline WITHOUT re-pinning the survivors.

    The gap this closes: ``--update-baseline`` re-pins everything — it
    drops stale debt but also accepts whatever is NEW right now, and (for
    value-carrying baselines like perf) overwrites every pinned value.
    Pruning is the surgical half: fixed debt leaves the ledger, surviving
    entries keep their values AND justifications byte-for-byte, and new
    findings keep gating.  ``stale_filter`` restricts which entries a
    partial run may declare fixed (same contract as ``split_entries``).

    Returns ``(surviving_count, dropped_entries)``.  A missing baseline
    file prunes nothing.
    """
    baseline = load_entries(path, version=version)
    if not baseline:
        return 0, []
    _new, _pinned, stale = split_entries(
        seen, baseline, stale_filter=stale_filter
    )
    if not stale:
        return len(baseline), []
    dropped_fps = {e["fingerprint"] for e in stale}
    # dict preserves the file's entry order: survivors keep their slot so
    # a prune diffs as pure deletions
    survivors = [
        e for fp, e in baseline.items() if fp not in dropped_fps
    ]
    save_entries(path, survivors, version=version)
    return len(survivors), stale


def split_entries(
    seen: Iterable[str],
    baseline: dict[str, dict],
    *,
    stale_filter=None,
) -> tuple[set[str], set[str], list[dict]]:
    """The ratchet split: (new, baselined, stale).

    ``seen`` are the fingerprints the current run produced.  ``new`` are
    seen-but-unpinned (the gate), ``baselined`` are seen-and-pinned
    (visible, not fatal), ``stale`` are baseline entries nothing matched
    (fixed debt, reported and dropped at the next re-pin).
    ``stale_filter(entry) -> bool`` restricts which baseline entries may
    be declared stale — a partial run must not report unexercised
    entries' debt as fixed.
    """
    seen = set(seen)
    new = seen - set(baseline)
    pinned = seen & set(baseline)
    stale = [
        e
        for fp, e in sorted(baseline.items())
        if fp not in seen and (stale_filter is None or stale_filter(e))
    ]
    return new, pinned, stale
