"""Three-tier configuration: dataclass defaults < environment < CLI.

The reference spreads configuration over three mechanisms (SURVEY.md §5):
hand-rolled getopt CLIs (concurency/main.cpp:121-199,
allreduce-mpi-sycl.cpp:106-131), compile-time defines (-DUSE_WIN,
-DHOST_THREADS/-DNOWAIT, APP_DATA_TYPE), and environment variables
(tile_mapping.sh:23-29, run_omp.sh:14-18).  Here all three collapse into one
scheme: every pattern's config is a dataclass; defaults are field defaults,
the environment tier reads ``TPU_PATTERNS_<FIELD>``, and the CLI tier is
auto-generated argparse flags.  Compile-time variants become enum-valued
fields (a run-time choice is idiomatic under XLA: each variant is a separate
traced/compiled program anyway).
"""

from __future__ import annotations

import argparse
import dataclasses
import enum
import os
import types
import typing
from typing import Any, Mapping, Sequence

ENV_PREFIX = "TPU_PATTERNS_"


def _coerce(field_type: Any, raw: str) -> Any:
    """Coerce a string (env var / CLI token) to a dataclass field type."""
    origin = typing.get_origin(field_type)
    if origin is typing.Union or origin is types.UnionType:  # Optional[T] / T | None
        args = [a for a in typing.get_args(field_type) if a is not type(None)]
        if not raw or raw.lower() == "none":
            return None
        return _coerce(args[0], raw)
    if origin in (list, tuple):
        (elem,) = typing.get_args(field_type)[:1] or (str,)
        items = [_coerce(elem, tok) for tok in raw.split(",") if tok != ""]
        return tuple(items) if origin is tuple else items
    if isinstance(field_type, type) and issubclass(field_type, enum.Enum):
        try:
            return field_type[raw.upper().replace("-", "_")]
        except KeyError:
            return field_type(raw)
    if field_type is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return field_type(raw)


def _env_value(name: str, env: Mapping[str, str]) -> str | None:
    return env.get(ENV_PREFIX + name.upper())


def add_config_args(
    parser: argparse.ArgumentParser,
    cls: type,
    env: Mapping[str, str] | None = None,
    skip: Sequence[str] = (),
) -> None:
    """Add one ``--<field>`` flag per dataclass field.

    The flag default is the env-tier value when set, else the field default,
    so precedence after ``parser.parse_args`` is CLI > env > default.
    ``skip`` names fields the caller wires up manually (e.g. repeatable
    flags that don't fit the one-token-per-field scheme).
    """
    env = os.environ if env is None else env
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        if not f.init or f.name in skip:
            continue
        ftype = hints[f.name]
        default = (
            f.default
            if f.default is not dataclasses.MISSING
            else f.default_factory()  # type: ignore[misc]
        )
        raw = _env_value(f.name, env)
        if raw is not None:
            default = _coerce(ftype, raw)
        flag = "--" + f.name
        help_text = f.metadata.get("help", "")
        if ftype is bool:
            parser.add_argument(
                flag,
                type=lambda s: _coerce(bool, s),
                default=default,
                metavar="BOOL",
                help=f"{help_text} (default: {default})",
            )
        elif typing.get_origin(ftype) in (list, tuple):
            parser.add_argument(
                flag,
                type=str,
                default=default,
                metavar="A,B,...",
                help=f"{help_text} (comma separated; default: {default})",
            )
        else:
            coerce = lambda s, t=ftype: _coerce(t, s)  # noqa: E731
            coerce.__name__ = getattr(ftype, "__name__", str(ftype))
            parser.add_argument(
                flag,
                type=coerce,
                default=default,
                choices=f.metadata.get("choices"),
                help=f"{help_text} (default: {default})",
            )


def config_from_tiers(
    cls: type,
    argv: Sequence[str] | None = None,
    env: Mapping[str, str] | None = None,
    **overrides: Any,
):
    """Build ``cls`` from default < env < CLI(argv) < explicit overrides."""
    parser = argparse.ArgumentParser(prog=cls.__name__, add_help=False)
    add_config_args(parser, cls, env=env)
    ns, _unknown = parser.parse_known_args(list(argv) if argv is not None else [])
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if not f.init:
            continue
        val = getattr(ns, f.name)
        if isinstance(val, str) and typing.get_origin(hints[f.name]) in (list, tuple):
            val = _coerce(hints[f.name], val)
        kwargs[f.name] = val
    kwargs.update(overrides)
    return cls(**kwargs)


def config_to_dict(cfg: Any) -> dict[str, Any]:
    """JSON-friendly dict of a config dataclass (enums -> names)."""
    out = {}
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if isinstance(v, enum.Enum):
            v = v.name.lower()
        elif isinstance(v, tuple):
            v = list(v)
        out[f.name] = v
    return out
