"""Timing discipline: barrier-synced repetitions, min-over-reps, global interval.

Reproduces the reference's metrology (SURVEY.md §1 L5):
  * barrier before each repetition            (p2p/peer2pear.cpp:26)
  * min over repetitions                      (concurency/bench_sycl.cpp:84-121)
  * global interval = max(end) - min(start)
    fused across ranks                        (p2p/peer2pear.cpp:46-52)
  * max-over-ranks wall time                  (allreduce-mpi-sycl.cpp:188-190)

GB/s convention: bytes / nanosecond, exactly the reference's
``N_byte*num_pair/min_time`` (peer2pear.cpp:137-139).

The clock is a C++ FFI monotonic clock when the native module is built
(tpu_patterns.interop.native), else ``time.perf_counter_ns``.  Device work is
fenced with ``block_until_ready`` — the analogue of queue ``wait()``
(bench_sycl.cpp:111-113) / ``taskwait`` (bench_omp.cpp:107-109).
"""

from __future__ import annotations

import dataclasses
import enum
import statistics
import time
from typing import Any, Callable, Sequence


def clock_ns() -> int:
    """Monotonic nanoseconds; prefers the native FFI clock when built."""
    native = _native_clock()
    return native() if native is not None else time.perf_counter_ns()


def wall_time_s() -> float:
    """Wall-clock epoch seconds — for PROVENANCE (record timestamps,
    episode open/close times), never for durations.  The one sanctioned
    wall-clock read: everything else in ``tpu_patterns/`` must time via
    :func:`clock_ns` (enforced by scripts/lint_timing.py)."""
    return time.time()


_NATIVE_CLOCK: Any = False  # False = unprobed, None = unavailable


def _native_clock():
    global _NATIVE_CLOCK
    if _NATIVE_CLOCK is False:
        try:
            from tpu_patterns.interop import native

            _NATIVE_CLOCK = native.clock_ns if native.available() else None
        except Exception:
            _NATIVE_CLOCK = None
    return _NATIVE_CLOCK


def device_barrier() -> None:
    """Synchronization point before a timed region (ref: MPI_Barrier,
    peer2pear.cpp:26).

    Single process: drain all local devices.  Multi-process: global device
    sync via multihost utils (collective over all processes).

    The span's deadline arms the hang watchdog (obs/watchdog.py): a dead
    device tunnel wedges exactly here, inside native code with the GIL
    held — post-mortem invisible, live-diagnosable.
    """
    import jax

    from tpu_patterns import obs

    with obs.span(
        "timing.device_barrier",
        deadline_s=obs.collective_deadline_s(),
        processes=jax.process_count(),
    ):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("tpu_patterns_barrier")
        else:
            for d in jax.local_devices():
                # A trivial transfer per device, then fence: leaves every
                # device queue empty so the next timestamp isn't charged
                # prior work.
                jax.device_put(0, d).block_until_ready()


@dataclasses.dataclass
class TimingResult:
    """Per-repetition wall times of one measured region."""

    times_ns: list[int]
    label: str = ""

    @property
    def min_ns(self) -> int:
        return min(self.times_ns)

    @property
    def mean_ns(self) -> float:
        return statistics.fmean(self.times_ns)

    @property
    def spread_ns(self) -> int:
        """max - min over reps: the jitter floor of this measurement."""
        return max(self.times_ns) - min(self.times_ns)

    @property
    def min_s(self) -> float:
        return self.min_ns * 1e-9

    def gbps(self, n_bytes: int) -> float:
        """bytes/ns == GB/s (decimal), the reference's unit
        (peer2pear.cpp:138)."""
        return n_bytes / self.min_ns

    def us(self) -> float:
        return self.min_ns * 1e-3


def min_over_reps(
    fn: Callable[[], Any],
    reps: int = 10,
    warmup: int = 1,
    barrier: Callable[[], None] | None = device_barrier,
    label: str = "",
) -> TimingResult:
    """Time ``fn`` ``reps`` times, barrier before each rep, keep every time.

    ``fn`` must block until its device work completes (return value with
    ``block_until_ready`` applied, or pure host work).  Warmup runs absorb
    compilation — the XLA analogue of the reference's first-touch effects.

    The obs span wraps the whole measurement (warmup + reps), never the
    timed region itself: between ``t0`` and ``t1`` nothing but ``fn`` and
    its fence runs, obs enabled or not — the min-over-reps numbers are
    identical either way.
    """
    from tpu_patterns import obs

    with obs.span(
        "timing.min_over_reps", label=label, reps=reps, warmup=warmup
    ):
        for _ in range(warmup):
            r = fn()
            _block(r)
        times = []
        for _ in range(reps):
            if barrier is not None:
                barrier()
            t0 = clock_ns()
            r = fn()
            _block(r)
            t1 = clock_ns()
            times.append(t1 - t0)
    return TimingResult(times_ns=times, label=label)


def _block(x: Any) -> None:
    import jax

    jax.block_until_ready(x)


def global_interval_ns(start_ns: int, end_ns: int) -> int:
    """Global interval across processes: max(end) - min(start).

    The reference fuses per-rank timestamps with MPI_Reduce(MIN) /
    MPI_Reduce(MAX) (peer2pear.cpp:46-52).  Across JAX processes the same
    fusion runs over allgathered host timestamps; one process returns the
    local interval.  Host clocks across hosts are not synchronized — the
    barrier preceding the region bounds the skew, exactly the accepted
    error model of the reference (SURVEY.md §7 hard parts).
    """
    import jax

    if jax.process_count() == 1:
        return end_ns - start_ns
    from jax.experimental import multihost_utils
    import numpy as np

    arr = multihost_utils.process_allgather(np.array([start_ns, end_ns], dtype=np.int64))
    return int(arr[:, 1].max() - arr[:, 0].min())


def max_over_processes_s(dt_s: float) -> float:
    """Max-over-ranks duration (ref: MPI_Allreduce(MPI_MAX),
    allreduce-mpi-sycl.cpp:188-190)."""
    import jax

    if jax.process_count() == 1:
        return dt_s
    from jax.experimental import multihost_utils
    import numpy as np

    return float(
        multihost_utils.process_allgather(np.array([dt_s], dtype=np.float64)).max()
    )


def measure_sequence(
    fns: Sequence[Callable[[], Any]],
    reps: int = 10,
    warmup: int = 1,
) -> list[TimingResult]:
    """Serial per-command minima (ref: bench_sycl.cpp:103-109): each fn timed
    separately, min over reps, device fenced between."""
    return [
        min_over_reps(fn, reps=reps, warmup=warmup, label=f"cmd{i}")
        for i, fn in enumerate(fns)
    ]


# ---------------------------------------------------------------------------
# Amortized (differential) timing.
#
# Host wall-clock around a dispatched program measures the runtime's ack
# latency, not device execution, whenever the runtime acknowledges
# asynchronously (remote-tunneled TPU runtimes do; even local runtimes hide
# dispatch overhead this way).  The robust discipline: build a chain of k
# DATA-DEPENDENT repetitions of the op inside one compiled program, force
# real execution by fetching a small data-dependent scalar to the host, and
# difference two chain lengths so fixed costs (dispatch, fetch round-trip)
# cancel:   per_op = (t[k1] - t[k0]) / (k1 - k0).
# The reference's per-rep host timing (peer2pear.cpp:26-52) is sound on its
# synchronous MPI runtime; DIRECT mode reproduces it where valid (CPU).
# ---------------------------------------------------------------------------


def noise_bound_note(what: str = "rate") -> str:
    """The shared not-a-measurement wording (see
    ChainMeasurement.noise_note)."""
    return (
        "amortized differential never cleared the jitter floor — "
        f"{what} is noise-bound, not measured"
    )


class TimingMode(enum.Enum):
    DIRECT = "direct"  # host wall clock around each rep (reference discipline)
    AMORTIZED = "amortized"  # differential chained in-program timing


def default_timing_mode() -> TimingMode:
    """Env override TPU_PATTERNS_TIMING, else AMORTIZED on accelerators."""
    import os

    v = os.environ.get("TPU_PATTERNS_TIMING")
    if v:
        return TimingMode(v.lower())
    import jax

    return TimingMode.DIRECT if jax.default_backend() == "cpu" else TimingMode.AMORTIZED


# Default ops-per-iteration for chained measurements.  A pallas_call output
# cannot alias a fori_loop's carried buffer, so XLA materialises one
# whole-array copy per loop iteration; unrolling U dependent ops inside each
# iteration amortises that (and any other per-iteration fixed cost) to 1/U.
# Measured on v5e: 2x apparent bandwidth for whole-buffer Pallas copies at U=8.
CHAIN_UNROLL = 8


def unrolled_chain(op: Callable[[Any], Any], a: Any, k: Any):
    """``k`` (traced bound) fori_loop iterations of exactly ``CHAIN_UNROLL``
    dependent ``op`` applications — the standard chain body for measure_chain
    callers passing ``ops_per_iter=CHAIN_UNROLL``.  The unroll count is not
    overridable precisely so it cannot drift from the accounting."""
    from jax import lax

    def step(_, b):
        for _ in range(CHAIN_UNROLL):
            b = op(b)
        return b

    return lax.fori_loop(0, k, step, a)


@dataclasses.dataclass
class ChainMeasurement:
    """Per-op time from chained differential measurement.

    ``converged``: whether the long-chain differential actually cleared
    the jitter threshold.  False means the chain hit ``max_chain`` (or a
    caller-pinned length) while the signal was still inside the noise —
    the per-op time is then an upper-bound-ish estimate, not a
    measurement, and callers should say so in their records (the live
    r4 VMEM-residency artifact rode exactly this path: 32768 near-free
    copies never separated from the fetch round trip)."""

    per_op_ns: float
    mode: TimingMode
    short: TimingResult
    long: TimingResult | None = None
    lengths: tuple[int, int] = (1, 1)
    converged: bool = True

    def gbps(self, n_bytes: int) -> float:
        return n_bytes / self.per_op_ns

    def us(self) -> float:
        return self.per_op_ns * 1e-3

    def noise_note(self, what: str = "rate") -> str | None:
        """The record note every runner attaches when the measurement is
        noise-bound — ONE wording, so runners cannot drift apart."""
        return None if self.converged else noise_bound_note(what)


def measure_chain(
    build_chain: Callable[[int], Callable[[], Any]],
    reps: int = 5,
    warmup: int = 1,
    lengths: tuple[int, int] | None = None,
    mode: TimingMode | None = None,
    barrier: Callable[[], None] | None = device_barrier,
    label: str = "",
    direct_fn: Callable[[], Any] | None = None,
    max_chain: int = 4096,
    ops_per_iter: int = 1,
) -> ChainMeasurement:
    """Measure one op via ``build_chain(k)`` = callable running k dependent
    iterations and returning a SMALL data-dependent array (fetched here to
    force execution).  Backends implement k as a traced ``fori_loop`` bound,
    so probing many chain lengths costs one compilation.

    DIRECT: min-over-reps of ``direct_fn`` (the *plain* op, fenced with
    block_until_ready) — the reference's discipline, which times only the
    transfer/kernel, not the verification reduction the chain carries.
    Falls back to chain(1) when no direct_fn is given.

    AMORTIZED: per_op = (min t[k1] - min t[k0]) / (k1 - k0).  With
    ``lengths=None`` the long length adapts: k grows geometrically until the
    differential clears the measured jitter floor (spread of the k0 reps) by
    4x — on remote-tunneled runtimes the fixed fetch round trip is tens of
    ms with several ms of jitter, so fast ops need long chains before the
    signal emerges.  The chain's trailing scalar reduction is shared by all
    chain lengths and cancels.  Clamped to min(t1)/k1 (an upper bound) when
    noise leaves a non-positive difference.

    ``ops_per_iter``: how many dependent ops each chain iteration carries
    (see :func:`unrolled_chain`); the returned per-op time is per single op.
    ``direct_fn``, when given, must be the PLAIN single op regardless.
    """
    import numpy as np

    import jax

    def fetch(x):
        # Force a host fetch of every leaf (remote runtimes complete the
        # fetch round trip here, not at block_until_ready); leaf-wise so
        # chains returning mixed-shape tuples (e.g. a dispatch group of C
        # and H2D commands) materialize without a ragged-array error.
        return jax.tree_util.tree_map(np.asarray, x)

    mode = mode or default_timing_mode()
    if mode is TimingMode.DIRECT:
        fn = direct_fn
        per_iter_ops = 1
        if fn is None:
            chain1 = build_chain(1)
            fn = lambda: fetch(chain1())  # noqa: E731
            per_iter_ops = ops_per_iter
        res = min_over_reps(
            fn, reps=reps, warmup=warmup, barrier=barrier, label=label
        )
        return ChainMeasurement(
            per_op_ns=res.min_ns / per_iter_ops, mode=mode, short=res,
            lengths=(1, 1),
        )

    def timed(k: int, w: int, n_reps: int | None = None) -> TimingResult:
        f = build_chain(k)
        return min_over_reps(
            lambda: fetch(f()), reps=n_reps or reps, warmup=w,
            barrier=barrier, label=f"{label}[k={k}]",
        )

    if lengths is not None:
        k0, k1 = lengths
        assert k1 > k0 >= 1
        r0 = timed(k0, warmup)
        r1 = timed(k1, warmup)
        threshold = max(4 * r0.spread_ns, 10_000_000)
    else:
        k0 = 1
        r0 = timed(k0, warmup)
        threshold = max(4 * r0.spread_ns, 10_000_000)  # >= 10 ms of signal
        # Intermediate probes only decide whether the differential clears
        # the jitter threshold — 2 reps suffice; the accepted k1 gets the
        # full rep count below.
        probe_reps = min(2, reps)
        k1 = min(8, max_chain)
        while True:
            r1 = timed(k1, 1, probe_reps)
            if r1.min_ns - r0.min_ns >= threshold or k1 >= max_chain:
                break
            k1 = min(k1 * 4, max_chain)
        if reps > probe_reps:
            r1 = timed(k1, 0)
    diff = r1.min_ns - r0.min_ns
    per_iter = diff / (k1 - k0) if diff > 0 else r1.min_ns / k1
    from tpu_patterns import obs

    obs.event(
        "timing.measure_chain", label=label, mode=mode.value,
        k0=k0, k1=k1, converged=bool(diff >= threshold),
    )
    return ChainMeasurement(
        per_op_ns=float(per_iter) / ops_per_iter, mode=mode, short=r0, long=r1,
        lengths=(k0, k1),
        # the chain ran out of length before the differential emerged
        # from the jitter floor: the number is noise-bound, not measured
        converged=diff >= threshold,
    )
