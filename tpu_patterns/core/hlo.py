"""Compiled-program structural assertions: perf evidence without a chip.

The reference's L5 turns a *measurement* into a verdict ("the runtime
must demonstrably overlap", /root/reference/concurency/main.cpp:314-318).
Measurement needs silicon; the *schedule* does not — XLA's optimized HLO
is available on any backend, and the properties our perf claims rest on
are visible in it:

* the decomposed collective matmul (`parallel/overlap.py`) only hides
  its transfers if transfer and matmul share one loop body — if XLA ever
  re-serializes the ring into collect-then-compute, the claim is dead
  long before a benchmark would notice;
* on TPU the scheduled module makes overlap explicit as
  ``collective-permute-start`` / ``-done`` pairs with compute scheduled
  between them;
* remat's whole point is a smaller buffer assignment — the compiled
  module's temp-allocation size, not a runtime number.

These helpers parse `compiled.as_text()` / `memory_analysis()` so CI can
fail on an XLA regression (ring serialized, remat re-materialized) with
no TPU attached (VERDICT r3 next #2).  Text parsing is intentionally
line-oriented and conservative: HLO's grammar here is one instruction
per line, `%name = type opcode(...)`.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Iterable

import jax


def optimized_hlo(fn: Callable[..., Any] | Any, *args: Any) -> str:
    """Post-optimization HLO text of ``fn`` compiled for ``args``.

    ``args`` may be real arrays or ``jax.ShapeDtypeStruct``s (AOT — no
    data materialized, which keeps flagship-shape compiles cheap enough
    for CI).  ``fn`` may already be jitted; plain callables are wrapped.
    """
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    return jitted.lower(*args).compile().as_text()


def temp_bytes(fn: Callable[..., Any] | Any, *args: Any) -> int | None:
    """Temp-buffer size of the compiled module (the activation stash the
    remat lever targets), or None when the backend has no analysis."""
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    try:
        ma = jitted.lower(*args).compile().memory_analysis()
        return int(ma.temp_size_in_bytes)
    except (AttributeError, NotImplementedError, jax.errors.JaxRuntimeError):
        return None


# one HLO computation: "%name (params) -> type {\n  instructions...\n}" —
# body lines are indented, the closing brace is column 0
_COMPUTATION_RE = re.compile(
    r"^(?:%|ENTRY\s+%?)(?P<name>[\w.\-]+)[^\n{]*\{\n(?P<body>.*?)^\}",
    re.M | re.S,
)
# `%name = TYPE opcode(operands...), attrs...` — TYPE may be a tuple
# containing commas, layouts, and `/*index=N*/` comments (which contain
# `=`), so the opcode is located as the first lowercase token whose `(`
# opens an operand list (starts with `%` or is empty) rather than by
# consuming the type.  Operand-less literal ops (`constant(0)`, `iota`)
# are intentionally not matched; the structural checks here never need
# them.
_INSTR_RE = re.compile(
    r"^\s+(?:ROOT\s+)?(?P<name>%?[\w.\-]+)\s*="
    r".*?\s(?P<op>[a-z][\w\-]*)\((?=%|\))(?P<rest>[^\n]*)"
)


def computations(txt: str) -> dict[str, str]:
    """Map computation name -> body text of an HLO module dump."""
    return {
        m.group("name"): m.group("body")
        for m in _COMPUTATION_RE.finditer(txt)
    }


def body_instructions(body: str) -> list[tuple[str, str, str]]:
    """``(result_name, opcode, rest_of_line)`` per instruction, in
    textual order — which in a scheduled module IS the schedule."""
    out = []
    for line in body.splitlines():
        m = _INSTR_RE.match(line)
        if m:
            out.append((m.group("name"), m.group("op"), m.group("rest")))
    return out


def body_opcodes(body: str) -> list[str]:
    """Opcodes of a computation body, in textual (= schedule) order."""
    return [op for _, op, _ in body_instructions(body)]


_PERM_OPS = ("collective-permute", "collective-permute-start")


def _reachable_opcodes(
    name: str, comps: dict[str, str], memo: dict[str, set[str]]
) -> set[str]:
    """Opcodes of ``name``'s body plus everything it calls (fusions,
    conditional branches, nested loops) — the per-iteration op set."""
    if name in memo:
        return memo[name]
    memo[name] = set()  # cycle guard; HLO call graphs are acyclic anyway
    body = comps.get(name, "")
    ops = set(body_opcodes(body))
    for other in comps:
        if other != name and re.search(
            r"%" + re.escape(other) + r"(?![\w.\-])", body
        ):
            ops |= _reachable_opcodes(other, comps, memo)
    memo[name] = ops
    return ops


def ring_interleaved(txt: str) -> bool:
    """True iff some loop body issues BOTH a collective-permute (sync or
    async-start) and a dot per iteration — transfer and matmul share one
    loop, the structure that lets a scheduler hide the hop.  Call edges
    (fusions, `lax.cond` branches) are followed, since the final hop's
    permute typically sits under a conditional.  False means the ring
    was serialized into collect-everything-then-compute (the regression
    this assertion exists to catch)."""
    comps = computations(txt)
    memo: dict[str, set[str]] = {}
    for body in comps.values():
        for _, op, rest in body_instructions(body):
            if op != "while":
                continue
            m = re.search(r"body=%([\w.\-]+)", rest)
            if not m:
                continue
            ops = _reachable_opcodes(m.group(1), comps, memo)
            if any(p in ops for p in _PERM_OPS) and "dot" in ops:
                return True
    return False


def async_overlap_spans(
    txt: str,
    compute_ops: tuple[str, ...] = ("fusion", "dot", "convolution"),
) -> list[tuple[str, int]]:
    """For each async collective-permute pair in a SCHEDULED module,
    count compute instructions issued between start and done.

    In a scheduled HLO dump the textual instruction order within a
    computation IS the schedule, so ``n_between > 0`` means the DMA has
    compute to hide under; all-zero means the schedule serialized every
    hop (start immediately awaited).  Returns ``[(start_name, n), ...]``
    across all computations; empty when the module has no async pairs
    (e.g. CPU, where collective-permute stays synchronous — callers
    should treat that as "not applicable", not success).
    """
    spans: list[tuple[str, int]] = []
    for body in computations(txt).values():
        insts = body_instructions(body)
        for i, (name, op, _) in enumerate(insts):
            if op != "collective-permute-start":
                continue
            # boundary-guarded: '%cp-start.1' must not close on the done
            # of '%cp-start.12'
            ref = re.compile(re.escape(name) + r"(?![\w.\-])")
            n_compute = 0
            for j in range(i + 1, len(insts)):
                dname, dop, doperands = insts[j]
                if dop == "collective-permute-done" and ref.search(
                    doperands
                ):
                    spans.append((name, n_compute))
                    break
                if dop in compute_ops:
                    n_compute += 1
    return spans


def opcode_counts(txt: str, ops: Iterable[str]) -> dict[str, int]:
    """How many times each opcode in ``ops`` is issued module-wide."""
    wanted = set(ops)
    counts = {o: 0 for o in wanted}
    for body in computations(txt).values():
        for op in body_opcodes(body):
            if op in wanted:
                counts[op] += 1
    return counts
