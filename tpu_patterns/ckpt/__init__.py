"""Sharded checkpoint / resume.

The aux subsystem the reference suite leaves to the job scheduler
(SURVEY §5: no checkpoint/resume in `/root/reference`) — but a framework
whose flagship is a distributed training step needs one: a sweep cell or
training run killed by a dead device tunnel must resume from its last
committed state, not restart (the same crash-vs-result discipline as
``sweep.py --resume``).

TPU-native design: leaves are ``jax.Array``s laid out by
``NamedSharding`` over a mesh; save writes only addressable replica-0
shards (no gather, no host round trip of replicated copies), and restore
rebuilds arrays for ANY target sharding — the saved mesh and the restore
mesh need not match (elastic restore onto a different topology).
"""

from tpu_patterns.ckpt.checkpoint import (
    AsyncSaver,
    available_steps,
    describe,
    latest_step,
    read_extra,
    restore,
    save,
)

__all__ = [
    "AsyncSaver", "available_steps", "describe", "latest_step",
    "read_extra", "restore", "save",
]
